# Developer drivers — the shape of the reference's isotope/Makefile
# (generate topology -> convert/deploy -> drive load), with simulation
# replacing kubectl apply.

PY ?= python
QPS ?= 1000
DURATION ?= 120s

.PHONY: test bench telemetry-smoke examples canonical tree star multitier \
	auxiliary-services star-auxiliary latency cpu_mem dot clean

test:
	$(PY) -m pytest tests/ -x -q

# bench prints the one-line JSON capture AND gates it against the
# previous round's driver capture (>15% per-case regression fails).
# No pipe: a bench.py crash must fail the target, not hand an empty
# capture to the regression gate.
bench:
	$(PY) bench.py > .bench_capture.json
	@cat .bench_capture.json
	$(PY) tools/bench_regress.py .bench_capture.json

# tiny end-to-end engine-telemetry check: run a 3-service chain with
# --telemetry=detail (segment fences armed) and validate the emitted
# JSONL against the schema (telemetry/core.py validate_jsonl).
telemetry-smoke:
	rm -f /tmp/isotope_telemetry_smoke.jsonl
	$(PY) -m isotope_tpu simulate examples/topologies/chain-3-services.yaml \
		--qps 50 --duration 2s --load-kind open --max-requests 256 \
		--telemetry=detail \
		--telemetry-out /tmp/isotope_telemetry_smoke.jsonl --flat \
		> /dev/null
	$(PY) -c "from isotope_tpu.telemetry import validate_jsonl; \
		n = validate_jsonl('/tmp/isotope_telemetry_smoke.jsonl'); \
		print(f'telemetry-smoke: {n} valid record(s)')"

examples:
	$(PY) tools/gen_examples.py

# -- single-topology runs (reference Makefile:30-72 targets) -------------

canonical:
	isotope-tpu simulate examples/topologies/canonical.yaml \
		--qps $(QPS) --duration $(DURATION) --load-kind open

tree:
	isotope-tpu generate tree --levels 4 --branches 3 -o /tmp/tree.yaml
	isotope-tpu simulate /tmp/tree.yaml --qps $(QPS) --duration $(DURATION) \
		--load-kind open

star multitier auxiliary-services star-auxiliary:
	isotope-tpu generate realistic --services 50 --type $@ -o /tmp/$@.yaml
	isotope-tpu simulate /tmp/$@.yaml --qps $(QPS) --duration $(DURATION) \
		--load-kind open

# -- benchmark sweeps (perf/benchmark/configs shapes) --------------------

latency:
	isotope-tpu sweep configs/latency.toml -o results/latency
	isotope-tpu plot results/latency/benchmark.csv --x conn \
		-o results/latency/latency.png

cpu_mem:
	isotope-tpu sweep configs/cpu_mem.toml -o results/cpu_mem
	isotope-tpu plot results/cpu_mem/benchmark.csv --x qps \
		--metrics p50,p99 -o results/cpu_mem/latency.png

dot:
	isotope-tpu graphviz examples/topologies/canonical.yaml canonical.dot

clean:
	rm -rf results canonical.dot /tmp/tree.yaml
