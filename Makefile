# Developer drivers — the shape of the reference's isotope/Makefile
# (generate topology -> convert/deploy -> drive load), with simulation
# replacing kubectl apply.

PY ?= python
QPS ?= 1000
DURATION ?= 120s

.PHONY: test lint vet-smoke grad-smoke bench telemetry-smoke \
	resilience-smoke \
	attribution-smoke sparse-smoke timeline-smoke multihost-smoke \
	policies-smoke rollout-smoke lb-smoke ensemble-smoke \
	chaosfleet-smoke chaosgrid-smoke search-smoke explain-smoke \
	ingest-smoke \
	examples \
	canonical tree star multitier auxiliary-services star-auxiliary \
	latency cpu_mem dot clean

test:
	$(PY) -m pytest tests/ -x -q

# ruff (lint + format check) and the permissive mypy baseline from
# pyproject.toml when installed; everywhere else tools/lint.py's
# built-in floor (syntax + unused imports) still gates.  Nonzero exit
# on any finding, so this composes into CI exactly like the smokes.
lint:
	$(PY) tools/lint.py

# static-analysis end-to-end check: the shipped examples must vet
# clean, and a seeded-defect run (injected host callback + f64 leak,
# plus a tiny fake device capacity to trip the OOM verdict) must
# report the planted rules and exit nonzero.  The graddead injection
# quantizes cpu_scale through floor, so the gradient audit must flip
# cpu_time_s to gradient-dead (VET-G001) — strict promotes the warn
# to blocking, hence the leading `!`.
vet-smoke: lint
	$(PY) -m isotope_tpu vet examples/topologies/canonical.yaml \
		examples/topologies/tree-13-services.yaml
	! ISOTOPE_VET_INJECT=callback,f64 ISOTOPE_VET_DEVICE_BYTES=65536 \
		$(PY) -m isotope_tpu vet \
		examples/topologies/chain-3-services.yaml \
		> /tmp/isotope_vet_smoke.txt 2>&1
	@grep -q "VET-J001" /tmp/isotope_vet_smoke.txt
	@grep -q "VET-J002" /tmp/isotope_vet_smoke.txt
	@grep -q "VET-M001" /tmp/isotope_vet_smoke.txt
	! ISOTOPE_VET_INJECT=graddead $(PY) -m isotope_tpu vet \
		--grad --strict --suppress "VET-G002,VET-G004" \
		examples/topologies/chain-3-services.yaml \
		> /tmp/isotope_vet_grad_inject.txt 2>&1
	@grep -q "VET-G001" /tmp/isotope_vet_grad_inject.txt
	@grep -q "floor" /tmp/isotope_vet_grad_inject.txt
	@echo "vet-smoke: clean examples pass, seeded defects caught"

# gradient-audit end-to-end check: `vet --grad` classifies every
# registered design knob on the canonical examples (exit 0 — VET-G
# findings are warn/info), and the isotope-gradaudit/v1 artifact
# demonstrates all three classes, with the gradient-dead finding
# naming its killing primitive and jaxpr path.
grad-smoke:
	$(PY) -m isotope_tpu vet --grad \
		--grad-json /tmp/isotope_gradaudit.json \
		examples/topologies/canonical.yaml \
		examples/topologies/canonical-errors.yaml
	$(PY) -c "import json; \
		doc = json.load(open('/tmp/isotope_gradaudit.json')); \
		assert doc['schema'] == 'isotope-gradaudit/v1', doc['schema']; \
		from isotope_tpu.sim.config import DESIGN_PARAMS; \
		names = {p.name for p in DESIGN_PARAMS}; \
		audits = doc['audits']; \
		assert all(set(a['classes']) == names for a in audits); \
		classes = {c for a in audits for c in a['classes'].values()}; \
		assert classes == {'differentiable', 'gradient-dead', \
		                   'trace-constant'}, classes; \
		err = [k for a in audits for k in a['knobs'] \
		       if k['name'] == 'error_rate_scale' and k['kills']]; \
		assert any('lt' in k['kills'][0] for k in err), err; \
		print('grad-smoke: all', len(names), 'knobs classified,', \
		      'killer named:', err[0]['kills'][0])"

# bench prints the one-line JSON capture AND gates it against the
# previous round's driver capture (>15% per-case regression fails).
# No pipe: a bench.py crash must fail the target, not hand an empty
# capture to the regression gate.
bench:
	$(PY) bench.py > .bench_capture.json
	@cat .bench_capture.json
	$(PY) tools/bench_regress.py .bench_capture.json

# tiny end-to-end engine-telemetry check: run a 3-service chain with
# --telemetry=detail (segment fences armed) and validate the emitted
# JSONL against the schema (telemetry/core.py validate_jsonl).
telemetry-smoke:
	rm -f /tmp/isotope_telemetry_smoke.jsonl
	$(PY) -m isotope_tpu simulate examples/topologies/chain-3-services.yaml \
		--qps 50 --duration 2s --load-kind open --max-requests 256 \
		--telemetry=detail \
		--telemetry-out /tmp/isotope_telemetry_smoke.jsonl --flat \
		> /dev/null
	$(PY) -c "from isotope_tpu.telemetry import validate_jsonl; \
		n = validate_jsonl('/tmp/isotope_telemetry_smoke.jsonl'); \
		print(f'telemetry-smoke: {n} valid record(s)')"

# engine-chaos end-to-end check: inject a transient failure AND an OOM
# into the run phase (resilience/faults.py), then assert the run still
# produced output — retried (retries_total >= 1) and degraded down the
# ladder (degradations_total >= 1, degraded_to recorded) instead of
# crashing.  The injected faults are deterministic; no flakiness.
resilience-smoke:
	rm -f /tmp/isotope_resilience_smoke.jsonl
	ISOTOPE_FAULT_INJECT=transient:engine.run:1,oom:engine.run:1 \
	ISOTOPE_COMPILE_CACHE=off \
	$(PY) -m isotope_tpu simulate examples/topologies/chain-3-services.yaml \
		--qps 50 --duration 2s --load-kind open --max-requests 256 \
		--telemetry \
		--telemetry-out /tmp/isotope_resilience_smoke.jsonl --flat \
		> /tmp/isotope_resilience_smoke.json
	$(PY) -c "import json; from isotope_tpu.telemetry import iter_jsonl; \
		rec = list(iter_jsonl('/tmp/isotope_resilience_smoke.jsonl'))[-1]; \
		assert rec.counters.get('retries_total', 0) >= 1, rec.counters; \
		assert rec.counters.get('degradations_total', 0) >= 1, rec.counters; \
		assert rec.meta.get('degraded_to'), rec.meta; \
		doc = json.load(open('/tmp/isotope_resilience_smoke.json')); \
		assert float(doc['ActualQPS']) > 0, doc; \
		print('resilience-smoke: degraded to', rec.meta['degraded_to'], \
		      '| retries', int(rec.counters['retries_total']), \
		      '| output intact (ActualQPS', doc['ActualQPS'], ')')"

# attribution end-to-end check: an example topology runs with
# --attribution=tail, then the artifacts are validated — blame shares
# present and summing to ~1, residual at f32 noise level, the
# flamegraph parsing as collapsed stacks, and the exemplar trace
# matching the jaeger_trace shape with tail_rank tags.
attribution-smoke:
	rm -f /tmp/isotope_attr_blame.json /tmp/isotope_attr_flame.txt \
		/tmp/isotope_attr_exemplars.json
	$(PY) -m isotope_tpu simulate examples/topologies/tree-13-services.yaml \
		--qps 50 --duration 4s --load-kind open --max-requests 512 \
		--attribution=tail --blame-out /tmp/isotope_attr_blame.json \
		--flamegraph /tmp/isotope_attr_flame.txt \
		--exemplar-trace /tmp/isotope_attr_exemplars.json --flat \
		> /dev/null
	$(PY) -c "import json; \
		doc = json.load(open('/tmp/isotope_attr_blame.json')); \
		shares = sum(r['share'] for r in doc['services']); \
		assert abs(shares - 1.0) < 1e-6, shares; \
		assert doc['residual_abs_s_per_request'] < 1e-6, doc; \
		assert doc['tail_cut_s'] and doc['tail_services'], doc; \
		lines = open('/tmp/isotope_attr_flame.txt').read().splitlines(); \
		assert lines and all(len(ln.rsplit(' ', 1)) == 2 and \
			ln.rsplit(' ', 1)[1].isdigit() and \
			ln.rsplit(' ', 1)[0].startswith('client;') \
			for ln in lines), lines[:3]; \
		ex = json.load(open('/tmp/isotope_attr_exemplars.json')); \
		tr = ex['data'][0]; \
		assert tr['spans'] and tr['processes'], tr; \
		tags = {t['key'] for t in tr['spans'][0]['tags']}; \
		assert {'tail_rank', 'tail_cut_s'} <= tags, tags; \
		print('attribution-smoke: blame sums to 1, flamegraph parses,', \
		      len(ex['data']), 'exemplar trace(s) validate')"

# flight-recorder end-to-end check: the timeline subcommand records a
# short run into windowed series, then the artifacts are validated —
# window counts reconciling with the run total, the timestamped
# Prometheus exposition parsing (with timestamps) and round-tripping
# through the query layer, and per-window alarm rows carrying sim-time
# stamps.
timeline-smoke:
	rm -f /tmp/isotope_tl.json /tmp/isotope_tl.prom \
		/tmp/isotope_tl_monitor.jsonl
	$(PY) -m isotope_tpu timeline examples/topologies/tree-13-services.yaml \
		--qps 200 --duration 6s --load-kind open --max-requests 1024 \
		--window 1s --out /tmp/isotope_tl.json \
		--prometheus /tmp/isotope_tl.prom \
		--alarms --alarm-sink /tmp/isotope_tl_monitor.jsonl \
		> /dev/null
	$(PY) -c "import json; \
		doc = json.load(open('/tmp/isotope_tl.json')); \
		assert doc['schema'] == 'isotope-timeline/v1', doc['schema']; \
		wins = doc['windows']; \
		total = sum(w['arrivals'] for w in wins); \
		assert total == doc['count'], (total, doc['count']); \
		assert doc['services'], 'no service series'; \
		from isotope_tpu.metrics.query import MetricStore; \
		store = MetricStore.from_text(open('/tmp/isotope_tl.prom').read(), 1.0); \
		v = store.query_value('timeline_client_requests_total'); \
		assert v == total, (v, total); \
		from isotope_tpu.metrics.monitor import MonitorSink; \
		rows = MonitorSink('/tmp/isotope_tl_monitor.jsonl').read(); \
		assert rows and all(r.window_index is not None for r in rows), \
			rows[:2]; \
		print('timeline-smoke:', len(wins), 'windows reconcile,', \
		      len(rows), 'window-stamped monitor rows')"

# sparse-executor end-to-end check: force the non-dense encodings
# (sparse_level_elems lowered) on a small star graph, run the dense /
# tiled / sparse / tiled+pallas executors, and diff their summaries —
# counts must be equal, latency sums within f32 reduction noise.
sparse-smoke:
	$(PY) tools/sparse_smoke.py

# multi-host end-to-end check: the 2 hosts x 8 devices EMULATED twin
# (16 shards on one CPU device) reconciles, the (slice, data, svc)
# shard_map program matches its emulated replay within 1 ULP,
# collective/compute overlap matches the single-merge path, the
# --mesh auto layout search scores <= the hand-picked {2,2,2} mesh,
# and an injected sharded.dcn_collective transient is retried.
multihost-smoke:
	$(PY) tools/multihost_smoke.py

# resilience-policy end-to-end check: a chaos kill phase on a retry
# chain runs unprotected vs. with breaker + retry budget + autoscaler;
# the protected run's retry-amplified hop events and error share must
# be STRICTLY lower, the breaker trip/recovery must land as sim-time
# onsets on the timeline window axis, and the autoscaler's replica
# series must recover the killed capacity.
policies-smoke:
	$(PY) tools/policies_smoke.py

# progressive-delivery end-to-end check (sim/rollout.py): a seeded bad
# canary must roll back inside its first bake window, its traffic
# exposure and error burn must stay strictly below the open-loop
# `churn`-equivalent twin's, and the 4-shard sharded trajectory must
# be bit-equal to the emulated twin.
rollout-smoke:
	$(PY) tools/rollout_smoke.py

# load-balancing end-to-end check (sim/lb.py): least-request beats the
# shared-queue fifo tail (and the mis-weighted hot pool) at rho ~0.9,
# prints the per-window per-backend load split, and panic routing
# keeps goodput nonzero through a 3/4-replica ejection storm
lb-smoke:
	$(PY) tools/lb_smoke.py

# scenario-ensemble end-to-end check (sim/ensemble.py): a 32-member
# svc-scale fleet on CPU — exactly one compile serves every member
# (telemetry trace/cache counters), the P(SLO-violation) estimate
# with its Wilson CI matches the brute-force per-seed loop exactly
# (member k bit-equals the solo run with that folded seed), and the
# fleet's aggregate wall-clock beats the sequential dispatch loop.
ensemble-smoke:
	$(PY) tools/ensemble_smoke.py

# chaos-fleet end-to-end check (PR 15): protected fleet over a
# retry-storm topology with per-member kill timing, member k bit-equal
# to its solo run_policies, importance splitting resolving a
# forced-rare outage at <= 10% of the brute-force budget, and the
# worst member's jittered schedule replaying solo bit-for-bit
chaosfleet-smoke:
	$(PY) tools/chaosfleet_smoke.py

# universal-member composition check (PR 18): the four compositions
# the pre-universal member rejected (ungraceful kills, LB panic,
# saturated -qps max, rollout kill splits) each run as member-jittered
# fleets bit-equal to their solo twins, then the ALL-ON fleet
# (policies + LB panic + rollouts + ungraceful member chaos in one
# program) with the worst member's postmortem replaying bit-for-bit
chaosgrid-smoke:
	$(PY) tools/chaosgrid_smoke.py

# config-search end-to-end check (sim/search.py): a 16-candidate
# successive-halving bracket over the svc-scale fan-out — the planted
# near-zero-error candidate wins, the bracket compiles <= once per
# rung (a repeat bracket adds zero traces), rung 0 bit-equals the
# plain screening fleet, and the winner's carry-continued segments
# replay the unbroken full-horizon member exactly
search-smoke:
	$(PY) tools/search_smoke.py

# fleet-observability end-to-end check (PR 17): a fleet with a
# planted slow-hop member (3/4 worker replicas killed at 0.3s) runs
# blame + recorder through ONE dispatch; the fleet-blame artifact +
# `isotope-tpu explain` must name the hop, the onset window, and the
# band departure from the artifact alone, and the worst member's
# blame must replay solo
explain-smoke:
	$(PY) tools/explain_smoke.py

# trace-driven ingest self-closure check (PR 20): simulate the
# power-law fixture with the timeline recorder armed, export the two
# Prometheus expositions a real scrape would see, ingest them back
# through readers -> fitters, and pin the reconstruction — per-service
# error share, mean self-time (90% band share), exact fan-out degree
# sequence, windowed qps schedule — within report.CLOSURE_TOLERANCES;
# coverage counters must partition every input line, the emitted TOML
# must decode through load_toml, vet must be clean, and the fitted
# topology must re-simulate to the source's client error share
ingest-smoke:
	$(PY) tools/ingest_smoke.py

examples:
	$(PY) tools/gen_examples.py

# -- single-topology runs (reference Makefile:30-72 targets) -------------

canonical:
	isotope-tpu simulate examples/topologies/canonical.yaml \
		--qps $(QPS) --duration $(DURATION) --load-kind open

tree:
	isotope-tpu generate tree --levels 4 --branches 3 -o /tmp/tree.yaml
	isotope-tpu simulate /tmp/tree.yaml --qps $(QPS) --duration $(DURATION) \
		--load-kind open

star multitier auxiliary-services star-auxiliary:
	isotope-tpu generate realistic --services 50 --type $@ -o /tmp/$@.yaml
	isotope-tpu simulate /tmp/$@.yaml --qps $(QPS) --duration $(DURATION) \
		--load-kind open

# -- benchmark sweeps (perf/benchmark/configs shapes) --------------------

latency:
	isotope-tpu sweep configs/latency.toml -o results/latency
	isotope-tpu plot results/latency/benchmark.csv --x conn \
		-o results/latency/latency.png

cpu_mem:
	isotope-tpu sweep configs/cpu_mem.toml -o results/cpu_mem
	isotope-tpu plot results/cpu_mem/benchmark.csv --x qps \
		--metrics p50,p99 -o results/cpu_mem/latency.png

dot:
	isotope-tpu graphviz examples/topologies/canonical.yaml canonical.dot

clean:
	rm -rf results canonical.dot /tmp/tree.yaml
