"""Synthetic topology generators.

Capability parity with the reference's two generators:

- ``tree_topology``: BFS-complete trees where each service calls its
  children in ONE concurrent step (isotope/create_tree_topology.py:24-80),
  generalized so depth/branching/sizes are parameters instead of constants.
- ``realistic_topology``: scale-free Barabási-Albert graphs with the four
  archetypes from isotope/create_realistic_topology.py:55-99 — star(0.9,
  0.01), multitier(0.9, 3.25), auxiliary-services(0.05, 3.25),
  star-auxiliary(0.05, 0.01) — with edges reversed so node 0 is the source
  (:34-47), node 0 the entrypoint, and children called SEQUENTIALLY
  (:176-187). The BA process is implemented directly in numpy (nonlinear
  preferential attachment, m=1) instead of igraph.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

ARCHETYPES: Dict[str, tuple] = {
    # name -> (power, zero_appeal); create_realistic_topology.py:55-78
    "star": (0.9, 0.01),
    "multitier": (0.9, 3.25),
    "auxiliary-services": (0.05, 3.25),
    "star-auxiliary": (0.05, 0.01),
}


def tree_topology(
    num_levels: int = 3,
    num_branches: int = 3,
    request_size: int = 128,
    response_size: int = 128,
    num_replicas: int = 1,
    sleep: Optional[str] = None,
    num_services: Optional[int] = None,
) -> dict:
    """Complete tree; each parent calls all children in one concurrent step.

    Service naming follows the reference's path scheme: root "svc-0",
    children "svc-0-0", "svc-0-1", ... (create_tree_topology.py:47-57).
    ``num_services`` caps the BFS at an exact count (the shape of the
    reference's N-svc_M-end example topologies); default is the complete
    tree.
    """
    if num_services is None:
        num_services = sum(num_branches**i for i in range(num_levels))
    services: List[dict] = []
    queue: List[tuple] = [({"name": "svc-0", "isEntrypoint": True}, ["0"])]
    while queue and len(services) < num_services:
        current, path = queue.pop(0)
        services.append(current)
        remaining = num_services - len(services) - len(queue)
        if remaining > 0:
            children = []
            for i in range(min(num_branches, remaining)):
                child_path = path + [str(i)]
                child = {"name": "svc-" + "-".join(child_path)}
                children.append(child)
                queue.append((child, child_path))
            step = [{"call": c["name"]} for c in children]
            if sleep:
                current["script"] = [{"sleep": sleep}, step]
            else:
                current["script"] = [step]
    return {
        "defaults": {
            "requestSize": request_size,
            "responseSize": response_size,
            "numReplicas": num_replicas,
        },
        "services": services,
    }


class _Fenwick:
    """Prefix-sum tree for O(log n) weighted sampling with updates —
    the nonlinear-preferential-attachment loop is O(n^2) with a dense
    weight array, which caps the generator at ~10k services; this keeps
    100k-service topologies (BASELINE configs[4]) in seconds."""

    def __init__(self, n: int):
        self.n = n
        self.tree = [0.0] * (n + 1)
        self.total = 0.0

    def add(self, i: int, delta: float) -> None:
        self.total += delta
        i += 1
        while i <= self.n:
            self.tree[i] += delta
            i += i & (-i)

    def sample(self, u: float, hi: int) -> int:
        """Index i < hi with cumweight(i-1) <= u*total < cumweight(i).

        ``hi`` bounds the attachable prefix: float accumulation drift
        (tree vs ``total`` sum the same deltas in different orders) can
        push the target a ULP past the tree sum, and the descent would
        then walk into the zero-weight suffix of not-yet-added nodes.
        """
        target = u * self.total
        idx = 0
        bit = 1 << (self.n.bit_length())
        tree = self.tree
        while bit:
            nxt = idx + bit
            if nxt <= self.n and tree[nxt] <= target:
                target -= tree[nxt]
                idx = nxt
            bit >>= 1
        return min(idx, hi - 1)


def barabasi_albert_edges(
    n: int,
    power: float,
    zero_appeal: float,
    rng: np.random.Generator,
) -> np.ndarray:
    """Nonlinear preferential attachment with m=1 (igraph Barabasi
    semantics: new node j attaches to existing i with probability
    proportional to in_degree(i)**power + zero_appeal).

    Returns an array of (source, target) pairs where source is the NEW node
    — the reference then reverses edges so node 0 becomes the root caller
    (create_realistic_topology.py:34-47); we emit caller->callee directly
    by treating the attachment target as the callee's caller, i.e. edge
    (target -> source) after reversal. Here we return (parent, child) pairs
    with parent < child, matching the reversed orientation.
    """
    if n < 1:
        raise ValueError("need at least one node")
    edges = np.empty((max(n - 1, 0), 2), dtype=np.int64)
    in_degree = np.zeros(n, dtype=np.int64)
    weights = _Fenwick(n)
    weights.add(0, zero_appeal)  # node 0: in_degree 0
    if n > 1 and zero_appeal <= 0:
        # node 0 starts with in_degree 0 => weight 0**power + 0 = 0;
        # nothing is attachable (the dense implementation hit the same
        # wall as a 0/0 in the probability normalization)
        raise ValueError(
            "zero_appeal must be positive: with no appeal an empty "
            "graph has all-zero attachment weights"
        )
    us = rng.random(max(n - 1, 0))
    for j in range(1, n):
        target = weights.sample(us[j - 1], j)
        # igraph edge j->target; reversed: target is the caller of j.
        edges[j - 1] = (target, j)
        d = in_degree[target]
        in_degree[target] = d + 1
        weights.add(target, float((d + 1) ** power - d**power))
        weights.add(j, zero_appeal)  # j becomes attachable
    return edges


def realistic_topology(
    num_services: int = 10,
    archetype: str = "multitier",
    request_size: int = 128,
    response_size: int = 128,
    num_replicas: int = 1,
    seed: int = 0,
    name_prefix: str = "mock-",
) -> dict:
    """Scale-free topology; node 0 is the entrypoint, children are called
    sequentially (one call step each, create_realistic_topology.py:176-187).
    """
    if archetype not in ARCHETYPES:
        raise ValueError(
            f"there is no graph model named as {archetype}; "
            f"try either: {sorted(ARCHETYPES)}"
        )
    power, zero_appeal = ARCHETYPES[archetype]
    rng = np.random.default_rng(seed)
    edges = barabasi_albert_edges(num_services, power, zero_appeal, rng)
    children: List[List[int]] = [[] for _ in range(num_services)]
    for parent, child in edges:
        children[int(parent)].append(int(child))
    services = []
    for i in range(num_services):
        svc: dict = {"name": f"{name_prefix}{i}"}
        if i == 0:
            svc["isEntrypoint"] = True
        if children[i]:
            svc["script"] = [
                {"call": f"{name_prefix}{c}"} for c in children[i]
            ]
        services.append(svc)
    return {
        "defaults": {
            "requestSize": request_size,
            "responseSize": response_size,
            "numReplicas": num_replicas,
        },
        "services": services,
    }


def powerlaw_topology(
    num_services: int = 100,
    exponent: float = 2.0,
    max_degree: Optional[int] = None,
    request_size: int = 128,
    response_size: int = 128,
    num_replicas: int = 1,
    seed: int = 0,
    name_prefix: str = "pl-",
    sleep_choices: Optional[List[str]] = None,
    error_rate_choices: Optional[List[str]] = None,
) -> dict:
    """Power-law (Zipf) out-degree topology: production-shaped skew.

    Real service meshes are dominated by a few high-fan-out aggregators
    over a long tail of leaf services (the Alibaba cluster-trace call
    graphs follow a Zipf out-degree law); the BA archetypes skew the
    IN-degree instead.  This generator draws an out-degree per service
    from ``Zipf(exponent)`` (minus 1, so leaves are common), sorts the
    sequence descending, and attaches BFS-style so the biggest hubs sit
    near the entrypoint — a tree with exactly ``num_services - 1``
    edges, children called SEQUENTIALLY (the ingest self-closure
    fixture relies on sequential calls: concurrent groups are only
    inferable from span traces, not from aggregate expositions).

    ``sleep_choices`` / ``error_rate_choices`` draw one per-service
    value each from the rng (e.g. ``["1ms", "4ms"]`` /
    ``["0%", "2%"]``) so fitted-vs-source residuals exercise
    heterogeneous services, not one global constant.
    """
    n = num_services
    if n < 1:
        raise ValueError("need at least one service")
    rng = np.random.default_rng(seed)
    if max_degree is None:
        max_degree = max(n // 4, 1)
    # Zipf support starts at 1; shift so degree 0 (a leaf) is common
    degrees = np.minimum(rng.zipf(exponent, size=n) - 1, max_degree)
    degrees = np.sort(degrees)[::-1]
    # BFS attachment: hand out children (hub-first) until the n-1 edge
    # budget is spent; later services keep degree 0 and stay leaves
    children: List[List[int]] = [[] for _ in range(n)]
    next_child = 1
    for i in range(n):
        want = int(degrees[i])
        take = min(want, n - next_child)
        if take <= 0:
            continue
        children[i] = list(range(next_child, next_child + take))
        next_child += take
    if next_child < n:
        # degree draw too light for the budget: chain the remainder
        # off the last placed service so the graph stays connected
        for j in range(next_child, n):
            children[j - 1].append(j)
    services = []
    for i in range(n):
        svc: dict = {"name": f"{name_prefix}{i}"}
        if i == 0:
            svc["isEntrypoint"] = True
        if error_rate_choices:
            er = error_rate_choices[int(rng.integers(
                len(error_rate_choices)
            ))]
            if er not in ("0", "0%", 0, 0.0):
                svc["errorRate"] = er
        script: List = []
        if sleep_choices:
            sl = sleep_choices[int(rng.integers(len(sleep_choices)))]
            if sl not in ("0", "0s", None):
                script.append({"sleep": sl})
        script.extend(
            {"call": f"{name_prefix}{c}"} for c in children[i]
        )
        if script:
            svc["script"] = script
        services.append(svc)
    return {
        "defaults": {
            "requestSize": request_size,
            "responseSize": response_size,
            "numReplicas": num_replicas,
        },
        "services": services,
    }


def with_call_policy(
    doc: dict,
    timeout: Optional[str] = None,
    retries: Optional[int] = None,
) -> dict:
    """Annotate every call command with a timeout and/or retry policy.

    BASELINE configs[3] — "10k-service realistic graph with
    retries/timeouts" — is a generated topology plus the reference's
    per-call policy fields (Script extension, models/script.py).  The
    generators emit bare ``{call: name}`` commands; this rewrites them
    to the object form carrying the policy, leaving everything else
    untouched.
    """

    def rewrite(cmd):
        if isinstance(cmd, list):
            return [rewrite(c) for c in cmd]
        if isinstance(cmd, dict) and "call" in cmd:
            call = cmd["call"]
            if isinstance(call, str):
                call = {"service": call}
            else:
                call = dict(call)
            if timeout is not None:
                call["timeout"] = timeout
            if retries is not None:
                call["retries"] = retries
            return {**cmd, "call": call}
        return cmd

    services = []
    for svc in doc.get("services", []):
        copy = dict(svc)
        if "script" in copy:
            copy["script"] = [rewrite(c) for c in copy["script"]]
        services.append(copy)
    out = dict(doc, services=services)
    defaults = doc.get("defaults")
    if defaults and "script" in defaults:
        out["defaults"] = dict(
            defaults, script=[rewrite(c) for c in defaults["script"]]
        )
    return out


def replicate_topology(
    doc: dict,
    instances: int,
    prefix: str = "ns",
) -> dict:
    """N disjoint copies of a topology in one graph — the shape of the
    reference's large-scale load test (perf/load/common.sh:68-90: N
    namespaces each running its own service-graph instance with its own
    load client).  Service ``svc`` of instance ``i`` becomes
    ``<prefix><i>-svc``; every instance keeps its own entrypoint, so a
    driver can target any instance (``compile_graph(entry=...)``) or
    deploy all of them (the converter emits every service).
    """
    if instances < 1:
        raise ValueError("instances must be >= 1")
    if instances == 1:
        return doc

    def rename(name: str, i: int) -> str:
        return f"{prefix}{i}-{name}"

    def rewrite_command(cmd, i):
        if isinstance(cmd, list):
            return [rewrite_command(c, i) for c in cmd]
        if isinstance(cmd, dict) and "call" in cmd:
            call = cmd["call"]
            if isinstance(call, dict):
                call = dict(call, service=rename(call["service"], i))
            else:
                call = rename(call, i)
            return {**cmd, "call": call}
        return cmd

    # a defaults-level script would be inherited with UN-prefixed call
    # targets; materialize it per instance instead
    defaults = dict(doc.get("defaults", {}))
    default_script = defaults.pop("script", None)

    services = []
    for i in range(instances):
        for svc in doc.get("services", []):
            copy = dict(svc, name=rename(svc["name"], i))
            script = svc.get("script", default_script)
            if script is not None:
                copy["script"] = [
                    rewrite_command(c, i) for c in script
                ]
            services.append(copy)
    out = dict(doc, services=services)
    if "defaults" in doc:
        out["defaults"] = defaults
    return out
