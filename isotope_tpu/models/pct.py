"""Percentage value type.

Semantics match the reference's ``pct.Percentage``
(isotope/convert/pkg/graph/pct/percentage.go:26-93): a float in [0, 1],
decodable from a JSON/YAML number in [0, 1] or a string like "12.5%"
(interpreted as value/100, which must land in [0, 1]).
"""
from __future__ import annotations


class InvalidPercentageStringError(ValueError):
    def __init__(self, s: str):
        self.string = s
        super().__init__(f'invalid percentage string "{s}"')


class OutOfRangeError(ValueError):
    def __init__(self, f: float):
        self.value = f
        super().__init__(f"percentage out of range [0, 1]: {f}")


class Percentage(float):
    """A float between 0 and 1, renderable as "X.XX%"."""

    def __str__(self) -> str:  # percentage.go:28-30
        return f"{float(self) * 100:.2f}%"

    @classmethod
    def from_string(cls, s: str) -> "Percentage":
        # percentage.go:69-81: require a '%', parse the prefix, divide by 100.
        idx = s.find("%")
        if idx < 0:
            raise InvalidPercentageStringError(s)
        try:
            f = float(s[:idx])
        except ValueError:
            raise InvalidPercentageStringError(s) from None
        return cls.from_float(f / 100)

    @classmethod
    def from_float(cls, f: float) -> "Percentage":
        # percentage.go:84-93: valid iff 0 <= f <= 1.
        if 0 <= f <= 1:
            return cls(f)
        raise OutOfRangeError(f)

    @classmethod
    def decode(cls, value) -> "Percentage":
        """Decode from a parsed YAML/JSON value (str or number)."""
        if isinstance(value, str):
            return cls.from_string(value)
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise InvalidPercentageStringError(repr(value))
        return cls.from_float(float(value))

    def encode(self) -> float:
        """Marshal as a JSON number (percentage.go:33-35)."""
        return float(self)
