"""ByteSize value type.

Semantics match the reference's ``size.ByteSize``
(isotope/convert/pkg/graph/size/byte_size.go:25-83), which delegates string
parsing to docker/go-units ``RAMInBytes`` (binary, 1024-based, suffixes
b/k/m/g/t/p with optional "b"/"ib") and formats with ``BytesSize``
(4-significant-digit binary units: "1KiB", "1.5MiB").
"""
from __future__ import annotations

import re

_RAM_RE = re.compile(r"^(\d+(?:\.\d+)*) ?([kKmMgGtTpP])?([iI])?[bB]?$")

_EXP = {"": 0, "k": 1, "m": 2, "g": 3, "t": 4, "p": 5}

_BINARY_ABBRS = ["B", "KiB", "MiB", "GiB", "TiB", "PiB", "EiB", "ZiB", "YiB"]


class InvalidSizeStringError(ValueError):
    def __init__(self, s: str):
        self.string = s
        super().__init__(f"invalid size: '{s}'")


class NegativeSizeError(ValueError):
    def __init__(self, x: int):
        self.value = x
        super().__init__(f"size must be non-negative: {x}")


class ByteSize(int):
    """A non-negative number of bytes."""

    def __str__(self) -> str:
        # go-units BytesSize: binary units, %.4g precision.
        size = float(int(self))
        i = 0
        while size >= 1024.0 and i < len(_BINARY_ABBRS) - 1:
            size /= 1024.0
            i += 1
        return f"{size:.4g}{_BINARY_ABBRS[i]}"

    @classmethod
    def from_string(cls, s: str) -> "ByteSize":
        # go-units RAMInBytes: "10k" == 10 KiB == 10240; "16 MiB"; "32".
        m = _RAM_RE.match(s.strip())
        if m is None:
            raise InvalidSizeStringError(s)
        try:
            value = float(m.group(1))
        except ValueError:
            # go-units' regex admits "32.3.4" but ParseFloat then rejects it.
            raise InvalidSizeStringError(s) from None
        unit = (m.group(2) or "").lower()
        return cls.from_int(int(value * 1024 ** _EXP[unit]))

    @classmethod
    def from_int(cls, x: int) -> "ByteSize":
        # byte_size.go:76-83: non-negative only.
        if x < 0:
            raise NegativeSizeError(x)
        return cls(x)

    @classmethod
    def decode(cls, value) -> "ByteSize":
        """Decode from a parsed YAML/JSON value (str or integer)."""
        if isinstance(value, str):
            return cls.from_string(value)
        if isinstance(value, bool) or not isinstance(value, int):
            if isinstance(value, float) and value.is_integer():
                value = int(value)
            else:
                raise InvalidSizeStringError(repr(value))
        return cls.from_int(value)

    def encode(self):
        """Marshal for YAML/JSON (byte_size.go:33-36).

        go-units' %.4g formatting is lossy for non-round sizes ("120.6KiB"
        re-decodes to a different byte count), which would silently perturb
        payload sizes on a load/save/deploy cycle.  Emit the pretty string
        only when it round-trips exactly; otherwise emit the plain integer
        (also valid input, byte_size.go:44-52).
        """
        pretty = str(self)
        if int(ByteSize.from_string(pretty)) == int(self):
            return pretty
        return int(self)
