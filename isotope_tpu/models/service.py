"""Service node type.

Mirrors ``svc.Service`` (isotope/convert/pkg/graph/svc/service.go:25-51):
name, type, numReplicas, isEntrypoint, errorRate, responseSize, script,
numRbacPolicies — with defaults applied from the graph-level ``defaults``
block during decode (svc/unmarshal.go:29-41).
"""
from __future__ import annotations

import dataclasses

from isotope_tpu.models.errors import config_path
from isotope_tpu.models.pct import Percentage
from isotope_tpu.models.script import RequestCommand, Script
from isotope_tpu.models.size import ByteSize
from isotope_tpu.models.svctype import ServiceType


class EmptyNameError(ValueError):
    def __init__(self):
        super().__init__("services must have a name")


_FIELDS = {
    "name",
    "type",
    "numReplicas",
    "isEntrypoint",
    "errorRate",
    "responseSize",
    "script",
    "numRbacPolicies",
    "cluster",
}


@dataclasses.dataclass
class Service:
    name: str
    type: ServiceType = ServiceType.HTTP
    num_replicas: int = 1
    is_entrypoint: bool = False
    error_rate: Percentage = Percentage(0.0)
    response_size: ByteSize = ByteSize(0)
    script: Script = dataclasses.field(default_factory=Script)
    num_rbac_policies: int = 0
    # Extension beyond svc.Service: the reference splits one service
    # graph across cluster1/cluster2 (+ VM workloads) at the helm layer
    # (perf/load/templates/service-graph.gen.yaml:1-3, common.sh:36-42)
    # so cross-cluster edges traverse egress/ingress gateways.  Here the
    # placement is a first-class topology field; "" = the default
    # cluster.  Cross-cluster edges pay NetworkModel's cross-cluster
    # latency/bandwidth class.
    cluster: str = ""

    @classmethod
    def decode(
        cls,
        value: dict,
        default: "Service",
        default_request: RequestCommand,
    ) -> "Service":
        if not isinstance(value, dict):
            raise ValueError(f"service must be a mapping: {value!r}")
        unknown = set(value) - _FIELDS
        if unknown:
            raise ValueError(f"unknown service fields: {sorted(unknown)}")
        name = value.get("name", "")
        if not name:
            raise EmptyNameError()

        def field(key, decode, fallback):
            if key not in value:
                return fallback
            with config_path(key):
                return decode(value[key])

        return cls(
            name=name,
            type=field("type", ServiceType.decode, default.type),
            num_replicas=field(
                "numReplicas",
                lambda v: decode_strict_int(v, "numReplicas"),
                default.num_replicas,
            ),
            is_entrypoint=bool(value.get("isEntrypoint", default.is_entrypoint)),
            error_rate=field(
                "errorRate", Percentage.decode, default.error_rate
            ),
            response_size=field(
                "responseSize", ByteSize.decode, default.response_size
            ),
            script=field(
                "script",
                lambda v: Script.decode(v, default_request),
                Script(default.script),
            ),
            num_rbac_policies=field(
                "numRbacPolicies",
                lambda v: decode_strict_int(v, "numRbacPolicies"),
                default.num_rbac_policies,
            ),
            cluster=field("cluster", decode_cluster, default.cluster),
        )

    def encode(self, default: "Service | None" = None) -> dict:
        """Marshal to a plain dict, omitting fields equal to ``default``.

        ``default`` must be the same effective default Service the graph was
        decoded with so that decode(encode(g)) round-trips even when the
        graph-level ``defaults`` block overrides built-in defaults.
        """
        if default is None:
            default = DEFAULT_SERVICE
        out: dict = {"name": self.name}
        if self.type != default.type:
            out["type"] = self.type.encode()
        if self.num_replicas != default.num_replicas:
            out["numReplicas"] = self.num_replicas
        if self.is_entrypoint:
            out["isEntrypoint"] = True
        if float(self.error_rate) != float(default.error_rate):
            out["errorRate"] = self.error_rate.encode()
        if int(self.response_size) != int(default.response_size):
            out["responseSize"] = self.response_size.encode()
        if list(self.script) != list(default.script):
            out["script"] = self.script.encode()
        if self.num_rbac_policies != default.num_rbac_policies:
            out["numRbacPolicies"] = self.num_rbac_policies
        if self.cluster != default.cluster:
            out["cluster"] = self.cluster
        return out


def decode_strict_int(value, field: str) -> int:
    """Reject bools and non-integers (YAML typos should fail loudly)."""
    if isinstance(value, bool) or not isinstance(value, int):
        raise ValueError(f"{field} must be an integer: {value!r}")
    return value


def decode_cluster(value) -> str:
    if not isinstance(value, str):
        raise ValueError(f"cluster must be a string: {value!r}")
    return value


DEFAULT_SERVICE = Service(name="", type=ServiceType.HTTP, num_replicas=1)
