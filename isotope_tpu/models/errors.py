"""Config-path error context for the YAML/TOML decode pipelines.

A loader error used to surface as a bare exception ("timeout must be a
duration string: 5") with no hint WHERE in a 10k-service document the
bad value sits.  :func:`config_path` wraps each decode scope with its
key-path segment; a ``ValueError`` bubbling through gains the joined
path (``services[3].script[1].sleep: ...``) while keeping its ORIGINAL
exception type — unit tests and callers matching on
``InvalidCommandError`` etc. see the same classes, just better
messages.

The path is accumulated on the exception object itself
(``e.config_path`` / ``e.config_base_msg``) so nesting composes from
the inside out without double-prefixing.
"""
from __future__ import annotations

import contextlib
from typing import Iterator


def _join(outer: str, inner: str) -> str:
    if not inner:
        return outer
    if inner.startswith("["):
        return outer + inner
    return f"{outer}.{inner}"


@contextlib.contextmanager
def config_path(segment: str) -> Iterator[None]:
    """Annotate any ValueError escaping this scope with ``segment``.

    Segments compose: ``services[3]`` around ``script`` around ``[1]``
    around ``sleep`` renders as ``services[3].script[1].sleep``.
    """
    try:
        yield
    except ValueError as e:
        prev = getattr(e, "config_path", "")
        base = getattr(e, "config_base_msg", None)
        if base is None:
            base = str(e)
        path = _join(segment, prev)
        e.config_path = path
        e.config_base_msg = base
        e.args = (f"{path}: {base}",)
        raise
