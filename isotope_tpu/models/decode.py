"""Shared scalar decoders for the topology control-plane YAML blocks.

``sim/policies.py`` (the ``policies:`` block) and ``sim/rollout.py``
(the ``rollouts:`` block) validate their configuration with the same
scalar vocabulary — durations ("30s" or seconds), fractions ("5%" or
0.05), plain numbers, integers — and the same optional-field idiom:
an absent or explicit-``null`` key falls back to the default, a
present value decodes under a key-pathed error context
(``models.errors.config_path``).  One copy here keeps the two blocks'
validation behavior from silently diverging.
"""
from __future__ import annotations

from isotope_tpu.models.errors import config_path
from isotope_tpu.models.pct import Percentage
from isotope_tpu.utils import duration as dur


def duration_s(value) -> float:
    """Seconds from a duration string ("250ms", "30s") or a number."""
    if isinstance(value, str):
        return dur.parse_duration_seconds(value)
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise ValueError(f"expected a duration: {value!r}")
    return float(value)


def fraction(value) -> float:
    """A fraction in [0, 1]: a number, or a percent string ("60%")."""
    return float(Percentage.decode(value))


def number(value) -> float:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise ValueError(f"expected a number: {value!r}")
    return float(value)


def integer(value) -> int:
    if isinstance(value, bool) or not isinstance(value, int):
        raise ValueError(f"expected an integer: {value!r}")
    return value


def keyword(value, options) -> str:
    """A string drawn from a closed vocabulary (e.g. the ``lb:`` law
    names) — anything else names the valid options in the error."""
    if not isinstance(value, str) or value not in options:
        raise ValueError(
            f"expected one of {'/'.join(options)}: {value!r}"
        )
    return value


def field(mapping: dict, key: str, decode, fallback):
    """Decode ``mapping[key]`` under a key-pathed error context, or the
    fallback when the key is absent or explicitly ``null``."""
    if key not in mapping or mapping[key] is None:
        return fallback
    with config_path(key):
        return decode(mapping[key])
