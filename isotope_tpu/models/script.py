"""Script / command grammar.

Mirrors the reference's polymorphic command decode
(isotope/convert/pkg/graph/script/command.go:73-105):

- a YAML list is a ``ConcurrentCommand`` (all sub-commands fan out in
  parallel);
- a single-key mapping is either ``{sleep: <Go duration>}`` or
  ``{call: <service name | {service, size, probability}>}``;
- multiple keys or unknown keys are errors.

A ``Script`` is an ordered list of commands executed sequentially
(script.go:22; srv/handler.go:66-76).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Union

from isotope_tpu.models.errors import config_path
from isotope_tpu.models.size import ByteSize
from isotope_tpu.utils import duration

SLEEP_COMMAND_KEY = "sleep"
REQUEST_COMMAND_KEY = "call"


class MultipleKeysInCommandError(ValueError):
    def __init__(self, mapping):
        super().__init__(f"multiple keys for command: {mapping}")


class UnknownCommandKeyError(ValueError):
    def __init__(self, key):
        self.key = key
        super().__init__(f"unknown command: {key}")


class InvalidCommandError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class SleepCommand:
    """Pause script execution (sleep_command.go:23-38).

    ``seconds`` holds the parsed Go duration.
    """

    seconds: float

    def __str__(self) -> str:
        return duration.format_duration_seconds(self.seconds)

    @classmethod
    def decode(cls, value: str) -> "SleepCommand":
        if not isinstance(value, str):
            raise InvalidCommandError(f"sleep duration must be a string: {value!r}")
        return cls(duration.parse_duration_seconds(value))

    def encode(self):
        return {SLEEP_COMMAND_KEY: str(self)}


@dataclasses.dataclass(frozen=True)
class RequestCommand:
    """Call another service (request_command.go:26-66).

    ``probability`` is an int percentage in [0, 100]; 0 means "always send"
    (matching srv/executable.go:84-90's shouldSkipRequest).

    ``timeout`` (seconds) and ``retries`` are extensions beyond the
    reference's call grammar: the reference delegates both to Istio
    VirtualService policy outside the topology spec, while the simulator
    models them at the call site.  ``timeout=None`` means no timeout;
    ``retries`` counts extra attempts after a failed one (a failure is a
    5xx response, a connection failure, or a timeout — Envoy's
    ``retry-on`` defaults).
    """

    service_name: str
    size: ByteSize = ByteSize(0)
    probability: int = 0
    timeout: float | None = None
    retries: int = 0

    @classmethod
    def decode(cls, value, default: "RequestCommand") -> "RequestCommand":
        # String form: just the service name, defaults fill the rest
        # (request_command.go:43-50).
        if isinstance(value, str):
            return cls(
                service_name=value,
                size=default.size,
                probability=default.probability,
                timeout=default.timeout,
                retries=default.retries,
            )
        if not isinstance(value, dict):
            raise InvalidCommandError(f"invalid call command: {value!r}")
        unknown = set(value) - {
            "service", "size", "probability", "timeout", "retries",
        }
        if unknown:
            raise InvalidCommandError(f"unknown call fields: {sorted(unknown)}")
        size = (
            ByteSize.decode(value["size"]) if "size" in value else default.size
        )
        probability = value.get("probability", default.probability)
        if (
            isinstance(probability, bool)
            or not isinstance(probability, int)
            or not 0 <= probability <= 100
        ):
            # request_command.go:60-62
            raise InvalidCommandError(
                "math: invalid probability, outside range: [0,100]"
            )
        if "timeout" in value:
            if not isinstance(value["timeout"], str):
                raise InvalidCommandError(
                    f"timeout must be a duration string: {value['timeout']!r}"
                )
            timeout = duration.parse_duration_seconds(value["timeout"])
            if timeout <= 0:
                raise InvalidCommandError("timeout must be positive")
        else:
            timeout = default.timeout
        retries = value.get("retries", default.retries)
        if (
            isinstance(retries, bool)
            or not isinstance(retries, int)
            or retries < 0
        ):
            raise InvalidCommandError(
                f"retries must be a non-negative integer: {retries!r}"
            )
        return cls(
            service_name=value.get("service", default.service_name),
            size=size,
            probability=probability,
            timeout=timeout,
            retries=retries,
        )

    def encode(self):
        body: dict = {"service": self.service_name, "size": self.size.encode()}
        if self.probability:
            body["probability"] = self.probability
        if self.timeout is not None:
            body["timeout"] = duration.format_duration_seconds(self.timeout)
        if self.retries:
            body["retries"] = self.retries
        return {REQUEST_COMMAND_KEY: body}

    @property
    def send_probability(self) -> float:
        """Chance the call is made, in [0, 1]. probability==0 => always."""
        return 1.0 if self.probability == 0 else self.probability / 100.0


class ConcurrentCommand(list):
    """A list of commands that fan out in parallel (concurrent_command.go:19).

    May not contain another ConcurrentCommand (validation.go:48-55).
    """

    def encode(self):
        return [cmd.encode() for cmd in self]


Command = Union[SleepCommand, RequestCommand, ConcurrentCommand]


def decode_command(value: Any, default_request: RequestCommand) -> Command:
    if isinstance(value, list):
        out = ConcurrentCommand()
        for i, v in enumerate(value):
            with config_path(f"[{i}]"):
                out.append(decode_command(v, default_request))
        return out
    if isinstance(value, dict):
        if len(value) > 1:
            raise MultipleKeysInCommandError(value)
        if len(value) == 0:
            raise InvalidCommandError("empty command mapping")
        (key, body), = value.items()
        if key == SLEEP_COMMAND_KEY:
            with config_path(SLEEP_COMMAND_KEY):
                return SleepCommand.decode(body)
        if key == REQUEST_COMMAND_KEY:
            with config_path(REQUEST_COMMAND_KEY):
                return RequestCommand.decode(body, default_request)
        raise UnknownCommandKeyError(key)
    raise InvalidCommandError(f"invalid command: {value!r}")


class Script(list):
    """Ordered list of commands executed sequentially."""

    @classmethod
    def decode(cls, value, default_request: RequestCommand) -> "Script":
        if value is None:
            return cls()
        if not isinstance(value, list):
            raise InvalidCommandError(f"script must be a list: {value!r}")
        out = cls()
        for i, v in enumerate(value):
            with config_path(f"[{i}]"):
                out.append(decode_command(v, default_request))
        return out

    def encode(self):
        return [cmd.encode() for cmd in self]
