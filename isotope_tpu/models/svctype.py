"""ServiceType enum.

Mirrors ``svctype.ServiceType`` (isotope/convert/pkg/graph/svctype/
service_type.go:26-85): {unknown, http, grpc}, decoded from the lowercase
strings "http" / "grpc".
"""
from __future__ import annotations

import enum


class InvalidServiceTypeStringError(ValueError):
    def __init__(self, s: str):
        self.string = s
        super().__init__(f"unknown service type: {s}")


class ServiceType(enum.IntEnum):
    UNKNOWN = 0
    HTTP = 1
    GRPC = 2

    def __str__(self) -> str:
        if self is ServiceType.HTTP:
            return "HTTP"
        if self is ServiceType.GRPC:
            return "gRPC"
        return ""

    @classmethod
    def from_string(cls, s: str) -> "ServiceType":
        if s == "http":
            return cls.HTTP
        if s == "grpc":
            return cls.GRPC
        raise InvalidServiceTypeStringError(s)

    @classmethod
    def decode(cls, value) -> "ServiceType":
        if isinstance(value, cls):
            return value
        if not isinstance(value, str):
            raise InvalidServiceTypeStringError(repr(value))
        return cls.from_string(value)

    def encode(self) -> str:
        return str(self).lower()
