from isotope_tpu.models.pct import Percentage
from isotope_tpu.models.size import ByteSize
from isotope_tpu.models.svctype import ServiceType
from isotope_tpu.models.script import (
    Command,
    ConcurrentCommand,
    RequestCommand,
    Script,
    SleepCommand,
)
from isotope_tpu.models.service import Service
from isotope_tpu.models.graph import ServiceGraph

__all__ = [
    "Percentage",
    "ByteSize",
    "ServiceType",
    "Command",
    "SleepCommand",
    "RequestCommand",
    "ConcurrentCommand",
    "Script",
    "Service",
    "ServiceGraph",
]
