"""ServiceGraph: the L0 topology IR.

Mirrors ``graph.ServiceGraph`` (isotope/convert/pkg/graph/graph.go:21-23)
plus the decode pipeline (unmarshal.go:30-112): a top-level ``defaults``
block seeds per-service and per-call defaults (type=http, numReplicas=1 when
absent), then each service is decoded against those defaults and the result
is validated (validation.go:28-67): every call must target a defined
service, and concurrent commands may not nest.
"""
from __future__ import annotations

import dataclasses
from typing import List

import yaml

from isotope_tpu.models.errors import config_path
from isotope_tpu.models.pct import Percentage
from isotope_tpu.models.script import (
    ConcurrentCommand,
    RequestCommand,
    Script,
)
from isotope_tpu.models.service import (
    Service,
    decode_cluster,
    decode_strict_int,
)
from isotope_tpu.models.size import ByteSize
from isotope_tpu.models.svctype import ServiceType


class RequestToUndefinedServiceError(ValueError):
    def __init__(self, service_name: str):
        self.service_name = service_name
        super().__init__(f'cannot call undefined service "{service_name}"')


class NestedConcurrentCommandError(ValueError):
    def __init__(self):
        super().__init__("concurrent commands may not be nested")


_DEFAULTS_FIELDS = {
    "type",
    "errorRate",
    "responseSize",
    "script",
    "requestSize",
    "numReplicas",
    "numRbacPolicies",
    "cluster",
}


@dataclasses.dataclass
class ServiceGraph:
    services: List[Service] = dataclasses.field(default_factory=list)
    # Retained so encode() can round-trip the defaults block.
    defaults: dict = dataclasses.field(default_factory=dict)
    # Raw ``policies:`` block (in-graph resilience policies — circuit
    # breakers, retry budgets, HPA autoscalers; sim/policies.py — plus
    # the per-service ``lb:`` load-balancing laws; sim/lb.py).  Kept
    # raw here so host-only consumers (converters, encode round-trip)
    # never pay the decode; the compiler lowers it to dense per-service
    # tables (compiler/compile.py compile_policies / compile_lb) with
    # key-pathed validation errors.
    policies: dict = dataclasses.field(default_factory=dict)
    # Raw ``rollouts:`` block (reactive canary rollouts — per-service
    # step schedules, SLO gates, rollback policies, canary physics
    # overrides; sim/rollout.py).  Same raw-until-compiled discipline
    # as ``policies`` (compiler/compile.py compile_rollouts).
    rollouts: dict = dataclasses.field(default_factory=dict)

    # -- decode ------------------------------------------------------------

    @classmethod
    def decode(cls, doc: dict) -> "ServiceGraph":
        if not isinstance(doc, dict):
            raise ValueError(f"service graph must be a mapping: {doc!r}")
        raw_defaults = doc.get("defaults") or {}
        with config_path("defaults"):
            default_service, default_request = _effective_defaults(
                raw_defaults
            )
        services = []
        for i, s in enumerate(doc.get("services") or []):
            with config_path(f"services[{i}]"):
                services.append(
                    Service.decode(s, default_service, default_request)
                )
        raw_policies = doc.get("policies") or {}
        if not isinstance(raw_policies, dict):
            with config_path("policies"):
                raise ValueError(
                    f"policies must be a mapping: {raw_policies!r}"
                )
        raw_rollouts = doc.get("rollouts") or {}
        if not isinstance(raw_rollouts, dict):
            with config_path("rollouts"):
                raise ValueError(
                    f"rollouts must be a mapping: {raw_rollouts!r}"
                )
        graph = cls(
            services=services,
            defaults=dict(raw_defaults),
            policies=dict(raw_policies),
            rollouts=dict(raw_rollouts),
        )
        graph.validate()
        return graph

    @classmethod
    def from_yaml(cls, text: str) -> "ServiceGraph":
        return cls.decode(yaml.safe_load(text))

    @classmethod
    def from_yaml_file(cls, path) -> "ServiceGraph":
        with open(path) as f:
            return cls.decode(yaml.safe_load(f))

    # -- encode ------------------------------------------------------------

    def encode(self) -> dict:
        out: dict = {}
        if self.defaults:
            out["defaults"] = dict(self.defaults)
        default_service, _ = _effective_defaults(self.defaults)
        out["services"] = [s.encode(default_service) for s in self.services]
        if self.policies:
            out["policies"] = dict(self.policies)
        if self.rollouts:
            out["rollouts"] = dict(self.rollouts)
        return out

    def to_yaml(self) -> str:
        return yaml.safe_dump(
            self.encode(), default_flow_style=False, sort_keys=False
        )

    # -- validation (validation.go:28-67) ----------------------------------

    def validate(self) -> None:
        names = {s.name for s in self.services}
        for i, service in enumerate(self.services):
            with config_path(f"services[{i}].script"):
                _validate_commands(service.script, names)

    # -- convenience -------------------------------------------------------

    def service_names(self) -> List[str]:
        return [s.name for s in self.services]

    def entrypoints(self) -> List[Service]:
        return [s for s in self.services if s.is_entrypoint]

    def __len__(self) -> int:
        return len(self.services)


def _effective_defaults(raw_defaults: dict):
    """Build the effective per-service / per-call defaults from a raw
    ``defaults`` block (unmarshal.go:66-112)."""
    unknown = set(raw_defaults) - _DEFAULTS_FIELDS
    if unknown:
        raise ValueError(f"unknown defaults fields: {sorted(unknown)}")

    def field(key, decode, fallback):
        if key not in raw_defaults:
            return fallback
        with config_path(key):
            return decode(raw_defaults[key])

    # Per-call default: requestSize seeds RequestCommand.Size
    # (unmarshal.go:104-107).
    default_request = RequestCommand(
        service_name="",
        size=field("requestSize", ByteSize.decode, ByteSize(0)),
    )
    # Per-service defaults (unmarshal.go:66-73, 96-103): type=http,
    # numReplicas=1 unless overridden.
    default_service = Service(
        name="",
        type=field("type", ServiceType.decode, ServiceType.HTTP),
        num_replicas=field(
            "numReplicas",
            lambda v: decode_strict_int(v, "numReplicas"),
            1,
        ),
        error_rate=field("errorRate", Percentage.decode, Percentage(0.0)),
        response_size=field("responseSize", ByteSize.decode, ByteSize(0)),
        # In the reference the defaults block is unmarshaled in the
        # metadata pass BEFORE DefaultRequestCommand is installed
        # (unmarshal.go:30-43), so calls inside the defaults script do
        # NOT inherit requestSize — they get a zero-size default.
        script=field(
            "script",
            lambda v: Script.decode(v, RequestCommand(service_name="")),
            Script(),
        ),
        num_rbac_policies=field(
            "numRbacPolicies",
            lambda v: decode_strict_int(v, "numRbacPolicies"),
            0,
        ),
        cluster=field("cluster", decode_cluster, ""),
    )
    return default_service, default_request


def _validate_commands(cmds, names) -> None:
    for cmd in cmds:
        if isinstance(cmd, RequestCommand):
            if cmd.service_name not in names:
                raise RequestToUndefinedServiceError(cmd.service_name)
        elif isinstance(cmd, ConcurrentCommand):
            _validate_commands(cmd, names)
            if any(isinstance(sub, ConcurrentCommand) for sub in cmd):
                raise NestedConcurrentCommandError()
