"""Dashboard-lite: a static HTML report from sweep artifacts.

The reference ships a Django dashboard (perf_dashboard/benchmarks/
views.py) that downloads published benchmark CSVs and renders latency /
CPU-vs-QPS/connection comparisons plus master-vs-release regression
views.  The sim's artifacts are local, so the whole dashboard collapses
to one self-contained HTML file: inline-SVG line charts (no external
assets, works offline), the full results table, and — given a baseline
run directory — a run-vs-run regression table with per-metric deltas.

Charts follow the dataviz method: categorical series colors assigned in
fixed slot order (the validated reference palette, light + dark steps
via CSS custom properties), 2px lines with >=8px hover targets, one
axis per chart, recessive grid, a legend for >=2 series, and the
results table as the always-available text alternative.
"""
from __future__ import annotations

import html
import json
import math
import os
import pathlib
import re
import urllib.parse
from typing import Dict, List, Optional, Sequence, Tuple

LATENCY_METRICS = ("p50", "p75", "p90", "p99", "p999")

# validated reference categorical palette (dataviz skill): light / dark
# steps of the same hues, in the fixed slot order that passes the
# adjacent-pair CVD checks in both modes
_SERIES_LIGHT = ("#2a78d6", "#eb6834", "#1baf7a", "#eda100", "#e87ba4",
                 "#008300", "#4a3aa7", "#e34948")
_SERIES_DARK = ("#3987e5", "#d95926", "#199e70", "#c98500", "#d55181",
                "#008300", "#9085e9", "#e66767")

_LABEL_RE = re.compile(
    r"^(?P<series>.+?)_(?P<qps>[0-9.]+(?:e[+-]?[0-9]+)?|max)qps_\d+c"
)


def _series_of(label: str) -> str:
    m = _LABEL_RE.match(str(label))
    return m.group("series") if m else str(label)


def load_results(results_dir) -> List[dict]:
    """The flat records of a sweep (results.jsonl)."""
    path = pathlib.Path(results_dir) / "results.jsonl"
    if not path.exists():
        raise FileNotFoundError(
            f"{path} not found — point at a sweep output directory"
        )
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    if not rows:
        raise ValueError(f"{path} is empty")
    return rows


# -- inline-SVG line chart --------------------------------------------------


def _ticks(lo: float, hi: float, n: int = 5) -> List[float]:
    if hi <= lo:
        hi = lo + 1.0
    span = hi - lo
    raw = span / max(n - 1, 1)
    mag = 10.0 ** math.floor(math.log10(raw))
    for step in (1, 2, 2.5, 5, 10):
        if raw <= step * mag:
            raw = step * mag
            break
    first = (int(lo / raw)) * raw
    ticks = []
    t = first
    while t <= hi + 1e-9:
        if t >= lo - 1e-9:
            ticks.append(round(t, 10))
        t += raw
    return ticks or [lo, hi]


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:g}"


def svg_line_chart(
    series: Dict[str, List[Tuple[float, float]]],
    title: str,
    x_label: str,
    y_label: str,
    width: int = 520,
    height: int = 300,
) -> str:
    """One SVG line chart: series colored by fixed slot order, 2px
    lines, 8px hover targets with native tooltips, recessive grid."""
    ml, mr, mt, mb = 56, 16, 34, 42
    pw, ph = width - ml - mr, height - mt - mb
    xs = [x for pts in series.values() for x, _ in pts]
    ys = [y for pts in series.values() for _, y in pts]
    if not xs:
        return ""
    x_lo, x_hi = min(xs), max(xs)
    y_lo, y_hi = 0.0, max(ys) * 1.08 if max(ys) > 0 else 1.0
    if x_hi == x_lo:
        x_lo, x_hi = x_lo - 1, x_hi + 1

    def X(v):
        return ml + (v - x_lo) / (x_hi - x_lo) * pw

    def Y(v):
        return mt + ph - (v - y_lo) / (y_hi - y_lo) * ph

    parts = [
        f'<svg viewBox="0 0 {width} {height}" role="img" '
        f'aria-label="{html.escape(title)}">',
        f'<text x="{ml}" y="18" class="chart-title">'
        f"{html.escape(title)}</text>",
    ]
    for t in _ticks(y_lo, y_hi):
        y = Y(t)
        parts.append(
            f'<line x1="{ml}" y1="{y:.1f}" x2="{ml + pw}" y2="{y:.1f}" '
            'class="grid"/>'
        )
        parts.append(
            f'<text x="{ml - 6}" y="{y + 4:.1f}" text-anchor="end" '
            f'class="tick">{_fmt(t)}</text>'
        )
    for t in _ticks(x_lo, x_hi):
        x = X(t)
        parts.append(
            f'<text x="{x:.1f}" y="{mt + ph + 16}" text-anchor="middle" '
            f'class="tick">{_fmt(t)}</text>'
        )
    parts.append(
        f'<line x1="{ml}" y1="{mt + ph}" x2="{ml + pw}" y2="{mt + ph}" '
        'class="axis"/>'
    )
    for i, (name, pts) in enumerate(series.items()):
        slot = i % len(_SERIES_LIGHT)
        pts = sorted(pts)
        path = " ".join(
            f"{'M' if j == 0 else 'L'}{X(x):.1f},{Y(y):.1f}"
            for j, (x, y) in enumerate(pts)
        )
        parts.append(
            f'<path d="{path}" fill="none" class="s{slot}" '
            'stroke-width="2"/>'
        )
        for x, y in pts:
            # 8px hit target with a native tooltip; visible 3px dot
            parts.append(
                f'<circle cx="{X(x):.1f}" cy="{Y(y):.1f}" r="3" '
                f'class="s{slot} dot"/>'
                f'<circle cx="{X(x):.1f}" cy="{Y(y):.1f}" r="8" '
                f'fill="transparent" stroke="none">'
                f"<title>{html.escape(name)}\n{x_label}={_fmt(x)} "
                f"{y_label}={y:g}</title></circle>"
            )
    parts.append(
        f'<text x="{ml + pw / 2:.0f}" y="{height - 8}" '
        f'text-anchor="middle" class="axis-label">'
        f"{html.escape(x_label)}</text>"
    )
    parts.append(
        f'<text x="14" y="{mt + ph / 2:.0f}" text-anchor="middle" '
        f'transform="rotate(-90 14 {mt + ph / 2:.0f})" '
        f'class="axis-label">{html.escape(y_label)}</text>'
    )
    parts.append("</svg>")
    return "".join(parts)


def _legend(names: Sequence[str]) -> str:
    items = "".join(
        f'<span class="legend-item"><span class="swatch '
        f's{i % len(_SERIES_LIGHT)}"></span>{html.escape(n)}</span>'
        for i, n in enumerate(names)
    )
    return f'<div class="legend">{items}</div>'


# -- report assembly --------------------------------------------------------


def _group_series(rows: Sequence[dict], x_col: str, y_col: str):
    out: Dict[str, List[Tuple[float, float]]] = {}
    for r in rows:
        y = r.get(y_col)
        if not isinstance(y, (int, float)):
            continue
        x = float(r[x_col])
        out.setdefault(_series_of(r["Labels"]), []).append((x, float(y)))
    return out


def _pick_x(rows: Sequence[dict]) -> Tuple[str, str]:
    conns = {r["NumThreads"] for r in rows}
    if len(conns) > 1:
        return "NumThreads", "Connections"
    return "ActualQPS", "QPS"


_CSS = """
:root { color-scheme: light dark; }
body {
  margin: 2rem; background: #fcfcfb; color: #0b0b0b;
  font: 14px/1.5 system-ui, sans-serif;
}
h1, h2 { font-weight: 600; }
.tiles { display: flex; gap: 1rem; flex-wrap: wrap; margin: 1rem 0; }
.tile {
  border: 1px solid #d8d7d3; border-radius: 8px; padding: .8rem 1.2rem;
  min-width: 8rem;
}
.tile .v { font-size: 1.6rem; font-weight: 600; }
.tile .k { color: #52514e; font-size: .85rem; }
.charts { display: flex; flex-wrap: wrap; gap: 1.5rem; }
figure { margin: 0; }
.chart-title { font-size: 13px; font-weight: 600; fill: #0b0b0b; }
.tick { font-size: 11px; fill: #52514e; }
.axis-label { font-size: 12px; fill: #52514e; }
.grid { stroke: #0b0b0b; stroke-opacity: .08; }
.axis { stroke: #52514e; }
.legend { margin: .4rem 0 1rem; }
.legend-item { margin-right: 1rem; white-space: nowrap; }
.swatch {
  display: inline-block; width: 10px; height: 10px; border-radius: 2px;
  margin-right: .35rem;
}
table { border-collapse: collapse; margin: 1rem 0; }
th, td { border: 1px solid #d8d7d3; padding: .35rem .6rem; text-align: right; }
th:first-child, td:first-child { text-align: left; }
th { background: #f1f0ec; }
.regress { color: #a11a1a; font-weight: 600; }
.improve { color: #0a6b0a; font-weight: 600; }
.discarded td { opacity: .5; }
.spark { vertical-align: middle; }
SERIES_CSS
@media (prefers-color-scheme: dark) {
  body { background: #1a1a19; color: #ffffff; }
  .tile { border-color: #3a3a38; }
  .tile .k, .tick, .axis-label { fill: #c3c2b7; color: #c3c2b7; }
  .chart-title { fill: #ffffff; }
  .grid { stroke: #ffffff; stroke-opacity: .1; }
  .axis { stroke: #c3c2b7; }
  th { background: #242423; }
  th, td { border-color: #3a3a38; }
  .regress { color: #e66767; }
  .improve { color: #31b058; }
  SERIES_DARK_CSS
}
"""


def _series_css() -> Tuple[str, str]:
    light = "\n".join(
        f".s{i} {{ stroke: {c}; }} .swatch.s{i} {{ background: {c}; }} "
        f".dot.s{i} {{ fill: {c}; stroke: none; }}"
        for i, c in enumerate(_SERIES_LIGHT)
    )
    dark = "\n".join(
        f"  .s{i} {{ stroke: {c}; }} .swatch.s{i} {{ background: {c}; }} "
        f".dot.s{i} {{ fill: {c}; stroke: none; }}"
        for i, c in enumerate(_SERIES_DARK)
    )
    return light, dark


_TABLE_COLS = (
    ("Labels", "run"),
    ("ActualQPS", "qps"),
    ("NumThreads", "conns"),
    ("p50", "p50 (µs)"),
    ("p90", "p90 (µs)"),
    ("p99", "p99 (µs)"),
    ("errorPercent", "errors %"),
)


def _results_table(rows: Sequence[dict]) -> str:
    head = "".join(f"<th>{html.escape(t)}</th>" for _, t in _TABLE_COLS)
    body = []
    for r in rows:
        cls = ' class="discarded"' if r.get("windowDiscarded") else ""
        tds = []
        for col, _ in _TABLE_COLS:
            v = r.get(col, "-")
            if isinstance(v, float):
                v = f"{v:.2f}"
            tds.append(f"<td>{html.escape(str(v))}</td>")
        body.append(f"<tr{cls}>{''.join(tds)}</tr>")
    return (
        f"<table><thead><tr>{head}</tr></thead>"
        f"<tbody>{''.join(body)}</tbody></table>"
    )


REGRESSION_METRICS = ("p50", "p90", "p99", "ActualQPS", "errorPercent")
REGRESSION_THRESHOLD = 0.05  # 5% — the dashboard's alert band


def regression_rows(
    current: Sequence[dict], baseline: Sequence[dict]
) -> List[dict]:
    """Join runs by label; per-metric relative deltas vs the baseline."""
    base_by_label = {r["Labels"]: r for r in baseline}
    out = []
    for r in current:
        b = base_by_label.get(r["Labels"])
        if b is None:
            continue
        deltas = {}
        for m in REGRESSION_METRICS:
            cur, old = r.get(m), b.get(m)
            if not isinstance(cur, (int, float)) or not isinstance(
                old, (int, float)
            ):
                continue
            if old:
                delta = (cur - old) / old
            else:
                # from zero: a nonzero current is an unbounded change
                # (e.g. errors newly appearing) — flag it, don't hide it
                delta = math.inf if cur else 0.0
            deltas[m] = {"current": cur, "baseline": old, "delta": delta}
        out.append({"label": r["Labels"], "metrics": deltas})
    return out


def _regression_table(rows: List[dict]) -> str:
    head = "<th>run</th>" + "".join(
        f"<th>{m} Δ%</th>" for m in REGRESSION_METRICS
    )
    body = []
    for row in rows:
        tds = [f"<td>{html.escape(row['label'])}</td>"]
        for m in REGRESSION_METRICS:
            d = row["metrics"].get(m)
            if d is None:
                tds.append("<td>-</td>")
                continue
            pct = d["delta"] * 100.0
            # latency/error up = regression; qps down = regression
            worse = d["delta"] > 0 if m != "ActualQPS" else d["delta"] < 0
            cls = ""
            if abs(d["delta"]) > REGRESSION_THRESHOLD:
                cls = ' class="regress"' if worse else ' class="improve"'
            text = "new" if math.isinf(pct) else f"{pct:+.1f}%"
            tds.append(
                f"<td{cls} title=\"{d['baseline']:g} → "
                f"{d['current']:g}\">{text}</td>"
            )
        body.append(f"<tr>{''.join(tds)}</tr>")
    return (
        f"<table><thead><tr>{head}</tr></thead>"
        f"<tbody>{''.join(body)}</tbody></table>"
    )


def load_timelines(results_dir) -> Dict[str, dict]:
    """Per-run timeline documents (``<label>.timeline.json``) written
    by a ``--timeline`` sweep (metrics/timeline.py); {} when none
    exist."""
    out: Dict[str, dict] = {}
    for p in sorted(pathlib.Path(results_dir).glob("*.timeline.json")):
        try:
            out[p.name[: -len(".timeline.json")]] = json.loads(
                p.read_text()
            )
        except (OSError, json.JSONDecodeError):
            continue
    return out


def _svg_sparkline(values: Sequence[float], width: int = 140,
                   height: int = 28) -> str:
    """A tiny inline-SVG sparkline for one windowed series (the
    dataviz sparkline form: one recessive line, no axes)."""
    vs = [float(v) for v in values]
    if not vs:
        return ""
    hi = max(vs) or 1.0
    n = max(len(vs) - 1, 1)
    pts = " ".join(
        f"{2 + i / n * (width - 4):.1f},"
        f"{height - 3 - v / hi * (height - 6):.1f}"
        for i, v in enumerate(vs)
    )
    return (
        f'<svg viewBox="0 0 {width} {height}" class="spark" '
        f'width="{width}" height="{height}" role="img">'
        f'<polyline points="{pts}" fill="none" class="s0" '
        'stroke-width="1.5"/></svg>'
    )


def _timeline_section(timelines: Dict[str, dict]) -> str:
    """Per-run timeline rows: client-qps and error sparklines over sim
    time plus the busiest services' peak utilization / queue."""
    head = (
        "<th>run</th><th>windows</th><th>qps over time</th>"
        "<th>errors over time</th><th>peak service</th>"
        "<th>peak util</th><th>convoy r</th>"
    )
    body = []
    for label, doc in timelines.items():
        wins = doc.get("windows", [])
        qps = [w.get("qps", 0.0) for w in wins]
        errs = [w.get("errors", 0.0) for w in wins]
        services = doc.get("services", {})
        peak_name, peak_util = "-", 0.0
        for name, svc in services.items():
            u = float(svc.get("peak_utilization", 0.0))
            if u > peak_util:
                peak_name, peak_util = name, u
        conv = (doc.get("convoy") or {}).get("correlation")
        body.append(
            "<tr>"
            f"<td>{html.escape(label)}</td>"
            f"<td>{len(wins)} x {doc.get('window_s', 0):g}s</td>"
            f"<td>{_svg_sparkline(qps)}</td>"
            f"<td>{_svg_sparkline(errs)}</td>"
            f"<td>{html.escape(peak_name)}</td>"
            f"<td>{peak_util * 100:.0f}%</td>"
            f"<td>{conv if conv is not None else '-'}</td>"
            "</tr>"
        )
    return (
        f"<table><thead><tr>{head}</tr></thead>"
        f"<tbody>{''.join(body)}</tbody></table>"
    )


def load_blame(results_dir) -> Dict[str, dict]:
    """Per-run blame documents (``<label>.blame.json``) written by an
    attributed sweep (``--attribution``); {} when none exist."""
    out: Dict[str, dict] = {}
    for p in sorted(pathlib.Path(results_dir).glob("*.blame.json")):
        try:
            out[p.name[: -len(".blame.json")]] = json.loads(
                p.read_text()
            )
        except (OSError, json.JSONDecodeError):
            continue
    return out


def _blame_table(blame: Dict[str, dict], top: int = 8) -> str:
    """Per-run critical-path blame rows: top services with mean (and,
    when the run was attributed in tail mode, p99-cut) blame shares."""
    any_tail = any(d.get("tail_services") for d in blame.values())
    head = (
        "<th>run</th><th>service</th><th>mean share</th>"
        "<th>wait (s)</th><th>self (s)</th><th>net (s)</th>"
        "<th>timeout (s)</th>"
    )
    if any_tail:
        head += "<th>tail share</th>"
    body = []
    for label, doc in blame.items():
        tail_rows = {
            r["service"]: r for r in doc.get("tail_services") or []
        }
        for i, r in enumerate(doc.get("services", [])[:top]):
            tds = [
                f"<td>{html.escape(label) if i == 0 else ''}</td>",
                f"<td>{html.escape(r['service'])}</td>",
                f"<td>{r['share'] * 100:.1f}%</td>",
                f"<td>{r['wait_s']:.4f}</td>",
                f"<td>{r['self_s']:.4f}</td>",
                f"<td>{r['net_s']:.4f}</td>",
                f"<td>{r['timeout_s']:.4f}</td>",
            ]
            if any_tail:
                t = tail_rows.get(r["service"])
                tds.append(
                    f"<td>{t['share'] * 100:.1f}%</td>" if t
                    else "<td>-</td>"
                )
            body.append(f"<tr>{''.join(tds)}</tr>")
    return (
        f"<table><thead><tr>{head}</tr></thead>"
        f"<tbody>{''.join(body)}</tbody></table>"
    )


def build_report(
    rows: Sequence[dict],
    baseline_rows: Optional[Sequence[dict]] = None,
    title: str = "isotope-tpu benchmark report",
    blame: Optional[Dict[str, dict]] = None,
    timelines: Optional[Dict[str, dict]] = None,
) -> str:
    x_col, x_label = _pick_x(rows)
    discarded = sum(1 for r in rows if r.get("windowDiscarded"))

    charts = []
    series_names: List[str] = []
    for metric, unit, scale in (
        ("p50", "latency (ms)", 1e-3),
        ("p99", "latency (ms)", 1e-3),
        ("errorPercent", "errors (%)", 1.0),
    ):
        grouped = _group_series(rows, x_col, metric)
        grouped = {
            k: [(x, y * scale) for x, y in pts]
            for k, pts in grouped.items()
        }
        if grouped:
            series_names = list(grouped)
            charts.append(
                "<figure>"
                + svg_line_chart(
                    grouped, f"{metric} vs {x_label.lower()}", x_label,
                    unit,
                )
                + "</figure>"
            )
    # mean CPU across services, if the sweep recorded it
    cpu_rows = []
    for r in rows:
        cores = [
            v for k, v in r.items()
            if k.startswith("cpu_cores_") and isinstance(v, (int, float))
        ]
        if cores:
            cpu_rows.append(dict(r, total_cpu=sum(cores)))
    if cpu_rows:
        grouped = _group_series(cpu_rows, x_col, "total_cpu")
        if grouped:
            charts.append(
                "<figure>"
                + svg_line_chart(
                    grouped, f"total service CPU vs {x_label.lower()}",
                    x_label, "cores",
                )
                + "</figure>"
            )

    light_css, dark_css = _series_css()
    css = _CSS.replace("SERIES_CSS", light_css).replace(
        "SERIES_DARK_CSS", dark_css
    )
    doc = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>{html.escape(title)}</title>",
        f"<style>{css}</style></head><body>",
        f"<h1>{html.escape(title)}</h1>",
        '<div class="tiles">',
        f'<div class="tile"><div class="v">{len(rows)}</div>'
        '<div class="k">runs</div></div>',
        f'<div class="tile"><div class="v">{discarded}</div>'
        '<div class="k">discarded</div></div>',
        f'<div class="tile"><div class="v">'
        f'{len({_series_of(r["Labels"]) for r in rows})}</div>'
        '<div class="k">series</div></div>',
        "</div>",
    ]
    if len(series_names) >= 2:
        doc.append(_legend(series_names))
    doc.append(f'<div class="charts">{"".join(charts)}</div>')

    if baseline_rows is not None:
        doc.append("<h2>Regression vs baseline</h2>")
        joined = regression_rows(rows, baseline_rows)
        if joined:
            doc.append(_regression_table(joined))
        else:
            doc.append("<p>No runs with matching labels.</p>")

    if blame:
        doc.append("<h2>Critical-path blame</h2>")
        doc.append(
            "<p>Per-service blame shares of the attributed runs "
            "(metrics/attribution.py): which service's wait / self / "
            "wire / timeout time the client latency decomposes into "
            "along the critical path.</p>"
        )
        doc.append(_blame_table(blame))
    if timelines:
        doc.append("<h2>Timelines</h2>")
        doc.append(
            "<p>Windowed series of the recorded runs "
            "(metrics/timeline.py): client throughput and errors over "
            "sim time, plus the busiest service's peak utilization "
            "and the convoy detector's entry-wait-vs-leaf-busy "
            "correlation.</p>"
        )
        doc.append(_timeline_section(timelines))
    doc.append("<h2>All runs</h2>")
    doc.append(_results_table(rows))
    doc.append("</body></html>")
    return "".join(doc)


def write_report(
    results_dir,
    out_path,
    baseline_dir=None,
    title: Optional[str] = None,
) -> int:
    """Render ``results_dir``'s sweep into one HTML file; returns the
    number of runs included.  Blame artifacts (``*.blame.json`` from an
    attributed sweep) render as a critical-path blame table."""
    rows = load_results(results_dir)
    baseline = load_results(baseline_dir) if baseline_dir else None
    doc = build_report(
        rows,
        baseline,
        title or f"isotope-tpu report — {pathlib.Path(results_dir).name}",
        blame=load_blame(results_dir),
        timelines=load_timelines(results_dir),
    )
    pathlib.Path(out_path).write_text(doc)
    return len(rows)


# -- history across publish ids --------------------------------------------

# the suite's publish-id format, `<date>_<loadgen>_<branch>_<ver>` —
# exactly what the reference dashboard scrapes from the GCS bucket
# (perf_dashboard/helpers/download.py:56-62, e.g. 20200525_fortio_master_1.7)
_PUBLISH_ID_RE = re.compile(r"^(?P<date>\d{8})_[^_]+_.+_.+$")


def load_history(
    root, lineage: Optional[str] = None
) -> List[Tuple[str, List[dict]]]:
    """Scan a directory of publish trees (``runner.suite`` output roots)
    and return ``(publish_id, rows)`` pairs in date order.

    Each publish tree holds one ``results.jsonl`` per config
    subdirectory; rows are merged with their config name so the same
    run label in different configs stays distinct.

    A history is one *lineage*: publishes sharing the id suffix after
    the date (``<loadgen>_<branch>_<ver>``).  Mixing lineages would
    mis-order same-date publishes and diff unrelated runs (open-loop
    nighthawk vs closed-loop fortio), so a root holding several
    demands an explicit ``lineage`` selector (substring of the
    suffix).
    """
    root = pathlib.Path(root)
    found: List[Tuple[str, str, pathlib.Path]] = []
    for child in sorted(p for p in root.iterdir() if p.is_dir()):
        m = _PUBLISH_ID_RE.match(child.name)
        if not m:
            continue
        if not any(child.glob("*/results.jsonl")):
            continue  # empty/stale publish dir (e.g. a crashed suite)
        suffix = child.name[len(m.group("date")) + 1:]
        if lineage is not None and lineage not in suffix:
            continue
        found.append((m.group("date"), suffix, child))
    suffixes = {s for _, s, _ in found}
    if len(suffixes) > 1:
        raise ValueError(
            f"{root} holds {len(suffixes)} publish lineages "
            f"({sorted(suffixes)}); pass a lineage selector to pick one"
        )
    out: List[Tuple[str, List[dict]]] = []
    for _, _, child in sorted(found):
        rows: List[dict] = []
        for results in sorted(child.glob("*/results.jsonl")):
            cfg = results.parent.name
            for r in load_results(results.parent):
                rows.append(dict(r, _config=cfg))
        if rows:
            out.append((child.name, rows))
    if not out:
        raise FileNotFoundError(
            f"no publish trees (<date>_<loadgen>_<branch>_<ver> dirs "
            f"with */results.jsonl) under {root}"
            + (f" matching lineage {lineage!r}" if lineage else "")
        )
    return out


HISTORY_METRICS = (
    ("p50", "latency (ms)", 1e-3),
    ("p99", "latency (ms)", 1e-3),
    ("ActualQPS", "qps", 1.0),
    ("errorPercent", "errors (%)", 1.0),
)


def artifact_listing(root) -> List[Tuple[str, int]]:
    """Relative (path, bytes) of every artifact in a publish/sweep tree
    — the reference dashboard's raw-artifact browsing
    (perf_dashboard/artifacts/ views backed by
    helpers/download.py:27-66, which lists and fetches each publish's
    raw files from the bucket)."""
    root = pathlib.Path(root)
    return [
        (str(p.relative_to(root)), p.stat().st_size)
        for p in sorted(root.rglob("*"))
        if p.is_file()
    ]


def _artifact_section(label: str, root, link_prefix: str = "") -> str:
    files = artifact_listing(root)
    items = "".join(
        # quote() first (URL metacharacters like '#'/'?' in filenames),
        # html.escape() second (the URL goes into an attribute)
        f'<li><a href="'
        f'{html.escape(urllib.parse.quote(link_prefix + rel))}">'
        f"{html.escape(rel)}</a> <small>{size:,} B</small></li>"
        for rel, size in files
    )
    return (
        f"<details><summary><code>{html.escape(label)}</code> — "
        f"{len(files)} artifacts</summary><ul>{items}</ul></details>"
    )


def build_history_report(
    history: Sequence[Tuple[str, List[dict]]],
    title: str = "isotope-tpu history",
    artifact_sections: Sequence[str] = (),
) -> str:
    """Metric-over-publish-id time series — the reference dashboard's
    day-over-day regression view (perf_dashboard/helpers/download.py:
    27-66 downloads one benchmark.csv per day and charts them together).

    X axis is the publish index (ids are date-prefixed and sorted);
    each series is one run label, joined across the publishes it
    appears in.
    """
    ids = [pid for pid, _ in history]

    def series_for(metric: str, scale: float):
        out: Dict[str, List[Tuple[float, float]]] = {}
        for i, (_, rows) in enumerate(history):
            for r in rows:
                v = r.get(metric)
                if not isinstance(v, (int, float)):
                    continue
                name = f"{r['_config']}/{r['Labels']}"
                out.setdefault(name, []).append((float(i), v * scale))
        # a one-point series renders as a dot; keep it (a new config's
        # first publish is still information)
        return out

    charts = []
    series_names: List[str] = []
    for metric, unit, scale in HISTORY_METRICS:
        grouped = series_for(metric, scale)
        if grouped:
            series_names = sorted(grouped)
            charts.append(
                "<figure>"
                + svg_line_chart(
                    {k: grouped[k] for k in series_names},
                    f"{metric} over publishes",
                    "publish",
                    unit,
                )
                + "</figure>"
            )

    light_css, dark_css = _series_css()
    css = _CSS.replace("SERIES_CSS", light_css).replace(
        "SERIES_DARK_CSS", dark_css
    )
    id_list = "".join(
        f"<li><code>{i}: {html.escape(pid)}</code> — "
        f"{len(rows)} runs</li>"
        for i, (pid, rows) in enumerate(history)
    )
    doc = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>{html.escape(title)}</title>",
        f"<style>{css}</style></head><body>",
        f"<h1>{html.escape(title)}</h1>",
        f"<p>{len(ids)} publishes, oldest to newest:</p>",
        f"<ul>{id_list}</ul>",
    ]
    if len(series_names) >= 2:
        doc.append(_legend(series_names))
    doc.append(f'<div class="charts">{"".join(charts)}</div>')

    # latest-vs-previous regression table (the dashboard's
    # master-vs-release deltas, applied day-over-day)
    if len(history) >= 2:
        prev_id, prev_rows = history[-2]
        cur_id, cur_rows = history[-1]
        doc.append(
            f"<h2>Regression: {html.escape(cur_id)} vs "
            f"{html.escape(prev_id)}</h2>"
        )

        # join on (config, label): the same run label may exist in
        # several configs of one publish tree
        def qualify(rows):
            return [
                dict(r, Labels=f"{r['_config']}/{r['Labels']}")
                for r in rows
            ]

        joined = regression_rows(qualify(cur_rows), qualify(prev_rows))
        if joined:
            doc.append(_regression_table(joined))
        else:
            doc.append("<p>No runs with matching labels.</p>")
    if artifact_sections:
        doc.append("<h2>Artifacts</h2>")
        doc.extend(artifact_sections)
    doc.append("</body></html>")
    return "".join(doc)


def write_history_report(
    root, out_path, title: Optional[str] = None,
    lineage: Optional[str] = None,
) -> int:
    """Render a metric-over-time page from a directory of publish
    trees; returns the number of publishes included.  Each publish gets
    a collapsible raw-artifact browser with links relative to the
    report's location (the reference dashboard's per-publish artifact
    view)."""
    history = load_history(root, lineage=lineage)
    root_p = pathlib.Path(root)
    out_p = pathlib.Path(out_path)
    # links are resolved by the browser relative to the report file,
    # so the prefix must be root relative to the report's directory
    # (os.path.relpath walks .. when the report lives inside root)
    prefix_base = os.path.relpath(
        root_p.resolve(), out_p.resolve().parent
    )
    sections = [
        _artifact_section(
            pid, root_p / pid,
            link_prefix=f"{pid}/" if prefix_base == "."
            else f"{prefix_base}/{pid}/",
        )
        for pid, _ in history
        if (root_p / pid).is_dir()
    ]
    doc = build_history_report(
        history,
        title or f"isotope-tpu history — {pathlib.Path(root).name}",
        artifact_sections=sections,
    )
    pathlib.Path(out_path).write_text(doc)
    return len(history)
