"""XLA trace capture: the backend of ``isotope-tpu telemetry --xla-trace``.

Promoted from ``tools/capture_profile.py`` (which remains as a thin
shim): captures a ``jax.profiler`` trace of warmed summary steps —
the same capture path the sweep runner uses per-run via ``--profile``
(runner/run.py wraps each run in ``jax.profiler.trace``) — readable in
TensorBoard/XProf.
"""
from __future__ import annotations

import glob
import os
from typing import List, Optional


def build_simulator(topology: Optional[str] = None):
    """A Simulator for ``topology`` (YAML path), or the flagship
    ~120-service tree (the bench headline) when ``None``."""
    from isotope_tpu.sim.engine import Simulator

    if topology is None:
        from __graft_entry__ import _flagship

        compiled = _flagship()
    else:
        from isotope_tpu.compiler import compile_graph
        from isotope_tpu.models.graph import ServiceGraph

        compiled = compile_graph(ServiceGraph.from_yaml_file(topology))
    return Simulator(compiled)


def capture_xla_trace(
    out_dir: str,
    topology: Optional[str] = None,
    num_requests: int = 65_536,
    qps: float = 100_000.0,
    steps: int = 3,
    seed: int = 0,
    sim=None,
) -> List[str]:
    """Capture a profiler trace of ``steps`` warmed summary runs.

    Pass an already-built ``sim`` to skip compiling the topology again
    (the ``telemetry`` command does); otherwise ``topology`` selects the
    graph as in :func:`build_simulator`.  The first run (trace +
    compile) happens OUTSIDE the capture window so the trace shows
    steady-state device work.  Returns the ``*.xplane.pb`` files
    written under ``out_dir``.
    """
    import jax

    from isotope_tpu.sim.config import LoadModel

    if sim is None:
        sim = build_simulator(topology)
    load = LoadModel(kind="open", qps=qps)
    block = min(sim.default_block_size(), num_requests)
    key = jax.random.PRNGKey(seed)

    def step(k):
        return sim.run_summary(load, num_requests, k, block_size=block)

    jax.block_until_ready(step(key).count)  # warm: compile outside capture

    with jax.profiler.trace(out_dir):
        out = None
        for i in range(steps):
            out = step(jax.random.fold_in(key, 1 + i))
        jax.block_until_ready(out.count)

    return glob.glob(
        os.path.join(out_dir, "**", "*.xplane.pb"), recursive=True
    )
