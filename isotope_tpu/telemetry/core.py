"""Engine self-telemetry: counters, gauges, phase timers, run records.

The simulated *workload* is already observable (metrics/prometheus.py
renders the reference's five service series), but the engine that runs
it — level bucketing, padding, the two-layer compile cache, segment
scheduling, mesh sharding — made consequential decisions invisibly.
This module is the always-on instrumentation layer those decisions
report through:

- **Counters / gauges / phase timers** live in one process-wide
  registry (plain host dicts — recording is a dict update, never a
  device op).  Instrumented code calls :func:`counter_inc`,
  :func:`gauge_set` / :func:`gauge_max`, and ``with phase("name"):``
  unconditionally; the cost is negligible and nothing is traced into
  compiled programs.  Counters recorded inside a jitted function body
  therefore count *traces* (host executions), not executed requests —
  which is exactly what makes them retrace detectors.
- **JAX monitoring hooks** (:func:`install_jax_hooks`) subscribe to
  jax's own event stream, splitting compile wall time into trace /
  lower / backend-compile phases and counting persistent-compilation-
  cache hits and misses — measurements the engine could not take from
  the outside.
- **Detail mode** (:func:`enable` with ``detail=True``) additionally
  arms :func:`segment_fence`: the engine executes eagerly (under
  ``jax.disable_jit``) and blocks at segment boundaries so each scan
  bucket / unrolled island gets its own wall-time phase.  The fences
  serialize dispatch, so detail mode is for *diagnosis*, not
  benchmarking; with detail off the fence helper returns before
  touching jax (zero added sync points — tests/test_telemetry.py pins
  this with a fence-counter monkeypatch).
- **Exposition**: :func:`snapshot` freezes the registry into a
  :class:`RunTelemetry` record that serializes to ``telemetry.jsonl``
  lines, and :func:`prometheus_text` renders the same state as
  ``isotope_engine_*`` Prometheus series so one scrape sees the
  workload *and* the engine.

jax is imported lazily throughout: the converter-only environment
(no jax installed) can still import this module.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import time
from typing import Any, Dict, Iterator, List, Optional, Set

SCHEMA = "isotope-engine-telemetry/v1"

#: jax duration events -> phase names (the trace/lower/compile split)
_JAX_EVENT_PHASES = {
    "/jax/core/compile/jaxpr_trace_duration": "compile.trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "compile.lower",
    "/jax/core/compile/backend_compile_duration": "compile.backend",
    "/jax/compilation_cache/cache_retrieval_time_sec":
        "compile.persistent_read",
    "/jax/compilation_cache/compile_time_saved_sec":
        "compile.persistent_saved",
}

#: jax counter events -> counter names (persistent-cache visibility)
_JAX_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "persistent_cache_hits",
    "/jax/compilation_cache/cache_misses": "persistent_cache_misses",
}


class _State:
    """The process-wide registry (one instance, module-level)."""

    def __init__(self) -> None:
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.phases: Dict[str, float] = {}
        self.meta: Dict[str, Any] = {}  # run annotations (degraded_to, ...)
        self.emit = False          # artifact emission requested (--telemetry)
        self.detail = False        # segment fencing armed (--telemetry=detail)
        self.trace_keys: Set[tuple] = set()
        self.last_fence_t: Optional[float] = None


_STATE = _State()
_HOOKS_INSTALLED = False


# -- mode switches ---------------------------------------------------------

def enable(detail: bool = False) -> None:
    """Request artifact emission (and optionally detail-mode fencing)."""
    _STATE.emit = True
    _STATE.detail = bool(detail)


def disable() -> None:
    _STATE.emit = False
    _STATE.detail = False


def emitting() -> bool:
    """Whether the caller asked for telemetry artifacts (``--telemetry``)."""
    return _STATE.emit


def detail_enabled() -> bool:
    return _STATE.detail


def reset() -> None:
    """Clear every counter/gauge/phase (tests, per-bench-case isolation).

    Leaves the emit/detail switches and installed jax hooks in place.
    """
    _STATE.counters.clear()
    _STATE.gauges.clear()
    _STATE.phases.clear()
    _STATE.meta.clear()
    _STATE.trace_keys.clear()
    _STATE.last_fence_t = None


# -- counters / gauges / phases --------------------------------------------

def counter_inc(name: str, n: float = 1.0) -> None:
    _STATE.counters[name] = _STATE.counters.get(name, 0.0) + n


def set_meta(key: str, value: Any) -> None:
    """Annotate the current run record (e.g. ``degraded_to``).

    Meta entries land in the snapshot's ``meta`` section and the
    ``summary_block`` headline — not in the numeric Prometheus series.
    """
    _STATE.meta[key] = value


def get_meta(key: str, default: Any = None) -> Any:
    return _STATE.meta.get(key, default)


def counter_get(name: str) -> float:
    return _STATE.counters.get(name, 0.0)


def _gauge_key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def gauge_set(name: str, value: float, **labels: Any) -> None:
    _STATE.gauges[_gauge_key(name, labels)] = float(value)


def gauge_max(name: str, value: float, **labels: Any) -> None:
    """High-water gauge: keeps the max ever observed (device memory)."""
    key = _gauge_key(name, labels)
    prev = _STATE.gauges.get(key)
    if prev is None or value > prev:
        _STATE.gauges[key] = float(value)


def gauge_get(name: str, **labels: Any) -> Optional[float]:
    return _STATE.gauges.get(_gauge_key(name, labels))


def phase_add(name: str, seconds: float) -> None:
    _STATE.phases[name] = _STATE.phases.get(name, 0.0) + seconds


def phase_seconds(name: str) -> float:
    return _STATE.phases.get(name, 0.0)


@contextlib.contextmanager
def phase(name: str) -> Iterator[None]:
    """Accumulating wall-clock phase timer.

    Re-entering the same name sums; nested phases time independently,
    so an enclosing phase's seconds include its children's (each name
    is its own accumulator — there is no implicit hierarchy).
    """
    t0 = time.perf_counter()
    try:
        yield
    finally:
        phase_add(name, time.perf_counter() - t0)


def _under_disable_jit() -> bool:
    """Whether jax is executing eagerly (detail mode, the resilience
    ladder's cpu-eager rung): program-level compile timings are
    meaningless there — an eager 'first call' is the whole run."""
    try:
        import jax

        return bool(jax.config.jax_disable_jit)
    except Exception:  # pragma: no cover - converter-only env
        return False


def time_first_call(fn, phase_name: str, counter: str = "jit_first_calls"):
    """Wrap a callable so its FIRST invocation is phase-timed.

    Used on jitted entry points: jax compiles synchronously inside the
    first call, so its wall time is the trace+lower+compile cost (plus
    one async dispatch — no fence is added).  Later calls pay one
    attribute check.
    """

    class _Timed:
        __slots__ = ("_fn", "_first_done")

        def __init__(self, inner):
            self._fn = inner
            self._first_done = False

        def __call__(self, *args, **kwargs):
            if self._first_done:
                return self._fn(*args, **kwargs)
            if detail_enabled() or _under_disable_jit():
                # eager execution (detail mode, or the degradation
                # ladder's cpu-eager rung): the call's wall time is the
                # whole run, not a compile — leave the first-call slot
                # open for a real jitted call
                return self._fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = self._fn(*args, **kwargs)
            phase_add(phase_name, time.perf_counter() - t0)
            counter_inc(counter)
            self._first_done = True
            return out

        def __getattr__(self, item):  # lower()/compile() passthrough
            return getattr(self._fn, item)

    return _Timed(fn)


# -- engine hooks ----------------------------------------------------------

def record_trace(sig: tuple, tracing: bool, **shape_gauges: float) -> None:
    """Called host-side from the engine's tensor-program body.

    ``tracing=True`` means the body is executing under a jit trace: the
    first trace of a signature counts as ``engine_traces``, any repeat
    as ``engine_retraces`` (the retrace detector).  ``tracing=False``
    is an eager (detail-mode) execution and counts separately.  Shape
    gauges (requests/hops per batch) record either way.
    """
    if tracing:
        counter_inc("engine_traces")
        if sig in _STATE.trace_keys:
            counter_inc("engine_retraces")
        else:
            _STATE.trace_keys.add(sig)
    else:
        counter_inc("engine_eager_calls")
    for k, v in shape_gauges.items():
        gauge_set(f"engine_last_{k}", v)


def fence_reset() -> None:
    """Start a new fence epoch (called at the top of a sweep)."""
    _STATE.last_fence_t = None


def segment_fence(label: str, value) -> None:
    """Detail-mode-only blocking fence at a segment boundary.

    Records the wall time since the previous fence (dispatch + device
    execution of this segment) under ``segment.<label>``.  With detail
    off this returns before touching jax — the default path gains zero
    sync points.  Tracer inputs (a jitted trace in flight) are skipped:
    fencing is only meaningful on concrete arrays.
    """
    if not _STATE.detail or value is None:
        return
    import jax

    if isinstance(value, jax.core.Tracer):
        return
    t_prev = _STATE.last_fence_t
    if t_prev is None:
        t_prev = time.perf_counter()
    jax.block_until_ready(value)
    t1 = time.perf_counter()
    counter_inc("engine_fences")
    phase_add(f"segment.{label}", t1 - t_prev)
    _STATE.last_fence_t = t1
    # numeric-sentinel localization: in detail mode the fence already
    # holds the segment's concrete output, so a NaN is pinned to the
    # segment that PRODUCED it (the post-run sentinel only sees the
    # reduced summary).  Never raises — the run-level sentinel decides.
    import numpy as np

    arr = np.asarray(value)
    if np.issubdtype(arr.dtype, np.floating) and np.isnan(arr).any():
        counter_inc("numeric_sentinel_violations")
        gauge_set("numeric_sentinel", 1.0, segment=label)


def record_device_memory() -> Optional[float]:
    """High-water per-device memory gauges via ``Device.memory_stats()``.

    Returns the max peak bytes across devices, or ``None`` where the
    backend exposes no stats (CPU).
    """
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return None
    peak = None
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        v = ms.get("peak_bytes_in_use", ms.get("bytes_in_use"))
        if v is None:
            continue
        gauge_max("device_memory_peak_bytes", float(v), device=str(d.id))
        peak = max(peak or 0.0, float(v))
    if peak is not None:
        gauge_max("device_memory_peak_bytes_max", peak)
    return peak


def install_jax_hooks() -> bool:
    """Subscribe to jax's monitoring stream (idempotent).

    Maps compile-pipeline duration events onto the ``compile.*`` phases
    and persistent-compilation-cache events onto counters.  Returns
    whether the hooks are (now) installed.
    """
    global _HOOKS_INSTALLED
    if _HOOKS_INSTALLED:
        return True
    try:
        from jax import monitoring
    except Exception:  # pragma: no cover - converter-only env
        return False

    # eager execution compiles op-by-op: those per-primitive
    # cache/compile events would drown the program-level numbers these
    # hooks exist to surface — same guard as time_first_call
    def _on_duration(event, duration, *args, **kwargs):
        name = _JAX_EVENT_PHASES.get(event)
        if name is not None and not _under_disable_jit():
            # clamp at 0: compile_time_saved_sec can go negative (a
            # cache read costing more than it saved), and a phase is
            # exported as a Prometheus counter, which must stay >= 0
            phase_add(name, max(float(duration), 0.0))

    def _on_event(event, *args, **kwargs):
        name = _JAX_EVENT_COUNTERS.get(event)
        if name is not None and not _under_disable_jit():
            counter_inc(name)

    monitoring.register_event_duration_secs_listener(_on_duration)
    monitoring.register_event_listener(_on_event)
    _HOOKS_INSTALLED = True
    return True


# -- derived views ---------------------------------------------------------

def summary_block() -> Dict[str, Any]:
    """The headline numbers every perf report should carry."""
    c, p, g = _STATE.counters, _STATE.phases, _STATE.gauges
    hits = c.get("executable_cache_hits", 0.0)
    misses = c.get("executable_cache_misses", 0.0)
    total = hits + misses
    padded = c.get("bucket_padded_elems", 0.0)
    real = c.get("bucket_real_elems", 0.0)
    peak = g.get("device_memory_peak_bytes_max")
    blk: Dict[str, Any] = {
        "retries_total": int(c.get("retries_total", 0.0)),
        "degradations_total": int(c.get("degradations_total", 0.0)),
        "compile_s": round(
            p.get("compile.trace", 0.0)
            + p.get("compile.lower", 0.0)
            + p.get("compile.backend", 0.0),
            4,
        ),
        "trace_s": round(p.get("compile.trace", 0.0), 4),
        "lower_s": round(p.get("compile.lower", 0.0), 4),
        "backend_s": round(p.get("compile.backend", 0.0), 4),
        "cache_hits": int(hits),
        "cache_misses": int(misses),
        "cache_hit_ratio": round(hits / total, 4) if total else None,
        "persistent_cache_hits": int(c.get("persistent_cache_hits", 0.0)),
        "persistent_cache_misses": int(
            c.get("persistent_cache_misses", 0.0)
        ),
        "padding_waste_fraction": (
            round((padded - real) / padded, 4) if padded else 0.0
        ),
        "peak_device_bytes": peak,
    }
    # key PRESENT only when the run actually degraded: bench_regress
    # keys its degraded-on-a-previously-clean-case gate on presence
    if _STATE.meta.get("degraded_to"):
        blk["degraded_to"] = _STATE.meta["degraded_to"]
    # vet keys PRESENT only when a vet pass actually ran this record:
    # bench_regress's opt-in new-vet-errors gate skips captures (and
    # baselines) that never vetted instead of reading absence as zero
    if c.get("vet_runs_total"):
        blk["vet_runs"] = int(c["vet_runs_total"])
        blk["vet_errors"] = int(c.get("vet_errors_total", 0.0))
        blk["vet_warnings"] = int(c.get("vet_warnings_total", 0.0))
    return blk


def summary_line() -> str:
    """One human-readable line over :func:`summary_block` — the shared
    stderr rendering of the ``simulate --telemetry`` / ``telemetry``
    commands (one format string, so the two CLIs cannot drift)."""
    blk = summary_block()
    peak = blk["peak_device_bytes"]
    return (
        "telemetry: compile {compile_s:.2f}s (trace {trace_s:.2f} / "
        "lower {lower_s:.2f} / backend {backend_s:.2f}), exec-cache "
        "{cache_hits}h/{cache_misses}m, persistent-cache "
        "{persistent_cache_hits}h/{persistent_cache_misses}m, padding "
        "waste {padding_waste_fraction:.1%}, peak device bytes {peak}"
    ).format(peak="n/a" if peak is None else f"{peak:.0f}", **blk)


# -- the per-run record ----------------------------------------------------

@dataclasses.dataclass
class RunTelemetry:
    """One frozen snapshot of the registry, serializable to JSONL."""

    label: Optional[str]
    phases: Dict[str, float]
    counters: Dict[str, float]
    gauges: Dict[str, float]
    meta: Dict[str, Any]
    schema: str = SCHEMA

    def to_dict(self) -> dict:
        return {
            "schema": self.schema,
            "label": self.label,
            "phases": self.phases,
            "counters": self.counters,
            "gauges": self.gauges,
            "meta": self.meta,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "RunTelemetry":
        return cls(
            label=d.get("label"),
            phases=dict(d.get("phases", {})),
            counters=dict(d.get("counters", {})),
            gauges=dict(d.get("gauges", {})),
            meta=dict(d.get("meta", {})),
            schema=d.get("schema", SCHEMA),
        )

    def to_json_line(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    def append_jsonl(self, path) -> None:
        # heal a crash-torn tail before appending: if the file does not
        # end in a newline (a killed run's half-written record), start
        # this record on a fresh line so the fragment stays an isolated
        # bad line (which the readers skip-and-count) instead of
        # swallowing this record into unreadable garbage
        lead = ""
        try:
            with open(path, "rb") as f:
                f.seek(-1, 2)
                if f.read(1) not in (b"\n", b""):
                    lead = "\n"
        except OSError:
            pass  # missing or empty file: nothing to heal
        with open(path, "a") as f:
            f.write(lead + self.to_json_line() + "\n")

    def prometheus_text(self) -> str:
        return _render_prometheus(self.phases, self.counters, self.gauges)


def snapshot(label: Optional[str] = None) -> RunTelemetry:
    """Freeze the current registry (refreshing device-memory gauges)."""
    record_device_memory()
    meta: Dict[str, Any] = {"unix_time": time.time()}
    try:
        import jax

        meta["backend"] = jax.default_backend()
        meta["device_count"] = jax.device_count()
        meta["jax_version"] = jax.__version__
    except Exception:  # pragma: no cover - converter-only env
        pass
    meta.update(_STATE.meta)  # run annotations (degraded_to, ...)
    return RunTelemetry(
        label=label,
        phases={k: round(v, 6) for k, v in sorted(_STATE.phases.items())},
        counters=dict(sorted(_STATE.counters.items())),
        gauges={k: float(v) for k, v in sorted(_STATE.gauges.items())},
        meta=meta,
    )


# -- Prometheus exposition -------------------------------------------------

def _render_prometheus(phases, counters, gauges) -> str:
    out: List[str] = []
    out.append(
        "# HELP isotope_engine_phase_seconds_total Wall seconds spent in"
        " each engine phase."
    )
    out.append("# TYPE isotope_engine_phase_seconds_total counter")
    for name, v in sorted(phases.items()):
        out.append(
            f'isotope_engine_phase_seconds_total{{phase="{name}"}}'
            f" {v:.10g}"
        )
    out.append(
        "# HELP isotope_engine_events_total Engine event counters"
        " (cache hits/misses, buckets formed, traces, fences)."
    )
    out.append("# TYPE isotope_engine_events_total counter")
    promoted = []
    for name, v in sorted(counters.items()):
        if name.endswith("_total"):
            # resilience headline counters (retries_total,
            # degradations_total, ...) get their own first-class series
            # — alert rules key on isotope_engine_degradations_total
            # directly, not on a label of the events grab-bag
            promoted.append((name, v))
            continue
        out.append(
            f'isotope_engine_events_total{{event="{name}"}} {v:.10g}'
        )
    for name, v in promoted:
        out.append(
            f"# HELP isotope_engine_{name} Engine resilience counter."
        )
        out.append(f"# TYPE isotope_engine_{name} counter")
        out.append(f"isotope_engine_{name} {v:.10g}")
    # gauges carry their own (optional) label block in the key
    seen_families: Set[str] = set()
    for key, v in sorted(gauges.items()):
        family = key.split("{", 1)[0]
        if family not in seen_families:
            seen_families.add(family)
            out.append(
                f"# HELP isotope_engine_{family} Engine gauge."
            )
            out.append(f"# TYPE isotope_engine_{family} gauge")
        out.append(f"isotope_engine_{key} {v:.10g}")
    return "\n".join(out) + "\n"


def prometheus_text() -> str:
    """Render the live registry as ``isotope_engine_*`` series."""
    return _render_prometheus(
        _STATE.phases, _STATE.counters, _STATE.gauges
    )


# -- JSONL validation / iteration (make telemetry-smoke, readers) ----------

def _jsonl_docs(path) -> Iterator[dict]:
    """Parsed records of a ``telemetry.jsonl`` file.

    An undecodable line — a crash mid-append leaving a torn tail, or a
    torn fragment a later ``append_jsonl`` healed onto its own line —
    is skipped and counted under ``telemetry_torn_lines``: one bad
    line costs one record, never the file.  (Same quarantine policy as
    the sweep checkpoint loader.)
    """
    with open(path) as f:
        lines = f.read().splitlines()
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            yield json.loads(line)
        except json.JSONDecodeError:
            counter_inc("telemetry_torn_lines")


def iter_jsonl(path) -> Iterator["RunTelemetry"]:
    """Iterate a ``telemetry.jsonl`` file as :class:`RunTelemetry`
    records, quarantining crash-torn lines (see ``_jsonl_docs``)."""
    for doc in _jsonl_docs(path):
        yield RunTelemetry.from_dict(doc)


def validate_jsonl(path) -> int:
    """Validate a ``telemetry.jsonl`` file; returns the record count.

    Raises ``ValueError`` on schema violations — the contract the
    ``make telemetry-smoke`` target enforces.  A crash-torn line (a
    killed run's half-written record) is skipped, not an error.
    """
    n = 0
    for i, doc in enumerate(_jsonl_docs(path), 1):
        if doc.get("schema") != SCHEMA:
            raise ValueError(
                f"{path}:{i}: schema {doc.get('schema')!r} != {SCHEMA!r}"
            )
        for section in ("phases", "counters", "gauges", "meta"):
            if not isinstance(doc.get(section), dict):
                raise ValueError(
                    f"{path}:{i}: missing/invalid {section!r} section"
                )
        for section in ("phases", "counters", "gauges"):
            for k, v in doc[section].items():
                if not isinstance(k, str) or not isinstance(
                    v, (int, float)
                ):
                    raise ValueError(
                        f"{path}:{i}: {section}[{k!r}] is not numeric"
                    )
        n += 1
    if n == 0:
        raise ValueError(f"{path}: no telemetry records")
    return n
