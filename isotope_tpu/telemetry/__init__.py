"""Engine self-telemetry (see telemetry/core.py for the design notes).

``isotope_tpu.telemetry.profile`` (the XLA-trace capture backend) is NOT
imported here: it depends on the engine, which itself imports this
package — callers import it lazily (``from isotope_tpu.telemetry import
profile``) from command handlers only.
"""
from isotope_tpu.telemetry.core import (  # noqa: F401
    SCHEMA,
    RunTelemetry,
    counter_get,
    counter_inc,
    detail_enabled,
    disable,
    emitting,
    enable,
    fence_reset,
    gauge_get,
    gauge_max,
    gauge_set,
    get_meta,
    install_jax_hooks,
    iter_jsonl,
    phase,
    phase_add,
    phase_seconds,
    prometheus_text,
    record_device_memory,
    record_trace,
    reset,
    segment_fence,
    set_meta,
    snapshot,
    summary_block,
    summary_line,
    time_first_call,
    validate_jsonl,
)
