"""Pluggable load-balancing laws: the per-station wait model menu.

PR 9/10 co-simulated Envoy's resilience control planes, but every
station still queued under ONE wait law — the shared-queue M/M/k
idealization.  Real Envoy data planes have no central queue: each
backend owns its own queue and the *balancing policy* decides which
backend a request joins, which changes the waiting-time law itself,
not just its parameters.  This module supplies that menu as per-service
laws declared in the topology YAML ``policies:`` block::

    policies:
      defaults:
        lb: least_request                # scalar shorthand
      worker:
        lb: {policy: least_request, choices_d: 3, panic_threshold: 40%}
      store:
        lb: {policy: wrr, weights: [3, 1, 1, 1]}
      cache:
        lb: {policy: ring_hash, hash_skew: 1.2}

Laws (each stays in the engine's coin + exponential sampling form —
``(p_wait, wait_rate)`` per station — so every executor path, the scan
buckets included, consumes them unchanged):

- ``fifo`` — the legacy shared-queue M/M/k law, untouched (the
  neutral law: declaring it changes nothing beyond table presence);
- ``least_request`` — Envoy's default, power-of-``choices_d``-choices:
  the request samples ``d`` backends and joins the least loaded.  The
  mean-field law (Mitzenmacher): the fraction of backends holding
  >= i jobs is ``rho^((d^i - 1)/(d - 1))``, so queue tails decay
  doubly exponentially.  We match the law's exact ``P(wait) = rho^d``
  and its mean-field mean wait, sampling the conditional wait as an
  exponential (an approximation over the per-backend census: the
  census is what ``d`` sampled backends expose).  ``d = 1`` recovers
  uniform-random per-backend dispatch (independent M/M/1s) exactly;
- ``ring_hash`` — consistent-hash stickiness with key-popularity skew:
  backend ``b`` attracts share ``(b+1)^(-hash_skew)`` (a Zipf profile
  over the ring's arcs — skew 0 is a uniform ring, larger skews model
  hot keys pinning their arc's backend).  The station becomes a
  share-weighted mixture of per-backend M/M/1 stations; we match the
  mixture's ``P(wait)`` and mean wait.  Composes with the PR 10 canary
  split: each version's endpoint set hashes its OWN ring, so the
  canary arm re-applies the law over its own replicas — hash
  stickiness respects version weights;
- ``wrr`` — weighted round-robin: deterministic weight-proportional
  admission, the same mixture law with declared per-backend
  ``weights`` (cycled over replicas the autoscaler adds);
- **panic routing** (any law, ``panic_threshold``): when the HEALTHY
  fraction of a service's pool — after PR 9 outlier ejection and chaos
  kills — drops below the threshold, Envoy abandons health filtering
  and routes to ALL backends, ejected ones included.  Requests landing
  on dead backends fast-fail (the breaker-shed 500 path: no queue, no
  script, nothing downstream), and the survivors keep their UNDEGRADED
  per-backend load instead of absorbing the whole stream — an ejection
  storm degrades goodput gracefully instead of collapsing the
  survivors' wait law.

Dynamic composition: the laws read the CURRENT effective pool (HPA
actuated count minus ejections minus chaos downs) every block, so they
ride the same scan carry as the PR 9 policy laws; with no policy
tables the pool is the static per-phase table and the laws are
piecewise-static.  ``lb`` absent keeps every traced program
byte-identical (pinned); an all-``fifo`` block with no panic is the
neutral-law <= 1 ULP pin.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from isotope_tpu.models.decode import (
    field as _field,
    fraction as _frac,
    integer as _int,
    keyword as _keyword,
    number as _num,
)
from isotope_tpu.models.errors import config_path


# -- configuration (the `lb:` entries of the `policies:` block) ------------


KINDS = ("fifo", "least_request", "ring_hash", "wrr")
KIND_FIFO, KIND_LEAST_REQUEST, KIND_RING_HASH, KIND_WRR = range(4)


@dataclasses.dataclass(frozen=True)
class LbPolicy:
    """One service's load-balancing law (Envoy's LB menu subset)."""

    policy: str = "fifo"
    choices_d: int = 2            # least_request: the power-of-d fan
    hash_skew: float = 1.0        # ring_hash: Zipf exponent over arcs
    weights: Tuple[float, ...] = ()  # wrr: per-backend weights
    panic_threshold: float = 0.0  # 0 disables panic routing

    _FIELDS = {
        "policy", "choices_d", "hash_skew", "weights", "panic_threshold",
    }

    @classmethod
    def decode(cls, value) -> "LbPolicy":
        if isinstance(value, str):
            value = {"policy": value}
        if not isinstance(value, dict):
            raise ValueError(
                f"lb must be a policy name or a mapping: {value!r}"
            )
        unknown = set(value) - cls._FIELDS
        if unknown:
            raise ValueError(f"unknown lb fields: {sorted(unknown)}")
        field = functools.partial(_field, value)
        policy = field("policy", lambda v: _keyword(v, KINDS), "fifo")

        def weights_list(v):
            if not isinstance(v, (list, tuple)) or not v:
                raise ValueError(
                    f"expected a non-empty list of weights: {v!r}"
                )
            out = tuple(_num(w) for w in v)
            if any(w <= 0 for w in out):
                raise ValueError(f"weights must be positive: {v!r}")
            return out

        out = cls(
            policy=policy,
            choices_d=field("choices_d", _int, 2),
            hash_skew=field("hash_skew", _num, 1.0),
            weights=field("weights", weights_list, ()),
            panic_threshold=field("panic_threshold", _frac, 0.0),
        )
        # per-law fields stay on their law: a `choices_d` on a ring-hash
        # service is a config typo, not a silent default
        if "choices_d" in value and policy != "least_request":
            with config_path("choices_d"):
                raise ValueError(
                    f"choices_d only applies to least_request "
                    f"(policy is {policy!r})"
                )
        if "hash_skew" in value and policy != "ring_hash":
            with config_path("hash_skew"):
                raise ValueError(
                    f"hash_skew only applies to ring_hash "
                    f"(policy is {policy!r})"
                )
        if "weights" in value and policy != "wrr":
            with config_path("weights"):
                raise ValueError(
                    f"weights only applies to wrr (policy is {policy!r})"
                )
        if out.choices_d < 1:
            with config_path("choices_d"):
                raise ValueError("choices_d must be >= 1")
        if out.hash_skew < 0:
            with config_path("hash_skew"):
                raise ValueError("hash_skew must be >= 0")
        return out

    @property
    def kind(self) -> int:
        return KINDS.index(self.policy)

    @property
    def active(self) -> bool:
        return self.policy != "fifo" or self.panic_threshold > 0.0


@dataclasses.dataclass(frozen=True)
class LbSet:
    """The decoded ``lb:`` entries of a topology's ``policies:`` block.

    Same defaults discipline as :class:`~isotope_tpu.sim.policies.
    PolicySet`: ``policies.defaults.lb`` seeds every service, a
    per-service ``lb:`` replaces it wholesale, an explicit ``lb: null``
    disables the default for that service.
    """

    per_service: Dict[str, Optional[LbPolicy]]
    defaults: Optional[LbPolicy]

    @classmethod
    def decode(cls, raw: dict, service_names) -> "LbSet":
        if not isinstance(raw, dict):
            raise ValueError(f"policies must be a mapping: {raw!r}")
        names = list(service_names)
        with config_path("policies"):
            default: Optional[LbPolicy] = None
            d = raw.get("defaults")
            if isinstance(d, dict) and d.get("lb") is not None:
                with config_path("defaults"), config_path("lb"):
                    default = LbPolicy.decode(d["lb"])
            per: Dict[str, Optional[LbPolicy]] = {}
            for key, value in raw.items():
                if key == "defaults":
                    continue
                if key not in names:
                    raise ValueError(
                        f"policies target unknown service {key!r}"
                    )
                if not isinstance(value, dict) or "lb" not in value:
                    continue
                with config_path(key), config_path("lb"):
                    per[key] = (
                        None if value["lb"] is None
                        else LbPolicy.decode(value["lb"])
                    )
        return cls(per_service=per, defaults=default)

    def for_service(self, name: str) -> Optional[LbPolicy]:
        if name in self.per_service:
            return self.per_service[name]
        return self.defaults

    @property
    def empty(self) -> bool:
        """True when NO service declares any lb law at all."""
        return self.defaults is None and not any(
            p is not None for p in self.per_service.values()
        )


def lint_lb(
    raw: dict, service_names
) -> Tuple[Optional["LbSet"], List[Tuple[str, str]]]:
    """Tolerant decode for the vet linter (the policies.lint_policies
    idiom): decode errors become findings instead of crashes."""
    try:
        return LbSet.decode(raw, service_names), []
    except ValueError as e:
        return None, [("decode", str(e))]


# -- dense per-service tables (compiler/compile.compile_lb) ----------------


@dataclasses.dataclass(frozen=True)
class LbTables:
    """The ``lb:`` entries lowered to dense per-service arrays in
    compiled service order — the device-constant form the engine's
    wait-law selection consumes (cache-keyed like the breaker/budget
    tables)."""

    names: Tuple[str, ...]
    static_replicas: np.ndarray   # (S,) i64 — topology numReplicas
    kind: np.ndarray              # (S,) i32 — KIND_* (fifo default)
    choices_d: np.ndarray         # (S,) f64
    hash_skew: np.ndarray         # (S,) f64
    panic_threshold: np.ndarray   # (S,) f64, 0 = panic off
    weights: np.ndarray           # (S, Wmax) f64, NaN-padded
    wlen: np.ndarray              # (S,) i64 — declared weight count

    @property
    def num_services(self) -> int:
        return len(self.names)

    @property
    def any_lr(self) -> bool:
        return bool((self.kind == KIND_LEAST_REQUEST).any())

    @property
    def any_mix(self) -> bool:
        return bool(
            ((self.kind == KIND_RING_HASH) | (self.kind == KIND_WRR))
            .any()
        )

    @property
    def any_panic(self) -> bool:
        return bool((self.panic_threshold > 0.0).any())

    @property
    def active(self) -> bool:
        """False when every service is fifo with panic off — the
        engine then skips the law selection entirely (but the tables
        still key the executable cache, so the <= 1 ULP neutral pin is
        about the selection math, not table presence)."""
        return self.any_lr or self.any_mix or self.any_panic

    def signature(self) -> str:
        """Stable identity for executable-cache keys."""
        parts = [f"{self.names!r}"]
        for f in dataclasses.fields(self)[1:]:
            parts.append(np.asarray(getattr(self, f.name)).tobytes().hex())
        return "lb:" + "|".join(parts)

    def backend_profile(self, k_max: int) -> np.ndarray:
        """(S, k_max) unnormalized per-backend attraction weights.

        The profile spans the WIDEST pool any law can see (the engine's
        Erlang ``k_max``, autoscaler max included); the device law
        masks columns past the current pool size and renormalizes, so
        a scale-up extends the ring / weight cycle consistently:
        ring-hash arcs keep their Zipf ranks, wrr weights cycle
        (``weights[b % len]`` — new pods inherit the declared
        pattern).  fifo / least_request rows are uniform (their laws
        never read the profile)."""
        S = self.num_services
        prof = np.ones((S, k_max), np.float64)
        b = np.arange(k_max, dtype=np.float64)
        for s in range(S):
            if self.kind[s] == KIND_RING_HASH:
                prof[s] = (b + 1.0) ** (-self.hash_skew[s])
            elif self.kind[s] == KIND_WRR:
                n = int(self.wlen[s])
                w = self.weights[s, :n]
                prof[s] = w[np.arange(k_max) % n]
        return prof


def build_tables(lbs: LbSet, services) -> LbTables:
    """Lower a decoded LbSet against a compiled ServiceTable."""
    names = tuple(services.names)
    S = len(names)
    kind = np.zeros(S, np.int32)
    choices = np.full(S, 2.0)
    skew = np.ones(S)
    panic = np.zeros(S)
    pols = [lbs.for_service(n) for n in names]
    wmax = max([len(p.weights) for p in pols if p is not None] + [1])
    weights = np.full((S, wmax), np.nan)
    wlen = np.zeros(S, np.int64)
    for s, p in enumerate(pols):
        if p is None:
            continue
        kind[s] = p.kind
        choices[s] = float(p.choices_d)
        skew[s] = float(p.hash_skew)
        panic[s] = float(p.panic_threshold)
        if p.weights:
            weights[s, : len(p.weights)] = p.weights
            wlen[s] = len(p.weights)
        elif p.kind == KIND_WRR:
            # wrr without declared weights is uniform round-robin
            weights[s, 0] = 1.0
            wlen[s] = 1
    return LbTables(
        names=names,
        static_replicas=np.asarray(services.replicas, np.int64),
        kind=kind,
        choices_d=choices,
        hash_skew=skew,
        panic_threshold=panic,
        weights=weights,
        wlen=wlen,
    )


# -- device-side laws ------------------------------------------------------
#
# Imported lazily below the host-only decode layer for the same reason
# as sim/policies.py: topo_lint and the converters decode lb blocks
# without a jax dependency.

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from isotope_tpu.sim import queueing  # noqa: E402
from isotope_tpu.sim.queueing import _MAX_RHO, QueueParams  # noqa: E402

#: truncation of the mean-field tail sum; terms decay doubly
#: exponentially for d >= 2 (the d = 1 geometric residue is summed in
#: closed form), so 8 terms are exact to f32 resolution
_LR_TERMS = 8


class DeviceLb(NamedTuple):
    """LbTables uploaded as device constants (plus the dense backend
    profile resolved against the engine's ``k_max``)."""

    is_lr: jax.Array            # (S,) bool
    is_mix: jax.Array           # (S,) bool — ring_hash | wrr
    choices_d: jax.Array        # (S,) f32
    panic_threshold: jax.Array  # (S,) f32
    profile: jax.Array          # (S, k_max) f32 backend attraction


def effective_profile(
    t: LbTables,
    k_max: int,
    degraded: Optional[Tuple[int, float]] = None,
) -> np.ndarray:
    """The backend-attraction profile the run actually executes:
    :meth:`LbTables.backend_profile` with the armed
    ``lb.degraded_backend`` chaos collapse applied.  ONE source for
    both the traced device constants and the host-side feedback
    mirror, so the static fixed point integrates the same gray-failure
    shares the engine samples."""
    prof = t.backend_profile(k_max)
    if degraded is not None:
        b, factor = degraded
        if 0 <= b < k_max:
            prof = prof.copy()
            prof[:, b] = prof[:, b] * factor
    return prof


def device_tables(
    t: LbTables,
    k_max: int,
    degraded: Optional[Tuple[int, float]] = None,
) -> DeviceLb:
    """Upload tables; ``degraded`` is the armed ``lb.degraded_backend``
    chaos site — ``(backend, factor)`` multiplies that backend's
    attraction weight (the gray failure where one endpoint's effective
    weight silently collapses: ring-hash arcs shrink, wrr skips it,
    while least_request — profile-free by design — routes around it).
    Trace-affecting, so it participates in ``faults.signature()``."""
    prof = effective_profile(t, k_max, degraded)
    return DeviceLb(
        is_lr=jnp.asarray(t.kind == KIND_LEAST_REQUEST),
        is_mix=jnp.asarray(
            (t.kind == KIND_RING_HASH) | (t.kind == KIND_WRR)
        ),
        choices_d=jnp.asarray(t.choices_d, jnp.float32),
        panic_threshold=jnp.asarray(t.panic_threshold, jnp.float32),
        profile=jnp.asarray(prof, jnp.float32),
    )


def wait_params(
    tables: LbTables,
    dlb: DeviceLb,
    arrival_rate: jax.Array,   # (..., S)
    service_rate,              # scalar or (S,) per-server mu
    replicas: jax.Array,       # (..., S) int
    k_max: int,
) -> QueueParams:
    """Per-station sampling parameters under the per-service LB laws.

    Starts from the shared-queue M/M/k parameters (the fifo law) and
    overlays the least-request and mixture laws where configured —
    fifo rows pass through ``queueing.mmk_params`` untouched, which is
    the <= 1 ULP neutral pin.  Aggregate ``utilization`` keeps the
    station-level ``lambda / (k mu)`` reading for every law;
    ``unstable`` flags the HOT BACKEND under a mixture (a skewed ring
    saturates its hottest arc long before the aggregate does)."""
    base = queueing.mmk_params(arrival_rate, service_rate, replicas,
                               k_max)
    lam = jnp.asarray(arrival_rate, jnp.float32)
    mu = jnp.broadcast_to(
        jnp.asarray(service_rate, jnp.float32), lam.shape
    )
    kf = jnp.asarray(replicas, jnp.int32).astype(jnp.float32)
    p_wait, rate = base.p_wait, base.wait_rate
    unstable = base.unstable

    rho_raw = lam / (kf * mu)
    # the same near-saturation clamp as the fifo law, floored away from
    # zero so log/exp stay finite on unreached services
    rho = jnp.clip(rho_raw, 1e-9, _MAX_RHO)

    if tables.any_lr:
        d = dlb.choices_d
        logr = jnp.log(rho)
        dm1 = jnp.maximum(d - 1.0, 1e-6)
        s_sum = jnp.zeros_like(rho)
        for i in range(1, _LR_TERMS + 1):
            # tail-fraction exponents (d^i - 1)/(d - 1); d = 1 -> i
            e_i = jnp.where(d > 1.5, (d**i - 1.0) / dm1, float(i))
            s_sum = s_sum + jnp.exp(e_i * logr)
        # d = 1 (random per-backend dispatch): geometric residue past
        # the truncation, so the law is the EXACT M/M/1 at every rho
        s_sum = s_sum + jnp.where(
            d < 1.5,
            jnp.exp(float(_LR_TERMS + 1) * logr) / (1.0 - rho),
            0.0,
        )
        # mean jobs per backend minus the in-service term -> queued
        q_len = jnp.maximum(s_sum - rho, 1e-12)
        p_lr = jnp.exp(d * logr)                     # P(all d busy)
        mean_w = q_len / (rho * mu)                  # Little, per server
        rate_lr = p_lr / jnp.maximum(mean_w, 1e-30)
        p_wait = jnp.where(dlb.is_lr, p_lr, p_wait)
        rate = jnp.where(dlb.is_lr, rate_lr, rate)

    if tables.any_mix:
        K = dlb.profile.shape[1]
        cols = jnp.arange(K, dtype=jnp.float32)
        mask = cols < kf[..., None]                  # (..., S, K)
        w = dlb.profile * mask
        share = w / jnp.maximum(w.sum(-1, keepdims=True), 1e-30)
        lam_b = lam[..., None] * share
        rho_b_raw = lam_b / mu[..., None]            # per-backend M/M/1
        rho_b = jnp.minimum(rho_b_raw, _MAX_RHO)
        p_mix = (share * rho_b).sum(-1)
        mean_mix = (
            share * rho_b / (mu[..., None] * (1.0 - rho_b))
        ).sum(-1)
        rate_mix = p_mix / jnp.maximum(mean_mix, 1e-30)
        hot = ((rho_b_raw >= 1.0) & (share > 0)).any(-1)
        p_wait = jnp.where(dlb.is_mix, p_mix, p_wait)
        rate = jnp.where(dlb.is_mix, rate_mix, rate)
        unstable = jnp.where(dlb.is_mix, hot, unstable)

    return QueueParams(
        p_wait=p_wait,
        wait_rate=jnp.maximum(rate, 1e-20),
        utilization=base.utilization,
        unstable=unstable,
    )


def panic_split(
    dlb: DeviceLb,
    arrival_rate: jax.Array,  # (..., S)
    alive: jax.Array,         # (..., S) healthy replicas (may be 0)
    total: jax.Array,         # (..., S) pool size incl. ejected/downed
) -> Tuple[jax.Array, jax.Array]:
    """Envoy panic-threshold routing, per (phase, service).

    Below the threshold the mesh routes to ALL backends: the share
    landing on dead/ejected ones (``1 - healthy_frac``) fast-fails
    (the caller draws the panic coin against it), and the wait law's
    offered load scales by ``healthy_frac`` — the survivors keep their
    undegraded per-backend load instead of absorbing the whole
    stream.  Returns ``(lambda_for_wait_law, panic_fail_prob)``."""
    frac = jnp.clip(alive / jnp.maximum(total, 1.0), 0.0, 1.0)
    panic = (dlb.panic_threshold > 0.0) & (frac < dlb.panic_threshold)
    lam_out = jnp.where(panic, arrival_rate * frac, arrival_rate)
    p_fail = jnp.where(panic, 1.0 - frac, 0.0)
    return lam_out, p_fail


# -- numpy mirror (sim/feedback.py's visit fixed point) --------------------


def np_wait_stats(
    tables: LbTables,
    profile: np.ndarray,   # (S, k_max) from backend_profile
    lam: np.ndarray,       # (S,)
    mu: float,
    k: np.ndarray,         # (S,) >= 1
) -> Tuple[np.ndarray, np.ndarray]:
    """Host mirror of :func:`wait_params` for the retry-storm fixed
    point: ``(p_wait, wait_rate)`` under the per-service laws, so the
    static visit estimates see the same skewed per-backend waits the
    engine samples (a hot ring-hash arc times out long before the
    aggregate M/M/k says so)."""
    from isotope_tpu.sim.feedback import np_mmk

    lam = np.asarray(lam, np.float64)
    k = np.asarray(np.maximum(k, 1.0), np.float64)
    p_wait, rate, _ = np_mmk(lam, mu, k)
    rho = np.clip(lam / (k * mu), 1e-9, _MAX_RHO)

    lr = tables.kind == KIND_LEAST_REQUEST
    if lr.any():
        d = tables.choices_d
        s_sum = np.zeros_like(rho)
        dm1 = np.maximum(d - 1.0, 1e-6)
        for i in range(1, _LR_TERMS + 1):
            e_i = np.where(d > 1.5, (d**i - 1.0) / dm1, float(i))
            s_sum = s_sum + rho**e_i
        s_sum = s_sum + np.where(
            d < 1.5, rho ** (_LR_TERMS + 1) / (1.0 - rho), 0.0
        )
        q_len = np.maximum(s_sum - rho, 1e-12)
        p_lr = rho**d
        mean_w = q_len / (rho * mu)
        p_wait = np.where(lr, p_lr, p_wait)
        rate = np.where(lr, p_lr / np.maximum(mean_w, 1e-30), rate)

    mix = (tables.kind == KIND_RING_HASH) | (tables.kind == KIND_WRR)
    if mix.any():
        K = profile.shape[1]
        mask = np.arange(K)[None, :] < k[:, None]
        w = profile * mask
        share = w / np.maximum(w.sum(-1, keepdims=True), 1e-30)
        rho_b = np.minimum(lam[:, None] * share / mu, _MAX_RHO)
        p_mix = (share * rho_b).sum(-1)
        mean_mix = (share * rho_b / (mu * (1.0 - rho_b))).sum(-1)
        p_wait = np.where(mix, p_mix, p_wait)
        rate = np.where(
            mix, p_mix / np.maximum(mean_mix, 1e-30), rate
        )
    return p_wait, np.maximum(rate, 1e-30)


# -- host-side reporting ---------------------------------------------------


def _np(x) -> np.ndarray:
    return np.asarray(x, np.float64)


def to_doc(
    tables: LbTables,
    tl=None,        # Optional[TimelineSummary] — per-window arrivals
    pol=None,       # Optional[PolicySummary] — actuated pool sizes
    max_windows: int = 64,
) -> dict:
    """The ``lb.json`` artifact (``isotope-lb/v1``): per-service law +
    parameters, the static per-backend load-split vector, and — with a
    flight-recorder summary — the per-window per-backend load split
    (window arrivals spread by the share vector over the pool size in
    effect at that window, the PolicySummary's ``effective`` series
    when the PR 9 loops ran).  The census is derived from the
    psum-merged recorder windows, so sharded runs report the global
    split."""
    k_max = int(tables.static_replicas.max(initial=1))
    eff_p = None
    done = None
    if pol is not None:
        eff_p = _np(pol.effective)
        k_max = max(k_max, int(np.ceil(eff_p.max(initial=1.0))))
        # protected runs know which windows COMPLETED: series past
        # pol.windows_done were never advanced (zero-filled on
        # device) and would read as a pool collapsed to one backend
        done = _np(pol.windows_done) > 0
    profile = tables.backend_profile(k_max)
    arr = None
    if tl is not None:
        arr = _np(tl.svc_arrivals)                      # (S, W)
    services: Dict[str, dict] = {}
    for s, name in enumerate(tables.names):
        kind = int(tables.kind[s])
        panic = float(tables.panic_threshold[s])
        if kind == KIND_FIFO and panic <= 0.0:
            continue
        k_s = int(tables.static_replicas[s])
        w = profile[s, :k_s]
        share = (w / max(w.sum(), 1e-30)).tolist()
        doc = {
            "policy": KINDS[kind],
            "replicas": k_s,
            "share": [round(v, 6) for v in share],
        }
        if kind == KIND_LEAST_REQUEST:
            doc["choices_d"] = int(tables.choices_d[s])
        if kind == KIND_RING_HASH:
            doc["hash_skew"] = float(tables.hash_skew[s])
        if kind == KIND_WRR:
            n = int(tables.wlen[s])
            doc["weights"] = list(tables.weights[s, :n])
        if panic > 0.0:
            doc["panic_threshold"] = panic
        if arr is not None:
            W = arr.shape[1]
            split = []
            for wi in range(min(W, max_windows)):
                if done is not None and not done[wi]:
                    break
                k_w = k_s
                if eff_p is not None:
                    k_w = max(int(round(eff_p[s, wi])), 1)
                pw = profile[s, :k_w]
                sh = pw / max(pw.sum(), 1e-30)
                split.append(
                    [round(float(arr[s, wi] * v), 3) for v in sh]
                )
            doc["window_split"] = split
            # totals span THIS service's widest pool across the run
            # (HPA growth included), not the doc-global k_max
            k_top = max([len(row) for row in split] + [k_s])
            doc["backend_hops"] = [
                round(float(v), 3)
                for v in np.sum(
                    [np.pad(row, (0, k_top - len(row)))
                     for row in split] or [np.zeros(k_top)],
                    axis=0,
                )
            ]
        services[name] = doc
    return {
        "schema": "isotope-lb/v1",
        "k_max": k_max,
        "services": services,
    }


def format_table(doc: dict) -> str:
    """Human-readable per-backend load-split table (CLI stderr)."""
    lines = ["lb:"]
    for name, svc in doc.get("services", {}).items():
        bits = [f"{name:<20} {svc['policy']}"]
        if "choices_d" in svc:
            bits.append(f"d={svc['choices_d']}")
        if "hash_skew" in svc:
            bits.append(f"skew={svc['hash_skew']:g}")
        if "panic_threshold" in svc:
            bits.append(f"panic<{svc['panic_threshold']:.0%}")
        share = svc.get("share", [])
        bits.append(
            "share [" + " ".join(f"{v:.2f}" for v in share) + "]"
        )
        hops = svc.get("backend_hops")
        if hops:
            bits.append(
                "hops [" + " ".join(f"{v:g}" for v in hops) + "]"
            )
        lines.append("  ".join(bits))
    return "\n".join(lines)
