"""Finite-population queueing model for the saturated closed loop.

Fortio's ``-qps max`` mode (the reference's default experiment:
``isotope/example-config.toml`` sets ``qps = "max"`` with 64
connections; built by perf/benchmark/runner/runner.py:255-268) keeps
exactly C requests in flight: each connection fires its next request the
moment the previous one returns.  The in-system population is therefore
hard-bounded at C, and the open-loop M/M/k stationary wait law — whose
conditional wait is an unbounded exponential with rate k*mu - lambda —
cannot represent the truncated sojourn distribution (engine p99 was +79%
vs the DES oracle before this model; ORACLE.md r3).

This module models the run as a **closed product-form network**:

- one FIFO station per service with load-dependent completion rate
  mu_s(j) = min(j, k_s) * mu  (k_s = NumReplicas, the M/M/k station);
- one delay (infinite-server) station — load-dependent rate j / Z —
  aggregating wire time and sleeps;
- population N = connections, visit ratios v_s = expected hops per
  root request.

Three pieces make the sampled latencies track the DES oracle:

1. **Exact load-dependent MVA** (Reiser-Lavenberg) yields the network
   throughput lambda(N) — Fortio's measured ``-qps max`` ActualQPS —
   and per-station queue-length marginals.  By the arrival theorem a
   request arriving at station s sees the stationary distribution with
   population N-1, so its wait is the mixture P(wait=0) = P(j < k_s),
   wait | j >= k_s ~ Erlang(j - k_s + 1, k_s * mu), which the engine
   samples via a per-station quantile polynomial in v = -log(1 - u)
   (Horner with per-hop coefficient rows: zero gathers).
2. **Fork-join cycle weights.**  MVA's cycle sums visits serially, but
   concurrent siblings overlap in time, so each member of an m-wide
   concurrent group contributes ~H_m/m of its response to the cycle
   (H_m the harmonic number: E[max of m iid Exp] = H_m * E[one]).  The
   weights scale only the cycle denominator — station utilizations
   keep the full visit ratios (every branch really executes).
3. **The population copula.**  Station queue lengths under a fixed
   population are negatively correlated (sum_s j_s + j_delay = N - 1
   exactly), so summing independently-sampled waits along a path
   overestimates the tail (+38% on chain3 p99).  The exact identity
   Var(sum_s j_s) = Var(j_delay) pins the average pairwise correlation
       rho = (Var_d - sum Var_s) / ((sum sigma_s)^2 - sum sigma_s^2)
   which the engine realizes as a mean-centering Gaussian copula over
   the active hops' wait draws.

For exponential service and FIFO stations the network is BCMP
product-form, so chains are modeled exactly up to the copula's
equicorrelation approximation; the measured envelope is gated in
tests/test_oracle.py.
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import numpy as np
from scipy.special import gammainc

# shared wait-quantile polynomial degree (tables_from_pi and the
# engine's degenerate-row stubs must agree on the coefficient count)
DEFAULT_QUANTILE_DEGREE = 10


class ClosedTables(NamedTuple):
    """Per-population sampling tables (see ``closed_network_tables``)."""

    throughput: float     # lambda(N): the network's saturated QPS
    p_zero: np.ndarray    # (S,) P(wait == 0) seen at arrival
    coef: np.ndarray      # (D+1, S) wait-quantile polynomial in v
    mean_wait: np.ndarray  # (S,) E[wait] at arrival (diagnostics)
    sigma: np.ndarray     # (S,) std of the queue census at arrival
    var_delay: float      # Var(j_delay): the census-sum variance target


def convolution_marginals(
    visits: np.ndarray,
    replicas: np.ndarray,
    mu: float,
    delay_s: float,
    population: int,
) -> Tuple[float, np.ndarray, np.ndarray]:
    """Exact product-form solution via Buzen's convolution algorithm.

    Returns (lambda(N), pi, pi_delay) where ``pi[s, j]`` /
    ``pi_delay[j]`` are queue-length distributions under population
    N-1 — what an arriving customer sees (arrival theorem).

    Load-dependent exact MVA is numerically unstable for multi-server
    stations: its per-population marginals rely on ``P(0|n) = 1 - sum``
    cancellations that corrupt catastrophically once a station's tail
    mass approaches 1 (observed: a k=2 station's computed throughput
    DROPPED below k=1's).  The convolution form has no cancellation —
    every term is a nonneg product — and stays exact with a common rate
    scale ``beta`` plus per-step max-normalization (tracked in log
    space):

        f_s(j)   = prod_{i<=j} beta * v_s / mu_s(i)     (station)
        f_d(j)   = (beta * Z)^j / j!                     (delay)
        G        = f_1 (*) ... (*) f_S (*) f_d
        lambda(N)= beta * G(N-1) / G(N)
        P_s(j|n) = f_s(j) * G_{-s}(n - j) / G(n)

    with ``G_{-s}`` assembled from prefix/suffix convolutions —
    O(S * N^2) total, like MVA.
    """
    v = np.asarray(visits, np.float64)
    k = np.asarray(replicas, np.float64)
    S = len(v)
    N = int(population)
    if N < 1:
        raise ValueError("population must be >= 1")
    z = max(float(delay_s), 1e-12)
    active = np.nonzero(v > 1e-15)[0]
    # common rate scale keeps the f magnitudes near 1
    beta = max(float((k * mu).max(initial=1.0)), 1.0 / z)

    def norm(c: np.ndarray, lg: float) -> Tuple[np.ndarray, float]:
        m = float(c.max())
        if m <= 0.0:
            return c, lg
        return c / m, lg + np.log(m)

    def log_station_f(s: int) -> np.ndarray:
        j = np.arange(1, N + 1, dtype=np.float64)
        rate = np.minimum(j, k[s]) * mu
        lf = np.empty(N + 1)
        lf[0] = 0.0
        lf[1:] = np.cumsum(np.log(beta * v[s] / rate))
        return lf

    def log_delay_f() -> np.ndarray:
        j = np.arange(1, N + 1, dtype=np.float64)
        lf = np.empty(N + 1)
        lf[0] = 0.0
        lf[1:] = np.cumsum(np.log(beta * z / j))
        return lf

    def from_log(lf: np.ndarray) -> Tuple[np.ndarray, float]:
        # factors span hundreds of orders of magnitude (beta*v/mu per
        # step can exceed 1 by k_max/k_s): exponentiate only after
        # centering on the max so nothing overflows
        m = float(lf.max())
        return np.exp(lf - m), m

    def conv(a: np.ndarray, b: np.ndarray) -> np.ndarray:
        return np.convolve(a, b)[: N + 1]

    # normalized factors (common log offsets cancel in the ratios below)
    fs: list = []
    lgs: list = []
    for s in active:
        f, lg = from_log(log_station_f(int(s)))
        fs.append(f)
        lgs.append(lg)
    fd, lgd = from_log(log_delay_f())
    fs.append(fd)
    lgs.append(lgd)
    M = len(fs)

    # prefix[i] = f_0 (*) ... (*) f_{i-1}; suffix[i] = f_i (*) ... last
    one = np.zeros(N + 1)
    one[0] = 1.0
    prefix = [(one, 0.0)]
    for i in range(M):
        c, lg = norm(conv(prefix[-1][0], fs[i]), prefix[-1][1] + lgs[i])
        prefix.append((c, lg))
    suffix = [(one, 0.0)]
    for i in reversed(range(M)):
        c, lg = norm(conv(fs[i], suffix[0][0]), lgs[i] + suffix[0][1])
        suffix.insert(0, (c, lg))
    g, _ = prefix[-1]
    if g[N] <= 0.0 or g[N - 1] <= 0.0:  # pragma: no cover - degenerate
        raise FloatingPointError("convolution underflow")
    lam = beta * g[N - 1] / g[N]

    # arriving-customer marginals at population N-1
    pi = np.zeros((S, N))
    pi[:, 0] = 1.0
    pi_d = np.zeros(N)
    pi_d[0] = 1.0
    n1 = N - 1
    for idx in range(M):
        gm = conv(prefix[idx][0], suffix[idx + 1][0])
        f = fs[idx]
        raw = f[: n1 + 1] * gm[n1::-1] if n1 >= 0 else f[:1]
        tot = float(raw.sum())
        marg = np.zeros(N)
        if tot > 0.0 and n1 >= 0:
            marg[: n1 + 1] = raw / tot
        else:
            marg[0] = 1.0
        if idx < len(active):
            pi[active[idx]] = marg
        else:
            pi_d = marg
    return lam, pi, pi_d


def mva_load_dependent(
    visits: np.ndarray,
    cycle_visits: np.ndarray,
    replicas: np.ndarray,
    mu: float,
    delay_s: float,
    population: int,
) -> Tuple[float, np.ndarray, np.ndarray]:
    """Exact MVA; returns (lambda(N), pi, pi_delay).

    ``pi[s, j]`` / ``pi_delay[j]`` are queue-length distributions under
    population N-1 — what an arriving customer sees (arrival theorem).
    ``visits`` drives utilization (the pi recursion); ``cycle_visits``
    weights the cycle denominator (fork-join overlap, see module doc).
    O(S * N^2) in float64; stations with ``visits == 0`` fall out
    naturally (their pi stays a point mass at 0).

    .. warning:: numerically unstable for multi-server (k > 1)
       stations near saturation — the production path uses
       :func:`convolution_marginals`; this remains as a cross-check
       for k == 1 networks.
    """
    v = np.asarray(visits, np.float64)
    cv = np.asarray(cycle_visits, np.float64)
    k = np.asarray(replicas, np.float64)
    S = len(v)
    N = int(population)
    if N < 1:
        raise ValueError("population must be >= 1")
    z = max(float(delay_s), 1e-12)
    # completion rate with j customers present, j = 1..N: the delay
    # "station" (row S) is an infinite server with rate j / Z
    j = np.arange(1, N + 1, dtype=np.float64)
    rate = np.empty((S + 1, N))
    rate[:S] = np.minimum(j[None, :], k[:, None]) * mu
    rate[S] = j / z
    v_all = np.concatenate([v, [1.0]])
    cv_all = np.concatenate([cv, [1.0]])

    pi_prev = np.zeros((S + 1, N + 1))  # distribution at population n-1
    pi_prev[:, 0] = 1.0
    pi_at_nm1 = pi_prev
    lam = 0.0
    for n in range(1, N + 1):
        # E[response per visit] = sum_j (j+1)/mu(j+1) * pi(j | n-1);
        # for the delay station this reduces to exactly Z.  The cycle
        # sums cv * W alone — cycle_visits already carries the reach
        # (visit ratio) times the fork-join overlap factor.
        w = (pi_prev[:, :n] * (j[None, :n] / rate[:, :n])).sum(axis=1)
        lam = n / float((cv_all * w).sum())
        pi = np.zeros((S + 1, N + 1))
        pi[:, 1 : n + 1] = (
            lam * v_all[:, None] / rate[:, :n] * pi_prev[:, :n]
        )
        # rounding can push the tail slightly negative; clamp then close
        np.clip(pi, 0.0, None, out=pi)
        pi[:, 0] = np.maximum(1.0 - pi[:, 1:].sum(axis=1), 0.0)
        if n == N:
            pi_at_nm1 = pi_prev
        pi_prev = pi
    return lam, pi_at_nm1[:S], pi_at_nm1[S]


def repairman_distribution(
    sources: int, k: int, mu: float, theta: float
) -> np.ndarray:
    """Stationary census of an M/M/k//N station (machine repairman).

    ``sources`` requests each cycle between a think phase of mean
    ``theta`` and this station; birth rate (N - j)/theta, death rate
    min(j, k) * mu.  Returns pi over j = 0..N (float64, normalized).
    """
    n = int(sources)
    pi = np.zeros(n + 1)
    # log-space recursion for numerical range
    logp = np.zeros(n + 1)
    for j_ in range(n):
        birth = (n - j_) / theta
        death = min(j_ + 1, k) * mu
        logp[j_ + 1] = logp[j_] + np.log(birth) - np.log(death)
    logp -= logp.max()
    pi = np.exp(logp)
    return pi / pi.sum()


def fork_join_decomposition(
    visits: np.ndarray,
    cycle_visits: np.ndarray,
    replicas: np.ndarray,
    mu: float,
    delay_s: float,
    population: int,
    iters: int = 200,
    tol: float = 1e-10,
) -> Tuple[float, np.ndarray, float]:
    """Per-station finite-source decomposition for fork-join graphs.

    MVA's single-token population constraint (sum_s j_s + j_d = N) is
    wrong under concurrent fan-out: a forked request holds one token at
    EACH branch station simultaneously, so every station's census is
    bounded by C on its own.  Decompose: station s is an M/M/k//C
    repairman queue whose per-source think time is the rest of the
    cycle, theta_s = cycle / v_s - W_s, with the cycle closed through
    the fork-join-weighted response sum (H_m/m overlap factors in
    ``cycle_visits``).  Damped fixed point; an arriving request sees
    the census with C-1 sources (finite-source arrival theorem).

    Returns (lambda(N), pi_seen[(S, N)], cycle_s).
    """
    v = np.asarray(visits, np.float64)
    cv = np.asarray(cycle_visits, np.float64)
    k = np.asarray(replicas, int)
    S = len(v)
    N = int(population)
    z = max(float(delay_s), 1e-12)
    w = np.full(S, 1.0 / mu)
    active = v > 1e-12
    pi_seen = np.zeros((S, N))
    cycle = z + float((cv * w).sum())
    for _ in range(iters):
        cycle_new = z + float((cv * w).sum())
        cycle = 0.5 * cycle + 0.5 * cycle_new
        w_new = w.copy()
        for s in range(S):
            if not active[s]:
                continue
            theta = max(cycle / v[s] - w[s], 1e-9)
            pi = repairman_distribution(N - 1, int(k[s]), mu, theta)
            pi_seen[s, : len(pi)] = pi
            j = np.arange(len(pi))
            mean_wait = float(
                (pi * np.maximum(j - k[s] + 1, 0)).sum()
            ) / (k[s] * mu)
            w_new[s] = mean_wait + 1.0 / mu
        if float(np.abs(w_new - w).max()) < tol / mu:
            w = w_new
            break
        w = 0.5 * w + 0.5 * w_new
    cycle = z + float((cv * w).sum())
    return N / cycle, pi_seen, cycle


def _erlang_mixture_quantiles(
    weights: np.ndarray, rate: float, v_grid: np.ndarray,
    scv: float = 1.0,
) -> np.ndarray:
    """Quantiles of the census-conditional wait mixture at the grid's
    conditional probabilities u = 1 - exp(-v) (weights sum to 1).

    A request seeing j >= k in queue waits for m = j - k + 1 service
    completions at aggregate rate k*mu.  For exponential service that
    wait is Erlang(m, rate); for general service it is a sum of m iid
    (residual) services — same mean m/rate, variance m * scv / rate^2 —
    matched here by Gamma(shape m/scv, rate rate/scv).  scv=1 recovers
    Erlang exactly; deterministic service (scv ~ 0) collapses the
    conditional wait onto its mean, which is what the DES shows (an
    exponential-stage tail overestimated M/D/k saturated p99 by +38%).
    """
    m = np.arange(1, len(weights) + 1, dtype=np.float64)
    u = -np.expm1(-v_grid)
    scv = min(max(float(scv), 1e-3), 25.0)
    shape = m / scv
    rate_g = rate / scv

    def cdf(t: np.ndarray) -> np.ndarray:
        # regularized lower incomplete gamma = Gamma(shape, rate_g) CDF
        return (
            weights[None, :] * gammainc(shape[None, :], rate_g * t[:, None])
        ).sum(axis=1)

    # bracket: mean + generous multiple of the largest-stage scale
    mean = float((weights * m).sum()) / rate
    hi = np.full(len(v_grid), max(mean * 4.0, 1.0 / rate))
    while (cdf(hi) < u).any():
        hi = np.where(cdf(hi) < u, hi * 2.0, hi)
    lo = np.zeros_like(hi)
    for _ in range(60):
        mid = 0.5 * (lo + hi)
        below = cdf(mid) < u
        lo = np.where(below, mid, lo)
        hi = np.where(below, hi, mid)
    return 0.5 * (lo + hi)


def repairman_marginals(
    visits: np.ndarray,
    replicas: np.ndarray,
    mu: float,
    cycle_s: float,
    w_prev: np.ndarray,
    population: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """One sweep of the finite-source decomposition at a known cycle.

    Given the request's current mean cycle time, each station's
    per-source think time is theta_s = cycle / v_s - W_s; returns the
    arriving-customer census (population - 1 sources) and the updated
    mean response W_s.  Used by the engine's self-consistent fork-join
    fixed point (the cycle is re-measured from the engine's own
    fork-join composition each iteration).
    """
    v = np.asarray(visits, np.float64)
    k = np.asarray(replicas, int)
    S = len(v)
    N = int(population)
    pi_seen = np.zeros((S, N))
    pi_seen[:, 0] = 1.0
    w_new = np.asarray(w_prev, np.float64).copy()
    for s in range(S):
        if v[s] <= 1e-12:
            continue
        theta = max(cycle_s / v[s] - w_prev[s], 1e-9)
        pi = repairman_distribution(N - 1, int(k[s]), mu, theta)
        pi_seen[s, : len(pi)] = pi
        j = np.arange(len(pi))
        mean_wait = float(
            (pi * np.maximum(j - k[s] + 1, 0)).sum()
        ) / (k[s] * mu)
        w_new[s] = mean_wait + 1.0 / mu
    return pi_seen, w_new


def census_sigma(pi: np.ndarray) -> np.ndarray:
    """Per-station standard deviation of census distributions
    ``pi[s, j]`` (rows are queue-length pmfs)."""
    jj = np.arange(pi.shape[1], dtype=np.float64)
    mean_j = (pi * jj).sum(axis=1)
    var_j = (pi * jj**2).sum(axis=1) - mean_j**2
    return np.sqrt(np.maximum(var_j, 0.0))


def compress_census(pi_row: np.ndarray, scv: float) -> np.ndarray:
    """QNA-style census reshaping for non-exponential service.

    The convolution/decomposition census assumes exponential service;
    the real queue-length fluctuation scales roughly with the
    arrival+service variability.  Two regimes:

    - scv >= 1: the open-network QNA form sqrt((1 + scv) / 2)
      (Poisson-ish arrival stream, ca^2 ~ 1) — heavy tails widen the
      census.
    - scv < 1: the closed saturated loop feeds each station with the
      DEPARTURES of its neighbors, whose variability collapses with
      the service scv (Whitt's departure interpolation at rho -> 1:
      cd^2 ~ cs^2), so ca^2 ~ scv and the factor is
      sqrt((scv + scv) / 2) = sqrt(scv) — reaching the deterministic
      pipeline's point census at scv -> 0 instead of QNA's 0.71
      floor, which left M/D/k saturated p99 at +25% (VERDICT r4).

    Both forms agree at scv = 1 (exponential: no reshaping).  Mass is
    remapped with linear interpolation (mean-preserving up to edge
    clipping).
    """
    scv = min(max(float(scv), 1e-3), 25.0)
    if abs(scv - 1.0) < 1e-9:
        return pi_row
    f = np.sqrt(scv) if scv < 1.0 else np.sqrt((1.0 + scv) / 2.0)
    n = len(pi_row)
    j = np.arange(n, dtype=np.float64)
    mean = float((pi_row * j).sum())
    tgt = np.clip(mean + (j - mean) * f, 0.0, n - 1)
    lo = np.floor(tgt).astype(int)
    hi = np.minimum(lo + 1, n - 1)
    w_hi = np.clip(tgt - lo, 0.0, 1.0)
    out = np.zeros(n)
    np.add.at(out, lo, pi_row * (1.0 - w_hi))
    np.add.at(out, hi, pi_row * w_hi)
    s = out.sum()
    return out / s if s > 0 else pi_row


def tables_from_pi(
    pi: np.ndarray,
    replicas: np.ndarray,
    mu: float,
    degree: int = DEFAULT_QUANTILE_DEGREE,
    v_max: float = 16.0,
    scv: float = 1.0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(p_zero, coef, mean_wait) quantile-polynomial tables from
    arriving-customer census distributions ``pi[s, j]``.

    The per-station conditional wait quantile W_s(v), v = -log(1 - u'),
    is least-squares fit with a degree-``degree`` polynomial over
    v in [0, v_max] (u' up to 1 - 1.1e-7); stations sharing the same
    (k, queue distribution) reuse one fit.
    """
    S = pi.shape[0]
    k = np.asarray(replicas, int)
    p_zero = np.empty(S)
    coef = np.zeros((degree + 1, S))
    mean_wait = np.zeros(S)
    # Exclude v = 0 from the fit and leave the intercept free: when the
    # census mixture sits at high stages (say ~40 — a single-replica
    # bottleneck under chaos), the true quantile leaps from 0 to the
    # mixture's bulk within u' ~ 1e-20; a polynomial dragged through an
    # exact W(0)=0 anchor undershoots the entire low-quantile region
    # (measured: sampled mean 3.46 ms vs the Little-law 4.92 ms).  The
    # free intercept ~= W(0.0625), distorting only ~6% mass near the
    # atom for low-stage mixtures where W really is ~0 there (the
    # engine clamps sampled waits at 0 either way).
    v_grid = np.linspace(0.0, v_max, 257)[1:]
    cache: Dict[bytes, Tuple[np.ndarray, float]] = {}
    for s in range(S):
        ks = int(k[s])
        p0 = float(pi[s, :ks].sum())
        # weights over m = j - k + 1 Erlang stages, j >= k
        w = pi[s, ks:]
        wsum = float(w.sum())
        if wsum <= 1e-12:
            p_zero[s] = 1.0
            continue
        w = w / wsum
        rate = ks * mu
        key = np.round(w, 12).tobytes() + bytes([ks & 0xFF])
        if key not in cache:
            t = _erlang_mixture_quantiles(w, rate, v_grid, scv)
            c = np.polynomial.polynomial.polyfit(v_grid, t, degree)
            m = np.arange(1, len(w) + 1)
            cache[key] = (c, float((w * m).sum()) / rate)
        c, cond_mean = cache[key]
        p_zero[s] = p0
        coef[:, s] = c
        mean_wait[s] = (1.0 - p0) * cond_mean
    return p_zero, coef, mean_wait


def closed_network_tables(
    visits: np.ndarray,
    cycle_visits: np.ndarray,
    replicas: np.ndarray,
    mu: float,
    delay_s: float,
    population: int,
    degree: int = DEFAULT_QUANTILE_DEGREE,
    v_max: float = 16.0,
    scv: float = 1.0,
) -> ClosedTables:
    """Exact product-form sampling tables for chain (no fork-join)
    graphs, via the numerically stable convolution algorithm
    (``cycle_visits`` equals ``visits`` on chains — forks are the only
    source of cycle reweighting, and concurrent graphs use the
    engine's self-consistent fixed point over ``repairman_marginals``
    instead: the single-token population constraint, and with it the
    variance identity, doesn't survive forks).
    """
    lam, pi, pi_d = convolution_marginals(
        visits, replicas, mu, delay_s, population
    )
    if abs(scv - 1.0) > 1e-9:
        pi = np.stack([compress_census(row, scv) for row in pi])
        pi_d = compress_census(pi_d, scv)
    p_zero, coef, mean_wait = tables_from_pi(
        pi, replicas, mu, degree, v_max, scv
    )

    if scv < 1.0 - 1e-9:
        # Low-variability limit: a deterministic closed network runs a
        # synchronized pipeline — throughput is exactly
        # min(N / C0, lambda*) (C0 the zero-wait cycle, lambda* the
        # capacity bound) with a DEGENERATE sojourn at N / lambda
        # (measured: the DES oracle's saturated M/D/1 chain has
        # p50 = p99 = N / capacity to the sample).  The exponential
        # product form undershoots that throughput (~4% on chain3) and
        # its census keeps residual burstiness, so blend the
        # throughput linearly in scv toward the pipeline bound and
        # rescale the wait tables so the mean sojourn obeys Little's
        # law at the blended rate.  scv = 1 recovers the product form
        # untouched; both corrections vanish there.
        v = np.asarray(visits, np.float64)
        cyc = np.asarray(cycle_visits, np.float64)
        k = np.asarray(replicas, np.float64)
        active = v > 1e-12
        lam_cap = float(np.min(k[active] * mu / v[active]))
        c0 = float((cyc / mu).sum()) + float(delay_s)
        lam_det = min(population / c0, lam_cap)
        g = max(float(scv), 0.0)
        lam_new = g * lam + (1.0 - g) * lam_det
        budget = max(population / lam_new - c0, 0.0)
        budget_tab = float((cyc * mean_wait).sum())
        if budget_tab > 1e-12:
            c = budget / budget_tab
            coef = coef * c
            mean_wait = mean_wait * c
        lam = lam_new

    # population copula inputs: Var(sum_s j_s) = Var(j_delay) exactly —
    # the engine shrinks the sigma-weighted z-combination to this target
    jd = np.arange(len(pi_d), dtype=np.float64)
    var_d = float((pi_d * jd**2).sum() - ((pi_d * jd).sum()) ** 2)
    return ClosedTables(
        throughput=lam,
        p_zero=p_zero,
        coef=coef,
        mean_wait=mean_wait,
        sigma=census_sigma(pi),
        var_delay=var_d,
    )
