"""M/M/k queueing model for service stations.

The reference's latency-beyond-sleeps comes from real contention: each
service is a Go HTTP server whose throughput saturates around 12-14k QPS
per vCPU (isotope/service/README.md:28-34), scaled out via ``NumReplicas``
k8s replicas (svc/service.go:33, kubernetes.go:200).  The simulator models
each service as an M/M/k station: k = NumReplicas servers, per-server rate
mu = 1 / cpu_time, offered load lambda = root RPS x expected visits.

The waiting-time distribution of M/M/k is exactly

    P(W > t) = C(k, a) * exp(-(k*mu - lambda) * t)

with ``C`` the Erlang-C delay probability and a = lambda/mu, so sampling a
wait is a coin flip + one exponential draw — fully vectorized over
(request, hop).  Closed forms below double as the oracle for golden tests
(SURVEY.md §4: validate simulated p50/p99 against M/M/1).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


def erlang_b(a: jax.Array, k_max: int) -> jax.Array:
    """Erlang-B blocking probability B(j, a) for j = 1..k_max.

    Uses the stable recursion B(j) = a*B(j-1) / (j + a*B(j-1)), B(0) = 1.
    Returns shape (k_max, *a.shape); row j-1 holds B(j, a).
    """
    a = jnp.asarray(a, jnp.float32)

    def body(b, j):
        b = a * b / (j + a * b)
        return b, b

    _, rows = jax.lax.scan(
        body, jnp.ones_like(a), jnp.arange(1, k_max + 1, dtype=jnp.float32)
    )
    return rows


class QueueParams(NamedTuple):
    """Per-station sampling parameters (all shaped like ``replicas``)."""

    p_wait: jax.Array       # Erlang-C delay probability C(k, a)
    wait_rate: jax.Array    # k*mu - lambda: rate of the conditional wait
    utilization: jax.Array  # rho = lambda / (k*mu)
    unstable: jax.Array     # bool: offered load >= capacity


# Stations at/over capacity have no stationary distribution; we pin them
# just under saturation so the sim stays finite and flag them instead
# (the reference analogue: runs with >10% errors are discarded by
# perf/benchmark/runner/fortio.py:175-177, and overload shows up as errors).
_MAX_RHO = 0.9999


def mmk_params(
    arrival_rate: jax.Array,
    service_rate: jax.Array,
    replicas: jax.Array,
    k_max: int,
) -> QueueParams:
    """Compute Erlang-C sampling parameters for each station.

    ``arrival_rate``: lambda per station; ``service_rate``: mu per server;
    ``replicas``: integer k per station; ``k_max``: static max k (sets the
    recursion length).
    """
    lam = jnp.asarray(arrival_rate, jnp.float32)
    mu = jnp.asarray(service_rate, jnp.float32)
    k = jnp.asarray(replicas, jnp.int32)
    kf = k.astype(jnp.float32)

    rho_raw = lam / (kf * mu)
    unstable = rho_raw >= 1.0
    rho = jnp.minimum(rho_raw, _MAX_RHO)
    a = rho * kf  # effective (possibly clamped) offered load in erlangs

    b_rows = erlang_b(a, k_max)                 # (k_max, S)
    b_k = jnp.take_along_axis(b_rows, (k - 1)[None, ...], axis=0)[0]
    p_wait = b_k / (1.0 - rho * (1.0 - b_k))
    wait_rate = kf * mu * (1.0 - rho)
    return QueueParams(
        p_wait=p_wait,
        wait_rate=wait_rate,
        utilization=rho_raw,
        unstable=unstable,
    )


def sample_wait(
    params: QueueParams,
    uniform: jax.Array,
    exponential: jax.Array,
) -> jax.Array:
    """Draw waiting times: coin ``uniform`` vs p_wait, scaled ``exponential``.

    ``uniform`` ~ U[0,1) and ``exponential`` ~ Exp(1) must broadcast with
    the station parameters (typically (N, H) vs per-hop-gathered params).
    """
    wait = exponential / params.wait_rate
    return jnp.where(uniform < params.p_wait, wait, 0.0)


def sample_wait_conditional(
    p_wait: jax.Array,
    wait_rate: jax.Array,
    uniform: jax.Array,
) -> jax.Array:
    """Single-tensor wait draw via the conditional-uniform trick.

    Given U ~ U[0,1), conditional on U < p the ratio U/p is again U[0,1),
    so one uniform yields both the Erlang-C delay coin and the conditional
    Exp(wait_rate) wait — halving the RNG tensors the engine materializes.
    Distributionally identical to :func:`sample_wait`.
    """
    ratio = uniform / jnp.maximum(p_wait, 1e-30)
    # floor must stay in f32 normal range: subnormals (e.g. 1e-38) are
    # flushed to zero on TPU/CPU XLA, which would let u == 0 produce inf
    return jnp.where(
        uniform < p_wait,
        -jnp.log(jnp.maximum(ratio, 1e-20)) / wait_rate,
        0.0,
    )


# -- closed forms (test oracles) ------------------------------------------


def mm1_sojourn_quantile(q, arrival_rate, service_rate):
    """M/M/1 sojourn time quantile: T ~ Exp(mu - lambda)."""
    return -jnp.log1p(-jnp.asarray(q)) / (service_rate - arrival_rate)


def mmk_mean_wait(arrival_rate, service_rate, replicas, k_max):
    """Mean M/M/k waiting time: C(k, a) / (k*mu - lambda)."""
    p = mmk_params(arrival_rate, service_rate, replicas, k_max)
    return p.p_wait / p.wait_rate
