"""Python driver for the exact DES fidelity oracle (native/des_oracle.cpp).

The oracle simulates the same physical system the analytic engine models —
FIFO k-replica stations, the reference executor's script semantics
(isotope/service/pkg/srv/executable.go:43-179), open/closed-loop load —
by exact event-driven simulation with **no** independence or stationarity
assumptions.  It is the ground truth for the north star's fidelity axis:
the engine's p50/p99 must track the oracle's (see tests/test_oracle.py and
ORACLE.md for the measured error envelope).

Slow by design relative to the TPU engine (one event at a time on the
host CPU), but fast in absolute terms (~10M events/s), so million-request
validation runs finish in seconds.
"""
from __future__ import annotations

import ctypes
import dataclasses
import math
from typing import Optional, Sequence, Tuple

import numpy as np

from isotope_tpu.compiler.compile import _lower_script
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.native import load_library
from isotope_tpu.sim.config import (
    CLOSED_LOOP,
    OPEN_LOOP,
    SERVICE_TIME_DETERMINISTIC,
    SERVICE_TIME_EXPONENTIAL,
    SERVICE_TIME_LOGNORMAL,
    SERVICE_TIME_PARETO,
    ChaosEvent,
    LoadModel,
    SimParams,
)

_ST_KIND = {
    SERVICE_TIME_EXPONENTIAL: 0,
    SERVICE_TIME_DETERMINISTIC: 1,
    SERVICE_TIME_LOGNORMAL: 2,
    SERVICE_TIME_PARETO: 3,
}

_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _bind():
    lib = load_library("des_oracle")
    fn = lib.des_run
    fn.restype = ctypes.c_int
    fn.argtypes = [
        ctypes.c_int32, _i32p, _f64p, _f64p,                 # services
        _i32p, _f64p, _i32p,                                 # script offsets
        ctypes.c_int32, ctypes.c_int32,                      # totals
        _i32p, _f64p, _f64p, _f64p, _i32p, _f64p, _f64p,     # calls
        ctypes.c_int32,                                      # entry
        ctypes.c_double, ctypes.c_double,                    # network
        ctypes.c_int32, ctypes.c_double, ctypes.c_double,    # service time
        ctypes.c_int32, _i32p, _f64p, _f64p, _i32p, _u8p,    # chaos
        ctypes.c_int32, ctypes.c_double, ctypes.c_int32,     # load
        ctypes.c_double,                                     # pace jitter
        ctypes.c_int64, ctypes.c_uint64,                     # n, seed
        _f64p, _f64p, _u8p, _f64p, _f64p,                    # outputs
        ctypes.POINTER(ctypes.c_int64),
    ]
    return fn


@dataclasses.dataclass(frozen=True)
class OracleResults:
    """Per-request ground truth from one oracle run."""

    client_start: np.ndarray    # (N,) send times
    client_latency: np.ndarray  # (N,) client-observed round trips
    client_error: np.ndarray    # (N,) bool
    busy_time: np.ndarray       # (S,) total CPU-seconds served per service
    arrivals: np.ndarray        # (S,) hop arrivals per service
    hop_events: int             # executed hops

    @property
    def client_end(self) -> np.ndarray:
        return self.client_start + self.client_latency

    def quantiles_s(self, qs=(0.5, 0.75, 0.9, 0.99, 0.999)) -> np.ndarray:
        return np.quantile(self.client_latency, qs)

    def steady_quantiles_s(
        self, qs=(0.5, 0.99), warmup_s: float = 0.0
    ) -> np.ndarray:
        """Quantiles over requests arriving after ``warmup_s`` — the
        oracle starts empty, so early requests see an underloaded system
        while the analytic engine samples the stationary law."""
        mask = self.client_start >= warmup_s
        return np.quantile(self.client_latency[mask], qs)

    def utilization(self, duration_s: float, replicas: np.ndarray):
        return self.busy_time / (np.asarray(replicas) * duration_s)


class OracleSimulator:
    """Lowers a ServiceGraph once; runs the native DES per load."""

    def __init__(
        self,
        graph: ServiceGraph,
        params: SimParams = SimParams(),
        chaos: Sequence[ChaosEvent] = (),
        entry: Optional[str] = None,
    ):
        self.graph = graph
        self.params = params
        if params.network.entry_extra_latency_s:
            # des_oracle.cpp models a uniform per-edge network; the
            # ingress gateway's entry-edge tax is engine-only for now
            raise ValueError(
                "the DES oracle does not model entry_extra_latency_s "
                "(ingress gateway environments); compare against an "
                "environment without a gateway"
            )
        names = tuple(s.name for s in graph.services)
        self.names = names
        idx = {n: i for i, n in enumerate(names)}
        if entry is None:
            eps = graph.entrypoints()
            if not eps:
                raise ValueError("service graph has no entrypoint")
            self._entry = idx[eps[0].name]
        else:
            self._entry = idx[entry]

        self.replicas = np.asarray(
            [max(1, s.num_replicas) for s in graph.services], np.int32
        )
        self._err = np.asarray(
            [float(s.error_rate) for s in graph.services], np.float64
        )
        self._resp = np.asarray(
            [float(int(s.response_size)) for s in graph.services], np.float64
        )

        # cross-cluster edge class (NetworkModel cross_cluster_*): a call
        # whose caller and callee have different ``cluster`` fields pays
        # the gateway extra and rides the cross bandwidth
        clusters = [getattr(s, "cluster", "") for s in graph.services]
        net = params.network
        cross_bps = net.cross_cluster_bytes_per_second or 0.0

        svc_step_off = [0]
        step_base: list = []
        step_call_off = [0]
        ct, cp, cs, cto, ca, cex, cbp = [], [], [], [], [], [], []
        for si, s in enumerate(graph.services):
            for step in _lower_script(s.script, idx):
                step_base.append(step.base)
                for call in step.calls:
                    ct.append(call.target)
                    cp.append(call.send_prob)
                    cs.append(call.size)
                    cto.append(
                        call.timeout if math.isfinite(call.timeout)
                        else math.inf
                    )
                    ca.append(call.attempts)
                    cross = clusters[si] != clusters[call.target]
                    cex.append(net.cross_cluster_latency_s if cross else 0.0)
                    cbp.append(cross_bps if cross else 0.0)
                step_call_off.append(len(ct))
            svc_step_off.append(len(step_base))
        self._svc_step_off = np.asarray(svc_step_off, np.int32)
        self._step_base = np.asarray(step_base, np.float64)
        self._step_call_off = np.asarray(step_call_off, np.int32)
        self._call_target = np.asarray(ct, np.int32)
        self._call_prob = np.asarray(cp, np.float64)
        self._call_size = np.asarray(cs, np.float64)
        self._call_timeout = np.asarray(cto, np.float64)
        self._call_attempts = np.asarray(ca, np.int32)
        self._call_extra = np.asarray(cex, np.float64)
        self._call_bps = np.asarray(cbp, np.float64)

        self._chaos_svc = np.asarray(
            [idx[ev.service] for ev in chaos], np.int32
        )
        self._chaos_start = np.asarray(
            [ev.start_s for ev in chaos], np.float64
        )
        self._chaos_end = np.asarray([ev.end_s for ev in chaos], np.float64)
        self._chaos_down = np.asarray(
            [-1 if ev.replicas_down is None else ev.replicas_down
             for ev in chaos],
            np.int32,
        )
        self._chaos_drain = np.asarray(
            [bool(ev.drain) for ev in chaos], np.uint8
        )
        self._fn = _bind()

    def run(
        self,
        load: LoadModel,
        num_requests: int,
        seed: int = 0,
        pace_jitter: float = 0.1,
    ) -> OracleResults:
        """``pace_jitter`` models fortio's always-on ``-jitter`` flag
        (perf/benchmark/runner/runner.py:255-268): each closed-loop pace
        gap is perturbed by +/-10% uniform, and paced connections start
        phase-staggered — the steady state of jittered periodic workers."""
        n = int(num_requests)
        S = len(self.names)
        out_start = np.empty(n, np.float64)
        out_lat = np.empty(n, np.float64)
        out_err = np.empty(n, np.uint8)
        out_busy = np.empty(S, np.float64)
        out_arr = np.empty(S, np.float64)
        out_hops = ctypes.c_int64(0)
        if load.kind == OPEN_LOOP:
            kind, qps, conns = 0, float(load.qps), 1
        elif load.kind == CLOSED_LOOP:
            kind = 1
            qps = float(load.qps) if load.qps is not None else 0.0
            conns = load.connections
        else:  # pragma: no cover - LoadModel validates
            raise ValueError(load.kind)
        net = self.params.network
        rc = self._fn(
            S, self.replicas, self._err, self._resp,
            self._svc_step_off, self._step_base, self._step_call_off,
            len(self._step_base), len(self._call_target),
            self._call_target, self._call_prob, self._call_size,
            self._call_timeout, self._call_attempts, self._call_extra,
            self._call_bps, self._entry,
            float(net.base_latency_s), float(net.bytes_per_second),
            _ST_KIND[self.params.service_time],
            float(self.params.cpu_time_s),
            float(self.params.service_time_param),
            len(self._chaos_svc), self._chaos_svc, self._chaos_start,
            self._chaos_end, self._chaos_down, self._chaos_drain,
            kind, qps, conns, float(pace_jitter), n, seed,
            out_start, out_lat, out_err, out_busy, out_arr,
            ctypes.byref(out_hops),
        )
        if rc != 0:
            raise RuntimeError(f"des_run failed with code {rc}")
        return OracleResults(
            client_start=out_start,
            client_latency=out_lat,
            client_error=out_err.astype(bool),
            busy_time=out_busy,
            arrivals=out_arr,
            hop_events=int(out_hops.value),
        )


def oracle_quantiles(
    yaml_text: str,
    load: LoadModel,
    num_requests: int,
    qs: Tuple[float, ...] = (0.5, 0.99),
    params: SimParams = SimParams(),
    seed: int = 0,
    warmup_s: float = 0.0,
) -> np.ndarray:
    """One-shot convenience used by the fidelity tests."""
    sim = OracleSimulator(ServiceGraph.from_yaml(yaml_text), params)
    res = sim.run(load, num_requests, seed)
    return res.steady_quantiles_s(qs, warmup_s)
