"""Bucketed ``lax.scan`` executor for the depth-level sweeps.

The engine's unrolled data plane traces one tensor-program body per
depth level (engine._simulate_core); this module is the scan twin: for
a bucket of consecutive levels (compiler/buckets.py) the per-level
constants are padded to the bucket bounds, stacked along a leading
level axis, and each sweep (upward latency/outcome, downward sent
propagation, downward start times) becomes ONE ``lax.scan`` whose body
is traced once — trace/HLO size O(buckets) instead of O(depth).

Equivalence contract: for every value a request can observe, the scan
body performs the *same floating-point operations in the same order* as
the unrolled general path, with padding lanes contributing exact zeros
(additions), exact ``False`` (boolean algebra), or scatter identities
(max with 0 on non-negative data, min with the step bound).  The
specialized unrolled fast paths (``ident_attempts``, ``uniform_calls``)
are algebraic no-op reductions of the general path, so results are
bit-identical on CPU — tests/test_levelscan.py asserts exactly that.
Levels the engine runs through the sparse call-slot encoding keep their
unrolled specialized path (they are never placed in a bucket).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from isotope_tpu.compiler.buckets import ScanBucketPlan


def call_outcome(t, timeout, down_child):
    """(transport_failure, duration) of one call attempt.

    ``t`` is the attempt's would-be round trip; a finite ``timeout``
    clamps it and fails the call past it (executable.go's http client
    timeout); a down callee (``down_child``) transport-fails at ~zero
    cost — the connection is refused, nothing runs.  ``None`` inputs
    mean the failure mode is statically impossible, and a ``None``
    transport result means no transport failure can occur at all.

    Shared by BOTH executors (the unrolled path imports it as
    ``engine._call_outcome``): the scan twin's bit-for-bit equivalence
    contract requires these ops to stay in exact lockstep.
    """
    transport = None
    dur = t
    if timeout is not None:
        transport = t > timeout
        dur = jnp.minimum(t, timeout)
    if down_child is not None:
        transport = (
            down_child if transport is None else (down_child | transport)
        )
        dur = jnp.where(down_child, 0.0, dur)
    return transport, dur


class SweepCtx(NamedTuple):
    """Per-run tensors the sweep bodies close over.

    ``err_coin`` / ``u_send`` / ``down`` / ``tax`` / ``churn_w`` are
    ``None`` exactly when the engine statically knows the feature is
    off — the scan bodies then emit no ops for it, mirroring the
    unrolled path's ``None``-sentinel specialization.
    """

    n: int
    wait: jax.Array                  # (N, H)
    svc_time: jax.Array              # (N, H)
    err_coin: Optional[jax.Array]    # (N, H) bool
    u_send: Optional[jax.Array]      # (N, H) f32
    down: Optional[jax.Array]        # (N, H) bool
    tax: Optional[jax.Array]         # (N,) f32
    churn_w: Optional[jax.Array]     # (N, E+1) f32
    track_err: bool                  # any hop can 500 / transport-fail
    # serve the per-step census join from the fused Pallas kernel
    # (native/census_pallas.py) instead of the XLA op chain
    pallas_census: bool = False
    # retry-budget gate (sim/policies.py): attempt >= 1 runs only when
    # its budget coin admits it.  None exactly when no budget can
    # throttle (the byte-identical default) — with it set, protected
    # runs ride the scan buckets too (the PR 6 fast path; previously
    # the gate lived in the unrolled attempt loop only and forced
    # plan_segments(enabled=False) under policies)
    retry_coin: Optional[jax.Array] = None  # (N, H) bool


@dataclasses.dataclass(frozen=True)
class ScanBucket:
    """Stacked, padded device constants for one scan segment."""

    plan: ScanBucketPlan
    sizes: Tuple[int, ...]    # real level sizes d0..d1
    child_size: int           # size of level d1+1 (the carry seed)
    span0: int                # hop offset of level d0
    span1: int                # end of level d1+1's hop slice
    xs: Dict[str, jax.Array]  # stacked (Lb, ...) constants, depth order
    has_churn: bool
    # static structure flags, mirroring the unrolled path's None-
    # sentinel specializations (engine._Level): single-attempt buckets
    # skip the retry bookkeeping (att_off is exactly 0, call k's only
    # child is child k), timeout-free buckets skip the transport-
    # failure machinery entirely (no call can fail in transit unless
    # chaos is active)
    single_attempt: bool = False
    any_finite_timeout: bool = True

    @property
    def num_hops(self) -> int:
        return int(sum(self.sizes))

    @property
    def num_levels(self) -> int:
        return len(self.sizes)


def build_bucket(
    plan: ScanBucketPlan,
    metas: List[dict],
    num_churn: int,
) -> ScanBucket:
    """Stack levels ``plan.d0..plan.d1`` into padded scan constants.

    ``metas`` holds one host-side dict per depth level (engine builds
    them while lowering); padding conventions (see module docstring):
    child lanes pad to index 0 / value 0, call lanes pad to slot 0 with
    +inf timeouts and all-False attempt validity, and the attempt table
    remaps each level's local dummy column (its child count) to the
    shared bucket dummy column ``B``.
    """
    B, P = plan.bound_hops, plan.bound_steps
    K, A = plan.bound_calls, plan.bound_attempts
    lvls = metas[plan.d0:plan.d1 + 1]
    child_meta = metas[plan.d1 + 1]
    span0 = int(lvls[0]["offset"])
    span1 = int(child_meta["offset"]) + int(child_meta["size"])

    def padv(a, width, value=0, dtype=None):
        a = np.asarray(a)
        out = np.full((width,), value, dtype or a.dtype)
        out[: len(a)] = a
        return out

    stack: Dict[str, List[np.ndarray]] = {k: [] for k in (
        "loff", "choff", "step_mask", "step_base", "cpl", "cstep",
        "crtt", "cnet", "cprob", "centry", "child_seg", "call_seg",
        "call_hop", "call_step", "call_timeout", "att_child", "att_valid",
    )}
    for li, m in enumerate(lvls):
        size, c, k = int(m["size"]), int(m["C"]), int(m["K"])
        nxt = metas[plan.d0 + li + 1]
        stack["loff"].append(np.int32(int(m["offset"]) - span0))
        stack["choff"].append(np.int32(int(nxt["offset"]) - span0))
        sm = np.zeros((B, P), np.float32)
        sm[:size, : m["pmax"]] = m["step_mask"]
        stack["step_mask"].append(sm)
        sb = np.zeros((B, P), np.float32)
        sb[:size, : m["pmax"]] = m["step_base"]
        stack["step_base"].append(sb)
        cpl = padv(m["parent_local"], B).astype(np.int32)
        cst = padv(m["child_step"], B).astype(np.int32)
        stack["cpl"].append(cpl)
        stack["cstep"].append(cst)
        stack["crtt"].append(padv(m["child_rtt"], B).astype(np.float32))
        stack["cnet"].append(
            padv(m["child_net_out"], B).astype(np.float32)
        )
        stack["cprob"].append(
            padv(m["child_send_prob"], B).astype(np.float32)
        )
        if num_churn:
            stack["centry"].append(
                padv(m["child_churn_entry"], B, value=num_churn)
                .astype(np.int32)
            )
        stack["child_seg"].append((cpl * P + cst).astype(np.int32))
        call_local = padv(m["call_local"], K).astype(np.int32)
        call_step = padv(m["call_step"], K).astype(np.int32)
        stack["call_hop"].append(call_local)
        stack["call_step"].append(call_step)
        stack["call_seg"].append(
            (call_local * P + call_step).astype(np.int32)
        )
        stack["call_timeout"].append(
            padv(m["call_timeout"], K, value=np.inf, dtype=np.float32)
        )
        att_c = np.full((A, K), B, np.int32)
        att_v = np.zeros((A, K), bool)
        a_l, k_l = m["att_child"].shape
        att_c[:a_l, :k_l] = np.where(m["att_child"] == c, B,
                                     m["att_child"])
        att_v[:a_l, :k_l] = m["att_valid"]
        stack["att_child"].append(att_c)
        stack["att_valid"].append(att_v)
    if not num_churn:
        del stack["centry"]
    xs = {k: jnp.asarray(np.stack(v)) for k, v in stack.items()}
    return ScanBucket(
        plan=plan,
        sizes=tuple(int(m["size"]) for m in lvls),
        child_size=int(child_meta["size"]),
        span0=span0,
        span1=span1,
        xs=xs,
        has_churn=bool(num_churn),
        single_attempt=A == 1,
        any_finite_timeout=any(
            bool(np.isfinite(np.asarray(m["call_timeout"])).any())
            for m in lvls
        ),
    )


# ---------------------------------------------------------------------------
# sweep helpers


def pad_cols(x: jax.Array, width: int) -> jax.Array:
    """Pad the trailing (hop) axis with zeros/False up to ``width``."""
    if x.shape[-1] == width:
        return x
    return jnp.pad(x, ((0, 0), (0, width - x.shape[-1])))


def segment_slice(arr: Optional[jax.Array], b: ScanBucket
                  ) -> Optional[jax.Array]:
    """Static (N, span+B) window of a global (N, H) tensor.

    The trailing ``B`` zero columns make every in-scan
    ``dynamic_slice`` of width ``B`` in-bounds without clamping.
    """
    if arr is None:
        return None
    return jnp.pad(
        arr[:, b.span0:b.span1], ((0, 0), (0, b.plan.bound_hops))
    )


def _dslice(seg: jax.Array, start: jax.Array, width: int) -> jax.Array:
    return jax.lax.dynamic_slice_in_dim(seg, start, width, axis=1)


def gather_levels(stacked: jax.Array, sizes: Tuple[int, ...]) -> jax.Array:
    """(Lb, N, B) stacked per-level values -> (N, sum(sizes)) hop order."""
    L, n, B = stacked.shape
    cols = np.concatenate(
        [l * B + np.arange(s) for l, s in enumerate(sizes)]
    )
    return jnp.moveaxis(stacked, 0, 1).reshape(n, L * B)[:, cols]


# ---------------------------------------------------------------------------
# the three sweeps


def up_sweep(
    ctx: SweepCtx,
    b: ScanBucket,
    lat_init: jax.Array,
    err_init: Optional[jax.Array],
) -> Dict[str, jax.Array]:
    """Upward (deepest-first) latency/outcome sweep over one bucket.

    ``lat_init`` / ``err_init`` are level ``d1+1``'s outputs padded to
    the bucket's hop bound.  Returns per-level stacked ys (depth
    order): ``lat``, ``fail``, ``used``, ``off`` and — when the run
    tracks errors — ``err``.
    """
    n, B = ctx.n, b.plan.bound_hops
    P, A = b.plan.bound_steps, b.plan.bound_attempts
    track_err = ctx.track_err
    census_mod = None
    if ctx.pallas_census:
        from isotope_tpu.native import census_pallas

        if census_pallas.supported(B, P):
            census_mod = census_pallas
    # static specializations, mirroring the unrolled path's sentinels:
    # no call in the bucket can transport-fail unless a finite timeout
    # or a chaos outage exists, and a single-attempt bucket's retry
    # bookkeeping (att_off, the attempt loop carry) is exactly zero
    transportable = b.any_finite_timeout or ctx.down is not None
    track_used = (not b.single_attempt) or ctx.u_send is not None
    seg_wait = segment_slice(ctx.wait, b)
    seg_svc = segment_slice(ctx.svc_time, b)
    seg_err = segment_slice(ctx.err_coin, b)
    seg_send = segment_slice(ctx.u_send, b)
    seg_down = segment_slice(ctx.down, b)
    # budget gate only matters past attempt 0 — single-attempt buckets
    # never consult it (their retry fan is statically empty)
    seg_retry = (
        segment_slice(ctx.retry_coin, b)
        if not b.single_attempt
        else None
    )
    churn_w = ctx.churn_w
    tax = ctx.tax

    def pad1(a):
        return jnp.pad(a, ((0, 0), (0, 1)))

    def outcome(t, x, dc):
        # padded call slots carry +inf timeouts — exact no-ops
        # (min(t, inf) == t, t > inf == False) on the real lanes
        return call_outcome(
            t, x["call_timeout"] if b.any_finite_timeout else None, dc
        )

    def body(carry, x):
        lat_c, err_c = carry
        wait_sl = _dslice(seg_wait, x["loff"], B)
        svc_sl = _dslice(seg_svc, x["loff"], B)
        err_sl = (
            _dslice(seg_err, x["loff"], B) if seg_err is not None else None
        )
        lat_child = pad1(lat_c)                       # (N, B+1)
        err_child = pad1(err_c) if err_c is not None else None
        down_child = (
            pad1(_dslice(seg_down, x["choff"], B))
            if seg_down is not None
            else None
        )
        rtt_child = jnp.pad(x["crtt"], (0, 1))

        a0 = x["att_child"][0]                        # (K,) in [0, B]
        if seg_send is not None:
            prob = jnp.pad(x["cprob"], (0, 1))[a0]
            if churn_w is not None:
                centry = jnp.pad(
                    x["centry"], (0, 1),
                    constant_values=churn_w.shape[1] - 1,
                )[a0]
                prob = prob * churn_w[:, centry]
            coin = pad1(_dslice(seg_send, x["choff"], B))[:, a0] < prob
        else:
            coin = None
        used = None
        if b.single_attempt:
            # call k's only child is child k: elementwise, no loop state
            t = rtt_child[a0] + lat_child[:, a0]
            if tax is not None:
                t = t + 2.0 * tax[:, None]
            transport_a, dur_a = outcome(
                t, x, down_child[:, a0] if down_child is not None else None
            )
            if coin is not None:
                dur_call = jnp.where(coin, dur_a, 0.0)
                final_transport = (
                    coin & transport_a if transport_a is not None else None
                )
                used = (
                    jnp.zeros((n, B + 1), bool).at[:, a0].set(coin)[:, :B]
                )
            else:
                dur_call = dur_a
                final_transport = transport_a
            att_off = None
        else:
            coin_a = (
                coin
                if coin is not None
                else jnp.ones((n, a0.shape[0]), bool)
            )
            # retry-budget gate (sim/policies.py): the child slice's
            # budget coins, padded like down_child — the bucket dummy
            # column ``B`` is False (dead lane), matching the unrolled
            # path's dead pad column
            retry_gate = (
                pad1(_dslice(seg_retry, x["choff"], B))
                if seg_retry is not None
                else None
            )
            dur_call = jnp.zeros((n, a0.shape[0]))
            final_transport = (
                jnp.zeros((n, a0.shape[0]), bool) if transportable
                else None
            )
            used_b = jnp.zeros((n, B + 1), bool)
            att_off = jnp.zeros((n, B + 1))
            used_a = coin_a
            for a in range(A):
                idx = x["att_child"][a]
                valid = x["att_valid"][a]
                use = used_a & valid
                if retry_gate is not None and a > 0:
                    # a suppressed retry surfaces the PREVIOUS
                    # attempt's failure to the caller (Envoy budget
                    # semantics) — same op as the unrolled gate
                    use = use & retry_gate[:, idx]
                t = rtt_child[idx] + lat_child[:, idx]
                if tax is not None:
                    t = t + 2.0 * tax[:, None]
                transport_a, dur_a = outcome(
                    t, x,
                    down_child[:, idx] if down_child is not None else None,
                )
                failed_a = transport_a
                if err_child is not None:
                    failed_a = (
                        err_child[:, idx]
                        if failed_a is None
                        else failed_a | err_child[:, idx]
                    )
                att_off = att_off.at[:, idx].set(
                    jnp.where(use, dur_call, 0.0)
                )
                used_b = used_b.at[:, idx].set(use)
                dur_call = dur_call + jnp.where(use, dur_a, 0.0)
                if final_transport is not None:
                    final_transport = jnp.where(
                        use, transport_a, final_transport
                    )
                used_a = (
                    use & failed_a
                    if failed_a is not None
                    else jnp.zeros_like(use)
                )
            used = used_b[:, :B]
        # -- aggregate calls into (hop, step) slots; padded calls carry
        # dur 0 / transport False, so max-with-0 and min-with-P are
        # identities on the real lanes
        agg = (
            jnp.zeros((n, B * P))
            .at[:, x["call_seg"]]
            .max(dur_call)
            .reshape(n, B, P)
        )
        fail_step = None
        if final_transport is not None:
            fail_contrib = jnp.where(
                final_transport, x["call_step"], P
            ).astype(jnp.int32)
            fail_step = (
                jnp.full((n, B), P, jnp.int32)
                .at[:, x["call_hop"]]
                .min(fail_contrib)
            )
        if census_mod is not None:
            # fused census kernel: max + mask + fail/err truncation +
            # row-sum + exclusive prefix in one pass
            busy, prefix = census_mod.census(
                x["step_base"], x["step_mask"], agg, fail_step, err_sl,
            )
        else:
            step_dur = jnp.maximum(x["step_base"], agg) * x["step_mask"]
            if fail_step is not None:
                executed = (
                    jnp.arange(P, dtype=jnp.int32)
                    <= fail_step[:, :, None]
                )
                if err_sl is not None:
                    executed = executed & ~err_sl[:, :, None]
                step_dur = step_dur * executed
            elif err_sl is not None:
                step_dur = step_dur * ~err_sl[:, :, None]
            busy = step_dur.sum(-1)
            prefix = jnp.cumsum(step_dur, axis=-1) - step_dur
        lat = wait_sl + svc_sl + busy
        off = prefix.reshape(n, -1)[:, x["child_seg"]]
        if att_off is not None:
            off = off + used * att_off[:, :B]
        ys = {"lat": lat, "off": off}
        if fail_step is not None:
            ys["fail"] = fail_step
        if track_used and used is not None:
            ys["used"] = used
        if track_err:
            if err_sl is not None and fail_step is not None:
                err = err_sl | (fail_step < P)
            elif err_sl is not None:
                err = err_sl
            elif fail_step is not None:
                err = fail_step < P
            else:
                err = jnp.zeros((n, B), bool)
            ys["err"] = err
        else:
            err = None
        return (lat, err), ys

    (_, _), ys = jax.lax.scan(
        body, (lat_init, err_init if track_err else None), b.xs,
        reverse=True,
    )
    return ys


def sent_sweep(
    ctx: SweepCtx,
    b: ScanBucket,
    ys: Dict[str, jax.Array],
    sent_init: jax.Array,
    refused_init: Optional[jax.Array] = None,
):
    """Downward sent-propagation over one bucket.

    ``sent_init`` is level ``d0``'s sent mask padded to the bound.
    Returns ``(own, carry)``: the bucket's stacked per-level sent masks
    (levels d0..d1, depth order) and level ``d1+1``'s sent mask (real
    width) for the next segment.

    With ``refused_init`` (level ``d0``'s refused mask, padded — the
    rollout co-sim's would-send-but-target-down track) the sweep ALSO
    emits per-level refused masks and returns
    ``(own, refused_own, sent_carry, refused_carry)``.
    """
    B = b.plan.bound_hops
    seg_err = segment_slice(ctx.err_coin, b)
    seg_down = segment_slice(ctx.down, b)
    xs = {
        "loff": b.xs["loff"],
        "choff": b.xs["choff"],
        "cpl": b.xs["cpl"],
        "cstep": b.xs["cstep"],
    }
    if "fail" in ys:
        xs["fail"] = ys["fail"]
    if "used" in ys:
        xs["used"] = ys["used"]
    track_refused = refused_init is not None

    def body(sent_p, x):
        sent = sent_p[:, x["cpl"]]
        if seg_err is not None:
            err_sl = _dslice(seg_err, x["loff"], B)
            sent = sent & ~err_sl[:, x["cpl"]]
        if "fail" in x:
            sent = sent & (x["cstep"] <= x["fail"][:, x["cpl"]])
        if "used" in x:
            sent = sent & x["used"]
        if seg_down is not None:
            dmask = _dslice(seg_down, x["choff"], B)
            refused = sent & dmask
            sent = sent & ~dmask
        else:
            refused = jnp.zeros_like(sent)
        if track_refused:
            return sent, (sent, refused)
        return sent, sent

    if track_refused:
        _, (sent_next, refused_next) = jax.lax.scan(body, sent_init, xs)
    else:
        _, sent_next = jax.lax.scan(body, sent_init, xs)
    own = jnp.concatenate(
        [sent_init[None], sent_next[: b.num_levels - 1]], axis=0
    )
    if not track_refused:
        return own, sent_next[-1][:, : b.child_size]
    refused_own = jnp.concatenate(
        [refused_init[None], refused_next[: b.num_levels - 1]], axis=0
    )
    return (
        own,
        refused_own,
        sent_next[-1][:, : b.child_size],
        refused_next[-1][:, : b.child_size],
    )


def start_sweep(
    ctx: SweepCtx,
    b: ScanBucket,
    ys: Dict[str, jax.Array],
    start_init: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Downward absolute-start-time sweep over one bucket.

    Same carry convention as :func:`sent_sweep`.
    """
    B = b.plan.bound_hops
    seg_wait = segment_slice(ctx.wait, b)
    tax = ctx.tax
    xs = {
        "loff": b.xs["loff"],
        "cpl": b.xs["cpl"],
        "cnet": b.xs["cnet"],
        "off": ys["off"],
    }

    def body(start_p, x):
        wait_sl = _dslice(seg_wait, x["loff"], B)
        base = (start_p + wait_sl)[:, x["cpl"]]
        out_wire = x["cnet"]
        if tax is not None:
            out_wire = out_wire + tax[:, None]
        s = base + x["off"] + out_wire
        return s, s

    _, start_next = jax.lax.scan(body, start_init, xs)
    own = jnp.concatenate(
        [start_init[None], start_next[: b.num_levels - 1]], axis=0
    )
    return own, start_next[-1][:, : b.child_size]
