"""Reactive canary rollouts: closed-loop progressive delivery co-sim.

PR 9's policy layer made the simulator react to its own physics
(breakers, budgets, HPA), but deployments in the modeled mesh stayed
open-loop: ``churn`` traffic-shift weights are pure clocks that keep
shifting traffic onto a canary even as it burns error budget.  This
module closes that loop — the Istio/Argo-Rollouts progressive-delivery
pattern as scan-carry arithmetic:

- a topology ``rollouts:`` block declares, per service, a two-version
  (baseline/canary) deployment: a **step schedule** of traffic weights
  (e.g. 1% -> 5% -> 25% -> 100%), a **bake time** per step, **SLO
  gates** (canary error share and a mean-latency proxy vs the baseline
  arm, with min-sample guards), and a **rollback policy** (cooldown +
  bounded retries);
- canary physics are real, not cosmetic: the canary arm carries its own
  ``error_rate`` / ``cpu_time`` / ``replicas`` overrides, a request hop
  routes to the canary with the CURRENT weight (a per-hop version coin),
  the canary arm is its own M/M/k station fed the split-off load (the
  same admission-weight multiplication the breaker shed uses), and a
  chaos kill on a rolled-out service takes the CANARY replicas first
  (the newest pods are the ones a bad push crashes);
- the controller observes a per-version observation channel — the PR 7
  flight-recorder idiom extended from (S, W) to (S, 2, W): per-service,
  per-ARM, per-window arrivals / errors / latency sums / executed hops
  (the latency means divide by EXECUTED hops only, so chaos-refused
  calls feed the error gate without diluting the latency gate) — and
  advances window-by-window in the block-scan carry: it **PROMOTES**
  to the next step when a bake window passes its gates, **HOLDS** while
  either arm lacks ``min_samples``, and **ROLLS BACK** (weight -> 0,
  cooldown, bounded retry count) the moment a gate trips.

Control-loop discretization matches sim/policies.py exactly: window-
granular observation, block-granular actuation (one-block lag — the
metric-scrape lag a real rollout controller has).  The law is pure
elementwise f32 carry arithmetic over (S,) state vectors, so it stays
on the differentiable-planner path (DrJAX idiom, PAPERS.md) and shards
advance the identical trajectory from psum-merged window signals,
bit-equal to the emulated twin.

Everything is off by default: a Simulator built without rollout tables
traces byte-identical programs (pinned, like ``policies`` / ``timeline``).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from isotope_tpu.models.decode import (
    duration_s as _dur,
    field as _field,
    fraction as _frac,
    integer as _int,
    number as _num,
)
from isotope_tpu.models.errors import config_path


# -- rollout configuration (the topology YAML `rollouts:` block) -----------


_INF = float("inf")


@dataclasses.dataclass(frozen=True)
class RolloutGates:
    """The per-step SLO gates a bake window must pass to promote.

    The error gate compares the canary arm's ERROR SHARE against the
    baseline arm's over the current step's accumulated samples:
    it trips when ``share_canary > max_error_ratio * share_baseline +
    error_slack`` (the additive slack keeps a zero-error baseline from
    tripping on one noisy canary 500) or when the absolute
    ``max_error_share`` is exceeded.  The latency gate compares the
    arms' mean-latency proxies (windowed latency sums / counts):
    ``mean_canary > max_latency_ratio * mean_baseline`` trips it.
    Gates only evaluate once BOTH arms hold ``min_samples`` executed
    hops — the min-sample guard that makes a 1% step statistically
    honest.  ``inf`` disables a gate.
    """

    max_error_ratio: float = 2.0
    error_slack: float = 0.01
    max_error_share: float = _INF
    max_latency_ratio: float = 2.0
    min_samples: float = 50.0

    _FIELDS = {
        "max_error_ratio", "error_slack", "max_error_share",
        "max_latency_ratio", "min_samples",
    }

    @classmethod
    def decode(cls, value: dict) -> "RolloutGates":
        if not isinstance(value, dict):
            raise ValueError(f"gates must be a mapping: {value!r}")
        unknown = set(value) - cls._FIELDS
        if unknown:
            raise ValueError(f"unknown gates fields: {sorted(unknown)}")

        field = functools.partial(_field, value)

        out = cls(
            max_error_ratio=field("max_error_ratio", _num, 2.0),
            error_slack=field("error_slack", _frac, 0.01),
            max_error_share=field("max_error_share", _frac, _INF),
            max_latency_ratio=field("max_latency_ratio", _num, 2.0),
            min_samples=field("min_samples", _num, 50.0),
        )
        if out.max_error_ratio <= 0 or out.max_latency_ratio <= 0:
            raise ValueError("gate ratios must be positive")
        if out.min_samples < 1:
            with config_path("min_samples"):
                raise ValueError("min_samples must be >= 1")
        return out


@dataclasses.dataclass(frozen=True)
class RollbackPolicy:
    """What happens after a gate trips: the canary weight snaps to 0,
    the rollout cools down for ``cooldown_s`` sim seconds, and then
    restarts from step 0 — at most ``max_retries`` times (0 = one
    strike and the rollout stays reverted)."""

    cooldown_s: float = 30.0
    max_retries: int = 0

    _FIELDS = {"cooldown", "max_retries"}

    @classmethod
    def decode(cls, value: dict) -> "RollbackPolicy":
        if not isinstance(value, dict):
            raise ValueError(f"rollback must be a mapping: {value!r}")
        unknown = set(value) - cls._FIELDS
        if unknown:
            raise ValueError(
                f"unknown rollback fields: {sorted(unknown)}"
            )

        field = functools.partial(_field, value)

        out = cls(
            cooldown_s=field("cooldown", _dur, 30.0),
            max_retries=field("max_retries", _int, 0),
        )
        if out.cooldown_s <= 0:
            with config_path("cooldown"):
                raise ValueError("cooldown must be positive")
        if out.max_retries < 0:
            with config_path("max_retries"):
                raise ValueError("max_retries must be >= 0")
        return out


@dataclasses.dataclass(frozen=True)
class CanaryOverrides:
    """The canary arm's OWN physics — what makes a bad push bad.

    ``None`` inherits the baseline service's value.  ``replicas``
    defaults to 1 (a canary deployment is one pod until promoted)."""

    error_rate: Optional[float] = None
    cpu_time_s: Optional[float] = None
    replicas: int = 1

    _FIELDS = {"error_rate", "cpu_time", "replicas"}

    @classmethod
    def decode(cls, value: dict) -> "CanaryOverrides":
        if not isinstance(value, dict):
            raise ValueError(f"canary must be a mapping: {value!r}")
        unknown = set(value) - cls._FIELDS
        if unknown:
            raise ValueError(f"unknown canary fields: {sorted(unknown)}")

        field = functools.partial(_field, value)

        out = cls(
            error_rate=field("error_rate", _frac, None),
            cpu_time_s=field("cpu_time", _dur, None),
            replicas=field("replicas", _int, 1),
        )
        if out.cpu_time_s is not None and out.cpu_time_s <= 0:
            with config_path("cpu_time"):
                raise ValueError("cpu_time must be positive")
        if out.replicas < 1:
            with config_path("replicas"):
                raise ValueError("replicas must be >= 1")
        return out


@dataclasses.dataclass(frozen=True)
class ServiceRollout:
    """One service's progressive-delivery declaration.

    A rollout is ACTIVE only when it declares a non-empty ``steps``
    schedule; an entry carrying canary overrides but no steps never
    actuates (the vet linter flags it, VET-T018)."""

    steps: Tuple[float, ...] = ()
    bake_s: float = 30.0
    gates: RolloutGates = RolloutGates()
    rollback: RollbackPolicy = RollbackPolicy()
    canary: CanaryOverrides = CanaryOverrides()

    _FIELDS = {"steps", "bake", "gates", "rollback", "canary"}

    @property
    def active(self) -> bool:
        return len(self.steps) > 0

    @classmethod
    def decode(
        cls, value: dict, default: "ServiceRollout"
    ) -> "ServiceRollout":
        if value is None:
            value = {}
        if not isinstance(value, dict):
            raise ValueError(
                f"service rollout must be a mapping: {value!r}"
            )
        unknown = set(value) - cls._FIELDS
        if unknown:
            raise ValueError(f"unknown rollout fields: {sorted(unknown)}")

        def block(key, decode, fallback):
            if key not in value or value[key] is None:
                return fallback
            with config_path(key):
                return decode(value[key])

        steps = default.steps
        if "steps" in value and value["steps"] is not None:
            raw = value["steps"]
            if not isinstance(raw, (list, tuple)) or not raw:
                with config_path("steps"):
                    raise ValueError(
                        f"steps must be a non-empty list: {raw!r}"
                    )
            decoded = []
            for i, s in enumerate(raw):
                with config_path(f"steps[{i}]"):
                    w = _frac(s)
                    if not 0.0 < w <= 1.0:
                        raise ValueError(
                            f"step weight must lie in (0, 100%]: {s!r}"
                        )
                    decoded.append(w)
            steps = tuple(decoded)
        return cls(
            steps=steps,
            bake_s=block("bake", _dur, default.bake_s),
            gates=block("gates", RolloutGates.decode, default.gates),
            rollback=block(
                "rollback", RollbackPolicy.decode, default.rollback
            ),
            canary=block(
                "canary", CanaryOverrides.decode, default.canary
            ),
        )


@dataclasses.dataclass(frozen=True)
class RolloutSet:
    """The decoded ``rollouts:`` block of a topology YAML.

    Schema::

        rollouts:
          defaults:              # seeds bake/gates/rollback (no steps)
            bake: 10s
            gates: {min_samples: 100}
          worker:
            steps: [1%, 5%, 25%, 50%, 100%]
            rollback: {cooldown: 30s, max_retries: 1}
            canary: {error_rate: 0.3%, cpu_time: 90us, replicas: 2}

    ``defaults`` may not declare ``steps`` or ``canary`` — a schedule
    applying to EVERY service would silently canary the whole mesh."""

    per_service: Dict[str, ServiceRollout]
    defaults: ServiceRollout

    @classmethod
    def decode(cls, raw: dict, service_names) -> "RolloutSet":
        if not isinstance(raw, dict):
            raise ValueError(f"rollouts must be a mapping: {raw!r}")
        names = list(service_names)
        with config_path("rollouts"):
            raw_defaults = raw.get("defaults") or {}
            with config_path("defaults"):
                if not isinstance(raw_defaults, dict):
                    raise ValueError(
                        f"defaults must be a mapping: {raw_defaults!r}"
                    )
                banned = {"steps", "canary"} & set(raw_defaults)
                if banned:
                    raise ValueError(
                        f"rollout defaults may not declare "
                        f"{sorted(banned)} (a schedule applying to "
                        "every service would canary the whole mesh)"
                    )
                default = ServiceRollout.decode(
                    raw_defaults, ServiceRollout()
                )
            per: Dict[str, ServiceRollout] = {}
            for key, value in raw.items():
                if key == "defaults":
                    continue
                if key not in names:
                    raise ValueError(
                        f"rollouts target unknown service {key!r}"
                    )
                with config_path(key):
                    per[key] = ServiceRollout.decode(value, default)
        return cls(per_service=per, defaults=default)

    def for_service(self, name: str) -> ServiceRollout:
        return self.per_service.get(name, self.defaults)

    @property
    def empty(self) -> bool:
        return not any(r.active for r in self.per_service.values())


def lint_rollouts(
    raw: dict, service_names
) -> Tuple[Optional[RolloutSet], List[Tuple[str, str]]]:
    """Decode a raw ``rollouts:`` block tolerantly for the vet linter
    (the sim/policies.py ``lint_policies`` idiom): decode errors become
    findings instead of crashes."""
    try:
        return RolloutSet.decode(raw, service_names), []
    except ValueError as e:
        return None, [("decode", str(e))]


# -- dense per-service tables (compiler/compile.compile_rollouts) ----------


@dataclasses.dataclass(frozen=True)
class RolloutTables:
    """The ``rollouts:`` block lowered to dense per-service arrays in
    compiled service order — the device-constant form the engine's
    rollout scan consumes.  ``steps`` is right-padded with each row's
    final weight so a promoted-past-the-end index stays at 100%."""

    names: Tuple[str, ...]
    has_rollout: np.ndarray        # (S,) bool
    steps: np.ndarray              # (S, M) f64
    num_steps: np.ndarray          # (S,) i64 — 0 = inactive
    bake_s: np.ndarray             # (S,) f64
    cooldown_s: np.ndarray         # (S,) f64
    max_retries: np.ndarray        # (S,) f64
    err_ratio: np.ndarray          # (S,) f64, inf = off
    err_slack: np.ndarray          # (S,) f64
    err_share: np.ndarray          # (S,) f64, inf = off
    lat_ratio: np.ndarray          # (S,) f64, inf = off
    min_samples: np.ndarray        # (S,) f64
    canary_error_rate: np.ndarray  # (S,) f64 — baseline-substituted
    canary_cpu_s: np.ndarray       # (S,) f64 — nan = inherit cpu_time
    canary_replicas: np.ndarray    # (S,) i64

    @property
    def num_services(self) -> int:
        return len(self.names)

    @property
    def max_steps(self) -> int:
        return int(self.steps.shape[1])

    @property
    def any_error_override(self) -> bool:
        """True when any canary arm can 500 — the engine must draw the
        error coins (and track errors) even on error-free baselines."""
        return bool(
            (self.canary_error_rate[self.has_rollout] > 0.0).any()
        )

    @property
    def any_cpu_override(self) -> bool:
        return bool(np.isfinite(self.canary_cpu_s).any())

    @property
    def k_max(self) -> int:
        """Widest canary station (extends the Erlang recursion length
        next to the static/autoscaled maxima)."""
        if not self.has_rollout.any():
            return 1
        return int(self.canary_replicas[self.has_rollout].max())

    def signature(self) -> str:
        """Stable identity for executable-cache keys."""
        fields = dataclasses.fields(self)
        parts = [f"{self.names!r}"]
        for f in fields[1:]:
            parts.append(np.asarray(getattr(self, f.name)).tobytes().hex())
        return "rollouts:" + "|".join(parts)


def build_tables(rset: RolloutSet, services) -> RolloutTables:
    """Lower a decoded RolloutSet against a compiled ServiceTable."""
    names = tuple(services.names)
    S = len(names)
    M = max(
        [len(rset.for_service(n).steps) for n in names] + [1]
    )

    def arr(fill):
        return np.full(S, fill, np.float64)

    has = np.zeros(S, bool)
    steps = np.zeros((S, M), np.float64)
    num_steps = np.zeros(S, np.int64)
    bake = arr(30.0)
    cooldown = arr(30.0)
    retries = arr(0.0)
    err_ratio = arr(_INF)
    err_slack = arr(0.0)
    err_share = arr(_INF)
    lat_ratio = arr(_INF)
    min_samples = arr(1.0)
    can_err = np.asarray(services.error_rate, np.float64).copy()
    can_cpu = arr(np.nan)
    can_reps = np.ones(S, np.int64)
    for s, name in enumerate(names):
        r = rset.for_service(name)
        if not r.active:
            continue
        has[s] = True
        k = len(r.steps)
        steps[s, :k] = r.steps
        steps[s, k:] = r.steps[-1]
        num_steps[s] = k
        bake[s] = r.bake_s
        cooldown[s] = r.rollback.cooldown_s
        retries[s] = float(r.rollback.max_retries)
        g = r.gates
        err_ratio[s] = g.max_error_ratio
        err_slack[s] = g.error_slack
        err_share[s] = g.max_error_share
        lat_ratio[s] = g.max_latency_ratio
        min_samples[s] = g.min_samples
        if r.canary.error_rate is not None:
            can_err[s] = r.canary.error_rate
        if r.canary.cpu_time_s is not None:
            can_cpu[s] = r.canary.cpu_time_s
        can_reps[s] = r.canary.replicas
    return RolloutTables(
        names=names,
        has_rollout=has,
        steps=steps,
        num_steps=num_steps,
        bake_s=bake,
        cooldown_s=cooldown,
        max_retries=retries,
        err_ratio=err_ratio,
        err_slack=err_slack,
        err_share=err_share,
        lat_ratio=lat_ratio,
        min_samples=min_samples,
        canary_error_rate=can_err,
        canary_cpu_s=can_cpu,
        canary_replicas=can_reps,
    )


# -- device-side state / control law --------------------------------------

import jax  # noqa: E402  (host-only callers above never trace)
import jax.numpy as jnp  # noqa: E402


#: RolloutState.phase codes — pure f32 carry values
PHASE_ROLLING = 0.0   # a step is baking (or holding for samples)
PHASE_DONE = 1.0      # promoted through the whole schedule
PHASE_COOLDOWN = 2.0  # rolled back; retry pending after the cooldown
PHASE_FAILED = 3.0    # rolled back with retries exhausted (weight 0)

PHASE_NAMES = {0: "rolling", 1: "done", 2: "cooldown", 3: "failed"}


class DeviceTables(NamedTuple):
    """RolloutTables uploaded as f32 device constants."""

    has_rollout: jax.Array     # (S,) bool
    steps: jax.Array           # (S, M)
    num_steps: jax.Array       # (S,)
    bake_s: jax.Array
    cooldown_s: jax.Array
    max_retries: jax.Array
    err_ratio: jax.Array       # inf = off
    err_slack: jax.Array
    err_share: jax.Array       # inf = off
    lat_ratio: jax.Array       # inf = off
    min_samples: jax.Array


def device_tables(t: RolloutTables) -> DeviceTables:
    f = lambda x: jnp.asarray(x, jnp.float32)  # noqa: E731
    return DeviceTables(
        has_rollout=jnp.asarray(t.has_rollout),
        steps=f(t.steps),
        num_steps=f(t.num_steps),
        bake_s=f(t.bake_s),
        cooldown_s=f(t.cooldown_s),
        max_retries=f(t.max_retries),
        err_ratio=f(t.err_ratio),
        err_slack=f(t.err_slack),
        err_share=f(t.err_share),
        lat_ratio=f(t.lat_ratio),
        min_samples=f(t.min_samples),
    )


class RolloutState(NamedTuple):
    """Per-service rollout-controller state riding the block-scan carry."""

    phase: jax.Array        # (S,) f32 — PHASE_* code
    step: jax.Array         # (S,) f32 — current schedule index
    weight: jax.Array       # (S,) f32 — actuated canary traffic weight
    bake_t: jax.Array       # (S,) f32 — sim seconds into the step
    cooldown_t: jax.Array   # (S,) f32 — rollback cooldown remaining
    retries_left: jax.Array  # (S,) f32
    # per-arm observation accumulators over the CURRENT step
    cnt_b: jax.Array        # (S,) f32 — baseline arrivals (incl. refused)
    cnt_c: jax.Array        # (S,) f32 — canary arrivals (incl. refused)
    err_b: jax.Array        # (S,) f32
    err_c: jax.Array        # (S,) f32
    lat_b: jax.Array        # (S,) f32 — latency sums (proxy numerator)
    lat_c: jax.Array        # (S,) f32
    exe_b: jax.Array        # (S,) f32 — EXECUTED hops (latency denom)
    exe_c: jax.Array        # (S,) f32
    last_window: jax.Array  # scalar i32 — last processed window


class RolloutFx(NamedTuple):
    """The rollout state's effect on one block's physics (traced)."""

    weight: jax.Array  # (S,) f32 — canary admission weight in [0, 1]


class RolloutSummary(NamedTuple):
    """Per-window actuation series + per-version observation series.

    The weight/step/phase series hold the state at each window's END
    (after that window's control update); event series mark the window
    a promote/hold/rollback landed in.  Replicated across shards (every
    shard advances the identical trajectory from the psum-merged
    per-version signals), so the sharded merge TAKES it — the
    ``PolicySummary`` idiom.  ``ver_*`` are the (S, 2, W) per-arm
    window series (version 0 = baseline, 1 = canary), attached from
    the psum-merged observation accumulator after the scan."""

    window_s: jax.Array      # scalar f32
    weight: jax.Array        # (S, W) f32
    step: jax.Array          # (S, W) f32
    phase: jax.Array         # (S, W) f32 — PHASE_* codes
    promotions: jax.Array    # (S, W) f32 (0/1 events)
    holds: jax.Array         # (S, W) f32
    rollbacks: jax.Array     # (S, W) f32
    windows_done: jax.Array  # (W,) f32 (0/1)
    ver_arrivals: jax.Array  # (S, 2, W) f32 — executed hops per arm
    ver_errors: jax.Array    # (S, 2, W) f32
    ver_latency_s: jax.Array  # (S, 2, W) f32 — hop-latency sums

    @property
    def num_windows(self) -> int:
        return int(np.asarray(self.windows_done).shape[0])


def init_state(dt: DeviceTables) -> RolloutState:
    """The scan carry's initial rollout state: every active rollout
    starts at step 0's weight with its full retry budget."""
    S = dt.num_steps.shape[0]
    z = jnp.zeros(S, jnp.float32)
    return RolloutState(
        phase=z,
        step=z,
        weight=jnp.where(dt.has_rollout, dt.steps[:, 0], 0.0),
        bake_t=z,
        cooldown_t=z,
        retries_left=dt.max_retries,
        cnt_b=z, cnt_c=z, err_b=z, err_c=z, lat_b=z, lat_c=z,
        exe_b=z, exe_c=z,
        last_window=jnp.int32(-1),
    )


def effects(state: RolloutState) -> RolloutFx:
    """What the NEXT block's physics sees: the canary admission weight
    (0 for un-rolled-out services, and 0 during cooldown/failed)."""
    return RolloutFx(weight=state.weight)


def zeros_summary(spec, num_services: int) -> RolloutSummary:
    W = spec.num_windows
    S = num_services
    return RolloutSummary(
        window_s=jnp.float32(spec.window_s),
        weight=jnp.zeros((S, W)),
        step=jnp.zeros((S, W)),
        phase=jnp.zeros((S, W)),
        promotions=jnp.zeros((S, W)),
        holds=jnp.zeros((S, W)),
        rollbacks=jnp.zeros((S, W)),
        windows_done=jnp.zeros(W),
        ver_arrivals=jnp.zeros((S, 2, W)),
        ver_errors=jnp.zeros((S, 2, W)),
        ver_latency_s=jnp.zeros((S, 2, W)),
    )


def observe_block(res, spec) -> jax.Array:
    """(S, 2, W, 4) per-service, per-ARM window sums of one block —
    channels (arrived hops incl. refused, hop 500s, hop-latency sum,
    EXECUTED hops), binned by hop start.  The flight-recorder
    observation channel extended along the version axis
    (``SimResults.hop_canary`` is the per-hop version coin); additive
    across blocks and shards exactly like the recorder's series."""
    from isotope_tpu.metrics import timeline as timeline_mod

    if res.hop_canary is None:
        raise ValueError(
            "rollout observation needs SimResults.hop_canary (produced "
            "by rollout-actuated blocks)"
        )
    T = spec.num_windows * spec.window_s
    s_c = jnp.clip(res.hop_start, 0.0, T)
    exe_f = res.hop_sent.astype(jnp.float32)
    err_f = (res.hop_sent & res.hop_error).astype(jnp.float32)
    lat_f = exe_f * res.hop_latency
    sent_f = exe_f
    if res.hop_refused is not None:
        # a would-send hop whose target arm was chaos-downed transport-
        # failed: the gate must see a killed canary's refused calls as
        # canary arrivals + errors, but they carry NO latency sample —
        # the latency mean divides by the executed-only channel, so a
        # partially killed canary cannot dilute its own latency gate
        ref_f = res.hop_refused.astype(jnp.float32)
        sent_f = sent_f + ref_f
        err_f = err_f + ref_f
    return timeline_mod.versioned_service_windows(
        spec, s_c, res.hop_canary, (sent_f, err_f, lat_f, exe_f)
    )


def advance(
    state: RolloutState,
    dt_tables: DeviceTables,
    obs_acc: jax.Array,      # (S, 2, W, 4) per-arm accumulator (global)
    t_complete: jax.Array,   # scalar f32 — sim time reached by EVERY
    #                          shard (windows ending before it are final)
    spec,                    # timeline.TimelineSpec
) -> Tuple[RolloutState, RolloutSummary]:
    """Advance the rollout controller through every newly COMPLETED
    window (the sim/policies.py ``advance`` idiom: an inner ``lax.scan``
    over the static window axis; live windows apply the law in order,
    the rest pass state through unchanged).

    Per live window, for each service with an active rollout:

    1. while ROLLING, fold the window's per-arm observations into the
       step accumulators and advance the bake clock;
    2. evaluate the gates the moment both arms hold ``min_samples`` —
       a trip ROLLS BACK immediately (weight 0, cooldown armed, retry
       budget decremented; exhausted budget parks the rollout FAILED);
    3. a bake window that elapses with passing gates PROMOTES to the
       next step (past the last step: DONE at the final weight); one
       that elapses still short of samples HOLDS (bake keeps running,
       samples keep accumulating);
    4. while COOLING DOWN, burn the cooldown clock; expiry restarts
       the schedule from step 0.
    """
    dtw = jnp.float32(spec.window_s)
    W = spec.num_windows
    done_below = jnp.floor(t_complete / dtw).astype(jnp.int32)
    cnt_w = obs_acc[:, :, :, 0]
    err_w = obs_acc[:, :, :, 1]
    lat_w = obs_acc[:, :, :, 2]
    exe_w = obs_acc[:, :, :, 3]

    def win_body(st: RolloutState, w):
        live = (w > st.last_window) & (w < done_below)
        rolling = dt_tables.has_rollout & (st.phase == PHASE_ROLLING)
        cooling = dt_tables.has_rollout & (st.phase == PHASE_COOLDOWN)

        roll_f = rolling.astype(jnp.float32)
        cnt_b = st.cnt_b + roll_f * cnt_w[:, 0, w]
        cnt_c = st.cnt_c + roll_f * cnt_w[:, 1, w]
        err_b = st.err_b + roll_f * err_w[:, 0, w]
        err_c = st.err_c + roll_f * err_w[:, 1, w]
        lat_b = st.lat_b + roll_f * lat_w[:, 0, w]
        lat_c = st.lat_c + roll_f * lat_w[:, 1, w]
        exe_b = st.exe_b + roll_f * exe_w[:, 0, w]
        exe_c = st.exe_c + roll_f * exe_w[:, 1, w]
        bake = st.bake_t + roll_f * dtw

        # -- gates (evaluated every window once min-samples are met) --
        # At a full-traffic step (weight 1.0, the terminal 100% rung)
        # the BASELINE arm is starved by construction — only the one-
        # block actuation-lag residue ever lands on it — so requiring
        # baseline min-samples there would park the rollout holding
        # forever with its gates disarmed.  The guard degrades to
        # canary-only and the vs-baseline RATIO gates disarm with it;
        # the absolute error-share gate stays armed so a canary that
        # goes bad at 100% still rolls back.
        M = dt_tables.steps.shape[1]
        cur_w = jnp.take_along_axis(
            dt_tables.steps,
            jnp.clip(st.step, 0.0, M - 1.0).astype(jnp.int32)[:, None],
            axis=1,
        )[:, 0]
        enough_c = cnt_c >= dt_tables.min_samples
        enough_b = cnt_b >= dt_tables.min_samples
        enough = enough_c & (enough_b | (cur_w >= 1.0))
        share_c = err_c / jnp.maximum(cnt_c, 1.0)
        share_b = err_b / jnp.maximum(cnt_b, 1.0)
        # latency means divide by EXECUTED hops only: chaos-refused
        # calls arrive with zero latency and would otherwise dilute a
        # genuinely slow canary below the ratio gate
        mean_c = lat_c / jnp.maximum(exe_c, 1.0)
        mean_b = lat_b / jnp.maximum(exe_b, 1.0)
        err_trip = (
            share_c > dt_tables.err_share
        ) | (
            jnp.isfinite(dt_tables.err_ratio)
            & enough_b
            & (share_c
               > dt_tables.err_ratio * share_b + dt_tables.err_slack)
        )
        lat_trip = (
            jnp.isfinite(dt_tables.lat_ratio)
            & enough_b
            & (mean_b > 0.0)
            & (mean_c > dt_tables.lat_ratio * mean_b)
        )
        trip = rolling & enough & (err_trip | lat_trip)

        # -- promote / hold at the bake boundary ----------------------
        baked = bake >= dt_tables.bake_s
        promote = rolling & ~trip & enough & baked
        hold = rolling & ~trip & ~enough & baked
        new_step = st.step + promote.astype(jnp.float32)
        finished = promote & (new_step >= dt_tables.num_steps)

        # -- rollback: trip -> weight 0, cooldown, bounded retries ----
        retries_left = st.retries_left - trip.astype(jnp.float32)
        rb_cool = trip & (retries_left >= 0.0)
        rb_fail = trip & (retries_left < 0.0)

        # -- cooldown countdown / restart -----------------------------
        cd = jnp.where(
            cooling, jnp.maximum(st.cooldown_t - dtw, 0.0),
            st.cooldown_t,
        )
        restart = cooling & (cd <= 0.0)

        phase = jnp.where(
            finished, PHASE_DONE,
            jnp.where(
                rb_fail, PHASE_FAILED,
                jnp.where(
                    rb_cool, PHASE_COOLDOWN,
                    jnp.where(restart, PHASE_ROLLING, st.phase),
                ),
            ),
        )
        step = jnp.where(
            finished, dt_tables.num_steps - 1.0,
            jnp.where(trip | restart, 0.0, new_step),
        )
        # a step transition (promote / trip / restart) resets the bake
        # clock and the per-step accumulators
        reset = promote | trip | restart

        def acc(v):
            return jnp.where(reset, 0.0, v)

        step_w = jnp.take_along_axis(
            dt_tables.steps,
            jnp.clip(step, 0.0, M - 1.0).astype(jnp.int32)[:, None],
            axis=1,
        )[:, 0]
        weight = jnp.where(
            dt_tables.has_rollout & (
                (phase == PHASE_ROLLING) | (phase == PHASE_DONE)
            ),
            step_w,
            0.0,
        )

        def pick(new, old):
            return jnp.where(live, new, old)

        nxt = RolloutState(
            phase=pick(phase, st.phase),
            step=pick(step, st.step),
            weight=pick(weight, st.weight),
            bake_t=pick(jnp.where(reset, 0.0, bake), st.bake_t),
            cooldown_t=pick(
                jnp.where(rb_cool, dt_tables.cooldown_s, cd),
                st.cooldown_t,
            ),
            retries_left=pick(
                jnp.where(trip, retries_left, st.retries_left),
                st.retries_left,
            ),
            cnt_b=pick(acc(cnt_b), st.cnt_b),
            cnt_c=pick(acc(cnt_c), st.cnt_c),
            err_b=pick(acc(err_b), st.err_b),
            err_c=pick(acc(err_c), st.err_c),
            lat_b=pick(acc(lat_b), st.lat_b),
            lat_c=pick(acc(lat_c), st.lat_c),
            exe_b=pick(acc(exe_b), st.exe_b),
            exe_c=pick(acc(exe_c), st.exe_c),
            last_window=jnp.where(live, w, st.last_window),
        )
        live_f = live.astype(jnp.float32)
        ys = (
            live_f * nxt.weight,
            live_f * nxt.step,
            live_f * nxt.phase,
            live_f * promote.astype(jnp.float32),
            live_f * hold.astype(jnp.float32),
            live_f * trip.astype(jnp.float32),
            live_f,
        )
        return nxt, ys

    final, ys = jax.lax.scan(
        win_body, state, jnp.arange(W, dtype=jnp.int32)
    )
    (weight, step, phase, promo, hold, rb, done) = ys
    S = state.weight.shape[0]
    delta = RolloutSummary(
        window_s=jnp.float32(spec.window_s),
        weight=weight.T,
        step=step.T,
        phase=phase.T,
        promotions=promo.T,
        holds=hold.T,
        rollbacks=rb.T,
        windows_done=done[:, 0] if done.ndim > 1 else done,
        ver_arrivals=jnp.zeros((S, 2, W)),
        ver_errors=jnp.zeros((S, 2, W)),
        ver_latency_s=jnp.zeros((S, 2, W)),
    )
    return final, delta


def accumulate_summary(
    acc: RolloutSummary, delta: RolloutSummary
) -> RolloutSummary:
    """Fold one block's per-window delta into the carried summary
    (each window is processed exactly once, so sums reconstruct the
    full series; the ``ver_*`` channels ride zero here and are attached
    from the observation accumulator after the scan)."""
    out = jax.tree.map(
        jnp.add,
        acc._replace(window_s=jnp.float32(0.0)),
        delta._replace(window_s=jnp.float32(0.0)),
    )
    return out._replace(window_s=acc.window_s)


def attach_observations(
    summary: RolloutSummary, obs_acc: jax.Array
) -> RolloutSummary:
    """Attach the final (S, 2, W, 4) observation accumulator's channels
    as the summary's per-version window series."""
    return summary._replace(
        ver_arrivals=obs_acc[:, :, :, 0],
        ver_errors=obs_acc[:, :, :, 1],
        ver_latency_s=obs_acc[:, :, :, 2],
    )


# -- host-side reporting ---------------------------------------------------


def _np(x) -> np.ndarray:
    return np.asarray(x, np.float64)


def to_doc(
    compiled, roll: RolloutSummary, tables: RolloutTables
) -> dict:
    """The ``rollout.json`` artifact (``isotope-rollout/v1``): per-
    service weight/step trajectories, per-arm observed error shares,
    and sim-time ONSETS for every promote / hold / rollback — the
    closed-loop evidence a progressive-delivery run produces."""
    names = compiled.services.names
    dt = float(roll.window_s)
    done = _np(roll.windows_done) > 0
    k = int(done.sum())
    weight = _np(roll.weight)
    step = _np(roll.step)
    phase = _np(roll.phase)
    promo = _np(roll.promotions)
    holds = _np(roll.holds)
    rb = _np(roll.rollbacks)
    arr = _np(roll.ver_arrivals)
    errs = _np(roll.ver_errors)

    def onsets(mask_row) -> List[float]:
        idx = np.nonzero(mask_row & done)[0]
        return [round(float(i) * dt, 6) for i in idx]

    services: Dict[str, dict] = {}
    for s, name in enumerate(names):
        if not tables.has_rollout[s]:
            continue
        cb, cc = arr[s, 0], arr[s, 1]
        eb, ec = errs[s, 0], errs[s, 1]
        share_c = np.where(cc > 0, ec / np.maximum(cc, 1.0), 0.0)
        share_b = np.where(cb > 0, eb / np.maximum(cb, 1.0), 0.0)
        final_phase = int(phase[s][done][-1]) if k else 0
        promote_t = onsets(promo[s] > 0)
        rollback_t = onsets(rb[s] > 0)
        services[name] = {
            "steps": [
                round(float(v), 6)
                for v in tables.steps[s][: int(tables.num_steps[s])]
            ],
            "weight": [round(float(v), 6) for v in weight[s][:k]],
            "step": [int(v) for v in step[s][:k]],
            "state": PHASE_NAMES.get(final_phase, str(final_phase)),
            "final_weight": (
                round(float(weight[s][done][-1]), 6) if k else 0.0
            ),
            "promotions": float(promo[s][done].sum()),
            "holds": float(holds[s][done].sum()),
            "rollbacks": float(rb[s][done].sum()),
            "promote_onsets_s": promote_t,
            "first_hold_onset_s": (
                onsets(holds[s] > 0)[0]
                if (holds[s][done] > 0).any()
                else None
            ),
            "rollback_onsets_s": rollback_t,
            "canary_samples": float(cc[:k].sum()),
            "canary_error_share": [
                round(float(v), 6) for v in share_c[:k]
            ],
            "baseline_error_share": [
                round(float(v), 6) for v in share_b[:k]
            ],
        }
    return {
        "schema": "isotope-rollout/v1",
        "window_s": dt,
        "num_windows": int(roll.num_windows),
        "windows_done": k,
        "services": services,
    }


def format_table(doc: dict) -> str:
    """Human-readable rollout trajectory table (CLI stderr rendering)."""
    from isotope_tpu.metrics.timeline import sparkline

    lines = [
        f"rollouts: {doc['windows_done']}/{doc['num_windows']} windows "
        f"x {doc['window_s']:g}s"
    ]
    for name, svc in doc.get("services", {}).items():
        bits = [
            f"{name:<20} weight {sparkline(svc['weight'])} "
            f"-> {svc['final_weight']:.0%} [{svc['state']}]"
        ]
        if svc["promotions"]:
            first = svc["promote_onsets_s"][0]
            bits.append(
                f"promotes {svc['promotions']:.0f} (first @{first:g}s)"
            )
        if svc["holds"]:
            bits.append(f"holds {svc['holds']:.0f}")
        if svc["rollbacks"]:
            t = svc["rollback_onsets_s"][0]
            bits.append(
                f"rollbacks {svc['rollbacks']:.0f} @{t:g}s"
            )
        lines.append("  ".join(bits))
    return "\n".join(lines)
