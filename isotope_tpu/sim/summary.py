"""Run summaries: the small, device-reducible view of a simulation.

The reference never ships per-request records off the cluster either —
Fortio reduces to duration histograms + counters in the client pod
(perf/benchmark/runner/fortio.py:38-75) and the services expose Prometheus
counters/histograms (srv/prometheus/handler.go:27-69).  ``RunSummary`` is
that same contract on device: everything in it is O(buckets), never O(N),
so request blocks of any count can accumulate into one summary under
``lax.scan`` (microbatching — HBM holds one block, not the whole run) and
shards can merge theirs with ``psum`` over the mesh.

The ``win_*`` fields accumulate the reference collector's steady-state
trim window (fortio.py:116-121: skip the first 62s, cap at 180s) on
device, so windowed percentiles survive without per-request data.
``win_lo``/``win_hi`` record the bounds actually used, so host-side
reporting never mixes the accumulated window with a recomputed one.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from isotope_tpu.metrics.histogram import (
    latency_histogram,
    quantile_from_histogram,
)
from isotope_tpu.metrics.prometheus import MetricsCollector, ServiceMetrics
from isotope_tpu.sim.engine import SimResults


class RunSummary(NamedTuple):
    """Globally-reduced run summary (small; per-request tensors stay
    device-local and are never materialized on host)."""

    count: jax.Array          # scalar — requests simulated
    error_count: jax.Array    # scalar — client-visible 500s
    hop_events: jax.Array     # scalar — executed hops (the benchmark unit)
    latency_sum: jax.Array    # scalar
    latency_m2: jax.Array     # scalar — centered second moment (Welford)
    latency_min: jax.Array
    latency_max: jax.Array
    latency_hist: jax.Array   # (NUM_BUCKETS,) fine log-spaced
    end_max: jax.Array        # scalar — max client_end (run duration)
    win_lo: jax.Array         # scalar — trim-window bounds actually used
    win_hi: jax.Array         # scalar — (inf when trim was off)
    win_count: jax.Array      # scalar — requests in the trim window
    win_error_count: jax.Array
    win_latency_hist: jax.Array  # (NUM_BUCKETS,)
    metrics: Optional[ServiceMetrics]  # per-service series (None = skipped)
    utilization: jax.Array    # (S,)
    unstable: jax.Array       # (S,) bool

    def quantiles_s(self, qs=(0.5, 0.75, 0.9, 0.99, 0.999)) -> np.ndarray:
        return quantile_from_histogram(np.asarray(self.latency_hist), qs)

    def window_quantiles_s(
        self, qs=(0.5, 0.75, 0.9, 0.99, 0.999)
    ) -> np.ndarray:
        return quantile_from_histogram(np.asarray(self.win_latency_hist), qs)

    @property
    def mean_latency_s(self) -> float:
        return float(self.latency_sum) / max(float(self.count), 1.0)

    @property
    def stddev_latency_s(self) -> float:
        n = max(float(self.count), 1.0)
        return float(np.sqrt(max(float(self.latency_m2), 0.0) / n))


def summarize(
    res: SimResults,
    collector: Optional[MetricsCollector] = None,
    window: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> RunSummary:
    """Reduce one block's SimResults to a RunSummary (jit-friendly).

    ``window`` is the ``[lo, hi)`` client-start interval whose requests
    also accumulate into the ``win_*`` fields (the collector's trim
    window); ``None`` aliases the window fields to the whole run — no
    second histogram scatter is paid.
    """
    lat = res.client_latency
    n = lat.shape[0]
    count = jnp.float32(n)
    error_count = res.client_error.sum().astype(jnp.float32)
    lat_sum = lat.sum()
    # centered second moment: conditioned for cv << 1 where the raw
    # E[x^2] - mean^2 form cancels catastrophically in f32
    mean = lat_sum / jnp.float32(max(n, 1))
    m2 = ((lat - mean) ** 2).sum()
    hist = latency_histogram(lat)
    if window is None:
        win_lo, win_hi = jnp.float32(0.0), jnp.float32(np.inf)
        win_count, win_error_count, win_hist = count, error_count, hist
    else:
        win_lo, win_hi = window
        in_win = (res.client_start >= win_lo) & (res.client_start < win_hi)
        win_w = in_win.astype(jnp.float32)
        win_count = win_w.sum()
        win_error_count = (
            (res.client_error & in_win).sum().astype(jnp.float32)
        )
        win_hist = latency_histogram(lat, win_w)
    return RunSummary(
        count=count,
        error_count=error_count,
        hop_events=res.hop_events.astype(jnp.float32),
        latency_sum=lat_sum,
        latency_m2=m2,
        latency_min=lat.min(),
        latency_max=lat.max(),
        latency_hist=hist,
        end_max=res.client_end.max(),
        win_lo=jnp.asarray(win_lo, jnp.float32),
        win_hi=jnp.asarray(win_hi, jnp.float32),
        win_count=win_count,
        win_error_count=win_error_count,
        win_latency_hist=win_hist,
        metrics=collector.collect(res) if collector is not None else None,
        utilization=res.utilization,
        unstable=res.unstable,
    )


def zeros_summary(
    collector: Optional[MetricsCollector],
    num_services: int,
    svc_rows: Optional[int] = None,
) -> RunSummary:
    """The identity element of :func:`summary_accumulate`.

    Primes the collective/compute overlap pipeline (parallel/
    sharded.py): sums start at 0, mins at +inf, maxes at -inf, so
    accumulating any real block summary over this leaves the block
    unchanged.  ``svc_rows`` overrides the leading dimension of the
    svc-sharded per-service histograms (after ``psum_scatter`` each
    shard holds an ``s_pad / svc``-row tile, not the full ``S``).
    """
    from isotope_tpu.metrics.histogram import NUM_BUCKETS

    metrics = None
    if collector is not None:
        metrics = collector.zeros()
        if svc_rows is not None:
            metrics = metrics._replace(
                duration_hist=jnp.zeros(
                    (svc_rows,) + metrics.duration_hist.shape[1:]
                ),
                response_size_hist=jnp.zeros(
                    (svc_rows,) + metrics.response_size_hist.shape[1:]
                ),
            )
    z = jnp.float32(0.0)
    return RunSummary(
        count=z,
        error_count=z,
        hop_events=z,
        latency_sum=z,
        latency_m2=z,
        latency_min=jnp.float32(np.inf),
        latency_max=jnp.float32(-np.inf),
        latency_hist=jnp.zeros((NUM_BUCKETS,)),
        end_max=z,
        win_lo=z,
        win_hi=z,
        win_count=z,
        win_error_count=z,
        win_latency_hist=jnp.zeros((NUM_BUCKETS,)),
        metrics=metrics,
        utilization=jnp.zeros((num_services,)),
        unstable=jnp.zeros((num_services,), bool),
    )


def summary_accumulate(acc: RunSummary, part: RunSummary) -> RunSummary:
    """Streaming two-summary merge (jit-friendly; no leading axis).

    The Chan/Welford pairwise form of :func:`reduce_stacked`'s block
    reduction — the overlap pipeline folds each block's
    collective-merged summary into a carried accumulator instead of
    stacking ``num_blocks`` copies.  Mathematically identical to the
    stacked reduction; float fields may differ by reduction order
    (<= a few ULP — pinned by tests/test_multihost.py).
    """
    n = acc.count + part.count
    mean_a = acc.latency_sum / jnp.maximum(acc.count, 1.0)
    mean_b = part.latency_sum / jnp.maximum(part.count, 1.0)
    delta = mean_b - mean_a
    m2 = (
        acc.latency_m2
        + part.latency_m2
        + delta * delta * acc.count * part.count / jnp.maximum(n, 1.0)
    )
    metrics = None
    if acc.metrics is not None:
        metrics = jax.tree.map(jnp.add, acc.metrics, part.metrics)
    return RunSummary(
        count=n,
        error_count=acc.error_count + part.error_count,
        hop_events=acc.hop_events + part.hop_events,
        latency_sum=acc.latency_sum + part.latency_sum,
        latency_m2=m2,
        latency_min=jnp.minimum(acc.latency_min, part.latency_min),
        latency_max=jnp.maximum(acc.latency_max, part.latency_max),
        latency_hist=acc.latency_hist + part.latency_hist,
        end_max=jnp.maximum(acc.end_max, part.end_max),
        win_lo=jnp.maximum(acc.win_lo, part.win_lo),
        win_hi=jnp.maximum(acc.win_hi, part.win_hi),
        win_count=acc.win_count + part.win_count,
        win_error_count=acc.win_error_count + part.win_error_count,
        win_latency_hist=acc.win_latency_hist + part.win_latency_hist,
        metrics=metrics,
        utilization=jnp.maximum(acc.utilization, part.utilization),
        unstable=acc.unstable | part.unstable,
    )


def merge_m2(counts, sums, m2s, axis=0):
    """Chan/Welford merge of per-part centered second moments."""
    n_tot = counts.sum(axis)
    s_tot = sums.sum(axis)
    mean_i = sums / jnp.maximum(counts, 1.0)
    mean_tot = s_tot / jnp.maximum(n_tot, 1.0)
    return m2s.sum(axis) + (counts * (mean_i - mean_tot) ** 2).sum(axis)


def reduce_stacked(parts: RunSummary) -> RunSummary:
    """Reduce a summary whose leaves carry a leading block axis (the
    stacked output of ``lax.scan``) to a single RunSummary."""
    metrics = None
    if parts.metrics is not None:
        metrics = jax.tree.map(lambda x: x.sum(0), parts.metrics)
    return RunSummary(
        count=parts.count.sum(0),
        error_count=parts.error_count.sum(0),
        hop_events=parts.hop_events.sum(0),
        latency_sum=parts.latency_sum.sum(0),
        latency_m2=merge_m2(parts.count, parts.latency_sum,
                            parts.latency_m2),
        latency_min=parts.latency_min.min(0),
        latency_max=parts.latency_max.max(0),
        latency_hist=parts.latency_hist.sum(0),
        end_max=parts.end_max.max(0),
        win_lo=parts.win_lo.max(0),   # identical across blocks
        win_hi=parts.win_hi.max(0),
        win_count=parts.win_count.sum(0),
        win_error_count=parts.win_error_count.sum(0),
        win_latency_hist=parts.win_latency_hist.sum(0),
        metrics=metrics,
        utilization=parts.utilization.max(0),
        unstable=parts.unstable.any(0),
    )
