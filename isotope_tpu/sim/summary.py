"""Run summaries: the small, device-reducible view of a simulation.

The reference never ships per-request records off the cluster either —
Fortio reduces to duration histograms + counters in the client pod
(perf/benchmark/runner/fortio.py:38-75) and the services expose Prometheus
counters/histograms (srv/prometheus/handler.go:27-69).  ``RunSummary`` is
that same contract on device: everything in it is O(buckets), never O(N),
so request blocks of any count can accumulate into one summary under
``lax.scan`` (microbatching — HBM holds one block, not the whole run) and
shards can merge theirs with ``psum`` over the mesh.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from isotope_tpu.metrics.histogram import (
    latency_histogram,
    quantile_from_histogram,
)
from isotope_tpu.metrics.prometheus import MetricsCollector, ServiceMetrics
from isotope_tpu.sim.engine import SimResults


class RunSummary(NamedTuple):
    """Globally-reduced run summary (small; per-request tensors stay
    device-local and are never materialized on host)."""

    count: jax.Array          # scalar — requests simulated
    error_count: jax.Array    # scalar — client-visible 500s
    hop_events: jax.Array     # scalar — executed hops (the benchmark unit)
    latency_sum: jax.Array    # scalar
    latency_min: jax.Array
    latency_max: jax.Array
    latency_hist: jax.Array   # (NUM_BUCKETS,) fine log-spaced
    metrics: Optional[ServiceMetrics]  # per-service series (None = skipped)
    utilization: jax.Array    # (S,)
    unstable: jax.Array       # (S,) bool

    def quantiles_s(self, qs=(0.5, 0.75, 0.9, 0.99, 0.999)) -> np.ndarray:
        return quantile_from_histogram(np.asarray(self.latency_hist), qs)

    @property
    def mean_latency_s(self) -> float:
        return float(self.latency_sum) / max(float(self.count), 1.0)


def summarize(
    res: SimResults, collector: Optional[MetricsCollector] = None
) -> RunSummary:
    """Reduce one block's SimResults to a RunSummary (jit-friendly)."""
    return RunSummary(
        count=jnp.float32(res.client_latency.shape[0]),
        error_count=res.client_error.sum().astype(jnp.float32),
        hop_events=res.hop_events.astype(jnp.float32),
        latency_sum=res.client_latency.sum(),
        latency_min=res.client_latency.min(),
        latency_max=res.client_latency.max(),
        latency_hist=latency_histogram(res.client_latency),
        metrics=collector.collect(res) if collector is not None else None,
        utilization=res.utilization,
        unstable=res.unstable,
    )


def reduce_stacked(parts: RunSummary) -> RunSummary:
    """Reduce a summary whose leaves carry a leading block axis (the
    stacked output of ``lax.scan``) to a single RunSummary."""
    metrics = None
    if parts.metrics is not None:
        metrics = jax.tree.map(lambda x: x.sum(0), parts.metrics)
    return RunSummary(
        count=parts.count.sum(0),
        error_count=parts.error_count.sum(0),
        hop_events=parts.hop_events.sum(0),
        latency_sum=parts.latency_sum.sum(0),
        latency_min=parts.latency_min.min(0),
        latency_max=parts.latency_max.max(0),
        latency_hist=parts.latency_hist.sum(0),
        metrics=metrics,
        utilization=parts.utilization.max(0),
        unstable=parts.unstable.any(0),
    )
