"""Vectorized discrete-event simulation of a compiled service graph.

The TPU-native replacement for running the mock-service fleet for real:
the reference's per-request script interpreter
(isotope/service/pkg/srv/executable.go) plus the Fortio load loop
(perf/benchmark/runner/runner.py:255-268) become one jit-compiled tensor
program over a (request x hop) event tensor.
"""
from isotope_tpu.sim.config import LoadModel, NetworkModel, SimParams
from isotope_tpu.sim.engine import SimResults, Simulator, simulate
from isotope_tpu.sim.ensemble import (
    EnsembleSpec,
    EnsembleSummary,
    wilson_interval,
)
from isotope_tpu.sim.search import (
    SearchSpec,
    SearchSummary,
    run_search,
    run_search_emulated,
    run_search_sharded,
)
from isotope_tpu.sim.splitting import SplitSpec, subset_estimate

__all__ = [
    "EnsembleSpec",
    "EnsembleSummary",
    "LoadModel",
    "NetworkModel",
    "SearchSpec",
    "SearchSummary",
    "SimParams",
    "SimResults",
    "Simulator",
    "SplitSpec",
    "run_search",
    "run_search_emulated",
    "run_search_sharded",
    "simulate",
    "subset_estimate",
    "wilson_interval",
]
