"""In-graph resilience policies: the mesh-policy control plane co-sim.

The reference system existed to benchmark *mesh resilience policy* —
Envoy circuit breakers, retry policies, and autoscaled deployments under
load — but the engine so far only simulated the unprotected failure
modes (chaos kills, timeout cascades, the retry-storm fixed point of
``sim/feedback.py``).  This module adds the in-graph mechanism that
*reacts*: per-service policy state lives in the block ``lax.scan``
carry, observes the PR-7 flight-recorder windows (metrics/timeline.py)
held in the same carry, and actuates on the next block's physics:

- **circuit breakers** (Envoy ``max_pending_requests`` /
  ``max_connections``): when a service's observed mean queue depth or
  in-flight concurrency overflows its caps, the overflow fraction of
  arriving requests is SHED — a shed request takes the error path (fast
  500, skips the script, sends nothing downstream) and *not the queue*
  (zero wait draw), and the wait law's offered load is scaled by the
  admitted fraction;
- **outlier ejection**: a run of erroring windows totaling the
  ``consecutive_errors`` threshold ejects one replica's capacity for a
  baseline interval (``base_ejection_s``), shrinking the effective ``k``
  of the M/M/k wait law, bounded by ``max_ejection_fraction``;
- **retry budgets** (Envoy ``retry_budget``): observed retry arrivals
  beyond ``budget_percent`` of active requests (plus
  ``min_retries_concurrent``) truncate the attempt fan — attempts past
  the first run only with the budgeted probability.  The same budget is
  threaded into the ``sim/feedback.py`` offered-load fixed point so the
  *static* visit estimates respect it too;
- an **HPA-style autoscaler**: per-service replica counts react to the
  per-window busy-share occupancy integral (busy seconds / (window x
  replicas)) with a configurable sync period, scale-down stabilization
  window, and per-sync scale-up/down step limits — capacity itself
  becomes scan-carry state that composes with the chaos kill/timeout
  phases (a kill trips breakers, trips budget caps, and the autoscaler
  recovers the capacity).

Control-loop discretization (stated envelope): the recorder OBSERVES at
window granularity and the loop ACTUATES at block granularity — the
state advanced through the windows completed by block ``b`` shapes
block ``b+1``'s physics (one-block actuation lag, exactly the
scrape-interval lag a real HPA/Envoy stack has).  All policy math is
pure scan-carry arithmetic — elementwise f32 over (S,) state vectors —
so the policy dynamics stay on the differentiable-planner path (DrJAX
idiom, PAPERS.md) and shards merge bit-equal to the emulated twin.

Everything is off by default: a Simulator built without policy tables
traces byte-identical programs (pinned, like ``timeline=off``).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from isotope_tpu.models.decode import (
    duration_s as _dur,
    field as _field,
    fraction as _frac,
    integer as _int,
    number as _num,
)
from isotope_tpu.models.errors import config_path


# -- policy configuration (the topology YAML `policies:` block) -----------


@dataclasses.dataclass(frozen=True)
class CircuitBreakerPolicy:
    """Envoy-style connection-pool caps + outlier ejection.

    ``max_pending`` caps the observed mean QUEUED requests,
    ``max_connections`` the observed mean in-flight concurrency; either
    overflowing sheds the overflow fraction.  ``consecutive_errors``
    (errors accumulated over a run of erroring windows) ejects one
    replica for ``base_ejection_s`` seconds, up to
    ``max_ejection_fraction`` of the current replicas.  ``None`` /
    ``0`` disables the respective mechanism.
    """

    max_pending: Optional[float] = None
    max_connections: Optional[float] = None
    consecutive_errors: int = 0
    base_ejection_s: float = 30.0
    max_ejection_fraction: float = 0.5

    _FIELDS = {
        "max_pending", "max_connections", "consecutive_errors",
        "base_ejection", "max_ejection_fraction",
    }

    @classmethod
    def decode(cls, value: dict) -> "CircuitBreakerPolicy":
        if not isinstance(value, dict):
            raise ValueError(f"breaker must be a mapping: {value!r}")
        unknown = set(value) - cls._FIELDS
        if unknown:
            raise ValueError(f"unknown breaker fields: {sorted(unknown)}")

        field = functools.partial(_field, value)

        out = cls(
            max_pending=field("max_pending", _num, None),
            max_connections=field("max_connections", _num, None),
            consecutive_errors=field("consecutive_errors", _int, 0),
            base_ejection_s=field("base_ejection", _dur, 30.0),
            max_ejection_fraction=field(
                "max_ejection_fraction", _frac, 0.5
            ),
        )
        for name in ("max_pending", "max_connections"):
            v = getattr(out, name)
            if v is not None and v <= 0:
                with config_path(name):
                    raise ValueError(f"{name} must be positive: {v!r}")
        if out.consecutive_errors < 0:
            with config_path("consecutive_errors"):
                raise ValueError("consecutive_errors must be >= 0")
        if out.base_ejection_s <= 0:
            with config_path("base_ejection"):
                raise ValueError("base_ejection must be positive")
        return out


@dataclasses.dataclass(frozen=True)
class RetryBudgetPolicy:
    """Envoy ``retry_budget``: concurrent retries may not exceed
    ``budget_percent`` of active requests, with a
    ``min_retries_concurrent`` floor so quiet services can still retry.
    A budget of 0 suppresses all retries once any are observed."""

    budget_percent: float = 0.2      # stored as a fraction in [0, 1]
    min_retries_concurrent: float = 3.0

    _FIELDS = {"budget_percent", "min_retries_concurrent"}

    @classmethod
    def decode(cls, value: dict) -> "RetryBudgetPolicy":
        if not isinstance(value, dict):
            raise ValueError(f"retry_budget must be a mapping: {value!r}")
        unknown = set(value) - cls._FIELDS
        if unknown:
            raise ValueError(
                f"unknown retry_budget fields: {sorted(unknown)}"
            )

        field = functools.partial(_field, value)

        out = cls(
            budget_percent=field("budget_percent", _frac, 0.2),
            min_retries_concurrent=field(
                "min_retries_concurrent", _num, 3.0
            ),
        )
        if out.min_retries_concurrent < 0:
            with config_path("min_retries_concurrent"):
                raise ValueError("min_retries_concurrent must be >= 0")
        return out


@dataclasses.dataclass(frozen=True)
class AutoscalerPolicy:
    """HPA-style per-service replica controller.

    At each sync (every ``sync_period_s`` of sim time) the desired
    count is ``ceil(current * utilization / target_utilization)``
    (the HPA formula), clamped to ``[min_replicas, max_replicas]`` and
    to at most ``scale_up_step`` up / ``scale_down_step`` down per
    sync; a scale-DOWN additionally requires the desired count to have
    sat below current continuously for ``stabilization_window_s``.
    """

    min_replicas: int = 1
    max_replicas: int = 8
    target_utilization: float = 0.6
    sync_period_s: float = 15.0
    stabilization_window_s: float = 60.0
    scale_up_step: int = 4
    scale_down_step: int = 1

    _FIELDS = {
        "min_replicas", "max_replicas", "target_utilization",
        "sync_period", "stabilization_window", "scale_up_step",
        "scale_down_step",
    }

    @classmethod
    def decode(cls, value: dict) -> "AutoscalerPolicy":
        if not isinstance(value, dict):
            raise ValueError(f"autoscaler must be a mapping: {value!r}")
        unknown = set(value) - cls._FIELDS
        if unknown:
            raise ValueError(
                f"unknown autoscaler fields: {sorted(unknown)}"
            )

        field = functools.partial(_field, value)

        out = cls(
            min_replicas=field("min_replicas", _int, 1),
            max_replicas=field("max_replicas", _int, 8),
            target_utilization=field("target_utilization", _frac, 0.6),
            sync_period_s=field("sync_period", _dur, 15.0),
            stabilization_window_s=field(
                "stabilization_window", _dur, 60.0
            ),
            scale_up_step=field("scale_up_step", _int, 4),
            scale_down_step=field("scale_down_step", _int, 1),
        )
        if out.min_replicas < 1:
            with config_path("min_replicas"):
                raise ValueError("min_replicas must be >= 1")
        if out.target_utilization <= 0:
            with config_path("target_utilization"):
                raise ValueError("target_utilization must be positive")
        if out.sync_period_s <= 0:
            with config_path("sync_period"):
                raise ValueError("sync_period must be positive")
        if out.scale_up_step < 1 or out.scale_down_step < 1:
            raise ValueError("scale steps must be >= 1")
        return out


@dataclasses.dataclass(frozen=True)
class ServicePolicy:
    """The resilience policies attached to one service (any subset)."""

    breaker: Optional[CircuitBreakerPolicy] = None
    retry_budget: Optional[RetryBudgetPolicy] = None
    autoscaler: Optional[AutoscalerPolicy] = None

    # ``lb`` shares the block but is decoded/compiled by sim/lb.py
    # (compiler/compile.compile_lb) into its own tables — listed here
    # only so the strict unknown-field check admits it
    _FIELDS = {"breaker", "retry_budget", "autoscaler", "lb"}

    @classmethod
    def decode(
        cls, value: dict, default: "ServicePolicy"
    ) -> "ServicePolicy":
        """Decode one service's entry; ``default`` seeds each policy
        block, an explicit ``null`` disables it for this service."""
        if value is None:
            value = {}
        if not isinstance(value, dict):
            raise ValueError(f"service policy must be a mapping: {value!r}")
        unknown = set(value) - cls._FIELDS
        if unknown:
            raise ValueError(f"unknown policy fields: {sorted(unknown)}")

        def block(key, decode, fallback):
            if key not in value:
                return fallback
            if value[key] is None:
                return None  # explicit null disables the default
            with config_path(key):
                return decode(value[key])

        return cls(
            breaker=block(
                "breaker", CircuitBreakerPolicy.decode, default.breaker
            ),
            retry_budget=block(
                "retry_budget", RetryBudgetPolicy.decode,
                default.retry_budget,
            ),
            autoscaler=block(
                "autoscaler", AutoscalerPolicy.decode, default.autoscaler
            ),
        )


@dataclasses.dataclass(frozen=True)
class PolicySet:
    """The decoded ``policies:`` block of a topology YAML.

    Schema::

        policies:
          defaults:               # applies to EVERY service
            retry_budget: {budget_percent: 20%}
          worker:                 # per-service overrides (block-wise)
            breaker: {max_pending: 8, consecutive_errors: 5}
            autoscaler: {min_replicas: 2, max_replicas: 16}
          frontend:
            retry_budget: null    # explicit null disables the default

    ``defaults`` seeds every service; a per-service entry replaces the
    named policy blocks wholesale (an explicit ``null`` disables one).
    """

    per_service: Dict[str, ServicePolicy]
    defaults: ServicePolicy

    @classmethod
    def decode(cls, raw: dict, service_names) -> "PolicySet":
        if not isinstance(raw, dict):
            raise ValueError(f"policies must be a mapping: {raw!r}")
        names = list(service_names)
        with config_path("policies"):
            with config_path("defaults"):
                default = ServicePolicy.decode(
                    raw.get("defaults") or {}, ServicePolicy()
                )
            per: Dict[str, ServicePolicy] = {}
            for key, value in raw.items():
                if key == "defaults":
                    continue
                if key not in names:
                    raise ValueError(
                        f"policies target unknown service {key!r}"
                    )
                with config_path(key):
                    per[key] = ServicePolicy.decode(value, default)
        return cls(per_service=per, defaults=default)

    def for_service(self, name: str) -> ServicePolicy:
        return self.per_service.get(name, self.defaults)

    @property
    def empty(self) -> bool:
        pols = list(self.per_service.values()) + [self.defaults]
        return all(
            p.breaker is None
            and p.retry_budget is None
            and p.autoscaler is None
            for p in pols
        )


# -- dense per-service tables (compiled by compiler/compile.py) -----------


_INF = float("inf")


@dataclasses.dataclass(frozen=True)
class PolicyTables:
    """The ``policies:`` block lowered to dense per-service arrays in
    compiled service order — the device-constant form the engine's
    policy scan consumes.  Sentinels: ``inf`` caps / thresholds disable
    the respective mechanism for a service."""

    names: Tuple[str, ...]
    static_replicas: np.ndarray       # (S,) i64 — topology numReplicas
    # breaker
    max_pending: np.ndarray           # (S,) f64, inf = uncapped
    max_connections: np.ndarray       # (S,) f64, inf = uncapped
    consecutive_errors: np.ndarray    # (S,) f64, inf = ejection off
    base_ejection_s: np.ndarray       # (S,) f64
    max_eject_frac: np.ndarray        # (S,) f64
    # retry budget
    has_budget: np.ndarray            # (S,) bool
    budget_frac: np.ndarray           # (S,) f64
    budget_min: np.ndarray            # (S,) f64
    # autoscaler
    has_hpa: np.ndarray               # (S,) bool
    min_replicas: np.ndarray          # (S,) f64
    max_replicas: np.ndarray          # (S,) f64
    target_util: np.ndarray           # (S,) f64
    sync_period_s: np.ndarray         # (S,) f64
    stabilization_s: np.ndarray       # (S,) f64
    up_step: np.ndarray               # (S,) f64
    down_step: np.ndarray             # (S,) f64

    @property
    def num_services(self) -> int:
        return len(self.names)

    @property
    def any_breaker(self) -> bool:
        return bool(
            np.isfinite(self.max_pending).any()
            or np.isfinite(self.max_connections).any()
        )

    @property
    def any_ejection(self) -> bool:
        return bool(np.isfinite(self.consecutive_errors).any())

    @property
    def any_budget(self) -> bool:
        return bool(self.has_budget.any())

    @property
    def any_hpa(self) -> bool:
        return bool(self.has_hpa.any())

    @property
    def k_max(self) -> int:
        """The widest station the dynamic wait law can reach (sets the
        Erlang recursion length next to the static replica max)."""
        k = int(self.static_replicas.max(initial=1))
        if self.any_hpa:
            k = max(k, int(self.max_replicas[self.has_hpa].max()))
        return k

    def signature(self) -> str:
        """Stable identity for executable-cache keys."""
        fields = dataclasses.fields(self)
        parts = [f"{self.names!r}"]
        for f in fields[1:]:
            parts.append(np.asarray(getattr(self, f.name)).tobytes().hex())
        return "policies:" + "|".join(parts)


def build_tables(pols: PolicySet, services) -> PolicyTables:
    """Lower a decoded PolicySet against a compiled ServiceTable."""
    names = tuple(services.names)
    S = len(names)

    def arr(fill):
        return np.full(S, fill, np.float64)

    static = np.asarray(services.replicas, np.int64)
    max_pending = arr(_INF)
    max_conns = arr(_INF)
    consec = arr(_INF)
    eject_s = arr(30.0)
    eject_frac = arr(0.5)
    has_budget = np.zeros(S, bool)
    budget = arr(0.0)
    budget_min = arr(0.0)
    has_hpa = np.zeros(S, bool)
    min_r = static.astype(np.float64)
    max_r = static.astype(np.float64)
    target = arr(0.6)
    sync_s = arr(15.0)
    stab_s = arr(60.0)
    up_step = arr(1.0)
    down_step = arr(1.0)
    for s, name in enumerate(names):
        p = pols.for_service(name)
        if p.autoscaler is not None and (
            p.autoscaler.min_replicas > p.autoscaler.max_replicas
        ):
            # vet reports this as VET-T011; compiling without vet must
            # still fail loudly instead of clipping into an empty range
            raise ValueError(
                f"policies.{name}.autoscaler: min_replicas="
                f"{p.autoscaler.min_replicas} > max_replicas="
                f"{p.autoscaler.max_replicas}"
            )
        if p.breaker is not None:
            b = p.breaker
            if b.max_pending is not None:
                max_pending[s] = b.max_pending
            if b.max_connections is not None:
                max_conns[s] = b.max_connections
            if b.consecutive_errors > 0:
                consec[s] = float(b.consecutive_errors)
            eject_s[s] = b.base_ejection_s
            eject_frac[s] = b.max_ejection_fraction
        if p.retry_budget is not None:
            has_budget[s] = True
            budget[s] = p.retry_budget.budget_percent
            budget_min[s] = p.retry_budget.min_retries_concurrent
        if p.autoscaler is not None:
            a = p.autoscaler
            has_hpa[s] = True
            min_r[s] = float(a.min_replicas)
            max_r[s] = float(a.max_replicas)
            target[s] = a.target_utilization
            sync_s[s] = a.sync_period_s
            stab_s[s] = a.stabilization_window_s
            up_step[s] = float(a.scale_up_step)
            down_step[s] = float(a.scale_down_step)
    return PolicyTables(
        names=names,
        static_replicas=static,
        max_pending=max_pending,
        max_connections=max_conns,
        consecutive_errors=consec,
        base_ejection_s=eject_s,
        max_eject_frac=eject_frac,
        has_budget=has_budget,
        budget_frac=budget,
        budget_min=budget_min,
        has_hpa=has_hpa,
        min_replicas=min_r,
        max_replicas=max_r,
        target_util=target,
        sync_period_s=sync_s,
        stabilization_s=stab_s,
        up_step=up_step,
        down_step=down_step,
    )


def lint_policies(
    raw: dict, service_names
) -> Tuple[Optional[PolicySet], List[Tuple[str, str]]]:
    """Decode a raw ``policies:`` block tolerantly for the vet linter.

    Returns ``(PolicySet | None, [(rule_hint, message), ...])`` — decode
    errors become findings instead of crashes (``rule_hint`` is
    ``"decode"``; semantic rules are checked by the caller against the
    decoded set).
    """
    try:
        return PolicySet.decode(raw, service_names), []
    except ValueError as e:
        return None, [("decode", str(e))]


# -- device-side state / control law --------------------------------------
#
# Everything below is jax-traced inside the engine's block scan; imports
# stay lazy-free because policies.py is imported by host-only paths
# (topo_lint) — jax imports live inside the functions' module-level
# import below, which every engine caller already has.

import jax  # noqa: E402  (host-only callers above never trace)
import jax.numpy as jnp  # noqa: E402


class DeviceTables(NamedTuple):
    """PolicyTables uploaded as f32 device constants."""

    static_replicas: jax.Array    # (S,)
    max_pending: jax.Array        # (S,) inf = uncapped
    max_connections: jax.Array    # (S,)
    consecutive_errors: jax.Array  # (S,) inf = off
    base_ejection_s: jax.Array
    max_eject_frac: jax.Array
    has_budget: jax.Array         # (S,) bool
    budget_frac: jax.Array
    budget_min: jax.Array
    has_hpa: jax.Array            # (S,) bool
    min_replicas: jax.Array
    max_replicas: jax.Array
    target_util: jax.Array
    sync_period_s: jax.Array
    stabilization_s: jax.Array
    up_step: jax.Array
    down_step: jax.Array


def device_tables(t: PolicyTables) -> DeviceTables:
    f = lambda x: jnp.asarray(x, jnp.float32)  # noqa: E731
    return DeviceTables(
        static_replicas=f(t.static_replicas),
        max_pending=f(t.max_pending),
        max_connections=f(t.max_connections),
        consecutive_errors=f(t.consecutive_errors),
        base_ejection_s=f(t.base_ejection_s),
        max_eject_frac=f(t.max_eject_frac),
        has_budget=jnp.asarray(t.has_budget),
        budget_frac=f(t.budget_frac),
        budget_min=f(t.budget_min),
        has_hpa=jnp.asarray(t.has_hpa),
        min_replicas=f(t.min_replicas),
        max_replicas=f(t.max_replicas),
        target_util=f(t.target_util),
        sync_period_s=f(t.sync_period_s),
        stabilization_s=f(t.stabilization_s),
        up_step=f(t.up_step),
        down_step=f(t.down_step),
    )


class PolicyState(NamedTuple):
    """Per-service control state riding the block-scan carry."""

    replicas: jax.Array       # (S,) f32 — autoscaler's actuated count
    ejected: jax.Array        # (S,) f32 — replicas currently ejected
    eject_timer_s: jax.Array  # (S,) f32 — sim seconds until return
    err_streak: jax.Array     # (S,) f32 — errors over consecutive
    #                           erroring windows (ejection trigger)
    shed: jax.Array           # (S,) f32 in [0,1] — breaker shed frac
    was_open: jax.Array       # (S,) bool — breaker ever tripped
    retry_allow: jax.Array    # (S,) f32 in [0,1] — budgeted retry prob
    down_streak_s: jax.Array  # (S,) f32 — time desired < current
    next_sync_s: jax.Array    # (S,) f32 — next autoscaler sync time
    last_window: jax.Array    # scalar i32 — last processed window
    trips: jax.Array          # (S,) f32 — breaker open transitions
    ejections: jax.Array      # (S,) f32 — ejection events
    scale_events: jax.Array   # (S,) f32 — autoscaler actuations


class PolicyFx(NamedTuple):
    """The policy state's effect on one block's physics (traced)."""

    replicas: jax.Array      # (S,) f32 — effective replica count >= 1
    shed: jax.Array          # (S,) f32 — admission-shed probability
    retry_allow: jax.Array   # (S,) f32 — attempt>=1 survival prob
    # panic-routing inputs (sim/lb.py): the actuated pool size and its
    # UNfloored healthy remainder (replicas minus ejections — 0 means
    # 0, unlike ``replicas`` above which keeps one server for the wait
    # law).  Optional with None defaults so hand-built fixtures and
    # older callers stay valid; engine paths that need panic always
    # receive them from :func:`effects`.
    total: Optional[jax.Array] = None   # (S,) f32
    alive: Optional[jax.Array] = None   # (S,) f32


class PolicySummary(NamedTuple):
    """Per-window actuation series + event counters, reduced on device.

    Series hold the state in effect at each window's END (after that
    window's control update); unprocessed windows are zero with
    ``windows_done`` 0.  Replicated across shards (every shard computes
    the identical control trajectory from the psum-merged signals), so
    the sharded merge TAKES it rather than summing — like
    ``window_s``."""

    window_s: jax.Array       # scalar f32
    replicas: jax.Array       # (S, W) f32 — actuated replicas
    effective: jax.Array      # (S, W) f32 — replicas minus ejected
    shed: jax.Array           # (S, W) f32
    retry_allow: jax.Array    # (S, W) f32
    ejected: jax.Array        # (S, W) f32
    breaker_open: jax.Array   # (S, W) f32 (0/1)
    windows_done: jax.Array   # (W,) f32 (0/1)
    trips: jax.Array          # (S,) f32
    ejections: jax.Array      # (S,) f32
    scale_events: jax.Array   # (S,) f32

    @property
    def num_windows(self) -> int:
        return int(np.asarray(self.windows_done).shape[0])


def init_state(
    dt: DeviceTables, lag_periods: int = 0
) -> PolicyState:
    """The scan carry's initial policy state.

    ``lag_periods`` delays the autoscaler's FIRST sync by that many
    sync periods — the ``policies.autoscaler_lag`` chaos site (the
    control loop missing N syncs at the worst time: startup)."""
    S = dt.static_replicas.shape[0]
    z = jnp.zeros(S, jnp.float32)
    replicas0 = jnp.where(
        dt.has_hpa,
        jnp.clip(dt.static_replicas, dt.min_replicas, dt.max_replicas),
        dt.static_replicas,
    )
    return PolicyState(
        replicas=replicas0,
        ejected=z,
        eject_timer_s=z,
        err_streak=z,
        shed=z,
        was_open=jnp.zeros(S, bool),
        retry_allow=jnp.ones(S, jnp.float32),
        down_streak_s=z,
        next_sync_s=dt.sync_period_s * jnp.float32(1 + lag_periods),
        last_window=jnp.int32(-1),
        trips=z,
        ejections=z,
        scale_events=z,
    )


def effects(state: PolicyState) -> PolicyFx:
    """What the NEXT block's physics sees: integer-actuated replicas
    minus ejected capacity (floored at 1 server), the breaker's shed
    probability, and the budgeted retry survival probability."""
    total = jnp.round(state.replicas)
    alive = total - jnp.round(state.ejected)
    return PolicyFx(
        replicas=jnp.maximum(alive, 1.0),
        shed=state.shed,
        retry_allow=state.retry_allow,
        total=total,
        alive=alive,
    )


def zeros_summary(spec, num_services: int) -> PolicySummary:
    W = spec.num_windows
    S = num_services
    return PolicySummary(
        window_s=jnp.float32(spec.window_s),
        replicas=jnp.zeros((S, W)),
        effective=jnp.zeros((S, W)),
        shed=jnp.zeros((S, W)),
        retry_allow=jnp.zeros((S, W)),
        ejected=jnp.zeros((S, W)),
        breaker_open=jnp.zeros((S, W)),
        windows_done=jnp.zeros(W),
        trips=jnp.zeros(S),
        ejections=jnp.zeros(S),
        scale_events=jnp.zeros(S),
    )


def observe_block(res, spec, retry_hop_mask: jax.Array) -> jax.Array:
    """(S, W) executed RETRY hops (attempt >= 1) of one block, binned
    by hop start — the budget law's numerator, an observation channel
    the flight recorder doesn't carry.  Additive across blocks/shards
    exactly like the recorder's series."""
    from isotope_tpu.metrics import timeline as timeline_mod

    T = spec.num_windows * spec.window_s
    s_c = jnp.clip(res.hop_start, 0.0, T)
    retry_f = (res.hop_sent & retry_hop_mask[None, :]).astype(jnp.float32)
    pref = timeline_mod._service_boundary_prefixes(spec, s_c, (retry_f,))
    return pref[:, 1:, 0] - pref[:, :-1, 0]


def advance(
    state: PolicyState,
    dt_tables: DeviceTables,
    tl_acc,                  # TimelineSummary accumulator (global sums)
    retry_acc: jax.Array,    # (S, W) retry-arrival accumulator (global)
    t_complete: jax.Array,   # scalar f32 — sim time reached by EVERY
    #                          shard (windows ending before it are final)
    spec,                    # timeline.TimelineSpec
    stuck_breaker: bool = False,
    downed_w: Optional[jax.Array] = None,  # (S, W) chaos-downed count
) -> Tuple[PolicyState, PolicySummary]:
    """Advance the control loop through every newly COMPLETED window.

    Runs an inner ``lax.scan`` over the static window axis; windows at
    indices ``(state.last_window, floor(t_complete / dt))`` apply the
    control law in order, the rest pass state through unchanged.
    Every block pays the full W-window sweep (mostly masked dead), but
    the recorder's planner caps W at ``timeline_max_windows`` (256),
    so the O(W x S) law is noise next to a block's (N x H) physics.  The
    returned PolicySummary delta holds the per-window actuation series
    for exactly the windows processed this call (summed into the outer
    accumulator by the engine scan).

    ``stuck_breaker`` is the ``policies.stuck_breaker`` chaos site: a
    tripped breaker never closes (its shed fraction only ratchets up).
    """
    dtw = jnp.float32(spec.window_s)
    W = spec.num_windows
    arr_w = tl_acc.svc_arrivals.astype(jnp.float32)       # (S, W)
    err_w = tl_acc.svc_errors.astype(jnp.float32)
    busy_w = tl_acc.svc_busy_s
    infl_w = tl_acc.svc_inflight_s
    done_below = jnp.floor(t_complete / dtw).astype(jnp.int32)

    def win_body(st: PolicyState, w):
        live = (w > st.last_window) & (w < done_below)
        arr = arr_w[:, w]
        err = err_w[:, w]
        queue = jnp.maximum(infl_w[:, w] - busy_w[:, w], 0.0) / dtw
        conc = infl_w[:, w] / dtw
        retries = retry_acc[:, w]

        # -- outlier ejection: errors over consecutive erroring windows.
        # A SHEDDING breaker holds the streak instead of accumulating:
        # shed requests take the error path, so counting them would
        # self-reinforce (shed -> eject -> less capacity -> more shed);
        # Envoy's overflow 503s are likewise not outlier-detection
        # events.  Real 500s while not shedding still accumulate.
        shedding = st.shed > 0.0
        streak = jnp.where(
            shedding,
            st.err_streak,
            jnp.where(err > 0, st.err_streak + err, 0.0),
        )
        current = jnp.maximum(jnp.round(st.replicas), 1.0)
        can_eject = (
            jnp.isfinite(dt_tables.consecutive_errors)
            & ~shedding
            & (streak >= dt_tables.consecutive_errors)
            & (st.ejected + 1.0
               <= jnp.floor(dt_tables.max_eject_frac * current) + 1e-6)
        )
        ejected = st.ejected + jnp.where(can_eject, 1.0, 0.0)
        timer = jnp.where(
            can_eject,
            dt_tables.base_ejection_s,
            jnp.maximum(st.eject_timer_s - dtw, 0.0),
        )
        # baseline interval over: every ejected replica returns
        restored = (timer <= 0.0) & (ejected > 0.0)
        ejected = jnp.where(restored, 0.0, ejected)
        streak = jnp.where(can_eject, 0.0, streak)

        # -- circuit breaker: shed the overflow past either cap.
        # The observed queue/concurrency already ran at the current
        # shed fraction — divide the admitted observation back out
        # (the same demand reconstruction as the retry budget below)
        # or the law flaps 0 <-> overflow every window instead of
        # settling at 1 - cap/demand.  The shed ceiling of 0.98 keeps
        # the reconstruction well-conditioned (denominator >= 0.02)
        # and matches Envoy, which sheds the excess, never everything.
        admit = jnp.maximum(1.0 - st.shed, 0.02)
        over = jnp.maximum(
            queue / (admit * dt_tables.max_pending),
            conc / (admit * dt_tables.max_connections),
        )
        open_now = over > 1.0
        shed_target = jnp.where(
            open_now, jnp.clip(1.0 - 1.0 / jnp.maximum(over, 1.0),
                               0.0, 0.98), 0.0
        )
        if stuck_breaker:
            # chaos: a tripped breaker never closes — the shed
            # fraction only ratchets upward
            shed_new = jnp.maximum(shed_target, st.shed)
        else:
            shed_new = shed_target
        # a TRIP is a closed -> open transition (shed was 0): a
        # breaker that recovers and re-trips on a second chaos phase
        # counts again
        trips = st.trips + jnp.where(
            open_now & (st.shed <= 0.0), 1.0, 0.0
        )
        was_open = st.was_open | open_now

        # -- retry budget: allow = headroom / UNSUPPRESSED demand -----
        # The observed retries already ran at the current allow, so
        # the demand estimate divides it back out — comparing the raw
        # observation to the headroom would snap allow back to 1 the
        # window after it throttled (bang-bang at ~2x the budget);
        # with the reconstruction, steady demand D > H settles at
        # allow = H/D (the same correction the static mirror in
        # sim/feedback.py applies).
        headroom = dt_tables.budget_frac * arr + dt_tables.budget_min
        demand = retries / jnp.maximum(st.retry_allow, 1e-3)
        allow = jnp.where(
            dt_tables.has_budget & (demand > headroom),
            jnp.clip(headroom / jnp.maximum(demand, 1e-6), 0.0, 1.0),
            1.0,
        )

        # -- autoscaler: HPA formula at sync boundaries ---------------
        # Utilization averages over the ALIVE capacity (actuated count
        # minus ejections minus the chaos phase's down delta) — the
        # ready-pod averaging a real HPA does.  Dividing by the
        # actuated count would make a killed service look idle and
        # scale it DOWN mid-outage.
        w_end = (w.astype(jnp.float32) + 1.0) * dtw
        down_now = (
            downed_w[:, w]
            if downed_w is not None
            else jnp.float32(0.0)
        )
        alive_raw = current - jnp.round(st.ejected) - down_now
        alive = jnp.maximum(alive_raw, 1.0)
        util = busy_w[:, w] / (dtw * alive)
        desired = jnp.clip(
            jnp.ceil(current * util / dt_tables.target_util),
            dt_tables.min_replicas,
            dt_tables.max_replicas,
        )
        # NO READY PODS report metrics during a full kill: a real HPA
        # skips the scale decision entirely — hold the count, the
        # stabilization streak, and the sync clock until capacity
        # returns (the first window after recovery syncs immediately)
        no_pods = alive_raw < 0.5
        down_streak = jnp.where(
            no_pods,
            st.down_streak_s,
            jnp.where(desired < current, st.down_streak_s + dtw, 0.0),
        )
        do_sync = (
            dt_tables.has_hpa & (w_end >= st.next_sync_s) & ~no_pods
        )
        scale_up = do_sync & (desired > current)
        scale_down = (
            do_sync
            & (desired < current)
            & (down_streak >= dt_tables.stabilization_s)
        )
        new_count = jnp.where(
            scale_up,
            jnp.minimum(desired, current + dt_tables.up_step),
            jnp.where(
                scale_down,
                jnp.maximum(desired, current - dt_tables.down_step),
                st.replicas,
            ),
        )
        next_sync = jnp.where(
            do_sync, st.next_sync_s + dt_tables.sync_period_s,
            st.next_sync_s,
        )
        scale_events = st.scale_events + jnp.where(
            scale_up | scale_down, 1.0, 0.0
        )

        def pick(new, old):
            return jnp.where(live, new, old)

        nxt = PolicyState(
            replicas=pick(new_count, st.replicas),
            ejected=pick(ejected, st.ejected),
            eject_timer_s=pick(timer, st.eject_timer_s),
            err_streak=pick(streak, st.err_streak),
            shed=pick(shed_new, st.shed),
            was_open=jnp.where(live, was_open, st.was_open),
            retry_allow=pick(allow, st.retry_allow),
            down_streak_s=pick(down_streak, st.down_streak_s),
            next_sync_s=pick(next_sync, st.next_sync_s),
            last_window=jnp.where(live, w, st.last_window),
            trips=pick(trips, st.trips),
            ejections=pick(
                st.ejections + jnp.where(can_eject, 1.0, 0.0),
                st.ejections,
            ),
            scale_events=pick(scale_events, st.scale_events),
        )
        fx = effects(nxt)
        live_f = live.astype(jnp.float32)
        ys = (
            live_f * nxt.replicas,
            live_f * fx.replicas,
            live_f * nxt.shed,
            live_f * nxt.retry_allow,
            live_f * nxt.ejected,
            live_f * (nxt.shed > 0.0),
            live_f,
        )
        return nxt, ys

    final, ys = jax.lax.scan(
        win_body, state, jnp.arange(W, dtype=jnp.int32)
    )
    (reps, eff, shed, allow, ejected, open_w, done) = ys
    delta = PolicySummary(
        window_s=jnp.float32(spec.window_s),
        replicas=reps.T,
        effective=eff.T,
        shed=shed.T,
        retry_allow=allow.T,
        ejected=ejected.T,
        breaker_open=open_w.T,
        windows_done=done[:, 0] if done.ndim > 1 else done,
        trips=final.trips - state.trips,
        ejections=final.ejections - state.ejections,
        scale_events=final.scale_events - state.scale_events,
    )
    return final, delta


def accumulate_summary(
    acc: PolicySummary, delta: PolicySummary
) -> PolicySummary:
    """Fold one block's per-window delta into the carried summary
    (each window is processed exactly once, so sums reconstruct the
    full series)."""
    out = jax.tree.map(
        jnp.add,
        acc._replace(window_s=jnp.float32(0.0)),
        delta._replace(window_s=jnp.float32(0.0)),
    )
    return out._replace(window_s=acc.window_s)


# -- host-side reporting ---------------------------------------------------


def _np(x) -> np.ndarray:
    return np.asarray(x, np.float64)


def to_doc(
    compiled, pol: PolicySummary, tables: PolicyTables
) -> dict:
    """The ``policies.json`` artifact (``isotope-policies/v1``):
    per-service actuation series plus sim-time ONSETS — the first
    breaker trip, first scale event, and recovery (shed back to 0)
    windows — so a chaos phase's breaker-trip -> budget-cap ->
    autoscaler-recovery cascade reads directly off the document."""
    names = compiled.services.names
    dt = float(pol.window_s)
    done = _np(pol.windows_done) > 0
    reps = _np(pol.replicas)
    eff = _np(pol.effective)
    shed = _np(pol.shed)
    allow = _np(pol.retry_allow)
    ejected = _np(pol.ejected)
    open_w = _np(pol.breaker_open)
    trips = _np(pol.trips)
    ejections = _np(pol.ejections)
    scale_events = _np(pol.scale_events)
    W = pol.num_windows
    # processed windows form a prefix of the grid; the series are
    # truncated to it — beyond ``windows_done`` the state was never
    # advanced (zero-filled on device), which would read as replicas=0
    # / allow=0
    k = int(done.sum())

    def onset(mask_row) -> Optional[float]:
        idx = np.nonzero(mask_row & done)[0]
        return round(float(idx[0]) * dt, 6) if len(idx) else None

    services: Dict[str, dict] = {}
    for s, name in enumerate(names):
        protected = (
            np.isfinite(tables.max_pending[s])
            or np.isfinite(tables.max_connections[s])
            or np.isfinite(tables.consecutive_errors[s])
            or bool(tables.has_budget[s])
            or bool(tables.has_hpa[s])
        )
        if not protected:
            continue
        trip_t = onset(open_w[s] > 0)
        recover_t = None
        if trip_t is not None:
            after = (np.arange(W) * dt > trip_t) & done
            closed = after & (shed[s] <= 0)
            idx = np.nonzero(closed)[0]
            recover_t = (
                round(float(idx[0]) * dt, 6) if len(idx) else None
            )
        services[name] = {
            "replicas": [round(float(v), 3) for v in reps[s][:k]],
            "effective_replicas": [
                round(float(v), 3) for v in eff[s][:k]
            ],
            "shed": [round(float(v), 6) for v in shed[s][:k]],
            "retry_allow": [
                round(float(v), 6) for v in allow[s][:k]
            ],
            "ejected": [round(float(v), 3) for v in ejected[s][:k]],
            "breaker_trips": float(trips[s]),
            "ejections": float(ejections[s]),
            "scale_events": float(scale_events[s]),
            "breaker_trip_onset_s": trip_t,
            "breaker_recovery_s": recover_t,
            # baseline = the INITIAL actuated count (init_state), not
            # the first window's post-update value — a scale landing
            # in window 0 is still an onset
            "first_scale_onset_s": onset(
                np.abs(
                    reps[s]
                    - (
                        float(np.clip(
                            tables.static_replicas[s],
                            tables.min_replicas[s],
                            tables.max_replicas[s],
                        ))
                        if tables.has_hpa[s]
                        else float(tables.static_replicas[s])
                    )
                ) > 1e-6
            ),
            "peak_replicas": float(reps[s].max(initial=0.0)),
        }
    return {
        "schema": "isotope-policies/v1",
        "window_s": dt,
        "num_windows": W,
        "windows_done": int(done.sum()),
        "services": services,
    }


def format_table(doc: dict) -> str:
    """Human-readable policy actuation table (CLI stderr rendering)."""
    from isotope_tpu.metrics.timeline import sparkline

    lines = [
        f"policies: {doc['windows_done']}/{doc['num_windows']} windows "
        f"x {doc['window_s']:g}s"
    ]
    for name, svc in doc.get("services", {}).items():
        bits = [f"{name:<20} replicas {sparkline(svc['replicas'])}"]
        if svc["breaker_trips"]:
            bits.append(
                f"trips {svc['breaker_trips']:.0f}"
                + (f" @{svc['breaker_trip_onset_s']:g}s"
                   if svc["breaker_trip_onset_s"] is not None else "")
            )
        if svc["ejections"]:
            bits.append(f"ejections {svc['ejections']:.0f}")
        if svc["scale_events"]:
            bits.append(
                f"scales {svc['scale_events']:.0f} "
                f"peak {svc['peak_replicas']:.0f}"
            )
        if any(a < 1.0 for a in svc["retry_allow"]):
            bits.append("budget-capped")
        lines.append("  ".join(bits))
    return "\n".join(lines)
