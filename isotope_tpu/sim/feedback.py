"""Load-dependent retry/timeout feedback for offered-load estimation.

The engine's queueing waits are driven by per-service visit counts
(``CompiledGraph.expected_visits``).  Statically, a retry attempt's reach
is discounted only by the target's ``errorRate`` (compiler/compile.py) —
but the reference's retries also fire on *timeouts*
(isotope/service/pkg/srv/executable.go: the http client timeout is a
transport error, and transport errors trigger the next serial attempt),
and timeout probability depends on load.  Under a chaos phase that cuts
capacity, waits lengthen, timeouts trip, retries amplify the offered
load, which lengthens waits further — the retry-storm feedback loop the
static tables cannot represent (VERDICT r3 §weak-3, ORACLE.md).

This module closes the loop with a per-phase fixed point, solved on the
host once per offered rate (cached):

    visits -> M/M/k waits -> P(timeout) per call -> per-attempt failure
    probabilities -> dynamic hop reach (retry amplification + transport
    truncation of later steps) -> visits'

Approximations (stated envelope; see ORACLE.md):

- An attempt's round trip is modeled as ``rtt + W + R`` where ``W`` is
  the target's stationary M/M/k wait (exact tail: an atom at 0 plus an
  exponential) and ``R`` — service time plus everything below —
  enters as a single exponential with the subtree's mean (deterministic
  service times shift instead).  Nested wait *variance* below the
  called service is folded into that mean.
- Mean of a concurrent group's join is approximated by the max of the
  member means.
- A 500 is fast (skips the script) and is assumed never to time out.

The fixed point is damped (0.5) and bounded: even when the amplified
load saturates a station, the clamped wait law keeps P(timeout) <= 1,
so visits are bounded by the full attempt tree.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from isotope_tpu.compiler.program import CompiledGraph, hop_wire_times
from isotope_tpu.sim.queueing import _MAX_RHO


def np_mmk(lam, mu, k):
    """Numpy mirror of queueing.mmk_params: (p_wait, wait_rate, rho_raw)."""
    lam = np.asarray(lam, np.float64)
    k = np.asarray(k, np.float64)
    rho_raw = lam / (k * mu)
    rho = np.minimum(rho_raw, _MAX_RHO)
    a = rho * k
    kmax = int(k.max()) if k.size else 1
    b = np.ones_like(a)
    bk = np.ones_like(a)
    for j in range(1, kmax + 1):
        b = a * b / (j + a * b)
        bk = np.where(k == j, b, bk)
    p_wait = bk / (1.0 - rho * (1.0 - bk))
    wait_rate = k * mu * (1.0 - rho)
    return p_wait, wait_rate, rho_raw


def _tail_w_plus_exp(p, r, rest_mean, x):
    """P(W + R > x): W = Exp(r) w.p. ``p`` else 0; R ~ Exp(1/rest_mean).

    Vectorized hypoexponential survival with the Erlang-C atom; the
    ``r == 1/rest_mean`` degeneracy uses the Gamma(2) limit.
    """
    x = np.maximum(x, 0.0)
    small = rest_mean < 1e-12
    mu_r = 1.0 / np.maximum(rest_mean, 1e-12)
    near = np.abs(r - mu_r) < 1e-9 * np.maximum(mu_r, 1.0)
    denom = np.where(near, 1.0, mu_r - r)
    hypo = np.where(
        near,
        (1.0 + r * x) * np.exp(-r * x),
        (mu_r * np.exp(-r * x) - r * np.exp(-mu_r * x)) / denom,
    )
    tail_r = np.exp(-mu_r * x)
    out = (1.0 - p) * tail_r + p * hypo
    # R negligible: pure wait tail (atom at zero when x == 0)
    pure = np.where(x > 0.0, p * np.exp(-r * x), 1.0)
    return np.clip(np.where(small, pure, out), 0.0, 1.0)


def _tail_w_shifted(p, r, rest_mean, x):
    """P(W + rest_mean > x) for deterministic service times."""
    y = x - rest_mean
    tail = p * np.exp(-r * np.maximum(y, 0.0))
    return np.clip(np.where(y > 0.0, tail, 1.0), 0.0, 1.0)


@dataclasses.dataclass
class _LevelCalls:
    """Per-level call tables (numpy, static)."""

    hop_ids: np.ndarray          # (L,) global hop ids of this level
    svc: np.ndarray              # (L,) service of each hop
    step_base: np.ndarray        # (L, P) sleep floors
    step_real: np.ndarray        # (L, P) bool
    # per call (K may be 0):
    parent_local: np.ndarray     # (K,)
    step: np.ndarray             # (K,)
    timeout: np.ndarray          # (K,) f64 (inf = none)
    attempts: np.ndarray         # (K,) i64
    target: np.ndarray           # (K,) service index
    send_prob: np.ndarray        # (K,)
    rtt: np.ndarray              # (K,) request+response wire time
    first_child: np.ndarray      # (K,) global hop id of attempt 0
    att_global: np.ndarray       # (maxA, K) global hop ids (garbage where
    att_valid: np.ndarray        # (maxA, K) bool              ... invalid)


class RetryFeedback:
    """Per-(chaos x churn)-phase visit counts with retry feedback.

    ``active`` is False when no call has a finite timeout — then timeouts
    can never fire, failure probabilities are the static error rates, and
    the static tables are already exact; callers should skip this path.
    """

    def __init__(
        self,
        compiled: CompiledGraph,
        params,
        mu: float,
        eff_replicas_pc: np.ndarray,   # (PC, S) clamped >= 1
        svc_down_pc: np.ndarray,       # (PC, S) bool
        own_combo: np.ndarray,         # (Cc, H) churn-combo hop multipliers
        static_visits_pc: np.ndarray,  # (PC, S)
        mtls=None,                     # Optional[MtlsSchedule]
        retry_budget=None,             # (has (S,), frac (S,), min (S,))
        lb=None,                       # (lb.LbTables, profile (S, k))
    ):
        self.compiled = compiled
        self.params = params
        self.mu = float(mu)
        self.eff = np.asarray(eff_replicas_pc, np.float64)
        self.down = np.asarray(svc_down_pc, bool)
        self.own = np.asarray(own_combo, np.float64)
        self.static = np.asarray(static_visits_pc, np.float64)
        self.n_combos = self.own.shape[0]
        # Envoy retry budgets (sim/policies.py): the static visit
        # estimates must respect the budget cap or the wait tables
        # overstate storm amplification the in-graph budget truncates.
        # ``min_retries_concurrent`` enters the rate law as a
        # per-second floor (stated approximation: the static estimate
        # has no concurrency axis).
        self.budget = None
        if retry_budget is not None:
            has, frac, floor = retry_budget
            self.budget = (
                np.asarray(has, bool),
                np.asarray(frac, np.float64),
                np.asarray(floor, np.float64),
            )
        # per-service LB wait laws (sim/lb.py): the fixed point's
        # P(timeout) integrates the same skewed per-backend tails the
        # engine samples.  Panic routing mirrors the wait-law load
        # scaling only — the panic share's fast-fail reach truncation
        # is NOT mirrored (stated approximation: the static estimate
        # keeps the full subtree load, conservatively overstating it).
        self.lb = lb
        self._static_replicas = np.maximum(
            np.asarray(compiled.services.replicas, np.float64), 1.0
        )
        self._retry_hop = compiled.hop_attempt > 0

        t = compiled.services
        self._err = t.error_rate.astype(np.float64)
        hs = compiled.hop_service
        net_out, net_back = hop_wire_times(compiled, params.network)
        if mtls is not None:
            # the engine taxes every attempt round trip by 2x the
            # phase's mTLS tax before the timeout comparison; the
            # feedback's P(timeout) must see the same inflation or it
            # under-counts retry load during taxed phases (ADVICE r4).
            # The fixed point is per-(chaos x churn) phase, not
            # per-mTLS phase, so fold the schedule's TIME-AVERAGED tax
            # (phases are equal-length); the residual phase-to-phase
            # wobble is documented in ORACLE.md.
            avg_tax = float(np.mean(mtls.taxes_s))
            net_out = net_out + avg_tax
            net_back = net_back + avg_tax

        self.active = False
        self._levels: List[_LevelCalls] = []
        ms = compiled.max_steps
        for lvl in compiled.levels:
            K = len(lvl.call_seg)
            if K:
                first_local = lvl.att_child[0]
                g0 = lvl.child_ids[first_local]
                att_global = lvl.child_ids[
                    np.clip(lvl.att_child, 0, max(len(lvl.child_ids) - 1, 0))
                ]
                self.active |= bool(np.isfinite(lvl.call_timeout).any())
            else:
                g0 = np.zeros(0, np.int64)
                att_global = np.zeros((1, 0), np.int64)
            self._levels.append(
                _LevelCalls(
                    hop_ids=lvl.hop_ids.astype(np.int64),
                    svc=hs[lvl.hop_ids].astype(np.int64),
                    step_base=lvl.step_base.astype(np.float64),
                    step_real=lvl.step_is_real.astype(bool),
                    parent_local=(lvl.call_seg // ms).astype(np.int64),
                    step=(lvl.call_seg % ms).astype(np.int64),
                    timeout=lvl.call_timeout.astype(np.float64),
                    attempts=lvl.att_valid.sum(0).astype(np.int64),
                    target=hs[g0].astype(np.int64),
                    send_prob=compiled.hop_send_prob[g0].astype(np.float64),
                    rtt=(net_out[g0] + net_back[g0]),
                    first_child=g0.astype(np.int64),
                    att_global=att_global.astype(np.int64),
                    att_valid=lvl.att_valid.astype(bool),
                )
            )
        self._cache: dict = {}

    # ------------------------------------------------------------------

    def visits_pc(self, offered: float) -> np.ndarray:
        """(PC, S) visit counts at root rate ``offered``, with feedback.

        The rate is quantized to 4 significant figures before keying the
        cache: visits are a smooth function of the rate, and the
        closed-loop bisection probes ~40 distinct rates per solve — raw
        float keys would re-run the host fixed point for every probe.
        """
        key = float(f"{float(offered):.4g}")
        if key not in self._cache:
            rows = [
                self._solve_row(key, i) for i in range(self.static.shape[0])
            ]
            self._cache[key] = np.stack(rows)
        return self._cache[key]

    def _upper_visits(self, row: int) -> np.ndarray:
        """Visit counts if every retry attempt always ran (pf=1, no
        truncation) — the all-attempts upper bound used to probe for the
        storm branch of a bistable fixed point."""
        compiled = self.compiled
        down = self.down[row]
        own = self.own[row % self.n_combos]
        reach = np.zeros(compiled.num_hops)
        reach[0] = 0.0 if down[compiled.hop_service[0]] else 1.0
        for lc in self._levels:
            K = len(lc.step)
            if not K:
                continue
            base = (
                reach[lc.hop_ids[lc.parent_local]]
                * (1.0 - self._err[lc.svc[lc.parent_local]])
                * lc.send_prob
                * own[lc.first_child]
            )
            base = np.where(down[lc.target], 0.0, base)
            for a in range(lc.att_global.shape[0]):
                valid = lc.att_valid[a]
                if valid.any():
                    reach[lc.att_global[a][valid]] = base[valid]
        return np.bincount(
            compiled.hop_service, weights=reach,
            minlength=compiled.num_services,
        )

    def _solve_row(self, offered: float, row: int) -> np.ndarray:
        """Solve the phase's visit fixed point, handling bistability.

        Retry feedback makes the load map non-monotone in a way that can
        admit TWO stable fixed points: a low branch (few timeouts) and a
        storm branch (every attempt times out, load = the full attempt
        tree).  The DES shows the physical system falls into the storm
        branch whenever it exists — one congestion burst trips timeouts,
        the retries sustain the backlog — so when iterating from the
        static (low) and the all-attempts (high) initializations
        converges to materially different loads, the pessimistic storm
        branch wins (and its >= 1 utilization raises ``unstable``).
        """
        low = self._iterate_row(offered, row, self.static[row].copy())
        high = self._iterate_row(offered, row, self._upper_visits(row))
        gap = np.abs(high - low).max() / max(high.max(), 1e-12)
        return high if gap > 0.05 else low

    def _iterate_row(
        self,
        offered: float,
        row: int,
        visits: np.ndarray,
        iters: int = 24,
        tol: float = 1e-5,
    ) -> np.ndarray:
        compiled = self.compiled
        S = compiled.num_services
        H = compiled.num_hops
        eff = self.eff[row]
        down = self.down[row]
        own = self.own[row % self.n_combos]
        cpu = self.params.cpu_time_s
        deterministic = self.params.service_time == "deterministic"
        if down[compiled.hop_service[0]]:
            return visits  # down entry: nothing flows; the init is exact

        # per-service retry admission probability (the static image of
        # the engine's budget gate); 1 everywhere without budgets
        allow = np.ones(S)
        for _ in range(iters):
            lam = offered * visits
            if self.lb is not None:
                from isotope_tpu.sim import lb as lb_mod

                tables, profile = self.lb
                if tables.any_panic:
                    alive = np.where(down, 0.0, eff)
                    frac = np.clip(
                        alive / self._static_replicas, 0.0, 1.0
                    )
                    panic = (tables.panic_threshold > 0.0) & (
                        frac < tables.panic_threshold
                    )
                    lam = np.where(panic, lam * frac, lam)
                p_wait, wait_rate = lb_mod.np_wait_stats(
                    tables, profile, lam, self.mu, eff
                )
            else:
                p_wait, wait_rate, _ = np_mmk(lam, self.mu, eff)
            ew = np.where(down, 0.0, p_wait / wait_rate)

            # -- bottom-up: subtree means + per-call failure probabilities
            mean_run = np.zeros(H)
            lvl_pf: List[Optional[np.ndarray]] = [None] * len(self._levels)
            lvl_surv: List[Optional[np.ndarray]] = [None] * len(self._levels)
            lvl_send: List[Optional[np.ndarray]] = [None] * len(self._levels)
            for d in reversed(range(len(self._levels))):
                lc = self._levels[d]
                L, P = lc.step_base.shape
                K = len(lc.step)
                if K:
                    t = lc.target
                    pe = self._err[t]
                    m_child = mean_run[lc.first_child]
                    rest = cpu + np.maximum(
                        m_child - ew[t] - cpu, 0.0
                    )  # mean below the wait: svc + busy
                    x = lc.timeout - lc.rtt
                    finite = np.isfinite(lc.timeout)
                    tail = _tail_w_shifted if deterministic else (
                        _tail_w_plus_exp
                    )
                    pt = np.where(
                        finite,
                        tail(p_wait[t], wait_rate[t], rest,
                             np.where(finite, x, 0.0)),
                        0.0,
                    )
                    pt = np.where(down[t], 1.0, pt)
                    pf = pe + (1.0 - pe) * pt
                    # P(an attempt ends in transport): a down callee always
                    # transport-fails; otherwise a 500 (fast) never times
                    # out, so transport == timeout on the non-500 branch
                    p_transport = np.where(down[t], 1.0, (1.0 - pe) * pt)
                    # budgeted continuation: attempt n+1 runs iff
                    # attempt n failed AND the budget admits the retry
                    # (q = pf * allow); a suppressed retry surfaces the
                    # prior attempt's transport failure
                    al = allow[t]
                    q = pf * al
                    a_m1 = np.maximum(lc.attempts - 1, 0)
                    with np.errstate(divide="ignore", invalid="ignore"):
                        geo_m1 = np.where(
                            q >= 1.0 - 1e-12,
                            a_m1.astype(np.float64),
                            (1.0 - q**a_m1) / (1.0 - q),
                        )
                    trunc = p_transport * (
                        (1.0 - al) * geo_m1 + q**a_m1
                    )
                    send_eff = lc.send_prob * own[lc.first_child]
                    # expected call duration over serial attempts
                    d_ok = lc.rtt + m_child
                    d_att = (1.0 - pe) * (
                        (1.0 - pt) * d_ok
                        + pt * np.where(finite, lc.timeout, d_ok)
                    ) + pe * (lc.rtt + ew[t] + cpu)
                    d_att = np.where(down[t], 0.0, d_att)
                    with np.errstate(divide="ignore", invalid="ignore"):
                        geo = np.where(
                            q >= 1.0 - 1e-12,
                            lc.attempts.astype(np.float64),
                            (1.0 - q ** lc.attempts) / (1.0 - q),
                        )
                    dur_call = send_eff * geo * d_att
                    seg = lc.parent_local * P + lc.step
                    slot_max = np.zeros(L * P)
                    np.maximum.at(slot_max, seg, dur_call)
                    ff = np.ones(L * P)
                    np.multiply.at(ff, seg, 1.0 - send_eff * trunc)
                    surv = np.cumprod(
                        np.concatenate(
                            [np.ones((L, 1)), ff.reshape(L, P)[:, :-1]],
                            axis=1,
                        ),
                        axis=1,
                    )
                    # the reach recursion continues attempts at the
                    # BUDGETED rate q, not raw pf
                    lvl_pf[d] = q
                    lvl_surv[d], lvl_send[d] = surv, send_eff
                    step_dur = np.maximum(
                        lc.step_base, slot_max.reshape(L, P)
                    ) * lc.step_real
                else:
                    surv = np.ones((L, P))
                    lvl_surv[d] = surv
                    step_dur = lc.step_base * lc.step_real
                busy = (surv * step_dur).sum(1)
                pe_h = self._err[lc.svc]
                mean_run[lc.hop_ids] = (
                    ew[lc.svc] + cpu + (1.0 - pe_h) * busy
                )

            # -- top-down: dynamic reach -------------------------------
            reach = np.zeros(H)
            reach[0] = 1.0
            for d, lc in enumerate(self._levels):
                K = len(lc.step)
                if not K:
                    continue
                # (1 - parent_err): a parent that 500s skips its script
                # and sends nothing (the same factor static hop_reach
                # carries, compiler/compile.py)
                base = (
                    reach[lc.hop_ids[lc.parent_local]]
                    * (1.0 - self._err[lc.svc[lc.parent_local]])
                    * lvl_surv[d][lc.parent_local, lc.step]
                    * lvl_send[d]
                )
                base = np.where(down[lc.target], 0.0, base)
                pf = lvl_pf[d]
                r_a = base
                for a in range(lc.att_global.shape[0]):
                    valid = lc.att_valid[a]
                    if valid.any():
                        reach[lc.att_global[a][valid]] = r_a[valid]
                    r_a = r_a * pf
            new = np.bincount(
                compiled.hop_service, weights=reach, minlength=S
            )
            if self.budget is not None:
                # close the budget loop: unsuppressed retry demand
                # (observed / current allow) vs the budgeted headroom
                # (budget% of active visits + the per-second floor)
                has, frac, floor = self.budget
                retry_v = np.bincount(
                    compiled.hop_service,
                    weights=reach * self._retry_hop,
                    minlength=S,
                )
                demand = offered * retry_v / np.maximum(allow, 1e-9)
                headroom = frac * offered * new + floor
                allow_new = np.where(
                    has & (demand > headroom),
                    np.clip(headroom / np.maximum(demand, 1e-9),
                            0.0, 1.0),
                    1.0,
                )
                allow = 0.5 * allow + 0.5 * allow_new
            delta = np.abs(new - visits).max() / max(new.max(), 1e-12)
            visits = 0.5 * visits + 0.5 * new
            if delta < tol:
                break
        return visits
