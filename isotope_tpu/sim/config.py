"""Simulation parameters: service-time, network, and load models.

These are the knobs the reference distributes across deployment reality —
vCPU limits on the service pods (isotope/example-config.toml [server]),
cluster networking, and the Fortio command line
(perf/benchmark/runner/runner.py:255-268: ``fortio load -c C -qps Q -t
Ds``).  Here they are explicit, reproducible model parameters.
"""
from __future__ import annotations

import dataclasses
from typing import Optional


# The reference's mock service saturates at 12-14k QPS on one vCPU
# (isotope/service/README.md:28-34) => ~77 microseconds of CPU per request.
DEFAULT_CPU_TIME_S = 1.0 / 13_000.0

SERVICE_TIME_EXPONENTIAL = "exponential"
SERVICE_TIME_DETERMINISTIC = "deterministic"
SERVICE_TIME_LOGNORMAL = "lognormal"
SERVICE_TIME_PARETO = "pareto"


@dataclasses.dataclass(frozen=True)
class NetworkModel:
    """Per-edge network delay: base one-way latency + bytes / bandwidth.

    The reference's edges are kube-DNS-addressed HTTP/1.1 keep-alive hops
    through optional Envoy sidecars (srv/request.go:30-48); intra-cluster
    one-way latency is typically a few hundred microseconds and payloads
    ride ~10 Gbps NICs.

    ``entry_extra_latency_s`` is additional one-way latency on the
    client -> entrypoint edge only — the ingress-gateway traversal of
    the reference's "ingress" sidecar mode (runner.py:96,190-197).

    ``cross_cluster_latency_s`` / ``cross_cluster_bytes_per_second``
    form the cross-cluster edge class: the reference splits one service
    graph across cluster1/cluster2 (+ VMs) so cross-cluster calls
    traverse an egress gateway, inter-cluster network, and the remote
    ingress gateway (perf/load/templates/service-graph.gen.yaml:1-3,
    common.sh:36-42).  Edges between services with different
    ``cluster`` fields pay the extra one-way latency and ride the
    (usually lower) cross-cluster bandwidth; ``None`` bandwidth means
    same as intra-cluster.
    """

    base_latency_s: float = 250e-6
    bytes_per_second: float = 1.25e9  # 10 Gbit/s
    entry_extra_latency_s: float = 0.0
    cross_cluster_latency_s: float = 1e-3
    cross_cluster_bytes_per_second: Optional[float] = None

    def one_way(self, size_bytes):
        return self.base_latency_s + size_bytes / self.bytes_per_second

    def entry_one_way(self, size_bytes):
        return self.one_way(size_bytes) + self.entry_extra_latency_s


@dataclasses.dataclass(frozen=True)
class SimParams:
    """Model parameters fixed at trace time."""

    cpu_time_s: float = DEFAULT_CPU_TIME_S
    # "exponential" matches the M/M/k queue model exactly (closed-form
    # validation); "deterministic" uses the fixed CPU demand (an M/D/k
    # approximation sampled with M/M/k waits); "lognormal" / "pareto" are
    # heavy-tail mixtures (BASELINE.json configs[4]) with the same mean —
    # ``service_time_param`` is sigma (log-space) resp. the tail index
    # alpha (> 1).
    service_time: str = SERVICE_TIME_EXPONENTIAL
    service_time_param: float = 1.0
    network: NetworkModel = NetworkModel()
    # Gaussian-copula correlation between the queueing-wait draws of
    # concurrent sibling hops.  Parallel stations fed by the same arrival
    # epochs have positively correlated backlogs, and correlated maxima
    # are smaller than independent ones — with iid draws the engine
    # overestimates fork-join p50 by ~6% at rho 0.7.  The normal-scores
    # correlation of two queues driven by a common Poisson stream is
    # ~0.4 nearly independent of rho (measured by Lindley recursion;
    # see ORACLE.md), and r=0.4 brings fork-join quantiles within ~1%
    # of the DES oracle.  0 disables (iid draws, exact for chains).
    sibling_copula_r: float = 0.4
    # Extra correlation among the serial RETRY attempts of one call, on
    # top of the sibling term: attempt n+1 re-enters the same station
    # milliseconds after attempt n timed out, so it sees nearly the same
    # backlog — with independent draws the engine misses the
    # timeout-cascade tail entirely (one timeout predicts the next).
    # Total attempt-attempt correlation = sibling_copula_r +
    # retry_copula_r; fit against the DES oracle (ORACLE.md).
    retry_copula_r: float = 0.5
    # Hierarchical decay of the sibling copula across the GROUP tree
    # (open loop only): two hops whose sibling groups share their
    # lowest common ancestor L levels up correlate at
    # sibling_copula_r * gamma^L — same-depth groups only, so serial
    # path sums stay independent (a parent-child term inflates the p99
    # tail; see engine).  gamma=0 recovers the flat within-group-only
    # copula.  Fork-join subtrees are fed by the same upstream
    # arrivals, so COUSIN subtree compositions correlate too — the
    # flat copula missed that, leaving tree13 p50 +7.9% at rho=0.9
    # (ORACLE.md r4 "known out-of-envelope" #1); 0.9 measured: +4.1%
    # p50 / +2.1% p99 at rho=0.9, monotone improvements at 0.3-0.85,
    # saturated sampler untouched.  Fit against the DES oracle like r.
    # SCOPE (ADVICE r5): only MULTI-MEMBER sibling groups — real
    # concurrent fan-outs / retry fans — join the hierarchy; singleton
    # groups (sequential single calls) keep their flat independent
    # factor, so r * gamma^L is NOT applied between a fan-out and a
    # same-depth single-call cousin on mixed sequential/concurrent
    # graphs.  Deliberate: a dense factor row per singleton group
    # captured ~7 GB of constants on a 30k-hop sequential graph (see
    # engine), and a singleton's own wait has no within-group
    # correlation to transfer in the first place.
    hierarchical_copula_gamma: float = 0.9
    # Dense-grid element threshold above which a skewed level (grid
    # > 4x its real call-step count) leaves the dense step grid — the
    # star-10k mitigation.  Lower it to force the non-dense path on
    # small graphs (tests).
    sparse_level_elems: int = 262_144
    # Dense-blocked sparse levels (engine._TiledSteps): a level past
    # the sparse threshold is partitioned into fixed-width dense tiles
    # (hops binned by script-width class, padded to the bin's widest
    # script — compiler/buckets.plan_tiles) executed with the exact
    # dense step-grid ops restricted to each bin; only scripts wider
    # than ``sparse_tile_pmax`` keep the true sparse call-slot
    # encoding as a residual.  Bit-identical to the dense grid in
    # eager, <= 1 ULP under jit (tests/test_sparse_tiles.py); off
    # falls back to the pure sparse encoding everywhere.
    sparse_tiling: bool = True
    sparse_tile_pmax: int = 64
    # Pallas census kernel (native/census_pallas.py): fuse the per-step
    # census / WaitGroup-max join (max with the sleep floor, step mask,
    # busy row-sum, exclusive step prefix — today a chain of XLA ops)
    # into one hand-written kernel.  None = auto: on for TPU backends,
    # off elsewhere (the CPU interpreter-mode kernel is for equivalence
    # tests, not speed).  False reproduces today's op-by-op path
    # exactly; True forces the kernel (interpreter mode off-TPU).
    pallas_census: Optional[bool] = None
    # Pack the census/blame carries where the <= 1 ULP pins allow:
    # attribution hop counters / blame-histogram censuses accumulate as
    # int32 (exact where f32 loses integers past 2^24) and the census
    # kernel's step mask rides as bf16 (0/1 exact).  Latency/blame
    # accumulators stay f32.  Attribution off is byte-identical either
    # way (the packing only touches attributed programs).  BOUND: any
    # single attributed run must keep every counter under 2^31 events
    # (int32 wraps where f32 merely lost precision; int64 needs the
    # globally-disabled x64 mode) — for longer soaks set
    # ``packed_carries=False`` or split the run.
    packed_carries: bool = True
    # Bucket scheduling discipline (compiler/buckets.plan_segments):
    # "critical-path" partitions each scan-eligible run by a DP
    # minimizing the summed per-segment critical-path cost (dispatch
    # overhead + padded elements); "greedy" is the historical
    # left-to-right maximal extension.
    bucket_schedule: str = "critical-path"
    # Bucketed level-scan executor (sim/levelscan.py): consecutive
    # depth levels with close shapes are padded to shared bounds and
    # swept by ONE lax.scan body per bucket, so trace/HLO size is
    # O(buckets) instead of O(depth) — the large-graph compile-wall
    # fix.  ``level_bucket_waste`` caps the padded/real element ratio
    # a bucket may cost (compiler/buckets.py); raise it to force wider
    # buckets (tests do), set ``bucketed_scan=False`` to fall back to
    # the fully unrolled trace.  Results are bit-identical either way.
    bucketed_scan: bool = True
    level_bucket_waste: float = 1.6
    # Critical-path blame attribution (metrics/attribution.py): when
    # True, ``Simulator.run_attributed`` accumulates per-hop blame
    # vectors + per-service blame histograms inside the block scan (and
    # the sharded psum merge).  Off (default) leaves every summary path
    # byte-identical — pinned by tests/test_attribution.py.
    attribution: bool = False
    # top-K slowest requests whose per-hop vectors are mined on device
    # (O(K * H)) and fed to the trace exporters as tail exemplars
    attribution_top_k: int = 8
    # the conditional-tail cut quantile estimated by the pilot pass in
    # ``--attribution=tail`` mode (p99 by default)
    attribution_tail_quantile: float = 0.99
    # Simulation flight recorder (metrics/timeline.py): when True,
    # ``Simulator.run_timeline`` bins every hop event into fixed
    # sim-time windows inside the block scan and accumulates
    # per-service x per-window series (O(S * W) carries, psum-merged
    # across shards).  Off (default) leaves every summary path
    # byte-identical — pinned like attribution.
    timeline: bool = False
    # window width in sim seconds — the scrape interval the reference's
    # Prometheus collection used against the mock services
    timeline_window_s: float = 10.0
    # hard cap on the window count; the planner widens windows (with a
    # warning) instead of letting the O(S * W) carries OOM the device
    timeline_max_windows: int = 256
    # Collective/compute overlap (parallel/sharded.py): when True, the
    # sharded runner issues each block's summary-merge collectives
    # INSIDE the scan, one block late behind a double-buffered carry —
    # block k's psum/psum_scatter results are consumed while block k+1
    # computes, so DCN merge latency hides behind the next block's
    # event sweep.  Off (default) keeps the historical single
    # post-scan merge byte-identical; on matches off exactly on
    # integer-valued fields and to reduction-order f32 noise on float
    # sums (tests/test_multihost.py).  SCOPE: the plain summary path
    # (ShardedSimulator.run) only — the attributed/timeline diagnostic
    # passes keep their single post-scan merge (their O(K*H)/O(S*W)
    # leaves merge once), and single-device Simulator runs ignore it
    # (there is no collective to overlap).
    overlap: bool = False
    # Scenario ensembles (sim/ensemble.py): the default Monte Carlo
    # fleet size of ``Simulator.run_ensemble`` when no explicit
    # EnsembleSpec is passed — N scenario variants (seeds, and
    # optionally qps/cpu/error-rate perturbations) run as ONE jitted
    # program per device with a leading member axis (jax.vmap), the
    # way the TPU Ising idiom batches independent lattices.  0 (the
    # default) leaves every existing entry point byte-identical: the
    # solo paths never see the member axis, and member k of a
    # seeds-only ensemble is bit-identical to a solo run with
    # ``fold_in(key, seeds[k])`` (tests/test_ensemble.py).
    ensemble: int = 0

    def __post_init__(self):
        if self.service_time not in (
            SERVICE_TIME_EXPONENTIAL,
            SERVICE_TIME_DETERMINISTIC,
            SERVICE_TIME_LOGNORMAL,
            SERVICE_TIME_PARETO,
        ):
            raise ValueError(f"unknown service_time: {self.service_time!r}")
        if self.cpu_time_s <= 0:
            raise ValueError("cpu_time_s must be positive")
        if self.service_time == SERVICE_TIME_PARETO and (
            self.service_time_param <= 1.0
        ):
            raise ValueError("pareto tail index alpha must be > 1 for a "
                             "finite mean")
        if self.service_time == SERVICE_TIME_LOGNORMAL and (
            self.service_time_param <= 0.0
        ):
            raise ValueError("lognormal sigma must be positive")
        if not 0.0 <= self.sibling_copula_r < 1.0:
            raise ValueError("sibling_copula_r must be in [0, 1)")
        if not 0.0 <= self.retry_copula_r < 1.0:
            raise ValueError("retry_copula_r must be in [0, 1)")
        if self.level_bucket_waste < 1.0:
            raise ValueError("level_bucket_waste must be >= 1")
        if self.sparse_tile_pmax < 1:
            raise ValueError("sparse_tile_pmax must be >= 1")
        if self.bucket_schedule not in ("critical-path", "greedy"):
            raise ValueError(
                f"unknown bucket_schedule: {self.bucket_schedule!r} "
                "(expected 'critical-path' or 'greedy')"
            )
        if self.attribution_top_k < 0:
            raise ValueError("attribution_top_k must be >= 0")
        if not 0.0 < self.attribution_tail_quantile < 1.0:
            raise ValueError(
                "attribution_tail_quantile must lie in (0, 1)"
            )
        if self.timeline_window_s <= 0.0:
            raise ValueError("timeline_window_s must be positive")
        if self.timeline_max_windows < 1:
            raise ValueError("timeline_max_windows must be >= 1")
        if self.ensemble < 0:
            raise ValueError("ensemble must be >= 0 (0 = off)")
        # (sibling_copula_r + retry_copula_r < 1 is required only for
        # hops inside a multi-attempt call; the Simulator enforces it
        # when such calls exist)


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """Kill replicas of a service during a time window.

    The simulation analogue of the reference's chaos CronJobs
    (perf/stability/istio-chaos-partial kills all-but-one replica every
    interval; istio-chaos-total scales components to zero and restores
    them after chaosDurationMinutes).  ``replicas_down=None`` means all
    replicas (total outage: callers get transport errors, which — unlike
    downstream 500s — DO propagate, srv/handler.go:66-76).

    ``drain`` selects the shutdown policy at the window's start — the
    axis the reference's graceful-shutdown stability test exercises
    (perf/stability/graceful-shutdown: a long in-flight request across
    a replica kill):

    - ``True`` (default, graceful): killed replicas finish their
      in-flight requests; only *new* work sees the reduced capacity
      (Kubernetes' default terminationGracePeriod behavior).
    - ``False`` (ungraceful): requests resident on a killed replica at
      the kill instant die with a connection reset — a transport error
      at their caller.  Each resident request dies with probability
      ``replicas_down / alive-replicas-before-the-kill``.
    """

    service: str
    start_s: float
    end_s: float
    replicas_down: Optional[int] = None  # None == all
    drain: bool = True

    def __post_init__(self):
        if self.end_s <= self.start_s:
            raise ValueError("chaos window must have end_s > start_s")
        if self.start_s < 0:
            raise ValueError("chaos window must start at t >= 0")
        if self.replicas_down is not None and self.replicas_down <= 0:
            raise ValueError("replicas_down must be positive (or None=all)")


def bounce_schedule(
    service: str,
    period_s: float,
    down_s: float,
    count: int,
    start_s: float = 0.0,
    replicas_down: Optional[int] = None,
    drain: bool = True,
) -> "tuple[ChaosEvent, ...]":
    """Rolling-restart chaos: ``count`` outage windows of ``down_s``
    seconds, one per ``period_s``.

    The simulation analogue of the reference's gateway-bouncer
    (perf/stability/gateway-bouncer/README.md:14-21: the ingress
    gateway is rolling-restarted on a loop and fortio clients crash on
    the connection errors the bounce causes).  Point it at the
    entrypoint service to bounce the ingress: during each window the
    entry refuses connections, outside the windows traffic is clean.
    """
    if down_s <= 0 or down_s > period_s:
        raise ValueError("bounce needs 0 < down_s <= period_s")
    if count <= 0:
        raise ValueError("bounce count must be positive")
    return tuple(
        ChaosEvent(
            service=service,
            start_s=start_s + i * period_s,
            end_s=start_s + i * period_s + down_s,
            replicas_down=replicas_down,
            drain=drain,
        )
        for i in range(count)
    )


@dataclasses.dataclass(frozen=True)
class TrafficSplit:
    """Time-varying traffic weight toward one service.

    The simulation analogue of the reference's config churner
    (perf/load/templates/config-map.yaml:40-60): an in-cluster
    ``rollout.sh`` rotates VirtualService v1/v2 weights through
    100/70/40/20 forever, producing steady-state control-plane churn
    that actually shifts traffic.  Here every call targeting
    ``service`` has its send probability multiplied by
    ``weights[floor(t / period_s) mod len(weights)]`` — model a canary
    as two services (v1/v2) with complementary weight schedules.
    """

    service: str
    period_s: float
    weights: "tuple[float, ...]"

    def __post_init__(self):
        if self.period_s <= 0:
            raise ValueError("churn period_s must be positive")
        if not self.weights:
            raise ValueError("churn weights must be non-empty")
        if any(not 0.0 <= w <= 1.0 for w in self.weights):
            raise ValueError("churn weights must lie in [0, 1]")
        object.__setattr__(self, "weights", tuple(self.weights))

    @property
    def mean_weight(self) -> float:
        return sum(self.weights) / len(self.weights)


@dataclasses.dataclass(frozen=True)
class MtlsSchedule:
    """Time-phased per-edge mTLS tax.

    The simulation analogue of the reference's auto-mTLS scale test
    (perf/load/auto-mtls/scale.py:1-130): istio-sidecar and legacy
    deployments are alternately scaled so the share of connections
    paying the mTLS handshake flips over time, exercising istiod's
    auto-mTLS switching.  Here the *data-plane consequence* is modeled
    directly: every edge's one-way wire latency gains
    ``taxes_s[floor(t / period_s) mod len(taxes_s)]`` at the request's
    arrival time — e.g. ``taxes_s=(0.0, 1e-3)`` alternates the tax off
    and on each period, and a mixed-fleet phase is a fractional tax.
    The tax is pure latency (the handshake burns proxy CPU, not
    service CPU), so offered-load/queueing tables are unaffected —
    matching how the sidecar-mode environments model proxies.
    """

    period_s: float
    taxes_s: "tuple[float, ...]"

    def __post_init__(self):
        if self.period_s <= 0:
            raise ValueError("mtls period_s must be positive")
        if not self.taxes_s:
            raise ValueError("mtls taxes_s must be non-empty")
        if any(x < 0 for x in self.taxes_s):
            raise ValueError("mtls taxes must be >= 0")
        object.__setattr__(self, "taxes_s", tuple(self.taxes_s))


OPEN_LOOP = "open"
CLOSED_LOOP = "closed"


@dataclasses.dataclass(frozen=True)
class LoadModel:
    """The client side of the experiment.

    - ``open``: Poisson arrivals at ``qps`` (Nighthawk's open-loop mode,
      runner.py:270-316) — arrival times are independent of latencies.
    - ``closed``: ``connections`` workers each issue requests serially,
      pacing to ``qps`` overall when it is finite (Fortio's default
      closed-loop mode, runner.py:255-268; ``qps=None`` is Fortio's
      ``-qps max``).
    """

    kind: str = OPEN_LOOP
    qps: float | None = 1000.0
    connections: int = 64
    duration_s: float = 240.0

    def __post_init__(self):
        if self.kind not in (OPEN_LOOP, CLOSED_LOOP):
            raise ValueError(f"unknown load model kind: {self.kind!r}")
        if self.kind == OPEN_LOOP and (self.qps is None or self.qps <= 0):
            raise ValueError("open-loop load requires a positive qps")
        if self.qps is not None and self.qps <= 0:
            raise ValueError("qps must be positive (or None for max)")
        if self.connections <= 0:
            raise ValueError("connections must be positive")


@dataclasses.dataclass(frozen=True)
class DesignParam:
    """One registered design knob for the gradient audit (VET-G rules).

    A knob either enters the traced program through named member-body
    invars (``invars`` — names from
    :data:`~isotope_tpu.analysis.grad_audit.GRAD_INVARS`, the ten
    traced arguments of the engine's universal member scan), or it is
    baked into the jaxpr at build time (``invars`` empty,
    ``constant_site`` says where) — the recompile-per-value population
    problem from the config-search residuals.  ``partial`` notes knobs
    that are only partly traced (the rest rides as constants)."""

    name: str
    doc: str
    invars: tuple = ()
    constant_site: str = ""
    partial: str = ""

    @property
    def traced(self) -> bool:
        return bool(self.invars)


#: every design parameter the gradient audit classifies.  Order is the
#: report order; names are stable API (tests/data pins key on them).
DESIGN_PARAMS: tuple = (
    DesignParam(
        "qps_scale",
        "offered-load scale: the open-loop arrival rate / closed-loop "
        "pacing gap the planner would sweep",
        invars=("offered_qps", "pace_gap", "nominal_gap"),
    ),
    DesignParam(
        "cpu_time_s",
        "per-service mean service time (the cpu_scale jitter scale "
        "multiplies every sampled service time and the utilization "
        "denominator)",
        invars=("cpu_scale",),
    ),
    DesignParam(
        "error_rate_scale",
        "per-service 5xx error-rate scale (the err_scale jitter scale "
        "multiplies every hop's errorRate before the 5xx coin)",
        invars=("err_scale",),
    ),
    DesignParam(
        "traffic_split_weights",
        "traffic-split / canary phase weights, as the per-phase visit "
        "vectors the closed-form solver bakes from them",
        invars=("visits_pc",),
        partial="per-hop churn send-coin thresholds stay baked "
                "constants (engine._churn_weights)",
    ),
    DesignParam(
        "timeout_ladder",
        "per-call deadline ladder",
        constant_site="compiled.call_timeout (per-hop f32 table baked "
                      "at compile time)",
    ),
    DesignParam(
        "retry_budgets",
        "per-call retry counts and per-service retry budgets",
        constant_site="compiled.hop_attempt unroll + "
                      "policies.device_tables retry_budget",
    ),
    DesignParam(
        "breaker_caps",
        "circuit-breaker max_pending / max_connections caps",
        constant_site="policies.device_tables breaker columns",
    ),
    DesignParam(
        "hpa_targets",
        "autoscaler target_utilization / min / max replicas",
        constant_site="policies.device_tables autoscaler columns",
    ),
    DesignParam(
        "canary_step_weights",
        "rollout step schedule weights and bake durations",
        constant_site="rollout.device_tables step/bake rows",
    ),
    DesignParam(
        "lb_choices_d",
        "load-balancer power-of-d choices_d and panic thresholds",
        constant_site="policies lb tables (choices_d, panic_threshold)",
    ),
)
