"""Importance splitting: rare-outage probability estimation.

Plain Monte Carlo cannot resolve the 1-in-10^5 outage tails resilience
engineering cares about — a 32-member fleet of a p = 1e-4 event sees
zero violations almost always, and the Wilson interval degenerates to
``[0, upper]``.  This module implements a multilevel splitting /
RESTART-style estimator (Au & Beck subset simulation) over the fleet's
RNG: run a short-horizon fleet, rank members by a severity statistic
from the recorder windows, clone-and-continue the worst quantile with
re-folded keys across K levels — one fleet dispatch (one jitted
program) per level — and combine the level conditionals into a
rare-event probability with a variance estimate.

The randomness of a fleet member decomposes into independent
components the proposal kernel can resample separately:

- the CHAOS seeds — ONE PER CHAOS EVENT, driving that event's
  jittered timing / target / magnitude (resilience/faults.py
  ``ChaosJitterSpec``), the components that usually *cause* an
  outage;
- the WORK seed — the workload RNG (arrival gaps, error coins, wait
  draws).

Each level ``l`` conditions on ``severity >= T_l`` (the survivor
quantile of the previous level).  Survivors seed one
Metropolis-with-prior-proposal step per clone: the proposal redraws
each chaos component independently with probability ``chaos_prob``
and the work seed with probability ``work_prob``; it is accepted iff
its severity clears ``T_l``, otherwise the clone keeps its parent's
draw.  Because the proposal IS the prior restricted component-wise,
the acceptance test alone leaves the conditional distribution
invariant — no likelihood ratios needed.  Mixing depends on the
component COUNT (a one-component chain can only jump or stay, and a
population of stuck chains biases the level quantiles); per-event
chaos seeds are what make the kernel local enough to climb.

The product estimator ``p = prod_l p_l * p_final`` is consistent; the
reported variance uses the independence approximation
``cv^2 ~= sum_l (1 - p_l) / (p_l N)`` (it understates the true
variance when chains correlate — stated, like every CPU-era constant
in this repo).  A COMMON event (p >= keep at level 0) short-circuits
to the plain Monte Carlo estimate, so the splitting path never does
worse than the fleet it started from.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np

from isotope_tpu.sim.ensemble import wilson_interval

#: the splitting block's schema key inside ``<label>.ensemble.json``
#: (isotope-ensemble/v2)
SPLIT_SCHEMA = "isotope-splitting/v1"

#: severity statistics the estimator can rank members by
#: ("trips" ranks PROTECTED fleets by breaker-trip + budget-ejection
#: events — the severity channel protected search brackets screen on)
SEVERITIES = ("err_peak", "err_share", "p99", "trips")


@dataclasses.dataclass(frozen=True)
class SplitSpec:
    """One splitting estimate's configuration.

    ``threshold`` defines the rare event (``severity >= threshold``);
    ``keep`` is the survivor fraction per level (the level quantile is
    ``1 - keep``); ``members`` is the fleet width per level, so the
    total simulation budget is at most ``levels * members`` member
    runs; ``horizon`` scales the per-member request count (splitting
    fleets are screening fleets — a short horizon ranks severity
    almost as well as the full run at a fraction of the cost);
    ``chaos_prob`` / ``work_prob`` are the proposal's per-component
    redraw probabilities.
    """

    levels: int = 4
    members: int = 64
    keep: float = 0.25
    threshold: float = 0.5
    severity: str = "err_peak"
    horizon: float = 0.25
    slo_s: Optional[float] = None   # the p99 severity's latency unit
    chaos_prob: float = 0.5
    work_prob: float = 0.5
    seed: int = 0

    def __post_init__(self):
        if self.levels < 1:
            raise ValueError("splitting levels must be >= 1")
        if self.members < 2:
            raise ValueError("splitting members must be >= 2")
        if not 0.0 < self.keep < 1.0:
            raise ValueError(
                "splitting keep (survivor fraction) must lie in (0, 1)"
            )
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"unknown splitting severity {self.severity!r} "
                f"(one of {SEVERITIES})"
            )
        if not 0.0 < self.horizon <= 1.0:
            raise ValueError("splitting horizon must lie in (0, 1]")
        if not 0.0 <= self.chaos_prob <= 1.0:
            raise ValueError("splitting chaos_prob must lie in [0, 1]")
        if not 0.0 <= self.work_prob <= 1.0:
            raise ValueError("splitting work_prob must lie in [0, 1]")

    @property
    def budget(self) -> int:
        """The worst-case member-run budget of one estimate."""
        return self.levels * self.members

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_split_spec(text: Optional[str]) -> Optional[SplitSpec]:
    """Parse the CLI/TOML spec, e.g.
    ``"levels=4,members=64,keep=0.25,threshold=0.5,sev=err_peak"``.
    ``"off"`` / empty returns None.  Unknown keys are errors — a
    typo'd knob must not silently run the defaults."""
    if not text or str(text).strip().lower() in ("off", "0", "false"):
        return None
    kw: dict = {}
    keys = {
        "levels": ("levels", int),
        "members": ("members", int),
        "keep": ("keep", float),
        "threshold": ("threshold", float),
        "sev": ("severity", str),
        "severity": ("severity", str),
        "horizon": ("horizon", float),
        "slo": ("slo_s", float),
        "chaos_prob": ("chaos_prob", float),
        "work_prob": ("work_prob", float),
        "seed": ("seed", int),
    }
    for part in str(text).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad splitting spec entry {part!r} (expected "
                f"key=value; keys: {', '.join(sorted(keys))})"
            )
        k, v = part.split("=", 1)
        k = k.strip().lower()
        if k not in keys:
            raise ValueError(
                f"unknown splitting spec key {k!r} (expected one of "
                f"{', '.join(sorted(keys))})"
            )
        name, conv = keys[k]
        kw[name] = conv(v.strip())
    return SplitSpec(**kw)


class _Draws:
    """One level's population: chaos (N, C) + work (N,) seed arrays."""

    def __init__(self, chaos: np.ndarray, work: np.ndarray):
        self.chaos = np.asarray(chaos, np.int64)
        self.work = np.asarray(work, np.int64)

    def take(self, idx) -> "_Draws":
        return _Draws(self.chaos[idx], self.work[idx])


def _fresh(rng: np.random.Generator, shape) -> np.ndarray:
    # 31-bit positive seeds: safe through jax fold_in uint32 and json
    return rng.integers(1, 2**31 - 1, size=shape, dtype=np.int64)


def subset_estimate(
    evaluate: Callable[[np.ndarray, np.ndarray], np.ndarray],
    spec: SplitSpec,
    chaos_components: int = 1,
) -> dict:
    """Estimate ``P(severity >= spec.threshold)`` by subset simulation.

    ``evaluate(chaos_seeds, work_seeds) -> severities`` runs one fleet
    of ``N = spec.members`` members (``chaos_seeds`` is ``(N, C)``
    with one column per chaos component, ``work_seeds`` ``(N,)``) and
    returns their severity scores — ONE call per level, so the engine
    backs it with one jitted fleet dispatch per level.  Deterministic
    given ``spec.seed``.

    Returns the ``isotope-splitting/v1`` dict: ``p`` (the estimate),
    ``cv`` / ``ci_lo`` / ``ci_hi`` (delta-method, independence
    approximation), per-level records, and the member-run budget
    actually spent.
    """
    rng = np.random.default_rng(np.random.SeedSequence([spec.seed]))
    N = spec.members
    C = max(int(chaos_components), 1)
    draws = _Draws(_fresh(rng, (N, C)), _fresh(rng, N))
    sev = np.asarray(evaluate(draws.chaos, draws.work), np.float64)
    if sev.shape != (N,):
        raise ValueError(
            f"evaluate returned shape {sev.shape}, expected ({N},)"
        )
    evals = N
    levels = []
    log_p = 0.0
    cv2 = 0.0
    p_final = None
    for level in range(spec.levels):
        above = float((sev >= spec.threshold).mean())
        last = level == spec.levels - 1
        # the intermediate threshold: the survivor quantile, clamped
        # at the target — once the population reaches the event, the
        # remaining fraction is the final conditional
        T = float(np.quantile(sev, 1.0 - spec.keep))
        if above >= spec.keep or T >= spec.threshold or last:
            p_final = above
            levels.append({
                "level": level, "threshold": spec.threshold,
                "p_level": above, "final": True,
            })
            break
        surv = np.nonzero(sev >= T)[0]
        if len(surv) == 0:  # degenerate population (constant severity)
            p_final = 0.0
            levels.append({
                "level": level, "threshold": T,
                "p_level": 0.0, "final": True,
            })
            break
        p_l = len(surv) / N
        levels.append({
            "level": level, "threshold": T, "p_level": p_l,
            "final": False,
        })
        log_p += float(np.log(p_l))
        cv2 += (1.0 - p_l) / (p_l * N)
        # clone-and-continue: survivors cycle over the N slots, each
        # clone takes one Metropolis step with the component-wise
        # prior proposal (re-folded keys)
        slot = surv[np.arange(N) % len(surv)]
        parents = draws.take(slot)
        sev_par = sev[slot]
        prop = _Draws(
            np.where(
                rng.random((N, C)) < spec.chaos_prob,
                _fresh(rng, (N, C)), parents.chaos,
            ),
            np.where(
                rng.random(N) < spec.work_prob,
                _fresh(rng, N), parents.work,
            ),
        )
        sev_prop = np.asarray(
            evaluate(prop.chaos, prop.work), np.float64
        )
        evals += N
        accept = sev_prop >= T
        draws = _Draws(
            np.where(accept[:, None], prop.chaos, parents.chaos),
            np.where(accept, prop.work, parents.work),
        )
        sev = np.where(accept, sev_prop, sev_par)
    assert p_final is not None
    if p_final > 0.0:
        p = float(np.exp(log_p) * p_final)
        cv2 += (1.0 - p_final) / (p_final * N)
    else:
        p = 0.0
    cv = float(np.sqrt(cv2)) if p > 0 else 0.0
    # lognormal-shaped CI: multiplicative error keeps the bound
    # positive where the rare estimate sits orders below 1
    z = 1.959963984540054  # norm_ppf(0.975)
    ci = (
        (p * np.exp(-z * cv), min(1.0, p * np.exp(z * cv)))
        if p > 0
        else (0.0, wilson_interval(0, spec.budget)[1])
    )
    return {
        "schema": SPLIT_SCHEMA,
        "spec": spec.to_dict(),
        "p": p,
        "cv": cv,
        "ci_lo": float(ci[0]),
        "ci_hi": float(ci[1]),
        "levels": levels,
        "evaluations": int(evals),
        "accept_note": (
            "variance assumes independent level samples; correlated "
            "clone chains understate it"
        ),
    }


# -- severity statistics ------------------------------------------------------


def severity_scores(
    spec: SplitSpec,
    summaries,
    timelines=None,
    policies=None,
) -> np.ndarray:
    """Per-member severity from a fleet's stacked outputs.

    - ``err_peak``: the PEAK per-window client error share from the
      recorder windows (``timelines``; the statistic that sees a
      transient outage a run-long average dilutes); falls back to
      ``err_share`` when no timeline rode the fleet;
    - ``err_share``: the run-long client error share;
    - ``p99``: the member's p99 latency in units of ``spec.slo_s``
      (severity 1.0 == exactly at the SLO — "SLO-violation depth");
    - ``trips``: breaker trips + retry-budget ejections summed over
      services from the stacked ``PolicySummary`` (``policies``) —
      the control-plane severity of a PROTECTED fleet; falls back to
      ``err_share`` when no policy summary rode the fleet.
    """
    if spec.severity == "trips" and policies is not None:
        trips = np.asarray(policies.trips, np.float64)       # (N, S)
        ej = np.asarray(policies.ejections, np.float64)      # (N, S)
        return trips.sum(axis=-1) + ej.sum(axis=-1)
    if spec.severity == "p99":
        if spec.slo_s is None or spec.slo_s <= 0:
            raise ValueError(
                "p99 splitting severity needs slo= (the latency that "
                "maps to severity 1.0)"
            )
        from isotope_tpu.metrics.histogram import quantile_from_histogram

        hists = np.asarray(summaries.latency_hist, np.float64)
        p99 = np.asarray([
            quantile_from_histogram(h, (0.99,))[0] for h in hists
        ])
        return p99 / float(spec.slo_s)
    if spec.severity == "err_peak" and timelines is not None:
        arr = np.asarray(timelines.arrivals, np.float64)   # (N, W)
        err = np.asarray(timelines.errors, np.float64)     # (N, W)
        share = err / np.maximum(arr, 1.0)
        return share.max(axis=1)
    counts = np.asarray(summaries.count, np.float64)
    errs = np.asarray(summaries.error_count, np.float64)
    return errs / np.maximum(counts, 1.0)


def severity_scores_device(
    severity: str,
    summaries,
    slo_s=None,
    policies=None,
):
    """On-device twin of :func:`severity_scores` over a member-stacked
    fleet summary — the rank channel of the search brackets
    (sim/search.py), where the scores feed a ``lexsort`` + gather
    WITHOUT leaving the device.

    Same channel semantics: ``p99`` is SLO-violation depth via the
    device histogram-quantile twin; ``err_share`` is the run-long
    client error share; ``err_peak`` falls back to ``err_share``
    exactly like the host function does when no recorder timeline
    rode the fleet (search fleets carry none — VET-T026 warns at the
    spec layer); ``trips`` sums breaker trips + budget ejections from
    the stacked ``PolicySummary`` (``policies`` — the protected
    bracket's rank channel), falling back to ``err_share`` on plain
    fleets.  Every bracket path (solo, sharded, emulated) ranks
    through THIS function, so severities — and therefore survivor
    lineages — are bit-identical across them.
    """
    import jax.numpy as jnp

    if severity == "trips" and policies is not None:
        trips = jnp.asarray(policies.trips, jnp.float32)
        ej = jnp.asarray(policies.ejections, jnp.float32)
        return trips.sum(axis=-1) + ej.sum(axis=-1)
    if severity == "p99":
        if slo_s is None or slo_s <= 0:
            raise ValueError(
                "p99 search severity needs slo= (the latency that "
                "maps to severity 1.0)"
            )
        from isotope_tpu.metrics.histogram import (
            quantile_from_histogram_device,
        )

        p99 = quantile_from_histogram_device(
            summaries.latency_hist, 0.99
        )
        return p99 / jnp.float32(slo_s)
    counts = jnp.asarray(summaries.count, jnp.float32)
    errs = jnp.asarray(summaries.error_count, jnp.float32)
    return errs / jnp.maximum(counts, 1.0)
