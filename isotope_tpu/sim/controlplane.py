"""Control-plane config-push convergence model (pilot load test).

The reference's pilot scale test (perf/load/pilot/load_test.py) creates
N ServiceEntries x M endpoints and measures how long until every Envoy's
cluster count reflects them (:33-44 polls config dumps) — convergence
time as a function of config size and fleet size.

The simulation model is the xDS push pipeline as a queueing system:

- a debounce window, then pilot generates the pushed config (cost grows
  with N x M — endpoints dominate memory/CPU);
- pushes fan out to P proxies through a bounded concurrent-push budget
  (istiod's PILOT_PUSH_THROTTLE), each push taking a sampled
  transfer+ACK latency that also grows with config size;
- a proxy has converged when its push ACKs.  Convergence quantiles are
  read off the completion times, vectorized with ``lax.scan`` over the
  push queue (the greedy earliest-free-channel assignment).
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class PilotModel:
    """Pilot/istiod push-pipeline parameters."""

    debounce_s: float = 0.1            # PILOT_DEBOUNCE_AFTER
    push_throttle: int = 100           # concurrent pushes
    gen_s_per_endpoint: float = 2e-6   # config generation CPU
    push_base_s: float = 5e-3          # per-push floor (RTT + ACK)
    push_s_per_endpoint: float = 1e-6  # transfer cost per endpoint
    push_jitter: float = 0.3           # lognormal sigma on push latency

    def __post_init__(self):
        if self.push_throttle <= 0:
            raise ValueError("push_throttle must be positive")


@dataclasses.dataclass(frozen=True)
class ConvergenceResult:
    ack_times_s: np.ndarray  # (P,) per-proxy convergence times

    def quantile_s(self, q) -> np.ndarray:
        return np.quantile(self.ack_times_s, q)

    @property
    def max_s(self) -> float:
        return float(self.ack_times_s.max())

    def converged_fraction(self, t: float) -> float:
        return float((self.ack_times_s <= t).mean())

    def window_series(self, window_s: float, num_windows: int) -> dict:
        """Project the per-proxy ACK times onto a data-plane timeline's
        window axis (metrics/timeline.py) — per-window ACK counts and
        the cumulative converged fraction at each window end — so a
        config-push timeline composes with the flight recorder's
        series on one shared time grid."""
        from isotope_tpu.metrics.timeline import controlplane_windows

        return controlplane_windows(
            self.ack_times_s, window_s, num_windows
        )


def push_convergence(
    model: PilotModel,
    num_entries: int,
    endpoints_per_entry: int,
    num_proxies: int,
    key=None,
) -> ConvergenceResult:
    """Convergence times for one config push to ``num_proxies`` Envoys."""
    if num_proxies <= 0:
        raise ValueError("num_proxies must be positive")
    endpoints = num_entries * endpoints_per_entry
    ready = model.debounce_s + endpoints * model.gen_s_per_endpoint
    mean_push = model.push_base_s + endpoints * model.push_s_per_endpoint

    if key is None:
        key = jax.random.PRNGKey(0)
    sigma = model.push_jitter
    z = jax.random.normal(key, (num_proxies,))
    # lognormal with the configured mean
    durations = mean_push * jnp.exp(sigma * z - 0.5 * sigma * sigma)

    c = min(model.push_throttle, num_proxies)

    def assign(free, dur):
        # greedy: the next push takes the earliest-free channel
        idx = jnp.argmin(free)
        end = jnp.maximum(free[idx], ready) + dur
        return free.at[idx].set(end), end

    free0 = jnp.full((c,), ready, jnp.float32)
    _, acks = jax.lax.scan(assign, free0, durations)
    return ConvergenceResult(
        ack_times_s=np.asarray(acks, np.float64)
    )


def convergence_sweep(
    model: PilotModel,
    entry_counts,
    endpoints_per_entry: int,
    num_proxies: int,
    seed: int = 0,
):
    """The reference test's measurement: convergence vs ServiceEntry
    count (load_test.py's N axis).  Returns rows of p50/p99/max."""
    rows = []
    key = jax.random.PRNGKey(seed)
    for i, n in enumerate(entry_counts):
        res = push_convergence(
            model, n, endpoints_per_entry, num_proxies,
            key=jax.random.fold_in(key, i),
        )
        p50, p99 = res.quantile_s([0.5, 0.99])
        rows.append(
            {
                "num_entries": int(n),
                "endpoints": int(n * endpoints_per_entry),
                "proxies": int(num_proxies),
                "p50_s": float(p50),
                "p99_s": float(p99),
                "max_s": res.max_s,
            }
        )
    return rows
