"""The vectorized event-tree simulation engine.

One jit-compiled tensor program replaces the reference's entire data plane:

- the per-request script interpreter (isotope/service/pkg/srv/handler.go:
  66-76 + executable.go:43-179) becomes two static sweeps over the depth
  levels of the unrolled call tree — an upward pass computing each hop's
  server-side duration (concurrent fan-out joins via scatter-max, the
  vectorized WaitGroup of executable.go:171-175; sequential steps sum,
  handler.go:66) and a downward pass assigning absolute start times;
- Fortio's load loop (perf/benchmark/runner/runner.py:255-268) becomes an
  arrival-time vector: Poisson cumsum for open-loop, per-connection pacing
  cumsum for closed-loop;
- queueing delay at each service is sampled from the analytic M/M/k model
  (see sim/queueing.py) with k = NumReplicas and offered load derived from
  the compile-time expected-visit counts;
- ``errorRate`` — spec'd but never implemented by the reference runtime
  (SURVEY.md §2.7) — is implemented for real: a hop errors with its
  service's probability, returns a fast 500 (skips its script), and sends
  nothing downstream.  Matching executable.go:132-143, a downstream 500
  does NOT fail the caller;
- chaos schedules (the CronJob replica-killers of perf/stability/
  istio-chaos-{partial,total}) become piecewise-stationary queue phases:
  a request samples its waits from the phase its arrival falls in, and a
  fully-down callee produces a *transport* error — which, unlike a 500,
  DOES fail the caller (handler.go:66-76): the caller stops at the failing
  step (concurrent siblings in that step still run, executable.go:148-179)
  and itself returns a 500 upward.

Everything is static-shaped: (num_requests x num_hops) event tensors, RNG
via ``jax.random`` keys.  Depth levels execute through the bucketed
``lax.scan`` executor by default (close-shaped consecutive levels are
padded to shared bounds and swept by one traced body per bucket —
sim/levelscan.py / compiler/buckets.py, trace size O(buckets)); levels
that don't bucket (skewed sparse levels, leaves, geometric trees) keep
their specialized unrolled per-level trace, bit-identical either way.
"""
from __future__ import annotations

import contextlib
import dataclasses
import time
from functools import partial
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from isotope_tpu import telemetry
from isotope_tpu.compiler import buckets
from isotope_tpu.compiler.cache import array_digest, executable_cache
from isotope_tpu.resilience import faults
from isotope_tpu.compiler.program import CompiledGraph, hop_wire_times
from isotope_tpu.sim import levelscan, queueing
from isotope_tpu.sim.config import (
    CLOSED_LOOP,
    OPEN_LOOP,
    SERVICE_TIME_DETERMINISTIC,
    SERVICE_TIME_LOGNORMAL,
    SERVICE_TIME_PARETO,
    ChaosEvent,
    LoadModel,
    MtlsSchedule,
    SimParams,
    TrafficSplit,
)


class SimResults(NamedTuple):
    """Raw per-request / per-hop outcomes of one simulated run.

    Hop axis order is the compiled BFS order (level-concatenated).  All
    times are seconds; ``hop_start`` is when the request *arrives* at the
    service (before queueing), ``hop_latency`` the server-side duration
    (wait + script + cpu) — i.e. what the reference's
    ``service_request_duration_seconds`` histogram observes
    (srv/prometheus/handler.go:57-61).
    """

    client_start: jax.Array    # (N,) client send time
    client_latency: jax.Array  # (N,) client-observed round trip
    client_error: jax.Array    # (N,) bool — entry returned a 500
    hop_sent: jax.Array        # (N, H) bool — hop actually executed
    hop_error: jax.Array       # (N, H) bool — hop returned 500 (where sent)
    hop_latency: jax.Array     # (N, H) f32
    hop_start: jax.Array       # (N, H) f32
    utilization: jax.Array     # (S,) rho per service at the offered load
    unstable: jax.Array        # (S,) bool — offered load >= capacity
    offered_qps: jax.Array     # scalar f32 — the rate the queues saw
    # queueing-wait component of hop_latency — the attribution layer's
    # wait-vs-service split (metrics/attribution.py).  Trailing optional
    # field: consumers that ignore it (summarize) leave the traced
    # program untouched, XLA dead-code-eliminates the alias.
    hop_wait: Optional[jax.Array] = None  # (N, H) f32
    # per-hop version coin of a rollout-actuated block (sim/rollout.py):
    # True where the hop routed to the CANARY arm.  Same trailing-
    # optional discipline as hop_wait — None everywhere rollouts are off.
    hop_canary: Optional[jax.Array] = None  # (N, H) bool
    # hops that WOULD have executed but whose target station was chaos-
    # downed (transport failure charged to that service's arm) — the
    # rollout gates must see a fully-killed canary's refused calls as
    # canary errors even though the hop never ran (hop_sent stays
    # False).  None everywhere rollouts are off.
    hop_refused: Optional[jax.Array] = None  # (N, H) bool

    @property
    def client_end(self) -> jax.Array:
        return self.client_start + self.client_latency

    @property
    def hop_events(self) -> jax.Array:
        """Total executed hops — the benchmark's unit of work."""
        return self.hop_sent.sum()


@dataclasses.dataclass(frozen=True)
class _Level:
    """Device-resident constants for one depth level."""

    offset: int                 # start of this level's slice in hop order
    size: int
    pmax: int
    step_mask: jax.Array        # (L, Pmax) f32 — 1 where a real step
    step_base: jax.Array        # (L, Pmax) f32
    child_seg: jax.Array        # (C,) i32 — parent_local * Pmax + step
    child_parent_local: jax.Array  # (C,) i32
    child_step: jax.Array       # (C,) i32 — step index within the parent
    child_rtt: jax.Array        # (C,) f32 — request + response wire time
    child_net_out: jax.Array    # (C,) f32 — one-way request wire time
    child_send_prob: jax.Array  # (C,) f32
    # call tables (see compiler.program.HopLevel)
    call_seg: jax.Array         # (K,) i32
    call_step: jax.Array        # (K,) i32
    call_timeout: jax.Array     # (K,) f32
    att_child: np.ndarray       # (maxA, K) i32 — static gather indices
    att_valid: np.ndarray       # (maxA, K) bool — static masks
    child_churn_entry: Optional[np.ndarray] = None  # (C,) i32 static
    # -- static structure flags (trace-time specialization) ---------------
    # single-attempt levels where call k's only child is child k: the
    # attempt loop degenerates to elementwise ops (no scatters)
    ident_attempts: bool = False
    # any call with a finite timeout (else timeouts can't fire)
    finite_timeout: bool = False
    # c when call_seg == repeat(arange(size*pmax), c): the per-step
    # aggregation is a reshape-reduce instead of a scatter
    uniform_calls: Optional[int] = None
    # sparse call-slot step encoding (skewed wide levels); None = dense
    sparse: Optional["_SparseSteps"] = None
    # dense-blocked tiling of a skewed wide level (the default sparse
    # mitigation when the level's fan-out classes tile; see
    # _TiledSteps); mutually exclusive with ``sparse``
    tiled: Optional["_TiledSteps"] = None
    # call-free levels: busy time is fully static — (L,) seconds
    leaf_busy: Optional[jax.Array] = None

    @property
    def num_children(self) -> int:
        return len(self.child_seg)

    @property
    def num_calls(self) -> int:
        return len(self.call_seg)

    @property
    def max_attempts(self) -> int:
        return self.att_child.shape[0]


@dataclasses.dataclass(frozen=True)
class _SparseSteps:
    """Call-slot step encoding for skewed wide levels.

    A level's dense step grid is (hops x Pmax_level); on skewed graphs
    (one ~2,000-step hub among thousands of single-step leaves — the
    star-10k archetype) that grid is >100x larger than the number of
    steps that actually exist.  This encoding keeps one dynamic slot
    per CALL-BEARING step only: pure-sleep steps fold into static
    per-hop totals/prefixes, per-hop busy times are packed segment sums
    (cumsum minus segment starts — no (L x P) tensor ever materializes)
    and child start offsets gather static sleep prefixes plus the
    dynamic call prefix at their slot.

    Transport failures (timeouts / chaos downs) are supported without
    ever rebuilding the dense executed-step mask: a transport failure
    can only originate at a CALL-BEARING step, so the first failing
    *slot* of a hop determines its truncation point.  A scatter-min
    over the slot axis yields the per-hop fail slot; slots past it are
    zeroed before the packed prefix sums, the executed pure-sleep part
    comes from a static per-slot sleep prefix, and children past the
    fail step take the parent's truncated busy time as their offset
    (matching the dense grid's flat prefix past the failure).
    """

    n_slots: int
    slot_base: jax.Array          # (S,) sleep floor of each call step
    call_slot: Optional[jax.Array]  # (K,) call -> slot; None == identity
    has_slots: jax.Array          # (L,) bool
    seg_first: jax.Array          # (L,) first slot of the hop (safe 0)
    seg_last: jax.Array           # (L,) last slot of the hop (safe 0)
    sleep_total: jax.Array        # (L,) static pure-sleep busy seconds
    child_sleep_prefix: jax.Array  # (C,) static sleep before child's step
    child_slot: jax.Array         # (C,) slot of the child's step
    child_seg_first: jax.Array    # (C,) first slot of the child's parent
    # -- transport-failure truncation tables (see class docstring) ------
    slot_hop: jax.Array           # (S,) local hop index of each slot
    slot_step: jax.Array          # (S,) step index of each slot
    slot_sleep_prefix: jax.Array  # (S,) static sleep before the slot


@dataclasses.dataclass(frozen=True)
class _Tile:
    """One dense sub-grid of a tiled sparse level (see _TiledSteps).

    ``hops`` / ``call_sel`` / ``child_sel`` are static selections into
    the LEVEL's local hop / call / child orders; the step tables are
    the level's rows restricted to the tile's hops and truncated to the
    tile width, so the per-tile census ops are the dense grid's ops on
    exactly those rows — bit-identical in eager.
    """

    hops: np.ndarray              # (T,) level-local hop indices, sorted
    width: int                    # W — padded step width of the bin
    step_mask: jax.Array          # (T, W) f32
    step_base: jax.Array          # (T, W) f32
    call_sel: np.ndarray          # (Kt,) indices into level call order
    call_pos: jax.Array           # (Kt,) parent position within tile
    call_step: jax.Array          # (Kt,) step index within the parent
    call_seg: jax.Array           # (Kt,) call_pos * W + call_step
    child_sel: np.ndarray         # (Ct,) indices into level child order
    child_pos: jax.Array          # (Ct,) parent position within tile
    child_step: jax.Array         # (Ct,)
    uniform_calls: Optional[int]  # c when call_seg == repeat(arange, c)


@dataclasses.dataclass(frozen=True)
class _TiledSteps:
    """Dense-blocked encoding of a skewed wide level.

    The dense (hops x Pmax) grid the sparse encoding avoids is instead
    PARTITIONED: hops are binned by script-width class into fixed-width
    tiles (compiler/buckets.plan_tiles) and each tile runs the exact
    dense step-grid ops restricted to its rows; only scripts wider than
    the tile cap keep the true sparse call-slot encoding as a
    ``residual``.  Per-part busy/fail/off vectors are re-assembled into
    level order by the static ``hop_inv`` / ``child_inv`` gathers.

    star-10k shape: 9,999 single-step spokes collapse into one
    (9999 x 1) tile — pure dense elementwise work — while the ~2,000-
    step hub stays on the sparse residual, instead of one 10k-slot
    serial gather/cumsum chain covering every hop.
    """

    tiles: Tuple[_Tile, ...]
    residual: Optional[_SparseSteps]     # over residual hops only
    res_hops: Optional[np.ndarray]       # (R,) level-local indices
    res_call_sel: Optional[np.ndarray]   # (Kr,) level call order indices
    res_child_sel: Optional[np.ndarray]  # (Cr,)
    res_child_pos: Optional[jax.Array]   # (Cr,) parent pos among residual
    res_child_step: Optional[jax.Array]  # (Cr,)
    hop_inv: np.ndarray                  # (L,) concat order -> level order
    child_inv: np.ndarray                # (C,) concat order -> level order
    elems: int                           # tile + residual element count


def _sparse_tables(
    num_hops: int,
    pmax: int,
    sleep_real: np.ndarray,      # (L, >=pmax) f64 — step_is_real * base
    step_base: np.ndarray,       # (L, >=pmax)
    call_seg_p: np.ndarray,      # (K,) parent_local * pmax + step
    parent_local: np.ndarray,    # (C,)
    child_step: np.ndarray,      # (C,)
) -> _SparseSteps:
    """Build the sparse call-slot tables for one (possibly restricted)
    hop set — shared by the pure sparse encoding and a tiled level's
    residual part (inputs already renumbered to the restricted order)."""
    slot_segs = np.unique(call_seg_p)  # sorted
    n_slots = len(slot_segs)
    n_calls = len(call_seg_p)
    slot_hop = slot_segs // pmax
    slot_step = slot_segs % pmax
    call_slot_np = np.searchsorted(slot_segs, call_seg_p)
    seg_first = np.zeros(num_hops, np.int64)
    seg_last = np.zeros(num_hops, np.int64)
    has = np.zeros(num_hops, bool)
    for i, h in enumerate(slot_hop):
        if not has[h]:
            seg_first[h] = i
            has[h] = True
        seg_last[h] = i
    has_call_step = np.zeros((num_hops, pmax), bool)
    has_call_step[slot_hop, slot_step] = True
    sleep_only = sleep_real[:, :pmax] * ~has_call_step
    sleep_prefix = np.cumsum(sleep_only, 1) - sleep_only
    child_sleep_prefix = sleep_prefix[parent_local, child_step]
    child_slot_np = np.searchsorted(
        slot_segs, parent_local * pmax + child_step
    )
    return _SparseSteps(
        n_slots=n_slots,
        slot_base=jnp.asarray(
            step_base[slot_hop, slot_step], jnp.float32
        ),
        call_slot=(
            None
            if np.array_equal(
                call_slot_np, np.arange(n_calls, dtype=np.int64)
            )
            else jnp.asarray(call_slot_np, jnp.int32)
        ),
        has_slots=jnp.asarray(has),
        seg_first=jnp.asarray(seg_first, jnp.int32),
        seg_last=jnp.asarray(seg_last, jnp.int32),
        sleep_total=jnp.asarray(sleep_only.sum(1), jnp.float32),
        child_sleep_prefix=jnp.asarray(
            child_sleep_prefix, jnp.float32
        ),
        child_slot=jnp.asarray(child_slot_np, jnp.int32),
        child_seg_first=jnp.asarray(
            seg_first[parent_local], jnp.int32
        ),
        slot_hop=jnp.asarray(slot_hop, jnp.int32),
        slot_step=jnp.asarray(slot_step, jnp.int32),
        slot_sleep_prefix=jnp.asarray(
            sleep_prefix[slot_hop, slot_step], jnp.float32
        ),
    )


def _build_tiled_steps(
    plan,                        # buckets.TilePlan
    pmax: int,
    step_is_real: np.ndarray,    # (L, >=pmax) bool
    step_base: np.ndarray,       # (L, >=pmax)
    sleep_real: np.ndarray,      # (L, >=pmax) f64
    call_seg_p: np.ndarray,      # (K,)
    parent_local: np.ndarray,    # (C,)
    child_step: np.ndarray,      # (C,)
) -> _TiledSteps:
    """Lower one level's tile plan into device constants."""
    call_parent = call_seg_p // pmax
    call_step_all = call_seg_p % pmax
    tiles: List[_Tile] = []
    hop_parts: List[np.ndarray] = []
    child_parts: List[np.ndarray] = []
    elems = 0
    # one-pass hop -> part map: selecting each part's calls/children is
    # then a vectorized compare instead of repeated np.isin (the
    # lowering is host-side but svc100k-sized levels feel O(T * K log))
    num_hops_total = (
        max(int(call_parent.max(initial=-1)),
            int(parent_local.max(initial=-1)),
            max((int(idx.max(initial=-1)) for _, idx in plan.tiles),
                default=-1),
            int(plan.residual.max(initial=-1)))
        + 1
    )
    part_of_hop = np.full(num_hops_total, -1, np.int64)
    for ti, (_, hop_idx) in enumerate(plan.tiles):
        part_of_hop[hop_idx] = ti
    if len(plan.residual):
        part_of_hop[plan.residual] = len(plan.tiles)
    part_of_call = part_of_hop[call_parent]
    part_of_child = part_of_hop[parent_local]
    for ti, (w, hop_idx) in enumerate(plan.tiles):
        w = int(w)
        call_sel = np.nonzero(part_of_call == ti)[0]
        call_pos = np.searchsorted(hop_idx, call_parent[call_sel])
        cstep = call_step_all[call_sel]
        call_seg_t = call_pos * w + cstep
        child_sel = np.nonzero(part_of_child == ti)[0]
        child_pos = np.searchsorted(hop_idx, parent_local[child_sel])
        slots_t = len(hop_idx) * w
        uniform: Optional[int] = None
        if len(call_sel) > 0 and len(call_sel) % slots_t == 0:
            c = len(call_sel) // slots_t
            if np.array_equal(
                call_seg_t, np.repeat(np.arange(slots_t), c)
            ):
                uniform = c
        tiles.append(_Tile(
            hops=hop_idx,
            width=w,
            step_mask=jnp.asarray(
                step_is_real[hop_idx][:, :w], jnp.float32
            ),
            step_base=jnp.asarray(step_base[hop_idx][:, :w]),
            call_sel=call_sel,
            call_pos=jnp.asarray(call_pos, jnp.int32),
            call_step=jnp.asarray(cstep, jnp.int32),
            call_seg=jnp.asarray(call_seg_t, jnp.int32),
            child_sel=child_sel,
            child_pos=jnp.asarray(child_pos, jnp.int32),
            child_step=jnp.asarray(child_step[child_sel], jnp.int32),
            uniform_calls=uniform,
        ))
        hop_parts.append(hop_idx)
        child_parts.append(child_sel)
        elems += len(hop_idx) * w
    residual = None
    res_hops = res_call_sel = res_child_sel = None
    res_child_pos = res_child_step = None
    if len(plan.residual):
        res_hops = plan.residual
        res_part = len(plan.tiles)
        res_call_sel = np.nonzero(part_of_call == res_part)[0]
        call_pos_r = np.searchsorted(res_hops, call_parent[res_call_sel])
        call_seg_r = call_pos_r * pmax + call_step_all[res_call_sel]
        res_child_sel = np.nonzero(part_of_child == res_part)[0]
        parent_r = np.searchsorted(
            res_hops, parent_local[res_child_sel]
        )
        child_step_r = child_step[res_child_sel]
        residual = _sparse_tables(
            len(res_hops), pmax,
            sleep_real[res_hops], step_base[res_hops],
            call_seg_r, parent_r, child_step_r,
        )
        res_child_pos = jnp.asarray(parent_r, jnp.int32)
        res_child_step = jnp.asarray(child_step_r, jnp.int32)
        hop_parts.append(res_hops)
        child_parts.append(res_child_sel)
        elems += residual.n_slots
    hop_order = np.concatenate(hop_parts) if hop_parts else np.zeros(
        0, np.int64
    )
    child_order = (
        np.concatenate(child_parts)
        if child_parts
        else np.zeros(0, np.int64)
    )
    return _TiledSteps(
        tiles=tuple(tiles),
        residual=residual,
        res_hops=res_hops,
        res_call_sel=res_call_sel,
        res_child_sel=res_child_sel,
        res_child_pos=res_child_pos,
        res_child_step=res_child_step,
        hop_inv=np.argsort(hop_order),
        child_inv=np.argsort(child_order),
        elems=int(elems),
    )


def _sparse_level_sweep(
    sp: _SparseSteps,
    n: int,
    P: int,
    size: int,
    dur_call: jax.Array,
    final_transport: Optional[jax.Array],
    err_par: Optional[jax.Array],       # (n, size) parent 500 coins
    child_parent_local: jax.Array,      # (C,) parent index in [0, size)
    child_step: jax.Array,              # (C,)
):
    """The sparse call-slot sweep over one hop set.

    Returns ``(busy, fail_step, off)`` — per-hop busy seconds (NOT yet
    500-zeroed; the level tail applies the err mask), the per-hop fail
    step (sentinel ``P`` = no transport failure; ``None`` when none can
    occur), and per-child start offsets (fail- and err-adjusted, before
    any retry att_off addition).  Shared by the pure sparse encoding
    and a tiled level's residual part — inputs come pre-restricted.

    Transport failures truncate via the per-slot fail scatter-min: a
    failure can only originate at a call-bearing step, so the first
    failing slot pins the hop's fail step exactly as the dense
    executed-step mask would.
    """
    S = sp.n_slots
    fail_step = None
    if S == 0:
        # call-free hop set (pure-sleep scripts wider than the tile
        # cap): busy is fully static, nothing can transport-fail, and
        # there are no children to offset
        busy = jnp.broadcast_to(sp.sleep_total, (n, size))
        off = jnp.zeros((n, child_step.shape[0]))
        return busy, None, off
    if sp.call_slot is None:
        slot_agg = dur_call
        slot_fail = final_transport
    else:
        slot_agg = (
            jnp.zeros((n, S))
            .at[:, sp.call_slot]
            .max(dur_call)
        )
        slot_fail = (
            jnp.zeros((n, S), bool)
            .at[:, sp.call_slot]
            .max(final_transport)
            if final_transport is not None
            else None
        )
    dyn = jnp.maximum(sp.slot_base, slot_agg)
    if slot_fail is not None:
        fail_slot = (
            jnp.full((n, size), S, jnp.int32)
            .at[:, sp.slot_hop]
            .min(
                jnp.where(
                    slot_fail,
                    jnp.arange(S, dtype=jnp.int32),
                    S,
                )
            )
        )
        failed = fail_slot < S
        safe = jnp.minimum(fail_slot, S - 1)
        fail_step = jnp.where(failed, sp.slot_step[safe], P)
        # slots past the hop's fail step don't execute
        dyn = jnp.where(
            sp.slot_step[None, :] <= fail_step[:, sp.slot_hop],
            dyn,
            0.0,
        )
        sleep_exec = jnp.where(
            failed, sp.slot_sleep_prefix[safe], sp.sleep_total,
        )
    else:
        sleep_exec = sp.sleep_total
    pcs = jnp.cumsum(dyn, axis=1)
    excl = pcs - dyn
    seg_sum = jnp.where(
        sp.has_slots,
        pcs[:, sp.seg_last] - excl[:, sp.seg_first],
        0.0,
    )
    busy = sleep_exec + seg_sum
    off = (
        sp.child_sleep_prefix
        + excl[:, sp.child_slot]
        - excl[:, sp.child_seg_first]
    )
    if fail_step is not None:
        # children past the fail step aren't sent; the dense grid's
        # prefix is flat there (== the truncated busy time) — match it
        off = jnp.where(
            child_step <= fail_step[:, child_parent_local],
            off,
            busy[:, child_parent_local],
        )
    if err_par is not None:
        # a 500ing parent runs no steps (dense zeroes the grid before
        # the prefix — match exactly)
        off = off * ~err_par[:, child_parent_local]
    return busy, fail_step, off


# one definition serves both executors: the scan twin's bit-for-bit
# contract requires the attempt-outcome ops to stay in exact lockstep
_call_outcome = levelscan.call_outcome


_FOLD_MEMBER_KEYS = None


def _fold_member_keys():
    """Cached jitted member-key derivation: fold_in vmapped over the
    fleet's seeds.  Eagerly the vmap re-traces on every fleet build;
    screening brackets build fleets in a hot loop."""
    global _FOLD_MEMBER_KEYS
    if _FOLD_MEMBER_KEYS is None:
        _FOLD_MEMBER_KEYS = jax.jit(
            lambda key, seeds: jax.vmap(
                lambda s: jax.random.fold_in(key, s)
            )(seeds)
        )
    return _FOLD_MEMBER_KEYS


class Simulator:
    """Holds a compiled graph's device constants and jitted entry points."""

    def __init__(
        self,
        compiled: CompiledGraph,
        params: SimParams = SimParams(),
        chaos: Sequence[ChaosEvent] = (),
        churn: Sequence[TrafficSplit] = (),
        mtls: Optional[MtlsSchedule] = None,
        policies=None,  # Optional[policies.PolicyTables]
        rollouts=None,  # Optional[rollout.RolloutTables]
        lb=None,  # Optional[lb.LbTables]
    ):
        # engine.build covers everything below: device-constant upload,
        # bucket planning, copula tables — the host-side cost a compile
        # report should show next to trace/lower/backend seconds
        telemetry.install_jax_hooks()
        faults.check("engine.build")
        _t_build = time.perf_counter()
        self.compiled = compiled
        self.params = params
        # auto-mTLS switching: a time-phased extra one-way latency on
        # every edge, indexed by the request's (nominal) arrival time —
        # pure wire tax, so queueing tables are untouched (see
        # config.MtlsSchedule)
        self._mtls = mtls
        if mtls is not None:
            self._mtls_taxes = jnp.asarray(mtls.taxes_s, jnp.float32)
        if params.attribution and mtls is not None:
            # the phased mTLS tax is indexed by each request's NOMINAL
            # arrival, which the assembled SimResults does not carry —
            # the blame sweep could not reproduce the per-edge tax
            # exactly, silently shifting wire blame into self blame
            raise ValueError(
                "SimParams.attribution does not support MtlsSchedule "
                "runs yet (the per-request tax is not recoverable from "
                "the assembled results)"
            )
        self._attr_tables = None  # built lazily on first attributed run
        t = compiled.services
        net = params.network

        # -- in-graph resilience policies (sim/policies.py) ----------------
        # Compiled per-service tables for the breaker / retry-budget /
        # autoscaler co-sim.  ``None`` (the default) leaves EVERY traced
        # program byte-identical — all policy effects below gate on it.
        self._policies = policies
        self._has_retries = any(
            lvl.att_child.shape[0] > 1 for lvl in compiled.levels
        )
        self._k_max = int(t.replicas.max())
        if policies is not None:
            # the autoscaler can grow stations past the static replica
            # max; the Erlang recursion length must cover the widest
            # station the dynamic wait law can reach
            self._k_max = max(self._k_max, policies.k_max)
        # -- reactive canary rollouts (sim/rollout.py) ---------------------
        # Compiled per-service step schedules + canary-arm physics
        # overrides.  ``None`` (the default) keeps every traced program
        # byte-identical — all rollout effects below gate on it.
        self._rollouts = rollouts
        if rollouts is not None:
            if mtls is not None:
                # the canary wait selection composes per-request; the
                # phased mTLS tax is orthogonal but untested together —
                # reject loudly rather than silently mis-taxing an arm
                raise ValueError(
                    "rollout runs do not support MtlsSchedule yet"
                )
            self._k_max = max(self._k_max, rollouts.k_max)
        self._mu = 1.0 / params.cpu_time_s
        if rollouts is not None:
            # canary-arm constants: per-service mu (cpu_time override),
            # per-hop cpu ratio and error rate (baseline-substituted)
            can_cpu = np.where(
                np.isfinite(rollouts.canary_cpu_s),
                rollouts.canary_cpu_s, params.cpu_time_s,
            )
            self._canary_mu = jnp.asarray(1.0 / can_cpu, jnp.float32)
            self._canary_cpu_varies = bool(
                (can_cpu != params.cpu_time_s).any()
            )
            self._canary_cpu_ratio_h = jnp.asarray(
                (can_cpu / params.cpu_time_s)[compiled.hop_service],
                jnp.float32,
            )
            self._canary_err_h = jnp.asarray(
                rollouts.canary_error_rate[compiled.hop_service],
                jnp.float32,
            )
            self._canary_reps_np = rollouts.canary_replicas.astype(
                np.float64
            )

        # -- pluggable load-balancing laws (sim/lb.py) ---------------------
        # Per-service wait-law selection (least_request / ring_hash /
        # wrr / panic routing) compiled from the topology's `lb:`
        # entries.  ``None`` or an all-fifo-no-panic table keeps every
        # traced wait draw on the legacy M/M/k path; the backend
        # profile is resolved against the FINAL k_max (autoscaler and
        # canary growth included) so dynamic pools extend the ring /
        # weight cycle instead of truncating it.  The armed
        # ``lb.degraded_backend`` chaos site bakes its weight collapse
        # into the profile constant (trace-affecting, covered by
        # faults.signature()).
        self._lb = lb
        self._lb_dev = None
        self._lb_profile_np = None
        if lb is not None and lb.active:
            from isotope_tpu.sim import lb as lb_mod

            self._lb_mod = lb_mod
            degraded = faults.lb_degraded_backend()
            # one profile serves the traced constants AND the host
            # feedback mirror below — the degraded-backend collapse
            # must be visible to both or the static fixed point
            # diverges from the traced physics under the chaos site
            self._lb_profile_np = lb_mod.effective_profile(
                lb, self._k_max, degraded
            )
            self._lb_dev = lb_mod.device_tables(
                lb, self._k_max, degraded=degraded
            )

        # -- traffic splits (config churner): per-hop schedule ids ---------
        # Each churned call's send probability is multiplied by its
        # schedule's current weight; descendants inherit through the
        # sent-propagation pass.  Offered load uses the time-averaged
        # weight, propagated down the unroll (a churned call scales its
        # whole subtree's reach).
        name_to_idx = {n: i for i, n in enumerate(t.names)}
        self._churn = tuple(churn)
        # the raw chaos schedule is kept for the chaos-fleet planners
        # (per-member jittered schedules, sim/ensemble.py)
        self._chaos_events = tuple(chaos)
        hop_mult = None
        if churn:
            entry_of_svc = np.full(compiled.num_services, -1, np.int64)
            for e_i, ts in enumerate(churn):
                if ts.service not in name_to_idx:
                    raise ValueError(
                        f"traffic split for unknown service: "
                        f"{ts.service!r}"
                    )
                if entry_of_svc[name_to_idx[ts.service]] >= 0:
                    raise ValueError(
                        f"multiple traffic splits target "
                        f"{ts.service!r}"
                    )
                entry_of_svc[name_to_idx[ts.service]] = e_i
            entry_of_hop = entry_of_svc[compiled.hop_service]
            entry_of_hop[0] = -1  # the client's edge is never churned
            for ts in churn:
                if not (entry_of_hop == entry_of_svc[
                        name_to_idx[ts.service]]).any():
                    # only the root targets it (or nothing does): the
                    # split would be a silent no-op
                    raise ValueError(
                        f"traffic split for {ts.service!r} matches no "
                        "callable edge (the client -> entrypoint edge "
                        "cannot be churned)"
                    )
            # sentinel column E holds weight 1.0 for unchurned calls
            self._hop_churn_entry = np.where(
                entry_of_hop >= 0, entry_of_hop, len(churn)
            ).astype(np.int32)
            self._churn_periods = tuple(
                float(ts.period_s) for ts in churn
            )
            self._churn_weights = tuple(
                jnp.asarray(ts.weights, jnp.float32) for ts in churn
            )
            means = np.asarray([ts.mean_weight for ts in churn])
            own = np.where(
                entry_of_hop >= 0, means[np.clip(entry_of_hop, 0, None)],
                1.0,
            )
            # hops are in BFS order, so parents precede children
            hop_mult = np.ones(compiled.num_hops, np.float64)
            for h in range(1, compiled.num_hops):
                hop_mult[h] = hop_mult[compiled.hop_parent[h]] * own[h]

            # Per-combo offered load: queueing waits must see the load
            # of the CURRENT schedule position, not the time average —
            # a square-wave split would otherwise report the averaged
            # (stable) latency in both its phases.  The combo space is
            # the product of the schedules' cycle positions; combined
            # with the chaos cuts it reuses the piecewise-phase
            # machinery below.
            import itertools

            ks = [len(ts.weights) for ts in churn]
            n_combos = int(np.prod(ks))
            if n_combos > 256:
                raise ValueError(
                    f"traffic-split cycle product is {n_combos} "
                    "combinations (> 256); shorten or align the "
                    "weight schedules"
                )
            mult_combo = np.empty(
                (n_combos, compiled.num_hops), np.float64
            )
            w_combo = np.asarray(
                [
                    [churn[e].weights[combo[e]] for e in range(len(churn))]
                    for combo in itertools.product(*map(range, ks))
                ]
            )  # (C, E)
            own_c = np.where(
                entry_of_hop >= 0,
                w_combo[:, np.clip(entry_of_hop, 0, None)],
                1.0,
            )  # (C, H)
            mult_combo[:, 0] = 1.0
            for h in range(1, compiled.num_hops):
                mult_combo[:, h] = (
                    mult_combo[:, compiled.hop_parent[h]] * own_c[:, h]
                )
            self._num_combos = n_combos
            own_combo_np = own_c
        else:
            self._num_combos = 1
            mult_combo = np.ones((1, compiled.num_hops), np.float64)
            own_combo_np = np.ones((1, compiled.num_hops), np.float64)
        self._visits = jnp.asarray(
            compiled.expected_visits(hop_mult), jnp.float32
        )

        # -- chaos phases: piecewise-constant effective replica counts -----
        for ev in chaos:
            if ev.service not in name_to_idx:
                raise ValueError(f"chaos for unknown service: {ev.service!r}")
        cuts = sorted(
            {0.0}
            | {ev.start_s for ev in chaos}
            | {ev.end_s for ev in chaos}
        )
        eff = np.tile(t.replicas.astype(np.int64), (len(cuts), 1))  # (P, S)
        for ev in chaos:
            s = name_to_idx[ev.service]
            for p, start in enumerate(cuts):
                if ev.start_s <= start < ev.end_s:
                    down = (
                        int(t.replicas[s])
                        if ev.replicas_down is None
                        else ev.replicas_down
                    )
                    eff[p, s] -= down
        eff = np.maximum(eff, 0)
        svc_down_np = eff == 0                               # (P, S)
        if policies is not None:
            # chaos kills compose with the autoscaler's dynamic count:
            # the per-phase DOWN delta (static replicas minus the
            # phase's effective count) subtracts from whatever count
            # the policy state actuated (floored at one server)
            self._downed_p_np = (
                t.replicas.astype(np.float64)[None, :] - eff
            )
        if rollouts is not None:
            # canary-first kill attribution: on a rolled-out service a
            # chaos phase's down delta removes CANARY replicas before
            # baseline ones — the newest pods are the ones a bad push
            # crashes, and a "canary-targeted kill" is exactly a chaos
            # event with replicas_down <= the canary arm's count.  The
            # baseline station then keeps (static - remaining delta)
            # and the canary station (canary_replicas - canary delta);
            # a fully-downed canary arm transport-fails its hops the
            # way a fully-down service does.
            downed_p = t.replicas.astype(np.float64)[None, :] - eff
            can_down_p = np.where(
                rollouts.has_rollout[None, :],
                np.minimum(downed_p, self._canary_reps_np[None, :]),
                0.0,
            )
            base_down_p = downed_p - can_down_p
            base_eff_p = t.replicas.astype(np.float64)[None, :] \
                - base_down_p
            can_eff_p = self._canary_reps_np[None, :] - can_down_p
            self._downed_base_p_np = base_down_p        # (P, S)
            self._base_eff_roll_p_np = base_eff_p
            self._can_eff_roll_p_np = can_eff_p
            self._svc_down_base_roll_p_np = base_eff_p <= 0
            self._svc_down_can_p_np = (
                rollouts.has_rollout[None, :] & (can_eff_p <= 0)
            )
        self._phase_starts = jnp.asarray(cuts, jnp.float32)  # (P,)
        self._svc_down = jnp.asarray(svc_down_np)            # (P, S) bool
        self._eff_replicas = jnp.asarray(np.maximum(eff, 1), jnp.int32)
        self.has_chaos = bool(chaos)

        # -- post-storm drain windows ---------------------------------------
        # The phase model is piecewise-stationary, but an OVERLOADED
        # phase (rho >= 1 somewhere — e.g. a retry storm under chaos)
        # leaves a backlog that the next phase drains at its freed
        # capacity before waits return to that phase's stationary law.
        # The engine models this with a phase-WINDOW table, (bounds,
        # row) pairs packed as one (2, W) array passed per run: drain
        # windows extend the congested row past its cut
        # (_phase_windows).  W is static: P real windows + up to P-1
        # drains.
        P_static = len(cuts)
        self._num_windows = 2 * P_static - 1 if P_static > 1 else 1
        ident_b = list(cuts) + [cuts[-1]] * (self._num_windows - P_static)
        ident_r = list(range(P_static)) + [P_static - 1] * (
            self._num_windows - P_static
        )
        self._ident_windows = np.stack(
            [np.asarray(ident_b), np.asarray(ident_r, np.float64)]
        ).astype(np.float32)
        self._window_cache: Dict[tuple, np.ndarray] = {}

        # -- ungraceful kills (drain=False): resident-request resets -------
        # A graceful kill (default) only removes capacity; an ungraceful
        # one also resets the requests resident on the killed replicas at
        # the kill instant (perf/stability/graceful-shutdown).  The
        # engine applies this post-hoc to requests whose hop on the
        # killed service straddles the kill time: each dies w.p.
        # down/k and the client sees a transport failure at ~the kill
        # instant.  Approximations (the oracle models them exactly):
        # retries of the killed call and mid-tree truncation effects on
        # downstream metrics are not re-simulated, and closed-loop
        # pacing keeps the uninterrupted latency.
        back_cum = None
        if any(not ev.drain for ev in chaos):
            # payload-free return legs, one per ancestor edge —
            # cluster-aware: a cross-cluster ancestor edge pays the
            # gateway extra on its return leg too, matching the
            # oracle's one_way(0.0) path (ADVICE r4: depth * base alone
            # diverged by the 1 ms/edge cross_cluster_latency_s on
            # multicluster drain=False runs)
            leg = np.full(
                compiled.num_hops, params.network.base_latency_s,
                np.float64,
            )
            if compiled.services.num_clusters > 1:
                cl = compiled.services.cluster
                hs_all = compiled.hop_service
                par = compiled.hop_parent
                leg[1:] += np.where(
                    cl[hs_all[par[1:]]] != cl[hs_all[1:]],
                    float(params.network.cross_cluster_latency_s),
                    0.0,
                )
            leg[0] += params.network.entry_extra_latency_s
            back_cum = leg.copy()
            hi = 1  # level-by-level prefix over the BFS order
            for lvl_c in compiled.levels:
                nxt = hi + lvl_c.num_children
                if lvl_c.num_children:
                    back_cum[hi:nxt] += back_cum[
                        compiled.hop_parent[hi:nxt]
                    ]
                hi = nxt
        # Canonical kill tables: ONE row per drain=False event, in this
        # schedule's own kill-time order, with surviving (k_before > 0)
        # events first and fully-down targets as inert zero-fraction
        # rows at the end.  Row e's RNG fold index is 9_990_000 + e, so
        # a jittered fleet (same event count by construction) can pass
        # the rows as stacked traced arguments through one program
        # while member k replays its solo run bit-for-bit.
        kill_t: list = []
        kill_frac: list = []
        for ev in sorted(chaos, key=lambda e: e.start_s):
            if ev.drain:
                continue
            s = name_to_idx[ev.service]
            down = (
                int(t.replicas[s])
                if ev.replicas_down is None
                else ev.replicas_down
            )
            # the residents are spread over the replicas ALIVE just
            # before this kill (the prior phase's effective count, which
            # overlapping chaos windows may already have reduced) — the
            # same denominator the DES oracle uses
            p = cuts.index(ev.start_s)
            k_before = int(eff[p - 1, s]) if p > 0 else int(t.replicas[s])
            if k_before <= 0:
                continue  # already fully down: nothing resident to kill
            kill_t.append(float(ev.start_s))
            kill_frac.append(np.where(
                compiled.hop_service == s,
                min(down / k_before, 1.0),
                0.0,
            ))
        self._num_kill_events = sum(1 for ev in chaos if not ev.drain)
        while len(kill_t) < self._num_kill_events:
            kill_t.append(0.0)
            kill_frac.append(np.zeros(compiled.num_hops))
        self._back_cum_np = back_cum
        if self._num_kill_events:
            self._kill_t_np = np.asarray(kill_t)
            self._kill_frac_np = np.stack(kill_frac)
        else:
            self._kill_t_np = None
            self._kill_frac_np = None

        # -- per-(chaos x churn)-phase offered load ------------------------
        # A total outage changes WHERE load flows, not just capacity: a
        # transport error truncates its caller's script, so services in
        # later steps (and the down subtree) see less traffic during the
        # window.  Compute per-phase reach multipliers statically —
        # VERDICT r2's "offered-load model ignores dynamic feedback".
        mult_phase = self._phase_reach_multipliers(svc_down_np)  # (P, H)
        P = mult_phase.shape[0]
        Cc = self._num_combos
        visits_pc = np.empty((P * Cc, compiled.num_services), np.float64)
        mult_pc = np.empty((P * Cc, compiled.num_hops), np.float64)
        for p in range(P):
            for c in range(Cc):
                mult_pc[p * Cc + c] = mult_phase[p] * mult_combo[c]
                visits_pc[p * Cc + c] = compiled.expected_visits(
                    mult_pc[p * Cc + c]
                )
        self._visits_pc_np = visits_pc
        self._mult_pc = mult_pc
        self._visits_pc = jnp.asarray(visits_pc, jnp.float32)
        self._eff_replicas_pc = jnp.repeat(self._eff_replicas, Cc, axis=0)
        self._svc_down_pc = jnp.repeat(self._svc_down, Cc, axis=0)
        if policies is not None:
            self._downed_pc = jnp.asarray(
                np.repeat(self._downed_p_np, Cc, axis=0), jnp.float32
            )
        if lb is not None and lb.any_panic:
            # static panic inputs: alive replicas per phase (UNclamped
            # — a fully-killed pool is 0 healthy, not 1) and the static
            # pool size.  Protected runs substitute the policy state's
            # actuated/ejected counts for these at trace time.
            self._lb_alive_pc = jnp.asarray(
                np.repeat(eff.astype(np.float64), Cc, axis=0),
                jnp.float32,
            )
            self._lb_total_row = jnp.asarray(
                t.replicas, jnp.float32
            )[None, :]
        if rollouts is not None:
            # Cc-repeated canary/baseline phase tables (the chaos split
            # above); without chaos they degenerate to the static rows
            rep = lambda a, dt_: jnp.asarray(  # noqa: E731
                np.repeat(a, Cc, axis=0), dt_
            )
            self._eff_base_roll_pc = rep(
                np.maximum(self._base_eff_roll_p_np, 1.0), jnp.int32
            ) if chaos else None
            self._svc_down_base_roll_pc = (
                rep(self._svc_down_base_roll_p_np, None)
                if chaos else None
            )
            self._can_reps_pc = (
                rep(np.maximum(self._can_eff_roll_p_np, 1.0),
                    jnp.float32)
                if chaos
                else jnp.broadcast_to(
                    jnp.asarray(self._canary_reps_np, jnp.float32),
                    (Cc, compiled.num_services),
                )
            )
            self._svc_down_can_pc = (
                rep(self._svc_down_can_p_np, None) if chaos else None
            )
            if policies is not None and chaos:
                self._downed_base_pc = rep(
                    self._downed_base_p_np, jnp.float32
                )

        # -- retry-storm feedback (load-dependent visits) ------------------
        # With finite call timeouts the retry/truncation probabilities are
        # load-dependent (timeouts trip more as waits grow), so the visit
        # tables become a per-rate fixed point (sim/feedback.py).  Without
        # finite timeouts the static tables are already exact and the
        # solver is skipped entirely.
        self._feedback = None
        if any(
            bool(np.isfinite(l.call_timeout).any()) for l in compiled.levels
        ):
            from isotope_tpu.sim.feedback import RetryFeedback

            self._feedback = RetryFeedback(
                compiled,
                params,
                self._mu,
                np.repeat(np.maximum(eff, 1), Cc, axis=0),
                np.repeat(svc_down_np, Cc, axis=0),
                own_combo_np,
                visits_pc,
                mtls=mtls,
                # retry budgets (sim/policies.py) cap the attempt fan;
                # the static visit estimates must respect the same cap
                # or the wait tables overstate storm amplification
                retry_budget=(
                    (
                        policies.has_budget,
                        policies.budget_frac,
                        policies.budget_min,
                    )
                    if policies is not None and policies.any_budget
                    else None
                ),
                # the LB laws change the per-station wait tails the
                # timeout probabilities integrate over; the fixed
                # point mirrors them (sim/lb.np_wait_stats) or a hot
                # ring-hash arc's retry storm goes statically unseen
                lb=(
                    (lb, self._lb_profile_np)
                    if self._lb_profile_np is not None
                    else None
                ),
            )
            if not self._feedback.active:  # pragma: no cover - guard match
                self._feedback = None

        # Per-hop gathers are resolved at trace time (static indices).
        hs = compiled.hop_service
        self._hop_service = jnp.asarray(hs)
        self._hop_err_rate = jnp.asarray(t.error_rate[hs])
        # cluster-aware wire times: cross-cluster edges pay the gateway
        # class, and the client -> entrypoint edge may traverse an
        # ingress gateway (compiler/program.py hop_wire_times)
        net_out, net_back = hop_wire_times(compiled, net)
        self._root_net = float(net_out[0] + net_back[0])
        # payload-free entry one-way: root start offset + refused-conn cost
        self._entry_one_way = net.entry_one_way(0.0)

        # -- closed-network (finite-population) model inputs ---------------
        # The saturated closed loop (-qps max) is modeled by exact MVA
        # over one station per service plus one delay station aggregating
        # wire time and sleeps (sim/closed.py).  Tables are built lazily
        # per connection count.
        # fork-join cycle factors: each member of an m-wide concurrent
        # group overlaps its siblings, contributing ~H_m/m of its
        # response to the request's cycle (H_m = harmonic number:
        # E[max of m iid Exp] = H_m * E[one]); factors multiply down
        # the unroll.  Utilization keeps the FULL visits — every branch
        # really executes (see sim/closed.py).
        hop_rtt = net_out + net_back  # (H,) f64
        fj = np.ones(compiled.num_hops)
        for lvl in compiled.levels:
            if not len(lvl.child_ids):
                continue
            seg_calls: Dict[int, int] = {}
            for seg in lvl.call_seg:
                seg_calls[int(seg)] = seg_calls.get(int(seg), 0) + 1
            factor = {
                seg: sum(1.0 / i for i in range(1, m + 1)) / m
                for seg, m in seg_calls.items()
            }
            parent_global = lvl.hop_ids[lvl.child_seg // compiled.max_steps]
            fj[lvl.child_ids] = fj[parent_global] * np.asarray(
                [factor[int(s)] for s in lvl.child_seg]
            )
        self._fj_factors = fj
        reach_f = compiled.hop_reach * fj
        hop_sleep = np.zeros(compiled.num_hops)
        for lvl in compiled.levels:
            hop_sleep[lvl.hop_ids] = (
                lvl.step_base * lvl.step_is_real
            ).sum(1)
        # per-hop delay weight: wire round trip + own sleeps; the delay
        # station's Z and the cycle visit ratios follow per phase row as
        # sums over reach_fj * mult_pc (phased saturated closed loop)
        self._reach_fj = reach_f
        self._hop_delay_w = hop_rtt + hop_sleep
        self._delay_s = float((reach_f * self._hop_delay_w).sum())
        self._cycle_visits = np.bincount(
            hs, weights=reach_f, minlength=compiled.num_services
        )
        self._closed_cache: Dict[int, tuple] = {}
        self._sat_pilot_fns: Dict[int, "jax.stages.Wrapped"] = {}
        # service-time squared coefficient of variation: the
        # census-conditional wait's variance scales with it (a sum of
        # j residual services — sim/closed._erlang_mixture_quantiles)
        if params.service_time == SERVICE_TIME_DETERMINISTIC:
            self._svc_scv = 0.0
        elif params.service_time == SERVICE_TIME_LOGNORMAL:
            self._svc_scv = float(
                np.expm1(params.service_time_param**2)
            )
        elif params.service_time == SERVICE_TIME_PARETO:
            a = params.service_time_param
            self._svc_scv = 1.0 / (a * (a - 2.0)) if a > 2.01 else 25.0
        else:
            self._svc_scv = 1.0

        # -- static RNG elimination -----------------------------------------
        # The reference's hot path only flips coins that can land both ways:
        # a topology with no sub-1 send probabilities needs no send RNG, one
        # with no errorRate needs no error RNG (executable.go:84-90 — the
        # coins exist, but p=0/p=100 make them deterministic).  Skipping the
        # (N, H) draws at trace time removes whole threefry invocations and
        # lets the downstream boolean algebra constant-fold.
        self._need_send = bool(churn) or bool(
            (compiled.hop_send_prob[1:] < 1.0).any()
        )
        self._need_err = bool((t.error_rate[hs] > 0.0).any()) or (
            # a canary arm that can 500 needs the error coins drawn
            # even when the baseline is error-free (sim/rollout.py)
            rollouts is not None and rollouts.any_error_override
        )

        levels: List[_Level] = []
        np_meta: List[dict] = []  # host-side shapes for bucket planning
        offset = 0
        for lvl in compiled.levels:
            cids = lvl.child_ids
            # Per-level step width: the compiler encodes segments with the
            # GLOBAL max_steps stride, but a level only needs the widest
            # script among ITS services — on skewed graphs (one huge
            # fan-out service, thousands of leaves) the global width
            # wastes multiples of the step-tensor footprint.
            pmax = max(int(lvl.step_is_real.sum(1).max(initial=0)), 1)
            parent_local = lvl.child_seg // compiled.max_steps
            child_step = lvl.child_seg % compiled.max_steps
            call_local = lvl.call_seg // compiled.max_steps
            call_step = lvl.call_seg % compiled.max_steps
            n_calls = len(lvl.call_seg)
            ident = (
                lvl.att_child.shape[0] == 1
                and n_calls == len(cids)
                and bool(lvl.att_valid.all())
                and np.array_equal(
                    lvl.att_child[0], np.arange(n_calls, dtype=np.int32)
                )
            )
            call_seg_p = call_local * pmax + call_step
            slots = lvl.num_hops * pmax  # > 0: every level has >= 1 hop
            uniform: Optional[int] = None
            if n_calls > 0 and n_calls % slots == 0:
                c = n_calls // slots
                if np.array_equal(
                    call_seg_p, np.repeat(np.arange(slots), c)
                ):
                    uniform = c

            # -- non-dense step encodings for skewed wide levels -------
            # A level whose dense (hops x Pmax) grid is pathological
            # (engine docstring) leaves the dense path.  The default
            # mitigation is the DENSE-BLOCKED tiling (_TiledSteps):
            # hops binned by script-width class run the dense grid ops
            # on fixed-width tiles, and only scripts wider than the
            # tile cap keep the true sparse call-slot encoding
            # (_SparseSteps) as a residual.  The decision is shared
            # with the vet linter (compiler/buckets.level_encoding).
            sparse: Optional[_SparseSteps] = None
            tiled: Optional[_TiledSteps] = None
            leaf_busy: Optional[jax.Array] = None
            sleep_real = lvl.step_is_real.astype(np.float64) * (
                lvl.step_base
            )
            if n_calls == 0:
                leaf_busy = jnp.asarray(sleep_real.sum(1), jnp.float32)
            else:
                n_slots = len(np.unique(call_seg_p))
                widths = lvl.step_is_real[:, :pmax].sum(1)
                enc, tile_plan = buckets.level_encoding(
                    lvl.num_hops, pmax, n_slots, widths,
                    sparse_level_elems=params.sparse_level_elems,
                    tiling=params.sparse_tiling,
                    tile_pmax=params.sparse_tile_pmax,
                )
                if enc == "tiled":
                    tiled = _build_tiled_steps(
                        tile_plan, pmax, lvl.step_is_real,
                        lvl.step_base, sleep_real, call_seg_p,
                        parent_local, child_step,
                    )
                elif enc == "sparse":
                    sparse = _sparse_tables(
                        lvl.num_hops, pmax, sleep_real, lvl.step_base,
                        call_seg_p, parent_local, child_step,
                    )
            meta = dict(
                size=lvl.num_hops, pmax=pmax, C=len(cids), K=n_calls,
                A=lvl.att_child.shape[0], offset=offset,
                sparse=sparse is not None or tiled is not None,
                leaf=n_calls == 0,
                tiles=(
                    tuple((len(t.hops), t.width) for t in tiled.tiles)
                    if tiled is not None
                    else None
                ),
                residual_slots=(
                    tiled.residual.n_slots
                    if tiled is not None and tiled.residual is not None
                    else (sparse.n_slots if sparse is not None else 0)
                ),
            )
            if params.bucketed_scan and not (meta["sparse"]
                                             or meta["leaf"]):
                # dense host copies only for scan-ELIGIBLE levels — a
                # sparse level's (size x pmax) grid is exactly what the
                # sparse encoding exists to avoid materializing
                meta.update(
                    step_mask=lvl.step_is_real[:, :pmax]
                    .astype(np.float32),
                    step_base=np.asarray(
                        lvl.step_base[:, :pmax], np.float32
                    ),
                    parent_local=parent_local, child_step=child_step,
                    child_rtt=(net_out[cids] + net_back[cids]),
                    child_net_out=net_out[cids],
                    child_send_prob=compiled.hop_send_prob[cids],
                    child_churn_entry=(
                        self._hop_churn_entry[cids] if churn else None
                    ),
                    call_local=call_local, call_step=call_step,
                    call_timeout=lvl.call_timeout,
                    att_child=lvl.att_child, att_valid=lvl.att_valid,
                )
            np_meta.append(meta)
            levels.append(
                _Level(
                    offset=offset,
                    size=lvl.num_hops,
                    pmax=pmax,
                    step_mask=jnp.asarray(
                        lvl.step_is_real[:, :pmax], jnp.float32
                    ),
                    step_base=jnp.asarray(lvl.step_base[:, :pmax]),
                    child_seg=jnp.asarray(parent_local * pmax + child_step),
                    child_parent_local=jnp.asarray(parent_local),
                    child_step=jnp.asarray(child_step),
                    child_rtt=jnp.asarray(
                        (net_out[cids] + net_back[cids]), jnp.float32
                    ),
                    child_net_out=jnp.asarray(net_out[cids], jnp.float32),
                    child_send_prob=jnp.asarray(
                        compiled.hop_send_prob[cids]
                    ),
                    call_seg=jnp.asarray(call_seg_p),
                    call_step=jnp.asarray(call_step),
                    call_timeout=jnp.asarray(lvl.call_timeout),
                    att_child=lvl.att_child,
                    att_valid=lvl.att_valid,
                    child_churn_entry=(
                        self._hop_churn_entry[cids] if churn else None
                    ),
                    ident_attempts=ident,
                    finite_timeout=bool(
                        np.isfinite(lvl.call_timeout).any()
                    ),
                    uniform_calls=uniform,
                    sparse=sparse,
                    tiled=tiled,
                    leaf_busy=leaf_busy,
                )
            )
            offset += lvl.num_hops
        self._levels: Tuple[_Level, ...] = tuple(levels)

        # -- bucketed level-scan plan (compiler/buckets.py) -----------------
        # Consecutive close-shaped levels collapse into lax.scan buckets
        # (sim/levelscan.py): the sweep body is traced once per bucket,
        # keeping trace/HLO size O(buckets) on deep graphs.  Sparse and
        # leaf levels keep their specialized unrolled path.
        self._track_err = (
            self._need_err
            or bool(chaos)
            or any(
                bool(np.isfinite(l.call_timeout).any())
                for l in compiled.levels
            )
            # breaker sheds take the 500 error path (sim/policies.py)
            or (policies is not None and policies.any_breaker)
            # canary-arm 500s feed the rollout gates (sim/rollout.py)
            or (rollouts is not None and rollouts.any_error_override)
            # panic routing fast-fails the dead-backend share
            # (sim/lb.py) — reachable only when something can actually
            # unhealth the pool (chaos kills or policy ejection)
            or (
                lb is not None and lb.any_panic
                and (bool(chaos) or policies is not None)
            )
        )
        shapes = [
            buckets.LevelShape(
                size=m["size"], pmax=m["pmax"], children=m["C"],
                calls=m["K"], attempts=m["A"], sparse=m["sparse"],
                offset=m["offset"], tiles=m.get("tiles"),
                residual_slots=m.get("residual_slots", 0),
            )
            for m in np_meta
        ]
        plan = buckets.plan_segments(
            shapes,
            waste=params.level_bucket_waste,
            # protected runs ride the scan buckets too: the
            # retry-budget gate reached the bucket attempt loop in
            # sim/levelscan.py (SweepCtx.retry_coin), so a policies
            # Simulator keeps the PR 6 fast path — pinned <= 1 ULP
            # against the unrolled plan (tests/test_lb.py)
            enabled=params.bucketed_scan,
            schedule=params.bucket_schedule,
        )
        self._segments = tuple(
            levelscan.build_bucket(p, np_meta, len(self._churn))
            if isinstance(p, buckets.ScanBucketPlan)
            else p
            for p in plan
        )
        self._plan_shapes = tuple(shapes)
        self._plan = tuple(plan)
        self._plan_sig = buckets.plan_signature(plan)
        # -- Pallas census kernel flag (native/census_pallas.py) ------------
        # auto: on for TPU backends, off elsewhere (the CPU
        # interpreter-mode kernel exists for equivalence tests, not
        # speed); False keeps today's op-by-op census byte-identical.
        self._pallas_census = (
            params.pallas_census
            if params.pallas_census is not None
            else jax.default_backend() == "tpu"
        )
        self._census_mod = None
        if self._pallas_census:
            from isotope_tpu.native import census_pallas

            self._census_mod = census_pallas

        # -- AOT shape signature (compiler/cache.py) ------------------------
        # Everything a traced entry point bakes in: the bucket plan, the
        # compiled graph's shape, and a content digest of every closed-
        # over constant — so two Simulator instances share executables
        # exactly when the traced programs would be identical.
        self.signature = (
            "engine-v1",
            self._plan_sig,
            compiled.shape_signature(),
            array_digest(
                # an armed NaN-injection plan bakes a poisoned constant
                # into the traced program: it must never share an
                # executable with the clean trace (empty when off)
                faults.signature(),
                # ensemble is the DEFAULT FLEET SIZE, not a traced
                # constant (the member axis rides call shapes, keyed
                # separately in _get_ensemble): normalize it out so an
                # ensemble-armed engine shares every solo executable
                # with its plain twin
                repr(dataclasses.replace(params, ensemble=0)),
                repr(tuple(chaos)), repr(self._churn),
                repr(mtls), repr(t.names),
                # policy tables bake into the traced control program;
                # absent tables contribute the historical empty digest
                policies.signature() if policies is not None else "",
                rollouts.signature() if rollouts is not None else "",
                # lb tables select the traced wait law per station
                lb.signature() if lb is not None else "",
                compiled.hop_service, compiled.hop_parent,
                compiled.hop_step, compiled.hop_attempt,
                compiled.hop_send_prob, compiled.hop_request_size,
                compiled.hop_reach, t.replicas, t.error_rate,
                t.response_size, t.cluster,
                *[
                    a
                    for l in compiled.levels
                    for a in (
                        l.step_is_real, l.step_base, l.child_ids,
                        l.child_seg, l.call_seg, l.call_timeout,
                        l.att_child, l.att_valid,
                    )
                ],
            ),
        )

        # -- sibling copula: static hop -> group id map ---------------------
        # Concurrent sibling hops (children spawned by the same parent
        # step, retry attempts included) share correlated wait draws.
        # Group normals are drawn as (n, G) — G is the number of groups
        # with >1 member, typically << H (a 1000-way fan-out is ONE
        # group) — and expanded by a static column gather; hops outside
        # any group get their own independent slot.  See
        # SimParams.sibling_copula_r.
        group = np.zeros(compiled.num_hops, np.int64)
        n_multi = 0
        off = 1  # hop 0 is the root; level d's children follow in order
        gid = {("root",): 0}
        gparent = [0]  # group -> parent group (the root group is its own)
        for d, lvl in enumerate(compiled.levels):
            segs = np.asarray(lvl.child_seg)
            counts: Dict[int, int] = {}
            for seg in segs:
                counts[int(seg)] = counts.get(int(seg), 0) + 1
            for local, seg in enumerate(segs):
                key = (d, int(seg))
                if key not in gid:
                    gid[key] = len(gid)
                    # the group's parent group is the sibling group of
                    # the PARENT HOP (the hop owning this call step) —
                    # already assigned: levels fill in BFS order
                    parent_hop = lvl.hop_ids[
                        int(seg) // compiled.max_steps
                    ]
                    gparent.append(int(group[parent_hop]))
                    if counts[int(seg)] > 1:
                        n_multi += 1
                group[off + local] = gid[key]
            off += lvl.num_children
        self._sib_group = group.astype(np.int32)
        self._num_sib_groups = len(gid)
        self._copula_active = n_multi > 0 and params.sibling_copula_r > 0.0

        # -- hierarchical copula mix (SimParams.hierarchical_copula_gamma) --
        # Same-depth sibling groups whose LCA sits L levels up
        # correlate at gamma^L (so hop waits at r * gamma^L): COUSIN
        # subtree compositions share upstream arrivals, which the flat
        # copula missed.  Crucially, groups at DIFFERENT depths stay
        # independent — a naive "mix down the group tree" recursion
        # (Z_g = sqrt(gamma) Z_parent + ...) also correlates each hop
        # with its ANCESTORS at r * gamma^(L/2), inflating the serial
        # path-sum variance (measured: tree13 rho=0.9 p99 blew from
        # +2.3% to +18.7%).  Independence across depths comes from
        # giving every (ancestor group a, depth offset l) pair its OWN
        # unit normal: group g at depth d loads
        # sqrt((1-gamma) gamma^l) on (anc_l(g), l) for l < d and
        # gamma^(d/2) on (root, d); rows have unit norm, and two rows
        # share a factor iff the groups have equal depth (same l for a
        # common ancestor), giving exactly gamma^L.
        #
        # Only MULTI-MEMBER groups (real concurrent fan-outs / retry
        # fans) join the hierarchy; singleton groups keep their flat
        # independent factor.  A dense (G, F) matrix over every group
        # captured 7.1 GB of constants on a 30k-hop sequential graph
        # (G ~ 30k singleton groups x a ~19-deep factor space) — the
        # active subset is (|A|, F) with |A| = the concurrent groups
        # only, identical behavior on fork-join topologies where every
        # group is concurrent.
        self._copula_mix = None
        self._copula_rows = None
        self._copula_dim = len(gid)
        gamma = params.hierarchical_copula_gamma
        sizes = np.bincount(group, minlength=len(gid))
        active_groups = np.nonzero(sizes > 1)[0]
        if (
            self._copula_active
            and gamma > 0.0
            and len(gid) > 1
            and len(active_groups)
        ):
            G = len(gid)
            # factor space: one base factor per group (columns [0, G)),
            # plus one factor per distinct (ancestor, depth>=1) pair
            # used by an active group's chain
            pair_idx: Dict[Tuple[int, int], int] = {}
            rows = []  # (row-in-A, factor, coeff)
            # active groups always sit below the root (group 0 holds
            # only hop 0, size 1), so every chain walks >= 1 level
            for i, g in enumerate(active_groups):
                w, a, lev = 1.0, int(g), 0
                while a != 0:
                    if lev == 0:
                        f = a  # own base factor
                    else:
                        key = (a, lev)
                        if key not in pair_idx:
                            pair_idx[key] = G + len(pair_idx)
                        f = pair_idx[key]
                    rows.append((i, f, np.sqrt(w * (1.0 - gamma))))
                    w *= gamma
                    a = gparent[a]
                    lev += 1
                key = (0, lev)
                if key not in pair_idx:
                    pair_idx[key] = G + len(pair_idx)
                rows.append((i, pair_idx[key], np.sqrt(w)))
            F = G + len(pair_idx)
            mix = np.zeros((len(active_groups), F), np.float64)
            for i, f, c in rows:
                mix[i, f] = c
            self._copula_mix = jnp.asarray(mix, jnp.float32)
            self._copula_rows = jnp.asarray(active_groups, jnp.int32)
            self._copula_dim = F

        # -- retry copula: static hop -> call-group map ---------------------
        # Serial retry attempts of ONE call get an extra shared normal on
        # top of the sibling term: attempt n+1 re-enters the same queue
        # right after attempt n failed, so consecutive attempts see nearly
        # the same backlog (the timeout-cascade correlation; see
        # SimParams.retry_copula_r).  Hops outside any multi-attempt call
        # carry weight 0 and gather a sentinel column.
        rg = np.zeros(compiled.num_hops, np.int64)
        in_rg = np.zeros(compiled.num_hops, bool)
        n_rg = 0
        for lvl in compiled.levels:
            if not len(lvl.call_seg):
                continue
            att_counts = lvl.att_valid.sum(0)
            for k in np.nonzero(att_counts > 1)[0]:
                gids = lvl.child_ids[
                    lvl.att_child[lvl.att_valid[:, k], k]
                ]
                rg[gids] = n_rg
                in_rg[gids] = True
                n_rg += 1
        self._retry_group = np.where(in_rg, rg, n_rg).astype(np.int32)
        self._num_retry_groups = n_rg
        self._retry_active = n_rg > 0 and params.retry_copula_r > 0.0
        if self._retry_active and (
            params.sibling_copula_r + params.retry_copula_r >= 1.0
        ):
            raise ValueError(
                "sibling_copula_r + retry_copula_r must be < 1 when the "
                "topology has multi-attempt calls (both correlations "
                "apply to retry hops)"
            )
        # per-hop weight of the retry-group normal (0 outside any group)
        self._retry_w = np.where(
            in_rg, np.sqrt(params.retry_copula_r), 0.0
        ).astype(np.float32)
        # (the finite-population law handles chaos/churn phases with
        # per-row tables; only the phased mTLS tax keeps a run on the
        # open-loop fallback — see _saturated)
        self._fns: Dict[Tuple[int, str, bool], "jax.stages.Wrapped"] = {}
        self._summary_fns: Dict[tuple, "jax.stages.Wrapped"] = {}
        self._ensemble_fns: Dict[tuple, "jax.stages.Wrapped"] = {}
        self._search_fns: Dict[tuple, "jax.stages.Wrapped"] = {}
        self._rate_cache: Dict[tuple, float] = {}
        telemetry.counter_inc("simulators_built")
        telemetry.phase_add("engine.build", time.perf_counter() - _t_build)

    def _phase_reach_multipliers(self, svc_down_np: np.ndarray) -> np.ndarray:
        """(P, H) static reach multipliers from outage-driven script
        truncation: a call to a down service transport-fails, its caller
        stops after that step (concurrent siblings still run), and the
        down subtree serves nothing."""
        compiled = self.compiled
        H = compiled.num_hops
        P = svc_down_np.shape[0]
        out = np.ones((P, H))
        parent = compiled.hop_parent
        step = compiled.hop_step
        send_prob = compiled.hop_send_prob.astype(np.float64)
        first_attempt = compiled.hop_attempt == 0
        for p in range(P):
            down = svc_down_np[p]
            if not down.any():
                continue
            tgt_down = down[compiled.hop_service]
            m = out[p]
            if tgt_down[0]:
                # a down entrypoint refuses every connection
                m[:] = 0.0
                continue
            # P(a step does NOT transport-fail): product over its
            # down-target calls' send coins (one coin per call; retry
            # attempts share it)
            no_fail: Dict[tuple, float] = {}
            for h in np.nonzero(tgt_down & first_attempt)[0]:
                key = (int(parent[h]), int(step[h]))
                no_fail[key] = no_fail.get(key, 1.0) * (
                    1.0 - float(send_prob[h])
                )
            per_parent: Dict[int, list] = {}
            for (q, j), pr in no_fail.items():
                per_parent.setdefault(q, []).append((j, pr))
            for items in per_parent.values():
                items.sort()

            def surv(q: int, k: int) -> float:
                pr = 1.0
                for j, pj in per_parent.get(q, ()):
                    if j >= k:
                        break
                    pr *= pj
                return pr

            for h in range(1, H):
                q = int(parent[h])
                m[h] = m[q] * surv(q, int(step[h]))
                if tgt_down[h]:
                    m[h] = 0.0
        return out

    def _closed_tables(self, connections: int):
        """Saturated-closed-loop sampling tables at ``connections``,
        stacked per (chaos x churn) phase row: (throughput (R,),
        p_zero (R, H), coef (R, D+1, H), e (R, H), center_c (R,),
        var_scale (R, H)) — lazily built, cached per C.  Unphased runs
        have R == 1 and index row 0 directly.

        ``center_c``/``var_scale`` realize the population copula:
        z' = scale * (z - c * e * (e . z)) has exact unit marginals and
        pairwise correlation rho (sim/closed.py) among the active hops.
        """
        if connections not in self._closed_cache:
            R = int(self._phase_starts.shape[0]) * self._num_combos
            rows = [
                self._closed_row(connections, r, refine=(R == 1))
                for r in range(R)
            ]
            self._closed_cache[connections] = (
                np.asarray([r[0] for r in rows]),
                jnp.asarray(np.stack([r[1] for r in rows]), jnp.float32),
                jnp.asarray(np.stack([r[2] for r in rows]), jnp.float32),
                jnp.asarray(np.stack([r[3] for r in rows]), jnp.float32),
                # center coefficients stay NumPy: the single-phase path
                # reads them as python floats inside an active trace
                np.asarray([r[4] for r in rows], np.float32),
                jnp.asarray(np.stack([r[5] for r in rows]), jnp.float32),
            )
        return self._closed_cache[connections]

    def _closed_row(self, connections: int, row: int, refine: bool):
        """One phase row's closed-network tables (numpy)."""
        from isotope_tpu.sim import closed

        compiled = self.compiled
        hs = compiled.hop_service
        H = compiled.num_hops
        visits = self._visits_pc_np[row]
        reps = np.maximum(
            np.asarray(self._eff_replicas_pc, np.float64)[row], 1.0
        )
        reach_r = self._reach_fj * self._mult_pc[row]
        delay_r = float((reach_r * self._hop_delay_w).sum())
        cycle_visits_r = np.bincount(
            hs, weights=reach_r, minlength=compiled.num_services
        )
        if visits.max(initial=0.0) <= 1e-12:
            # down entry: every connection spins on refused connects
            lam = connections / max(2.0 * self._entry_one_way, 1e-9)
            deg = closed.DEFAULT_QUANTILE_DEGREE
            return (lam, np.ones(H), np.zeros((deg + 1, H)),
                    np.zeros(H), 0.0, np.ones(H))
        if bool((self._fj_factors < 1.0).any()):
            # fork-join: finite-source decomposition; for unphased runs
            # the cycle is refined through the ENGINE's own composition
            # (max over siblings, copula) so Little's law closes:
            # E[sampled latency] = C / lambda.  Phase rows keep the
            # H_m/m-initialized decomposition (the pilot measures one
            # stationary phase at a time, which phased runs don't have).
            lam, pi, cycle = closed.fork_join_decomposition(
                visits, cycle_visits_r, reps, self._mu,
                delay_r, connections,
            )
            if refine:
                # Little-law closure: find the cycle c* with E(c*) = c*
                # where E(c) is the engine's own composed mean latency
                # under tables built at cycle c.  The map's contraction
                # factor is ~0.9 (nearly marginal), so the old damped
                # iteration amplified pilot noise ~10x and "converged"
                # wherever the RNG stream pushed it (measured: a 0.3%
                # pilot perturbation moved throughput 5%, flipping the
                # r4 quantile calibration).  Instead: sample E at a
                # spread of cycles around the decomposition estimate,
                # fit the locally-linear map E(c) ~ a + b c by least
                # squares, and solve c* = a / (1 - b) — one regression
                # is robust to pilot noise where a marginal iteration
                # is not.
                pilot = self._sat_pilot(connections)
                key = jax.random.PRNGKey(20_260_730)

                def census_at(c):
                    # the repairman sweep is itself a per-station fixed
                    # point in w; iterate it to convergence at cycle c
                    pi_c = pi
                    w_c = np.full(len(visits), 1.0 / self._mu)
                    for _ in range(4):
                        pi_c, w_c = closed.repairman_marginals(
                            visits, reps, self._mu, c, w_c, connections
                        )
                    return pi_c

                c0 = cycle
                cs, es = [], []
                for it, f in enumerate(
                    (0.85, 0.925, 1.0, 1.075, 1.15)
                ):
                    c = c0 * f
                    pi_c = census_at(c)
                    p0, coef, _ = closed.tables_from_pi(
                        pi_c, reps, self._mu, scv=self._svc_scv
                    )
                    e_c, cc, sc = self._center_terms(
                        closed.census_sigma(pi_c), None, hs
                    )
                    e = float(
                        pilot(
                            jax.random.fold_in(key, it),
                            jnp.float32(c / connections),
                            jnp.asarray(p0[hs], jnp.float32),
                            jnp.asarray(coef[:, hs], jnp.float32),
                            jnp.asarray(e_c, jnp.float32),
                            jnp.float32(cc),
                            jnp.asarray(sc, jnp.float32),
                        )
                    )
                    cs.append(c)
                    es.append(e)
                b, a = np.polyfit(np.asarray(cs), np.asarray(es), 1)
                if b < 0.98:  # sane slope: solve the linear map
                    cycle = float(a / (1.0 - b))
                    # clamp to the sampled neighborhood: the linear
                    # model is local
                    cycle = float(np.clip(cycle, 0.7 * c0, 1.6 * c0))
                else:  # degenerate fit: keep the decomposition value
                    cycle = c0
                pi = census_at(cycle)
            p0, coef, _ = closed.tables_from_pi(
                pi, reps, self._mu, scv=self._svc_scv
            )
            throughput = connections / cycle
            # Partial population centering for fork-join: the exact
            # census variance identity (chains) does not survive forks,
            # but the physical constraint — at -qps max the total
            # in-system population is pinned at C, so station censuses
            # are negatively correlated — still holds.  var_d = None
            # tells the shared tail below to use the EMPIRICAL target
            # alpha * sum(sigma_h^2) with alpha = 0.25, fit against
            # the DES oracle on tree13/star9 (ORACLE.md r5: p99
            # +7.7%/+3.8% -> +2.9%/-1.7% at unchanged p50).
            sigma = closed.census_sigma(pi)
            var_d = None
        else:
            tabs = closed.closed_network_tables(
                visits, cycle_visits_r, reps, self._mu,
                delay_r, connections, scv=self._svc_scv,
            )
            p0, coef = tabs.p_zero, tabs.coef
            throughput = tabs.throughput
            sigma, var_d = tabs.sigma, tabs.var_delay
        p0_h = p0[hs]
        e_h, c_center, scale_h = self._center_terms(sigma, var_d, hs)
        return (throughput, p0_h, coef[:, hs], e_h, c_center, scale_h)

    @staticmethod
    def _center_terms(sigma, var_d, hs):
        """Population-copula centering terms from census sigmas.

        Linearize j_s ~ mean + sigma_s * z_s; the census constraint
        sum_s j_s + j_d = C-1 means the sigma-weighted z-combination
        must carry Var(j_delay), not the independent sum Sigma sigma^2
        — shrink its projection: z' = (z - c * e * (e . z)) / norm,
        c = 1 - sqrt(Vd / Ss^2).  ``var_d=None`` selects the fork-join
        empirical target 0.25 * Ss^2 (see _closed_row).
        """
        c_center = 0.0
        e_h = np.zeros(len(hs))
        scale_h = np.ones(len(hs))
        if sigma is not None:
            # a station's weight spreads over its hops (independent
            # draws): sigma/m per hop keeps multi-visit stations from
            # dominating the projection
            n_hops_s = np.bincount(hs, minlength=len(sigma))
            sig_h = sigma[hs] / np.maximum(n_hops_s[hs], 1)
            ss = float((sig_h**2).sum())
            if var_d is None:
                var_d = 0.25 * ss
            if ss > 1e-18 and var_d < ss:
                c_center = 1.0 - float(np.sqrt(max(var_d, 0.0) / ss))
                e_h = sig_h / np.sqrt(ss)
                shrink = (2 * c_center - c_center**2) * e_h**2
                scale_h = 1.0 / np.sqrt(1.0 - shrink)
        return e_h, c_center, scale_h

    def _sat_pilot(self, connections: int, n: int = 32_768):
        """Jitted mean-latency probe for the fork-join fixed point: the
        quantile tables are ARGUMENTS (not baked constants) so the one
        compilation serves every iteration.  The probe averages two
        independent key streams at 32k requests — the cycle fixed
        point amplifies probe noise (a ~0.3% mean perturbation was
        measured to move the converged throughput by 5% between RNG
        streams), so the estimator must be tight for the iteration to
        land in the same basin regardless of upstream RNG layout."""
        if connections not in self._sat_pilot_fns:
            c = max(connections, 1)

            def fn(key, nominal_gap, p0_h, coef_h, e_h, c_ctr, scale_h):
                means = []
                for i in range(2):
                    res, _, _ = self._simulate_core(
                        n, CLOSED_LOOP, connections,
                        jax.random.fold_in(key, i),
                        jnp.float32(1.0), jnp.float32(0.0),
                        jnp.float32(1.0),
                        nominal_gap, jnp.float32(0.0),
                        jnp.zeros((c,), jnp.float32), jnp.float32(0.0),
                        sat_conns=connections,
                        sat_override=(p0_h, coef_h, e_h, c_ctr, scale_h),
                    )
                    means.append(res.client_latency.mean())
                return (means[0] + means[1]) / 2.0

            self._sat_pilot_fns[connections] = jax.jit(fn)
        return self._sat_pilot_fns[connections]

    # -- public entry points ----------------------------------------------

    def _vis_arg(self, offered: float) -> jax.Array:
        """The (P*Cc, S) visit table the queues should see at ``offered``:
        the static table, or the retry-feedback fixed point at that rate
        when finite timeouts make failure probabilities load-dependent."""
        if self._feedback is None:
            return self._visits_pc
        return jnp.asarray(
            self._feedback.visits_pc(float(offered)), jnp.float32
        )

    def _windows_arg(self, offered: float, sat: bool) -> jax.Array:
        """The (2, W) packed (bounds, row) phase-window table at
        ``offered``: identity unless an overloaded phase leaves a
        backlog, in which case drain windows keep the congested row
        active past its cut for backlog / freed-capacity seconds.

        Saturated (-qps max) runs skip drains: the closed population
        bounds the backlog at C, so queues drain within one cycle.
        """
        P = int(self._phase_starts.shape[0])
        if P == 1 or sat or not self.has_chaos:
            # one cached device copy: fleets stack this row per
            # member, and a fresh device_put per member would defeat
            # the identical-row broadcast in _ensemble_args
            dev = getattr(self, "_ident_windows_dev", None)
            if dev is None:
                dev = jnp.asarray(self._ident_windows)
                self._ident_windows_dev = dev
            return dev
        key = (float(f"{float(offered):.4g}"),)
        if key not in self._window_cache:
            cuts = np.asarray(self._phase_starts, np.float64)
            S = self.compiled.num_services
            Cc = self._num_combos
            visits = (
                self._feedback.visits_pc(offered)
                if self._feedback is not None
                else self._visits_pc_np
            )
            lam = offered * visits.reshape(P, Cc, S).mean(1)  # (P, S)
            eff = np.asarray(self._eff_replicas_pc, np.float64)[
                ::Cc
            ]  # (P, S) clamped >= 1
            down = np.asarray(self._svc_down_pc, bool)[::Cc]
            cap = np.where(down, 0.0, eff * self._mu)
            lam = np.where(down, 0.0, lam)

            seq = [(float(cuts[0]), 0)]
            backlog = np.zeros(S)
            for p in range(P - 1):
                dur = float(cuts[p + 1] - cuts[p])
                backlog += np.maximum(lam[p] - cap[p], 0.0) * dur
                free = cap[p + 1] - lam[p + 1]
                drainable = (backlog > 1e-9) & (free > 1e-9)
                nxt_end = float(cuts[p + 2]) if p + 2 < P else np.inf
                if drainable.any():
                    drain_t = float(
                        (backlog[drainable] / free[drainable]).max()
                    )
                    drain_end = min(cuts[p + 1] + drain_t, nxt_end)
                    if drain_end > cuts[p + 1] + 1e-9:
                        # the congested row stays live while draining
                        seq.append((float(cuts[p + 1]), p))
                        if drain_end < nxt_end:
                            seq.append((float(drain_end), p + 1))
                        drained = (
                            np.maximum(free, 0.0)
                            * (drain_end - cuts[p + 1])
                        )
                        backlog = np.maximum(backlog - drained, 0.0)
                        continue
                seq.append((float(cuts[p + 1]), p + 1))
            while len(seq) < self._num_windows:
                seq.append(seq[-1])
            self._window_cache[key] = np.asarray(
                [[b for b, _ in seq], [r for _, r in seq]], np.float32
            )
        return jnp.asarray(self._window_cache[key])

    def run(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        fixed_point_iters: int = 3,
    ) -> SimResults:
        """Simulate ``num_requests`` under ``load``.

        Open-loop: queues see exactly ``load.qps``.  Closed-loop: the rate
        the queues see is latency-dependent (Fortio's workers self-throttle),
        so we solve ``lam = min(qps, C / E[latency(lam)], capacity)`` by a
        few pilot iterations before the full run.
        """
        faults.check("engine.run")
        self._check_lb_load(load)
        if load.kind == OPEN_LOOP:
            with self._detail_ctx():
                return self._get(num_requests, OPEN_LOOP)(
                    key, jnp.float32(load.qps), jnp.float32(0.0),
                    jnp.float32(load.qps), jnp.float32(0.0),
                    visits_pc=self._vis_arg(load.qps),
                    phase_windows=self._windows_arg(load.qps, False),
                )
        lam = self.solve_closed_rate(load, num_requests, key,
                                     fixed_point_iters)
        gap = (
            jnp.float32(load.connections / load.qps)
            if load.qps is not None
            else jnp.float32(0.0)
        )
        # Nominal pacing (chaos-phase placement) always reflects the real
        # rate: with ``qps=None`` (Fortio's -qps max) the workers still
        # issue at the solved throughput, so placing every request at t=0
        # would silently skip chaos phases.
        nominal_gap = jnp.float32(load.connections / lam)
        sat = self._saturated(load)
        with self._detail_ctx():
            return self._get(num_requests, CLOSED_LOOP, load.connections,
                             sat=sat)(
                key, jnp.float32(lam), gap, jnp.float32(lam), nominal_gap,
                visits_pc=self._vis_arg(lam),
                phase_windows=self._windows_arg(lam, sat),
            )

    @staticmethod
    def _detail_ctx():
        """Telemetry detail mode runs the tensor program EAGERLY (under
        ``jax.disable_jit``) so the per-segment fences see concrete
        arrays and can block at segment boundaries.  Fences serialize
        dispatch — detail mode is for diagnosis, not benchmarking."""
        if telemetry.detail_enabled():
            return jax.disable_jit()
        return contextlib.nullcontext()

    def _saturated(self, load: LoadModel) -> bool:
        """True when the run uses the finite-population (MVA) wait law:
        ``-qps max``, with per-phase tables under chaos/churn.  A
        phased mTLS tax falls back to the open-loop law (the MVA delay
        station is static)."""
        return (
            load.kind == CLOSED_LOOP
            and load.qps is None
            and self._mtls is None
        )

    def _check_lb_load(self, load: LoadModel) -> None:
        """LB-law preconditions for one run: the saturated ``-qps
        max`` path samples the finite-population MVA law, which has no
        per-backend dispatch notion — reject loudly rather than
        silently falling back to fifo.  Also the ``lb.degraded_backend``
        chaos site's classified-fault entry (the supervisor retry path
        covers the lb layer like the PR 9 policy sites)."""
        if self._lb is None or not self._lb.active:
            return
        faults.check("lb.degraded_backend")
        if self._saturated(load):
            raise ValueError(
                "lb laws do not support saturated -qps max loads: the "
                "finite-population wait tables have no per-backend "
                "dispatch; use a paced closed loop or open loop"
            )

    def solve_closed_rate(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        fixed_point_iters: int = 3,
    ) -> float:
        """Equilibrium offered rate of Fortio's closed loop.

        The workers' aggregate throughput satisfies ``lam = min(qps,
        C / E[latency(lam)])`` with ``E[latency]`` increasing in ``lam``,
        so ``g(lam) = min(qps, C / E[lat(lam)]) - lam`` is strictly
        decreasing and has one root — found by bisection over short pilot
        runs.  (Picard iteration ``lam <- implied(lam)`` diverges near
        saturation, where the latency curve is steep: starting at the
        capacity it ping-pongs between ~0 and the cap.  Validated against
        the DES oracle's measured closed-loop throughput, test_oracle.py.)

        The solved rate is a physical property of (load, topology), not of
        the RNG key, so it is memoized per load shape.
        """
        if self._saturated(load):
            # the closed network's throughput is what MVA computes exactly
            # (product-form) — no pilot runs needed.  Phased runs
            # time-weight the per-row rates over the chaos windows the
            # run actually spans.
            thr = self._closed_tables(load.connections)[0]
            return self._sat_phased_rate(thr, num_requests)
        cache_key = (load.qps, load.connections, min(num_requests, 2048),
                     fixed_point_iters)
        if cache_key in self._rate_cache:
            return self._rate_cache[cache_key]
        cap = 0.999 * self.capacity_qps()
        hi = min(load.qps, cap) if load.qps is not None else cap
        pilot_n = min(num_requests, 2048)
        pilot = self._get(pilot_n, CLOSED_LOOP, load.connections)
        gap = (
            jnp.float32(load.connections / load.qps)
            if load.qps is not None
            else jnp.float32(0.0)
        )

        def implied(lam: float, i: int) -> float:
            res = pilot(
                jax.random.fold_in(key, i), jnp.float32(lam), gap,
                jnp.float32(lam), jnp.float32(load.connections / lam),
                visits_pc=self._vis_arg(lam),
                phase_windows=self._windows_arg(lam, False),
            )
            mean_lat = float(res.client_latency.mean())
            out = load.connections / max(mean_lat, 1e-9)
            return min(out, load.qps) if load.qps is not None else out

        if implied(hi, 0) >= hi:
            # pacing (or capacity) binds before self-throttling
            self._rate_cache[cache_key] = hi
            return hi
        lo = 0.0
        for i in range(1, max(4 * fixed_point_iters, 10)):
            mid = 0.5 * (lo + hi)
            if implied(mid, i) >= mid:
                lo = mid
            else:
                hi = mid
            if hi - lo < 1e-3 * hi:
                break
        lam = 0.5 * (lo + hi)
        self._rate_cache[cache_key] = lam
        return lam

    def _sat_phased_rate(self, thr: np.ndarray, num_requests: int) -> float:
        """Average ``-qps max`` throughput over the chaos phases a run of
        ``num_requests`` spans: walk the phase windows accumulating
        requests at each window's rate until the count is reached
        (churn combos cycle uniformly, so they average arithmetically
        within a chaos phase)."""
        P = int(self._phase_starts.shape[0])
        Cc = self._num_combos
        if P * Cc == 1:
            return float(thr[0])
        lam_p = np.asarray(thr, np.float64).reshape(P, Cc).mean(1)
        cuts = np.asarray(self._phase_starts, np.float64)
        acc = 0.0
        for p in range(P):
            start = cuts[p]
            end = cuts[p + 1] if p + 1 < P else np.inf
            rate = max(float(lam_p[p]), 1e-9)
            seg = (end - start) * rate
            if p + 1 >= P or acc + seg >= num_requests:
                t_end = start + (num_requests - acc) / rate
                return num_requests / max(t_end, 1e-9)
            acc += seg
        return float(lam_p[-1])  # pragma: no cover - loop always returns

    def run_summary(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        *,
        block_size: int = 65_536,
        collector=None,
        fixed_point_iters: int = 3,
        trim: bool = False,
    ):
        """Simulate >= ``num_requests`` in HBM-bounded blocks.

        A ``lax.scan`` over request blocks accumulates an O(buckets)
        :class:`~isotope_tpu.sim.summary.RunSummary` on device — the
        request count is unbounded by memory (the reference's analogue:
        Fortio streams requests and keeps only histograms,
        perf/benchmark/runner/fortio.py:38-75).  Arrival clocks carry
        across blocks, so chaos phases and closed-loop pacing see one
        continuous timeline.

        ``trim=True`` also accumulates the reference collector's
        steady-state window (fortio.py:116-121: skip 62s, cap 180s) into
        the summary's ``win_*`` fields.  The window is placed from the
        run's *expected* duration (simulated count / offered rate) since
        the actual end isn't known until the scan finishes; the relative
        error is O(1/sqrt(N)) of the arrival process.
        """
        if load.kind == OPEN_LOOP:
            offered = float(load.qps)
            pace = 0.0
            nominal = 0.0
            conns = 0
            block = max(1, min(block_size, num_requests))
        else:
            conns = load.connections
            offered = self.solve_closed_rate(load, num_requests, key,
                                             fixed_point_iters)
            pace = conns / load.qps if load.qps is not None else 0.0
            nominal = conns / offered
            # block_size is a soft HBM bound: each connection needs at
            # least one request per block, so when connections > block_size
            # the block grows to ``connections`` requests
            per = max(1, min(block_size, num_requests) // conns)
            block = per * conns
        num_blocks = max(1, -(-num_requests // block))
        if trim:
            # lazy: metrics.fortio imports this module for its types
            from isotope_tpu.metrics.fortio import trim_window_bounds

            window = trim_window_bounds(num_blocks * block, offered)
        else:
            window = (0.0, np.inf)
        sat = self._saturated(load)
        fn = self._get_summary(block, num_blocks, load.kind, conns,
                               collector, trim, sat=sat)
        faults.check("engine.run")
        self._check_lb_load(load)
        telemetry.gauge_set("engine_block_requests", block)
        telemetry.gauge_set("engine_num_blocks", num_blocks)
        with self._detail_ctx():
            return fn(
                key, jnp.float32(offered), jnp.float32(pace),
                jnp.float32(offered), jnp.float32(nominal),
                jnp.float32(window[0]), jnp.float32(window[1]),
                self._vis_arg(offered),
                self._windows_arg(offered, sat),
            )

    # -- scenario ensembles (sim/ensemble.py) ---------------------------

    def _member_planner(self, events) -> "Simulator":
        """A host-side sibling Simulator carrying ONE fleet member's
        jittered chaos schedule: its phase reach multipliers, retry-
        feedback fixed point, drain windows, and closed-loop rate
        solves are exactly what the solo run with that schedule would
        use, so member k with the solo schedule reproduces its solo
        run bit-for-bit.  Only the HOST tables are read off planners;
        the traced fleet program belongs to ``self`` with the
        planner's chaos rows riding as stacked arguments."""
        return Simulator(
            self.compiled, self.params, chaos=events,
            churn=self._churn, mtls=self._mtls,
            policies=self._policies, rollouts=self._rollouts,
            lb=self._lb,
        )

    def _check_member_chaos(self) -> None:
        """Per-member chaos needs a base schedule to jitter; every
        other composition — ungraceful kills, rollouts, lb panic
        pools, saturated closed loops — now rides as stacked traced
        :class:`~isotope_tpu.compiler.compile.ChaosFx` leaves (the
        PR 18 universal-fleet contract)."""
        if not self.has_chaos:
            raise ValueError(
                "per-member chaos needs a base chaos schedule to "
                "jitter (Simulator(..., chaos=[...]))"
            )

    def _resolve_member_chaos(self, member_chaos, seeds,
                              with_pol: bool = False,
                              roll: bool = False,
                              sat_conns: int = 0):
        """Normalize the ``member_chaos`` fleet argument.

        Accepts a :class:`~isotope_tpu.resilience.faults.ChaosJitterSpec`
        (per-member schedules derived from the member seeds via the
        fold_in discipline), or an explicit per-member list of
        ``ChaosEvent`` sequences (the splitting estimator's re-folded
        clones).  ``with_pol`` stacks the policy chaos-down tables,
        ``roll`` the rollout canary-first split tables, and a nonzero
        ``sat_conns`` the saturated finite-population tables (fleets
        read exactly the ``chaos_fx_layout`` fields — absent layers
        skip the transfer).  Returns
        ``(member_events, planners, chaos_fx)`` —
        ``(None, None, None)`` when off."""
        if member_chaos is None:
            return None, None, None
        from isotope_tpu.compiler.compile import compile_chaos_members

        self._check_member_chaos()
        if isinstance(member_chaos, faults.ChaosJitterSpec):
            reps = self.compiled.services.replicas_by_name()
            E = len(self._chaos_events)
            member_events = [
                faults.jitter_chaos_events(
                    self._chaos_events, member_chaos,
                    faults.member_event_seeds(member_chaos, s, E),
                    reps,
                )
                for s in seeds
            ]
        else:
            member_events = [tuple(evts) for evts in member_chaos]
            if len(member_events) != len(seeds):
                raise ValueError(
                    f"member_chaos has {len(member_events)} schedules "
                    f"for {len(seeds)} members"
                )
        planners, fx = compile_chaos_members(
            self, member_events, with_pol=with_pol, roll=roll,
            sat_conns=sat_conns,
        )
        return member_events, planners, fx

    def _member_fn(self, block: int, num_blocks: int,
                   kind: str, connections: int, trim: bool,
                   sat: bool, jittered: bool,
                   member_chaos: bool = False,
                   carry_io: bool = False,
                   attr: Optional[str] = None,
                   tl_plan: Optional[Tuple[int, float]] = None,
                   prot: Optional[str] = None):
        """The ONE universal member block-scan program every fleet
        maps — plain, observed, protected, and search-bracket members
        are all flag combinations of the same body, with every layer
        an OPTIONAL leaf of one scan carry: absent layers ride as
        ``None`` and vanish from the jaxpr.

        Body-identical to the plain ``_get_summary`` scan (same
        fold_in layout, same summarize/reduce), so a seeds-only member
        reproduces its solo ``run_summary`` twin bit-for-bit; the
        jitter scales thread into ``_simulate_core`` only when
        ``jittered`` (the seeds-only fleet trace stays the solo trace,
        just batched).

        ``carry_io`` is the search-bracket contract (sim/search.py):
        the member takes extra traced arguments after the ten standard
        ones — a block offset ``b0`` plus the flattened scan-carry
        leaves (plain members: ``(t0, conn_t0, req_off)``; protected
        members: every leaf of :meth:`_protected_carry0`) — and
        returns ``(out, carry_out)``.  The per-block RNG folds
        ``1_000_000 + b0 + b`` so a member resumed at ``b0`` draws the
        EXACT streams the unbroken run drew for those blocks; with
        ``b0 == 0`` and zero carries the program is value-identical to
        the plain member (pinned by tests/test_search.py).

        ``attr`` / ``tl_plan`` arm the fleet observability pass: the
        member reduces an ``AttributionSummary`` (blame exemplar state
        in the scan carry, per-block blame vectors/hists in the
        stacked ys) and/or a ``TimelineSummary`` (carry-resident, the
        PR 7 recorder body), returning ``(summary[, tl][, attr])``.
        With ``attr`` the member takes ONE extra traced argument
        before the chaos rows: its ``tail_cut`` (``+inf`` = mean
        attribution).  Member k's blame/windows are bit-identical to
        its solo ``run_attributed`` / ``run_timeline`` twin.

        ``prot`` arms the protected layers: ``"policies"`` /
        ``"rollouts"`` thread the control state (breakers / budgets /
        HPA, rollout controller) through the carry exactly like the
        solo ``_get_protected`` body, returning
        ``(summary, tl[, roll][, pol][, attr])`` — a seeds-only
        member reproduces its solo ``run_policies`` / ``run_rollouts``
        twin bit-for-bit.

        ``member_chaos`` appends the member's stacked chaos rows — the
        composition's ``chaos_fx_layout`` fields (eff replicas, outage
        flags, and per the armed layers: policy chaos-down deltas,
        rollout canary-first split tables, LB panic healthy pools,
        ungraceful-kill reset rows, saturated finite-population
        tables), plus, under policies, the recorder-window down table
        the autoscaler's alive-capacity denominator reads — as
        trailing traced arguments.  With everything off this member
        program is the historical one, untouched."""
        from isotope_tpu.sim import summary as summary_mod

        protected = prot is not None
        roll = prot == "rollouts"
        with_pol = protected and self._policies is not None
        if protected and tl_plan is None:
            raise ValueError(
                "protected fleet members need a timeline plan (the "
                "flight recorder feeds the control loops)"
            )
        if carry_io and member_chaos:
            raise ValueError(
                "carry_io fleets (search brackets) do not support "
                "per-member chaos schedules yet (ROADMAP residual)"
            )
        if carry_io and (
            attr is not None
            or (tl_plan is not None and not protected)
        ):
            raise ValueError(
                "carry_io fleets (search brackets) do not carry the "
                "attribution/timeline reductions (screen first, then "
                "explain the winner with an observed fleet)"
            )
        c = max(connections, 1)
        per = block // c
        observed = attr is not None or tl_plan is not None
        packed = self.params.packed_carries
        if attr is not None:
            from isotope_tpu.metrics import attribution

            # trace constants (tables/top_k) build OUTSIDE the member
            # body — inside they would be cached as tracers and leak
            atables = self._attribution_tables()
            top_k = self.params.attribution_top_k
        if tl_plan is not None:
            from isotope_tpu.metrics import timeline as timeline_mod

            tspec = timeline_mod.build_spec(
                self.compiled, tl_plan[0], tl_plan[1]
            )
        if roll:
            from isotope_tpu.sim import rollout as rollout_mod

            rdtab = rollout_mod.device_tables(self._rollouts)
        if with_pol:
            from isotope_tpu.sim import policies as policies_mod

            pdtab = policies_mod.device_tables(self._policies)
            downed_w_const = self._policy_downed_windows(
                tspec, base_split=roll
            )
            stuck = faults.stuck_breaker()
            lag = faults.autoscaler_lag()
            retry_mask = jnp.asarray(self.compiled.hop_attempt > 0)
        if member_chaos:
            from isotope_tpu.compiler.compile import chaos_fx_layout

            layout = chaos_fx_layout(self, with_pol, roll, sat)
            n_rows = len(layout) + (1 if with_pol else 0)
        else:
            n_rows = 0
        tag = (
            ("rollouts-fleet" if roll else "policies-fleet")
            if protected else "ensemble"
        )

        def zero_carry(ex0=None):
            return self._protected_carry0(
                connections, tl_plan, roll=roll, with_pol=with_pol
            )[:-1] + (ex0,)

        def member_scan(key, offered_qps, pace_gap, nominal_gap,
                        win_lo, win_hi, visits_pc, phase_windows,
                        cpu_scale, err_scale, *rest):
            if protected:
                telemetry.record_trace(
                    (tag, self.signature[3], block, num_blocks, kind,
                     connections, trim, tl_plan, with_pol, jittered,
                     member_chaos)
                    + (("carry",) if carry_io else ())
                    + ((attr,) if attr is not None else ()),
                    tracing=isinstance(key, jax.core.Tracer),
                    requests=block, hops=self.compiled.num_hops,
                )
            else:
                telemetry.record_trace(
                    (tag, self.signature[3], block, num_blocks,
                     kind, connections, trim, sat, jittered,
                     member_chaos)
                    + (("carry",) if carry_io else ())
                    + ((attr,) if attr is not None else ())
                    + ((tl_plan,) if tl_plan is not None else ()),
                    tracing=isinstance(key, jax.core.Tracer),
                    requests=block * num_blocks,
                    hops=self.compiled.num_hops,
                )
            b0 = 0
            tail_cut = None
            chaos_rows = ()
            if carry_io:
                b0 = rest[0]
                carry_leaves = rest[1:]
            else:
                pos = 0
                if attr is not None:
                    tail_cut = rest[0]
                    pos = 1
                chaos_rows = rest[pos:pos + n_rows]
            if member_chaos:
                cfx = self._member_chaos_fx(
                    chaos_rows[:len(layout)], layout
                )
                downed_w = (
                    chaos_rows[len(layout)] if with_pol else None
                )
            else:
                cfx = None
                downed_w = downed_w_const if with_pol else None

            def body(carry, b):
                ((t0, conn_t0, req_off), tl_acc, robs_acc,
                 rstate, roll_acc, pobs_acc, pstate, pol_acc,
                 ex) = carry
                rfx = rollout_mod.effects(rstate) if roll else None
                pfx = (
                    policies_mod.effects(pstate)
                    if with_pol else None
                )
                kb = jax.random.fold_in(key, 1_000_000 + b0 + b)
                res, t_end, conn_end = self._simulate_core(
                    block, kind, connections, kb, offered_qps,
                    pace_gap, offered_qps, nominal_gap, t0,
                    conn_t0, req_off,
                    sat_conns=connections if sat else 0,
                    visits_pc=visits_pc,
                    phase_windows=phase_windows,
                    policy_fx=pfx,
                    rollout_fx=rfx,
                    cpu_scale=cpu_scale if jittered else None,
                    err_scale=err_scale if jittered else None,
                    chaos_fx=cfx,
                )
                s = summary_mod.summarize(
                    res, None,
                    window=(win_lo, win_hi) if trim else None,
                )
                if tl_plan is not None:
                    tl_acc = timeline_mod.accumulate(
                        tl_acc,
                        timeline_mod.timeline_block(
                            res, tspec, packed=packed
                        ),
                    )
                if protected:
                    t_done = (
                        jnp.min(conn_end)
                        if kind == CLOSED_LOOP
                        else t_end
                    )
                if roll:
                    robs_acc = (
                        robs_acc
                        + rollout_mod.observe_block(res, tspec)
                    )
                    rstate, rdelta = rollout_mod.advance(
                        rstate, rdtab, robs_acc, t_done, tspec
                    )
                    roll_acc = rollout_mod.accumulate_summary(
                        roll_acc, rdelta
                    )
                if with_pol:
                    pobs_acc = (
                        pobs_acc
                        + policies_mod.observe_block(
                            res, tspec, retry_mask
                        )
                    )
                    pstate, pdelta = policies_mod.advance(
                        pstate, pdtab, tl_acc, pobs_acc, t_done,
                        tspec, stuck_breaker=stuck,
                        downed_w=downed_w,
                    )
                    pol_acc = policies_mod.accumulate_summary(
                        pol_acc, pdelta
                    )
                ys = s
                if attr is not None:
                    a, ex = attribution.attribute_block(
                        res, atables,
                        tail_cut=(
                            tail_cut if attr == "tail" else None
                        ),
                        top_k=top_k, ex_state=ex,
                        packed=packed,
                    )
                    ys = (s, a)
                return (
                    (t_end, conn_end, req_off + per),
                    tl_acc, robs_acc, rstate, roll_acc,
                    pobs_acc, pstate, pol_acc, ex,
                ), ys

            if carry_io:
                if protected:
                    carry0 = jax.tree.unflatten(
                        jax.tree.structure(zero_carry()),
                        carry_leaves,
                    )
                else:
                    t0_in, conn_t0_in, req_off_in = carry_leaves
                    carry0 = (
                        (
                            jnp.asarray(t0_in, jnp.float32),
                            jnp.asarray(conn_t0_in, jnp.float32),
                            jnp.asarray(req_off_in, jnp.float32),
                        ),
                    ) + zero_carry()[1:]
            else:
                ex0 = None
                if attr is not None:
                    k0 = min(top_k, block) if top_k > 0 else 0
                    ex0 = (
                        attribution.empty_exemplars(
                            k0, self.compiled.num_hops
                        )
                        if k0 > 0
                        else None
                    )
                carry0 = zero_carry(ex0)
            carry_out, ys = jax.lax.scan(
                body, carry0, jnp.arange(num_blocks)
            )
            (_, tl_final, robs_final, _, roll_final, _, _,
             pol_final, ex_final) = carry_out
            if roll:
                roll_final = rollout_mod.attach_observations(
                    roll_final, robs_final
                )
            if attr is not None:
                parts, aparts = ys
                summary = summary_mod.reduce_stacked(parts)
                a_out = attribution.reduce_stacked(aparts, ex_final)
            else:
                summary = summary_mod.reduce_stacked(ys)
            if protected:
                out = (summary, tl_final)
                if roll:
                    out = out + (roll_final,)
                if with_pol:
                    out = out + (pol_final,)
                if attr is not None:
                    out = out + (a_out,)
                if carry_io:
                    return out, carry_out
                return out
            if observed:
                out = (summary,)
                if tl_plan is not None:
                    out = out + (tl_final,)
                if attr is not None:
                    out = out + (a_out,)
                return out
            if carry_io:
                return summary, carry_out[0]
            return summary

        return member_scan

    def _protected_carry0(self, connections: int,
                          tl_plan: Optional[Tuple[int, float]],
                          roll: bool = False,
                          with_pol: Optional[bool] = None):
        """The solo zero scan carry of the universal member body —
        every layer an optional pytree leaf: ``((t0, conn_t0,
        req_off), timeline, rollout obs/state/summary, policy
        obs/state/summary, exemplars)``, with ``None`` for the layers
        the composition leaves off.  The carry-I/O fleet contract
        flattens exactly these leaves (:meth:`zero_protected_carry`
        stacks them per member)."""
        if with_pol is None:
            with_pol = self._policies is not None
        c = max(connections, 1)
        tl0 = None
        if tl_plan is not None:
            from isotope_tpu.metrics import timeline as timeline_mod

            tspec = timeline_mod.build_spec(
                self.compiled, tl_plan[0], tl_plan[1]
            )
            S = self.compiled.num_services
            W = tspec.num_windows
            tl0 = timeline_mod.zeros_summary(
                tspec, packed=self.params.packed_carries
            )
        robs0 = rstate0 = racc0 = None
        if roll:
            from isotope_tpu.sim import rollout as rollout_mod

            rdtab = rollout_mod.device_tables(self._rollouts)
            robs0 = jnp.zeros((S, 2, W, 4))
            rstate0 = rollout_mod.init_state(rdtab)
            racc0 = rollout_mod.zeros_summary(tspec, S)
        pobs0 = pstate0 = pacc0 = None
        if with_pol:
            from isotope_tpu.sim import policies as policies_mod

            pdtab = policies_mod.device_tables(self._policies)
            pobs0 = jnp.zeros((S, W))
            pstate0 = policies_mod.init_state(
                pdtab, lag_periods=faults.autoscaler_lag()
            )
            pacc0 = policies_mod.zeros_summary(tspec, S)
        return (
            (
                jnp.float32(0.0),
                jnp.zeros((c,), jnp.float32),
                jnp.float32(0.0),
            ),
            tl0, robs0, rstate0, racc0, pobs0, pstate0, pacc0, None,
        )

    @staticmethod
    def _member_chaos_fx(chaos_rows, layout):
        """ONE member's :class:`~isotope_tpu.compiler.compile.ChaosFx`
        from the trailing positional chaos arguments of a fleet member
        program — the positional order is ``layout``
        (:func:`~isotope_tpu.compiler.compile.chaos_fx_layout`), the
        same tuple :meth:`_chaos_fx_args` packed with."""
        from isotope_tpu.compiler.compile import ChaosFx

        return ChaosFx(**dict(zip(layout, chaos_rows)))

    def _chaos_fx_args(self, fx, with_pol: bool, roll: bool = False,
                       sat: bool = False):
        """The stacked trailing chaos arguments matching
        :meth:`_member_chaos_fx`'s unpack order (the composition's
        ``chaos_fx_layout``)."""
        if fx is None:
            return ()
        from isotope_tpu.compiler.compile import chaos_fx_layout

        layout = chaos_fx_layout(self, with_pol, roll, sat)
        return tuple(getattr(fx, f) for f in layout)

    def _get_ensemble(self, block: int, num_blocks: int, kind: str,
                      connections: int, trim: bool, sat: bool,
                      chunk_members: int, jittered: bool,
                      mode: str = "vmap", member_chaos: bool = False,
                      attr: Optional[str] = None,
                      tl_plan: Optional[Tuple[int, float]] = None):
        """One jitted fleet program over a ``chunk_members``-wide
        member axis: ``vmap(member_scan)`` (true batch dim — the
        accelerator idiom) or ``lax.map`` over members (serial inside
        the program — the CPU idiom; see EnsembleSpec.mode).  The
        ensemble dim (chunk width + jitter arming + mode) keys the
        AOT executable cache — and ONLY those trace facts: the total
        fleet size stays out, so every chunk of a fleet, and every
        fleet auto-chunked to the same width, reuses ONE compile
        (in-process and through the persistent XLA cache)."""
        cache_key = (block, num_blocks, kind, connections, trim, sat,
                     chunk_members, jittered, mode, member_chaos,
                     attr, tl_plan)
        if cache_key not in self._ensemble_fns:
            member = self._member_fn(
                block, num_blocks, kind, connections, trim, sat,
                jittered, member_chaos=member_chaos, attr=attr,
                tl_plan=tl_plan,
            )
            if mode == "map":
                def fleet(*xs):
                    return jax.lax.map(lambda t: member(*t), xs)
            else:
                fleet = jax.vmap(member)
            self._ensemble_fns[cache_key] = (
                executable_cache.get_or_build(
                    ("ensemble", self.signature) + cache_key,
                    lambda: telemetry.time_first_call(
                        jax.jit(fleet),
                        "compile.jit_first_call",
                    ),
                )
            )
        return self._ensemble_fns[cache_key]

    def _get_search(self, block: int, num_blocks: int, kind: str,
                    connections: int, sat: bool, chunk_members: int,
                    jittered: bool, mode: str = "vmap"):
        """One jitted CARRY-I/O fleet program per rung shape: the
        :meth:`_get_ensemble` fleet with the four carry arguments
        threaded through (``b0, t0, conn_t0, req_off`` in, carry out)
        so a search bracket continues its survivors where the previous
        rung stopped instead of re-simulating from t=0.

        The carry buffers are donated (``donate_argnums``) on
        accelerators — each rung consumes the previous rung's gathered
        carries in place, so bracket memory stays O(survivors), not
        O(rungs x survivors).  CPU skips donation (XLA:CPU cannot
        alias them and warns per dispatch).  Cache family is
        ``("search", ...)``: rung shapes deliberately share executables
        across brackets of the same bucket width (sim/search.py pads
        rung widths to powers of two for exactly this reuse)."""
        cache_key = (block, num_blocks, kind, connections, sat,
                     chunk_members, jittered, mode)
        if cache_key not in self._search_fns:
            member = self._member_fn(
                block, num_blocks, kind, connections, False, sat,
                jittered, carry_io=True,
            )
            if mode == "map":
                def fleet(*xs):
                    return jax.lax.map(lambda t: member(*t), xs)
            else:
                fleet = jax.vmap(member)
            donate = (
                () if jax.default_backend() == "cpu" else (11, 12, 13)
            )
            self._search_fns[cache_key] = (
                executable_cache.get_or_build(
                    ("search", self.signature) + cache_key,
                    lambda: telemetry.time_first_call(
                        jax.jit(fleet, donate_argnums=donate),
                        "compile.jit_first_call",
                    ),
                )
            )
        return self._search_fns[cache_key]

    def _ensemble_args(self, load: LoadModel, num_requests: int,
                       key: jax.Array, spec, tables,
                       member_keys=None, block_size: int = 65_536,
                       trim: bool = False,
                       fixed_point_iters: int = 3,
                       member_qps=None, planners=None) -> dict:
        """Host-side per-member planning: stacked fleet arguments.

        One shared (block, num_blocks) shape serves every member (the
        whole point: one compile per fleet); per-member offered rates,
        trim windows, visit fixed points, and phase-window tables
        stack along the leading member axis.  Closed-loop members
        solve their equilibrium rate individually (with their own
        folded key — the solo solver's exact pilot streams), at the
        BASE cpu: a member cpu jitter perturbs the wait law and the
        service draws exactly, but the rate solve and the retry-
        feedback visit fixed point are base-cpu approximations.

        ``member_qps`` overrides each member's target qps with an
        EXACT per-member value (the runner's same-shape case collapse
        packs several grid cells' fleets into one dispatch this way —
        a relative qps_scale would re-round each cell's rate).

        ``planners`` (chaos fleets) supplies one host-side sibling
        Simulator per member carrying that member's jittered chaos
        schedule: rate solves, visit fixed points, and drain windows
        come off the member's OWN planner, so the stacked host
        arguments describe each member's bad day exactly.
        """
        sat = self._saturated(load)
        if sat and (spec.jittered or spec.qps_scale is not None):
            raise ValueError(
                "saturated -qps max ensembles support seed members "
                "only (the finite-population wait tables are host-side"
                " constants); pace the closed loop or jitter an "
                "open-loop run"
            )
        if spec.qps_scale is not None and load.qps is None:
            raise ValueError(
                "qps jitter needs a finite target qps (load.qps is "
                "None)"
            )
        n_mem = spec.members
        if member_qps is not None:
            member_qps = np.asarray(member_qps, np.float64)
            if member_qps.shape != (n_mem,):
                raise ValueError(
                    f"member_qps must have shape ({n_mem},); got "
                    f"{member_qps.shape}"
                )
            if sat:
                raise ValueError(
                    "member_qps cannot override a saturated -qps max "
                    "load"
                )
        if planners is not None and len(planners) != n_mem:
            raise ValueError(
                f"planners has {len(planners)} entries for {n_mem} "
                "members"
            )
        closed = load.kind != OPEN_LOOP
        if member_keys is None:
            if closed:
                # the closed-loop rate solver consumes each member's
                # key host-side (pilot streams) — materialize them
                member_keys = [
                    jax.random.fold_in(key, s) for s in spec.seeds
                ]
                keys_arr = jnp.stack(member_keys)
            else:
                # ONE vectorized derivation instead of N tiny
                # dispatches (threefry is bit-identical under vmap —
                # the member==solo pin covers this path); jitted so
                # repeat fleets skip the eager vmap retrace
                keys_arr = _fold_member_keys()(
                    key, jnp.asarray(spec.seeds, jnp.uint32)
                )
        else:
            member_keys = list(member_keys)
            if len(member_keys) != n_mem:
                raise ValueError(
                    f"member_keys has {len(member_keys)} entries for "
                    f"{n_mem} members"
                )
            keys_arr = jnp.stack(member_keys)
        if load.kind == OPEN_LOOP:
            conns = 0
            block = max(1, min(block_size, num_requests))
        else:
            conns = load.connections
            per = max(1, min(block_size, num_requests) // conns)
            block = per * conns
        num_blocks = max(1, -(-num_requests // block))
        if trim:
            from isotope_tpu.metrics.fortio import trim_window_bounds

        offered = np.empty(n_mem, np.float64)
        pace = np.empty(n_mem, np.float64)
        nominal = np.empty(n_mem, np.float64)
        win_lo = np.zeros(n_mem, np.float64)
        win_hi = np.full(n_mem, np.inf, np.float64)
        vis_rows = []
        win_rows = []
        # seeds-only fleets share one offered rate: build each
        # distinct rate's visit/window/trim tables ONCE (the fleet's
        # host planning must not cost O(members) table builds).
        # Per-member-chaos fleets key per member TOO — each planner's
        # tables describe a different schedule.
        per_off: Dict[float, tuple] = {}
        for m in range(n_mem):
            host = self if planners is None else planners[m]
            scale = float(tables.qps_scale[m])
            if member_qps is not None:
                qps_m = float(member_qps[m])
            elif load.qps is None:
                qps_m = None
            else:
                qps_m = (
                    float(load.qps)
                    if scale == 1.0
                    else float(load.qps) * scale
                )
            if load.kind == OPEN_LOOP:
                off = qps_m
                pc = 0.0
                nom = 0.0
            else:
                load_m = (
                    load
                    if qps_m == load.qps
                    else dataclasses.replace(load, qps=qps_m)
                )
                off = host.solve_closed_rate(
                    load_m, num_requests, member_keys[m],
                    fixed_point_iters,
                )
                pc = (
                    conns / load_m.qps
                    if load_m.qps is not None
                    else 0.0
                )
                nom = conns / off
            offered[m] = off
            pace[m] = pc
            nominal[m] = nom
            cache_k = off if planners is None else (m, off)
            if cache_k not in per_off:
                per_off[cache_k] = (
                    host._vis_arg(off),
                    host._windows_arg(off, sat),
                    trim_window_bounds(num_blocks * block, off)
                    if trim else (0.0, np.inf),
                )
            vis_m, win_m, (lo, hi) = per_off[cache_k]
            vis_rows.append(vis_m)
            win_rows.append(win_m)
            if trim:
                win_lo[m], win_hi[m] = lo, hi

        def _stack(rows):
            # rate-independent tables (no retry feedback / no drains)
            # hand every member the SAME row object: broadcast it
            # instead of paying members x device_put + concatenate
            first = rows[0]
            if all(r is first for r in rows[1:]):
                first = jnp.asarray(first)
                return jnp.broadcast_to(
                    first[None], (len(rows),) + first.shape
                )
            return jnp.stack(rows)

        return dict(
            sat=sat,
            kind=load.kind,
            conns=conns,
            block=block,
            num_blocks=num_blocks,
            keys=keys_arr,
            offered=offered,
            pace=pace,
            nominal=nominal,
            win_lo=win_lo,
            win_hi=win_hi,
            visits=_stack(vis_rows),
            windows=_stack(win_rows),
            cpu_scale=tables.cpu_scale,
            err_scale=tables.err_scale,
        )

    @staticmethod
    def _ensemble_stacked_args(args: dict):
        """The member-axis-stacked argument tuple of the vmapped fleet
        program, in ``member_scan`` order."""
        return (
            args["keys"],
            jnp.asarray(args["offered"], jnp.float32),
            jnp.asarray(args["pace"], jnp.float32),
            jnp.asarray(args["nominal"], jnp.float32),
            jnp.asarray(args["win_lo"], jnp.float32),
            jnp.asarray(args["win_hi"], jnp.float32),
            args["visits"],
            args["windows"],
            args["cpu_scale"],
            args["err_scale"],
        )

    @staticmethod
    def _ensemble_pad_args(stacked, n_mem: int, total: int):
        """Pad every member-stacked argument to ``total`` members by
        repeating the last member (the extras are dropped by
        :meth:`_ensemble_concat` after the dispatch).  The ONE pad law
        every chunked/sharded fleet path shares — the chunked ==
        unchunked and sharded == emulated bit-equality pins depend on
        each path padding identically."""
        if total == n_mem:
            return tuple(jnp.asarray(x) for x in stacked)

        def pad(x):
            x = jnp.asarray(x)
            reps = jnp.repeat(x[-1:], total - n_mem, axis=0)
            return jnp.concatenate([x, reps], axis=0)

        return tuple(pad(x) for x in stacked)

    @staticmethod
    def _ensemble_concat(parts, n_mem: int):
        """Concatenate per-chunk stacked summaries along the member
        axis and drop the pad — the shared inverse of
        :meth:`_ensemble_pad_args`."""
        if len(parts) == 1:
            return jax.tree.map(
                lambda x: np.asarray(x)[:n_mem], parts[0]
            )
        return jax.tree.map(
            lambda *xs: np.concatenate(
                [np.asarray(x) for x in xs], axis=0
            )[:n_mem],
            *parts,
        )

    def ensemble_chunk_size(self, members: int, block: int,
                            attr: bool = False,
                            timeline_windows: Optional[int] = None
                            ) -> int:
        """The auto member-chunk: how many fleet members fit one
        device dispatch, from the vet cost model's plan-only peak-
        bytes estimate vs device capacity — pre-computed the way the
        VET-M* memory verdict pre-selects degradation-ladder rungs
        (unknown capacity, e.g. CPU, runs the whole fleet at once).

        ``attr`` / ``timeline_windows`` add the stacked fleet
        observability footprint (members x blame hists + window
        series — the VET-M006 accounting) to the carry-aware split."""
        from isotope_tpu.analysis import costmodel

        cap = costmodel.device_capacity_bytes()
        est = costmodel.estimate_run(self, block)
        obs = costmodel.observability_carry_bytes(
            self, attr=attr, timeline_windows=timeline_windows,
        )
        return costmodel.ensemble_chunk(
            members, est.peak_bytes_at_block, cap,
            carry_bytes_per_member=obs,
        )

    def run_ensemble(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        spec=None,  # Optional[ensemble.EnsembleSpec]
        *,
        block_size: int = 65_536,
        trim: bool = False,
        fixed_point_iters: int = 3,
        chunk: Optional[int] = None,
        member_keys=None,
        member_qps=None,
        member_chaos=None,
        carry_in=None,
        return_carry: bool = False,
        block_offset: int = 0,
        attribution: bool = False,
        tail: bool = False,
        tail_cut: Optional[float] = None,
        timeline: bool = False,
        window_s: Optional[float] = None,
    ):
        """Simulate a Monte Carlo fleet: N scenario variants in ONE
        jitted program per device (sim/ensemble.py).

        Each member is a full ``run_summary``-shaped run of
        ``num_requests`` — member seeds derive their RNG via
        ``fold_in(key, seed)`` (the runner's checkpoint idiom), so a
        seeds-only member is bit-identical to the solo run with that
        folded key.  The fleet batches behind a leading ``vmap`` axis:
        one trace, one XLA compile, one dispatch per member-chunk.

        ``spec`` defaults to a seeds-only fleet of
        ``SimParams.ensemble`` members.  ``chunk`` (or ``spec.chunk``)
        caps members per dispatch; None pre-computes the chunk from
        the vet cost model (:meth:`ensemble_chunk_size`) so an
        over-wide fleet is a planned split, not an OOM.  Chunked and
        unchunked fleets are bit-equal (the member axis is
        embarrassingly parallel; pinned by tests/test_ensemble.py).

        ``member_keys`` overrides the seed derivation with explicit
        per-member base keys — the runner's same-shape case collapse
        packs several grid cells' fleets into one dispatch this way.

        ``member_chaos`` arms per-member chaos schedules (chaos
        fleets): a :class:`~isotope_tpu.resilience.faults.ChaosJitterSpec`
        jitters the base schedule's kill timing / target / magnitude
        per member (derived from the member seeds), or an explicit
        per-member list of ``ChaosEvent`` sequences runs exact
        schedules (the splitting estimator's clones).  Member k with
        the solo schedule stays bit-identical to its solo run; the
        stacked chaos rows ride as traced arguments so the whole
        fleet still compiles once.

        Returns an :class:`~isotope_tpu.sim.ensemble.EnsembleSummary`
        (per-member RunSummary stack + quantile bands + SLO-violation
        probabilities with Wilson CIs).  The per-service collector
        series stay out of the fleet program (O(N * S * buckets)
        leaves); run a solo collector pass for those.

        The carry export (search brackets, sim/search.py):
        ``block_offset`` resumes every member's per-block RNG at that
        block index, ``carry_in`` seeds the ``(t0, conn_t0, req_off)``
        scan carries (member-stacked; ``None`` = fresh t=0 start), and
        ``return_carry`` returns ``(summary, carry_out)`` so the next
        segment can continue where this one stopped.  A run split into
        carry-continued segments reproduces the unbroken run's RNG
        streams and carries exactly; the summed float reductions
        (``latency_sum``/``latency_m2``) may differ by reduction order
        like :func:`~isotope_tpu.sim.summary.summary_accumulate`.
        These knobs require ``trim=False`` and no ``member_chaos``.

        Fleet observability (metrics/fleetblame.py): ``attribution``
        (needs ``SimParams.attribution``) reduces each member's
        critical-path blame inside the same member body — the
        returned summary's ``attributions`` stacks per-member
        :class:`~isotope_tpu.metrics.attribution.AttributionSummary`
        leaves along the member axis, with member k bit-identical to
        its solo :meth:`run_attributed`.  ``tail=True`` arms the
        conditional-tail accumulators at ``tail_cut`` — estimated
        once from a pilot on the FLEET key when not given (one pilot
        serves every member; pass an explicit cut for exact
        solo-tail equivalence).  ``timeline`` (needs
        ``SimParams.timeline``) likewise stacks per-member
        :class:`~isotope_tpu.metrics.timeline.TimelineSummary` series
        under ``timelines`` — ``window_s`` overrides the window
        width.  With both off, every traced program and result is the
        historical one, byte-identical (pinned).
        """
        from isotope_tpu.compiler.compile import compile_ensemble
        from isotope_tpu.sim import ensemble as ens_mod

        if spec is None:
            if self.params.ensemble <= 0:
                raise ValueError(
                    "run_ensemble needs an EnsembleSpec (or "
                    "SimParams.ensemble > 0 for the seeds-only "
                    "default fleet)"
                )
            spec = ens_mod.EnsembleSpec.of(self.params.ensemble)
        spec.check(allow_duplicate_seeds=member_keys is not None)
        faults.check("engine.run")
        self._check_lb_load(load)
        if attribution and not self.params.attribution:
            raise ValueError(
                "attributed fleets need SimParams(attribution=True)"
            )
        if timeline and not self.params.timeline:
            raise ValueError(
                "timeline fleets need SimParams(timeline=True)"
            )
        if attribution and tail and tail_cut is None:
            # ONE pilot (on the fleet key) serves every member — a
            # per-member cut would cost N pilot dispatches; pass an
            # explicit tail_cut for exact solo-tail equivalence
            tail_cut = self.estimate_tail_cut(
                load, num_requests, key, block_size=block_size
            )
        tables = compile_ensemble(spec)
        sat_load = self._saturated(load)
        member_events, planners, chaos_fx = self._resolve_member_chaos(
            member_chaos, spec.seeds,
            sat_conns=load.connections if sat_load else 0,
        )
        args = self._ensemble_args(
            load, num_requests, key, spec, tables,
            member_keys=member_keys, block_size=block_size, trim=trim,
            fixed_point_iters=fixed_point_iters,
            member_qps=member_qps, planners=planners,
        )
        n_mem = spec.members
        carry_run = (
            carry_in is not None or return_carry or block_offset != 0
        )
        if carry_run and (trim or chaos_fx is not None):
            raise ValueError(
                "the ensemble carry export (carry_in/return_carry/"
                "block_offset) requires trim=False and no member_chaos"
            )
        observed = attribution or timeline
        if carry_run and observed:
            raise ValueError(
                "the ensemble carry export does not compose with the "
                "attribution/timeline reductions (screen first, then "
                "explain with an observed fleet)"
            )
        attr_mode = (
            ("tail" if tail else "mean") if attribution else None
        )
        tl_plan = None
        if timeline:
            tl_plan = self.plan_timeline_windows(
                args["num_blocks"] * args["block"],
                float(args["offered"][0]), window_s,
            )
        chunk_sz = chunk if chunk is not None else spec.chunk
        if chunk_sz is None:
            chunk_sz = self.ensemble_chunk_size(
                n_mem, args["block"], attr=attribution,
                timeline_windows=(
                    tl_plan[0] if tl_plan is not None else None
                ),
            )
        chunk_sz = max(1, min(int(chunk_sz), n_mem))
        n_chunks = -(-n_mem // chunk_sz)
        telemetry.counter_inc("ensemble_runs")
        telemetry.gauge_set("ensemble_members", n_mem)
        telemetry.gauge_set("ensemble_chunk", chunk_sz)
        telemetry.gauge_set("engine_block_requests", args["block"])
        telemetry.gauge_set("engine_num_blocks", args["num_blocks"])
        telemetry.set_meta("ensemble_mode", tables.mode)
        stacked = self._ensemble_stacked_args(args)
        if carry_run:
            fn = self._get_search(
                args["block"], args["num_blocks"], args["kind"],
                args["conns"], args["sat"], chunk_sz,
                tables.jittered, tables.mode,
            )
            if carry_in is None:
                carry_in = self.zero_ensemble_carry(
                    n_mem, args["conns"]
                )
            b0 = jnp.full((n_mem,), int(block_offset), jnp.int32)
            stacked = stacked + (b0,) + tuple(carry_in)
        else:
            fn = self._get_ensemble(
                args["block"], args["num_blocks"], args["kind"],
                args["conns"], trim, args["sat"], chunk_sz,
                tables.jittered, tables.mode,
                member_chaos=chaos_fx is not None,
                attr=attr_mode, tl_plan=tl_plan,
            )
            if attr_mode is not None:
                # per-member tail cuts ride as a traced argument
                # BEFORE the chaos rows (the member_scan unpack order)
                stacked = stacked + (jnp.full(
                    (n_mem,),
                    tail_cut
                    if (tail and tail_cut is not None)
                    else np.inf,
                    jnp.float32,
                ),)
            stacked = stacked + self._chaos_fx_args(
                chaos_fx, with_pol=False, sat=args["sat"]
            )
        padded = self._ensemble_pad_args(
            stacked, n_mem, n_chunks * chunk_sz,
        )
        parts = []
        carry_parts = []
        with self._detail_ctx():
            for ci in range(n_chunks):
                sl = slice(ci * chunk_sz, (ci + 1) * chunk_sz)
                out = fn(*(x[sl] for x in padded))
                if carry_run:
                    out, carry_out = out
                    carry_parts.append(carry_out)
                parts.append(out)
                if n_chunks > 1:
                    # serialize chunks: live memory stays bounded by
                    # one chunk's event tensors (the point of chunking)
                    head = parts[-1][0] if observed else parts[-1]
                    jax.block_until_ready(head.count)
        out = self._ensemble_concat(parts, n_mem)
        if observed:
            summaries = out[0]
            rest = list(out[1:])
            tl_stack = rest.pop(0) if timeline else None
            attr_stack = rest.pop(0) if attribution else None
        else:
            summaries, tl_stack, attr_stack = out, None, None
        ens = ens_mod.EnsembleSummary(
            spec=spec,
            summaries=summaries,
            offered_qps=args["offered"],
            chunk=chunk_sz,
            member_chaos=member_events,
            timelines=tl_stack,
            attributions=attr_stack,
        )
        if return_carry:
            return ens, self._ensemble_concat(carry_parts, n_mem)
        return ens

    @staticmethod
    def zero_ensemble_carry(n_mem: int, connections: int):
        """The fresh-start ``(t0, conn_t0, req_off)`` member-stacked
        carry — what a carry-I/O fleet resumes from at t=0 (the same
        zeros the plain member scan starts with)."""
        c = max(connections, 1)
        return (
            jnp.zeros((n_mem,), jnp.float32),
            jnp.zeros((n_mem, c), jnp.float32),
            jnp.zeros((n_mem,), jnp.float32),
        )

    def zero_protected_carry(self, n_mem: int, connections: int,
                             tl_plan: Tuple[int, float],
                             roll: bool = False):
        """The fresh-start member-stacked PROTECTED scan carry — the
        carry-I/O contract of :meth:`run_policies_ensemble` /
        :meth:`run_rollouts_ensemble`: every leaf of the universal
        member carry (:meth:`_protected_carry0` — clocks, timeline
        accumulator, rollout obs/state/summary, policy
        obs/state/summary) broadcast along a leading member axis.
        A protected search bracket resuming from exactly these zeros
        at ``block_offset=0`` is bit-identical to the unbroken
        protected fleet."""
        carry = self._protected_carry0(connections, tl_plan, roll=roll)
        return jax.tree.map(
            lambda x: jnp.broadcast_to(
                jnp.asarray(x)[None],
                (n_mem,) + jnp.shape(jnp.asarray(x)),
            ),
            carry,
        )

    def run_search(self, load: LoadModel, num_requests: int,
                   key: jax.Array, spec, *,
                   block_size: int = 65_536,
                   chunk: Optional[int] = None):
        """Screen a config population by successive halving in a few
        jitted dispatches (sim/search.py :func:`run_search`)."""
        from isotope_tpu.sim import search as search_mod

        return search_mod.run_search(
            self, load, num_requests, key, spec,
            block_size=block_size, chunk=chunk,
        )

    def run_search_protected(self, load: LoadModel, num_requests: int,
                             key: jax.Array, spec, *,
                             roll: bool = False,
                             block_size: int = 65_536,
                             chunk: Optional[int] = None,
                             window_s: Optional[float] = None):
        """Successive halving over a PROTECTED population — each
        candidate a full policies/rollouts member whose breakers,
        budgets, HPA, and rollout controller carry BETWEEN rungs via
        the :meth:`run_policies_ensemble` carry-I/O contract, ranked
        by any severity channel including ``trips`` (breaker trips +
        budget ejections).  sim/search.py
        :func:`run_search_protected`."""
        from isotope_tpu.sim import search as search_mod

        return search_mod.run_search_protected(
            self, load, num_requests, key, spec, roll=roll,
            block_size=block_size, chunk=chunk, window_s=window_s,
        )

    def plan_timeline_windows(
        self, total_requests: int, offered: float,
        window_s: Optional[float] = None,
    ) -> Tuple[int, float]:
        """Resolve the static ``(num_windows, window_s)`` grid for a
        run: the expected sim duration (requests / offered rate) cut
        into ``timeline_window_s`` windows, clamped (with a warning)
        by ``timeline_max_windows`` and the recorder's element budget
        instead of OOMing (metrics/timeline.py plan_windows)."""
        from isotope_tpu.metrics import timeline as timeline_mod

        dt = (
            float(window_s)
            if window_s is not None
            else self.params.timeline_window_s
        )
        expected = total_requests / max(float(offered), 1e-9)
        w, dt_eff, clamped = timeline_mod.plan_windows(
            expected, dt, self.params.timeline_max_windows,
            self.compiled.num_services,
        )
        if clamped:
            telemetry.counter_inc("timeline_window_clamps")
        return w, dt_eff

    def run_timeline(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        *,
        block_size: int = 65_536,
        collector=None,
        fixed_point_iters: int = 3,
        trim: bool = False,
        window_s: Optional[float] = None,
    ):
        """Like :meth:`run_summary`, but the block scan ALSO reduces a
        :class:`~isotope_tpu.metrics.timeline.TimelineSummary` — the
        flight recorder's per-service x per-window series, binned on
        device from each block's absolute sim-time clocks.

        Identical keys/blocking to :meth:`run_summary`, so the
        returned ``RunSummary`` matches an unrecorded run of the same
        arguments.  Returns ``(RunSummary, TimelineSummary)``.
        """
        if not self.params.timeline:
            raise ValueError(
                "timeline runs need SimParams(timeline=True)"
            )
        if load.kind == OPEN_LOOP:
            offered = float(load.qps)
            pace = 0.0
            nominal = 0.0
            conns = 0
            block = max(1, min(block_size, num_requests))
        else:
            conns = load.connections
            offered = self.solve_closed_rate(load, num_requests, key,
                                             fixed_point_iters)
            pace = conns / load.qps if load.qps is not None else 0.0
            nominal = conns / offered
            per = max(1, min(block_size, num_requests) // conns)
            block = per * conns
        num_blocks = max(1, -(-num_requests // block))
        if trim:
            from isotope_tpu.metrics.fortio import trim_window_bounds

            window = trim_window_bounds(num_blocks * block, offered)
        else:
            window = (0.0, np.inf)
        sat = self._saturated(load)
        tl_plan = self.plan_timeline_windows(
            num_blocks * block, offered, window_s
        )
        fn = self._get_summary(
            block, num_blocks, load.kind, conns, collector, trim,
            sat=sat, timeline=tl_plan,
        )
        faults.check("engine.run")
        self._check_lb_load(load)
        telemetry.gauge_set("engine_block_requests", block)
        telemetry.gauge_set("engine_num_blocks", num_blocks)
        telemetry.counter_inc("timeline_runs")
        with self._detail_ctx():
            return fn(
                key, jnp.float32(offered), jnp.float32(pace),
                jnp.float32(offered), jnp.float32(nominal),
                jnp.float32(window[0]), jnp.float32(window[1]),
                self._vis_arg(offered),
                self._windows_arg(offered, sat),
            )

    def run_policies(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        *,
        block_size: int = 65_536,
        collector=None,
        fixed_point_iters: int = 3,
        trim: bool = False,
        window_s: Optional[float] = None,
        attribution: bool = False,
        tail: bool = False,
        tail_cut: Optional[float] = None,
    ):
        """Co-simulate the per-service resilience policies
        (sim/policies.py) inside the block scan: the scan carry holds
        the policy state next to the flight-recorder accumulator, each
        block runs under the CURRENT policy effects (breaker sheds,
        budgeted retries, autoscaled capacity in the wait law), and the
        control law advances through every window the block completed
        — observation at window granularity, actuation at block
        granularity (one-block lag, the scrape-interval lag a real
        HPA/Envoy stack has).

        Returns ``(RunSummary, TimelineSummary, PolicySummary)`` — the
        summary/timeline reflect the PROTECTED physics.  Requires
        policy tables (``Simulator(..., policies=...)``) and
        ``SimParams.timeline=True`` (the recorder is the observation
        side of every control loop).  Saturated ``-qps max`` loads are
        rejected: the finite-population tables are host-built from
        static replica counts the policy state cannot reach.

        ``attribution=True`` (needs ``SimParams.attribution``) ALSO
        reduces the PR-5 critical-path blame over the protected
        physics inside the same scan — identical streams and policy
        trajectory — returning a 4-tuple ``(..., AttributionSummary)``
        so a protected run's blame shift is measurable against the
        unprotected twin's.  ``tail=True`` arms the conditional-tail
        accumulators at ``tail_cut`` (estimated from an UNPROTECTED
        pilot histogram when not given — conservative: the protected
        run's latencies sit below it, so the cut selects its deepest
        tail).
        """
        if self._policies is None:
            raise ValueError(
                "policy runs need compiled policy tables "
                "(Simulator(..., policies=compile_policies(graph, "
                "compiled)))"
            )
        if not self.params.timeline:
            raise ValueError(
                "policy runs need SimParams(timeline=True) — the "
                "flight recorder is the control loop's observation side"
            )
        if self._saturated(load):
            raise ValueError(
                "policy runs do not support saturated -qps max loads: "
                "the finite-population wait tables are host-built from "
                "static replica counts the policy state cannot change; "
                "use a paced closed loop or open loop"
            )
        if attribution and not self.params.attribution:
            raise ValueError(
                "attributed policy runs need SimParams(attribution="
                "True) alongside the policy tables"
            )
        # the policy layer's own chaos sites: standard fault kinds
        # (oom/transient/corrupt) raise classified faults here so the
        # supervisor's retry path covers the policy runner too; the
        # behavioral kinds (stuck/lag) alter the traced control program
        # below instead
        faults.check("policies.stuck_breaker")
        faults.check("policies.autoscaler_lag")
        return self._run_protected(
            load, num_requests, key, roll=False, block_size=block_size,
            collector=collector, fixed_point_iters=fixed_point_iters,
            trim=trim, window_s=window_s, attribution=attribution,
            tail=tail, tail_cut=tail_cut,
        )

    def _run_protected(self, load, num_requests, key, *, roll: bool,
                       block_size: int, collector, fixed_point_iters: int,
                       trim: bool, window_s: Optional[float],
                       attribution: bool, tail: bool,
                       tail_cut: Optional[float]):
        """Shared tail of the protected runners (:meth:`run_policies` /
        :meth:`run_rollouts`): tail-cut pilot, load planning, the jitted
        program fetch, and the traced invocation — one copy so the two
        control planes cannot diverge."""
        if attribution and tail and tail_cut is None:
            tail_cut = self.estimate_tail_cut(
                load, num_requests, key, block_size=block_size
            )
        if load.kind == OPEN_LOOP:
            offered = float(load.qps)
            pace = 0.0
            nominal = 0.0
            conns = 0
            block = max(1, min(block_size, num_requests))
        else:
            conns = load.connections
            offered = self.solve_closed_rate(load, num_requests, key,
                                             fixed_point_iters)
            pace = conns / load.qps if load.qps is not None else 0.0
            nominal = conns / offered
            per = max(1, min(block_size, num_requests) // conns)
            block = per * conns
        num_blocks = max(1, -(-num_requests // block))
        if trim:
            from isotope_tpu.metrics.fortio import trim_window_bounds

            window = trim_window_bounds(num_blocks * block, offered)
        else:
            window = (0.0, np.inf)
        tl_plan = self.plan_timeline_windows(
            num_blocks * block, offered, window_s
        )
        fn = self._get_protected(
            block, num_blocks, load.kind, conns, collector, trim,
            tl_plan,
            attr=("tail" if tail else "mean") if attribution else None,
            roll=roll,
        )
        faults.check("engine.run")
        self._check_lb_load(load)
        telemetry.gauge_set("engine_block_requests", block)
        telemetry.gauge_set("engine_num_blocks", num_blocks)
        telemetry.counter_inc("rollout_runs" if roll else "policy_runs")
        with self._detail_ctx():
            return fn(
                key, jnp.float32(offered), jnp.float32(pace),
                jnp.float32(offered), jnp.float32(nominal),
                jnp.float32(window[0]), jnp.float32(window[1]),
                jnp.float32(
                    tail_cut
                    if (attribution and tail_cut is not None)
                    else np.inf
                ),
                self._vis_arg(offered),
                self._windows_arg(offered, False),
            )

    def _policy_downed_windows(self, spec, base_split: bool = False):
        """(S, W) chaos-downed replica counts per recorder window (the
        nominal phase covering each window's END), or None without
        chaos — the autoscaler's alive-capacity denominator must see
        the kill or a dead service reads as idle and scales DOWN.

        ``base_split`` (rollout runs) reports the BASELINE arm's share
        of the delta only — the canary-first kill attribution removes
        canary pods before the pods the autoscaler manages."""
        if self._policies is None or not self.has_chaos:
            return None
        cuts = np.asarray(self._phase_starts, np.float64)
        w_end = (
            np.arange(spec.num_windows, dtype=np.float64) + 1.0
        ) * spec.window_s
        p_idx = np.clip(
            np.searchsorted(cuts, w_end, side="right") - 1,
            0, len(cuts) - 1,
        )
        downed = (
            self._downed_base_p_np if base_split else self._downed_p_np
        )
        return jnp.asarray(downed[p_idx].T, jnp.float32)

    def run_rollouts(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        *,
        block_size: int = 65_536,
        collector=None,
        fixed_point_iters: int = 3,
        trim: bool = False,
        window_s: Optional[float] = None,
        attribution: bool = False,
        tail: bool = False,
        tail_cut: Optional[float] = None,
    ):
        """Co-simulate the progressive-delivery rollout controller
        (sim/rollout.py) inside the block scan: the scan carry holds
        the per-service rollout state (step index, canary traffic
        weight, bake/cooldown clocks, per-arm sample accumulators)
        next to the flight-recorder accumulator, each block's hops
        route to the canary arm with the CURRENT weight (its own
        M/M/k station, error-rate and cpu-time overrides), and the
        controller advances through every completed window — PROMOTE /
        HOLD / ROLLBACK from the per-version observation channel.
        Same discretization as :meth:`run_policies`: window-granular
        observation, block-granular actuation (one-block lag).

        Returns ``(RunSummary, TimelineSummary, RolloutSummary)``;
        with policy tables ALSO compiled the PR 9 control loops ride
        the same carry (a rolled-back canary's load surge flows
        through breakers/HPA) and a ``PolicySummary`` is appended;
        ``attribution=True`` (needs ``SimParams.attribution``)
        additionally reduces the critical-path blame over the same
        physics and appends an ``AttributionSummary``.

        Requires rollout tables (``Simulator(..., rollouts=...)``) and
        ``SimParams.timeline=True``; saturated ``-qps max`` loads are
        rejected (static finite-population tables).  The baseline
        arm's station reports utilization/stability; the canary
        station's instability folds into its sampled waits.
        """
        if self._rollouts is None:
            raise ValueError(
                "rollout runs need compiled rollout tables "
                "(Simulator(..., rollouts=compile_rollouts(graph, "
                "compiled)))"
            )
        if not self.params.timeline:
            raise ValueError(
                "rollout runs need SimParams(timeline=True) — the "
                "flight recorder is the control loop's observation side"
            )
        if self._saturated(load):
            raise ValueError(
                "rollout runs do not support saturated -qps max loads: "
                "the finite-population wait tables are host-built from "
                "static replica counts the rollout state cannot split; "
                "use a paced closed loop or open loop"
            )
        if attribution and not self.params.attribution:
            raise ValueError(
                "attributed rollout runs need SimParams(attribution="
                "True) alongside the rollout tables"
            )
        if self._policies is not None:
            # the policy layer's chaos sites cover composed runs too
            faults.check("policies.stuck_breaker")
            faults.check("policies.autoscaler_lag")
        return self._run_protected(
            load, num_requests, key, roll=True, block_size=block_size,
            collector=collector, fixed_point_iters=fixed_point_iters,
            trim=trim, window_s=window_s, attribution=attribution,
            tail=tail, tail_cut=tail_cut,
        )

    def _get_protected(self, block: int, num_blocks: int, kind: str,
                       connections: int, collector, trim: bool,
                       tl_plan: Tuple[int, float],
                       attr: Optional[str] = None, *,
                       roll: bool = False):
        """Jitted scan-over-blocks program co-simulating the in-graph
        control planes — the PR 9 policy loops, the rollout controller,
        or BOTH in the same carry: carry = (clocks, timeline
        accumulator[, (S, 2, W, 4) per-version observation accumulator,
        rollout state, rollout series][, policy obs/state/series][,
        exemplar state]).  An absent layer rides as ``None`` (an empty
        pytree — the traced program never mentions it), so ONE body
        serves both protected runners and a fix applied to the policy
        wiring cannot diverge the composed path (the same rationale as
        parallel/sharded.py's ``_prot_body``).

        Return ordering (the runner unpacks by construction):
        ``roll`` -> (summary, tl, roll[, pol][, attr]); policies-only
        -> (summary, tl, pol[, attr])."""
        from isotope_tpu.metrics import timeline as timeline_mod
        from isotope_tpu.sim import summary as summary_mod

        with_pol = self._policies is not None
        tag = "rollouts" if roll else "policies"
        cache_key = (block, num_blocks, kind, connections,
                     collector is not None, trim, tl_plan, attr,
                     with_pol, tag)
        if cache_key not in self._summary_fns:
            c = max(connections, 1)
            per = block // c
            tspec = timeline_mod.build_spec(
                self.compiled, tl_plan[0], tl_plan[1]
            )
            S = self.compiled.num_services
            W = tspec.num_windows
            packed = self.params.packed_carries
            if roll:
                from isotope_tpu.sim import rollout as rollout_mod

                rdtab = rollout_mod.device_tables(self._rollouts)
            if with_pol:
                from isotope_tpu.sim import policies as policies_mod

                pdtab = policies_mod.device_tables(self._policies)
                # rollout runs split the canary-first kill delta off
                # the baseline arm the autoscaler manages
                downed_w = self._policy_downed_windows(
                    tspec, base_split=roll
                )
                stuck = faults.stuck_breaker()
                lag = faults.autoscaler_lag()
                retry_mask = jnp.asarray(self.compiled.hop_attempt > 0)
            if attr is not None:
                from isotope_tpu.metrics import attribution

                atables = self._attribution_tables()
                top_k = self.params.attribution_top_k

            def scanfn(key, offered_qps, pace_gap, arrival_qps,
                       nominal_gap, win_lo, win_hi, tail_cut,
                       visits_pc, phase_windows):
                telemetry.record_trace(
                    (tag, self.signature[3]) + cache_key,
                    tracing=isinstance(key, jax.core.Tracer),
                    requests=block, hops=self.compiled.num_hops,
                )

                def body(carry, b):
                    ((t0, conn_t0, req_off), tl_acc, robs_acc,
                     rstate, roll_acc, pobs_acc, pstate, pol_acc,
                     ex) = carry
                    rfx = rollout_mod.effects(rstate) if roll else None
                    pfx = (
                        policies_mod.effects(pstate)
                        if with_pol else None
                    )
                    kb = jax.random.fold_in(key, 1_000_000 + b)
                    res, t_end, conn_end = self._simulate_core(
                        block, kind, connections, kb, offered_qps,
                        pace_gap, arrival_qps, nominal_gap, t0,
                        conn_t0, req_off,
                        visits_pc=visits_pc,
                        phase_windows=phase_windows,
                        policy_fx=pfx,
                        rollout_fx=rfx,
                    )
                    s = summary_mod.summarize(
                        res, collector,
                        window=(win_lo, win_hi) if trim else None,
                    )
                    tl_acc = timeline_mod.accumulate(
                        tl_acc,
                        timeline_mod.timeline_block(
                            res, tspec, packed=packed
                        ),
                    )
                    # closed loop: a window is final only once the
                    # SLOWEST connection passed it — later blocks on
                    # faster connections still write into windows
                    # before conn_end.max()
                    t_done = (
                        jnp.min(conn_end)
                        if kind == CLOSED_LOOP
                        else t_end
                    )
                    if roll:
                        robs_acc = (
                            robs_acc
                            + rollout_mod.observe_block(res, tspec)
                        )
                        rstate, rdelta = rollout_mod.advance(
                            rstate, rdtab, robs_acc, t_done, tspec
                        )
                        roll_acc = rollout_mod.accumulate_summary(
                            roll_acc, rdelta
                        )
                    if with_pol:
                        pobs_acc = (
                            pobs_acc
                            + policies_mod.observe_block(
                                res, tspec, retry_mask
                            )
                        )
                        pstate, pdelta = policies_mod.advance(
                            pstate, pdtab, tl_acc, pobs_acc, t_done,
                            tspec, stuck_breaker=stuck,
                            downed_w=downed_w,
                        )
                        pol_acc = policies_mod.accumulate_summary(
                            pol_acc, pdelta
                        )
                    ys = s
                    if attr is not None:
                        a, ex = attribution.attribute_block(
                            res, atables,
                            tail_cut=(
                                tail_cut if attr == "tail" else None
                            ),
                            top_k=top_k, ex_state=ex,
                            packed=packed,
                        )
                        ys = (s, a)
                    return (
                        (t_end, conn_end, req_off + per),
                        tl_acc, robs_acc, rstate, roll_acc,
                        pobs_acc, pstate, pol_acc, ex,
                    ), ys

                ex0 = None
                if attr is not None:
                    k0 = min(top_k, block) if top_k > 0 else 0
                    H = self.compiled.num_hops
                    ex0 = (
                        attribution.empty_exemplars(k0, H)
                        if k0 > 0
                        else None
                    )
                carry0 = (
                    (
                        jnp.float32(0.0),
                        jnp.zeros((c,), jnp.float32),
                        jnp.float32(0.0),
                    ),
                    timeline_mod.zeros_summary(tspec, packed=packed),
                    jnp.zeros((S, 2, W, 4)) if roll else None,
                    rollout_mod.init_state(rdtab) if roll else None,
                    (
                        rollout_mod.zeros_summary(tspec, S)
                        if roll else None
                    ),
                    jnp.zeros((S, W)) if with_pol else None,
                    (
                        policies_mod.init_state(pdtab, lag_periods=lag)
                        if with_pol else None
                    ),
                    (
                        policies_mod.zeros_summary(tspec, S)
                        if with_pol else None
                    ),
                    ex0,
                )
                (
                    (_, tl_final, robs_final, _, roll_final, _, _,
                     pol_final, ex_final),
                    ys,
                ) = jax.lax.scan(body, carry0, jnp.arange(num_blocks))
                if roll:
                    roll_final = rollout_mod.attach_observations(
                        roll_final, robs_final
                    )
                if attr is not None:
                    parts, aparts = ys
                    summary = summary_mod.reduce_stacked(parts)
                    a_out = attribution.reduce_stacked(
                        aparts, ex_final
                    )
                else:
                    summary = summary_mod.reduce_stacked(ys)
                out = (summary, tl_final)
                if roll:
                    out = out + (roll_final,)
                if with_pol:
                    out = out + (pol_final,)
                if attr is not None:
                    out = out + (a_out,)
                return out

            self._summary_fns[cache_key] = executable_cache.get_or_build(
                (tag, self.signature) + cache_key,
                lambda: telemetry.time_first_call(
                    jax.jit(scanfn), "compile.jit_first_call"
                ),
            )
        return self._summary_fns[cache_key]

    # -- protected ensembles: chaos fleets (sim/ensemble.py) ------------

    def _get_protected_ensemble(self, block: int, num_blocks: int,
                                kind: str, connections: int,
                                trim: bool, tl_plan: Tuple[int, float],
                                roll: bool, chunk_members: int,
                                jittered: bool, mode: str,
                                member_chaos: bool,
                                attr: Optional[str] = None,
                                carry_io: bool = False):
        """One jitted PROTECTED fleet program over a
        ``chunk_members``-wide member axis (the :meth:`_get_ensemble`
        batching applied to the protected member scan).  The control
        state is per member — each member's breakers / budgets / HPA /
        rollout controller react to ITS OWN bad day — which is exactly
        why the stacked carry batches for free under vmap.

        ``carry_io`` is the protected search-bracket program: the
        member takes ``(b0, *carry_leaves)`` after the standard ten
        arguments and returns ``(out, carry)`` — the contract
        :meth:`zero_protected_carry` documents."""
        cache_key = ("prot-ens", block, num_blocks, kind, connections,
                     trim, tl_plan, roll, chunk_members, jittered,
                     mode, member_chaos, attr, carry_io)
        if cache_key not in self._ensemble_fns:
            member = self._member_fn(
                block, num_blocks, kind, connections, trim, False,
                jittered, member_chaos=member_chaos,
                carry_io=carry_io, attr=attr, tl_plan=tl_plan,
                prot="rollouts" if roll else "policies",
            )
            if mode == "map":
                def fleet(*xs):
                    return jax.lax.map(lambda t: member(*t), xs)
            else:
                fleet = jax.vmap(member)
            self._ensemble_fns[cache_key] = (
                executable_cache.get_or_build(
                    ("ensemble", self.signature) + cache_key,
                    lambda: telemetry.time_first_call(
                        jax.jit(fleet),
                        "compile.jit_first_call",
                    ),
                )
            )
        return self._ensemble_fns[cache_key]

    def protected_ensemble_chunk(self, members: int, block: int,
                                 tl_plan: Tuple[int, float],
                                 roll: bool,
                                 attr: bool = False) -> int:
        """The protected fleet's auto member-chunk: the plain fleet's
        capacity split (:meth:`ensemble_chunk_size`) extended with the
        stacked per-member control carry — timeline accumulator plus
        policy / rollout state and series — the VET-T025 accounting,
        and (``attr``) the stacked blame footprint (VET-M006)."""
        from isotope_tpu.analysis import costmodel

        cap = costmodel.device_capacity_bytes()
        est = costmodel.estimate_run(self, block)
        carry = costmodel.protected_carry_bytes(
            self, tl_plan[0], roll=roll,
        )
        if attr:
            carry += costmodel.observability_carry_bytes(
                self, attr=True,
            )
        return costmodel.ensemble_chunk(
            members, est.peak_bytes_at_block, cap,
            carry_bytes_per_member=carry,
        )

    def run_policies_ensemble(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        spec=None,  # Optional[ensemble.EnsembleSpec]
        *,
        block_size: int = 65_536,
        trim: bool = False,
        window_s: Optional[float] = None,
        fixed_point_iters: int = 3,
        chunk: Optional[int] = None,
        member_keys=None,
        member_qps=None,
        member_chaos=None,
        attribution: bool = False,
        tail: bool = False,
        tail_cut: Optional[float] = None,
        carry_in=None,
        return_carry: bool = False,
        block_offset: int = 0,
    ):
        """A Monte Carlo fleet of PROTECTED runs: N members of
        :meth:`run_policies` behind one jitted program per device —
        each member's policy control loops (breakers, retry budgets,
        HPA) ride its own scan carry and react to its own streams
        (and, under ``member_chaos``, its own jittered failure
        schedule).  A seeds-only member is bit-identical to the solo
        ``run_policies`` with its folded key (pinned).

        ``attribution=True`` threads the critical-path blame pass
        through every member (needs ``SimParams(attribution=True)``):
        the returned fleet carries a stacked
        :class:`~isotope_tpu.metrics.attribution.AttributionSummary`
        (``attributions``), member k bit-identical to its solo
        attributed twin.

        The carry export (protected search brackets, sim/search.py):
        ``block_offset`` resumes every member's per-block RNG at that
        block index, ``carry_in`` seeds the FULL protected scan carry
        (clocks + timeline accumulator + policy/rollout control
        state, member-stacked; ``None`` = the
        :meth:`zero_protected_carry` fresh start), and
        ``return_carry`` returns ``(summary, carry_out)`` so the next
        rung continues each survivor's breakers / budgets / recorder
        where this segment stopped.  A bracket's rung 0 at
        ``block_offset=0`` with zero carries is bit-identical to the
        unbroken protected fleet (pinned by tests).  These knobs
        require ``trim=False``, no ``member_chaos``, and no
        ``attribution``.

        Returns an :class:`~isotope_tpu.sim.ensemble.EnsembleSummary`
        with the per-member ``TimelineSummary`` and ``PolicySummary``
        stacks attached (``timelines`` / ``policies``), severity
        ranking, and the worst-member postmortem accessors."""
        if self._policies is None:
            raise ValueError(
                "policy fleets need compiled policy tables "
                "(Simulator(..., policies=...))"
            )
        if not self.params.timeline:
            raise ValueError(
                "policy fleets need SimParams(timeline=True) — the "
                "flight recorder is the control loop's observation side"
            )
        faults.check("policies.stuck_breaker")
        faults.check("policies.autoscaler_lag")
        return self._run_protected_ensemble(
            load, num_requests, key, spec, roll=False,
            block_size=block_size, trim=trim, window_s=window_s,
            fixed_point_iters=fixed_point_iters, chunk=chunk,
            member_keys=member_keys, member_qps=member_qps,
            member_chaos=member_chaos, attribution=attribution,
            tail=tail, tail_cut=tail_cut,
            carry_in=carry_in, return_carry=return_carry,
            block_offset=block_offset,
        )

    def run_rollouts_ensemble(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        spec=None,
        *,
        block_size: int = 65_536,
        trim: bool = False,
        window_s: Optional[float] = None,
        fixed_point_iters: int = 3,
        chunk: Optional[int] = None,
        member_keys=None,
        member_qps=None,
        member_chaos=None,
        attribution: bool = False,
        tail: bool = False,
        tail_cut: Optional[float] = None,
        carry_in=None,
        return_carry: bool = False,
        block_offset: int = 0,
    ):
        """A Monte Carlo fleet of :meth:`run_rollouts` runs — the
        progressive-delivery controller advanced per member in the
        stacked scan carry (plus the PR 9 policy loops when policy
        tables are also compiled).  ``member_chaos`` composes with the
        rollout split: each member's canary-first kill-split tables
        ride as traced rows next to its chaos schedule (chaos ×
        rollout fleets), with member k bit-identical to its solo
        chaos ``run_rollouts`` twin.  ``attribution=True`` threads the
        blame pass through every member, and the
        ``carry_in``/``return_carry``/``block_offset`` carry export
        works as in :meth:`run_policies_ensemble` (protected search
        brackets)."""
        if self._rollouts is None:
            raise ValueError(
                "rollout fleets need compiled rollout tables "
                "(Simulator(..., rollouts=...))"
            )
        if not self.params.timeline:
            raise ValueError(
                "rollout fleets need SimParams(timeline=True) — the "
                "flight recorder is the control loop's observation side"
            )
        if self._policies is not None:
            faults.check("policies.stuck_breaker")
            faults.check("policies.autoscaler_lag")
        return self._run_protected_ensemble(
            load, num_requests, key, spec, roll=True,
            block_size=block_size, trim=trim, window_s=window_s,
            fixed_point_iters=fixed_point_iters, chunk=chunk,
            member_keys=member_keys, member_qps=member_qps,
            member_chaos=member_chaos, attribution=attribution,
            tail=tail, tail_cut=tail_cut,
            carry_in=carry_in, return_carry=return_carry,
            block_offset=block_offset,
        )

    def _run_protected_ensemble(self, load, num_requests, key, spec,
                                *, roll: bool, block_size: int,
                                trim: bool, window_s: Optional[float],
                                fixed_point_iters: int,
                                chunk: Optional[int], member_keys,
                                member_qps, member_chaos,
                                attribution: bool = False,
                                tail: bool = False,
                                tail_cut: Optional[float] = None,
                                carry_in=None,
                                return_carry: bool = False,
                                block_offset: int = 0):
        """Shared tail of the protected fleet runners — the
        :meth:`run_ensemble` planning/dispatch pipeline over the
        protected member program."""
        from isotope_tpu.compiler.compile import compile_ensemble
        from isotope_tpu.metrics import timeline as timeline_mod
        from isotope_tpu.sim import ensemble as ens_mod

        if attribution and not self.params.attribution:
            raise ValueError(
                "attributed fleets need SimParams(attribution=True)"
            )
        if attribution and tail and tail_cut is None:
            # ONE pilot (on the fleet key) serves every member; pass
            # an explicit tail_cut for exact solo-tail equivalence
            tail_cut = self.estimate_tail_cut(
                load, num_requests, key, block_size=block_size
            )
        if spec is None:
            if self.params.ensemble <= 0:
                raise ValueError(
                    "protected fleets need an EnsembleSpec (or "
                    "SimParams.ensemble > 0 for the seeds-only "
                    "default fleet)"
                )
            spec = ens_mod.EnsembleSpec.of(self.params.ensemble)
        spec.check(allow_duplicate_seeds=member_keys is not None)
        if self._saturated(load):
            raise ValueError(
                "protected fleets do not support saturated -qps max "
                "loads (static finite-population tables; see "
                "run_policies)"
            )
        faults.check("engine.run")
        self._check_lb_load(load)
        tables = compile_ensemble(spec)
        member_events, planners, chaos_fx = self._resolve_member_chaos(
            member_chaos, spec.seeds, with_pol=True, roll=roll,
        )
        args = self._ensemble_args(
            load, num_requests, key, spec, tables,
            member_keys=member_keys, block_size=block_size, trim=trim,
            fixed_point_iters=fixed_point_iters,
            member_qps=member_qps, planners=planners,
        )
        n_mem = spec.members
        carry_run = (
            carry_in is not None or return_carry or block_offset != 0
        )
        if carry_run and (trim or chaos_fx is not None or attribution):
            raise ValueError(
                "the protected carry export (carry_in/return_carry/"
                "block_offset) requires trim=False, no member_chaos, "
                "and no attribution"
            )
        tl_plan = self.plan_timeline_windows(
            args["num_blocks"] * args["block"],
            float(args["offered"][0]), window_s,
        )
        chaos_args = self._chaos_fx_args(
            chaos_fx, with_pol=True, roll=roll
        )
        if chaos_fx is not None and self._policies is not None:
            # the recorder-window chaos-down table the autoscaler's
            # alive-capacity denominator reads, per member
            tspec = timeline_mod.build_spec(
                self.compiled, tl_plan[0], tl_plan[1]
            )
            chaos_args = chaos_args + (jnp.stack([
                pl._policy_downed_windows(tspec, base_split=roll)
                for pl in planners
            ]),)
        attr_mode = (
            ("tail" if tail else "mean") if attribution else None
        )
        cut_arg = ()
        if attribution:
            cut_arg = (jnp.full(
                (n_mem,),
                tail_cut if (tail and tail_cut is not None) else np.inf,
                jnp.float32,
            ),)
        chunk_sz = chunk if chunk is not None else spec.chunk
        if chunk_sz is None:
            chunk_sz = self.protected_ensemble_chunk(
                n_mem, args["block"], tl_plan, roll,
                attr=attribution,
            )
        chunk_sz = max(1, min(int(chunk_sz), n_mem))
        n_chunks = -(-n_mem // chunk_sz)
        telemetry.counter_inc(
            "rollout_fleet_runs" if roll else "policy_fleet_runs"
        )
        telemetry.gauge_set("ensemble_members", n_mem)
        telemetry.gauge_set("ensemble_chunk", chunk_sz)
        telemetry.gauge_set("engine_block_requests", args["block"])
        telemetry.gauge_set("engine_num_blocks", args["num_blocks"])
        telemetry.set_meta("ensemble_mode", tables.mode)
        fn = self._get_protected_ensemble(
            args["block"], args["num_blocks"], args["kind"],
            args["conns"], trim, tl_plan, roll, chunk_sz,
            tables.jittered, tables.mode, chaos_fx is not None,
            attr=attr_mode, carry_io=carry_run,
        )
        stacked = (
            self._ensemble_stacked_args(args) + cut_arg + chaos_args
        )
        if carry_run:
            if carry_in is None:
                carry_in = self.zero_protected_carry(
                    n_mem, args["conns"], tl_plan, roll=roll,
                )
            b0 = jnp.full((n_mem,), int(block_offset), jnp.int32)
            stacked = stacked + (b0,) + tuple(
                jax.tree.leaves(carry_in)
            )
        padded = self._ensemble_pad_args(
            stacked, n_mem, n_chunks * chunk_sz,
        )
        parts = []
        carry_parts = []
        with self._detail_ctx():
            for ci in range(n_chunks):
                sl = slice(ci * chunk_sz, (ci + 1) * chunk_sz)
                out = fn(*(x[sl] for x in padded))
                if carry_run:
                    out, carry_out = out
                    carry_parts.append(carry_out)
                parts.append(out)
                if n_chunks > 1:
                    jax.block_until_ready(parts[-1][0].count)
        out = self._ensemble_concat(parts, n_mem)
        # unpack by construction (the universal member ordering):
        # roll -> (summary, tl, roll[, pol][, attr]); policies-only ->
        # (summary, tl, pol[, attr])
        summary, tl = out[0], out[1]
        rest = list(out[2:])
        roll_stack = rest.pop(0) if roll else None
        pol_stack = (
            rest.pop(0) if self._policies is not None else None
        )
        attr_stack = rest.pop(0) if attribution else None
        ens = ens_mod.EnsembleSummary(
            spec=spec,
            summaries=summary,
            offered_qps=args["offered"],
            chunk=chunk_sz,
            member_chaos=member_events,
            timelines=tl,
            policies=pol_stack,
            rollouts=roll_stack,
            attributions=attr_stack,
        )
        if return_carry:
            return ens, self._ensemble_concat(carry_parts, n_mem)
        return ens

    def _attribution_tables(self):
        """Blame-sweep index tables (metrics/attribution.py), built
        lazily — a Simulator that never runs attributed pays nothing."""
        if self._attr_tables is None:
            from isotope_tpu.metrics import attribution

            self._attr_tables = attribution.build_tables(
                self.compiled, self.params.network
            )
        return self._attr_tables

    def estimate_tail_cut(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        *,
        block_size: int = 65_536,
        quantile: Optional[float] = None,
    ) -> float:
        """Streaming-threshold tail cut: a small pilot run's latency
        histogram recovers the requested quantile (p99 by default) so
        the conditional-tail accumulators of an attributed run can be
        filled in ONE pass instead of two full passes."""
        from isotope_tpu.metrics.histogram import quantile_from_histogram

        q = (
            quantile
            if quantile is not None
            else self.params.attribution_tail_quantile
        )
        pilot_n = max(1, min(num_requests, 8_192))
        pilot = self.run_summary(
            load, pilot_n, jax.random.fold_in(key, 777_000),
            block_size=min(block_size, pilot_n)
            if load.kind == OPEN_LOOP
            else block_size,
        )
        return float(
            quantile_from_histogram(
                np.asarray(pilot.latency_hist), [q]
            )[0]
        )

    def run_attributed(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        *,
        block_size: int = 65_536,
        collector=None,
        fixed_point_iters: int = 3,
        trim: bool = False,
        tail: bool = False,
        tail_cut: Optional[float] = None,
    ):
        """Like :meth:`run_summary`, but the block scan ALSO reduces an
        :class:`~isotope_tpu.metrics.attribution.AttributionSummary` —
        per-hop critical-path blame, wait-vs-service split, blame
        histograms, and top-K tail exemplars, all on device.

        Identical keys/blocking to :meth:`run_summary`, so the returned
        ``RunSummary`` matches an unattributed run of the same
        arguments.  ``tail=True`` arms the conditional-tail
        accumulators at ``tail_cut`` (estimated from a pilot histogram
        when not given).  Returns ``(RunSummary, AttributionSummary)``.
        """
        if not self.params.attribution:
            raise ValueError(
                "attributed runs need SimParams(attribution=True)"
            )
        if tail and tail_cut is None:
            tail_cut = self.estimate_tail_cut(
                load, num_requests, key, block_size=block_size
            )
        if load.kind == OPEN_LOOP:
            offered = float(load.qps)
            pace = 0.0
            nominal = 0.0
            conns = 0
            block = max(1, min(block_size, num_requests))
        else:
            conns = load.connections
            offered = self.solve_closed_rate(load, num_requests, key,
                                             fixed_point_iters)
            pace = conns / load.qps if load.qps is not None else 0.0
            nominal = conns / offered
            per = max(1, min(block_size, num_requests) // conns)
            block = per * conns
        num_blocks = max(1, -(-num_requests // block))
        if trim:
            from isotope_tpu.metrics.fortio import trim_window_bounds

            window = trim_window_bounds(num_blocks * block, offered)
        else:
            window = (0.0, np.inf)
        sat = self._saturated(load)
        fn = self._get_summary(
            block, num_blocks, load.kind, conns, collector, trim,
            sat=sat, attr="tail" if tail else "mean",
        )
        faults.check("engine.run")
        self._check_lb_load(load)
        telemetry.gauge_set("engine_block_requests", block)
        telemetry.gauge_set("engine_num_blocks", num_blocks)
        telemetry.counter_inc("attributed_runs")
        with self._detail_ctx():
            return fn(
                key, jnp.float32(offered), jnp.float32(pace),
                jnp.float32(offered), jnp.float32(nominal),
                jnp.float32(window[0]), jnp.float32(window[1]),
                jnp.float32(tail_cut if tail else np.inf),
                self._vis_arg(offered),
                self._windows_arg(offered, sat),
            )

    def trace_entry_args(self, n: int, kind: str, connections: int = 0):
        """``(fn, abstract_args)`` for trace-only analysis.

        The static-analysis subsystem (analysis/jaxpr_audit.py) runs
        ``jax.make_jaxpr(fn)(*abstract_args)`` to obtain the exact
        program a run of ``n`` requests would jit — every argument is a
        ``jax.ShapeDtypeStruct``, so nothing touches a device and no
        XLA compile happens.  ``sat`` is always False: the saturated
        ``-qps max`` tables are built by host-side pilot *executions*
        (``_closed_tables``), which a trace-only caller must not
        trigger; the plain closed-loop program shares the same sweep
        body and segment structure.
        """
        sds = jax.ShapeDtypeStruct
        f32 = jnp.float32
        P = int(self._phase_starts.shape[0]) * self._num_combos
        args = (
            sds((2,), jnp.uint32),       # PRNG key
            sds((), f32), sds((), f32),  # offered_qps, pace_gap
            sds((), f32), sds((), f32),  # arrival_qps, nominal_gap
            sds((P, self.compiled.num_services), f32),  # visits_pc
            sds((2, self._num_windows), f32),           # phase_windows
        )
        return partial(self._simulate, n, kind, connections, False), args

    def default_block_size(self, budget_elems: int = 33_554_432) -> int:
        """A block size keeping each (block, H) event tensor near
        ``budget_elems`` elements (~128 MiB at f32) — the HBM knob of
        the scan path.  Measured sweet spots on a v5e chip scale as
        ~budget/H: 262k for the 121-hop tree, 16-32k for the 1000-hop
        fan-out — big blocks amortize per-dispatch overhead, which
        dominates small-H topologies."""
        h = max(self.compiled.num_hops, 1)
        return int(max(256, min(524_288, budget_elems // h)))

    def capacity_qps(self) -> float:
        """Saturation throughput: the bottleneck station's capacity."""
        t = self.compiled.services
        visits = np.asarray(self._visits)
        with np.errstate(divide="ignore"):
            per_svc = np.where(
                visits > 0,
                t.replicas * self._mu / np.maximum(visits, 1e-30),
                np.inf,
            )
        return float(per_svc.min())

    # -- jit plumbing ------------------------------------------------------

    def _get(self, n: int, kind: str, connections: int = 0,
             sat: bool = False):
        key = (n, kind, connections, sat)
        if key not in self._fns:
            # process-wide AOT reuse: an equal signature means the
            # traced program would be identical (compiler/cache.py), so
            # a re-instantiated Simulator for the same topology family
            # skips retracing AND recompiling
            self._fns[key] = executable_cache.get_or_build(
                ("simulate", self.signature) + key,
                lambda: telemetry.time_first_call(
                    jax.jit(
                        partial(self._simulate, n, kind, connections, sat)
                    ),
                    "compile.jit_first_call",
                ),
            )
        return self._fns[key]

    def _get_summary(self, block: int, num_blocks: int, kind: str,
                     connections: int, collector, trim: bool = False,
                     sat: bool = False, attr: Optional[str] = None,
                     timeline: Optional[Tuple[int, float]] = None):
        """Jitted scan-over-blocks program producing a RunSummary (and,
        with ``attr`` set, an AttributionSummary alongside it).

        ``attr=None`` keeps the historical scan program — the traced
        signature and body are untouched, so attribution-off runs stay
        byte-identical.  ``attr in ("mean", "tail")`` threads the blame
        reduction through the same block scan: per-block blame vectors
        stack and sum, the top-K exemplar state rides the carry, and
        ``"tail"`` additionally weights a second accumulator set by
        ``client_latency >= tail_cut`` (a traced scalar argument).

        ``timeline=(num_windows, window_s)`` threads the flight
        recorder (metrics/timeline.py) through the same scan instead:
        per-block O(S * W) windowed series stack and sum next to the
        RunSummary — mutually exclusive with ``attr``."""
        from isotope_tpu.sim import summary as summary_mod

        if attr is not None and timeline is not None:
            raise ValueError(
                "one scan reduces either blame or the timeline, "
                "not both"
            )
        cache_key = (block, num_blocks, kind, connections,
                     collector is not None, trim, sat, attr, timeline)
        if cache_key not in self._summary_fns:
            c = max(connections, 1)
            per = block // c
            if attr is not None:
                from isotope_tpu.metrics import attribution

                tables = self._attribution_tables()
                top_k = self.params.attribution_top_k
            if timeline is not None:
                from isotope_tpu.metrics import timeline as timeline_mod

                tspec = timeline_mod.build_spec(
                    self.compiled, timeline[0], timeline[1]
                )

            if timeline is not None:
                def scanfn(key, offered_qps, pace_gap, arrival_qps,
                           nominal_gap, win_lo, win_hi, visits_pc,
                           phase_windows):
                    telemetry.record_trace(
                        ("summary", self.signature[3]) + cache_key,
                        tracing=isinstance(key, jax.core.Tracer),
                        requests=block, hops=self.compiled.num_hops,
                    )

                    def body(carry, b):
                        (t0, conn_t0, req_off), tl_acc = carry
                        kb = jax.random.fold_in(key, 1_000_000 + b)
                        res, t_end, conn_end = self._simulate_core(
                            block, kind, connections, kb, offered_qps,
                            pace_gap, arrival_qps, nominal_gap, t0,
                            conn_t0, req_off,
                            sat_conns=connections if sat else 0,
                            visits_pc=visits_pc,
                            phase_windows=phase_windows,
                        )
                        s = summary_mod.summarize(
                            res, collector,
                            window=(win_lo, win_hi) if trim else None,
                        )
                        # the recorder accumulates in the CARRY (not
                        # stacked ys): device cost stays O(S * W) no
                        # matter how many blocks the run scans
                        tl_acc = timeline_mod.accumulate(
                            tl_acc,
                            timeline_mod.timeline_block(
                                res, tspec,
                                packed=self.params.packed_carries,
                            ),
                        )
                        return (
                            (t_end, conn_end, req_off + per), tl_acc
                        ), s

                    carry0 = (
                        (
                            jnp.float32(0.0),
                            jnp.zeros((c,), jnp.float32),
                            jnp.float32(0.0),
                        ),
                        timeline_mod.zeros_summary(
                            tspec, packed=self.params.packed_carries
                        ),
                    )
                    (_, tl_final), parts = jax.lax.scan(
                        body, carry0, jnp.arange(num_blocks)
                    )
                    return summary_mod.reduce_stacked(parts), tl_final
            elif attr is None:
                def scanfn(key, offered_qps, pace_gap, arrival_qps,
                           nominal_gap, win_lo, win_hi, visits_pc,
                           phase_windows):
                    telemetry.record_trace(
                        ("summary", self.signature[3]) + cache_key,
                        tracing=isinstance(key, jax.core.Tracer),
                        requests=block, hops=self.compiled.num_hops,
                    )

                    def body(carry, b):
                        t0, conn_t0, req_off = carry
                        kb = jax.random.fold_in(key, 1_000_000 + b)
                        res, t_end, conn_end = self._simulate_core(
                            block, kind, connections, kb, offered_qps,
                            pace_gap, arrival_qps, nominal_gap, t0,
                            conn_t0, req_off,
                            sat_conns=connections if sat else 0,
                            visits_pc=visits_pc,
                            phase_windows=phase_windows,
                        )
                        s = summary_mod.summarize(
                            res, collector,
                            window=(win_lo, win_hi) if trim else None,
                        )
                        return (t_end, conn_end, req_off + per), s

                    carry0 = (
                        jnp.float32(0.0),
                        jnp.zeros((c,), jnp.float32),
                        jnp.float32(0.0),
                    )
                    _, parts = jax.lax.scan(
                        body, carry0, jnp.arange(num_blocks)
                    )
                    return summary_mod.reduce_stacked(parts)
            else:
                def scanfn(key, offered_qps, pace_gap, arrival_qps,
                           nominal_gap, win_lo, win_hi, tail_cut,
                           visits_pc, phase_windows):
                    telemetry.record_trace(
                        ("summary", self.signature[3]) + cache_key,
                        tracing=isinstance(key, jax.core.Tracer),
                        requests=block, hops=self.compiled.num_hops,
                    )

                    def body(carry, b):
                        (t0, conn_t0, req_off), ex = carry
                        kb = jax.random.fold_in(key, 1_000_000 + b)
                        res, t_end, conn_end = self._simulate_core(
                            block, kind, connections, kb, offered_qps,
                            pace_gap, arrival_qps, nominal_gap, t0,
                            conn_t0, req_off,
                            sat_conns=connections if sat else 0,
                            visits_pc=visits_pc,
                            phase_windows=phase_windows,
                        )
                        s = summary_mod.summarize(
                            res, collector,
                            window=(win_lo, win_hi) if trim else None,
                        )
                        a, ex = attribution.attribute_block(
                            res, tables,
                            tail_cut=(
                                tail_cut if attr == "tail" else None
                            ),
                            top_k=top_k, ex_state=ex,
                            packed=self.params.packed_carries,
                        )
                        carry_out = (
                            (t_end, conn_end, req_off + per), ex
                        )
                        return carry_out, (s, a)

                    # the exemplar carry needs concrete leaves before
                    # the scan starts: seed it from a zero-latency
                    # dummy block shaped like the real ones
                    k0 = min(top_k, block) if top_k > 0 else 0
                    H = self.compiled.num_hops
                    ex0 = (
                        attribution.empty_exemplars(k0, H)
                        if k0 > 0
                        else None
                    )
                    carry0 = (
                        (
                            jnp.float32(0.0),
                            jnp.zeros((c,), jnp.float32),
                            jnp.float32(0.0),
                        ),
                        ex0,
                    )
                    (_, ex_final), (parts, aparts) = jax.lax.scan(
                        body, carry0, jnp.arange(num_blocks)
                    )
                    return (
                        summary_mod.reduce_stacked(parts),
                        attribution.reduce_stacked(aparts, ex_final),
                    )

            self._summary_fns[cache_key] = executable_cache.get_or_build(
                ("summary", self.signature) + cache_key,
                lambda: telemetry.time_first_call(
                    jax.jit(scanfn), "compile.jit_first_call"
                ),
            )
        return self._summary_fns[cache_key]

    def _sample_service_time(self, key: jax.Array, shape) -> jax.Array:
        """Per-hop CPU time draws with mean ``cpu_time_s``.

        Heavy-tail options model the latency mixtures real fleets show
        (GC pauses, cold caches): lognormal(sigma) and Pareto(alpha),
        both scaled so the mean stays the configured CPU demand — the
        queueing waits remain the M/M/k approximation.
        """
        mean = self.params.cpu_time_s
        kind = self.params.service_time
        p = self.params.service_time_param
        if kind == SERVICE_TIME_DETERMINISTIC:
            return jnp.full(shape, mean)
        if kind == SERVICE_TIME_LOGNORMAL:
            # E[exp(sigma Z + mu)] = exp(mu + sigma^2/2) == mean
            z = jax.random.normal(key, shape)
            return jnp.exp(p * z - 0.5 * p * p) * mean
        if kind == SERVICE_TIME_PARETO:
            # standard Pareto (x_m=1): E = alpha/(alpha-1); rescale to mean
            x = jnp.exp(jax.random.exponential(key, shape) / p)
            return x * (mean * (p - 1.0) / p)
        return jax.random.exponential(key, shape) * mean

    # -- the tensor program ------------------------------------------------

    def _simulate(
        self,
        n: int,
        kind: str,
        connections: int,
        sat: bool,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        arrival_qps: jax.Array,
        nominal_gap: Optional[jax.Array] = None,
        visits_pc: Optional[jax.Array] = None,
        phase_windows: Optional[jax.Array] = None,
    ) -> SimResults:
        """One self-contained block starting at t=0 (see _simulate_core)."""
        # host-side telemetry: this body executes once per TRACE (jit)
        # or once per eager call (detail mode) — never per request, so
        # the counters survive the jit boundary by construction, and a
        # repeated trace of one signature is a retrace detection
        telemetry.record_trace(
            ("simulate", self.signature[3], n, kind, connections, sat),
            tracing=isinstance(key, jax.core.Tracer),
            requests=n, hops=self.compiled.num_hops,
        )
        if nominal_gap is None:
            nominal_gap = pace_gap
        c = max(connections, 1)
        res, _, _ = self._simulate_core(
            n, kind, connections, key, offered_qps, pace_gap, arrival_qps,
            nominal_gap, jnp.float32(0.0), jnp.zeros((c,), jnp.float32),
            jnp.float32(0.0),
            sat_conns=connections if sat else 0,
            visits_pc=visits_pc,
            phase_windows=phase_windows,
        )
        return res

    def _simulate_core(
        self,
        n: int,
        kind: str,
        connections: int,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        arrival_qps: jax.Array,
        nominal_gap: jax.Array,
        t0: jax.Array,
        conn_t0: jax.Array,
        req_offset: jax.Array,
        sat_conns: int = 0,
        sat_override: Optional[Tuple[jax.Array, jax.Array]] = None,
        visits_pc: Optional[jax.Array] = None,
        phase_windows: Optional[jax.Array] = None,
        policy_fx=None,  # Optional[policies.PolicyFx]
        rollout_fx=None,  # Optional[rollout.RolloutFx]
        cpu_scale: Optional[jax.Array] = None,
        err_scale: Optional[jax.Array] = None,
        chaos_fx=None,  # Optional[compile.ChaosFx] (ONE member's rows)
    ) -> Tuple[SimResults, jax.Array, jax.Array]:
        """``offered_qps`` drives the queueing model (the rate the whole
        fleet of services sees); ``arrival_qps`` paces this batch's
        open-loop arrival stream.  They differ only under sharded
        execution, where each shard generates 1/shards of the stream.

        ``nominal_gap`` is the closed-loop per-connection pacing used for
        chaos-phase placement (the real throughput's gap even when
        ``pace_gap`` is 0, i.e. ``-qps max``).  ``t0`` / ``conn_t0`` /
        ``req_offset`` are the block's starting clocks so scanned blocks
        form one continuous timeline; returns ``(results, t_end,
        conn_end)`` for the next block's carry.

        ``sat_conns > 0`` switches the wait law to the finite-population
        closed-network model (sim/closed.py) with that TOTAL connection
        count — the ``-qps max`` mode where the open-loop M/M/k law
        misrepresents the C-bounded sojourn tail (ORACLE.md).

        ``cpu_scale`` / ``err_scale`` are the ensemble members'
        per-member physics perturbations (sim/ensemble.py): traced
        scalars so one vmapped fleet program serves every jitter draw.
        ``cpu_scale`` multiplies the sampled service times and divides
        every station's mu inside the wait law (canary arm included);
        ``err_scale`` multiplies the per-hop error rates (clipped to
        [0, 1]).  ``None`` (every solo entry point) leaves the traced
        program byte-identical to the pre-ensemble one.

        ``chaos_fx`` (chaos fleets, compiler/compile.ChaosFx) swaps
        the trace-constant chaos phase tables — effective replicas,
        outage flags, policy chaos-down deltas — for ONE member's
        traced rows, so every fleet member survives its own jittered
        failure schedule under one compiled program.  Combinations
        whose chaos tables stay host constants (ungraceful kills,
        rollout canary-split tables, lb panic pools, saturated
        finite-population tables) are rejected at the fleet entry
        points, not here."""
        H = self.compiled.num_hops
        telemetry.fence_reset()
        any_copula = self._copula_active or self._retry_active
        if any_copula:
            (k_send, k_err, k_wait_u, k_svc, k_arr, k_wait2,
             k_wait3) = jax.random.split(key, 7)
        else:
            k_send, k_err, k_wait_u, k_svc, k_arr = jax.random.split(key, 5)
        # deterministic coins are not drawn (see __init__): the key split
        # layout stays fixed so the OTHER streams are unchanged either way
        u_send = (
            jax.random.uniform(k_send, (n, H)) if self._need_send else None
        )
        u_err = (
            jax.random.uniform(k_err, (n, H)) if self._need_err else None
        )
        # -- policy coins (sim/policies.py) --------------------------------
        # Drawn from a FOLDED key so every existing stream keeps its
        # layout: a protected run differs from the unprotected twin only
        # by the policy effects themselves, not by RNG re-shuffling —
        # the low-variance comparison tools/policies_smoke.py relies on.
        shed_coin = None
        retry_coin = None
        if policy_fx is not None:
            pol = self._policies
            k_shed, k_retry = jax.random.split(
                jax.random.fold_in(key, 770_001)
            )
            if pol.any_breaker:
                shed_h = policy_fx.shed[self._hop_service]
                shed_coin = (
                    jax.random.uniform(k_shed, (n, H)) < shed_h[None, :]
                )
            if pol.any_budget and self._has_retries:
                allow_h = policy_fx.retry_allow[self._hop_service]
                retry_coin = (
                    jax.random.uniform(k_retry, (n, H))
                    < allow_h[None, :]
                )
        # -- rollout version coin (sim/rollout.py) -------------------------
        # Each hop routes to the CANARY arm with the controller's
        # CURRENT traffic weight for its service (0 everywhere a
        # service has no active rollout, and 0 during cooldown /
        # failed).  Folded key, same discipline as the policy coins: a
        # rollout-actuated run differs from its open-loop twin only by
        # the rollout effects, never by RNG re-shuffling.
        can_coin = None
        if rollout_fx is not None:
            w_h = rollout_fx.weight[self._hop_service]  # (H,)
            can_coin = (
                jax.random.uniform(
                    jax.random.fold_in(key, 880_001), (n, H)
                )
                < w_h[None, :]
            )
        # Wait draws: the saturated path (sat_conns > 0) consumes unit
        # NORMALS (its copulas compose in normal space); the open-loop
        # law consumes uniforms.  Either way the copulas — exact U(0,1)
        # marginals; pairwise correlation r within a concurrent group
        # (the backlog correlation of parallel stations fed by common
        # arrivals) plus an extra retry term among one call's serial
        # attempts (consecutive attempts see nearly the same queue) —
        # are applied here, once.
        z_wait = None
        u_wait = None
        if any_copula:
            r = (
                self.params.sibling_copula_r
                if self._copula_active
                else 0.0
            )
            z_h = jax.random.normal(k_wait_u, (n, H))
            z_wait = 0.0
            w_own_sq = 1.0 - r
            if self._copula_active:
                # the saturated path skips the hierarchical mix, so it
                # draws the flat (n, G) tensor — not the (n, F) factor
                # space whose extra columns it would discard
                dim = (
                    self._copula_dim
                    if self._copula_mix is not None and not sat_conns
                    else self._num_sib_groups
                )
                z_small = jax.random.normal(k_wait2, (n, dim))
                if self._copula_mix is not None and not sat_conns:
                    # hierarchical mix for the ACTIVE (concurrent)
                    # groups only: Z_act = z @ mix.T combines each
                    # group's ancestor factors (unit variance,
                    # same-depth cousin corr r * gamma^L, zero across
                    # depths); singleton groups keep their base
                    # column.  OPEN LOOP ONLY: the saturated sampler's
                    # composition (population centering + repairman
                    # join) was calibrated with the flat copula, and
                    # the mix collapses its join median (measured
                    # tree13 -qps max p50 -3.7% -> -11.6% at gamma=0.8)
                    z_act = jnp.matmul(
                        z_small, self._copula_mix.T,
                        precision=jax.lax.Precision.HIGHEST,
                    )
                    z_groups = (
                        z_small[:, : self._num_sib_groups]
                        .at[:, self._copula_rows]
                        .set(z_act)
                    )
                else:
                    z_groups = z_small[:, : self._num_sib_groups]
                z_wait = z_wait + np.sqrt(r) * z_groups[:, self._sib_group]
            if self._retry_active:
                z_call = jax.random.normal(
                    k_wait3, (n, self._num_retry_groups + 1)
                )
                z_wait = z_wait + (
                    self._retry_w * z_call[:, self._retry_group]
                )
                w_own_sq = w_own_sq - self._retry_w**2
            z_wait = z_wait + np.sqrt(w_own_sq) * z_h
            if not sat_conns:
                u_wait = jax.scipy.special.ndtr(z_wait)
        elif sat_conns:
            z_wait = jax.random.normal(k_wait_u, (n, H))
        else:
            u_wait = jax.random.uniform(k_wait_u, (n, H))

        # ---- arrival times (open loop exact; closed loop nominal, used
        # only to place requests into chaos phases) ------------------------
        if kind == OPEN_LOOP:
            gaps = jax.random.exponential(k_arr, (n,)) / arrival_qps
            arrivals = t0 + jnp.cumsum(gaps)
            nominal_arrivals = arrivals
        else:
            c = max(connections, 1)
            per = n // c
            num_phases_static = (
                int(self._phase_starts.shape[0]) * self._num_combos
            )
            if sat_conns and num_phases_static > 1:
                # phased -qps max: the closed loop's rate differs per
                # chaos phase, so a constant-gap nominal clock drifts
                # off the real timeline and mis-places requests around
                # the cuts.  Warp nominal time piecewise from each
                # phase's MVA throughput: the q-th request (globally)
                # nominally fires at Rinv(q), R(t) = cumulative requests
                # under the per-phase rates.
                P_n = int(self._phase_starts.shape[0])
                if chaos_fx is not None and chaos_fx.sat_lam is not None:
                    # a chaos fleet member's own warp rows, traced
                    cuts_f = chaos_fx.sat_cuts
                    lam_f = chaos_fx.sat_lam
                    breaks_f = chaos_fx.sat_breaks
                else:
                    thr = self._closed_tables(sat_conns)[0]  # np (R,)
                    lam_p = np.maximum(
                        thr.reshape(P_n, self._num_combos).mean(1),
                        1e-9,
                    )
                    cuts_np = np.asarray(
                        self._phase_starts, np.float64
                    )
                    r_breaks = np.concatenate(
                        [[0.0], np.cumsum(lam_p[:-1] * np.diff(cuts_np))]
                    )
                    cuts_f = jnp.asarray(cuts_np, jnp.float32)
                    lam_f = jnp.asarray(lam_p, jnp.float32)
                    breaks_f = jnp.asarray(r_breaks, jnp.float32)

                def warp(idx):
                    q = idx * float(sat_conns)
                    k_ph = jnp.clip(
                        jnp.searchsorted(breaks_f, q, side="right")
                        - 1,
                        0,
                        P_n - 1,
                    )
                    return (
                        cuts_f[k_ph]
                        + (q - breaks_f[k_ph]) / lam_f[k_ph]
                    )

                nominal = warp(
                    req_offset + jnp.arange(per, dtype=jnp.float32)
                )
                rem_nominal = warp(
                    jnp.full((n - c * per,), req_offset + per)
                )
            else:
                nominal = (
                    req_offset + jnp.arange(per, dtype=jnp.float32)
                ) * nominal_gap
                rem_nominal = jnp.full(
                    (n - c * per,), (req_offset + per) * nominal_gap
                )
            nominal_arrivals = jnp.concatenate(
                [
                    jnp.broadcast_to(nominal, (c, per)).reshape(-1),
                    # remainder requests nominally follow the per-connection
                    # stream (chaos-phase placement only)
                    rem_nominal,
                ]
            )
            arrivals = None  # closed-loop arrivals derive from latencies

        # ---- phased mTLS tax at each request's arrival time --------------
        # (n,) extra one-way latency added to EVERY edge leg — the
        # auto-mTLS alternation (config.MtlsSchedule)
        tax = None
        if self._mtls is not None:
            t_idx = (
                jnp.floor(
                    nominal_arrivals / self._mtls.period_s
                ).astype(jnp.int32)
                % len(self._mtls.taxes_s)
            )
            tax = self._mtls_taxes[t_idx]

        # ---- traffic-split weights at each request's arrival time --------
        # (N, E+1): one column per schedule + a sentinel 1.0 column for
        # unchurned calls; the nominal arrival places closed-loop
        # requests like the chaos phases do.  ``combo_idx`` linearizes
        # the schedules' cycle positions for the queueing-phase tables.
        combo_idx = None
        churn_w = None
        if self._churn:
            cols = []
            combo_idx = jnp.zeros(n, jnp.int32)
            for p, wts in zip(self._churn_periods, self._churn_weights):
                idx = (
                    jnp.floor(nominal_arrivals / p).astype(jnp.int32)
                    % len(wts)
                )
                cols.append(wts[idx])
                combo_idx = combo_idx * len(wts) + idx
            churn_w = jnp.stack(
                cols + [jnp.ones_like(nominal_arrivals)], axis=1
            )

        # ---- queueing parameters, per (chaos x churn) phase --------------
        # Offered load is per-service; the (P*Cc, S) tables hold each
        # chaos-phase x churn-combo's own visit rates (incl. outage
        # truncation) and effective replica counts.
        P = int(self._phase_starts.shape[0])
        Cc = self._num_combos
        if visits_pc is None:
            visits_pc = self._visits_pc
        lam_pc = offered_qps * visits_pc
        eff_replicas_pc = (
            self._eff_replicas_pc
            if chaos_fx is None
            else chaos_fx.eff_replicas_pc
        )
        if policy_fx is not None:
            pol = self._policies
            if pol.any_breaker:
                # shed requests never enter the queue: the wait law
                # sees the ADMITTED load (downstream reach coupling of
                # sheds is a stated approximation — a shed hop's
                # subtree load still counts statically)
                lam_pc = lam_pc * (1.0 - policy_fx.shed)[None, :]
            if pol.any_hpa or pol.any_ejection:
                # autoscaled/ejected capacity composes with the chaos
                # phases' down deltas; every station keeps >= 1 server.
                # Under a rollout the kill takes CANARY replicas first,
                # so the HPA-scaled BASELINE arm only absorbs the
                # remainder of the delta.
                if chaos_fx is not None:
                    downed = (
                        chaos_fx.downed_base_pc
                        if rollout_fx is not None and self.has_chaos
                        else chaos_fx.downed_pc
                    )
                else:
                    downed = (
                        self._downed_base_pc
                        if rollout_fx is not None and self.has_chaos
                        else self._downed_pc
                    )
                eff_replicas_pc = jnp.maximum(
                    policy_fx.replicas[None, :] - downed, 1.0
                ).astype(jnp.int32)
        lam_can = None
        if rollout_fx is not None:
            # -- two-version split (sim/rollout.py): the canary arm is
            # its OWN M/M/k station fed the split-off admitted load
            # (the same admission-weight multiplication the breaker
            # shed uses), with its own replica count and cpu-time
            # override; the baseline station keeps the complement.
            # Un-rolled-out services have weight 0, so their baseline
            # row is untouched and their canary row is load-free.
            w_row = rollout_fx.weight[None, :]  # (1, S)
            lam_can = lam_pc * w_row
            lam_pc = lam_pc * (1.0 - w_row)
            if self.has_chaos and not (
                policy_fx is not None
                and (pol.any_hpa or pol.any_ejection)
            ):
                # baseline capacity under chaos: the canary-first
                # split's remainder, not the full-delta table (a chaos
                # fleet member's own stacked rows when traced)
                eff_replicas_pc = (
                    chaos_fx.eff_base_roll_pc
                    if chaos_fx is not None
                    else self._eff_base_roll_pc
                )
        # -- panic-threshold routing (sim/lb.py) ---------------------------
        # When the healthy fraction of a pool (after outlier ejection
        # and chaos kills) drops below the service's panic threshold,
        # route to ALL backends: the dead-backend share fast-fails via
        # the panic coin below and the wait law's load scales by the
        # healthy fraction (survivors keep undegraded per-backend
        # load).  Baseline arm only — a rolled-out canary has its own
        # kill physics (transport failures on a downed arm).
        panic_fail_ph = None
        lbd = self._lb_dev
        if (
            lbd is not None
            and self._lb.any_panic
            and not sat_conns
            and (self.has_chaos or policy_fx is not None)
        ):
            if policy_fx is not None and policy_fx.total is not None:
                total = policy_fx.total[None, :]
                alive = policy_fx.alive[None, :]
                if self.has_chaos:
                    if chaos_fx is not None:
                        alive = alive - (
                            chaos_fx.downed_base_pc
                            if rollout_fx is not None
                            else chaos_fx.downed_pc
                        )
                    else:
                        alive = alive - (
                            self._downed_base_pc
                            if rollout_fx is not None
                            else self._downed_pc
                        )
                alive = jnp.maximum(alive, 0.0)
            else:
                total = self._lb_total_row
                alive = (
                    chaos_fx.lb_alive_pc
                    if chaos_fx is not None
                    and chaos_fx.lb_alive_pc is not None
                    else self._lb_alive_pc
                )
            lam_pc, panic_fail_pc = self._lb_mod.panic_split(
                lbd, lam_pc, alive, total
            )
            panic_fail_ph = panic_fail_pc[:, self._hop_service]
        # -- per-station wait law (sim/lb.py) ------------------------------
        # The lb tables swap the wait law per service (power-of-d /
        # mixture); fifo rows pass through mmk_params untouched.  The
        # saturated -qps max path keeps its finite-population law (lb
        # runs reject it loudly at the entry points).
        # per-member cpu perturbation (ensembles): demand scales by s,
        # so every station's service rate scales by 1/s — the one
        # knob that moves BOTH the wait law and the service draws
        mu = self._mu if cpu_scale is None else self._mu / cpu_scale
        if rollout_fx is not None:
            can_reps_pc = (
                chaos_fx.can_reps_pc
                if chaos_fx is not None
                and chaos_fx.can_reps_pc is not None
                else self._can_reps_pc
            )
        if lbd is not None and not sat_conns:
            qp = self._lb_mod.wait_params(
                self._lb, lbd, lam_pc, mu, eff_replicas_pc,
                self._k_max,
            )
            if rollout_fx is not None:
                # the canary arm hashes its OWN ring / weight cycle
                # over its own replicas: stickiness respects version
                # weights (each version's endpoint set is its own pool)
                qp_can = self._lb_mod.wait_params(
                    self._lb, lbd, lam_can,
                    self._canary_mu if cpu_scale is None
                    else self._canary_mu / cpu_scale,
                    can_reps_pc, self._k_max,
                )
        else:
            qp = queueing.mmk_params(
                lam_pc,
                mu,
                eff_replicas_pc,
                self._k_max,
            )
            if rollout_fx is not None:
                qp_can = queueing.mmk_params(
                    lam_can,
                    self._canary_mu if cpu_scale is None
                    else self._canary_mu / cpu_scale,
                    can_reps_pc,
                    self._k_max,
                )
        svc_down_pc = (
            self._svc_down_pc
            if chaos_fx is None
            else chaos_fx.svc_down_pc
        )
        if rollout_fx is not None and self.has_chaos:
            # baseline-arm outage flags (canary downs selected per hop
            # below); utilization reporting follows the baseline arm
            svc_down_pc = (
                chaos_fx.svc_down_base_roll_pc
                if chaos_fx is not None
                else self._svc_down_base_roll_pc
            )
        hop_svc = self._hop_service  # (H,)
        # Per-hop parameter tables are tiny (P*Cc, H); expanding them over
        # the request axis with a direct (N, H) 2D gather is catastrophically
        # slow on TPU (~2 GiB/s element gathers — 90% of step time in r1).
        # Instead: single-phase runs broadcast the one row for free, phased
        # runs expand via a one-hot (N, P*Cc) @ (P*Cc, H) matmul on the MXU.
        p_wait_ph = qp.p_wait[:, hop_svc]        # (P*Cc, H)
        wait_rate_ph = qp.wait_rate[:, hop_svc]  # (P*Cc, H)
        down_ph = svc_down_pc[:, hop_svc]        # (P*Cc, H) bool
        if rollout_fx is not None:
            # canary-station tables, merged per HOP by the version coin
            # after the phase expansion below
            p_wait_c_ph = qp_can.p_wait[:, hop_svc]
            rate_c_ph = qp_can.wait_rate[:, hop_svc]
            down_c_ph = (
                (
                    chaos_fx.svc_down_can_pc
                    if chaos_fx is not None
                    and chaos_fx.svc_down_can_pc is not None
                    else self._svc_down_can_pc
                )[:, hop_svc]
                if self.has_chaos
                else None
            )
        num_phases = P * Cc
        pf_nh = None
        if num_phases == 1:
            p_wait_nh = p_wait_ph[0][None, :]
            wait_rate_nh = wait_rate_ph[0][None, :]
            if panic_fail_ph is not None:
                pf_nh = panic_fail_ph[0][None, :]
            down = (
                jnp.broadcast_to(down_ph[0][None, :], (n, H))
                if self.has_chaos
                else None
            )
            if rollout_fx is not None:
                p_wait_nh = jnp.where(
                    can_coin, p_wait_c_ph[0][None, :], p_wait_nh
                )
                wait_rate_nh = jnp.where(
                    can_coin, rate_c_ph[0][None, :], wait_rate_nh
                )
                if down_c_ph is not None:
                    down = jnp.where(
                        can_coin, down_c_ph[0][None, :], down
                    )
        else:
            if P > 1:
                # phase WINDOWS, not raw cuts: drain windows keep an
                # overloaded row live past its cut (_windows_arg)
                if phase_windows is None:
                    phase_windows = jnp.asarray(self._ident_windows)
                win_idx = (
                    jnp.searchsorted(
                        phase_windows[0], nominal_arrivals,
                        side="right",
                    ).astype(jnp.int32)
                    - 1
                )  # (N,)
                chaos_idx = phase_windows[1].astype(jnp.int32)[
                    jnp.clip(win_idx, 0, self._num_windows - 1)
                ]
            else:
                chaos_idx = jnp.zeros(n, jnp.int32)
            phase_idx = (
                chaos_idx * Cc + combo_idx
                if combo_idx is not None
                else chaos_idx
            )
            oh = jax.nn.one_hot(phase_idx, num_phases, dtype=jnp.float32)
            # HIGHEST keeps the f32 tables exact (default TPU matmul
            # precision rounds operands through bfloat16)
            hi = jax.lax.Precision.HIGHEST
            p_wait_nh = jnp.matmul(oh, p_wait_ph, precision=hi)
            wait_rate_nh = jnp.matmul(oh, wait_rate_ph, precision=hi)
            if panic_fail_ph is not None:
                pf_nh = jnp.matmul(oh, panic_fail_ph, precision=hi)
            down = (
                jnp.matmul(oh, down_ph.astype(jnp.float32), precision=hi)
                > 0.5
                if self.has_chaos
                else None
            )
            if rollout_fx is not None:
                p_wait_nh = jnp.where(
                    can_coin,
                    jnp.matmul(oh, p_wait_c_ph, precision=hi),
                    p_wait_nh,
                )
                wait_rate_nh = jnp.where(
                    can_coin,
                    jnp.matmul(oh, rate_c_ph, precision=hi),
                    wait_rate_nh,
                )
                if down_c_ph is not None:
                    down = jnp.where(
                        can_coin,
                        jnp.matmul(
                            oh, down_c_ph.astype(jnp.float32),
                            precision=hi,
                        ) > 0.5,
                        down,
                    )
        # -- panic coin (sim/lb.py): the dead-backend share fast-fails.
        # Folded key like the policy/rollout coins, so a panicking run
        # differs from its healthy twin only by the panic effects.  A
        # canary-routed hop is exempt (its arm's kill physics already
        # transport-fail it); the coin merges into the shed path —
        # identical fast-500 semantics at admission.
        if pf_nh is not None:
            panic_coin = (
                jax.random.uniform(
                    jax.random.fold_in(key, 660_001), (n, H)
                )
                < pf_nh
            )
            if can_coin is not None:
                panic_coin = panic_coin & ~can_coin
            shed_coin = (
                panic_coin
                if shed_coin is None
                else (shed_coin | panic_coin)
            )
        if sat_conns:
            # finite-population law: per-hop quantile polynomial in
            # v = -log(1 - u') — Horner with per-hop coefficient rows,
            # zero gathers (coefficients broadcast over the request axis;
            # phased runs expand the per-row tables with the same
            # one-hot matmul as the open-loop phase tables).
            # The wait draws stay in normal space: the sibling copula
            # (if active) correlates concurrent branches positively, and
            # the population copula (negative equicorrelation from the
            # fixed in-flight census, chains only) centers across hops.
            hi = jax.lax.Precision.HIGHEST

            def _horner(v, coef_h):
                w = coef_h[-1]
                for ci in range(coef_h.shape[0] - 2, -1, -1):
                    w = w * v + coef_h[ci]
                return w

            if sat_override is not None:
                # fixed-point pilot: tables AND centering are traced
                # arguments — the pilot must sample exactly the
                # composition the final tables deliver (a pilot without
                # the partial population centering solves a cycle the
                # delivered mean then misses; measured star9 thr +7%)
                p0_h, coef_h, e_o, c_o, scale_o = sat_override
                z = z_wait
                zproj = (z * e_o).sum(-1, keepdims=True)
                z = (z - c_o * e_o * zproj) * scale_o
                eval_poly = partial(_horner, coef_h=coef_h)
            elif num_phases == 1:
                (_, p0_R, coef_R, e_R, c_R,
                 scale_R) = self._closed_tables(sat_conns)
                p0_h = p0_R[0]
                c_center = float(c_R[0])
                z = z_wait
                if c_center > 0.0:
                    zproj = (z * e_R[0]).sum(-1, keepdims=True)
                    z = (z - c_center * e_R[0] * zproj) * scale_R[0]
                eval_poly = partial(_horner, coef_h=coef_R[0])
            else:
                # per-phase tables selected by each request's arrival
                # phase (``oh`` from the phase-table expansion above);
                # a chaos fleet member's own stacked rows when traced
                if chaos_fx is not None and chaos_fx.sat_p0 is not None:
                    p0_R = chaos_fx.sat_p0
                    coef_R = chaos_fx.sat_coef
                    e_R = chaos_fx.sat_e
                    c_col = chaos_fx.sat_c[:, None]
                    scale_R = chaos_fx.sat_scale
                else:
                    (_, p0_R, coef_R, e_R, c_R,
                     scale_R) = self._closed_tables(sat_conns)
                    c_col = jnp.asarray(c_R)[:, None]
                p0_h = jnp.matmul(oh, p0_R, precision=hi)
                e_n = jnp.matmul(oh, e_R, precision=hi)
                c_n = jnp.matmul(oh, c_col, precision=hi)
                scale_n = jnp.matmul(oh, scale_R, precision=hi)
                z = z_wait
                zproj = (z * e_n).sum(-1, keepdims=True)
                z = (z - c_n * e_n * zproj) * scale_n

                def eval_poly(v, coef_R=coef_R):
                    deg = coef_R.shape[1]
                    w = jnp.matmul(
                        oh, coef_R[:, deg - 1, :], precision=hi
                    )
                    for ci in range(deg - 2, -1, -1):
                        w = w * v + jnp.matmul(
                            oh, coef_R[:, ci, :], precision=hi
                        )
                    return w
            u_sat = jax.scipy.special.ndtr(z)
            u_c = jnp.clip(
                (u_sat - p0_h) / jnp.maximum(1.0 - p0_h, 1e-9),
                0.0,
                1.0 - 1e-7,
            )
            v = -jnp.log1p(-u_c)
            wait = jnp.where(
                u_sat < p0_h, 0.0, jnp.maximum(eval_poly(v), 0.0)
            )
        else:
            wait = queueing.sample_wait_conditional(
                p_wait_nh, wait_rate_nh, u_wait
            )  # (N, H)
        if shed_coin is not None:
            # a shed request fast-fails at admission: it takes the
            # error path below, NOT the queue (Envoy overflow 503s
            # before the connection pool)
            wait = jnp.where(shed_coin, 0.0, wait)
        # a fully-down service does no work: report zero utilization for
        # those phases instead of the clamped-to-1-replica saturation
        util_phase = jnp.where(svc_down_pc, 0.0, qp.utilization)
        unstable_phase = jnp.where(svc_down_pc, False, qp.unstable)

        svc_time = self._sample_service_time(k_svc, (n, H))
        if cpu_scale is not None:
            # multiplicative rescale keeps the configured service-time
            # SHAPE while moving the member's mean CPU demand (the
            # same trick the canary cpu override uses below)
            svc_time = svc_time * cpu_scale
        if can_coin is not None and self._canary_cpu_varies:
            # canary cpu_time override: a multiplicative rescale keeps
            # the configured service-time SHAPE (exp/lognormal/pareto)
            # while moving the mean to the canary's cpu demand
            svc_time = jnp.where(
                can_coin,
                svc_time * self._canary_cpu_ratio_h[None, :],
                svc_time,
            )

        # None == "statically no 500s" (all error rates are zero) —
        # a multiplicative member err_scale preserves zeros, so the
        # static gate stays sound under ensembles
        if err_scale is None:
            err_rate_h = self._hop_err_rate
        else:
            err_rate_h = jnp.clip(
                self._hop_err_rate * err_scale, 0.0, 1.0
            )
        if u_err is None:
            err_coin = None
        elif can_coin is not None:
            # per-arm error rates: a canary hop draws against its own
            # override (baseline-substituted where none was declared)
            can_err_h = (
                self._canary_err_h
                if err_scale is None
                else jnp.clip(self._canary_err_h * err_scale, 0.0, 1.0)
            )
            err_coin = u_err < jnp.where(
                can_coin,
                can_err_h[None, :],
                err_rate_h[None, :],
            )  # (N, H)
        else:
            err_coin = u_err < err_rate_h  # (N, H)
        if shed_coin is not None:
            # breaker sheds ride the errorRate path exactly: fast 500,
            # script skipped, nothing sent downstream, and — matching
            # executable.go:132-143 — the caller does NOT fail
            err_coin = (
                shed_coin if err_coin is None else err_coin | shed_coin
            )

        # ---- upward pass: outcomes + server-side durations ---------------
        # Processed deepest-first so every call site sees its callees'
        # (hypothetical) latency and status.  Per level it derives:
        #   - per-call duration (serial retry attempts sum; each attempt is
        #     capped by the call's timeout; a down callee costs ~0),
        #   - the call's final outcome: ok / http-500 / transport (down or
        #     timeout on the LAST attempt) — transport fails the caller at
        #     that step (fail_step), a 500 does not (executable.go:132-143),
        #   - which attempt hops would actually run (``used``), and each
        #     attempt's time offset inside its step (for start times).
        # ``None`` sentinels carry static knowledge through the sweep so
        # impossible branches vanish from the compiled program entirely:
        # err_lvls[d] is None when no hop can 500, fail_lvls[d] is None
        # when no call can transport-fail, used_lvls[d] is None when every
        # call is deterministically sent.
        # Scan-bucket segments (sim/levelscan.py) sweep several levels
        # with one traced body; unrolled/sparse islands keep the
        # specialized per-level trace below.  Boundary levels (a
        # bucket's shallowest, every unrolled level) are materialized
        # into the per-level lists so neighbors compose transparently.
        lat_lvls: List[Optional[jax.Array]] = [None] * len(self._levels)
        err_lvls: List[Optional[jax.Array]] = [None] * len(self._levels)
        fail_lvls: List[Optional[jax.Array]] = [None] * len(self._levels)
        used_lvls: List[Optional[jax.Array]] = [None] * len(self._levels)
        off_lvls: List[Optional[jax.Array]] = [None] * len(self._levels)
        ctx = levelscan.SweepCtx(
            n=n, wait=wait, svc_time=svc_time, err_coin=err_coin,
            u_send=u_send, down=down, tax=tax, churn_w=churn_w,
            track_err=self._track_err,
            pallas_census=self._pallas_census,
            retry_coin=retry_coin,
        )
        bucket_ys: Dict[int, dict] = {}
        up_units: List[tuple] = []
        for si in reversed(range(len(self._segments))):
            seg = self._segments[si]
            if isinstance(seg, levelscan.ScanBucket):
                up_units.append(("bucket", si, si))
            else:
                up_units.append(("lvl", seg.d, si))
        # engine-level chaos (trace-time): ISOTOPE_FAULT_INJECT
        # nan:segment:<i> poisons segment i's output so the numeric
        # sentinels (and detail-mode localization) are CPU-testable
        nan_seg = faults.nan_segment()
        for _kind, _idx, _si in up_units:
            if _kind == "bucket":
                seg = self._segments[_idx]
                B = seg.plan.bound_hops
                d0, d1 = seg.plan.d0, seg.plan.d1
                lat_init = levelscan.pad_cols(lat_lvls[d1 + 1], B)
                err_init = None
                if self._track_err:
                    ce = err_lvls[d1 + 1]
                    err_init = (
                        levelscan.pad_cols(ce, B)
                        if ce is not None
                        else jnp.zeros((n, B), bool)
                    )
                ys = levelscan.up_sweep(ctx, seg, lat_init, err_init)
                bucket_ys[_idx] = ys
                s0 = seg.sizes[0]
                lat_lvls[d0] = ys["lat"][0][:, :s0]
                if self._track_err:
                    err_lvls[d0] = ys["err"][0][:, :s0]
                if nan_seg == _si:
                    lat_lvls[d0] = lat_lvls[d0].at[:, 0].set(jnp.nan)
                telemetry.segment_fence(
                    f"up.scan[{d0}-{d1}]", lat_lvls[d0]
                )
                continue
            d = _idx
            lvl = self._levels[d]
            sl = slice(lvl.offset, lvl.offset + lvl.size)
            P = lvl.pmax
            fail_step = None
            dense_excl = None  # census-kernel exclusive step prefix
            if lvl.num_children > 0:
                nxt = self._levels[d + 1]
                csl = slice(nxt.offset, nxt.offset + nxt.size)
                C = lvl.num_children
                child_err = err_lvls[d + 1]
                if lvl.ident_attempts:
                    # single attempt, call k <-> child k: the whole attempt
                    # loop reduces to elementwise ops — no scatters
                    tt = lvl.child_rtt + lat_lvls[d + 1]  # (N, C)
                    if tax is not None:
                        tt = tt + 2.0 * tax[:, None]
                    down_child = down[:, csl] if down is not None else None
                    transport_a, dur_a = _call_outcome(
                        tt,
                        lvl.call_timeout if lvl.finite_timeout else None,
                        down_child,
                    )
                    if self._need_send:
                        prob = lvl.child_send_prob
                        if self._churn:
                            prob = prob * churn_w[:, lvl.child_churn_entry]
                        coin = u_send[:, csl] < prob  # (N, C)
                        used_lvls[d] = coin
                        dur_call = jnp.where(coin, dur_a, 0.0)
                        # an unsent call cannot fail anything
                        final_transport = (
                            coin & transport_a
                            if transport_a is not None
                            else None
                        )
                    else:
                        dur_call = dur_a
                        final_transport = transport_a
                    att_off = None
                else:
                    # general path: serial retry attempts.  dummy column C
                    # absorbs invalid attempt slots
                    pad = lambda x: jnp.pad(x, ((0, 0), (0, 1)))  # noqa: E731
                    lat_child = pad(lat_lvls[d + 1])
                    err_child = (
                        pad(child_err.astype(jnp.float32)) > 0
                        if child_err is not None
                        else None
                    )
                    down_child = (
                        pad(down[:, csl].astype(jnp.float32)) > 0
                        if down is not None
                        else None
                    )
                    rtt_child = jnp.pad(lvl.child_rtt, (0, 1))

                    a0 = lvl.att_child[0]  # (K,) attempt-0 local child idx
                    if self._need_send:
                        prob = lvl.child_send_prob[a0]
                        if self._churn:
                            # current schedule weight scales the send prob
                            prob = prob * churn_w[
                                :, lvl.child_churn_entry[a0]
                            ]
                        coin = u_send[:, csl][:, a0] < prob  # (N, K)
                    else:
                        coin = jnp.ones((n, lvl.num_calls), bool)
                    transportable = (
                        down_child is not None or lvl.finite_timeout
                    )
                    # retry-budget gate (sim/policies.py): attempt >= 1
                    # runs only when its budget coin admits it — a
                    # suppressed retry surfaces the PREVIOUS attempt's
                    # failure to the caller (Envoy budget semantics)
                    retry_gate = None
                    if retry_coin is not None and lvl.max_attempts > 1:
                        retry_gate = (
                            pad(retry_coin[:, csl].astype(jnp.float32))
                            > 0
                        )  # (N, C + 1); pad col False is dead (invalid)
                    dur_call = jnp.zeros((n, lvl.num_calls))
                    final_transport = (
                        jnp.zeros((n, lvl.num_calls), bool)
                        if transportable
                        else None
                    )
                    used = jnp.zeros((n, C + 1), bool)
                    att_off = jnp.zeros((n, C + 1))
                    used_a = coin
                    for a in range(lvl.max_attempts):
                        idx = lvl.att_child[a]       # (K,) in [0, C]
                        valid = lvl.att_valid[a]     # (K,) static
                        use = used_a & valid
                        if retry_gate is not None and a > 0:
                            use = use & retry_gate[:, idx]
                        t = rtt_child[idx] + lat_child[:, idx]
                        if tax is not None:
                            t = t + 2.0 * tax[:, None]
                        transport_a, dur_a = _call_outcome(
                            t,
                            lvl.call_timeout if lvl.finite_timeout else None,
                            down_child[:, idx]
                            if down_child is not None
                            else None,
                        )
                        failed_a = transport_a
                        if err_child is not None:
                            ec = err_child[:, idx]
                            failed_a = (
                                ec if failed_a is None else failed_a | ec
                            )
                        att_off = att_off.at[:, idx].set(
                            jnp.where(use, dur_call, 0.0)
                        )
                        used = used.at[:, idx].set(use)
                        dur_call = dur_call + jnp.where(use, dur_a, 0.0)
                        if final_transport is not None:
                            final_transport = jnp.where(
                                use, transport_a, final_transport
                            )
                        used_a = (
                            use & failed_a
                            if failed_a is not None
                            else jnp.zeros_like(use)
                        )
                    used_lvls[d] = used[:, :C]

                # -- aggregate calls into (parent, step) slots -------------
                if lvl.sparse is not None:
                    # sparse call-slot path (skewed wide level): per-hop
                    # busy times are packed segment sums, pure-sleep
                    # steps are static (_sparse_level_sweep — shared
                    # with the tiled encoding's residual part).
                    busy, fail_step, off = _sparse_level_sweep(
                        lvl.sparse, n, P, lvl.size, dur_call,
                        final_transport,
                        (
                            err_coin[:, sl]
                            if err_coin is not None
                            else None
                        ),
                        lvl.child_parent_local,
                        lvl.child_step,
                    )
                    if att_off is not None:
                        off = off + used_lvls[d] * att_off[:, :C]
                    off_lvls[d] = off
                    step_dur = None
                elif lvl.tiled is not None:
                    # dense-blocked tiles + sparse residual (see
                    # _TiledSteps): every tile runs the dense step-grid
                    # ops restricted to its rows — bit-identical to the
                    # full dense grid on those hops — and the residual
                    # keeps the sparse call-slot sweep; per-part
                    # busy/fail/off re-assemble into level order by the
                    # static inverse gathers.
                    tl = lvl.tiled
                    err_lvl = (
                        err_coin[:, sl] if err_coin is not None else None
                    )
                    transportable = final_transport is not None
                    busy_parts: List[jax.Array] = []
                    fail_parts: List[jax.Array] = []
                    off_parts: List[jax.Array] = []
                    for tile in tl.tiles:
                        T, W = len(tile.hops), tile.width
                        need_off = tile.child_sel.size > 0
                        if tile.call_sel.size:
                            dc = dur_call[:, tile.call_sel]
                            if tile.uniform_calls is not None:
                                agg = dc.reshape(
                                    n, T, W, tile.uniform_calls
                                ).max(-1)
                            else:
                                agg = (
                                    jnp.zeros((n, T * W))
                                    .at[:, tile.call_seg]
                                    .max(dc)
                                    .reshape(n, T, W)
                                )
                        else:
                            agg = None
                        fail_t = None
                        if transportable:
                            if tile.call_sel.size:
                                ft = final_transport[:, tile.call_sel]
                                fail_contrib = jnp.where(
                                    ft, tile.call_step, P
                                ).astype(jnp.int32)
                                if tile.uniform_calls is not None:
                                    fail_t = fail_contrib.reshape(
                                        n, T, W * tile.uniform_calls
                                    ).min(-1)
                                else:
                                    fail_t = (
                                        jnp.full((n, T), P, jnp.int32)
                                        .at[:, tile.call_pos]
                                        .min(fail_contrib)
                                    )
                            else:
                                # call-free rows cannot transport-fail
                                fail_t = jnp.full((n, T), P, jnp.int32)
                        prefix = None
                        if agg is None:
                            # the dense grid's agg is all-zero here
                            busy_t = jnp.broadcast_to(
                                (
                                    jnp.maximum(tile.step_base, 0.0)
                                    * tile.step_mask
                                ).sum(-1),
                                (n, T),
                            )
                        elif (
                            self._census_mod is not None
                            and self._census_mod.supported(T, W)
                        ):
                            busy_t, excl = self._census_mod.census(
                                tile.step_base, tile.step_mask, agg,
                                fail_t, None,
                            )
                            prefix = excl if need_off else None
                        else:
                            step_dur_t = (
                                jnp.maximum(tile.step_base, agg)
                                * tile.step_mask
                            )
                            if fail_t is not None:
                                step_dur_t = step_dur_t * (
                                    jnp.arange(W, dtype=jnp.int32)
                                    <= fail_t[:, :, None]
                                )
                            busy_t = step_dur_t.sum(-1)
                            if need_off:
                                prefix = (
                                    jnp.cumsum(step_dur_t, axis=-1)
                                    - step_dur_t
                                )
                        busy_parts.append(busy_t)
                        if transportable:
                            fail_parts.append(fail_t)
                        if need_off:
                            off_t = prefix.reshape(n, -1)[
                                :, tile.child_pos * W + tile.child_step
                            ]
                            if err_lvl is not None:
                                # dense zeroes the grid before the
                                # prefix for a 500ing parent — match
                                off_t = off_t * ~err_lvl[
                                    :, tile.hops
                                ][:, tile.child_pos]
                            off_parts.append(off_t)
                    if tl.residual is not None:
                        busy_r, fail_r, off_r = _sparse_level_sweep(
                            tl.residual, n, P, len(tl.res_hops),
                            dur_call[:, tl.res_call_sel],
                            (
                                final_transport[:, tl.res_call_sel]
                                if transportable
                                else None
                            ),
                            (
                                err_lvl[:, tl.res_hops]
                                if err_lvl is not None
                                else None
                            ),
                            tl.res_child_pos,
                            tl.res_child_step,
                        )
                        busy_parts.append(busy_r)
                        if transportable:
                            # a call-free residual cannot fail: carry
                            # the sentinel so the assembly stays dense
                            fail_parts.append(
                                fail_r
                                if fail_r is not None
                                else jnp.full(
                                    (n, len(tl.res_hops)), P, jnp.int32
                                )
                            )
                        if tl.res_child_sel.size:
                            off_parts.append(off_r)
                    busy = jnp.concatenate(busy_parts, axis=1)[
                        :, tl.hop_inv
                    ]
                    fail_step = (
                        jnp.concatenate(fail_parts, axis=1)[
                            :, tl.hop_inv
                        ]
                        if transportable
                        else None
                    )
                    off = jnp.concatenate(off_parts, axis=1)[
                        :, tl.child_inv
                    ]
                    if att_off is not None:
                        off = off + used_lvls[d] * att_off[:, :C]
                    off_lvls[d] = off
                    step_dur = None
                else:
                    if lvl.uniform_calls is not None:
                        # call_seg == repeat(arange(size*P), c):
                        # reshape-reduce
                        agg = dur_call.reshape(
                            n, lvl.size, P, lvl.uniform_calls
                        ).max(-1)
                    else:
                        agg = (
                            jnp.zeros((n, lvl.size * P))
                            .at[:, lvl.call_seg]
                            .max(dur_call)
                            .reshape(n, lvl.size, P)
                        )
                    if final_transport is not None:
                        fail_contrib = jnp.where(
                            final_transport, lvl.call_step, P
                        ).astype(jnp.int32)
                        if lvl.uniform_calls is not None:
                            fail_step = fail_contrib.reshape(
                                n, lvl.size, P * lvl.uniform_calls
                            ).min(-1)
                        else:
                            fail_step = (
                                jnp.full((n, lvl.size), P, jnp.int32)
                                .at[:, lvl.call_seg // P]
                                .min(fail_contrib)
                            )
                    if (
                        self._census_mod is not None
                        and self._census_mod.supported(lvl.size, P)
                    ):
                        # fused census kernel (native/census_pallas.py):
                        # max + mask + fail/err truncation + row-sum +
                        # exclusive prefix in one pass; the masked
                        # (N, size, P) step grid never round-trips HBM
                        busy, dense_excl = self._census_mod.census(
                            lvl.step_base, lvl.step_mask, agg,
                            fail_step,
                            (
                                err_coin[:, sl]
                                if err_coin is not None
                                else None
                            ),
                        )
                        step_dur = None
                    else:
                        step_dur = (
                            jnp.maximum(lvl.step_base, agg)
                            * lvl.step_mask
                        )
            else:
                # call-free level: busy time is fully static
                busy = jnp.broadcast_to(lvl.leaf_busy, (n, lvl.size))
                step_dur = None
            fail_lvls[d] = fail_step
            if step_dur is not None:
                # executed-step mask: errorRate 500s skip the whole
                # script; transport errors truncate after the failing
                # step
                if fail_step is not None:
                    executed = (
                        jnp.arange(P, dtype=jnp.int32)
                        <= fail_step[:, :, None]
                    )
                    if err_coin is not None:
                        executed = executed & ~err_coin[:, sl][:, :, None]
                    step_dur = step_dur * executed
                elif err_coin is not None:
                    step_dur = step_dur * ~err_coin[:, sl][:, :, None]
                busy = step_dur.sum(-1)
            elif err_coin is not None:
                # errorRate 500 skips the whole script
                busy = busy * ~err_coin[:, sl]
            lat_lvls[d] = wait[:, sl] + svc_time[:, sl] + busy
            # this hop's own response status: 500 iff errorRate coin or a
            # transport-failed step
            if err_coin is not None and fail_step is not None:
                err_lvls[d] = err_coin[:, sl] | (fail_step < P)
            elif err_coin is not None:
                err_lvls[d] = err_coin[:, sl]
            elif fail_step is not None:
                err_lvls[d] = fail_step < P
            if lvl.num_children > 0 and step_dur is not None:
                prefix = jnp.cumsum(step_dur, axis=-1) - step_dur
                off = prefix.reshape(n, -1)[:, lvl.child_seg]
                if att_off is not None:
                    off = off + (
                        used_lvls[d] * att_off[:, : lvl.num_children]
                    )
                off_lvls[d] = off
            elif lvl.num_children > 0 and dense_excl is not None:
                # census-kernel path: the fused prefix already carries
                # the fail/err truncation the masked grid would
                off = dense_excl.reshape(n, -1)[:, lvl.child_seg]
                if att_off is not None:
                    off = off + (
                        used_lvls[d] * att_off[:, : lvl.num_children]
                    )
                off_lvls[d] = off
            if nan_seg == _si:
                lat_lvls[d] = lat_lvls[d].at[:, 0].set(jnp.nan)
            telemetry.segment_fence(f"up.lvl[{d}]", lat_lvls[d])

        # ---- downward pass: which hops actually execute ------------------
        # a down ENTRY service refuses the client's connection itself
        # rollout runs additionally track REFUSED hops (would-send but
        # target down): the canary gates charge a killed arm's
        # transport failures to that arm (observe_block)
        track_refused = rollout_fx is not None
        if down is not None:
            root_down = down[:, 0]
            sent_cur: jax.Array = ~root_down[:, None]
            refused_cur = root_down[:, None]
        else:
            root_down = None
            sent_cur = jnp.ones((n, 1), bool)
            refused_cur = jnp.zeros((n, 1), bool)
        last_level = len(self._levels) - 1
        sent_chunks: List[jax.Array] = []
        refused_chunks: List[jax.Array] = []
        for si, seg in enumerate(self._segments):
            if isinstance(seg, levelscan.ScanBucket):
                if track_refused:
                    own, ref_own, sent_cur, refused_cur = (
                        levelscan.sent_sweep(
                            ctx, seg, bucket_ys[si],
                            levelscan.pad_cols(
                                sent_cur, seg.plan.bound_hops
                            ),
                            refused_init=levelscan.pad_cols(
                                refused_cur, seg.plan.bound_hops
                            ),
                        )
                    )
                    refused_chunks.append(
                        levelscan.gather_levels(ref_own, seg.sizes)
                    )
                else:
                    own, sent_cur = levelscan.sent_sweep(
                        ctx, seg, bucket_ys[si],
                        levelscan.pad_cols(sent_cur, seg.plan.bound_hops),
                    )
                sent_chunks.append(
                    levelscan.gather_levels(own, seg.sizes)
                )
                continue
            d = seg.d
            sent_chunks.append(sent_cur)
            if track_refused:
                refused_chunks.append(refused_cur)
            if d >= last_level:
                continue
            lvl = self._levels[d]
            sl = slice(lvl.offset, lvl.offset + lvl.size)
            nxt = self._levels[d + 1]
            csl = slice(nxt.offset, nxt.offset + nxt.size)
            sent = sent_cur[:, lvl.child_parent_local]
            if err_coin is not None:
                sent = sent & ~err_coin[:, sl][:, lvl.child_parent_local]
            if fail_lvls[d] is not None:
                sent = sent & (
                    lvl.child_step
                    <= fail_lvls[d][:, lvl.child_parent_local]
                )
            if used_lvls[d] is not None:
                sent = sent & used_lvls[d]
            if down is not None:
                refused_cur = sent & down[:, csl]
                sent = sent & ~down[:, csl]
            else:
                refused_cur = jnp.zeros_like(sent)
            sent_cur = sent

        # ---- closed-loop arrivals (need latencies) -----------------------
        # a refused connection to the entry costs one wire round trip
        root_wire = self._root_net
        if tax is not None:
            # the client -> entry edge pays the tax on both legs too
            root_wire = root_wire + 2.0 * tax
        if root_down is not None:
            root_lat = jnp.where(
                root_down,
                2 * self._entry_one_way,
                root_wire + lat_lvls[0][:, 0],
            )
        else:
            root_lat = root_wire + lat_lvls[0][:, 0]
        if kind == CLOSED_LOOP:
            c = max(connections, 1)
            per = n // c
            rem = n - c * per
            lat_conn = root_lat[: c * per].reshape(c, per)
            spent = jnp.maximum(lat_conn, pace_gap)
            starts = conn_t0[:, None] + jnp.cumsum(spent, axis=-1) - spent
            conn_end = conn_t0 + spent.sum(-1)
            if rem:
                # remainder requests (n % c) continue on the first ``rem``
                # connections — each starts when its connection frees up
                arrivals = jnp.concatenate(
                    [starts.reshape(-1), conn_end[:rem]]
                )
                spent_rem = jnp.maximum(root_lat[c * per:], pace_gap)
                conn_end = conn_end.at[:rem].add(spent_rem)
            else:
                arrivals = starts.reshape(-1)
        else:
            conn_end = conn_t0

        # ---- downward pass 2: absolute start times -----------------------
        entry_wire = self._entry_one_way
        if tax is not None:
            entry_wire = entry_wire + tax
        start_cur: jax.Array = (arrivals + entry_wire)[:, None]
        start_chunks: List[jax.Array] = []
        telemetry.fence_reset()
        for si, seg in enumerate(self._segments):
            if isinstance(seg, levelscan.ScanBucket):
                own, start_cur = levelscan.start_sweep(
                    ctx, seg, bucket_ys[si],
                    levelscan.pad_cols(start_cur, seg.plan.bound_hops),
                )
                start_chunks.append(
                    levelscan.gather_levels(own, seg.sizes)
                )
                telemetry.segment_fence(
                    f"start.scan[{seg.plan.d0}-{seg.plan.d1}]",
                    start_chunks[-1],
                )
                continue
            d = seg.d
            start_chunks.append(start_cur)
            telemetry.segment_fence(f"start.lvl[{d}]", start_cur)
            if d >= last_level:
                continue
            lvl = self._levels[d]
            sl = slice(lvl.offset, lvl.offset + lvl.size)
            base = (start_cur + wait[:, sl])[:, lvl.child_parent_local]
            out_wire = lvl.child_net_out
            if tax is not None:
                out_wire = out_wire + tax[:, None]
            start_cur = base + off_lvls[d] + out_wire

        # ---- per-segment assembly into BFS hop order ---------------------
        lat_chunks: List[jax.Array] = []
        err_chunks: List[jax.Array] = []
        for si, seg in enumerate(self._segments):
            if isinstance(seg, levelscan.ScanBucket):
                ys = bucket_ys[si]
                lat_chunks.append(
                    levelscan.gather_levels(ys["lat"], seg.sizes)
                )
                err_chunks.append(
                    levelscan.gather_levels(ys["err"], seg.sizes)
                    if self._track_err
                    else jnp.zeros((n, seg.num_hops), bool)
                )
            else:
                d = seg.d
                lat_chunks.append(lat_lvls[d])
                e = err_lvls[d]
                err_chunks.append(
                    e
                    if e is not None
                    else jnp.zeros((n, self._levels[d].size), bool)
                )
        hop_sent = jnp.concatenate(sent_chunks, axis=1)
        hop_refused = (
            jnp.concatenate(refused_chunks, axis=1)
            if track_refused
            else None
        )
        hop_lat = jnp.concatenate(lat_chunks, axis=1)
        hop_start = jnp.concatenate(start_chunks, axis=1)
        err_hop = jnp.concatenate(err_chunks, axis=1)
        client_error = err_hop[:, 0]
        if root_down is not None:
            client_error = client_error | root_down
        # ungraceful kills: a request whose hop on the killed service is
        # in flight at the kill instant dies (transport) w.p. down/k —
        # the client sees the reset at ~the kill time (see __init__)
        if self._num_kill_events:
            # the rows are either this schedule's own constants or a
            # fleet member's stacked traced rows — identical values on
            # either path, so the bit-equality pin holds by the same
            # traced-vs-constant argument the chaos phase tables use
            if chaos_fx is not None and chaos_fx.kill_t is not None:
                kill_t = chaos_fx.kill_t        # (E,) f32
                kill_frac = chaos_fx.kill_frac  # (E, H) f32
            else:
                kill_t = jnp.asarray(self._kill_t_np, jnp.float32)
                kill_frac = jnp.asarray(self._kill_frac_np, jnp.float32)
            back_h = jnp.asarray(self._back_cum_np, jnp.float32)  # (H,)
            died_any = jnp.zeros(n, bool)
            for i in range(self._num_kill_events):
                t_k = kill_t[i]
                strad = (
                    hop_sent
                    & (hop_start < t_k)
                    & (hop_start + hop_lat > t_k)
                )
                coin = (
                    jax.random.uniform(
                        jax.random.fold_in(key, 9_990_000 + i),
                        strad.shape,
                    )
                    < kill_frac[i][None, :]
                )
                died_h = strad & coin
                died = died_h.any(axis=1) & ~died_any
                # the earliest reset to reach the client wins: the
                # shortest payload-free return path among killed hops
                ret = jnp.where(died_h, back_h[None, :], jnp.inf).min(1)
                reset_lat = jnp.maximum(t_k - arrivals, 0.0) + jnp.where(
                    jnp.isfinite(ret), ret, 0.0
                )
                root_lat = jnp.where(died, reset_lat, root_lat)
                client_error = client_error | died
                died_any = died_any | died
        res = SimResults(
            client_start=arrivals,
            client_latency=root_lat,
            client_error=client_error,
            hop_sent=hop_sent,
            hop_error=err_hop & hop_sent,
            hop_latency=hop_lat,
            hop_start=hop_start,
            utilization=util_phase.max(axis=0),
            unstable=unstable_phase.any(axis=0),
            offered_qps=offered_qps,
            # only materialized for attributed / timeline simulators:
            # the dense run() path would otherwise pay a fifth (N, H)
            # output buffer nothing reads
            hop_wait=(
                wait
                if self.params.attribution or self.params.timeline
                else None
            ),
            hop_canary=can_coin,
            hop_refused=hop_refused,
        )
        t_end = conn_end.max() if kind == CLOSED_LOOP else arrivals[-1]
        return res, t_end, conn_end


def simulate(
    compiled: CompiledGraph,
    load: LoadModel,
    num_requests: int,
    key: jax.Array,
    params: SimParams = SimParams(),
    chaos: Sequence[ChaosEvent] = (),
) -> SimResults:
    """One-shot convenience wrapper around :class:`Simulator`."""
    return Simulator(compiled, params, chaos).run(load, num_requests, key)
