"""The vectorized event-tree simulation engine.

One jit-compiled tensor program replaces the reference's entire data plane:

- the per-request script interpreter (isotope/service/pkg/srv/handler.go:
  66-76 + executable.go:43-179) becomes two static sweeps over the depth
  levels of the unrolled call tree — an upward pass computing each hop's
  server-side duration (concurrent fan-out joins via scatter-max, the
  vectorized WaitGroup of executable.go:171-175; sequential steps sum,
  handler.go:66) and a downward pass assigning absolute start times;
- Fortio's load loop (perf/benchmark/runner/runner.py:255-268) becomes an
  arrival-time vector: Poisson cumsum for open-loop, per-connection pacing
  cumsum for closed-loop;
- queueing delay at each service is sampled from the analytic M/M/k model
  (see sim/queueing.py) with k = NumReplicas and offered load derived from
  the compile-time expected-visit counts;
- ``errorRate`` — spec'd but never implemented by the reference runtime
  (SURVEY.md §2.7) — is implemented for real: a hop errors with its
  service's probability, returns a fast 500 (skips its script), and sends
  nothing downstream.  Matching executable.go:132-143, a downstream error
  does NOT fail the caller.

Everything is static-shaped: (num_requests x num_hops) event tensors, depth
levels unrolled at trace time, RNG via ``jax.random`` keys.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from isotope_tpu.compiler.program import CompiledGraph
from isotope_tpu.sim import queueing
from isotope_tpu.sim.config import (
    CLOSED_LOOP,
    OPEN_LOOP,
    SERVICE_TIME_DETERMINISTIC,
    LoadModel,
    SimParams,
)


class SimResults(NamedTuple):
    """Raw per-request / per-hop outcomes of one simulated run.

    Hop axis order is the compiled BFS order (level-concatenated).  All
    times are seconds; ``hop_start`` is when the request *arrives* at the
    service (before queueing), ``hop_latency`` the server-side duration
    (wait + script + cpu) — i.e. what the reference's
    ``service_request_duration_seconds`` histogram observes
    (srv/prometheus/handler.go:57-61).
    """

    client_start: jax.Array    # (N,) client send time
    client_latency: jax.Array  # (N,) client-observed round trip
    client_error: jax.Array    # (N,) bool — entry service injected a 500
    hop_sent: jax.Array        # (N, H) bool
    hop_error: jax.Array       # (N, H) bool (only where sent)
    hop_latency: jax.Array     # (N, H) f32
    hop_start: jax.Array       # (N, H) f32
    utilization: jax.Array     # (S,) rho per service at the offered load
    unstable: jax.Array        # (S,) bool — offered load >= capacity
    offered_qps: jax.Array     # scalar f32 — the rate the queues saw

    @property
    def client_end(self) -> jax.Array:
        return self.client_start + self.client_latency

    @property
    def hop_events(self) -> jax.Array:
        """Total executed hops — the benchmark's unit of work."""
        return self.hop_sent.sum()


@dataclasses.dataclass(frozen=True)
class _Level:
    """Device-resident constants for one depth level."""

    offset: int                 # start of this level's slice in hop order
    size: int
    pmax: int
    step_mask: jax.Array        # (L, Pmax) f32 — 1 where a real step
    step_base: jax.Array        # (L, Pmax) f32
    child_seg: jax.Array        # (C,) i32 — parent_local * Pmax + step
    child_parent_local: jax.Array  # (C,) i32
    child_rtt: jax.Array        # (C,) f32 — request + response wire time
    child_net_out: jax.Array    # (C,) f32 — one-way request wire time
    child_send_prob: jax.Array  # (C,) f32

    @property
    def num_children(self) -> int:
        return len(self.child_seg)


class Simulator:
    """Holds a compiled graph's device constants and jitted entry points."""

    def __init__(self, compiled: CompiledGraph, params: SimParams = SimParams()):
        self.compiled = compiled
        self.params = params
        t = compiled.services
        net = params.network

        self._replicas = jnp.asarray(t.replicas)
        self._k_max = int(t.replicas.max())
        self._visits = jnp.asarray(compiled.expected_visits(), jnp.float32)
        self._mu = 1.0 / params.cpu_time_s

        # Per-hop gathers are resolved at trace time (static indices).
        hs = compiled.hop_service
        self._hop_service = jnp.asarray(hs)
        self._hop_err_rate = jnp.asarray(t.error_rate[hs])
        resp = t.response_size.astype(np.float64)
        req = compiled.hop_request_size.astype(np.float64)
        net_out = net.base_latency_s + req / net.bytes_per_second
        net_back = net.base_latency_s + resp[hs] / net.bytes_per_second
        self._root_net = float(net_out[0] + net_back[0])

        levels: List[_Level] = []
        offset = 0
        for lvl in compiled.levels:
            cids = lvl.child_ids
            levels.append(
                _Level(
                    offset=offset,
                    size=lvl.num_hops,
                    pmax=compiled.max_steps,
                    step_mask=jnp.asarray(lvl.step_is_real, jnp.float32),
                    step_base=jnp.asarray(lvl.step_base),
                    child_seg=jnp.asarray(lvl.child_seg),
                    child_parent_local=jnp.asarray(
                        lvl.child_seg // compiled.max_steps
                    ),
                    child_rtt=jnp.asarray(
                        (net_out[cids] + net_back[cids]), jnp.float32
                    ),
                    child_net_out=jnp.asarray(net_out[cids], jnp.float32),
                    child_send_prob=jnp.asarray(
                        compiled.hop_send_prob[cids]
                    ),
                )
            )
            offset += lvl.num_hops
        self._levels: Tuple[_Level, ...] = tuple(levels)
        self._fns: Dict[Tuple[int, str, bool], "jax.stages.Wrapped"] = {}

    # -- public entry points ----------------------------------------------

    def run(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        fixed_point_iters: int = 3,
    ) -> SimResults:
        """Simulate ``num_requests`` under ``load``.

        Open-loop: queues see exactly ``load.qps``.  Closed-loop: the rate
        the queues see is latency-dependent (Fortio's workers self-throttle),
        so we solve ``lam = min(qps, C / E[latency(lam)], capacity)`` by a
        few pilot iterations before the full run.
        """
        if load.kind == OPEN_LOOP:
            return self._get(num_requests, OPEN_LOOP)(
                key, jnp.float32(load.qps), jnp.float32(0.0),
                jnp.float32(load.qps),
            )
        cap = 0.999 * self.capacity_qps()
        lam = min(load.qps, cap) if load.qps is not None else cap
        pilot_n = min(num_requests, 2048)
        pilot = self._get(pilot_n, CLOSED_LOOP, load.connections)
        gap = (
            jnp.float32(load.connections / load.qps)
            if load.qps is not None
            else jnp.float32(0.0)
        )
        for i in range(fixed_point_iters):
            res = pilot(
                jax.random.fold_in(key, i), jnp.float32(lam), gap,
                jnp.float32(lam),
            )
            mean_lat = float(res.client_latency.mean())
            implied = load.connections / max(mean_lat, 1e-9)
            lam = min(implied, cap)
            if load.qps is not None:
                lam = min(lam, load.qps)
        return self._get(num_requests, CLOSED_LOOP, load.connections)(
            key, jnp.float32(lam), gap, jnp.float32(lam)
        )

    def capacity_qps(self) -> float:
        """Saturation throughput: the bottleneck station's capacity."""
        t = self.compiled.services
        visits = np.asarray(self._visits)
        with np.errstate(divide="ignore"):
            per_svc = np.where(
                visits > 0,
                t.replicas * self._mu / np.maximum(visits, 1e-30),
                np.inf,
            )
        return float(per_svc.min())

    # -- jit plumbing ------------------------------------------------------

    def _get(self, n: int, kind: str, connections: int = 0):
        key = (n, kind, connections)
        if key not in self._fns:
            self._fns[key] = jax.jit(
                partial(self._simulate, n, kind, connections)
            )
        return self._fns[key]

    # -- the tensor program ------------------------------------------------

    def _simulate(
        self,
        n: int,
        kind: str,
        connections: int,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        arrival_qps: jax.Array,
    ) -> SimResults:
        """``offered_qps`` drives the queueing model (the rate the whole
        fleet of services sees); ``arrival_qps`` paces this batch's
        open-loop arrival stream.  They differ only under sharded
        execution, where each shard generates 1/shards of the stream."""
        H = self.compiled.num_hops
        k_send, k_err, k_wait_u, k_wait_e, k_svc, k_arr = jax.random.split(
            key, 6
        )
        u_send = jax.random.uniform(k_send, (n, H))
        u_err = jax.random.uniform(k_err, (n, H))
        u_wait = jax.random.uniform(k_wait_u, (n, H))
        e_wait = jax.random.exponential(k_wait_e, (n, H))

        # M/M/k parameters at the offered load; gather to hops.
        qp = queueing.mmk_params(
            offered_qps * self._visits, self._mu, self._replicas, self._k_max
        )
        hop_qp = queueing.QueueParams(
            p_wait=qp.p_wait[self._hop_service],
            wait_rate=qp.wait_rate[self._hop_service],
            utilization=None,
            unstable=None,
        )
        wait = queueing.sample_wait(hop_qp, u_wait, e_wait)  # (N, H)
        if self.params.service_time == SERVICE_TIME_DETERMINISTIC:
            svc_time = jnp.full((n, H), self.params.cpu_time_s)
        else:
            svc_time = (
                jax.random.exponential(k_svc, (n, H)) * self.params.cpu_time_s
            )

        err_coin = u_err < self._hop_err_rate  # (N, H)

        # ---- downward pass 1: which hops actually happen -----------------
        sent_lvls: List[jax.Array] = [jnp.ones((n, 1), bool)]
        for lvl in self._levels[:-1]:
            if lvl.num_children == 0:
                sent_lvls.append(jnp.zeros((n, 0), bool))
                continue
            sl = slice(lvl.offset, lvl.offset + lvl.size)
            parent_sent = sent_lvls[-1][:, lvl.child_parent_local]
            parent_err = err_coin[:, sl][:, lvl.child_parent_local]
            nxt = self._levels[len(sent_lvls)]
            csl = slice(nxt.offset, nxt.offset + nxt.size)
            coin = u_send[:, csl] < lvl.child_send_prob
            sent_lvls.append(parent_sent & ~parent_err & coin)

        # ---- upward pass: server-side durations --------------------------
        lat_lvls: List[Optional[jax.Array]] = [None] * len(self._levels)
        off_lvls: List[Optional[jax.Array]] = [None] * len(self._levels)
        for d in reversed(range(len(self._levels))):
            lvl = self._levels[d]
            sl = slice(lvl.offset, lvl.offset + lvl.size)
            if lvl.num_children > 0:
                contrib = jnp.where(
                    sent_lvls[d + 1],
                    lvl.child_rtt + lat_lvls[d + 1],
                    0.0,
                )
                agg = (
                    jnp.zeros((n, lvl.size * lvl.pmax))
                    .at[:, lvl.child_seg]
                    .max(contrib)
                    .reshape(n, lvl.size, lvl.pmax)
                )
                step_dur = jnp.maximum(lvl.step_base, agg) * lvl.step_mask
            else:
                step_dur = (
                    jnp.broadcast_to(
                        lvl.step_base, (n, lvl.size, lvl.pmax)
                    )
                    * lvl.step_mask
                )
            busy = step_dur.sum(-1)
            errored = err_coin[:, sl]
            lat_lvls[d] = (
                wait[:, sl]
                + svc_time[:, sl]
                + jnp.where(errored, 0.0, busy)
            )
            if lvl.num_children > 0:
                prefix = jnp.cumsum(step_dur, axis=-1) - step_dur
                off_lvls[d] = prefix.reshape(n, -1)[:, lvl.child_seg]

        # ---- arrivals ----------------------------------------------------
        root_lat = self._root_net + lat_lvls[0][:, 0]
        if kind == OPEN_LOOP:
            gaps = jax.random.exponential(k_arr, (n,)) / arrival_qps
            arrivals = jnp.cumsum(gaps)
        else:
            # closed loop: C workers, serial requests, paced to qps overall.
            c = connections
            per = n // c
            lat_conn = root_lat[: c * per].reshape(c, per)
            spent = jnp.maximum(lat_conn, pace_gap)
            starts = jnp.cumsum(spent, axis=-1) - spent
            arrivals = jnp.concatenate(
                [
                    starts.reshape(-1),
                    # remainder requests (n % c) start at t=0 on fresh conns
                    jnp.zeros((n - c * per,)),
                ]
            )

        # ---- downward pass 2: absolute start times -----------------------
        start_lvls: List[jax.Array] = [
            (arrivals + self.params.network.one_way(0.0))[:, None]
        ]
        for d in range(len(self._levels) - 1):
            lvl = self._levels[d]
            if lvl.num_children == 0:
                start_lvls.append(jnp.zeros((n, 0)))
                continue
            sl = slice(lvl.offset, lvl.offset + lvl.size)
            base = (start_lvls[d] + wait[:, sl])[:, lvl.child_parent_local]
            start_lvls.append(base + off_lvls[d] + lvl.child_net_out)

        hop_sent = jnp.concatenate(sent_lvls, axis=1)
        hop_lat = jnp.concatenate(lat_lvls, axis=1)
        hop_start = jnp.concatenate(start_lvls, axis=1)
        return SimResults(
            client_start=arrivals,
            client_latency=root_lat,
            client_error=err_coin[:, 0],
            hop_sent=hop_sent,
            hop_error=err_coin & hop_sent,
            hop_latency=hop_lat,
            hop_start=hop_start,
            utilization=qp.utilization,
            unstable=qp.unstable,
            offered_qps=offered_qps,
        )


def simulate(
    compiled: CompiledGraph,
    load: LoadModel,
    num_requests: int,
    key: jax.Array,
    params: SimParams = SimParams(),
) -> SimResults:
    """One-shot convenience wrapper around :class:`Simulator`."""
    return Simulator(compiled, params).run(load, num_requests, key)
