"""Scenario ensembles: vmapped Monte Carlo fleets.

One compiled program simulates one ``(topology, config, seed)`` — so a
distributional question ("what is P(p99 > SLO)?") used to cost a full
Python re-dispatch per seed.  This module batches N scenario variants
behind ONE jitted program per device (the TPU Ising idiom from
PAPERS.md: thousands of independent lattices behind one program), with
the ensemble axis as a leading ``jax.vmap`` dimension over the engine's
block-scan summary program:

- :class:`EnsembleSpec` declares the fleet — member seeds (the RNG
  axis) plus optional per-member multiplicative perturbations of the
  offered qps, the per-request CPU demand, and the per-hop error
  rates, stacked as ``(N,)`` leaves that ride the traced program as
  arguments (one compile serves every member AND every jitter draw);
- :class:`EnsembleSummary` holds the per-member
  :class:`~isotope_tpu.sim.summary.RunSummary` stack (leaves with a
  leading member axis) plus the distributional reductions: per-member
  quantiles, quantile bands across members, and SLO-violation
  probabilities with Wilson confidence intervals;
- :func:`wilson_interval` is the closed-form CI (exact for the
  binomial "k of N members violated" estimator — no scipy needed).

Member RNG derives via ``fold_in(seed_key, member_seed)`` — the
checkpoint/resume idiom of runner/run.py — so member k of a seeds-only
ensemble is bit-identical to a solo ``run_summary`` with that folded
seed (pinned by tests/test_ensemble.py).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np

#: artifact schema tag (runner/run.py writes ``<label>.ensemble.json``)
#: — v2 adds the schema-versioned ``splitting`` block (importance
#: splitting, sim/splitting.py), the protected-fleet severity/worst-
#: member block, and the per-member chaos marker; v1 documents remain
#: readable (see :func:`doc_member_quantiles`)
DOC_SCHEMA = "isotope-ensemble/v2"
DOC_SCHEMAS = ("isotope-ensemble/v1", "isotope-ensemble/v2")

#: quantiles reported per member in the artifact / tables
DOC_QUANTILES = (0.5, 0.9, 0.99)


def _scale_array(x, n: int, what: str) -> Optional[np.ndarray]:
    if x is None:
        return None
    a = np.asarray(x, np.float64)
    if a.shape != (n,):
        raise ValueError(
            f"{what} must have shape ({n},) to match the member count; "
            f"got {a.shape}"
        )
    if not np.all(np.isfinite(a)) or (a <= 0).any():
        raise ValueError(f"{what} entries must be finite and positive")
    return a


@dataclasses.dataclass(frozen=True)
class EnsembleSpec:
    """One Monte Carlo fleet: seeds + per-member perturbations.

    ``seeds`` are the fold indices deriving each member's RNG key
    (``fold_in(run_key, seed)``); duplicates make two members
    bit-identical copies, which is almost always a configuration bug —
    the vet gate errors on them (VET-T023) and ``run_ensemble``
    rejects them unless explicit per-member keys override the seed
    derivation (the runner's same-shape case collapse does).

    The scale leaves are multiplicative and mean-1 by convention
    (:meth:`from_jitter` draws mean-preserving lognormal factors):

    - ``qps_scale`` multiplies the offered rate (open loop) / target
      qps (closed loop) — threads through the traced ``offered_qps``
      argument, so it is exact;
    - ``cpu_scale`` multiplies the per-request CPU demand: service
      draws scale by s and every station's mu scales by 1/s inside
      the traced wait law (engine ``_simulate_core``).  The
      closed-loop equilibrium rate and the host-side retry-feedback
      visit fixed point are solved at the BASE cpu (a second-order
      approximation, documented on ``Simulator.run_ensemble``);
    - ``error_scale`` multiplies the per-hop error rates (clipped to
      [0, 1]); statically-zero rates stay zero.

    ``chunk`` caps how many members run in one device dispatch; None
    lets the engine pre-compute it from the vet cost model the way
    VET-M* pre-selects degradation-ladder rungs.

    ``mode`` selects how the one jitted fleet program batches the
    member axis — ``"vmap"`` (a true leading batch dimension: the
    accelerator idiom, every member's tensors fused into wide ops the
    MXU eats) or ``"map"`` (``lax.map``: members sweep serially
    INSIDE the program — still one trace / one compile / one dispatch
    for the whole fleet, but per-member op shapes stay the solo
    program's, which on CPU keeps scatters vectorized and working
    sets cache-sized).  ``None`` auto-selects like
    ``SimParams.pallas_census``: vmap on accelerator backends, map on
    CPU.  Either mode keeps member k bit-identical to its solo run.
    """

    seeds: Tuple[int, ...]
    qps_scale: Optional[np.ndarray] = None
    cpu_scale: Optional[np.ndarray] = None
    error_scale: Optional[np.ndarray] = None
    chunk: Optional[int] = None
    mode: Optional[str] = None

    def __post_init__(self):
        object.__setattr__(
            self, "seeds", tuple(int(s) for s in self.seeds)
        )
        n = len(self.seeds)
        for name in ("qps_scale", "cpu_scale", "error_scale"):
            object.__setattr__(
                self, name,
                _scale_array(getattr(self, name), n, name),
            )
        if self.chunk is not None and self.chunk < 1:
            raise ValueError("chunk must be >= 1 (or None = auto)")
        if self.mode not in (None, "vmap", "map"):
            raise ValueError(
                f"unknown ensemble mode {self.mode!r} (expected "
                "'vmap', 'map', or None = auto)"
            )

    def resolved_mode(self) -> str:
        """The concrete batching mode (auto resolves per backend)."""
        if self.mode is not None:
            return self.mode
        import jax

        return "vmap" if jax.default_backend() != "cpu" else "map"

    @property
    def members(self) -> int:
        return len(self.seeds)

    @property
    def jittered(self) -> bool:
        """True when any per-member physics perturbation is armed
        (the traced program then threads the scale arguments)."""
        return (
            self.cpu_scale is not None or self.error_scale is not None
        )

    def check(self, allow_duplicate_seeds: bool = False) -> None:
        """Run-entry validation (the loud version of VET-T023)."""
        if self.members == 0:
            raise ValueError(
                "ensemble spec has zero members (VET-T023)"
            )
        if not allow_duplicate_seeds and (
            len(set(self.seeds)) != self.members
        ):
            dupes = sorted(
                {s for s in self.seeds if self.seeds.count(s) > 1}
            )
            raise ValueError(
                f"ensemble spec has duplicate member seeds {dupes} "
                "(VET-T023): duplicated members are bit-identical "
                "copies, not extra Monte Carlo samples"
            )

    @classmethod
    def of(cls, members: int, chunk: Optional[int] = None,
           mode: Optional[str] = None) -> "EnsembleSpec":
        """The plain seeds-only fleet: seeds 0..members-1."""
        return cls(seeds=tuple(range(int(members))), chunk=chunk,
                   mode=mode)

    @classmethod
    def from_jitter(
        cls,
        members: int,
        *,
        qps_jitter: float = 0.0,
        cpu_jitter: float = 0.0,
        error_jitter: float = 0.0,
        jitter_seed: int = 0,
        chunk: Optional[int] = None,
        mode: Optional[str] = None,
    ) -> "EnsembleSpec":
        """Seeds 0..N-1 plus deterministic lognormal perturbations.

        Each jitter is the log-space sigma of a mean-preserving
        lognormal factor ``exp(sigma Z - sigma^2 / 2)`` drawn from a
        host RNG seeded by ``jitter_seed`` — the same fleet spec
        reproduces bit-identical scale tables on every host.
        """
        members = int(members)
        for name, j in (("qps_jitter", qps_jitter),
                        ("cpu_jitter", cpu_jitter),
                        ("error_jitter", error_jitter)):
            if j < 0:
                raise ValueError(f"{name} must be >= 0")
        rng = np.random.default_rng(int(jitter_seed))

        def draw(sigma):
            # one draw per axis regardless of arming keeps the axes'
            # streams independent of which jitters are on
            z = rng.standard_normal(max(members, 1))
            if sigma <= 0:
                return None
            return np.exp(sigma * z - 0.5 * sigma * sigma)

        qps = draw(qps_jitter)
        cpu = draw(cpu_jitter)
        err = draw(error_jitter)
        return cls(
            seeds=tuple(range(members)),
            qps_scale=qps, cpu_scale=cpu, error_scale=err,
            chunk=chunk, mode=mode,
        )

    def to_dict(self) -> dict:
        return {
            "seeds": list(self.seeds),
            "qps_scale": (
                None if self.qps_scale is None
                else [float(x) for x in self.qps_scale]
            ),
            "cpu_scale": (
                None if self.cpu_scale is None
                else [float(x) for x in self.cpu_scale]
            ),
            "error_scale": (
                None if self.error_scale is None
                else [float(x) for x in self.error_scale]
            ),
            "chunk": self.chunk,
            "mode": self.mode,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "EnsembleSpec":
        return cls(
            seeds=tuple(d["seeds"]),
            qps_scale=d.get("qps_scale"),
            cpu_scale=d.get("cpu_scale"),
            error_scale=d.get("error_scale"),
            chunk=d.get("chunk"),
            mode=d.get("mode"),
        )


def wilson_interval(k: float, n: float, confidence: float = 0.95
                    ) -> Tuple[float, float]:
    """Wilson score interval for a binomial proportion k/n.

    The interval of choice for small-N rare-event estimates: unlike
    the Wald interval it never collapses to width 0 at k in {0, n}
    and never leaves [0, 1].  ``confidence`` maps to the normal
    quantile via the Acklam/Beasley-Springer inverse-normal
    approximation (|relative error| < 1.2e-9 — closed form, no scipy).
    """
    n = float(n)
    if n <= 0:
        return (0.0, 1.0)
    if not 0.0 < confidence < 1.0:
        raise ValueError("confidence must lie in (0, 1)")
    z = norm_ppf(0.5 + confidence / 2.0)
    p = float(k) / n
    z2 = z * z
    denom = 1.0 + z2 / n
    center = (p + z2 / (2.0 * n)) / denom
    half = (
        z / denom * np.sqrt(p * (1.0 - p) / n + z2 / (4.0 * n * n))
    )
    return (float(max(0.0, center - half)),
            float(min(1.0, center + half)))


def norm_ppf(q: float) -> float:
    """Inverse standard-normal CDF (Acklam's rational approximation).

    Deliberately NOT ``jax.scipy.special.ndtri``: under the repo's
    x64-off policy that evaluates in f32 (~1e-7 error on CI bounds,
    plus a device dispatch per call), while this closed form runs in
    f64 on host (|rel err| < 1.2e-9, pinned against scipy in
    tests/test_ensemble.py)."""
    if not 0.0 < q < 1.0:
        raise ValueError("q must lie in (0, 1)")
    # coefficients from Acklam (2003); relative error < 1.15e-9
    a = (-3.969683028665376e+01, 2.209460984245205e+02,
         -2.759285104469687e+02, 1.383577518672690e+02,
         -3.066479806614716e+01, 2.506628277459239e+00)
    b = (-5.447609879822406e+01, 1.615858368580409e+02,
         -1.556989798598866e+02, 6.680131188771972e+01,
         -1.328068155288572e+01)
    c = (-7.784894002430293e-03, -3.223964580411365e-01,
         -2.400758277161838e+00, -2.549732539343734e+00,
         4.374664141464968e+00, 2.938163982698783e+00)
    d = (7.784695709041462e-03, 3.224671290700398e-01,
         2.445134137142996e+00, 3.754408661907416e+00)
    p_low = 0.02425
    if q < p_low:
        u = np.sqrt(-2.0 * np.log(q))
        return (
            (((((c[0] * u + c[1]) * u + c[2]) * u + c[3]) * u + c[4])
             * u + c[5])
            / ((((d[0] * u + d[1]) * u + d[2]) * u + d[3]) * u + 1.0)
        )
    if q > 1.0 - p_low:
        return -norm_ppf(1.0 - q)
    u = q - 0.5
    t = u * u
    return (
        (((((a[0] * t + a[1]) * t + a[2]) * t + a[3]) * t + a[4])
         * t + a[5]) * u
        / (((((b[0] * t + b[1]) * t + b[2]) * t + b[3]) * t + b[4])
           * t + 1.0)
    )


def member_summary(stacked, k: int):
    """Member ``k``'s RunSummary sliced out of a stacked summary
    (every leaf carries a leading member axis; ``metrics`` is None on
    ensemble runs)."""
    import jax

    return jax.tree.map(lambda x: np.asarray(x)[k], stacked)


@dataclasses.dataclass(frozen=True)
class EnsembleSummary:
    """The reduced view of one ensemble dispatch.

    ``summaries`` is a :class:`~isotope_tpu.sim.summary.RunSummary`
    whose leaves carry a leading ``(N,)`` member axis (``metrics`` is
    None — the per-service collector series stay out of the vmapped
    program).  Everything distributional derives from the per-member
    windowed latency histograms, so the ensemble's device footprint is
    O(N * buckets), never O(N * requests).
    """

    spec: EnsembleSpec
    summaries: object  # RunSummary with (N,)-leading leaves
    offered_qps: np.ndarray  # (N,) per-member offered rate actually run
    chunk: int               # members per device dispatch actually used
    # -- chaos fleets (PR 15) -------------------------------------------
    # per-member jittered ChaosEvent tuples (None = every member ran
    # the base schedule); protected fleets additionally stack the
    # flight-recorder timelines and the policy / rollout actuation
    # series per member (None on plain fleets)
    member_chaos: Optional[list] = None
    timelines: Optional[object] = None   # TimelineSummary, (N,)-leading
    policies: Optional[object] = None    # PolicySummary, (N,)-leading
    rollouts: Optional[object] = None    # RolloutSummary, (N,)-leading
    # fleet observability (PR 17): per-member critical-path blame —
    # an AttributionSummary with (N,)-leading leaves when the fleet
    # ran with attribution armed
    attributions: Optional[object] = None

    @property
    def members(self) -> int:
        return self.spec.members

    @property
    def protected(self) -> bool:
        return self.policies is not None or self.rollouts is not None

    def member(self, k: int):
        return member_summary(self.summaries, k)

    def member_timeline(self, k: int):
        if self.timelines is None:
            raise ValueError("this fleet carried no timelines")
        return member_summary(self.timelines, k)

    def member_policies(self, k: int):
        if self.policies is None:
            raise ValueError("this fleet carried no policy series")
        return member_summary(self.policies, k)

    def member_rollouts(self, k: int):
        if self.rollouts is None:
            raise ValueError("this fleet carried no rollout series")
        return member_summary(self.rollouts, k)

    def member_attribution(self, k: int):
        if self.attributions is None:
            raise ValueError("this fleet carried no attribution")
        return member_summary(self.attributions, k)

    def severity(self, mode: str = "err_peak",
                 slo_s: Optional[float] = None) -> np.ndarray:
        """(N,) per-member severity scores (sim/splitting.py): the
        statistic fleets are ranked by — peak per-window client error
        share when the recorder rode the fleet, run-long error share
        otherwise, or SLO-violation depth (``p99``)."""
        from isotope_tpu.sim.splitting import (
            SplitSpec,
            severity_scores,
        )

        spec = SplitSpec(severity=mode, slo_s=slo_s)
        return severity_scores(spec, self.summaries, self.timelines)

    def worst_member(self, mode: str = "err_peak",
                     slo_s: Optional[float] = None) -> int:
        """The most-severe member — the fleet's postmortem subject
        (the runner dumps its policies/rollout/timeline artifacts
        with a member + seed stamp so the bad day replays solo)."""
        return int(np.argmax(self.severity(mode, slo_s)))

    def member_quantiles(self, qs=DOC_QUANTILES, window: bool = True
                         ) -> np.ndarray:
        """(N, len(qs)) per-member latency quantiles, from each
        member's (windowed, when ``window``) histogram.  A member
        whose trim window accumulated nothing (a run shorter than the
        collector's 62s skip) falls back to its full-run histogram —
        empty-window quantiles would read as ~0 latency."""
        from isotope_tpu.metrics.histogram import quantile_from_histogram

        full = np.asarray(self.summaries.latency_hist)
        if window:
            win = np.asarray(self.summaries.win_latency_hist)
            hists = np.where(
                (win.sum(axis=1) > 0)[:, None], win, full
            )
        else:
            hists = full
        return np.stack(
            [quantile_from_histogram(h, qs) for h in hists]
        )

    def quantile_band(self, q: float = 0.99,
                      band=(0.1, 0.5, 0.9)) -> dict:
        """The across-member spread of one latency quantile: the
        ensemble's answer to "how uncertain is my p99?"."""
        per_member = self.member_quantiles((q,))[:, 0]
        lo, mid, hi = np.quantile(per_member, band)
        return {
            "quantile": float(q),
            "members": int(self.members),
            "band": [float(b) for b in band],
            "lo_s": float(lo),
            "mid_s": float(mid),
            "hi_s": float(hi),
            "min_s": float(per_member.min()),
            "max_s": float(per_member.max()),
        }

    def slo_violation(self, slo_s: float, quantile: float = 0.99,
                      confidence: float = 0.95,
                      splitting: Optional[dict] = None) -> dict:
        """P(member's latency quantile exceeds ``slo_s``) with a
        Wilson confidence interval over the member count.

        At ZERO observed violations the Wilson interval degenerates
        to ``[0, upper]`` — the exact regime importance splitting
        exists for — so when a ``splitting`` block
        (sim/splitting.py) is available its estimate is reported
        alongside instead of leaving only the one-sided bound."""
        per_member = self.member_quantiles((quantile,))[:, 0]
        n = self.members
        k = int((per_member > float(slo_s)).sum())
        lo, hi = wilson_interval(k, n, confidence)
        out = {
            "slo_s": float(slo_s),
            "quantile": float(quantile),
            "members": int(n),
            "violations": k,
            "p_violation": k / max(n, 1),
            "confidence": float(confidence),
            "ci_lo": lo,
            "ci_hi": hi,
        }
        if k == 0 and splitting is not None:
            out["p_splitting"] = float(splitting.get("p", 0.0))
            out["splitting_ci"] = [
                float(splitting.get("ci_lo", 0.0)),
                float(splitting.get("ci_hi", hi)),
            ]
            out["note"] = (
                "zero observed violations: the Wilson interval is "
                "one-sided; p_splitting is the importance-splitting "
                "estimate of the tail"
            )
        return out

    def error_rate_stats(self) -> dict:
        """Across-member client error-share distribution."""
        counts = np.asarray(self.summaries.count, np.float64)
        errs = np.asarray(self.summaries.error_count, np.float64)
        shares = errs / np.maximum(counts, 1.0)
        return {
            "mean": float(shares.mean()),
            "min": float(shares.min()),
            "max": float(shares.max()),
        }

    def pooled(self):
        """All members merged into ONE RunSummary (the solo-shaped
        view the runner reports when an ensemble served the case)."""
        from isotope_tpu.sim.summary import reduce_stacked

        return reduce_stacked(self.summaries)

    def to_doc(self, label: str = "",
               slo_s: Optional[float] = None,
               qs: Sequence[float] = DOC_QUANTILES,
               splitting: Optional[dict] = None) -> dict:
        """The ``isotope-ensemble/v2`` artifact document.

        ``splitting`` attaches a rare-event estimate block
        (``isotope-splitting/v1``, sim/splitting.py) behind the
        schema-versioned ``splitting`` key; protected fleets
        additionally record per-member severity and the worst
        member's identity (the postmortem pointer)."""
        mq = self.member_quantiles(qs)
        counts = np.asarray(self.summaries.count, np.float64)
        errs = np.asarray(self.summaries.error_count, np.float64)
        hops = np.asarray(self.summaries.hop_events, np.float64)
        doc = {
            "schema": DOC_SCHEMA,
            "label": label,
            "members": int(self.members),
            "chunk": int(self.chunk),
            "spec": self.spec.to_dict(),
            "offered_qps": [float(x) for x in self.offered_qps],
            "quantiles": [float(q) for q in qs],
            "member_quantiles_s": [
                [float(x) for x in row] for row in mq
            ],
            "member_counts": [float(x) for x in counts],
            "member_error_counts": [float(x) for x in errs],
            "member_hop_events": [float(x) for x in hops],
            "quantile_band_p99": self.quantile_band(0.99),
            "error_share": self.error_rate_stats(),
        }
        if self.protected or self.timelines is not None:
            sev = self.severity()
            worst = int(np.argmax(sev))
            doc["protected"] = self.protected
            doc["severity"] = [float(x) for x in sev]
            doc["worst_member"] = worst
            # valid for fold_in-derived fleets; callers that supplied
            # explicit member_keys (the runner's control member 0)
            # must override this with their own key recipe
            doc["worst_member_seed"] = int(self.spec.seeds[worst])
        if self.member_chaos is not None:
            doc["member_chaos"] = True
        if slo_s is not None:
            doc["slo"] = self.slo_violation(slo_s, splitting=splitting)
        if splitting is not None:
            doc["splitting"] = splitting
        return doc


def doc_member_quantiles(doc: dict) -> np.ndarray:
    """Round-trip reader: the (N, Q) per-member quantile table out of
    an ``isotope-ensemble/v1`` or ``v2`` document (runner artifact)."""
    if doc.get("schema") not in DOC_SCHEMAS:
        raise ValueError(
            f"not an {DOC_SCHEMA} document: {doc.get('schema')!r}"
        )
    return np.asarray(doc["member_quantiles_s"], np.float64)


def parse_jitter_spec(text: Optional[str]) -> dict:
    """Parse the CLI seed-jitter spec ``"qps=0.1,cpu=0.05,error=0.2"``
    into :meth:`EnsembleSpec.from_jitter` kwargs."""
    out = {"qps_jitter": 0.0, "cpu_jitter": 0.0, "error_jitter": 0.0}
    if not text:
        return out
    keys = {"qps": "qps_jitter", "cpu": "cpu_jitter",
            "error": "error_jitter", "err": "error_jitter",
            "seed": "jitter_seed"}
    for part in str(text).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad jitter spec entry {part!r} (expected "
                "axis=value, axes: qps, cpu, error, seed)"
            )
        k, v = part.split("=", 1)
        k = k.strip().lower()
        if k not in keys:
            raise ValueError(
                f"unknown jitter axis {k!r} (expected qps, cpu, "
                "error, or seed)"
            )
        out[keys[k]] = (
            int(v) if keys[k] == "jitter_seed" else float(v)
        )
    return out
