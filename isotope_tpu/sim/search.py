"""On-device config search: successive-halving brackets as a few
jitted dispatches.

The sweep runner was the last layer that scaled O(configs) in host
overhead — one dispatch, one host round-trip, and often one retrace
per candidate — while the fleet engine (sim/ensemble.py) scales O(1)
in compiles.  A :class:`SearchSpec` closes that gap for *screening*:
the candidate population is an :class:`EnsembleSpec` (stacked ``(N,)``
traced perturbations via ``compiler/compile.compile_ensemble`` — qps /
cpu / error scales today; trace-constant knobs like replica counts and
timeout budgets are a ROADMAP residual), and the bracket is classic
successive halving (ASHA without the asynchrony):

- rung 0 runs all N candidates for a SHORT horizon in one fleet
  dispatch (chunked only by the carry-aware cost model);
- candidates are ranked ON DEVICE by a severity channel — the same
  channels ``sim/splitting.py`` ranks by (``err_share``, ``p99``
  SLO-violation depth; ``err_peak`` falls back to ``err_share`` since
  no recorder rides a search fleet), via
  :func:`~isotope_tpu.sim.splitting.severity_scores_device`;
- the best ``1/eta`` advance: a ``jnp.take`` gather over the stacked
  argument tables AND the ``(t0, conn_t0, req_off)`` scan carries
  (``compiler/compile.ensemble_take``), so the next rung *continues*
  the survivors' trajectories at a longer horizon instead of
  re-simulating from t=0 — no host round-trip between rungs.

One executable serves each rung shape: the horizon (``num_blocks``) is
a static arg and rung widths pad to powers of two
(``compiler/compile.rung_bucket``), so a whole bracket compiles once
per rung — 3 traces for a 64-candidate, 3-rung bracket vs 64 for the
sequential sweep (the ``search64`` bench case carries the evidence).
The carry buffers are donated between rungs on accelerators, keeping
bracket memory O(survivors).

Determinism contract: candidate k's rung-0 rows are bit-identical to
its ``run_ensemble`` member (same fold_in layout), a survivor's
continued trajectory replays the unbroken solo run's RNG streams and
carries exactly (``fold_in(key, 1_000_000 + b0 + b)``), and ties rank
through a fold_in-derived per-candidate uniform — so the full survivor
lineage is a pure function of (spec, key, horizon) on every path
(solo / sharded / emulated; pinned by tests/test_search.py).

The winner is the ``optimize`` roadmap item's warm start:
:meth:`SearchSummary.winner_config` hands over the surviving
candidate's exact scales and offered rate.
"""
from __future__ import annotations

import dataclasses
import json
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

from isotope_tpu import telemetry
from isotope_tpu.sim.ensemble import EnsembleSpec
from isotope_tpu.sim.splitting import SEVERITIES

DOC_SCHEMA = "isotope-search/v1"

#: rank channels — the splitting estimator's severity channels
SEARCH_RANKS = SEVERITIES


@dataclasses.dataclass(frozen=True)
class SearchSpec:
    """One successive-halving bracket over a candidate population.

    ``candidates`` is the stacked config population (every
    :class:`EnsembleSpec` perturbation axis is a search axis).
    ``eta`` is the halving rate: each rung keeps the best
    ``ceil(width / eta)``.  ``rungs`` counts screening levels
    including the final full-horizon rung.  ``growth`` scales the
    cumulative horizon between rungs (None = ``eta``, the classic
    budget-balanced bracket: every rung spends about the same total
    simulated requests).  ``rank`` picks the severity channel
    (:data:`SEARCH_RANKS`); ``p99`` needs ``slo_s``.  ``seed`` derives
    the deterministic tie-break draws.  ``chunk`` caps members per
    rung dispatch (None = carry-aware cost model).
    """

    candidates: EnsembleSpec
    eta: int = 4
    rungs: int = 3
    growth: Optional[int] = None
    rank: str = "err_share"
    slo_s: Optional[float] = None
    seed: int = 0
    chunk: Optional[int] = None

    def __post_init__(self):
        if not isinstance(self.candidates, EnsembleSpec):
            object.__setattr__(
                self, "candidates",
                EnsembleSpec.from_dict(dict(self.candidates)),
            )
        if self.eta < 2:
            raise ValueError(f"eta must be >= 2; got {self.eta}")
        if self.rungs < 1:
            raise ValueError(f"rungs must be >= 1; got {self.rungs}")
        if self.growth is not None and self.growth < 2:
            raise ValueError(
                f"growth must be >= 2 (or None = eta); got "
                f"{self.growth}: the horizon schedule could not "
                "increase between rungs (VET-T026)"
            )
        if self.rank not in SEARCH_RANKS:
            raise ValueError(
                f"unknown search rank {self.rank!r} (expected one of "
                f"{SEARCH_RANKS})"
            )
        if self.rank == "p99" and (
            self.slo_s is None or self.slo_s <= 0
        ):
            raise ValueError(
                "rank='p99' needs slo_s > 0 (the latency that maps "
                "to severity 1.0)"
            )
        if self.chunk is not None and self.chunk < 1:
            raise ValueError("chunk must be >= 1 (or None = auto)")

    @property
    def members(self) -> int:
        return self.candidates.members

    def resolved_growth(self) -> int:
        return self.eta if self.growth is None else self.growth

    def rung_widths(self) -> Tuple[int, ...]:
        """Live candidates per rung: ``ceil(N / eta^r)``."""
        n = self.members
        return tuple(
            -(-n // self.eta ** r) for r in range(self.rungs)
        )

    def check(self) -> None:
        """Run-entry validation (the loud version of VET-T026)."""
        self.candidates.check()
        widths = self.rung_widths()
        for a, b in zip(widths, widths[1:]):
            if b >= a:
                raise ValueError(
                    f"population of {self.members} cannot support "
                    f"{self.rungs} rungs at eta={self.eta}: rung "
                    f"widths {widths} stop shrinking (VET-T026) — "
                    "grow the population or drop rungs"
                )

    def to_dict(self) -> dict:
        return {
            "candidates": self.candidates.to_dict(),
            "eta": self.eta,
            "rungs": self.rungs,
            "growth": self.growth,
            "rank": self.rank,
            "slo_s": self.slo_s,
            "seed": self.seed,
            "chunk": self.chunk,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SearchSpec":
        return cls(
            candidates=EnsembleSpec.from_dict(d["candidates"]),
            eta=int(d.get("eta", 4)),
            rungs=int(d.get("rungs", 3)),
            growth=d.get("growth"),
            rank=d.get("rank", "err_share"),
            slo_s=d.get("slo_s"),
            seed=int(d.get("seed", 0)),
            chunk=d.get("chunk"),
        )


class RungPlan(NamedTuple):
    """One rung's static shape: the trace facts of its executable."""

    rung: int
    width: int        # live candidates
    bucket: int       # width padded to the pow2 executable family
    start_block: int  # cumulative blocks already simulated (b0)
    num_blocks: int   # this rung's continuation segment
    cum_requests: int  # per-candidate requests simulated through here


def plan_bracket(spec: SearchSpec, num_requests: int,
                 block: int) -> Tuple[RungPlan, ...]:
    """Resolve the bracket's static rung schedule.

    The cumulative horizon after rung r is
    ``ceil(total_blocks / growth^(rungs-1-r))`` blocks — the final
    rung lands exactly on the requested horizon and each earlier rung
    screens at ``1/growth`` of the next one's budget.  Rungs simulate
    only their *segment* (cumulative minus what the carries already
    hold), which is where the warm-start saving lives.  A schedule
    that fails to increase (horizon too short for the rung count) is
    the runtime edge VET-T026 lints for — raised loudly here.
    """
    from isotope_tpu.compiler.compile import rung_bucket

    spec.check()
    total_nb = max(1, -(-int(num_requests) // int(block)))
    growth = spec.resolved_growth()
    cum = [
        max(1, -(-total_nb // growth ** (spec.rungs - 1 - r)))
        for r in range(spec.rungs)
    ]
    for a, b in zip(cum, cum[1:]):
        if b <= a:
            raise ValueError(
                f"search horizon schedule is not increasing "
                f"({cum} blocks of {block} requests over "
                f"{spec.rungs} rungs at growth={growth}) — raise "
                "num_requests or drop rungs/growth (VET-T026)"
            )
    widths = spec.rung_widths()
    plans = []
    prev = 0
    for r in range(spec.rungs):
        plans.append(RungPlan(
            rung=r,
            width=widths[r],
            bucket=rung_bucket(widths[r]),
            start_block=prev,
            num_blocks=cum[r] - prev,
            cum_requests=cum[r] * block,
        ))
        prev = cum[r]
    return tuple(plans)


@dataclasses.dataclass(frozen=True)
class RungResult:
    """One rung's lineage: who ran, how they scored, who survived."""

    rung: int
    width: int
    chunk: int
    start_block: int
    num_blocks: int
    cum_requests: int
    candidates: np.ndarray   # (width,) global candidate ids, rank order of the PREVIOUS rung
    severity: np.ndarray     # (width,) this rung's severity per candidate
    survivors: np.ndarray    # global ids advanced (rank order; final rung: the winner)
    summaries: object        # member-stacked RunSummary (np leaves)
    # per-rung evidence (PR 17): what the rung COST and how close the
    # cut was — enough for ``isotope-tpu explain`` to narrate the
    # bracket without re-running it
    order: Optional[np.ndarray] = None   # (width,) rank order (row indices)
    traces: int = 0                      # engine traces this rung triggered
    compile_s: float = 0.0               # jit first-call wall this rung paid


@dataclasses.dataclass(frozen=True)
class SearchSummary:
    """One bracket's outcome: winner + full per-rung survivor lineage."""

    spec: SearchSpec
    block: int
    plan: Tuple[RungPlan, ...]
    rungs: List[RungResult]
    winner: int
    winner_severity: float
    offered_qps: np.ndarray   # (N,) per-candidate planned rates
    traces: int
    mode: str

    def winner_config(self) -> dict:
        """The surviving candidate's exact config — the warm start
        the ``optimize`` roadmap item picks up."""
        pop = self.spec.candidates
        k = self.winner

        def scale(arr):
            return None if arr is None else float(arr[k])

        return {
            "candidate": k,
            "seed": pop.seeds[k],
            "qps_scale": scale(pop.qps_scale),
            "cpu_scale": scale(pop.cpu_scale),
            "error_scale": scale(pop.error_scale),
            "offered_qps": float(self.offered_qps[k]),
            "severity": self.winner_severity,
            "rank": self.spec.rank,
        }

    def winner_summary(self):
        """The winner's bracket-combined RunSummary: its per-rung
        segment rows merged with the streaming accumulate (same float
        caveat as :func:`~isotope_tpu.sim.summary.summary_accumulate`)."""
        import jax

        from isotope_tpu.sim import summary as summary_mod

        acc = None
        for r in self.rungs:
            row = int(np.where(r.candidates == self.winner)[0][0])
            part = jax.tree.map(lambda x: x[row], r.summaries)
            acc = part if acc is None else summary_mod.summary_accumulate(
                acc, part
            )
        return acc

    def pooled(self):
        """Every simulated row of the whole bracket reduced to ONE
        RunSummary — the bench unit (total hop events the bracket
        bought for its wall-clock)."""
        from isotope_tpu.sim import summary as summary_mod

        acc = None
        for r in self.rungs:
            part = summary_mod.reduce_stacked(r.summaries)
            acc = part if acc is None else summary_mod.summary_accumulate(
                acc, part
            )
        return acc

    def to_doc(self, label: str = "") -> dict:
        """The ``<label>.search.json`` isotope-search/v1 artifact."""
        return {
            "schema": DOC_SCHEMA,
            "label": label,
            "rank": self.spec.rank,
            "rank_effective": (
                "err_share" if self.spec.rank == "err_peak"
                else self.spec.rank
            ),
            "eta": self.spec.eta,
            "growth": self.spec.resolved_growth(),
            "candidates": self.spec.members,
            "block": self.block,
            "traces": self.traces,
            "mode": self.mode,
            "winner": self.winner_config(),
            "lineage": [
                self._rung_entry(r) for r in self.rungs
            ],
            "spec": self.spec.to_dict(),
        }

    def _rung_entry(self, r: RungResult) -> dict:
        """One lineage row with its evidence block (PR 17): per-rung
        trace/compile cost plus the CUT LINE — the last-kept vs
        first-cut severities (rank channel values) — so ``isotope-tpu
        explain`` can narrate why the winner beat the runner-up at
        every rung without re-running the bracket."""
        entry = {
            "rung": r.rung,
            "width": r.width,
            "chunk": r.chunk,
            "start_block": r.start_block,
            "num_blocks": r.num_blocks,
            "cum_requests": r.cum_requests,
            "candidates": [int(x) for x in r.candidates],
            "severity": [float(x) for x in r.severity],
            "survivors": [int(x) for x in r.survivors],
        }
        evidence = {
            "traces": int(r.traces),
            "compile_s": round(float(r.compile_s), 4),
        }
        if r.order is not None:
            keep = len(r.survivors)
            ranked = [int(r.candidates[i]) for i in r.order]
            evidence["rank_order"] = ranked
            last_kept = int(r.order[keep - 1])
            cut = {
                "kept": keep,
                "last_kept": {
                    "candidate": int(r.candidates[last_kept]),
                    "severity": float(r.severity[last_kept]),
                },
            }
            if keep < r.width:
                first_cut = int(r.order[keep])
                cut["first_cut"] = {
                    "candidate": int(r.candidates[first_cut]),
                    "severity": float(r.severity[first_cut]),
                }
                cut["margin"] = float(
                    r.severity[first_cut] - r.severity[last_kept]
                )
            entry["cut"] = cut
        entry["evidence"] = evidence
        return entry


def check_doc(doc: dict) -> dict:
    """Validate an isotope-search/v1 document (round-trip guard)."""
    if doc.get("schema") != DOC_SCHEMA:
        raise ValueError(
            f"not an {DOC_SCHEMA} document: {doc.get('schema')!r}"
        )
    return doc


def load_doc(path: str) -> dict:
    with open(path) as f:
        return check_doc(json.load(f))


# -- the bracket engine ------------------------------------------------


def tiebreak_draws(spec: SearchSpec):
    """One deterministic uniform per candidate: the rank tie-break.

    Derived ``fold_in(PRNGKey(spec.seed), candidate_seed)`` — a pure
    function of the spec, independent of the run key and of which
    rung the candidate reaches, so ties resolve identically on every
    bracket path and every rung (rank-determinism pin)."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(int(spec.seed))
    seeds = jnp.asarray(spec.candidates.seeds, jnp.uint32)
    return _tiebreak_fn()(key, seeds)


_TIEBREAK = None


def _tiebreak_fn():
    global _TIEBREAK
    if _TIEBREAK is None:
        import jax

        @jax.jit
        def draws(key, seeds):
            return jax.vmap(
                lambda s: jax.random.uniform(
                    jax.random.fold_in(key, s)
                )
            )(seeds)

        _TIEBREAK = draws
    return _TIEBREAK


def _floor_pow2(x: int) -> int:
    return 1 << (max(int(x), 1).bit_length() - 1)


_RANK_ADVANCE = None


def _rank_advance_fn():
    """The jitted rank-and-advance program: severity -> lexsort ->
    survivor gathers in ONE dispatch per rung shape.  Eagerly these
    are ~40 tiny op dispatches per rung — on a 1-core host they cost
    as much as the fleet itself at screening horizons.  Compiled
    lazily and cached per (rank, slo, keep, shapes) by jax.jit; NOT an
    engine trace (record_trace never fires), so the <= rungs
    engine-trace bound is untouched."""
    global _RANK_ADVANCE
    if _RANK_ADVANCE is None:
        import functools

        import jax
        import jax.numpy as jnp

        from isotope_tpu.compiler.compile import ensemble_take
        from isotope_tpu.sim.splitting import severity_scores_device

        @functools.partial(
            jax.jit, static_argnames=("rank", "slo_s", "keep")
        )
        def advance(summ, tb, ids, cur, carry, *, rank, slo_s, keep):
            sev = severity_scores_device(rank, summ, slo_s)
            # primary: severity ascending; ties: the fold_in uniforms
            order = jnp.lexsort((tb, sev))
            surv = order[:keep]
            return (
                sev,
                order,
                ensemble_take(cur, surv),
                ensemble_take(carry, surv),
                jnp.take(ids, surv),
                jnp.take(tb, surv),
            )

        _RANK_ADVANCE = advance
    return _RANK_ADVANCE


def _device_concat(parts, width: int):
    """jnp chunk concat + pad drop — the device-resident inverse of
    the pad law (``Simulator._ensemble_concat`` round-trips through
    host numpy; rung advancement must NOT)."""
    import jax
    import jax.numpy as jnp

    if len(parts) == 1:
        return jax.tree.map(lambda x: x[:width], parts[0])
    return jax.tree.map(
        lambda *xs: jnp.concatenate(xs, axis=0)[:width], *parts
    )


def search_auto_chunk(sim, members: int, block: int,
                      connections: int) -> int:
    """The carry-aware member chunk (VET-T025 discipline): the
    ensemble chunk law with the search carries' per-member bytes on
    the ledger."""
    from isotope_tpu.analysis import costmodel

    cap = costmodel.device_capacity_bytes()
    est = costmodel.estimate_run(sim, block)
    return costmodel.ensemble_chunk(
        members, est.peak_bytes_at_block, cap,
        carry_bytes_per_member=costmodel.search_carry_bytes(
            connections
        ),
    )


def _solo_dispatch(sim, args, tables, spec, chunk, plan):
    """Per-rung dispatcher for the single-device bracket: pow2-
    bucketed member chunks through ``Simulator._get_search``."""
    import jax

    block, conns = args["block"], args["conns"]
    cap = chunk if chunk is not None else spec.chunk
    if cap is None:
        cap = search_auto_chunk(sim, plan[0].bucket, block, conns)

    def dispatch(rp, xs):
        chunk_sz = max(1, min(rp.bucket, _floor_pow2(cap)))
        n_chunks = -(-rp.width // chunk_sz)
        total = n_chunks * chunk_sz
        fn = sim._get_search(
            block, rp.num_blocks, args["kind"], conns, args["sat"],
            chunk_sz, tables.jittered, tables.mode,
        )
        padded = sim._ensemble_pad_args(xs, rp.width, total)
        if n_chunks == 1 and chunk_sz == rp.width:
            # the common screening shape (pow2 rung widths, one
            # chunk): no pad rows to strip, so skip the per-leaf
            # eager slicing entirely — at short horizons those ~30
            # dispatches cost more than the rung's compute
            out, cout = fn(*padded)
            return out, cout, chunk_sz
        parts, carries = [], []
        for ci in range(n_chunks):
            sl = slice(ci * chunk_sz, (ci + 1) * chunk_sz)
            out, cout = fn(*(x[sl] for x in padded))
            parts.append(out)
            carries.append(cout)
            if n_chunks > 1:
                jax.block_until_ready(parts[-1].count)
        return (
            _device_concat(parts, rp.width),
            _device_concat(carries, rp.width),
            chunk_sz,
        )

    return dispatch


def _run_bracket(sim, load, num_requests: int, key, spec: SearchSpec,
                 block_size: int, dispatch_factory, path: str
                 ) -> SearchSummary:
    """The shared bracket loop every path (solo/sharded/emulated)
    drives: plan once, then per rung dispatch -> rank on device ->
    gather survivors' stacked args + carries -> continue.  Only the
    final lineage materializes on host."""
    import jax
    import jax.numpy as jnp

    from isotope_tpu.compiler.compile import compile_ensemble

    spec.check()
    sim._check_lb_load(load)
    pop = spec.candidates
    tables = compile_ensemble(pop)
    args = sim._ensemble_args(
        load, num_requests, key, pop, tables,
        block_size=block_size, trim=False,
    )
    block = args["block"]
    plan = plan_bracket(spec, num_requests, block)
    telemetry.counter_inc("search_runs")
    telemetry.gauge_set("search_candidates", pop.members)
    telemetry.gauge_set("search_rungs", spec.rungs)
    telemetry.set_meta("search_path", path)
    dispatch = dispatch_factory(args, tables, plan)
    cur = sim._ensemble_stacked_args(args)
    carry = sim.zero_ensemble_carry(pop.members, args["conns"])
    tb = tiebreak_draws(spec)
    ids = jnp.arange(pop.members, dtype=jnp.int32)
    lineage = []
    chunk_szs = []
    rung_costs = []
    advance = _rank_advance_fn()
    traces0 = telemetry.counter_get("engine_traces")
    for r, rp in enumerate(plan):
        # per-rung cost evidence (PR 17): trace and compile-wall
        # deltas around the rung's dispatch, so the search artifact
        # can say WHICH rung paid the compiles
        rt0 = telemetry.counter_get("engine_traces")
        rc0 = telemetry.phase_seconds("compile.jit_first_call")
        b0 = np.full((rp.width,), rp.start_block, np.int32)
        summ, carry_out, chunk_sz = dispatch(
            rp, cur + (b0,) + tuple(carry)
        )
        keep = plan[r + 1].width if r + 1 < len(plan) else 1
        sev, order, cur_n, carry_n, ids_n, tb_n = advance(
            summ, tb, ids, cur, carry_out,
            rank=spec.rank, slo_s=spec.slo_s, keep=keep,
        )
        lineage.append((ids, sev, order, summ))
        chunk_szs.append(chunk_sz)
        rung_costs.append((
            int(telemetry.counter_get("engine_traces") - rt0),
            telemetry.phase_seconds("compile.jit_first_call") - rc0,
        ))
        cur, carry, ids, tb = cur_n, carry_n, ids_n, tb_n
    traces = int(telemetry.counter_get("engine_traces") - traces0)
    telemetry.gauge_set("search_traces", traces)
    # ONE batched host transfer for the whole lineage (per-leaf
    # np.asarray costs a sync each — measurably slow at screening
    # horizons on a 1-core host)
    lineage = jax.device_get(lineage)
    rungs = []
    for rp, (ids_r, sev_r, order_r, summ_r), chunk_sz, cost in zip(
        plan, lineage, chunk_szs, rung_costs
    ):
        ids_np = np.asarray(ids_r)
        order_np = np.asarray(order_r)
        keep = (
            plan[rp.rung + 1].width
            if rp.rung + 1 < len(plan) else 1
        )
        rungs.append(RungResult(
            rung=rp.rung,
            width=rp.width,
            chunk=int(chunk_sz),
            start_block=rp.start_block,
            num_blocks=rp.num_blocks,
            cum_requests=rp.cum_requests,
            candidates=ids_np,
            severity=np.asarray(sev_r),
            survivors=ids_np[order_np[:keep]],
            summaries=summ_r,
            order=order_np,
            traces=cost[0],
            compile_s=cost[1],
        ))
    winner = int(rungs[-1].survivors[0])
    win_row = int(np.where(rungs[-1].candidates == winner)[0][0])
    return SearchSummary(
        spec=spec,
        block=block,
        plan=plan,
        rungs=rungs,
        winner=winner,
        winner_severity=float(rungs[-1].severity[win_row]),
        offered_qps=args["offered"],
        traces=traces,
        mode=tables.mode,
    )


def run_search(sim, load, num_requests: int, key, spec: SearchSpec,
               *, block_size: int = 65_536,
               chunk: Optional[int] = None) -> SearchSummary:
    """Run one successive-halving bracket on a single device."""
    return _run_bracket(
        sim, load, num_requests, key, spec, block_size,
        lambda args, tables, plan: _solo_dispatch(
            sim, args, tables, spec, chunk, plan
        ),
        path="solo",
    )


def _sharded_geometry(sh, rp, cap):
    """Balanced (width, rounds) for one rung over the flattened mesh
    — the ``_plan_ensemble`` round law applied per rung."""
    per_shard = -(-rp.width // sh.n_shards)
    width = max(1, min(int(cap), per_shard))
    rounds = -(-per_shard // width)
    return -(-per_shard // rounds), rounds


def _sharded_dispatch(sh, args, tables, spec, chunk, plan):
    """Per-rung dispatcher over the mesh: rounds of shard_mapped
    carry-I/O fleet slices, member order identical to the emulated
    twin's flat (round, shard) walk."""
    import jax

    sim = sh.sim
    block, conns = args["block"], args["conns"]
    cap = chunk if chunk is not None else spec.chunk
    if cap is None:
        cap = search_auto_chunk(
            sim, -(-plan[0].width // sh.n_shards), block, conns
        )

    def dispatch(rp, xs):
        width, rounds = _sharded_geometry(sh, rp, cap)
        total = rounds * width * sh.n_shards
        fn = sh._get_search_fn(
            block, rp.num_blocks, args["kind"], conns, args["sat"],
            width, tables,
        )
        padded = sim._ensemble_pad_args(xs, rp.width, total)
        per_round = width * sh.n_shards
        parts, carries = [], []
        for r in range(rounds):
            sl = slice(r * per_round, (r + 1) * per_round)
            out, cout = fn(*(x[sl] for x in padded))
            parts.append(out)
            carries.append(cout)
            if rounds > 1:
                jax.block_until_ready(parts[-1].count)
        return (
            _device_concat(parts, rp.width),
            _device_concat(carries, rp.width),
            width,
        )

    return dispatch


def _emulated_dispatch(sh, args, tables, spec, chunk, plan):
    """The sharded dispatcher's single-device twin: the same geometry
    walked serially as flat (round, shard) slices through the solo
    carry-I/O program — bit-equal to :func:`_sharded_dispatch` (no
    collectives exist in the fleet program)."""
    import jax

    sim = sh.sim
    block, conns = args["block"], args["conns"]
    cap = chunk if chunk is not None else spec.chunk
    if cap is None:
        cap = search_auto_chunk(
            sim, -(-plan[0].width // sh.n_shards), block, conns
        )

    def dispatch(rp, xs):
        width, rounds = _sharded_geometry(sh, rp, cap)
        total = rounds * width * sh.n_shards
        fn = sim._get_search(
            block, rp.num_blocks, args["kind"], conns, args["sat"],
            width, tables.jittered, tables.mode,
        )
        padded = sim._ensemble_pad_args(xs, rp.width, total)
        parts, carries = [], []
        with telemetry.phase("sharded.emulated"):
            for c in range(rounds * sh.n_shards):
                sl = slice(c * width, (c + 1) * width)
                out, cout = fn(*(x[sl] for x in padded))
                jax.block_until_ready(out.count)
                parts.append(out)
                carries.append(cout)
        return (
            _device_concat(parts, rp.width),
            _device_concat(carries, rp.width),
            width,
        )

    return dispatch


_RANK_ADVANCE_PROT = None


def _rank_advance_protected_fn():
    """The protected bracket's jitted rank-and-advance: same lexsort +
    gather as :func:`_rank_advance_fn`, with the stacked
    ``PolicySummary`` threaded in so the ``trips`` severity channel
    (breaker trips + budget ejections) can rank the population, and
    the FULL protected carry pytree gathered (clocks + recorder +
    control state) so survivors keep their breakers and budgets."""
    global _RANK_ADVANCE_PROT
    if _RANK_ADVANCE_PROT is None:
        import functools

        import jax
        import jax.numpy as jnp

        from isotope_tpu.compiler.compile import ensemble_take
        from isotope_tpu.sim.splitting import severity_scores_device

        @functools.partial(
            jax.jit, static_argnames=("rank", "slo_s", "keep")
        )
        def advance(summ, pol, tb, ids, cur, carry, *,
                    rank, slo_s, keep):
            sev = severity_scores_device(
                rank, summ, slo_s, policies=pol
            )
            order = jnp.lexsort((tb, sev))
            surv = order[:keep]
            return (
                sev,
                order,
                ensemble_take(cur, surv),
                ensemble_take(carry, surv),
                jnp.take(ids, surv),
                jnp.take(tb, surv),
            )

        _RANK_ADVANCE_PROT = advance
    return _RANK_ADVANCE_PROT


def run_search_protected(sim, load, num_requests: int, key,
                         spec: SearchSpec, *, roll: bool = False,
                         block_size: int = 65_536,
                         chunk: Optional[int] = None,
                         window_s: Optional[float] = None
                         ) -> SearchSummary:
    """A successive-halving bracket over a PROTECTED population — the
    config-search residual (a): each candidate is a full
    ``run_policies`` / ``run_rollouts`` member whose breakers,
    budgets, HPA, and rollout controller ride the carry BETWEEN rungs
    via the :meth:`Simulator.run_policies_ensemble` carry-I/O
    contract.  Survivors continue their control state where the rung
    stopped — a breaker that tripped at the screening horizon is still
    open when the next rung resumes.

    Ranking goes through the same device severity channels, with the
    ``trips`` channel (breaker trips + budget ejections from the
    stacked ``PolicySummary``) available to rank control-plane pain
    directly.  The flight-recorder window grid is planned ONCE over
    the full horizon (the carry's windowed accumulator must keep one
    static shape across rungs), so a 1-rung bracket is bit-identical
    to the protected fleet at the same horizon, and rung 0's member
    rows replay the protected fleet's exact streams (fold
    ``1_000_000 + b``, zero carries)."""
    import jax
    import jax.numpy as jnp

    from isotope_tpu.compiler.compile import compile_ensemble

    if roll and sim._rollouts is None:
        raise ValueError(
            "protected rollout brackets need compiled rollout tables "
            "(Simulator(..., rollouts=...))"
        )
    if not roll and sim._policies is None:
        raise ValueError(
            "protected policy brackets need compiled policy tables "
            "(Simulator(..., policies=...))"
        )
    if not sim.params.timeline:
        raise ValueError(
            "protected brackets need SimParams(timeline=True) — the "
            "flight recorder is the control loop's observation side"
        )
    if sim._saturated(load):
        raise ValueError(
            "protected brackets do not support saturated -qps max "
            "loads (see run_policies)"
        )
    spec.check()
    sim._check_lb_load(load)
    pop = spec.candidates
    tables = compile_ensemble(pop)
    args = sim._ensemble_args(
        load, num_requests, key, pop, tables,
        block_size=block_size, trim=False,
    )
    block, conns = args["block"], args["conns"]
    plan = plan_bracket(spec, num_requests, block)
    tl_plan = sim.plan_timeline_windows(
        args["num_blocks"] * block, float(args["offered"][0]),
        window_s,
    )
    with_pol = sim._policies is not None
    telemetry.counter_inc("search_protected_runs")
    telemetry.gauge_set("search_candidates", pop.members)
    telemetry.gauge_set("search_rungs", spec.rungs)
    telemetry.set_meta(
        "search_path", "protected-rollouts" if roll else "protected"
    )
    cap = chunk if chunk is not None else spec.chunk
    if cap is None:
        cap = sim.protected_ensemble_chunk(
            plan[0].bucket, block, tl_plan, roll,
        )

    def dispatch(rp, xs):
        chunk_sz = max(1, min(rp.bucket, _floor_pow2(cap)))
        n_chunks = -(-rp.width // chunk_sz)
        total = n_chunks * chunk_sz
        fn = sim._get_protected_ensemble(
            block, rp.num_blocks, args["kind"], conns, False,
            tl_plan, roll, chunk_sz, tables.jittered, tables.mode,
            False, attr=None, carry_io=True,
        )
        padded = sim._ensemble_pad_args(xs, rp.width, total)
        if n_chunks == 1 and chunk_sz == rp.width:
            out, cout = fn(*padded)
            return out, cout, chunk_sz
        parts, carries = [], []
        for ci in range(n_chunks):
            sl = slice(ci * chunk_sz, (ci + 1) * chunk_sz)
            out, cout = fn(*(x[sl] for x in padded))
            parts.append(out)
            carries.append(cout)
            if n_chunks > 1:
                jax.block_until_ready(parts[-1][0].count)
        return (
            _device_concat(parts, rp.width),
            _device_concat(carries, rp.width),
            chunk_sz,
        )

    cur = sim._ensemble_stacked_args(args)
    carry = sim.zero_protected_carry(
        pop.members, conns, tl_plan, roll=roll,
    )
    tb = tiebreak_draws(spec)
    ids = jnp.arange(pop.members, dtype=jnp.int32)
    lineage = []
    chunk_szs = []
    rung_costs = []
    advance = _rank_advance_protected_fn()
    traces0 = telemetry.counter_get("engine_traces")
    for r, rp in enumerate(plan):
        rt0 = telemetry.counter_get("engine_traces")
        rc0 = telemetry.phase_seconds("compile.jit_first_call")
        b0 = np.full((rp.width,), rp.start_block, np.int32)
        out, carry_out, chunk_sz = dispatch(
            rp, cur + (b0,) + tuple(jax.tree.leaves(carry))
        )
        # out = (summary, tl[, roll][, pol]) — the universal member
        # ordering; pol feeds the trips severity channel
        summ = out[0]
        pol = out[2 + (1 if roll else 0)] if with_pol else None
        keep = plan[r + 1].width if r + 1 < len(plan) else 1
        sev, order, cur_n, carry_n, ids_n, tb_n = advance(
            summ, pol, tb, ids, cur, carry_out,
            rank=spec.rank, slo_s=spec.slo_s, keep=keep,
        )
        lineage.append((ids, sev, order, summ))
        chunk_szs.append(chunk_sz)
        rung_costs.append((
            int(telemetry.counter_get("engine_traces") - rt0),
            telemetry.phase_seconds("compile.jit_first_call") - rc0,
        ))
        cur, carry, ids, tb = cur_n, carry_n, ids_n, tb_n
    traces = int(telemetry.counter_get("engine_traces") - traces0)
    telemetry.gauge_set("search_traces", traces)
    lineage = jax.device_get(lineage)
    rungs = []
    for rp, (ids_r, sev_r, order_r, summ_r), chunk_sz, cost in zip(
        plan, lineage, chunk_szs, rung_costs
    ):
        ids_np = np.asarray(ids_r)
        order_np = np.asarray(order_r)
        keep = (
            plan[rp.rung + 1].width
            if rp.rung + 1 < len(plan) else 1
        )
        rungs.append(RungResult(
            rung=rp.rung,
            width=rp.width,
            chunk=int(chunk_sz),
            start_block=rp.start_block,
            num_blocks=rp.num_blocks,
            cum_requests=rp.cum_requests,
            candidates=ids_np,
            severity=np.asarray(sev_r),
            survivors=ids_np[order_np[:keep]],
            summaries=summ_r,
            order=order_np,
            traces=cost[0],
            compile_s=cost[1],
        ))
    winner = int(rungs[-1].survivors[0])
    win_row = int(np.where(rungs[-1].candidates == winner)[0][0])
    return SearchSummary(
        spec=spec,
        block=block,
        plan=plan,
        rungs=rungs,
        winner=winner,
        winner_severity=float(rungs[-1].severity[win_row]),
        offered_qps=args["offered"],
        traces=traces,
        mode=tables.mode,
    )


def run_search_sharded(sh, load, num_requests: int, key,
                       spec: SearchSpec, *,
                       block_size: int = 65_536,
                       chunk: Optional[int] = None) -> SearchSummary:
    """The bracket over a device mesh: each rung's member axis
    distributes over the flattened device list; ranking and gathers
    stay the solo path's jnp ops, so lineage is bit-identical to
    :func:`run_search` and :func:`run_search_emulated`."""
    sh._require_mesh("run_search")
    telemetry.counter_inc("sharded_search_runs")
    return _run_bracket(
        sh.sim, load, num_requests, key, spec, block_size,
        lambda args, tables, plan: _sharded_dispatch(
            sh, args, tables, spec, chunk, plan
        ),
        path="sharded",
    )


def run_search_emulated(sh, load, num_requests: int, key,
                        spec: SearchSpec, *,
                        block_size: int = 65_536,
                        chunk: Optional[int] = None) -> SearchSummary:
    """The sharded bracket's laptop twin (EmulatedMesh-friendly)."""
    telemetry.counter_inc("sharded_search_emulated_runs")
    return _run_bracket(
        sh.sim, load, num_requests, key, spec, block_size,
        lambda args, tables, plan: _emulated_dispatch(
            sh, args, tables, spec, chunk, plan
        ),
        path="emulated",
    )
