"""The vet orchestrator: compose the three passes into one report.

``isotope-tpu vet`` (commands/vet_cmd.py) and the ``--vet`` pre-flight
gate (runner/run.py) both funnel through here:

1. **topology & config lint** (topo_lint) — pure host;
2. **jaxpr audit** (jaxpr_audit) — trace-only, no device execution;
3. **pre-flight cost model** (costmodel) — memory verdict + ladder
   rung recommendation;
4. **gradient audit** (grad_audit, opt-in via ``--grad``) — the
   design-knob taint classification feeding the ``optimize``
   relaxation worklist.

Every finding increments the telemetry registry
(``isotope_engine_vet_errors_total`` / ``_warnings_total`` render as
first-class Prometheus series; per-rule counts land in the events
grab-bag), so a scrape of a vetted run shows what vet decided.
"""
from __future__ import annotations

import os
from typing import Optional

from isotope_tpu import telemetry
from isotope_tpu.analysis import costmodel, jaxpr_audit, topo_lint
from isotope_tpu.analysis.findings import (
    SEV_ERROR,
    SEV_WARN,
    Finding,
    Report,
    suppression_patterns,
)

ENV_VET = "ISOTOPE_VET"
ENV_VET_SUPPRESS = "ISOTOPE_VET_SUPPRESS"

#: rules the runner's gate never blocks on while the degradation
#: ladder is armed — the rung pre-selection IS their recovery
MEMORY_RULES = ("VET-M001", "VET-M002")


class VetError(ValueError):
    """A blocking vet verdict (deterministic: the case is recorded as
    failed, never retried)."""

    def __init__(self, report: Report, strict: bool,
                 nonblocking=()):
        self.report = report
        blocking = report.blocking(strict, nonblocking)
        lines = "; ".join(
            f"{f.rule} {f.path}".strip() for f in blocking[:4]
        )
        more = len(blocking) - 4
        super().__init__(
            f"vet found {len(blocking)} blocking finding(s): {lines}"
            + (f" (+{more} more)" if more > 0 else "")
        )


def vet_mode(cli_value: Optional[str] = None) -> Optional[str]:
    """Resolve the gate mode: CLI ``--vet[=strict]`` wins, then
    ``$ISOTOPE_VET`` (``1``/``on`` or ``strict``); None = gate off."""
    if cli_value:
        return cli_value
    env = os.environ.get(ENV_VET, "").strip().lower()
    if env in ("1", "on", "true", "yes"):
        return "on"
    if env == "strict":
        return "strict"
    return None


def default_suppressions() -> list:
    return suppression_patterns(os.environ.get(ENV_VET_SUPPRESS))


def _count(report: Report) -> None:
    """Fold a report into the telemetry registry."""
    telemetry.counter_inc("vet_runs_total")
    for f in report.findings:
        telemetry.counter_inc("vet_findings")
        telemetry.counter_inc(f"vet_rule.{f.rule}")
        if f.severity == SEV_ERROR:
            telemetry.counter_inc("vet_errors_total")
        elif f.severity == SEV_WARN:
            telemetry.counter_inc("vet_warnings_total")
    for _ in report.suppressed:
        telemetry.counter_inc("vet_suppressed")


def vet_simulator(
    sim,
    load,
    block_requests: Optional[int] = None,
    *,
    graph=None,
    entry: Optional[str] = None,
    trace: bool = True,
    device_bytes: Optional[float] = None,
    suppress=(),
    rung_names=("scan", "half-block", "cpu-eager"),
    ensemble=None,
    protected: bool = False,
    split_spec=None,
    search_spec=None,
    grad: bool = False,
) -> Report:
    """Full vet of one built Simulator under one load.

    Used by the CLI (after it builds the sim) and by the runner's
    ``--vet`` gate (on the sim it was about to run anyway).  Lint runs
    when ``graph`` is given; the audit and cost model always run
    (``trace=False`` degrades the cost model to the plan-only
    estimate).  The recommended ladder start rung lands in
    ``report.meta['start_rung']``.

    ``ensemble`` (an EnsembleSpec, or a member count) additionally
    lints the fleet spec (VET-T023) and runs the member-capacity
    verdict (VET-M004: members x peak-bytes vs device budget,
    reporting the auto-chunk the engine would pre-select).
    ``protected=True`` runs the protected-fleet variant instead
    (VET-T025: the stacked policy/rollout/timeline carry counts
    toward each member's footprint).  ``split_spec`` (a SplitSpec or
    its raw string) lints the importance-splitting config
    (VET-T024).  ``search_spec`` (a SearchSpec or its raw ``[search]``
    dict) lints the successive-halving bracket (VET-T026) and runs
    the widest-rung capacity verdict (VET-M005, carry-aware).
    ``grad=True`` runs the gradient audit (VET-G rules,
    analysis/grad_audit.py) as a fourth pass — off by default: it
    traces the full knob-armed engine body a second time.  Its
    ``isotope-gradaudit/v1`` document lands in
    ``report.meta['grad']``.
    """
    report = Report(suppress=suppress)
    with telemetry.phase("vet.total"):
        if graph is not None:
            report.extend(topo_lint.lint_graph(
                graph, entry=entry, params=sim.params,
            ))
        report.extend(topo_lint.lint_compiled(
            sim.compiled, params=sim.params,
        ))
        audit_findings, closed, traced_n = jaxpr_audit.audit_simulator(
            sim, load, trace=trace,
        )
        report.extend(audit_findings)
        block = (
            int(block_requests) if block_requests
            else sim.default_block_size()
        )
        est = costmodel.estimate_run(
            sim, block, closed_jaxpr=closed,
            trace_requests=traced_n,
            capacity_override=device_bytes,
        )
        mem_findings, start_rung = costmodel.memory_findings(
            est, rung_names=rung_names,
        )
        report.extend(mem_findings)
        report.extend(costmodel.timeline_findings(est))
        if ensemble is not None:
            if isinstance(ensemble, int):
                from isotope_tpu.sim.ensemble import EnsembleSpec

                ensemble = EnsembleSpec.of(ensemble)
            report.extend(topo_lint.lint_ensemble(ensemble))
            carry = 0.0
            if protected:
                # size the carry from the windows this LOAD would
                # actually plan (duration / window width, clamped the
                # way the run-time planner clamps) — the worst-case
                # timeline_max_windows would overstate the carry and
                # misreport the chunk the engine really picks
                from isotope_tpu.metrics.timeline import plan_windows

                w, _, _ = plan_windows(
                    getattr(load, "duration_s", 0.0) or 1.0,
                    sim.params.timeline_window_s,
                    sim.params.timeline_max_windows,
                    sim.compiled.num_services,
                    log=lambda m: None,
                )
                carry = costmodel.protected_carry_bytes(
                    sim, w,
                    roll=getattr(sim, "_rollouts", None) is not None,
                )
                report.extend(costmodel.protected_ensemble_findings(
                    est, ensemble.members, carry,
                ))
            else:
                report.extend(costmodel.ensemble_findings(
                    est, ensemble.members,
                ))
            # VET-M006: an OBSERVED fleet (attribution / timeline
            # armed on the sim params) stacks per-member blame
            # histograms and window series on top of the event
            # tensors; the protected carry above already counts the
            # recorder, so only the attribution part adds there
            obs_carry = 0.0
            if sim.params.attribution or (
                sim.params.timeline and not protected
            ):
                obs_windows = None
                if sim.params.timeline and not protected:
                    from isotope_tpu.metrics.timeline import (
                        plan_windows,
                    )

                    obs_windows, _, _ = plan_windows(
                        getattr(load, "duration_s", 0.0) or 1.0,
                        sim.params.timeline_window_s,
                        sim.params.timeline_max_windows,
                        sim.compiled.num_services,
                        log=lambda m: None,
                    )
                obs_carry = costmodel.observability_carry_bytes(
                    sim, attr=bool(sim.params.attribution),
                    timeline_windows=obs_windows,
                )
                report.extend(costmodel.observed_ensemble_findings(
                    est, ensemble.members, obs_carry,
                    base_carry_bytes=carry,
                ))
            report.meta["ensemble"] = {
                "members": ensemble.members,
                "protected": bool(protected),
                "chunk": costmodel.ensemble_chunk(
                    ensemble.members, est.peak_bytes_at_block,
                    est.capacity_bytes,
                    carry_bytes_per_member=carry + obs_carry,
                ),
            }
        if grad:
            from isotope_tpu.analysis import grad_audit

            with telemetry.phase("vet.grad"):
                gfinds, gdoc = grad_audit.audit_grad(sim, load)
            report.extend(gfinds)
            report.meta["grad"] = gdoc
        if split_spec is not None:
            report.extend(topo_lint.lint_split(split_spec))
        if search_spec is not None:
            report.extend(topo_lint.lint_search(search_spec))
            from isotope_tpu.sim.search import SearchSpec

            if isinstance(search_spec, SearchSpec):
                widths = search_spec.rung_widths()
                conns = getattr(load, "connections", 0) or 0
                report.extend(costmodel.search_findings(
                    est, widths[0], connections=conns,
                ))
                report.meta["search"] = {
                    "candidates": search_spec.members,
                    "rungs": search_spec.rungs,
                    "eta": search_spec.eta,
                    "widths": list(widths),
                    "chunk": costmodel.ensemble_chunk(
                        widths[0], est.peak_bytes_at_block,
                        est.capacity_bytes,
                        carry_bytes_per_member=(
                            costmodel.search_carry_bytes(conns)
                        ),
                    ),
                }
        report.meta["cost"] = {
            "block_requests": est.block_requests,
            "flops_at_block": est.flops_at_block,
            "peak_bytes_at_block": est.peak_bytes_at_block,
            "critical_path": est.critical_path,
            "capacity_bytes": est.capacity_bytes,
            "num_segments": len(est.segments),
            "timeline_bytes": est.timeline_bytes,
        }
        # the engine's chosen bucket schedule, ranked by per-segment
        # critical-path cost (``vet --json`` surfaces it verbatim)
        report.meta["bucket_schedule"] = costmodel.schedule_rows(sim)
        # the comm-augmented layout verdict (parallel/layout.py): what
        # ``--mesh auto`` would pick for this topology on this host,
        # with the per-collective ICI/DCN cost rows — the cost model
        # feeding BACK into the mesh choice instead of dead-ending in
        # a report (ISSUE 8)
        try:
            import jax

            from isotope_tpu.parallel import layout as mesh_layout

            chosen = mesh_layout.choose_layout(
                jax.device_count(), sim.compiled.num_services,
                max_slices=getattr(jax, "process_count", lambda: 1)(),
            )
            report.meta["mesh_layout"] = chosen.to_dict()
        except Exception:  # pragma: no cover - advisory only
            pass
        # a suppressed memory finding must also suppress the verdict
        report.meta["start_rung"] = (
            start_rung if mem_findings and any(
                f.rule in MEMORY_RULES for f in report.findings
            ) else 0
        )
        report.meta["rung_names"] = list(rung_names)
    _count(report)
    return report


def vet_topology_path(
    path,
    *,
    load=None,
    entry: Optional[str] = None,
    trace: bool = True,
    device_bytes: Optional[float] = None,
    suppress=(),
    params=None,
    graph=None,
    grad: bool = False,
) -> Report:
    """Vet one topology YAML end to end (decode -> lint -> build ->
    audit -> cost model).  Decode/compile failures become findings
    instead of tracebacks — vet is the tool that must not crash on the
    config it exists to judge.  ``graph`` supplies an already-decoded
    ServiceGraph (vet_config_path passes the copy its config lint
    loaded, so a 10k-service document is decoded once, not twice)."""
    import yaml

    from isotope_tpu.models.graph import ServiceGraph

    report = Report(suppress=suppress)
    if graph is None:
        try:
            graph = ServiceGraph.from_yaml_file(path)
        except (OSError, ValueError, yaml.YAMLError) as e:
            # yaml syntax errors are YAMLError, not ValueError — both
            # must become findings, never tracebacks
            report.add(Finding(
                "VET-C001", SEV_ERROR, str(e), path=str(path),
            ))
            _count(report)
            return report

    report.extend(topo_lint.lint_graph(graph, entry=entry, params=params))
    if report.errors:
        # graph-level errors (cycles, no entrypoint, unreachable
        # services) make the compiled program meaningless; report them
        # without attempting the build
        _count(report)
        return report

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.sim.config import LoadModel, SimParams
    from isotope_tpu.sim.engine import Simulator

    if load is None:
        load = LoadModel(kind="open", qps=1000.0)
    sim = Simulator(
        compile_graph(graph, entry=entry),
        params if params is not None else SimParams(),
    )
    sub = vet_simulator(
        sim, load, graph=None, entry=entry, trace=trace,
        device_bytes=device_bytes, suppress=suppress, grad=grad,
    )
    # merge: sub already counted itself; move its findings over
    report.findings.extend(sub.findings)
    report.suppressed.extend(sub.suppressed)
    report.meta.update(sub.meta)
    return report


def vet_config_path(
    config_path,
    *,
    trace: bool = True,
    device_bytes: Optional[float] = None,
    suppress=(),
    grad: bool = False,
) -> Report:
    """Vet a sweep TOML: config lint plus every referenced topology."""
    from isotope_tpu.runner.config import load_toml

    report = Report(suppress=suppress)
    try:
        config = load_toml(config_path)
    except (OSError, ValueError) as e:
        report.add(Finding(
            "VET-C001", SEV_ERROR, str(e), path=str(config_path),
        ))
        _count(report)
        return report
    cfg_findings, graphs = topo_lint.lint_config(config)
    report.extend(cfg_findings)
    _count(report)
    for p, g in graphs.items():
        sub = vet_topology_path(
            p, entry=config.entry, trace=trace,
            device_bytes=device_bytes, suppress=suppress,
            params=config.sim_params(), graph=g, grad=grad,
        )
        report.findings.extend(sub.findings)
        report.suppressed.extend(sub.suppressed)
        if sub.meta:
            report.meta[str(p)] = sub.meta
    return report
