"""Static program & config analysis (``isotope-tpu vet``).

The GSPMD move applied to pre-flight: analyze the program and its
configuration *before* execution.  Three passes over purely static
inputs —

- :mod:`~isotope_tpu.analysis.topo_lint` — the topology & experiment
  config linter (structured rule-id diagnostics over the service graph
  and sweep grid);
- :mod:`~isotope_tpu.analysis.jaxpr_audit` — the jaxpr auditor
  (``jax.make_jaxpr`` traces of the planned tensor program, walked for
  host-sync points, dtype leaks, nondeterministic accumulation, and
  retrace hazards — no device execution);
- :mod:`~isotope_tpu.analysis.costmodel` — the pre-flight cost model
  (FLOPs, peak bytes, critical path; the memory-vs-capacity verdict
  that pre-selects the resilience ladder's starting rung);
- :mod:`~isotope_tpu.analysis.grad_audit` — the gradient audit
  (opt-in ``vet --grad``: forward taint from every registered design
  knob through the engine jaxpr, classifying each as differentiable /
  gradient-dead / trace-constant — the ``isotope-gradaudit/v1``
  relaxation worklist of the planned ``optimize`` command).

Surfaced as the ``isotope-tpu vet`` subcommand and the opt-in
``--vet`` / ``$ISOTOPE_VET`` gate on simulate/sweep/suite.  With the
gate off, nothing here ever runs — the default path is byte-identical.
"""
from isotope_tpu.analysis.findings import (  # noqa: F401
    RULES,
    SEV_ERROR,
    SEV_INFO,
    SEV_WARN,
    Finding,
    Report,
    suppression_patterns,
)
from isotope_tpu.analysis.grad_audit import (  # noqa: F401
    CLASS_CONSTANT,
    CLASS_DEAD,
    CLASS_DIFFERENTIABLE,
    GRAD_INVARS,
    audit_grad,
)
from isotope_tpu.analysis.vet import (  # noqa: F401
    ENV_VET,
    ENV_VET_SUPPRESS,
    MEMORY_RULES,
    VetError,
    default_suppressions,
    vet_config_path,
    vet_mode,
    vet_simulator,
    vet_topology_path,
)

__all__ = [
    "RULES",
    "SEV_ERROR",
    "SEV_INFO",
    "SEV_WARN",
    "Finding",
    "Report",
    "suppression_patterns",
    "CLASS_CONSTANT",
    "CLASS_DEAD",
    "CLASS_DIFFERENTIABLE",
    "GRAD_INVARS",
    "audit_grad",
    "ENV_VET",
    "ENV_VET_SUPPRESS",
    "MEMORY_RULES",
    "VetError",
    "default_suppressions",
    "vet_config_path",
    "vet_mode",
    "vet_simulator",
    "vet_topology_path",
]
