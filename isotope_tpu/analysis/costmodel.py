"""Pre-flight cost model: FLOPs, peak bytes, critical path — statically.

Two estimators compose (the critical-path discipline of static schedule
analysis — PAPERS.md "It's the Critical Path!" — applied to the engine's
own program):

- **Jaxpr walker** (:func:`jaxpr_cost`): primitive-level FLOP counts,
  a liveness-sweep working-set high-water mark, and the longest
  dependency chain through the eqn DAG (``lax.scan`` bodies multiply
  by their trip count).  Runs on the trace the auditor already took —
  no device, no XLA.
- **Plan table** (:func:`segment_table`): per-segment padded element
  counts straight from the bucket plan (compiler/buckets.py), scaled
  by the request-block size — the per-segment split the jaxpr (which
  sees one fused program) cannot provide.

The headline product is the **memory verdict**: the estimated peak
device bytes of a run at its planned block size, compared against the
device capacity, selects the resilience ladder rung the run should
*start* on (runner/run.py) — turning PR 3's OOM-crash-then-degrade
into a pre-flight decision.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

from isotope_tpu.analysis.findings import (
    SEV_ERROR,
    SEV_WARN,
    Finding,
)

ENV_DEVICE_BYTES = "ISOTOPE_VET_DEVICE_BYTES"

#: share of device capacity the timeline recorder's O(S x W) carries
#: may take before VET-M003 reports them (informational — the window
#: planner clamps instead of OOMing)
ENV_TIMELINE_SHARE = "ISOTOPE_VET_TIMELINE_SHARE"
DEFAULT_TIMELINE_SHARE = 0.10

#: fraction of reported device capacity the estimate may fill — XLA
#: needs headroom for fusion temporaries and the allocator never packs
#: perfectly
CAPACITY_FILL = 0.85

# -- collective cost constants (parallel/layout.py feeds on these) -------
#
# Per-link bandwidth/latency used by :func:`comm_table` to price the
# summary-merge collectives.  ICI numbers are v5e-class per-link
# figures; DCN is a 100 Gbps-class host NIC with millisecond-scale
# all-reduce setup.  CPU-era GUESSES, like SEGMENT_OVERHEAD_ELEMS —
# calibrating them against a real multi-slice capture is a ROADMAP
# follow-up.  What matters for the layout SEARCH is the ordering
# (DCN ~20x slower, ~100x higher latency), which is robust.
ICI_BANDWIDTH_BYTES_S = 1.6e11
DCN_BANDWIDTH_BYTES_S = 8.0e9
ICI_LATENCY_S = 1e-6
DCN_LATENCY_S = 1e-4

#: elementwise-ish primitives costed at one flop per output element;
#: anything unknown falls back to the same rate (a floor, not truth)
_FREE_PRIMITIVES = frozenset({
    "broadcast_in_dim", "reshape", "squeeze", "transpose", "copy",
    "convert_element_type", "bitcast_convert_type", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "gather", "iota", "stop_gradient", "device_put",
})


@dataclasses.dataclass(frozen=True)
class JaxprCost:
    flops: float
    peak_bytes: float          # liveness high-water of the traced block
    critical_path: int         # longest primitive dependency chain

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _aval_bytes(aval) -> float:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0.0
    n = 1
    for d in shape:
        n *= int(d)
    return float(n) * getattr(dtype, "itemsize", 4)


def _aval_size(aval) -> float:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 1.0
    n = 1
    for d in shape:
        n *= int(d)
    return float(n)


def _dot_flops(eqn) -> float:
    """2 * output elements * contracted extent for dot_general."""
    out = sum(_aval_size(v.aval) for v in eqn.outvars)
    dims = eqn.params.get("dimension_numbers")
    contract = 1.0
    if dims:
        (lhs_c, _rhs_c), _batch = dims
        lhs_shape = getattr(eqn.invars[0].aval, "shape", ())
        for d in lhs_c:
            contract *= int(lhs_shape[d])
    return 2.0 * out * contract


def jaxpr_cost(closed_jaxpr) -> JaxprCost:
    """Static cost of one ClosedJaxpr (recursing into sub-jaxprs)."""
    import jax

    def cost(jxp) -> Tuple[float, float, int]:
        # -- liveness sweep: last use index per var -----------------------
        last_use: Dict[object, int] = {}
        for i, eqn in enumerate(jxp.eqns):
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    last_use[v] = i
        for v in jxp.outvars:
            if not isinstance(v, jax.core.Literal):
                last_use[v] = len(jxp.eqns)

        live = sum(
            _aval_bytes(v.aval)
            for v in (*jxp.invars, *jxp.constvars)
        )
        peak = live
        flops = 0.0
        depth_of: Dict[object, int] = {}
        max_depth = 0

        for i, eqn in enumerate(jxp.eqns):
            prim = str(eqn.primitive)
            sub_f = sub_b = 0.0
            sub_d = 0
            trips = 1
            for v in eqn.params.values():
                subs = v if isinstance(v, (list, tuple)) else (v,)
                for s in subs:
                    inner = None
                    if isinstance(s, jax.core.ClosedJaxpr):
                        inner = s.jaxpr
                    elif isinstance(s, jax.core.Jaxpr):
                        inner = s
                    if inner is not None:
                        f, b, d = cost(inner)
                        sub_f += f
                        sub_b = max(sub_b, b)
                        sub_d = max(sub_d, d)
            if prim == "scan":
                trips = int(eqn.params.get("length", 1))
            out_elems = sum(_aval_size(v.aval) for v in eqn.outvars)
            if sub_f:
                flops += sub_f * trips
            elif prim == "dot_general":
                flops += _dot_flops(eqn)
            elif prim in _FREE_PRIMITIVES:
                pass  # data movement, not arithmetic
            elif prim.startswith(("scatter", "reduce", "cum", "sort",
                                  "argsort")):
                flops += out_elems + sum(
                    _aval_size(v.aval) for v in eqn.invars
                )
            else:
                flops += out_elems

            # working set: everything live plus this eqn's operands,
            # outputs, and (for nested bodies) the body's own peak
            out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
            peak = max(peak, live + out_bytes + sub_b)
            live += out_bytes
            for v in eqn.invars:
                if (
                    not isinstance(v, jax.core.Literal)
                    and last_use.get(v) == i
                ):
                    live -= _aval_bytes(v.aval)

            d_in = max(
                (
                    depth_of.get(v, 0)
                    for v in eqn.invars
                    if not isinstance(v, jax.core.Literal)
                ),
                default=0,
            )
            step = max(1, sub_d) * trips
            d_out = d_in + step
            for v in eqn.outvars:
                depth_of[v] = d_out
            max_depth = max(max_depth, d_out)
        return flops, peak, max_depth

    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    f, b, d = cost(jaxpr)
    return JaxprCost(flops=f, peak_bytes=b, critical_path=d)


def segment_table(sim, block_requests: int) -> List[dict]:
    """Per-segment static costs at ``block_requests`` requests.

    One row per executor segment (scan bucket or unrolled island),
    with padded element counts from the bucket plan — multiplied by
    the request axis these are the event-tensor footprints each
    segment's sweep touches."""
    from isotope_tpu.compiler import buckets
    from isotope_tpu.sim import levelscan

    rows: List[dict] = []
    n = int(block_requests)
    for i, seg in enumerate(sim._segments):
        if isinstance(seg, levelscan.ScanBucket):
            elems = n * seg.num_levels * (
                seg.plan.bound_hops * (seg.plan.bound_steps + 3)
            )
            rows.append({
                "segment": i,
                "kind": "scan",
                "levels": seg.num_levels,
                "elems": elems,
                "bytes_f32": 4.0 * elems,
            })
        elif isinstance(seg, buckets.UnrolledLevelPlan):
            lvl = sim._levels[seg.d]
            if lvl.tiled is not None:
                # dense-blocked tiles + sparse residual: the step
                # footprint is the tiles' padded grids plus residual
                # slots — the whole point of the encoding
                kind = "tiled"
                step_elems = lvl.tiled.elems
            elif lvl.sparse is not None:
                kind = "sparse"
                step_elems = lvl.sparse.n_slots
            elif lvl.leaf_busy is not None:
                kind = "leaf"
                step_elems = lvl.size
            else:
                kind = "unrolled"
                step_elems = lvl.size * lvl.pmax
            elems = n * (
                step_elems + 3 * lvl.size
                + 2 * lvl.num_calls * lvl.max_attempts
            )
            rows.append({
                "segment": i,
                "kind": kind,
                "levels": 1,
                "elems": elems,
                "bytes_f32": 4.0 * elems,
            })
    return rows


def schedule_rows(sim) -> List[dict]:
    """The engine's chosen bucket schedule, ranked by each segment's
    critical-path cost (compiler/buckets.schedule_table over the plan
    the Simulator actually lowered) — the ``bucket_schedule`` block of
    ``vet --json``."""
    from isotope_tpu.compiler import buckets

    return buckets.schedule_table(sim._plan_shapes, sim._plan)


def device_capacity_bytes(override: Optional[float] = None
                          ) -> Optional[float]:
    """Per-device memory capacity in bytes, or None when unknown.

    Resolution order: explicit override (``--device-bytes``), the
    ``ISOTOPE_VET_DEVICE_BYTES`` env knob, then the backend's own
    ``memory_stats()['bytes_limit']`` (TPU/GPU; CPU reports nothing —
    host RAM is the allocator's problem, not the vet gate's)."""
    if override is not None:
        return float(override)
    env = os.environ.get(ENV_DEVICE_BYTES, "").strip()
    if env:
        return float(env)
    try:
        import jax

        for d in jax.local_devices():
            ms = d.memory_stats()
            if ms and ms.get("bytes_limit"):
                return float(ms["bytes_limit"])
    except Exception:
        pass
    return None


def timeline_bytes(sim, num_windows: Optional[int] = None) -> float:
    """Worst-case bytes of the flight recorder's windowed carries
    (metrics/timeline.py): the per-service (S, W) series (5 fields),
    the client (W,) series, and the (W, 64) latency histogram.  The
    recorder accumulates these in the scan CARRY (one persistent copy,
    independent of the block count — timeline.zeros_summary), so this
    IS the run-long device footprint, not a per-block term that
    multiplies.  Zero when ``SimParams.timeline`` is off.

    ``num_windows`` defaults to the planner's worst case —
    ``timeline_max_windows`` clamped by the recorder's element budget
    — exactly the bound the run-time planner enforces."""
    params = sim.params
    if not getattr(params, "timeline", False):
        return 0.0
    from isotope_tpu.metrics.timeline import (
        ELEM_BUDGET,
        NUM_BLAME_BUCKETS,
    )

    s = max(sim.compiled.num_services, 1)
    w = (
        int(num_windows)
        if num_windows
        else max(
            1,
            min(int(params.timeline_max_windows), ELEM_BUDGET // s),
        )
    )
    elems = 5 * s * w + 4 * w + w * NUM_BLAME_BUCKETS
    return 4.0 * elems


def summary_bytes(num_services: int,
                  num_edges: Optional[int] = None) -> dict:
    """Byte sizes of one RunSummary's collective-merged leaf groups.

    Split by how the sharded merge moves them (parallel/sharded.py):

    - ``replicated``: scalars, the two fine latency histograms, and
      the non-svc-sharded metric series — ``psum`` over every axis,
      every shard ends with a full copy;
    - ``scattered``: the per-service duration / response-size
      histograms — ``psum`` over the request axes then ``psum_scatter``
      over ``svc``, each shard keeps a 1/svc tile.

    Shapes mirror metrics/prometheus.py (duration hist (S, 2, 33),
    size hists (., len(SIZE_BUCKETS)+1)) and metrics/histogram.py
    (NUM_BUCKETS fine buckets); ``num_edges`` defaults to
    ``num_services`` (tree-ish graphs have ~1 inbound edge/service).
    """
    from isotope_tpu.metrics.histogram import NUM_BUCKETS
    from isotope_tpu.metrics.prometheus import (
        DURATION_BUCKETS,
        SIZE_BUCKETS,
    )

    s = max(int(num_services), 1)
    e = int(num_edges) if num_edges else s
    nsb = len(SIZE_BUCKETS) + 1
    nb = len(DURATION_BUCKETS) + 1  # prometheus duration axis (_NB)
    replicated = 4.0 * (
        14                      # RunSummary scalars
        + 2 * NUM_BUCKETS       # latency_hist + win_latency_hist
        + s                     # incoming_total
        + e * (2 + nsb)         # outgoing_total/size_sum/size_hist
        + s * 2 * 2             # duration_sum + response_size_sum
        + 2 * s                 # utilization + unstable
    )
    scattered = 4.0 * (s * 2 * nb + s * 2 * nsb)
    return {"replicated": replicated, "scattered": scattered}


def _collective_s(bytes_: float, participants: int, link: str,
                  scatter: bool = False) -> float:
    """Ring-collective time: latency per step + wire bytes.

    All-reduce moves ``2 (p-1)/p`` of the payload per link;
    reduce-scatter half that.  ``p == 1`` is free.
    """
    p = max(int(participants), 1)
    if p == 1:
        return 0.0
    lat, bw = (
        (DCN_LATENCY_S, DCN_BANDWIDTH_BYTES_S)
        if link == "dcn"
        else (ICI_LATENCY_S, ICI_BANDWIDTH_BYTES_S)
    )
    factor = (p - 1) / p if scatter else 2.0 * (p - 1) / p
    return lat * (p - 1) + factor * bytes_ / bw


def comm_table(
    num_services: int,
    data: int,
    svc: int,
    slices: int = 1,
    num_edges: Optional[int] = None,
    num_merges: int = 1,
) -> List[dict]:
    """Per-collective cost rows for one mesh layout's summary merge.

    One row per collective the sharded merge issues (parallel/
    sharded.py ``_merge_summary_collective``): the replicated ``psum``
    over the ICI axes, the per-service ``psum_scatter`` over ``svc``,
    and — when the layout has a DCN axis — the cross-slice ``psum`` of
    both groups (issued LAST, on the already-scattered tiles, so DCN
    carries 1/svc of the per-service state).  ``num_merges`` scales the
    whole table (1 = the post-scan merge; collective/compute overlap
    issues one merge per block).

    Bytes are per-shard payloads; ``time_s`` prices each row with the
    ICI/DCN constants above.
    """
    sizes = summary_bytes(num_services, num_edges)
    s = max(int(num_services), 1)
    s_pad = -(-s // max(svc, 1)) * max(svc, 1)
    scat = sizes["scattered"] * (s_pad / s)     # svc-padding rides the wire
    tile = scat / max(svc, 1)
    rows = [
        {
            "collective": "psum_replicated",
            "link": "ici",
            "participants": data * svc,
            "bytes": sizes["replicated"],
            "time_s": _collective_s(
                sizes["replicated"], data * svc, "ici"
            ),
        },
        {
            "collective": "psum_scatter_svc",
            "link": "ici",
            "participants": svc,
            "bytes": scat,
            "time_s": (
                _collective_s(scat, svc, "ici", scatter=True)
                # the request-axis psum feeding the scatter
                + _collective_s(scat, data, "ici")
            ),
        },
    ]
    if slices > 1:
        dcn_bytes = sizes["replicated"] + tile
        rows.append({
            "collective": "psum_dcn",
            "link": "dcn",
            "participants": slices,
            "bytes": dcn_bytes,
            "time_s": _collective_s(dcn_bytes, slices, "dcn"),
        })
    for r in rows:
        r["time_s"] *= max(int(num_merges), 1)
    return rows


@dataclasses.dataclass(frozen=True)
class CostEstimate:
    """The pre-flight verdict for one planned run."""

    block_requests: int
    trace_requests: int
    jaxpr: Optional[JaxprCost]      # costs of the traced (small-n) block
    peak_bytes_at_block: float      # extrapolated to the real block
    flops_at_block: float
    critical_path: int
    segments: List[dict]
    capacity_bytes: Optional[float]
    # flight-recorder carry bytes (0 when SimParams.timeline is off);
    # already included in peak_bytes_at_block
    timeline_bytes: float = 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if self.jaxpr is not None:
            d["jaxpr"] = self.jaxpr.to_dict()
        return d


def estimate_run(
    sim,
    block_requests: int,
    closed_jaxpr=None,
    trace_requests: int = 8,
    capacity_override: Optional[float] = None,
) -> CostEstimate:
    """Estimate one run's per-block cost at ``block_requests``.

    When the auditor already traced the program, its ``closed_jaxpr``
    (at ``trace_requests`` requests) seeds the estimate and the
    request-proportional parts scale up linearly; without a trace the
    plan table alone provides the (coarser) bytes estimate."""
    segments = segment_table(sim, block_requests)
    plan_bytes = sum(r["bytes_f32"] for r in segments)
    jc = None
    if closed_jaxpr is not None:
        jc = jaxpr_cost(closed_jaxpr)
        scale = block_requests / max(trace_requests, 1)
        peak = jc.peak_bytes * scale
        flops = jc.flops * scale
        depth = jc.critical_path
    else:
        # plan-only fallback: the live working set is a few event
        # tensors wide, not the sum over all segments
        h = max(sim.compiled.num_hops, 1)
        peak = 10.0 * 4.0 * block_requests * h
        flops = plan_bytes / 4.0  # ~1 flop per touched element
        depth = len(segments)
    # the flight recorder's O(S x W) carries ride the scan next to the
    # event tensors (the traced plain program doesn't contain them)
    tl_bytes = timeline_bytes(sim)
    return CostEstimate(
        block_requests=int(block_requests),
        trace_requests=int(trace_requests),
        jaxpr=jc,
        peak_bytes_at_block=float(peak) + tl_bytes,
        flops_at_block=float(flops),
        critical_path=int(depth),
        segments=segments,
        capacity_bytes=device_capacity_bytes(capacity_override),
        timeline_bytes=tl_bytes,
    )


def timeline_findings(estimate: CostEstimate) -> List[Finding]:
    """The VET-M003 info verdict: the recorder's windowed carries take
    more than the configured share of device capacity.

    Informational by design — the run-time window planner clamps the
    window count (widening windows, with a warning) instead of OOMing,
    so the finding documents the pressure rather than blocking."""
    from isotope_tpu.analysis.findings import SEV_INFO

    tl = estimate.timeline_bytes
    cap = estimate.capacity_bytes
    if tl <= 0 or cap is None or cap <= 0:
        return []
    share_env = os.environ.get(ENV_TIMELINE_SHARE, "").strip()
    share = float(share_env) if share_env else DEFAULT_TIMELINE_SHARE
    if tl <= share * cap:
        return []
    return [Finding(
        "VET-M003", SEV_INFO,
        f"timeline recorder carries {tl:.3g} B exceed "
        f"{share:.0%} of the {cap:.3g} B device capacity; the window "
        "planner will clamp the window count (widening windows) — "
        "lower SimParams.timeline_max_windows or widen "
        "timeline_window_s to silence",
    )]


def protected_carry_bytes(sim, num_windows: int,
                          roll: bool = False) -> float:
    """Per-member bytes of a PROTECTED fleet's stacked scan carry
    (engine ``_member_fn`` with ``prot`` armed): the flight-recorder windowed
    accumulator plus the policy / rollout control state, observation
    channels, and actuation series — the terms a plain fleet does not
    carry and VET-T025 accounts for.  All f32."""
    s = max(sim.compiled.num_services, 1)
    w = max(int(num_windows), 1)
    total = timeline_bytes(sim, num_windows=w)
    if getattr(sim, "_policies", None) is not None:
        # PolicyState (~6 S-vectors + clocks) + (S, W) observation
        # channel + PolicySummary series (6 (S, W) + (W,) + 3 (S,))
        total += 4.0 * (7 * s + s * w + 6 * s * w + w + 3 * s)
    if roll and getattr(sim, "_rollouts", None) is not None:
        # RolloutState (~6 S-vectors) + (S, 2, W, 4) observation
        # accumulator + RolloutSummary series (6 (S, W) + (W,) +
        # 3 (S, 2, W))
        total += 4.0 * (6 * s + s * 2 * w * 4 + 6 * s * w + w
                        + 3 * s * 2 * w)
    return total


def observability_carry_bytes(sim, attr: bool = False,
                              timeline_windows: Optional[int] = None
                              ) -> float:
    """Per-member bytes of an OBSERVED fleet's stacked observability
    carry (engine ``_member_fn`` with attribution / timeline armed,
    protected or not): the
    blame reduction's exemplar state plus its reduced
    ``AttributionSummary`` leaves (5 scalars, 11 per-hop vectors, two
    ``(S, 64)`` blame histograms), and the flight recorder's windowed
    accumulator — the VET-M006 accounting.  All f32."""
    from isotope_tpu.metrics.attribution import NUM_BLAME_BUCKETS

    total = 0.0
    if attr:
        s = max(sim.compiled.num_services, 1)
        h = max(sim.compiled.num_hops, 1)
        k = max(int(getattr(sim.params, "attribution_top_k", 0)), 0)
        # reduced summary leaves + the top-K exemplar carry
        # (ExemplarBatch: 3 (K,) + 4 (K, H))
        total += 4.0 * (5 + 11 * h + 2 * s * NUM_BLAME_BUCKETS)
        total += 4.0 * (k * (3 + 4 * h))
    if timeline_windows is not None:
        total += timeline_bytes(sim, num_windows=timeline_windows)
    return total


def ensemble_chunk(
    members: int,
    peak_bytes_per_member: float,
    capacity_bytes: Optional[float],
    fill: float = CAPACITY_FILL,
    carry_bytes_per_member: float = 0.0,
) -> int:
    """Members per device dispatch for a Monte Carlo fleet
    (sim/ensemble.py): the vmapped member axis multiplies every event
    tensor, so ``members * peak_bytes`` must fit the capacity budget.

    Balanced split: when the fleet must chunk, the chunk count is
    minimized first and members spread evenly across chunks (a
    33-member fleet over a 16-member budget runs 11+11+11, not
    16+16+1), so every chunk reuses ONE compiled program shape after
    the last chunk pads.
    Unknown capacity (CPU backend, no env override) runs the whole
    fleet in one dispatch — the vet gate never invents OOMs it cannot
    substantiate.  Pre-computed at plan time the way the VET-M memory
    verdict pre-selects degradation-ladder rungs.  CPU-era heuristic:
    the real-TPU retune rides the ROADMAP calibration-debt item.

    ``carry_bytes_per_member`` adds a protected fleet's stacked
    control carry (:func:`protected_carry_bytes`) to each member's
    footprint — the VET-T025 accounting.
    """
    members = max(int(members), 1)
    if (
        capacity_bytes is None
        or capacity_bytes <= 0
        or peak_bytes_per_member <= 0
    ):
        return members
    budget = fill * float(capacity_bytes)
    per_member = float(peak_bytes_per_member) + max(
        float(carry_bytes_per_member), 0.0
    )
    per_dispatch = int(budget // per_member)
    if per_dispatch >= members:
        return members
    per_dispatch = max(per_dispatch, 1)
    num_chunks = -(-members // per_dispatch)
    return -(-members // num_chunks)


def ensemble_findings(
    estimate: CostEstimate,
    members: int,
) -> List[Finding]:
    """The VET-M004 verdict: an ensemble fleet whose
    ``members x peak-bytes`` exceeds the device budget — WARN (never
    blocking): the engine pre-computes the member chunk and splits the
    fleet instead of OOMing, and the finding reports that auto-chunk.
    """
    cap = estimate.capacity_bytes
    members = int(members)
    if members <= 1 or cap is None or cap <= 0:
        return []
    peak = estimate.peak_bytes_at_block
    budget = CAPACITY_FILL * cap
    if members * peak <= budget:
        return []
    chunk = ensemble_chunk(members, peak, cap)
    return [Finding(
        "VET-M004", SEV_WARN,
        f"ensemble of {members} members needs {members * peak:.3g} B "
        f"(> the {budget:.3g} B budget, {CAPACITY_FILL:.0%} of "
        f"{cap:.3g} B capacity); the fleet will run in member chunks "
        f"of {chunk} — shrink the block or the fleet to run it in "
        "one dispatch",
    )]


def protected_ensemble_findings(
    estimate: CostEstimate,
    members: int,
    carry_bytes: float,
) -> List[Finding]:
    """The VET-T025 verdict: a PROTECTED fleet whose members' event
    tensors PLUS stacked control carries (timeline accumulator,
    policy / rollout state and series — :func:`protected_carry_bytes`)
    exceed the device budget.  WARN, never blocking: the engine
    pre-computes the carry-aware member chunk and splits the fleet
    (``Simulator.protected_ensemble_chunk``)."""
    cap = estimate.capacity_bytes
    members = int(members)
    if members <= 1 or cap is None or cap <= 0:
        return []
    peak = estimate.peak_bytes_at_block
    budget = CAPACITY_FILL * cap
    need = members * (peak + max(carry_bytes, 0.0))
    if need <= budget:
        return []
    chunk = ensemble_chunk(
        members, peak, cap, carry_bytes_per_member=carry_bytes
    )
    return [Finding(
        "VET-T025", SEV_WARN,
        f"protected fleet of {members} members needs {need:.3g} B "
        f"including {carry_bytes:.3g} B/member of stacked control "
        f"carry (> the {budget:.3g} B budget); the fleet will run in "
        f"member chunks of {chunk} — shrink the block, the window "
        "count, or the fleet to run it in one dispatch",
    )]


def observed_ensemble_findings(
    estimate: CostEstimate,
    members: int,
    obs_carry_bytes: float,
    base_carry_bytes: float = 0.0,
) -> List[Finding]:
    """The VET-M006 verdict: an OBSERVED fleet (attribution and/or
    timeline threaded through the member axis) whose members' event
    tensors PLUS stacked observability carries — blame histograms,
    exemplar state, windowed recorder accumulators
    (:func:`observability_carry_bytes`) — exceed the device budget.
    WARN, never blocking: the engine pre-computes the carry-aware
    member chunk (``Simulator.ensemble_chunk_size`` /
    ``protected_ensemble_chunk``) and splits the fleet."""
    cap = estimate.capacity_bytes
    members = int(members)
    obs = max(float(obs_carry_bytes), 0.0)
    if members <= 1 or cap is None or cap <= 0 or obs <= 0:
        return []
    peak = estimate.peak_bytes_at_block
    carry = obs + max(float(base_carry_bytes), 0.0)
    budget = CAPACITY_FILL * cap
    need = members * (peak + carry)
    if need <= budget:
        return []
    chunk = ensemble_chunk(
        members, peak, cap, carry_bytes_per_member=carry
    )
    return [Finding(
        "VET-M006", SEV_WARN,
        f"observed fleet of {members} members needs {need:.3g} B "
        f"including {obs:.3g} B/member of stacked blame/timeline "
        f"carry (> the {budget:.3g} B budget); the fleet will run in "
        f"member chunks of {chunk} — shrink the block, the window "
        "count, or the fleet, or drop attribution/timeline, to run "
        "it in one dispatch",
    )]


def search_carry_bytes(connections: int) -> float:
    """Per-member bytes of a search bracket's carry-I/O arguments
    (sim/search.py): the block offset ``b0`` (i32) plus the
    ``(t0, conn_t0, req_off)`` scan carry (f32; ``conn_t0`` holds one
    slot per closed-loop connection)."""
    return 4.0 * (3 + max(int(connections), 1))


def search_findings(
    estimate: CostEstimate,
    widest_members: int,
    connections: int = 0,
) -> List[Finding]:
    """The VET-M005 verdict: a search bracket whose WIDEST rung's
    ``members x (peak + carry)-bytes`` exceeds the device budget.
    WARN, never blocking: the bracket pre-computes the carry-aware
    member chunk (``search_auto_chunk``) and splits the rung —
    narrower rungs inherit smaller footprints, so the widest rung is
    the only one that needs auditing."""
    cap = estimate.capacity_bytes
    members = int(widest_members)
    if members <= 1 or cap is None or cap <= 0:
        return []
    peak = estimate.peak_bytes_at_block
    carry = search_carry_bytes(connections)
    budget = CAPACITY_FILL * cap
    need = members * (peak + carry)
    if need <= budget:
        return []
    chunk = ensemble_chunk(
        members, peak, cap, carry_bytes_per_member=carry
    )
    return [Finding(
        "VET-M005", SEV_WARN,
        f"search bracket's widest rung of {members} candidates needs "
        f"{need:.3g} B (> the {budget:.3g} B budget, "
        f"{CAPACITY_FILL:.0%} of {cap:.3g} B capacity); the rung will "
        f"run in member chunks of {chunk} — shrink the block or the "
        "population to run each rung in one dispatch",
    )]


def memory_findings(
    estimate: CostEstimate,
    rung_names: Sequence[str] = ("scan", "half-block", "cpu-eager"),
) -> Tuple[List[Finding], int]:
    """The VET-M verdict: findings plus the recommended start rung.

    Rung economics mirror the supervisor's ladder
    (resilience/supervisor.py): the half-block rung halves the live
    event-tensor footprint; the final rung executes off-device (host
    RAM) and always "fits".  Unknown capacity (CPU backend, no env
    override) recommends rung 0 and reports nothing — the vet gate must
    not invent OOMs it cannot substantiate."""
    cap = estimate.capacity_bytes
    if cap is None or cap <= 0:
        return [], 0
    budget = CAPACITY_FILL * cap
    peak = estimate.peak_bytes_at_block
    if peak <= budget:
        return [], 0
    half = peak / 2.0
    last = len(rung_names) - 1
    if half <= budget:
        rung = min(1, last)
        return [Finding(
            "VET-M002", SEV_WARN,
            f"estimated peak {peak:.3g} B exceeds the "
            f"{budget:.3g} B budget ({CAPACITY_FILL:.0%} of "
            f"{cap:.3g} B capacity); start the ladder at "
            f"{rung_names[rung]!r}",
        )], rung
    return [Finding(
        "VET-M001", SEV_ERROR,
        f"estimated peak {peak:.3g} B exceeds the {budget:.3g} B "
        f"budget even at half-block ({half:.3g} B): every on-device "
        f"rung would OOM — only {rung_names[last]!r} (host) is viable; "
        "shard over a mesh or shrink the block",
    )], last
