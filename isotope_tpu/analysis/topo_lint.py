"""Topology & experiment-config linter (pure host-side, no jax).

Structured diagnostics over the L0 topology IR (models/graph.py) and
the sweep config (runner/config.py): every rule reports a stable id, a
severity, and the config path of the offending node, so defects that
today surface as engine crashes minutes into compile — or never surface
at all (a service nobody calls silently idles) — become pre-flight
findings.  The GSPMD discipline applied to configuration: analyze the
graph before anything executes.
"""
from __future__ import annotations

import pathlib
from typing import Dict, List, Optional, Tuple

from isotope_tpu.analysis.findings import (
    SEV_ERROR,
    SEV_INFO,
    SEV_WARN,
    Finding,
)
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.models.script import ConcurrentCommand, RequestCommand

#: payloads past this are flagged (VET-T006): at the default 10 Gbit/s
#: model a 256 MiB body is >200 ms of pure wire time per direction —
#: beyond any plausible call timeout in these workloads
PAYLOAD_BOUND_BYTES = 256 * 1024 * 1024

#: the engine's default HBM element budget and block floor
#: (sim/engine.py default_block_size) — VET-T007 mirrors them
BLOCK_ELEM_BUDGET = 33_554_432
BLOCK_FLOOR = 256


def _call_targets(script) -> List[str]:
    out: List[str] = []
    for cmd in script:
        if isinstance(cmd, RequestCommand):
            out.append(cmd.service_name)
        elif isinstance(cmd, ConcurrentCommand):
            for sub in cmd:
                if isinstance(sub, RequestCommand):
                    out.append(sub.service_name)
    return out


def _adjacency(graph: ServiceGraph) -> Dict[str, List[str]]:
    return {s.name: _call_targets(s.script) for s in graph.services}


def _find_cycle(entry: str, adj: Dict[str, List[str]]
                ) -> Optional[List[str]]:
    """First cycle reachable from ``entry`` (as a name path), or None.

    Iterative DFS with an explicit stack: the svc10k/svc100k-scale
    topologies this pass targets are deeper than Python's recursion
    limit (a 2000-service chain already blows it)."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    path: List[str] = []
    # (node, iterator-over-targets) frames
    stack = [(entry, iter(adj.get(entry, ())))]
    color[entry] = GRAY
    path.append(entry)
    while stack:
        node, targets = stack[-1]
        for t in targets:
            if t not in color:
                continue  # undefined target: decode already failed
            if color[t] == GRAY:
                return path[path.index(t):] + [t]
            if color[t] == WHITE:
                color[t] = GRAY
                path.append(t)
                stack.append((t, iter(adj.get(t, ()))))
                break
        else:
            color[node] = BLACK
            path.pop()
            stack.pop()
    return None


def lint_graph(
    graph: ServiceGraph,
    entry: Optional[str] = None,
    params=None,
) -> List[Finding]:
    """Lint one service graph.  ``params`` (a SimParams) refines the
    shape-dependent rules (block budget, bucket waste); None uses the
    engine defaults without importing jax."""
    findings: List[Finding] = []
    adj = _adjacency(graph)
    names = [s.name for s in graph.services]
    idx = {n: i for i, n in enumerate(names)}

    # -- entrypoint (VET-T003) --------------------------------------------
    if entry is not None and entry not in idx:
        findings.append(Finding(
            "VET-T003", SEV_ERROR,
            f"--entry names unknown service {entry!r}",
        ))
        entry = None
    if entry is None:
        entries = [s.name for s in graph.services if s.is_entrypoint]
        if not entries:
            findings.append(Finding(
                "VET-T003", SEV_ERROR,
                "no service sets isEntrypoint: true",
            ))
            return findings  # reachability/cycle need a root
        entry = entries[0]

    # -- cycles (VET-T002) -------------------------------------------------
    cycle = _find_cycle(entry, adj)
    if cycle is not None:
        findings.append(Finding(
            "VET-T002", SEV_ERROR,
            "cycle: " + " -> ".join(cycle) + " (the reproducible-cycle "
            "solve covers closed-loop rate cycles, not call-graph "
            "recursion; break the call loop)",
            path=f"services[{idx[cycle[0]]}]",
        ))

    # -- reachability (VET-T001) ------------------------------------------
    seen = set()
    stack = [entry]
    while stack:
        n = stack.pop()
        if n in seen:
            continue
        seen.add(n)
        stack.extend(t for t in adj.get(n, ()) if t in idx)
    for i, name in enumerate(names):
        if name not in seen:
            findings.append(Finding(
                "VET-T001", SEV_ERROR,
                f"service {name!r} is never called from entrypoint "
                f"{entry!r} (dead capacity, or a mistyped call target)",
                path=f"services[{i}]",
            ))

    # -- per-service bounds (VET-T004/T005/T006) ---------------------------
    for i, svc in enumerate(graph.services):
        if svc.num_replicas < 1:
            findings.append(Finding(
                "VET-T004", SEV_ERROR,
                f"numReplicas={svc.num_replicas}: the M/M/k station has "
                "no servers (the compiler would silently clamp to 1)",
                path=f"services[{i}].numReplicas",
            ))
        if float(svc.error_rate) >= 1.0 and svc.name in seen:
            findings.append(Finding(
                "VET-T005", SEV_WARN,
                f"errorRate={svc.error_rate}: every request to "
                f"{svc.name!r} fails"
                + (" — the entrypoint 500s the whole run"
                   if svc.name == entry else ""),
                path=f"services[{i}].errorRate",
            ))
        if int(svc.response_size) > PAYLOAD_BOUND_BYTES:
            findings.append(Finding(
                "VET-T006", SEV_WARN,
                f"responseSize={svc.response_size} exceeds "
                f"{PAYLOAD_BOUND_BYTES} bytes",
                path=f"services[{i}].responseSize",
            ))
        for j, cmd in enumerate(svc.script):
            calls = (
                [c for c in cmd if isinstance(c, RequestCommand)]
                if isinstance(cmd, ConcurrentCommand)
                else [cmd] if isinstance(cmd, RequestCommand) else []
            )
            for call in calls:
                if int(call.size) > PAYLOAD_BOUND_BYTES:
                    findings.append(Finding(
                        "VET-T006", SEV_WARN,
                        f"call to {call.service_name!r} sends "
                        f"{call.size} (> {PAYLOAD_BOUND_BYTES} bytes)",
                        path=f"services[{i}].script[{j}]",
                    ))

    findings.extend(_lint_policies(graph, params))
    findings.extend(_lint_rollouts(graph, params))
    findings.extend(_lint_lb(graph, params))
    return findings


def _lint_policies(graph: ServiceGraph, params) -> List[Finding]:
    """Resilience-policy misconfiguration rules (VET-T010..T013) over
    the topology's ``policies:`` block (sim/policies.py).

    VET-T010 (the steady-state breaker-capacity rule) needs an offered
    rate, so it lives in :func:`lint_config`; the load-free rules here
    are: VET-T011 autoscaler ``min_replicas > max_replicas``,
    VET-T012 a zero retry budget on a retried call target,
    VET-T013 an autoscaler sync period shorter than the timeline
    window (the control loop cannot observe faster than the recorder
    samples), and VET-T014 a policies block that does not decode at
    all (typo'd keys, malformed values).
    """
    if not getattr(graph, "policies", None):
        return []
    # lazy: keeps the no-policies lint path jax-free
    from isotope_tpu.sim import policies as policies_mod

    findings: List[Finding] = []
    names = [s.name for s in graph.services]
    pset, problems = policies_mod.lint_policies(graph.policies, names)
    for _, msg in problems:
        findings.append(Finding(
            "VET-T014", SEV_ERROR,
            f"policies block does not decode: {msg}",
            path="policies",
        ))
    if pset is None:
        return findings
    if params is None:
        from isotope_tpu.sim.config import SimParams

        params = SimParams()
    # which services are the target of a call with retries > 0
    retried = set()
    for svc in graph.services:
        for cmd in svc.script:
            calls = (
                [c for c in cmd if isinstance(c, RequestCommand)]
                if isinstance(cmd, ConcurrentCommand)
                else [cmd] if isinstance(cmd, RequestCommand) else []
            )
            for call in calls:
                if call.retries > 0:
                    retried.add(call.service_name)
    for name in names:
        p = pset.for_service(name)
        if p.autoscaler is not None:
            a = p.autoscaler
            if a.min_replicas > a.max_replicas:
                findings.append(Finding(
                    "VET-T011", SEV_ERROR,
                    f"autoscaler min_replicas={a.min_replicas} > "
                    f"max_replicas={a.max_replicas}: the desired-count "
                    "clamp is empty (the controller could never "
                    "actuate a legal count)",
                    path=f"policies.{name}.autoscaler",
                ))
            if a.sync_period_s < params.timeline_window_s:
                findings.append(Finding(
                    "VET-T013", SEV_WARN,
                    f"autoscaler sync_period {a.sync_period_s:g}s is "
                    "shorter than the timeline window "
                    f"{params.timeline_window_s:g}s: the control loop "
                    "cannot observe faster than the flight recorder "
                    "samples, so syncs between window boundaries see "
                    "stale signals (widen sync_period or narrow "
                    "--timeline)",
                    path=f"policies.{name}.autoscaler.sync_period",
                ))
        if (
            p.retry_budget is not None
            and p.retry_budget.budget_percent <= 0.0
            and p.retry_budget.min_retries_concurrent <= 0.0
            and name in retried
        ):
            findings.append(Finding(
                "VET-T012", SEV_WARN,
                f"retry_budget of 0 on {name!r}, but calls to it set "
                "retries > 0: every retry will be suppressed once any "
                "are observed (drop the retries or raise the budget)",
                path=f"policies.{name}.retry_budget",
            ))
    return findings


def _lint_rollouts(graph: ServiceGraph, params) -> List[Finding]:
    """Progressive-delivery misconfiguration rules (VET-T015..T018)
    over the topology's ``rollouts:`` block (sim/rollout.py).

    VET-T017 (min-samples reachability) needs an offered rate, so it
    lives in :func:`lint_config`; the load-free rules here are:
    VET-T015 a step schedule that is not strictly increasing or does
    not end at 100% (the rollout can thrash between equal weights, or
    "finishes" while still splitting traffic) — and, as an error, a
    rollouts block that does not decode at all; VET-T016 a bake time
    shorter than the recorder window (a step can promote before the
    controller ever observes a completed window of it); VET-T018
    canary overrides on a service with no step schedule (the canary
    physics never actuate).
    """
    if not getattr(graph, "rollouts", None):
        return []
    # lazy: keeps the no-rollouts lint path jax-free
    from isotope_tpu.sim import rollout as rollout_mod

    findings: List[Finding] = []
    names = [s.name for s in graph.services]
    rset, problems = rollout_mod.lint_rollouts(graph.rollouts, names)
    for _, msg in problems:
        findings.append(Finding(
            "VET-T015", SEV_ERROR,
            f"rollouts block does not decode: {msg}",
            path="rollouts",
        ))
    if rset is None:
        return findings
    if params is None:
        from isotope_tpu.sim.config import SimParams

        params = SimParams()
    for name in names:
        r = rset.for_service(name)
        raw = (
            graph.rollouts.get(name)
            if isinstance(graph.rollouts, dict) else None
        )
        if not r.active:
            if isinstance(raw, dict) and raw.get("canary"):
                findings.append(Finding(
                    "VET-T018", SEV_WARN,
                    f"canary overrides on {name!r} but no step "
                    "schedule: the rollout never actuates (declare "
                    "`steps:` or drop the `canary:` block)",
                    path=f"rollouts.{name}.canary",
                ))
            continue
        steps = r.steps
        if any(b <= a for a, b in zip(steps, steps[1:])):
            findings.append(Finding(
                "VET-T015", SEV_WARN,
                f"step schedule {[f'{w:.0%}' for w in steps]} on "
                f"{name!r} is not strictly increasing: a promotion "
                "that does not raise the canary weight re-bakes the "
                "same split and gains nothing",
                path=f"rollouts.{name}.steps",
            ))
        if steps[-1] < 1.0:
            findings.append(Finding(
                "VET-T015", SEV_WARN,
                f"step schedule on {name!r} ends at {steps[-1]:.0%}, "
                "not 100%: the rollout finishes DONE while still "
                "splitting traffic between two deployments forever",
                path=f"rollouts.{name}.steps",
            ))
        if r.bake_s < params.timeline_window_s:
            findings.append(Finding(
                "VET-T016", SEV_WARN,
                f"bake {r.bake_s:g}s on {name!r} is shorter than the "
                f"recorder window {params.timeline_window_s:g}s: a "
                "step can promote before the controller observes a "
                "single completed window of it (widen bake or narrow "
                "--timeline)",
                path=f"rollouts.{name}.bake",
            ))
    return findings


def _lint_lb(graph: ServiceGraph, params) -> List[Finding]:
    """Load-balancing misconfiguration rules (VET-T019..T022) over the
    topology's per-service ``lb:`` entries (sim/lb.py).

    VET-T019: ``choices_d`` exceeds the replica count — power-of-d
    sampling cannot draw more distinct backends than exist, so the law
    silently degenerates to full-pool least-request (JSQ); VET-T020:
    ring-hash on a single-replica service — every key maps to the one
    backend, stickiness is a no-op (info); VET-T021: a panic threshold
    of 1.0 or above (every run starts panicked — error), or one the
    breaker's ``max_ejection_fraction`` can never reach (ejection
    leaves ``1 - max_ejection_fraction`` healthy, so panic is dead
    code under ejection-only unhealth — warn); VET-T022: lb entries
    that do not decode at all.
    """
    if not getattr(graph, "policies", None):
        return []
    # lazy: keeps the no-lb lint path jax-free
    from isotope_tpu.sim import lb as lb_mod
    from isotope_tpu.sim import policies as policies_mod

    findings: List[Finding] = []
    names = [s.name for s in graph.services]
    lbs, problems = lb_mod.lint_lb(graph.policies, names)
    for _, msg in problems:
        findings.append(Finding(
            "VET-T022", SEV_ERROR,
            f"lb entries do not decode: {msg}",
            path="policies",
        ))
    if lbs is None or lbs.empty:
        return findings
    pset, _ = policies_mod.lint_policies(graph.policies, names)
    replicas = {s.name: max(1, s.num_replicas) for s in graph.services}
    for name in names:
        p = lbs.for_service(name)
        if p is None or not p.active:
            continue
        k = replicas[name]
        if p.policy == "least_request" and p.choices_d > k:
            findings.append(Finding(
                "VET-T019", SEV_WARN,
                f"lb choices_d={p.choices_d} on {name!r} exceeds its "
                f"{k} replica(s): power-of-d cannot sample more "
                "distinct backends than exist — the law degenerates "
                "to full-pool least-request (lower choices_d or add "
                "replicas)",
                path=f"policies.{name}.lb.choices_d",
            ))
        if p.policy == "ring_hash" and k <= 1:
            findings.append(Finding(
                "VET-T020", SEV_INFO,
                f"ring_hash on {name!r} with replicas: 1 — every key "
                "maps to the single backend, so hash stickiness (and "
                "hash_skew) is a no-op",
                path=f"policies.{name}.lb",
            ))
        if p.panic_threshold >= 1.0:
            findings.append(Finding(
                "VET-T021", SEV_ERROR,
                f"panic_threshold={p.panic_threshold:g} on {name!r}: "
                "the healthy fraction is always < 1.0 under any "
                "unhealth, so the pool PANICS from the first ejection "
                "or kill (thresholds are fractions in [0, 1))",
                path=f"policies.{name}.lb.panic_threshold",
            ))
        elif p.panic_threshold > 0.0 and pset is not None:
            b = pset.for_service(name).breaker
            if (
                b is not None
                and b.consecutive_errors > 0
                and 1.0 - b.max_ejection_fraction >= p.panic_threshold
            ):
                findings.append(Finding(
                    "VET-T021", SEV_WARN,
                    f"panic_threshold={p.panic_threshold:g} on "
                    f"{name!r} is unreachable via outlier ejection: "
                    f"max_ejection_fraction={b.max_ejection_fraction:g}"
                    f" leaves {1.0 - b.max_ejection_fraction:g} of the"
                    " pool healthy, above the threshold — panic only "
                    "fires under chaos kills (raise the threshold or "
                    "the ejection cap)",
                    path=f"policies.{name}.lb.panic_threshold",
                ))
    return findings


def lint_ensemble(spec) -> List[Finding]:
    """Ensemble-spec misconfiguration rules (VET-T023) over an
    :class:`~isotope_tpu.sim.ensemble.EnsembleSpec`.

    VET-T023 errors on a fleet with zero members (nothing to
    simulate) or duplicate member seeds: duplicated seeds make two
    members bit-identical copies of one trajectory, silently
    narrowing every confidence interval the ensemble exists to
    produce.  ``run_ensemble`` raises the same defects loudly at run
    entry (sim/ensemble.py ``EnsembleSpec.check``)."""
    findings: List[Finding] = []
    if spec is None:
        return findings
    if spec.members == 0:
        findings.append(Finding(
            "VET-T023", SEV_ERROR,
            "ensemble spec has zero members: the fleet would simulate "
            "nothing (set members >= 1 or drop the ensemble)",
            path="sim.ensemble",
        ))
        return findings
    seeds = tuple(spec.seeds)
    dupes = sorted({s for s in seeds if seeds.count(s) > 1})
    if dupes:
        findings.append(Finding(
            "VET-T023", SEV_ERROR,
            f"ensemble spec has duplicate member seeds {dupes}: "
            "duplicated members replay one trajectory bit-for-bit — "
            "they are not extra Monte Carlo samples and silently "
            "narrow every confidence interval",
            path="sim.ensemble",
        ))
    return findings


def lint_split(spec) -> List[Finding]:
    """Importance-splitting misconfiguration rules (VET-T024) over a
    :class:`~isotope_tpu.sim.splitting.SplitSpec` (or its raw
    ``--ensemble-split`` string).

    Errors on an undecodable spec, a survivor fraction outside
    (0, 1) (``keep >= 1`` keeps every member — the levels never climb
    toward the rare event; ``keep <= 0`` keeps none), and a budget of
    fewer than one survivor per level (``keep * members < 1``: the
    level quantile falls on an empty survivor set).  The estimator
    raises the same defects loudly at run entry
    (sim/splitting.py ``SplitSpec``)."""
    findings: List[Finding] = []
    if spec is None:
        return findings
    if isinstance(spec, str):
        from isotope_tpu.sim.splitting import parse_split_spec

        try:
            spec = parse_split_spec(spec)
        except (ValueError, TypeError) as e:
            findings.append(Finding(
                "VET-T024", SEV_ERROR,
                f"undecodable importance-splitting spec: {e}",
                path="sim.ensemble_split",
            ))
            return findings
        if spec is None:
            return findings
    if spec.keep * spec.members < 1.0:
        findings.append(Finding(
            "VET-T024", SEV_ERROR,
            f"splitting budget has fewer than one survivor per level "
            f"(keep {spec.keep:g} x members {spec.members} < 1): the "
            "level quantile falls on an empty survivor set — raise "
            "members or keep",
            path="sim.ensemble_split",
        ))
    if spec.levels <= 1:
        findings.append(Finding(
            "VET-T024", SEV_WARN,
            "a single splitting level degenerates to plain Monte "
            f"Carlo at the first threshold (resolving floor ~1/"
            f"{spec.members}); raise levels for rarer events",
            path="sim.ensemble_split",
        ))
    return findings


def lint_search(spec, num_requests=None,
                block: int = 65_536) -> List[Finding]:
    """Search-bracket misconfiguration rules (VET-T026) over a
    :class:`~isotope_tpu.sim.search.SearchSpec` (or its raw
    ``[search]`` table dict).

    Errors on an undecodable spec, a population too small for the
    bracket (rung widths ``ceil(N / eta^r)`` must strictly shrink —
    population < eta degenerates at the first halving), and — when
    ``num_requests`` is known — a horizon schedule that fails to
    increase between rungs (the continuation segments would be
    empty).  Warns when the population is not a power of ``eta``
    (non-integer survivor counts: ceil rounds rungs up, so padded
    slots re-run candidates the severity rank already rejected) and
    when the rank channel needs a recorder no search fleet carries
    (``err_peak`` falls back to ``err_share``).  ``run_search``
    raises the ERROR-grade defects loudly at run entry
    (sim/search.py ``SearchSpec.check`` / ``plan_bracket``)."""
    findings: List[Finding] = []
    if spec is None:
        return findings
    from isotope_tpu.sim.search import SearchSpec

    if isinstance(spec, dict):
        try:
            spec = SearchSpec.from_dict(spec)
        except (ValueError, TypeError, KeyError) as e:
            findings.append(Finding(
                "VET-T026", SEV_ERROR,
                f"undecodable search spec: {e}",
                path="search",
            ))
            return findings
    widths = spec.rung_widths()
    if any(b >= a for a, b in zip(widths, widths[1:])):
        findings.append(Finding(
            "VET-T026", SEV_ERROR,
            f"population of {spec.members} cannot support "
            f"{spec.rungs} rungs at eta={spec.eta}: rung widths "
            f"{widths} stop shrinking — the bracket degenerates at "
            "the first halving (grow the population or drop rungs)",
            path="search",
        ))
    else:
        n = spec.members
        if any(n % spec.eta ** r for r in range(spec.rungs)):
            findings.append(Finding(
                "VET-T026", SEV_WARN,
                f"population {n} is not a power-of-eta multiple "
                f"(eta={spec.eta}, widths {widths}): ceil rounds "
                "survivor counts up and pow2 buckets pad the rungs — "
                "some dispatch slots re-run already-rejected "
                "candidates (harmless, but a power of eta wastes "
                "none)",
                path="search",
            ))
    if spec.rank == "err_peak":
        findings.append(Finding(
            "VET-T026", SEV_WARN,
            "rank='err_peak' needs the recorder-window timelines no "
            "search fleet carries — the bracket ranks by the run-long "
            "'err_share' fallback (use rank='err_share' to say what "
            "runs, or rank='p99' with slo= for tail risk)",
            path="search.rank",
        ))
    if num_requests is not None and not any(
        f.severity == SEV_ERROR for f in findings
    ):
        from isotope_tpu.sim.search import plan_bracket

        try:
            plan_bracket(spec, int(num_requests), int(block))
        except ValueError as e:
            findings.append(Finding(
                "VET-T026", SEV_ERROR, str(e), path="search",
            ))
    return findings


def lint_compiled(compiled, params=None) -> List[Finding]:
    """Shape rules needing the unrolled hop tree (VET-T007/T008).

    Pure NumPy over the CompiledGraph — compiling is host-side, so
    these rules still run without a device."""
    from isotope_tpu.compiler import buckets
    from isotope_tpu.sim.config import SimParams

    if params is None:
        params = SimParams()
    findings: List[Finding] = []
    h = max(compiled.num_hops, 1)

    # VET-T007: the default block floors at BLOCK_FLOOR requests; when
    # hops alone exceed budget/floor every block busts the element
    # budget the block size exists to respect (default_block_size)
    if h * BLOCK_FLOOR > BLOCK_ELEM_BUDGET:
        findings.append(Finding(
            "VET-T007", SEV_WARN,
            f"{h} hops x the {BLOCK_FLOOR}-request block floor = "
            f"{h * BLOCK_FLOOR} elements per event tensor "
            f"(budget {BLOCK_ELEM_BUDGET}); expect the OOM ladder "
            "or shard over a mesh",
        ))

    # VET-T008: plan the buckets exactly as the engine will and check
    # the realized padding against the configured budget.  The step
    # encoding decision (dense / tiled / sparse) is the engine's own
    # (compiler/buckets.level_encoding), so VET-C006 reports the
    # executor's real fallbacks, not a reimplementation's.
    shapes = []
    offset = 0
    for d, lvl in enumerate(compiled.levels):
        pmax = max(int(lvl.step_is_real.sum(1).max(initial=0)), 1)
        import numpy as np

        sparse = False
        tiles = None
        residual_slots = 0
        if lvl.num_calls:
            n_slots = len(np.unique(lvl.call_seg))
            widths = lvl.step_is_real[:, :pmax].sum(1)
            enc, tile_plan = buckets.level_encoding(
                lvl.num_hops, pmax, n_slots, widths,
                sparse_level_elems=params.sparse_level_elems,
                tiling=params.sparse_tiling,
                tile_pmax=params.sparse_tile_pmax,
            )
            sparse = enc != "dense"
            if enc == "tiled":
                tiles = tile_plan.shapes()
                res_widths = widths[tile_plan.residual]
                # EXACT residual slot count (call-bearing steps of the
                # residual hops) — the engine's tiled.residual.n_slots,
                # not the script-width approximation, so the vet
                # surface agrees with costmodel.schedule_rows(sim)
                call_parent = lvl.call_seg // compiled.max_steps
                res_mask = np.isin(call_parent, tile_plan.residual)
                residual_slots = len(np.unique(lvl.call_seg[res_mask]))
                if len(tile_plan.residual):
                    grid = lvl.num_hops * pmax
                    # pure padding of the avoided dense grid: slots the
                    # grid holds beyond EVERY hop's real steps (tiled
                    # hops' real work is not padding)
                    pad = grid - int(widths.sum())
                    findings.append(Finding(
                        "VET-C006", SEV_INFO,
                        f"{len(tile_plan.residual)} of {lvl.num_hops} "
                        f"hop(s) at depth {d} exceed the "
                        f"sparse_tile_pmax={params.sparse_tile_pmax} "
                        f"tile cap (widest script "
                        f"{int(res_widths.max(initial=0))} steps) and "
                        "stay on the residual sparse path "
                        f"({residual_slots} slot(s)); the dense grid "
                        f"they avoid is {grid} element-slots "
                        f"({pad} pure padding, "
                        f"{pad / max(grid, 1):.1%} waste)",
                        path=f"levels[{d}]",
                    ))
            elif enc == "sparse":
                grid = lvl.num_hops * pmax
                residual_slots = n_slots
                pad = grid - int(widths.sum())
                findings.append(Finding(
                    "VET-C006", SEV_INFO,
                    f"level at depth {d} ({lvl.num_hops} hop(s), "
                    f"widest script {pmax} steps) does not tile — the "
                    f"whole level runs the sparse call-slot path over "
                    f"{n_slots} slot(s); its dense grid would be "
                    f"{grid} element-slots "
                    f"({pad / max(grid, 1):.1%} pure padding)",
                    path=f"levels[{d}]",
                ))
        shapes.append(buckets.LevelShape(
            size=lvl.num_hops, pmax=pmax, children=lvl.num_children,
            calls=lvl.num_calls, attempts=lvl.max_attempts,
            sparse=sparse, offset=offset, tiles=tiles,
            residual_slots=residual_slots,
        ))
        offset += lvl.num_hops
    plan = buckets.plan_segments(
        shapes, waste=params.level_bucket_waste,
        enabled=params.bucketed_scan,
        schedule=params.bucket_schedule,
    )
    stats = buckets.plan_stats(shapes, plan)
    waste_budget = params.level_bucket_waste - 1.0
    if stats["padded_elems"] and stats["padding_waste_fraction"] > max(
        waste_budget / (1.0 + waste_budget), 0.0
    ) + 1e-9:
        findings.append(Finding(
            "VET-T008", SEV_WARN,
            f"bucket plan pads {stats['padding_waste_fraction']:.1%} of "
            f"element slots (budget from level_bucket_waste="
            f"{params.level_bucket_waste:g}); retune the waste knob for "
            "this topology family",
        ))
    return findings


def _capacity_qps(compiled, params) -> float:
    """Static saturation throughput (the engine's capacity_qps without
    building a Simulator): bottleneck station capacity over expected
    visits."""
    import numpy as np

    visits = compiled.expected_visits()
    mu = 1.0 / params.cpu_time_s
    reps = compiled.services.replicas.astype(np.float64)
    with np.errstate(divide="ignore"):
        per_svc = np.where(
            visits > 0, reps * mu / np.maximum(visits, 1e-30), np.inf
        )
    return float(per_svc.min())


def lint_config(config) -> Tuple[List[Finding], Dict[str, object]]:
    """Lint an ExperimentConfig (sweep TOML): grid and schedule rules.

    Returns ``(findings, graphs)`` where ``graphs`` maps each readable
    topology path to its decoded ServiceGraph so callers can chain the
    per-graph passes without re-reading files."""
    from isotope_tpu.runner.run import _label  # the label law itself

    findings: List[Finding] = []
    graphs: Dict[str, object] = {}

    # VET-C001: missing/unreadable/undecodable topologies (YAML syntax
    # errors are yaml.YAMLError, NOT ValueError — vet must report them,
    # not crash on them)
    import yaml

    for i, p in enumerate(config.topology_paths):
        try:
            graphs[p] = ServiceGraph.from_yaml_file(p)
        except OSError as e:
            findings.append(Finding(
                "VET-C001", SEV_ERROR, str(e),
                path=f"topology_paths[{i}]",
            ))
        except (ValueError, yaml.YAMLError) as e:
            findings.append(Finding(
                "VET-C001", SEV_ERROR, f"{p}: {e}",
                path=f"topology_paths[{i}]",
            ))

    # VET-C002: duplicate labels (the runner raises at run time; vet
    # reports the same defect statically, with the colliding labels)
    labels = [
        _label(topo, env.name, load, config.labels)
        for topo in config.topology_paths
        for env in config.environments
        for load in config.load_models()
    ]
    dupes = sorted({lb for lb in labels if labels.count(lb) > 1})
    if dupes:
        findings.append(Finding(
            "VET-C002", SEV_ERROR,
            f"colliding run labels: {', '.join(dupes)} (topology file "
            "stems and the load grid must disambiguate)",
        ))

    # schedule rules need the union of service names across topologies
    all_names = {
        s.name for g in graphs.values() for s in g.services
    }
    duration = float(config.duration_s)
    for i, ev in enumerate(config.chaos):
        if graphs and ev.service not in all_names:
            findings.append(Finding(
                "VET-C003", SEV_ERROR,
                f"chaos targets unknown service {ev.service!r}",
                path=f"chaos[{i}]",
            ))
        elif ev.start_s >= duration:
            findings.append(Finding(
                "VET-C004", SEV_WARN,
                f"chaos window [{ev.start_s:g}, {ev.end_s:g})s starts "
                f"after the {duration:g}s run ends",
                path=f"chaos[{i}]",
            ))
    for i, ts in enumerate(config.churn):
        if graphs and ts.service not in all_names:
            findings.append(Finding(
                "VET-C003", SEV_ERROR,
                f"churn targets unknown service {ts.service!r}",
                path=f"churn[{i}]",
            ))
        elif ts.period_s >= duration and len(ts.weights) > 1:
            findings.append(Finding(
                "VET-C004", SEV_WARN,
                f"churn period {ts.period_s:g}s never completes a "
                f"weight rotation within the {duration:g}s run",
                path=f"churn[{i}]",
            ))
    if config.mtls is not None and (
        config.mtls.period_s >= duration and len(config.mtls.taxes_s) > 1
    ):
        findings.append(Finding(
            "VET-C004", SEV_WARN,
            f"mtls period {config.mtls.period_s:g}s never alternates "
            f"within the {duration:g}s run",
            path="mtls",
        ))

    # VET-C005: open-loop offered rate vs static capacity
    # VET-T010: breaker caps vs steady-state expected queue/concurrency
    if config.load_kind == "open":
        params = config.sim_params()
        for p, g in graphs.items():
            try:
                from isotope_tpu.compiler import compile_graph

                compiled = compile_graph(g, entry=config.entry)
            except ValueError:
                continue  # compile defects are the graph passes' job
            cap = _capacity_qps(compiled, params)
            stem = pathlib.Path(p).stem
            for q in config.qps:
                if q is not None and q >= cap:
                    findings.append(Finding(
                        "VET-C005", SEV_WARN,
                        f"open-loop qps {q:g} >= static capacity "
                        f"{cap:.1f} of {stem}: queues are unstable "
                        "(waits grow without bound over the run)",
                    ))
            findings.extend(
                _lint_breaker_capacity(g, compiled, params, config.qps)
            )
            findings.extend(
                _lint_rollout_samples(g, compiled, config.qps)
            )

    # VET-T023: the sweep's ensemble spec (zero members / duplicate
    # seeds) — config-level, so a broken fleet fails before any
    # topology compiles
    if getattr(config, "ensemble", 0):
        try:
            findings.extend(lint_ensemble(config.ensemble_spec()))
        except ValueError as e:
            findings.append(Finding(
                "VET-T023", SEV_ERROR, str(e), path="sim.ensemble",
            ))

    # VET-T026: the sweep's search bracket (degenerate population /
    # horizon schedule / rank channel) — config-level for the same
    # fail-before-compile reason
    if getattr(config, "search_candidates", 0):
        try:
            findings.extend(lint_search(
                config.search_spec(),
                num_requests=config.num_requests,
            ))
        except ValueError as e:
            findings.append(Finding(
                "VET-T026", SEV_ERROR, str(e), path="search",
            ))
    return findings, graphs


def _lint_rollout_samples(graph, compiled, qps_grid) -> List[Finding]:
    """VET-T017: a gate whose ``min_samples`` cannot accumulate on the
    canary arm within one bake at a configured offered rate — the
    controller HOLDS forever (or near enough that the schedule never
    finishes inside the run).  The canary arm's sample rate at a step
    of weight ``w`` is ``qps x expected_visits x w``, so the binding
    step is the first (smallest) one."""
    if not getattr(graph, "rollouts", None):
        return []
    from isotope_tpu.sim import rollout as rollout_mod

    rset, _ = rollout_mod.lint_rollouts(
        graph.rollouts, [s.name for s in graph.services]
    )
    if rset is None:
        return []
    findings: List[Finding] = []
    visits = compiled.expected_visits()
    name_idx = {n: i for i, n in enumerate(compiled.services.names)}
    for name, r in rset.per_service.items():
        if not r.active or name not in name_idx:
            continue
        w0 = r.steps[0]
        per_visit = visits[name_idx[name]]
        for q in qps_grid:
            if q is None:
                continue
            expected = q * per_visit * w0 * r.bake_s
            if expected < r.gates.min_samples:
                findings.append(Finding(
                    "VET-T017", SEV_WARN,
                    f"gate min_samples={r.gates.min_samples:g} on "
                    f"{name!r} is unreachable within one bake at "
                    f"{q:g} qps: step 0 ({w0:.0%}) collects only "
                    f"~{expected:.0f} canary samples per "
                    f"{r.bake_s:g}s bake — the controller holds "
                    "indefinitely (lower min_samples, raise the "
                    "first step, or lengthen bake)",
                    path=f"rollouts.{name}.gates.min_samples",
                ))
    return findings


def _lint_breaker_capacity(
    graph, compiled, params, qps_grid
) -> List[Finding]:
    """VET-T010: a circuit breaker whose ``max_pending`` /
    ``max_connections`` sit below the M/M/k STEADY-STATE expected
    queue depth / in-flight concurrency at a configured offered rate
    sheds healthy traffic permanently — a misconfiguration, not a
    protection."""
    if not getattr(graph, "policies", None):
        return []
    import numpy as np

    from isotope_tpu.sim import policies as policies_mod
    from isotope_tpu.sim.feedback import np_mmk

    pset, _ = policies_mod.lint_policies(
        graph.policies, [s.name for s in graph.services]
    )
    if pset is None:
        return []
    findings: List[Finding] = []
    visits = compiled.expected_visits()
    mu = 1.0 / params.cpu_time_s
    reps = compiled.services.replicas.astype(np.float64)
    names = compiled.services.names
    for q in qps_grid:
        if q is None:
            continue
        p_wait, wait_rate, rho = np_mmk(q * visits, mu, reps)
        rho_c = np.minimum(rho, 0.9999)
        lq = p_wait * rho_c / np.maximum(1.0 - rho_c, 1e-9)
        inflight = lq + rho_c * reps
        for s, name in enumerate(names):
            pol = pset.for_service(name)
            if pol.breaker is None:
                continue
            b = pol.breaker
            if b.max_pending is not None and b.max_pending < lq[s]:
                findings.append(Finding(
                    "VET-T010", SEV_WARN,
                    f"breaker max_pending={b.max_pending:g} on "
                    f"{name!r} is below the steady-state expected "
                    f"queue depth {lq[s]:.1f} at {q:g} qps: the "
                    "breaker sheds HEALTHY traffic permanently",
                    path=f"policies.{name}.breaker.max_pending",
                ))
            if (
                b.max_connections is not None
                and b.max_connections < inflight[s]
            ):
                findings.append(Finding(
                    "VET-T010", SEV_WARN,
                    f"breaker max_connections={b.max_connections:g} "
                    f"on {name!r} is below the steady-state expected "
                    f"concurrency {inflight[s]:.1f} at {q:g} qps",
                    path=f"policies.{name}.breaker.max_connections",
                ))
    return findings


# -- trace-driven ingest (isotope-ingest/v1 artifacts) -----------------


def lint_ingest(graph: ServiceGraph, report_doc: dict) -> List[Finding]:
    """Lint a fitted topology against its own ingest report.

    Host-side companions to the fit: VET-T027 checks the fitted qps
    schedule's PEAK against the fitted station capacity (expected
    visits computed by DP over the fitted DAG — an errored parent
    skips its calls, so visits carry the (1 - errorRate) factor the
    engine applies); VET-T028 surfaces services the fitter emitted
    with zero observed samples (graph closure required the node, but
    every knob on it is a default, not a measurement).
    """
    findings: List[Finding] = []
    fit = report_doc.get("fit", {})
    entry = report_doc.get("entry")
    names = [s.name for s in graph.services]
    idx = {n: i for i, n in enumerate(names)}
    by_name = {s.name: s for s in graph.services}
    if entry not in idx:
        return findings

    # expected visits per entry request: DFS accumulation over the
    # (acyclic — the fitter broke cycles) fitted call graph
    visits: Dict[str, float] = {n: 0.0 for n in names}
    visits[entry] = 1.0
    order: List[str] = []
    seen = set()

    def topo(n: str) -> None:
        # iterative post-order: fitted graphs can be chain-deep
        stack = [(n, False)]
        while stack:
            node, done = stack.pop()
            if done:
                order.append(node)
                continue
            if node in seen:
                continue
            seen.add(node)
            stack.append((node, True))
            for t in _call_targets(by_name[node].script):
                if t in by_name and t not in seen:
                    stack.append((t, False))

    topo(entry)
    for node in reversed(order):
        v = visits[node]
        if v <= 0:
            continue
        svc = by_name[node]
        passthrough = 1.0 - float(svc.error_rate)
        for cmd in svc.script:
            subs = cmd if isinstance(cmd, ConcurrentCommand) else [cmd]
            for sub in subs:
                if isinstance(sub, RequestCommand) and (
                    sub.service_name in visits
                ):
                    visits[sub.service_name] += (
                        v * passthrough * sub.send_probability
                    )

    schedule = fit.get("qps_schedule") or []
    cpu_time = float(fit.get("cpu_time_s") or 0.0)
    if schedule and cpu_time > 0:
        peak = max(schedule)
        mu = 1.0 / cpu_time
        for name in names:
            v = visits.get(name, 0.0)
            if v <= 0:
                continue
            reps = max(by_name[name].num_replicas, 1)
            capacity = reps * mu / v
            if peak > capacity:
                findings.append(Finding(
                    "VET-T027", SEV_WARN,
                    f"window-peak {peak:g} qps x {v:.2f} expected "
                    f"visits exceeds {name!r}'s fitted station "
                    f"capacity {capacity:.0f} qps ({reps} replica(s) "
                    f"at cpu_time {cpu_time * 1e6:.0f}us): the replay "
                    "saturates where the source did not",
                    path=f"services[{idx[name]}]",
                ))

    for row in fit.get("services", []):
        samples = row.get("observed", {}).get("samples", 0.0)
        name = row.get("name")
        if name in idx and (samples or 0.0) <= 0:
            findings.append(Finding(
                "VET-T028", SEV_WARN,
                f"service {name!r} was emitted with zero observed "
                "samples: its error/timing knobs are fit defaults",
                path=f"services[{idx[name]}]",
            ))
    return findings
