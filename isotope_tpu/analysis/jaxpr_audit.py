"""Jaxpr auditor: trace-only inspection of the engine's tensor program.

``jax.make_jaxpr`` runs the Python of a traced entry point with
abstract values — no device execution, no XLA compile — and yields the
ClosedJaxpr the engine would jit.  Walking that jaxpr statically
surfaces whole classes of hot-path defects before a single request
simulates:

- **host sync points** (VET-J001): ``pure_callback`` / ``io_callback``
  / ``debug_callback`` / infeed/outfeed primitives force a
  device-to-host round trip per dispatch — on the scan hot path that
  serializes every block;
- **dtype leaks** (VET-J002): float64/complex128 avals double the
  event-tensor footprint and fall off the TPU fast path;
- **nondeterministic accumulation** (VET-J003, info): floating-point
  scatter-add reductions depend on accumulation order on parallel
  backends;
- **retrace hazards** (VET-J004): the AOT executable cache
  (compiler/cache.py) keys on the engine's shape signature + constant
  digest; an unhashable component would crash the key, and an id-based
  ``repr`` (``<object at 0x...>``) digests differently every process —
  every run silently retraces.

The auditor never executes the program: the trace-only property is
pinned by ``tests/test_vet.py`` (no jit first-calls, no backend
compile seconds, ``Simulator.run`` monkeypatched to raise).

``$ISOTOPE_VET_INJECT`` (comma list of ``callback`` / ``f64`` /
``graddead``) seeds those defects into the traced program — the
engine-chaos discipline of ``ISOTOPE_FAULT_INJECT`` aimed at the
auditors, so the detection path is exercisable end-to-end from the CLI
and smoke targets (``graddead`` is consumed by the gradient audit,
analysis/grad_audit.py, and ignored here).
"""
from __future__ import annotations

import os
from typing import Iterator, List, Optional, Tuple

from isotope_tpu.analysis.findings import (
    SEV_ERROR,
    SEV_INFO,
    SEV_WARN,
    Finding,
)

ENV_VET_INJECT = "ISOTOPE_VET_INJECT"

#: primitives that force a host round trip / sync point on the hot path
HOST_SYNC_PRIMITIVES = frozenset({
    "pure_callback",
    "io_callback",
    "debug_callback",
    "host_callback_call",
    "outside_call",
    "python_callback",
    "infeed",
    "outfeed",
})

#: dtypes whose presence in the traced program is a leak (VET-J002)
WIDE_DTYPES = frozenset({"float64", "complex128"})

#: scatter variants whose float accumulation is order-dependent
SCATTER_ACCUM_PRIMITIVES = frozenset({"scatter-add", "scatter_add"})

#: how many distinct sites one rule reports before folding into a count
MAX_SITES_PER_RULE = 5


def inject_spec() -> Tuple[str, ...]:
    """The armed defect-injection kinds (env ISOTOPE_VET_INJECT)."""
    spec = os.environ.get(ENV_VET_INJECT, "")
    kinds = tuple(k.strip() for k in spec.split(",") if k.strip())
    for k in kinds:
        if k not in ("callback", "f64", "graddead"):
            raise ValueError(
                f"unknown {ENV_VET_INJECT} kind {k!r} "
                "(one of: callback, f64, graddead)"
            )
    return kinds


def _first_array_leaf(out):
    import jax

    leaves = [
        x for x in jax.tree_util.tree_leaves(out)
        if hasattr(x, "dtype")
    ]
    return leaves[0] if leaves else None


def trace_entry(sim, load, num_requests: int = 8):
    """``(ClosedJaxpr, n)`` of the engine program ``load`` would run.

    Abstract (ShapeDtypeStruct) arguments only — nothing touches a
    device.  ``n`` is the request count actually traced: closed-loop
    programs need at least one request per connection, so it may
    exceed ``num_requests`` — the cost model must scale by THIS n, not
    the requested one (dividing a 64-connection trace by 8 would
    inflate every estimate 8x).  The saturated ``-qps max`` program is
    skipped (its MVA tables run host-side pilot executions at build
    time, violating the trace-only contract); the plain closed-loop
    program is audited in its place — same sweep body, same segment
    structure.
    """
    import jax
    import jax.numpy as jnp

    from isotope_tpu.sim.config import CLOSED_LOOP

    kind = load.kind
    connections = load.connections if kind == CLOSED_LOOP else 0
    n = max(int(num_requests), 1)
    if kind == CLOSED_LOOP:
        n = max(n, connections)
    fn, args = sim.trace_entry_args(n, kind, connections)

    kinds = inject_spec()
    if kinds:
        inner = fn

        def fn(*a):  # noqa: F811 - deliberate defect-seeding wrapper
            out = inner(*a)
            leaf = _first_array_leaf(out)
            if leaf is not None and "callback" in kinds:
                jax.debug.callback(lambda _x: None, leaf)
            if leaf is not None and "f64" in kinds:
                wide = jax.lax.convert_element_type(leaf, jnp.float64)
                out = out._replace(
                    client_latency=(wide * 2.0).astype(leaf.dtype)
                )
            return out

    if "f64" in kinds:
        # f64 is canonicalized away under the default x64-off config;
        # the seeded leak is only representable with x64 enabled for
        # the duration of the (still trace-only) trace
        with jax.experimental.enable_x64():
            return jax.make_jaxpr(fn)(*args), n
    return jax.make_jaxpr(fn)(*args), n


def iter_eqns(closed_or_jaxpr) -> Iterator[tuple]:
    """Yield ``(eqn, depth)`` over a jaxpr and every sub-jaxpr.

    The one shared walker of the static passes (this auditor and the
    gradient audit, analysis/grad_audit.py).  Descends every
    jaxpr-valued eqn param — scan/cond/while bodies, ``pjit`` calls,
    ``custom_jvp``/``custom_vjp`` call jaxprs, and lists of branch
    jaxprs — so a defect wrapped under any of them is still found
    (pinned by tests/test_vet.py).  Accepts a ClosedJaxpr or a bare
    Jaxpr."""
    import jax

    def rec(jxp, depth):
        for eqn in jxp.eqns:
            yield eqn, depth
            for v in eqn.params.values():
                if isinstance(v, jax.core.ClosedJaxpr):
                    yield from rec(v.jaxpr, depth + 1)
                elif isinstance(v, jax.core.Jaxpr):
                    yield from rec(v, depth + 1)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, jax.core.ClosedJaxpr):
                            yield from rec(x.jaxpr, depth + 1)
                        elif isinstance(x, jax.core.Jaxpr):
                            yield from rec(x, depth + 1)

    yield from rec(getattr(closed_or_jaxpr, "jaxpr", closed_or_jaxpr), 0)


def _fold_sites(rule: str, severity: str, sites: List[str],
                message: str) -> List[Finding]:
    """One finding per distinct site, folding the tail into a count."""
    seen = dict.fromkeys(sites)  # order-preserving dedupe
    distinct = list(seen)
    out = [
        Finding(rule, severity, message, path=site)
        for site in distinct[:MAX_SITES_PER_RULE]
    ]
    extra = len(distinct) - MAX_SITES_PER_RULE
    if extra > 0:
        out.append(Finding(
            rule, severity,
            f"{message} ({extra} more distinct site(s), "
            f"{len(sites)} occurrences total)",
            path=distinct[MAX_SITES_PER_RULE],
        ))
    return out


def audit_jaxpr(closed_jaxpr) -> List[Finding]:
    """Walk a ClosedJaxpr (incl. sub-jaxprs) for the VET-J rules."""
    sync_sites: List[str] = []
    wide_sites: List[str] = []
    scatter_sites: List[str] = []
    for eqn, depth in iter_eqns(closed_jaxpr):
        prim = str(eqn.primitive)
        site = f"{prim}@depth{depth}"
        if prim in HOST_SYNC_PRIMITIVES or "callback" in prim:
            sync_sites.append(site)
        if prim in SCATTER_ACCUM_PRIMITIVES:
            if any(
                str(getattr(v.aval, "dtype", "")).startswith("float")
                for v in eqn.outvars
            ):
                scatter_sites.append(site)
        for v in eqn.outvars:
            dtype = str(getattr(v.aval, "dtype", ""))
            if dtype in WIDE_DTYPES:
                wide_sites.append(f"{site}->{dtype}")
                break

    findings: List[Finding] = []
    findings += _fold_sites(
        "VET-J001", SEV_ERROR, sync_sites,
        "host callback forces a device-to-host sync per dispatch on "
        "the hot path",
    )
    findings += _fold_sites(
        "VET-J002", SEV_ERROR, wide_sites,
        "wide dtype in the traced program (doubles event-tensor "
        "footprint; off the TPU fast path)",
    )
    findings += _fold_sites(
        "VET-J003", SEV_INFO, scatter_sites,
        "float scatter-add: accumulation order is backend-dependent",
    )
    return findings


def audit_cache_signature(signature) -> List[Finding]:
    """Cross-check the engine's AOT cache key against compiler/cache.py.

    The executable cache keys on ``(tag, signature, shape...)`` tuples
    and ``array_digest`` hashes non-array components by ``repr``.  Two
    static hazards are detectable without running anything:

    - an **unhashable** component crashes the dict lookup;
    - a component whose ``repr`` embeds its memory address
      (``... at 0x...``) digests differently in every process, so the
      persistent/in-process caches miss forever — a silent retrace per
      run.
    """
    findings: List[Finding] = []

    def rec(obj, path: str) -> None:
        if isinstance(obj, tuple):
            try:
                hash(obj)
            except TypeError:
                findings.append(Finding(
                    "VET-J004", SEV_ERROR,
                    "unhashable executable-cache key component "
                    "(the AOT cache lookup would raise)",
                    path=path,
                ))
                return
            for i, x in enumerate(obj):
                rec(x, f"{path}[{i}]")
            return
        try:
            hash(obj)
        except TypeError:
            findings.append(Finding(
                "VET-J004", SEV_ERROR,
                f"unhashable signature component of type "
                f"{type(obj).__name__}",
                path=path,
            ))
            return
        r = repr(obj)
        if " at 0x" in r:
            findings.append(Finding(
                "VET-J004", SEV_WARN,
                f"id-based repr {r[:60]!r}: array_digest "
                "(compiler/cache.py) hashes this component by repr, so "
                "the cache key changes every process — a guaranteed "
                "retrace",
                path=path,
            ))

    rec(signature, "signature")
    return findings


def audit_simulator(sim, load, num_requests: int = 8,
                    trace: bool = True
                    ) -> Tuple[List[Finding], Optional[object], int]:
    """All jaxpr-auditor findings for one Simulator under one load.

    Returns ``(findings, closed_jaxpr, traced_n)``; the jaxpr and the
    request count it was traced at are handed to the cost model so the
    trace happens once and the per-request scaling is exact.
    ``trace=False`` skips the jaxpr passes (signature audit still runs
    — it is pure host data).
    """
    findings = audit_cache_signature(sim.signature)
    closed = None
    traced_n = max(int(num_requests), 1)
    if trace:
        closed, traced_n = trace_entry(sim, load, num_requests)
        findings += audit_jaxpr(closed)
    return findings, closed, traced_n
