"""Gradient-flow audit: taint analysis from design knobs to objectives.

The ROADMAP's differentiable-planning item (``isotope-tpu optimize``)
needs one inventory before any ``SimParams.soft`` relaxation lands:
which of the engine's hard joins actually sit on the gradient path
from each design parameter to the SLO objective, and which knobs no
relaxation can rescue because they never enter the jaxpr at all.

This pass answers that statically.  It traces the engine's universal
member body (``Simulator._member_fn`` with the jitter scales armed, so
``cpu_scale`` / ``err_scale`` are *traced invars* rather than baked
constants) via ``jax.make_jaxpr`` — same trace-only discipline as
:mod:`~isotope_tpu.analysis.jaxpr_audit`, no device execution, pinned
by test — then runs a forward dataflow over the ClosedJaxpr:

- **seed** taint at every registered design parameter
  (:data:`~isotope_tpu.sim.config.DESIGN_PARAMS` maps knob -> traced
  invar names or a trace-constant site);
- **propagate** through every eqn, descending into ``scan`` / ``while``
  / ``cond`` / ``pjit`` / custom-derivative sub-jaxprs (scan and while
  carries iterate to a fixpoint — the lattice is monotone in the live
  bit, so a handful of sweeps converge);
- **kill** liveness where the chain rule dies: ``argmin``/``argmax``,
  ``floor``/``ceil``/``round``/``sign``, ``stop_gradient``, any
  non-inexact output dtype (comparisons, integer casts, boolean
  coins), and comparison-fed ``select_n`` whose only taint arrives
  through the predicate.

Every knob lands in one of three classes — **differentiable** (live
taint reaches an objective output), **gradient-dead** (every tainted
path crosses a killer; the finding names the killing primitive and its
jaxpr path, e.g. ``scan/body/select_n←lt``), or **trace-constant**
(the knob never enters the jaxpr) — reported as the VET-G rules and as
the ``isotope-gradaudit/v1`` artifact the future ``optimize`` command
consumes as its relaxation worklist.

``$ISOTOPE_VET_INJECT=graddead`` routes ``cpu_scale`` through a
``floor`` quantization before it enters the engine, flipping
``cpu_time_s`` to gradient-dead — the end-to-end detection check of
``make grad-smoke``.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from isotope_tpu.analysis.findings import (
    SEV_INFO,
    SEV_WARN,
    Finding,
)
from isotope_tpu.analysis.jaxpr_audit import inject_spec

SCHEMA = "isotope-gradaudit/v1"

CLASS_DIFFERENTIABLE = "differentiable"
CLASS_DEAD = "gradient-dead"
CLASS_CONSTANT = "trace-constant"

#: the ten traced invars of the engine's universal member body
#: (engine.Simulator._member_fn -> member_scan), in position order;
#: DESIGN_PARAMS entries name these to say where their taint seeds
GRAD_INVARS = (
    "key",
    "offered_qps",
    "pace_gap",
    "nominal_gap",
    "win_lo",
    "win_hi",
    "visits_pc",
    "phase_windows",
    "cpu_scale",
    "err_scale",
)

#: primitives with no usable derivative: live taint crossing one dies
KILLER_PRIMITIVES = frozenset({
    "floor",
    "ceil",
    "round",
    "sign",
    "stop_gradient",
    "argmax",
    "argmin",
})

#: sub-jaxpr call-like primitives inlined under their own path segment
_CALL_PRIMITIVES = (
    "pjit",
    "closed_call",
    "core_call",
    "remat2",
    "checkpoint",
    "custom_jvp_call",
    "custom_vjp_call",
    "custom_jvp_call_jaxpr",
    "custom_vjp_call_jaxpr",
)

#: the SLO objectives ``optimize`` would target (RunSummary leaves):
#: mean latency, quantiles (histogram), error share
OBJECTIVE_LEAVES = ("latency_sum", "latency_hist", "error_count")

_MAX_FIXPOINT_SWEEPS = 30


def _is_inexact(aval) -> bool:
    import jax.numpy as jnp

    dt = getattr(aval, "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jnp.inexact)


def _merge(a: tuple, b: tuple) -> tuple:
    """Join two taint values ``(live, killer)``: live wins; a dead
    result keeps the first recorded killer."""
    live = a[0] or b[0]
    return (live, None if live else (a[1] or b[1]))


class _TaintState:
    """Cross-jaxpr accumulators of one analysis run."""

    def __init__(self):
        # knob -> ordered distinct kill sites (where live taint died)
        self.kills: Dict[str, Dict[str, None]] = {}
        # knob -> ordered distinct float scatter-add sites crossed live
        self.scatter: Dict[str, Dict[str, None]] = {}

    def record_kill(self, knob: str, site: str) -> None:
        self.kills.setdefault(knob, {})[site] = None

    def record_scatter(self, knob: str, site: str) -> None:
        self.scatter.setdefault(knob, {})[site] = None


def _analyze(jaxpr, in_taints, path: str, state: _TaintState):
    """Forward taint over one (sub-)jaxpr.

    ``in_taints[i]`` is the taint of ``jaxpr.invars[i]`` — a dict
    ``knob -> (live, killer)``.  Returns the taints of the outvars.
    """
    import jax

    Literal = jax.core.Literal

    env: Dict[object, dict] = {}

    def read(a) -> dict:
        if isinstance(a, Literal):
            return {}
        return env.get(a, {})

    def write(v, t: dict) -> None:
        if t:
            env[v] = dict(t)

    def mergev(v, t: dict) -> None:
        cur = env.get(v, {})
        new = dict(cur)
        for k, tv in t.items():
            new[k] = _merge(cur[k], tv) if k in cur else tv
        if new:
            env[v] = new

    def live_bits(t: dict) -> dict:
        return {k: v[0] for k, v in t.items()}

    for v, t in zip(jaxpr.invars, in_taints):
        write(v, t)

    for eqn in jaxpr.eqns:
        prim = str(eqn.primitive)
        site = f"{path}{prim}"
        ins = [read(a) for a in eqn.invars]

        if prim == "scan":
            inner = eqn.params["jaxpr"]
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            cur = [dict(t) for t in ins]
            outs = []
            for _ in range(_MAX_FIXPOINT_SWEEPS):
                outs = _analyze(
                    inner.jaxpr, cur, path + "scan/body/", state,
                )
                changed = False
                for i in range(ncar):
                    slot = nc + i
                    before = live_bits(cur[slot])
                    for k, tv in outs[i].items():
                        cur[slot][k] = (
                            _merge(cur[slot][k], tv)
                            if k in cur[slot] else tv
                        )
                    if live_bits(cur[slot]) != before:
                        changed = True
                if not changed:
                    break
            # outs: ncar carry outputs then the stacked ys
            for v, t in zip(eqn.outvars, outs):
                write(v, t)
            continue

        if prim == "while":
            cj = eqn.params["cond_jaxpr"]
            bj = eqn.params["body_jaxpr"]
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            cconsts = ins[:cn]
            bconsts = ins[cn:cn + bn]
            carry = [dict(t) for t in ins[cn + bn:]]
            for _ in range(_MAX_FIXPOINT_SWEEPS):
                outs = _analyze(
                    bj.jaxpr, bconsts + carry, path + "while/body/",
                    state,
                )
                changed = False
                for i, o in enumerate(outs):
                    before = live_bits(carry[i])
                    for k, tv in o.items():
                        carry[i][k] = (
                            _merge(carry[i][k], tv)
                            if k in carry[i] else tv
                        )
                    if live_bits(carry[i]) != before:
                        changed = True
                if not changed:
                    break
            # the predicate gates the trip count: knobs tainting it
            # influence the outputs non-differentiably
            pred_outs = _analyze(
                cj.jaxpr, cconsts + carry, path + "while/cond/", state,
            )
            pred_t = pred_outs[0] if pred_outs else {}
            dead = {
                k: (False, tv[1] or f"{path}while/cond")
                for k, tv in pred_t.items()
            }
            for v, t in zip(eqn.outvars, carry):
                write(v, t)
                if dead:
                    mergev(v, dead)
            continue

        if prim == "cond":
            branches = eqn.params["branches"]
            pred_t = ins[0]
            for br in branches:
                outs = _analyze(
                    br.jaxpr, [dict(t) for t in ins[1:]],
                    path + "cond/branch/", state,
                )
                for v, t in zip(eqn.outvars, outs):
                    mergev(v, t)
            if pred_t:
                dead = {
                    k: (False, tv[1] or site)
                    for k, tv in pred_t.items()
                }
                for v in eqn.outvars:
                    mergev(v, dead)
            continue

        if prim in _CALL_PRIMITIVES:
            inner = (
                eqn.params.get("jaxpr")
                or eqn.params.get("call_jaxpr")
                or eqn.params.get("fun_jaxpr")
            )
            if inner is not None:
                sub = getattr(inner, "jaxpr", inner)
                nm = eqn.params.get("name") or prim
                outs = _analyze(sub, ins, path + f"{nm}/", state)
                for v, t in zip(eqn.outvars, outs):
                    write(v, t)
                continue

        if prim == "select_n":
            # invars[0] is the predicate; the rest are branches.  A
            # knob live in a branch stays live (a smooth path exists);
            # a knob arriving ONLY through the predicate is routing —
            # dead, named after the comparison that fed the predicate.
            pred, br_ins = ins[0], ins[1:]
            out_t: dict = {}
            knobs = set()
            for t in ins:
                knobs |= set(t)
            for k in knobs:
                br_ts = [t[k] for t in br_ins if k in t]
                if br_ts:
                    tv = br_ts[0]
                    for o in br_ts[1:]:
                        tv = _merge(tv, o)
                    if not tv[0] and k in pred and tv[1] is None:
                        tv = (False, pred[k][1] or site)
                elif k in pred:
                    pk = pred[k][1]
                    feeder = pk.rsplit("/", 1)[-1] if pk else "pred"
                    kill_site = f"{site}←{feeder}"
                    if pred[k][0]:
                        state.record_kill(k, kill_site)
                    tv = (False, kill_site)
                else:
                    continue
                out_t[k] = tv
            for v in eqn.outvars:
                write(v, out_t)
            continue

        # generic propagation: union the input taints; liveness
        # survives only grad-defined primitives onto inexact outputs
        union: dict = {}
        for t in ins:
            for k, tv in t.items():
                union[k] = _merge(union[k], tv) if k in union else tv
        if not union:
            continue
        kills = prim in KILLER_PRIMITIVES
        if prim in ("scatter-add", "scatter_add") and _is_inexact(
            eqn.outvars[0].aval
        ):
            for k, tv in union.items():
                if tv[0]:
                    state.record_scatter(k, site)
        for v in eqn.outvars:
            out_t = {}
            for k, tv in union.items():
                if tv[0]:
                    if kills or not _is_inexact(v.aval):
                        state.record_kill(k, site)
                        out_t[k] = (False, site)
                    else:
                        out_t[k] = (True, None)
                else:
                    out_t[k] = tv
            write(v, out_t)

    return [read(v) for v in jaxpr.outvars]


def grad_trace_entry(sim, load, num_requests: int = 8):
    """``(ClosedJaxpr, out_shapes, n)`` of the knob-armed engine body.

    Unlike ``jaxpr_audit.trace_entry`` this traces the universal
    member body with the jitter scales armed (``jittered=True``), so
    ``cpu_scale`` / ``err_scale`` are traced invars the taint can seed
    at — the plain entry bakes them away.  Abstract arguments only:
    nothing touches a device, no XLA compile.
    """
    import jax
    import jax.numpy as jnp

    from isotope_tpu.sim.config import CLOSED_LOOP

    kind = load.kind
    connections = load.connections if kind == CLOSED_LOOP else 0
    n = max(int(num_requests), 1)
    if kind == CLOSED_LOOP:
        n = max(n, connections)
    fn = sim._member_fn(
        n, 1, kind, connections, False, False, True,
    )

    if "graddead" in inject_spec():
        inner = fn

        def fn(key, oq, pg, ng, wl, wh, vp, pw, cs, es):  # noqa: F811
            # seeded defect: quantize cpu_scale through floor before
            # it reaches the engine — cpu_time_s must flip to
            # gradient-dead with `floor` as the named killer
            cs = jnp.floor(cs * 1048576.0) / 1048576.0
            return inner(key, oq, pg, ng, wl, wh, vp, pw, cs, es)

    sds = jax.ShapeDtypeStruct
    f32 = jnp.float32
    P = int(sim._phase_starts.shape[0]) * sim._num_combos
    S = sim.compiled.num_services
    W = sim._num_windows
    args = (
        sds((2,), jnp.uint32),       # key
        sds((), f32), sds((), f32),  # offered_qps, pace_gap
        sds((), f32),                # nominal_gap
        sds((), f32), sds((), f32),  # win_lo, win_hi
        sds((P, S), f32),            # visits_pc
        sds((2, W), f32),            # phase_windows
        sds((), f32), sds((), f32),  # cpu_scale, err_scale
    )
    closed, shapes = jax.make_jaxpr(fn, return_shape=True)(*args)
    return closed, shapes, n


def _leaf_names(shapes) -> List[str]:
    """Objective-output leaf names aligned with the jaxpr outvars."""
    import jax.tree_util as jtu

    leaves = jtu.tree_flatten_with_path(shapes)[0]
    fields = getattr(type(shapes), "_fields", None)
    if fields is not None and len(leaves) == len(fields):
        return list(fields)
    return [
        jtu.keystr(p).lstrip(".") or f"out{i}"
        for i, (p, _) in enumerate(leaves)
    ]


def analyze_design_taint(closed_jaxpr, shapes) -> dict:
    """Run the taint analysis and classify every registered knob.

    Returns the ``isotope-gradaudit/v1`` body (sans topology header):
    per-knob class / live outputs / kill sites / scatter crossings,
    plus the per-objective live-knob map.
    """
    from isotope_tpu.sim.config import DESIGN_PARAMS

    jaxpr = closed_jaxpr.jaxpr
    state = _TaintState()
    in_taints: List[dict] = [{} for _ in jaxpr.invars]
    for p in DESIGN_PARAMS:
        for invar in p.invars:
            idx = GRAD_INVARS.index(invar)
            if idx < len(in_taints):
                in_taints[idx][p.name] = (True, None)
    out_taints = _analyze(jaxpr, in_taints, "", state)
    names = _leaf_names(shapes)
    if len(names) != len(out_taints):  # pragma: no cover - guard
        names = [f"out{i}" for i in range(len(out_taints))]

    knobs = []
    live_by_leaf: Dict[str, List[str]] = {nm: [] for nm in names}
    for p in DESIGN_PARAMS:
        if not p.traced:
            knobs.append({
                "name": p.name,
                "class": CLASS_CONSTANT,
                "doc": p.doc,
                "invars": [],
                "constant_site": p.constant_site,
                "live_outputs": [],
                "kills": [],
                "scatter_sites": [],
                "partial": p.partial,
            })
            continue
        live_outputs = []
        dead_killers: Dict[str, None] = {}
        for nm, t in zip(names, out_taints):
            tv = t.get(p.name)
            if tv is None:
                continue
            if tv[0]:
                live_outputs.append(nm)
                live_by_leaf[nm].append(p.name)
            elif tv[1]:
                dead_killers[tv[1]] = None
        kills = list(state.kills.get(p.name, {}))
        # prefer kill sites observed on output-reaching paths
        ordered_kills = list(dead_killers) + [
            k for k in kills if k not in dead_killers
        ]
        knobs.append({
            "name": p.name,
            "class": (
                CLASS_DIFFERENTIABLE if live_outputs else CLASS_DEAD
            ),
            "doc": p.doc,
            "invars": list(p.invars),
            "constant_site": p.constant_site,
            "live_outputs": live_outputs,
            "kills": ordered_kills,
            "scatter_sites": list(state.scatter.get(p.name, {})),
            "partial": p.partial,
        })

    vacuous = [
        nm for nm in OBJECTIVE_LEAVES
        if nm in live_by_leaf and not live_by_leaf[nm]
    ]
    return {
        "schema": SCHEMA,
        "invars": list(GRAD_INVARS),
        "knobs": knobs,
        "objectives": {
            nm: sorted(live_by_leaf[nm]) for nm in names
        },
        "vacuous_objectives": vacuous,
    }


def grad_findings(doc: dict) -> List[Finding]:
    """VET-G findings from one gradient-audit document."""
    findings: List[Finding] = []
    for k in doc["knobs"]:
        if k["class"] == CLASS_CONSTANT:
            findings.append(Finding(
                "VET-G002", SEV_INFO,
                f"design knob {k['name']!r} is a trace constant: "
                f"baked into {k['constant_site'] or 'the jaxpr'}; "
                "every new value recompiles and no relaxation "
                "recovers a gradient",
                path=k["constant_site"],
            ))
            continue
        if k["class"] == CLASS_DEAD:
            if k["kills"]:
                killer = k["kills"][0]
                findings.append(Finding(
                    "VET-G001", SEV_WARN,
                    f"design knob {k['name']!r} is gradient-dead: "
                    "every tainted path to the objective crosses a "
                    f"non-differentiable primitive (first kill: "
                    f"{killer})",
                    path=killer,
                ))
            else:
                findings.append(Finding(
                    "VET-G001", SEV_WARN,
                    f"design knob {k['name']!r} is gradient-dead: "
                    "its traced value never reaches an objective "
                    "output under this configuration (the knob is "
                    "inert here, not relaxable)",
                    path=",".join(k["invars"]),
                ))
        for site in k["scatter_sites"]:
            findings.append(Finding(
                "VET-G003", SEV_INFO,
                f"design knob {k['name']!r} crosses a float "
                "scatter-add: its gradient accumulates in "
                "backend-dependent order",
                path=site,
            ))
    if doc["vacuous_objectives"]:
        findings.append(Finding(
            "VET-G004", SEV_WARN,
            "objective output(s) with zero live design-taint: "
            f"{', '.join(doc['vacuous_objectives'])} — planning over "
            "them is vacuous until a soft relaxation replaces their "
            "integer/comparison paths",
            path=",".join(doc["vacuous_objectives"]),
        ))
    return findings


def audit_grad(sim, load, num_requests: int = 8
               ) -> Tuple[List[Finding], dict]:
    """The full gradient audit of one Simulator under one load."""
    from isotope_tpu.analysis.jaxpr_audit import iter_eqns

    closed, shapes, n = grad_trace_entry(sim, load, num_requests)
    doc = analyze_design_taint(closed, shapes)
    doc["traced_requests"] = n
    doc["eqns_walked"] = sum(1 for _ in iter_eqns(closed))
    doc["classes"] = {
        k["name"]: k["class"] for k in doc["knobs"]
    }
    return grad_findings(doc), doc
