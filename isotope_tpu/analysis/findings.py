"""Vet findings: structured diagnostics with rule ids and severities.

Every defect the static passes (topology linter, jaxpr auditor,
pre-flight cost model — see :mod:`isotope_tpu.analysis`) can report is
a :class:`Finding`: a stable rule id, a severity, the config/program
path it anchors to, and a message.  The :class:`Report` aggregates
findings across passes, applies suppressions, and decides the exit
status — ``vet`` exits nonzero on errors, ``strict`` mode promotes
warnings to blocking.

Rule ids are stable API (suppression patterns, bench gates, and alert
rules key on them): never renumber an existing rule; retire ids by
leaving a tombstone in :data:`RULES`.
"""
from __future__ import annotations

import dataclasses
import fnmatch
import json
from typing import Dict, Iterable, List, Optional, Sequence

SEV_ERROR = "error"
SEV_WARN = "warn"
SEV_INFO = "info"

_SEV_ORDER = {SEV_ERROR: 0, SEV_WARN: 1, SEV_INFO: 2}

#: rule id -> one-line description (the README table is generated from
#: the same text; suppression validation checks membership here)
RULES: Dict[str, str] = {
    # -- topology / service-graph linter (host-only) ----------------------
    "VET-T001": "service is unreachable from the entrypoint",
    "VET-T002": "call graph contains a cycle reachable from the "
                "entrypoint (the unroll cannot terminate)",
    "VET-T003": "no entrypoint service (or unknown --entry override)",
    "VET-T004": "numReplicas < 1: a zero-capacity queueing station",
    "VET-T005": "errorRate >= 100%: the service fails every request",
    "VET-T006": "payload size exceeds the plausible wire budget",
    "VET-T007": "hop count forces the request block under its floor — "
                "event tensors exceed the HBM element budget",
    "VET-T008": "bucket-plan padding waste exceeds level_bucket_waste",
    # -- policy / rollout / lb config linter --------------------------------
    "VET-T010": "circuit-breaker cap (max_pending / max_connections) "
                "sits below the steady-state queue depth or "
                "concurrency at the planned qps — the breaker sheds "
                "healthy traffic permanently",
    "VET-T011": "autoscaler min_replicas > max_replicas: the "
                "desired-count clamp is empty",
    "VET-T012": "retry_budget of 0 while calls to the service set "
                "retries > 0: every retry is suppressed",
    "VET-T013": "autoscaler sync_period shorter than the timeline "
                "window: the control loop reads stale signals",
    "VET-T014": "policies block does not decode",
    "VET-T015": "rollouts block does not decode, or a step schedule "
                "is not strictly increasing / never reaches 100%",
    "VET-T016": "canary bake shorter than the recorder window: a step "
                "can promote before one completed window of it",
    "VET-T017": "canary gate min_samples is unreachable within one "
                "bake at the planned qps (the rollout holds forever)",
    "VET-T018": "canary overrides declared without a step schedule: "
                "the rollout never actuates",
    "VET-T019": "lb choices_d exceeds the replica count: power-of-d "
                "degenerates to full-pool least-request",
    "VET-T020": "ring_hash with a single replica: hash stickiness is "
                "a no-op",
    "VET-T021": "lb panic_threshold >= 1.0 or unreachable via outlier "
                "ejection",
    "VET-T022": "lb entries do not decode",
    # -- experiment-config linter -----------------------------------------
    "VET-C001": "topology file is missing or unreadable",
    "VET-C002": "duplicate run labels in the sweep grid",
    "VET-C003": "chaos/churn schedule targets an unknown service or "
                "matches no churnable edge",
    "VET-C004": "chaos/churn/mtls schedule lies beyond the run duration "
                "(it never fires)",
    "VET-C005": "open-loop qps meets or exceeds the static capacity "
                "(unstable queues)",
    "VET-C006": "level falls back to the residual sparse call-slot "
                "path (script wider than the tile cap) — un-tiled "
                "slots run the serial gather/cumsum sweep",
    # -- jaxpr auditor ------------------------------------------------------
    "VET-J001": "host callback / device-to-host sync primitive in the "
                "hot path",
    "VET-J002": "float64/complex128 dtype leak in the traced program",
    "VET-J003": "float scatter-add accumulation (order-nondeterministic "
                "on parallel backends)",
    "VET-J004": "executable-cache signature component is unhashable or "
                "has an id-based repr (retrace hazard: the AOT cache "
                "key changes every process)",
    # -- pre-flight cost model ---------------------------------------------
    "VET-M001": "memory estimate exceeds device capacity on every "
                "on-device ladder rung (predictable OOM)",
    "VET-M002": "memory estimate exceeds device capacity at the default "
                "rung; the resilience ladder should start degraded",
    "VET-M003": "timeline recorder carries (O(services x windows) per "
                "scan block) take a large share of device capacity; "
                "the window planner will clamp or widen windows",
    # -- scenario ensembles (sim/ensemble.py) ------------------------------
    "VET-T023": "ensemble spec has zero members or duplicate member "
                "seeds (duplicated members are bit-identical copies, "
                "not extra Monte Carlo samples)",
    "VET-M004": "ensemble members x peak-bytes exceed device capacity; "
                "the fleet runs in pre-computed member chunks",
    # -- chaos fleets (sim/splitting.py, PR 15) ----------------------------
    "VET-T024": "importance-splitting config is undecodable, keeps no "
                "(or every) member per level, or budgets fewer than "
                "one survivor per level",
    "VET-T025": "protected fleet members x (peak-bytes + stacked "
                "policy/rollout/timeline carry) exceed device "
                "capacity; the fleet runs in carry-aware member "
                "chunks",
    # -- on-device config search (sim/search.py) ---------------------------
    "VET-T026": "search spec is undecodable, or the bracket is "
                "degenerate (population cannot support the rungs, "
                "non-power-of-eta padding, rank needs uncarried "
                "timelines)",
    "VET-M005": "search bracket's widest rung x peak-bytes exceed "
                "device capacity; the rung runs in member chunks",
    "VET-M006": "observed fleet members x (peak-bytes + stacked "
                "blame/timeline carry) exceed device capacity; the "
                "fleet runs in member chunks",
    # -- trace-driven ingest (ingest/, analysis/topo_lint.lint_ingest) ----
    "VET-T027": "fitted qps schedule exceeds the fitted capacity at "
                "the observed window peak (the reconstructed replay "
                "will saturate where the source mesh did not)",
    "VET-T028": "degenerate fit: a service with zero observed samples "
                "was emitted into the topology (its timing/error "
                "knobs are defaults, not measurements)",
    # -- gradient audit (analysis/grad_audit.py) ---------------------------
    "VET-G001": "design knob is gradient-dead: every tainted path to "
                "the objective crosses a non-differentiable primitive "
                "(the finding names the killer and its jaxpr path)",
    "VET-G002": "design knob is a trace constant: it never enters the "
                "jaxpr, so every new value recompiles and no soft "
                "relaxation recovers a gradient",
    "VET-G003": "design knob's gradient crosses a float scatter-add "
                "(accumulation order is backend-dependent)",
    "VET-G004": "objective output carries zero live design-taint: "
                "planning over it is vacuous until a soft relaxation "
                "replaces its integer/comparison paths",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One static-analysis diagnostic."""

    rule: str          # stable id, e.g. "VET-T001"
    severity: str      # error | warn | info
    message: str
    path: str = ""     # config path ("services[3].script[1]") or site

    def render(self) -> str:
        where = f" {self.path}" if self.path else ""
        return f"{self.severity:5s} {self.rule}{where}: {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def suppression_patterns(spec: Optional[str]) -> List[str]:
    """Parse a comma-separated suppression spec (``--suppress`` /
    ``$ISOTOPE_VET_SUPPRESS``) into fnmatch patterns over rule ids."""
    if not spec:
        return []
    pats = [p.strip() for p in spec.split(",") if p.strip()]
    for p in pats:
        if "*" not in p and "?" not in p and p not in RULES:
            raise ValueError(
                f"unknown vet rule in suppression: {p!r} "
                f"(known rules: {', '.join(sorted(RULES))})"
            )
    return pats


class Report:
    """Aggregated vet findings plus the suppression bookkeeping."""

    def __init__(self, suppress: Sequence[str] = ()):
        self.findings: List[Finding] = []
        self.suppressed: List[Finding] = []
        self._patterns = list(suppress)
        self.meta: Dict[str, object] = {}  # cost estimates, rung advice

    def add(self, finding: Finding) -> None:
        if any(fnmatch.fnmatchcase(finding.rule, p)
               for p in self._patterns):
            self.suppressed.append(finding)
        else:
            self.findings.append(finding)

    def extend(self, findings: Iterable[Finding]) -> None:
        for f in findings:
            self.add(f)

    def by_severity(self, severity: str) -> List[Finding]:
        return [f for f in self.findings if f.severity == severity]

    @property
    def errors(self) -> List[Finding]:
        return self.by_severity(SEV_ERROR)

    @property
    def warnings(self) -> List[Finding]:
        return self.by_severity(SEV_WARN)

    def blocking(self, strict: bool = False,
                 nonblocking_rules: Sequence[str] = ()) -> List[Finding]:
        """The findings that make vet fail: errors, plus warns under
        ``strict``.  ``nonblocking_rules`` exempts rules another layer
        already handles (the runner exempts the VET-M* memory rules
        when the degradation ladder is armed — the rung pre-selection
        IS the recovery, so the finding informs instead of blocking).
        """
        sevs = (SEV_ERROR, SEV_WARN) if strict else (SEV_ERROR,)
        return [
            f for f in self.findings
            if f.severity in sevs and f.rule not in nonblocking_rules
        ]

    def sorted(self) -> List[Finding]:
        return sorted(
            self.findings,
            key=lambda f: (_SEV_ORDER.get(f.severity, 9), f.rule, f.path),
        )

    def summary_line(self) -> str:
        n_err = len(self.errors)
        n_warn = len(self.warnings)
        n_info = len(self.by_severity(SEV_INFO))
        extra = (
            f", {len(self.suppressed)} suppressed" if self.suppressed
            else ""
        )
        return (
            f"vet: {n_err} error(s), {n_warn} warning(s), "
            f"{n_info} info{extra}"
        )

    def to_dict(self) -> dict:
        return {
            "findings": [f.to_dict() for f in self.sorted()],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "meta": self.meta,
            "summary": self.summary_line(),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)
