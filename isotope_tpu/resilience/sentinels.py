"""Numeric sentinels: validate run outputs before they reach artifacts.

A NaN escaping one segment of the tensor program used to propagate
silently into histograms, quantiles, and the benchmark CSV — or crash
a downstream ``int()`` hours later.  The sentinels check the O(buckets)
summary (never the per-request tensors) right after the run blocks:

- every scalar / histogram field is finite;
- latencies and counts are non-negative (a negative latency means the
  downward start-time pass went wrong, not that the workload is odd).

Violations raise :class:`NumericSentinelError` — DETERMINISTIC in the
taxonomy: the same trace reproduces the same NaN, so the supervisor
fails the case instead of retrying it.  Localization to the offending
segment/bucket happens in ``--telemetry=detail`` mode, where the
engine's per-segment fences see concrete arrays (telemetry.core
``segment_fence`` records ``numeric_sentinel{segment=...}`` gauges).
"""
from __future__ import annotations

from typing import Iterable, Tuple

import numpy as np

from isotope_tpu import telemetry
from isotope_tpu.resilience.taxonomy import NumericSentinelError

#: summary fields that must be finite AND non-negative
_NONNEG_FIELDS = (
    "count", "error_count", "hop_events", "latency_sum", "latency_m2",
    "latency_min", "latency_max", "latency_hist", "end_max",
    "win_count", "win_error_count", "win_latency_hist",
)


def _violations(named: Iterable[Tuple[str, object]],
                nonneg: bool) -> list:
    bad = []
    for name, v in named:
        if v is None:
            continue
        a = np.asarray(v)
        if not np.issubdtype(a.dtype, np.floating):
            continue
        # win_hi is +inf when the trim window is off: finite-or-+inf is
        # the contract for bounds; NaN is never acceptable
        if np.isnan(a).any():
            bad.append(f"{name}: NaN")
        elif np.isneginf(a).any():
            bad.append(f"{name}: -inf")
        elif nonneg and (a < 0).any():
            bad.append(f"{name}: negative ({float(a.min()):g})")
    return bad


def check_summary(summary, label: str = "run") -> None:
    """Validate a :class:`~isotope_tpu.sim.summary.RunSummary`."""
    fields = summary._asdict()
    bad = _violations(
        ((n, fields.get(n)) for n in _NONNEG_FIELDS), nonneg=True
    )
    # utilization may legitimately exceed 1 (overload) but never NaN
    bad += _violations((("utilization", fields.get("utilization")),),
                       nonneg=True)
    if bad:
        telemetry.counter_inc("numeric_sentinel_violations")
        raise NumericSentinelError(
            f"numeric sentinel tripped on {label}: {'; '.join(bad)} "
            "(re-run with --telemetry=detail to localize the offending "
            "segment)"
        )


def check_results(res, label: str = "run") -> None:
    """Validate raw :class:`~isotope_tpu.sim.engine.SimResults`
    (the non-summary entry points: ``Simulator.run``, tracing)."""
    bad = _violations(
        (
            ("client_latency", res.client_latency),
            ("client_start", res.client_start),
            ("hop_latency", res.hop_latency),
            ("utilization", res.utilization),
        ),
        nonneg=True,
    )
    if bad:
        telemetry.counter_inc("numeric_sentinel_violations")
        raise NumericSentinelError(
            f"numeric sentinel tripped on {label}: {'; '.join(bad)} "
            "(re-run with --telemetry=detail to localize the offending "
            "segment)"
        )
