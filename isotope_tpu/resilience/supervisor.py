"""The run supervisor: bounded retry, backoff, and the OOM ladder.

Two primitives, composed by the sweep driver (runner/run.py):

- :func:`call_with_retries` retries TRANSIENT failures with exponential
  backoff and *deterministic* jitter (hash of site + attempt — two
  resumed sweeps desynchronize their retry storms identically, and
  tests reproduce exact schedules);
- :func:`run_ladder` walks an ordered list of execution rungs, moving
  down one rung per RESOURCE_EXHAUSTED failure.  :func:`execution_rungs`
  builds the standard ladder for a run:

  sharded:        sharded -> sharded half-block -> single-device
                  (per-shard emulation, collectives replayed on host)
                  -> CPU eager
  single-device:  scan -> half-block -> CPU eager

  Every descent increments ``degradations_total`` (Prometheus:
  ``isotope_engine_degradations_total``); the rung that finally served
  the run is recorded as ``degraded_to`` in telemetry metadata and run
  records.  DETERMINISTIC failures propagate immediately — the caller
  records the case as failed and the sweep continues.
"""
from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import Callable, List, Optional, Sequence, Tuple

from isotope_tpu import telemetry
from isotope_tpu.resilience.taxonomy import (
    RESOURCE_EXHAUSTED,
    TRANSIENT,
    classify,
)

ENV_MAX_RETRIES = "ISOTOPE_MAX_RETRIES"
ENV_NO_DEGRADE = "ISOTOPE_NO_DEGRADE"


@dataclasses.dataclass(frozen=True)
class ResiliencePolicy:
    """Knobs of the supervisor (CLI ``--max-retries`` / ``--no-degrade``,
    env ``ISOTOPE_MAX_RETRIES`` / ``ISOTOPE_NO_DEGRADE``)."""

    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    degrade: bool = True
    # injectable clock for tests (sleep=lambda s: None)
    sleep: Callable[[float], None] = time.sleep

    @classmethod
    def from_env(
        cls,
        max_retries: Optional[int] = None,
        degrade: Optional[bool] = None,
    ) -> "ResiliencePolicy":
        if max_retries is None:
            max_retries = int(os.environ.get(ENV_MAX_RETRIES, "3"))
        if degrade is None:
            degrade = os.environ.get(ENV_NO_DEGRADE, "").strip().lower() \
                not in ("1", "true", "yes", "on")
        return cls(max_retries=max_retries, degrade=degrade)


def backoff_seconds(site: str, attempt: int,
                    policy: ResiliencePolicy) -> float:
    """Exponential backoff with deterministic jitter in [0.5x, 1.0x].

    The jitter fraction is a hash of (site, attempt): reproducible
    run-to-run, yet decorrelated across sites so N phases retrying the
    same hiccup don't stampede in lockstep.
    """
    base = min(
        policy.backoff_base_s * (2.0 ** attempt), policy.backoff_cap_s
    )
    h = hashlib.sha256(f"{site}:{attempt}".encode()).digest()
    frac = int.from_bytes(h[:4], "big") / 2**32  # [0, 1)
    return base * (0.5 + 0.5 * frac)


def call_with_retries(fn: Callable[[], object], site: str,
                      policy: ResiliencePolicy):
    """Run ``fn``, retrying TRANSIENT failures up to ``max_retries``.

    RESOURCE_EXHAUSTED and DETERMINISTIC failures propagate to the
    caller (the ladder / the sweep driver decide what happens next).
    """
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:
            if classify(e) != TRANSIENT or attempt >= policy.max_retries:
                raise
            delay = backoff_seconds(site, attempt, policy)
            telemetry.counter_inc("retries_total")
            telemetry.counter_inc(f"retries.{site}")
            telemetry.phase_add("resilience.backoff", delay)
            policy.sleep(delay)
            attempt += 1


def run_ladder(
    rungs: Sequence[Tuple[str, Callable[[], object]]],
    policy: ResiliencePolicy,
    site_prefix: str = "run",
) -> Tuple[object, Optional[str]]:
    """Execute the first rung that survives, degrading on OOM.

    ``rungs`` is an ordered ``(name, thunk)`` list; rung 0 is the
    undegraded path.  Each rung gets its own transient-retry budget.
    Returns ``(result, degraded_to)`` with ``degraded_to=None`` when
    rung 0 served the run.  With ``policy.degrade`` off (or rungs
    exhausted) the RESOURCE_EXHAUSTED failure propagates.
    """
    last = len(rungs) - 1
    for level, (name, thunk) in enumerate(rungs):
        try:
            out = call_with_retries(
                thunk, site=f"{site_prefix}.{name}", policy=policy
            )
        except Exception as e:
            if (
                classify(e) == RESOURCE_EXHAUSTED
                and policy.degrade
                and level < last
            ):
                telemetry.counter_inc("degradations_total")
                telemetry.gauge_set("engine_degraded_level", level + 1)
                continue
            raise
        if level > 0:
            telemetry.set_meta("degraded_to", name)
        return out, (name if level > 0 else None)
    raise AssertionError("run_ladder: empty rung list")  # pragma: no cover


def execution_rungs(
    sim,
    sharded,
    use_sharded: bool,
    load,
    num_requests: int,
    key,
    block_size: int,
    collector=None,
    trim: bool = True,
) -> List[Tuple[str, Callable[[], object]]]:
    """The standard degradation ladder for one sweep case.

    Every thunk blocks on the result and runs the numeric sentinels, so
    deferred device errors AND poisoned outputs surface inside the
    supervised scope (an async OOM otherwise escapes to the caller
    after the ladder already returned).  The half-block rung halves the
    per-shard request chunk (same request count, twice the scan steps,
    half the live event-tensor footprint); the single-device rung
    replays the sharded program shard-by-shard on one device (bit-
    compatible streams, collectives merged on host); CPU eager
    (``jax.disable_jit``) is the rung of last resort — it also survives
    compile-time OOM.
    """
    import contextlib

    import jax

    from isotope_tpu.resilience import sentinels

    def _finish(summary):
        jax.block_until_ready(summary.count)
        sentinels.check_summary(summary)
        return summary

    half = max(256, block_size // 2)
    if use_sharded:
        def _sharded(block):
            return lambda: _finish(
                sharded.run(load, num_requests, key, block_size=block,
                            trim=trim)
            )

        def _emulated(eager: bool):
            def thunk():
                ctx = (
                    jax.disable_jit() if eager
                    else contextlib.nullcontext()
                )
                with ctx:
                    return _finish(sharded.run_emulated(
                        load, num_requests, key, block_size=block_size,
                        trim=trim,
                    ))
            return thunk

        return [
            ("sharded", _sharded(block_size)),
            ("sharded-half-block", _sharded(half)),
            ("single-device", _emulated(eager=False)),
            ("cpu-eager", _emulated(eager=True)),
        ]

    def _scan(block):
        return lambda: _finish(
            sim.run_summary(load, num_requests, key, block_size=block,
                            collector=collector, trim=trim)
        )

    def _eager():
        with jax.disable_jit():
            return _finish(
                sim.run_summary(load, num_requests, key, block_size=half,
                                collector=collector, trim=trim)
            )

    return [
        ("scan", _scan(block_size)),
        ("half-block", _scan(half)),
        ("cpu-eager", _eager),
    ]
