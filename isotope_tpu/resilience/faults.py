"""Deterministic engine-level fault injection (chaos for the engine).

The workload simulator already has chaos schedules (replica killers,
outages); this module aims the same discipline at the ENGINE: every
recovery path in the supervisor — retry, degradation ladder, cache
quarantine, numeric sentinels — must be exercisable on CPU in tests
and smoke targets, not just on a TPU that happens to OOM.

Spec syntax (``$ISOTOPE_FAULT_INJECT`` or :func:`install`)::

    ISOTOPE_FAULT_INJECT=oom:sharded.gather:1,nan:segment:2

comma-separated ``kind:site[:arg]`` entries:

- ``oom:<site>[:count]`` — raise a ``RESOURCE_EXHAUSTED``-shaped fault
  the first ``count`` times ``check(site)`` runs (default 1);
- ``transient:<site>[:count]`` — same, ``UNAVAILABLE``-shaped;
- ``corrupt:<site>[:count]`` — same, shaped like a corrupted
  persistent-cache entry (unpickle/digest failure);
- ``nan:segment:<index>`` — poison the output of tensor-program
  segment ``<index>`` with NaN at trace time (``arg`` is the segment
  index, not a count; exercises the numeric sentinels and detail-mode
  localization);
- ``stuck:policies.stuck_breaker`` — BEHAVIORAL chaos against the
  policy co-sim (sim/policies.py): a tripped circuit breaker never
  closes (its shed fraction only ratchets up);
- ``lag:policies.autoscaler_lag[:N]`` — the autoscaler control loop
  misses its first ``N`` sync periods (default 1) — the
  HPA-controller-restart failure mode;
- ``degrade:lb.degraded_backend[:B]`` — BEHAVIORAL chaos against the
  load-balancing laws (sim/lb.py): backend ``B``'s (default 0)
  effective attraction weight silently collapses to 1% — the classic
  gray-failure LB scenario (a ring-hash arc shrinks, wrr skips the
  pod) the profile-free least_request law routes around.

Sites are the supervisor's phase names: ``engine.build``,
``engine.run``, ``sharded.args_put``, ``sharded.compute``,
``sharded.dcn_collective`` (DCN-axis meshes only — the dropped
cross-host collective), ``sharded.gather``, ``cache.load``, plus the
policy-layer sites ``policies.stuck_breaker`` /
``policies.autoscaler_lag`` / ``lb.degraded_backend`` — the standard
kinds (oom / transient / corrupt) may target those too, raising a
taxonomy-classified fault at the protected run's entry so the
supervisor's retry path covers the policy AND lb layers.  ``check(site)`` is a dict lookup
returning immediately when no plan is armed — the default no-fault
path gains zero work and zero sync points.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from isotope_tpu import telemetry
from isotope_tpu.resilience.taxonomy import (
    DETERMINISTIC,
    RESOURCE_EXHAUSTED,
    TRANSIENT,
    InjectedFault,
)

ENV_FAULT_INJECT = "ISOTOPE_FAULT_INJECT"

KINDS = ("oom", "transient", "corrupt", "nan", "stuck", "lag",
         "degrade")

#: every instrumented ``check(site)`` call site in the engine — the
#: closed universe a spec may target.  A typo'd site used to parse
#: fine and silently never fire (the chaos test then "passed" without
#: exercising anything); now it raises at parse time with this list.
#: ``nan`` targets the pseudo-site ``segment`` (trace-time poisoning).
VALID_SITES = (
    "engine.build",
    "engine.run",
    "sharded.args_put",
    "sharded.compute",
    # fires only when the mesh has a DCN (slice) axis — the
    # dropped-cross-host-collective chaos site, so the transient
    # retry path for jaxlib DCN errors is testable without real hosts
    "sharded.dcn_collective",
    "sharded.gather",
    "cache.load",
    # the policy co-sim's own chaos sites (sim/policies.py): the
    # standard kinds raise classified faults at the policy run's
    # entry; the behavioral kinds ("stuck"/"lag") alter the traced
    # control program instead of raising
    "policies.stuck_breaker",
    "policies.autoscaler_lag",
    # the LB layer's chaos site (sim/lb.py): "degrade" collapses one
    # backend's weight in the traced profile; the standard kinds raise
    # classified faults at the protected run's entry like the policy
    # sites (the supervisor retry path is pinned for both)
    "lb.degraded_backend",
)

#: fault kind -> (message template, taxonomy class).  Messages imitate
#: the real failure text so the taxonomy classifies injected faults by
#: the same patterns as real ones (the explicit class is a backstop).
_SHAPES = {
    "oom": (
        "RESOURCE_EXHAUSTED: out of memory while running {site} "
        "(injected fault)",
        RESOURCE_EXHAUSTED,
    ),
    "transient": (
        "UNAVAILABLE: injected transient fault at {site}",
        TRANSIENT,
    ),
    "corrupt": (
        "corrupted persistent-cache entry at {site}: digest mismatch "
        "(injected fault, unpickling failed)",
        DETERMINISTIC,
    ),
}


@dataclasses.dataclass
class _Entry:
    kind: str
    site: str
    arg: int          # fire count (oom/transient/corrupt) or segment (nan)
    remaining: int


class FaultPlan:
    """A parsed, mutable injection plan (per-entry fire budgets)."""

    def __init__(self, entries: List[_Entry]):
        self.entries = entries
        self._by_site: Dict[str, List[_Entry]] = {}
        for e in entries:
            if e.kind not in ("nan", "stuck", "lag", "degrade"):
                self._by_site.setdefault(e.site, []).append(e)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        entries: List[_Entry] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) not in (2, 3):
                raise ValueError(
                    f"bad fault spec {part!r} (want kind:site[:arg])"
                )
            kind, site = bits[0].strip(), bits[1].strip()
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (one of {KINDS})"
                )
            arg = int(bits[2]) if len(bits) == 3 else (
                0 if kind in ("nan", "degrade") else 1
            )
            if kind == "nan" and site != "segment":
                raise ValueError(
                    f"nan faults target segments (nan:segment:<idx>), "
                    f"got site {site!r}"
                )
            if kind == "stuck" and site != "policies.stuck_breaker":
                raise ValueError(
                    "stuck faults target the breaker "
                    "(stuck:policies.stuck_breaker), got site "
                    f"{site!r}"
                )
            if kind == "lag" and site != "policies.autoscaler_lag":
                raise ValueError(
                    "lag faults target the autoscaler "
                    "(lag:policies.autoscaler_lag[:N]), got site "
                    f"{site!r}"
                )
            if kind == "degrade" and site != "lb.degraded_backend":
                raise ValueError(
                    "degrade faults target the lb layer "
                    "(degrade:lb.degraded_backend[:B]), got site "
                    f"{site!r}"
                )
            if kind != "nan" and site not in VALID_SITES:
                raise ValueError(
                    f"unknown fault site {site!r} — the plan would "
                    f"never fire (valid sites: "
                    f"{', '.join(VALID_SITES)})"
                )
            behavioral = kind in ("nan", "stuck", "lag", "degrade")
            entries.append(
                _Entry(kind=kind, site=site, arg=arg,
                       remaining=0 if behavioral else arg)
            )
        return cls(entries)

    def pop(self, site: str) -> Optional[_Entry]:
        """The first live entry at ``site``, its budget decremented."""
        for e in self._by_site.get(site, ()):
            if e.remaining > 0:
                e.remaining -= 1
                return e
        return None

    def nan_segment(self) -> Optional[int]:
        for e in self.entries:
            if e.kind == "nan":
                return e.arg
        return None

    def stuck_breaker(self) -> bool:
        return any(e.kind == "stuck" for e in self.entries)

    def autoscaler_lag(self) -> int:
        for e in self.entries:
            if e.kind == "lag":
                return max(e.arg, 1)
        return 0

    #: the collapse factor of a degraded backend's attraction weight —
    #: small but nonzero: the pod still advertises (gray failure), it
    #: just draws ~no traffic
    DEGRADED_FACTOR = 0.01

    def lb_degraded_backend(self):
        for e in self.entries:
            if e.kind == "degrade":
                return (max(e.arg, 0), self.DEGRADED_FACTOR)
        return None

    def signature(self) -> str:
        """Stable identity of the TRACE-AFFECTING part of the plan.

        The BEHAVIORAL kinds change the traced program — NaN poisoning
        bakes a poisoned constant into a segment, stuck/lag alter the
        policy control trace — so they participate; the executable
        caches must not share an altered program with a clean one,
        while pure host-side faults keep full cache reuse.
        """
        parts = []
        seg = self.nan_segment()
        if seg is not None:
            parts.append(f"nan:segment:{seg}")
        if self.stuck_breaker():
            parts.append("stuck:policies.stuck_breaker")
        lag = self.autoscaler_lag()
        if lag:
            parts.append(f"lag:policies.autoscaler_lag:{lag}")
        deg = self.lb_degraded_backend()
        if deg is not None:
            parts.append(f"degrade:lb.degraded_backend:{deg[0]}")
        return ",".join(parts)


_plan: Optional[FaultPlan] = None
_env_loaded = False


def _load_env() -> None:
    global _plan, _env_loaded
    _env_loaded = True
    spec = os.environ.get(ENV_FAULT_INJECT)
    if spec:
        _plan = FaultPlan.parse(spec)
        telemetry.counter_inc("fault_plan_armed", 0.0)  # visibility key


def install(spec: str) -> FaultPlan:
    """Arm a plan programmatically (tests); replaces any existing one."""
    global _plan, _env_loaded
    _plan = FaultPlan.parse(spec)
    _env_loaded = True
    return _plan


def clear() -> None:
    """Disarm injection (and stop re-reading the environment)."""
    global _plan, _env_loaded
    _plan = None
    _env_loaded = True


def active() -> bool:
    if not _env_loaded:
        _load_env()
    return _plan is not None


def check(site: str) -> None:
    """Raise the planned fault for ``site``, if any budget remains.

    Called unconditionally from the instrumented phases; with no plan
    armed this is one boolean test.
    """
    if not _env_loaded:
        _load_env()
    if _plan is None:
        return
    entry = _plan.pop(site)
    if entry is None:
        return
    telemetry.counter_inc("faults_injected")
    telemetry.counter_inc(f"faults_injected.{entry.kind}")
    msg, fault_class = _SHAPES[entry.kind]
    raise InjectedFault(msg.format(site=site), fault_class)


def nan_segment() -> Optional[int]:
    """The segment index to poison with NaN, or None (trace-time hook)."""
    if not _env_loaded:
        _load_env()
    return None if _plan is None else _plan.nan_segment()


def stuck_breaker() -> bool:
    """Behavioral policy chaos: tripped breakers never close
    (trace-time hook for sim/policies.advance)."""
    if not _env_loaded:
        _load_env()
    return False if _plan is None else _plan.stuck_breaker()


def autoscaler_lag() -> int:
    """Behavioral policy chaos: sync periods the autoscaler misses at
    startup (0 = chaos off; trace-time hook for policies.init_state)."""
    if not _env_loaded:
        _load_env()
    return 0 if _plan is None else _plan.autoscaler_lag()


def lb_degraded_backend():
    """Behavioral LB chaos: ``(backend, factor)`` collapsing that
    backend's attraction weight in the traced profile, or None
    (trace-time hook for sim/lb.device_tables)."""
    if not _env_loaded:
        _load_env()
    return None if _plan is None else _plan.lb_degraded_backend()


def signature() -> str:
    """Trace-affecting plan identity for executable-cache keys."""
    if not _env_loaded:
        _load_env()
    return "" if _plan is None else _plan.signature()
