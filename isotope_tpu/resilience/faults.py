"""Deterministic engine-level fault injection (chaos for the engine).

The workload simulator already has chaos schedules (replica killers,
outages); this module aims the same discipline at the ENGINE: every
recovery path in the supervisor — retry, degradation ladder, cache
quarantine, numeric sentinels — must be exercisable on CPU in tests
and smoke targets, not just on a TPU that happens to OOM.

Spec syntax (``$ISOTOPE_FAULT_INJECT`` or :func:`install`)::

    ISOTOPE_FAULT_INJECT=oom:sharded.gather:1,nan:segment:2

comma-separated ``kind:site[:arg]`` entries:

- ``oom:<site>[:count]`` — raise a ``RESOURCE_EXHAUSTED``-shaped fault
  the first ``count`` times ``check(site)`` runs (default 1);
- ``transient:<site>[:count]`` — same, ``UNAVAILABLE``-shaped;
- ``corrupt:<site>[:count]`` — same, shaped like a corrupted
  persistent-cache entry (unpickle/digest failure);
- ``nan:segment:<index>`` — poison the output of tensor-program
  segment ``<index>`` with NaN at trace time (``arg`` is the segment
  index, not a count; exercises the numeric sentinels and detail-mode
  localization);
- ``stuck:policies.stuck_breaker`` — BEHAVIORAL chaos against the
  policy co-sim (sim/policies.py): a tripped circuit breaker never
  closes (its shed fraction only ratchets up);
- ``lag:policies.autoscaler_lag[:N]`` — the autoscaler control loop
  misses its first ``N`` sync periods (default 1) — the
  HPA-controller-restart failure mode;
- ``degrade:lb.degraded_backend[:B]`` — BEHAVIORAL chaos against the
  load-balancing laws (sim/lb.py): backend ``B``'s (default 0)
  effective attraction weight silently collapses to 1% — the classic
  gray-failure LB scenario (a ring-hash arc shrinks, wrr skips the
  pod) the profile-free least_request law routes around.

Sites are the supervisor's phase names: ``engine.build``,
``engine.run``, ``sharded.args_put``, ``sharded.compute``,
``sharded.dcn_collective`` (DCN-axis meshes only — the dropped
cross-host collective), ``sharded.gather``, ``cache.load``, plus the
policy-layer sites ``policies.stuck_breaker`` /
``policies.autoscaler_lag`` / ``lb.degraded_backend`` — the standard
kinds (oom / transient / corrupt) may target those too, raising a
taxonomy-classified fault at the protected run's entry so the
supervisor's retry path covers the policy AND lb layers.  ``check(site)`` is a dict lookup
returning immediately when no plan is armed — the default no-fault
path gains zero work and zero sync points.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from isotope_tpu import telemetry
from isotope_tpu.resilience.taxonomy import (
    DETERMINISTIC,
    RESOURCE_EXHAUSTED,
    TRANSIENT,
    InjectedFault,
)

ENV_FAULT_INJECT = "ISOTOPE_FAULT_INJECT"

KINDS = ("oom", "transient", "corrupt", "nan", "stuck", "lag",
         "degrade")

#: every instrumented ``check(site)`` call site in the engine — the
#: closed universe a spec may target.  A typo'd site used to parse
#: fine and silently never fire (the chaos test then "passed" without
#: exercising anything); now it raises at parse time with this list.
#: ``nan`` targets the pseudo-site ``segment`` (trace-time poisoning).
VALID_SITES = (
    "engine.build",
    "engine.run",
    "sharded.args_put",
    "sharded.compute",
    # fires only when the mesh has a DCN (slice) axis — the
    # dropped-cross-host-collective chaos site, so the transient
    # retry path for jaxlib DCN errors is testable without real hosts
    "sharded.dcn_collective",
    "sharded.gather",
    "cache.load",
    # the policy co-sim's own chaos sites (sim/policies.py): the
    # standard kinds raise classified faults at the policy run's
    # entry; the behavioral kinds ("stuck"/"lag") alter the traced
    # control program instead of raising
    "policies.stuck_breaker",
    "policies.autoscaler_lag",
    # the LB layer's chaos site (sim/lb.py): "degrade" collapses one
    # backend's weight in the traced profile; the standard kinds raise
    # classified faults at the protected run's entry like the policy
    # sites (the supervisor retry path is pinned for both)
    "lb.degraded_backend",
)

#: fault kind -> (message template, taxonomy class).  Messages imitate
#: the real failure text so the taxonomy classifies injected faults by
#: the same patterns as real ones (the explicit class is a backstop).
_SHAPES = {
    "oom": (
        "RESOURCE_EXHAUSTED: out of memory while running {site} "
        "(injected fault)",
        RESOURCE_EXHAUSTED,
    ),
    "transient": (
        "UNAVAILABLE: injected transient fault at {site}",
        TRANSIENT,
    ),
    "corrupt": (
        "corrupted persistent-cache entry at {site}: digest mismatch "
        "(injected fault, unpickling failed)",
        DETERMINISTIC,
    ),
}


@dataclasses.dataclass
class _Entry:
    kind: str
    site: str
    arg: int          # fire count (oom/transient/corrupt) or segment (nan)
    remaining: int


class FaultPlan:
    """A parsed, mutable injection plan (per-entry fire budgets)."""

    def __init__(self, entries: List[_Entry]):
        self.entries = entries
        self._by_site: Dict[str, List[_Entry]] = {}
        for e in entries:
            if e.kind not in ("nan", "stuck", "lag", "degrade"):
                self._by_site.setdefault(e.site, []).append(e)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        entries: List[_Entry] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) not in (2, 3):
                raise ValueError(
                    f"bad fault spec {part!r} (want kind:site[:arg])"
                )
            kind, site = bits[0].strip(), bits[1].strip()
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (one of {KINDS})"
                )
            arg = int(bits[2]) if len(bits) == 3 else (
                0 if kind in ("nan", "degrade") else 1
            )
            if kind == "nan" and site != "segment":
                raise ValueError(
                    f"nan faults target segments (nan:segment:<idx>), "
                    f"got site {site!r}"
                )
            if kind == "stuck" and site != "policies.stuck_breaker":
                raise ValueError(
                    "stuck faults target the breaker "
                    "(stuck:policies.stuck_breaker), got site "
                    f"{site!r}"
                )
            if kind == "lag" and site != "policies.autoscaler_lag":
                raise ValueError(
                    "lag faults target the autoscaler "
                    "(lag:policies.autoscaler_lag[:N]), got site "
                    f"{site!r}"
                )
            if kind == "degrade" and site != "lb.degraded_backend":
                raise ValueError(
                    "degrade faults target the lb layer "
                    "(degrade:lb.degraded_backend[:B]), got site "
                    f"{site!r}"
                )
            if kind != "nan" and site not in VALID_SITES:
                raise ValueError(
                    f"unknown fault site {site!r} — the plan would "
                    f"never fire (valid sites: "
                    f"{', '.join(VALID_SITES)})"
                )
            behavioral = kind in ("nan", "stuck", "lag", "degrade")
            entries.append(
                _Entry(kind=kind, site=site, arg=arg,
                       remaining=0 if behavioral else arg)
            )
        return cls(entries)

    def pop(self, site: str) -> Optional[_Entry]:
        """The first live entry at ``site``, its budget decremented."""
        for e in self._by_site.get(site, ()):
            if e.remaining > 0:
                e.remaining -= 1
                return e
        return None

    def nan_segment(self) -> Optional[int]:
        for e in self.entries:
            if e.kind == "nan":
                return e.arg
        return None

    def stuck_breaker(self) -> bool:
        return any(e.kind == "stuck" for e in self.entries)

    def autoscaler_lag(self) -> int:
        for e in self.entries:
            if e.kind == "lag":
                return max(e.arg, 1)
        return 0

    #: the collapse factor of a degraded backend's attraction weight —
    #: small but nonzero: the pod still advertises (gray failure), it
    #: just draws ~no traffic
    DEGRADED_FACTOR = 0.01

    def lb_degraded_backend(self):
        for e in self.entries:
            if e.kind == "degrade":
                return (max(e.arg, 0), self.DEGRADED_FACTOR)
        return None

    def signature(self) -> str:
        """Stable identity of the TRACE-AFFECTING part of the plan.

        The BEHAVIORAL kinds change the traced program — NaN poisoning
        bakes a poisoned constant into a segment, stuck/lag alter the
        policy control trace — so they participate; the executable
        caches must not share an altered program with a clean one,
        while pure host-side faults keep full cache reuse.
        """
        parts = []
        seg = self.nan_segment()
        if seg is not None:
            parts.append(f"nan:segment:{seg}")
        if self.stuck_breaker():
            parts.append("stuck:policies.stuck_breaker")
        lag = self.autoscaler_lag()
        if lag:
            parts.append(f"lag:policies.autoscaler_lag:{lag}")
        deg = self.lb_degraded_backend()
        if deg is not None:
            parts.append(f"degrade:lb.degraded_backend:{deg[0]}")
        return ",".join(parts)


_plan: Optional[FaultPlan] = None
_env_loaded = False


def _load_env() -> None:
    global _plan, _env_loaded
    _env_loaded = True
    spec = os.environ.get(ENV_FAULT_INJECT)
    if spec:
        _plan = FaultPlan.parse(spec)
        telemetry.counter_inc("fault_plan_armed", 0.0)  # visibility key


def install(spec: str) -> FaultPlan:
    """Arm a plan programmatically (tests); replaces any existing one."""
    global _plan, _env_loaded
    _plan = FaultPlan.parse(spec)
    _env_loaded = True
    return _plan


def clear() -> None:
    """Disarm injection (and stop re-reading the environment)."""
    global _plan, _env_loaded
    _plan = None
    _env_loaded = True


def active() -> bool:
    if not _env_loaded:
        _load_env()
    return _plan is not None


def check(site: str) -> None:
    """Raise the planned fault for ``site``, if any budget remains.

    Called unconditionally from the instrumented phases; with no plan
    armed this is one boolean test.
    """
    if not _env_loaded:
        _load_env()
    if _plan is None:
        return
    entry = _plan.pop(site)
    if entry is None:
        return
    telemetry.counter_inc("faults_injected")
    telemetry.counter_inc(f"faults_injected.{entry.kind}")
    msg, fault_class = _SHAPES[entry.kind]
    raise InjectedFault(msg.format(site=site), fault_class)


def nan_segment() -> Optional[int]:
    """The segment index to poison with NaN, or None (trace-time hook)."""
    if not _env_loaded:
        _load_env()
    return None if _plan is None else _plan.nan_segment()


def stuck_breaker() -> bool:
    """Behavioral policy chaos: tripped breakers never close
    (trace-time hook for sim/policies.advance)."""
    if not _env_loaded:
        _load_env()
    return False if _plan is None else _plan.stuck_breaker()


def autoscaler_lag() -> int:
    """Behavioral policy chaos: sync periods the autoscaler misses at
    startup (0 = chaos off; trace-time hook for policies.init_state)."""
    if not _env_loaded:
        _load_env()
    return 0 if _plan is None else _plan.autoscaler_lag()


def lb_degraded_backend():
    """Behavioral LB chaos: ``(backend, factor)`` collapsing that
    backend's attraction weight in the traced profile, or None
    (trace-time hook for sim/lb.device_tables)."""
    if not _env_loaded:
        _load_env()
    return None if _plan is None else _plan.lb_degraded_backend()


def signature() -> str:
    """Trace-affecting plan identity for executable-cache keys."""
    if not _env_loaded:
        _load_env()
    return "" if _plan is None else _plan.signature()


# -- per-member chaos schedules (chaos fleets, sim/ensemble.py) ---------------
#
# The workload chaos schedule (sim/config.ChaosEvent) is one fixed bad
# day; a Monte Carlo fleet wants every member to survive a DIFFERENT
# bad day.  ChaosJitterSpec perturbs each event's kill timing, target,
# and magnitude per member — deterministically from per-event seeds
# derived by the fold_in discipline — while preserving the schedule's
# phase-cut STRUCTURE (same number of distinct cuts, same order), so
# every member's phase tables stay shape-aligned and one traced fleet
# program serves them all (engine `_simulate_core(chaos_fx=...)`).


@dataclasses.dataclass(frozen=True)
class ChaosJitterSpec:
    """Per-member chaos-schedule perturbations.

    - ``time``: log-space sigma of a mean-preserving lognormal factor
      on each distinct event boundary (kill start / recovery time);
      jittered boundaries are re-ranked to the solo order, so the cut
      count and ordering — the traced program's shape — never change;
    - ``magnitude``: log-space sigma on each event's ``replicas_down``
      (rounded, clamped to ``[1, replicas(target)]``);
    - ``target``: probability an event re-targets a service drawn
      uniformly from ``pool`` (default: the set of services the solo
      schedule already targets);
    - ``seed``: the jitter stream root; member ``m``'s event ``e``
      draws from ``SeedSequence([seed, member_event_seed])`` so the
      same spec reproduces bit-identical schedules on every host, and
      the splitting estimator can resample events independently.

    ``time == magnitude == target == 0`` is the identity: every
    member keeps the solo schedule (pinned byte-identical).
    """

    time: float = 0.0
    magnitude: float = 0.0
    target: float = 0.0
    pool: tuple = ()
    seed: int = 0

    def __post_init__(self):
        for name in ("time", "magnitude"):
            if getattr(self, name) < 0:
                raise ValueError(f"chaos jitter {name} must be >= 0")
        if not 0.0 <= self.target <= 1.0:
            raise ValueError("chaos jitter target must lie in [0, 1]")
        object.__setattr__(self, "pool", tuple(self.pool))

    @property
    def identity(self) -> bool:
        return (
            self.time == 0.0
            and self.magnitude == 0.0
            and self.target == 0.0
        )

    def to_dict(self) -> dict:
        return {
            "time": self.time, "magnitude": self.magnitude,
            "target": self.target, "pool": list(self.pool),
            "seed": self.seed,
        }


def parse_chaos_jitter(text: Optional[str]):
    """Parse ``"time=0.2,magnitude=0.5,target=0.3,seed=7"`` into a
    :class:`ChaosJitterSpec` (None for empty/``off``)."""
    if not text or str(text).strip().lower() in ("off", "0", "false"):
        return None
    kw: Dict[str, object] = {}
    keys = {"time": float, "magnitude": float, "mag": float,
            "target": float, "seed": int}
    names = {"mag": "magnitude"}
    for part in str(text).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad chaos jitter entry {part!r} (expected "
                f"key=value; keys: {', '.join(sorted(keys))})"
            )
        k, v = part.split("=", 1)
        k = k.strip().lower()
        if k not in keys:
            raise ValueError(
                f"unknown chaos jitter key {k!r} (expected one of "
                f"{', '.join(sorted(keys))})"
            )
        kw[names.get(k, k)] = keys[k](v.strip())
    return ChaosJitterSpec(**kw)


def member_event_seeds(spec: ChaosJitterSpec, member_seed: int,
                       num_events: int):
    """The (E,) per-event jitter seeds of one fleet member — the
    components the splitting estimator's proposal kernel resamples
    independently (sim/splitting.py)."""
    import numpy as np

    rng = np.random.default_rng(
        np.random.SeedSequence([int(spec.seed), int(member_seed) &
                                0x7FFFFFFF])
    )
    return rng.integers(1, 2**31 - 1, size=max(num_events, 1),
                        dtype=np.int64)


def jitter_chaos_events(chaos, spec: ChaosJitterSpec, event_seeds,
                        replicas_by_name):
    """One member's jittered schedule: same event count, same distinct
    cut count, same cut ORDER as the solo schedule (the shape-aligned
    contract the stacked fleet tables need).

    Ties are preserved: boundaries sharing one solo value share one
    jitter draw (first event wins), so coinciding cuts never split
    into extra phases.  Re-ranking (sort the jittered values, assign
    by solo rank) keeps ``start < end`` per event and the global
    ordering intact even when draws cross."""
    import numpy as np

    chaos = tuple(chaos)
    if not chaos:
        return chaos
    seeds = np.asarray(event_seeds, np.int64)
    if seeds.shape != (len(chaos),):
        raise ValueError(
            f"event_seeds must have shape ({len(chaos)},); got "
            f"{seeds.shape}"
        )
    # distinct solo boundary values, in order (0 is never a boundary
    # here unless an event starts at 0 — it stays pinned at 0)
    values = sorted({float(ev.start_s) for ev in chaos}
                    | {float(ev.end_s) for ev in chaos})
    factor: Dict[float, float] = {}
    jittered = []
    for ev, s in zip(chaos, seeds):
        rng = np.random.default_rng(
            np.random.SeedSequence([int(spec.seed), int(s)])
        )
        # fixed draw layout regardless of arming: the axes' streams
        # stay independent of which jitters are on
        z_start, z_end, z_mag = rng.standard_normal(3)
        u_flip, u_pick = rng.random(2)
        for v, z in ((float(ev.start_s), z_start),
                     (float(ev.end_s), z_end)):
            if v not in factor:
                factor[v] = (
                    float(np.exp(spec.time * z
                                 - 0.5 * spec.time * spec.time))
                    if spec.time > 0 else 1.0
                )
        target = ev.service
        if spec.target > 0 and u_flip < spec.target:
            pool = spec.pool or tuple(sorted(
                {e.service for e in chaos}
            ))
            target = pool[min(int(u_pick * len(pool)), len(pool) - 1)]
        reps = int(replicas_by_name[target])
        down = ev.replicas_down
        if spec.magnitude > 0:
            base = reps if down is None else int(down)
            mag = float(np.exp(
                spec.magnitude * z_mag
                - 0.5 * spec.magnitude * spec.magnitude
            ))
            down = int(np.clip(round(base * mag), 1, reps))
        elif down is not None and target != ev.service:
            # a re-targeted kill keeps its size but never exceeds the
            # new pool; the identity spec leaves the event untouched
            down = min(int(down), reps)
        jittered.append((ev, target, down))
    # re-rank: jittered values sorted ascending map back to the solo
    # ranks, preserving order/count (a crossing draw swaps magnitudes,
    # not structure)
    jit_vals = np.sort([v * factor[v] for v in values])
    remap = {v: float(jv) for v, jv in zip(values, jit_vals)}
    out = []
    for ev, target, down in jittered:
        out.append(dataclasses.replace(
            ev, service=target,
            start_s=remap[float(ev.start_s)],
            end_s=remap[float(ev.end_s)],
            replicas_down=down,
        ))
    return tuple(out)
