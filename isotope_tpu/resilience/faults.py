"""Deterministic engine-level fault injection (chaos for the engine).

The workload simulator already has chaos schedules (replica killers,
outages); this module aims the same discipline at the ENGINE: every
recovery path in the supervisor — retry, degradation ladder, cache
quarantine, numeric sentinels — must be exercisable on CPU in tests
and smoke targets, not just on a TPU that happens to OOM.

Spec syntax (``$ISOTOPE_FAULT_INJECT`` or :func:`install`)::

    ISOTOPE_FAULT_INJECT=oom:sharded.gather:1,nan:segment:2

comma-separated ``kind:site[:arg]`` entries:

- ``oom:<site>[:count]`` — raise a ``RESOURCE_EXHAUSTED``-shaped fault
  the first ``count`` times ``check(site)`` runs (default 1);
- ``transient:<site>[:count]`` — same, ``UNAVAILABLE``-shaped;
- ``corrupt:<site>[:count]`` — same, shaped like a corrupted
  persistent-cache entry (unpickle/digest failure);
- ``nan:segment:<index>`` — poison the output of tensor-program
  segment ``<index>`` with NaN at trace time (``arg`` is the segment
  index, not a count; exercises the numeric sentinels and detail-mode
  localization).

Sites are the supervisor's phase names: ``engine.build``,
``engine.run``, ``sharded.args_put``, ``sharded.compute``,
``sharded.dcn_collective`` (DCN-axis meshes only — the dropped
cross-host collective), ``sharded.gather``, ``cache.load``.
``check(site)`` is a dict lookup
returning immediately when no plan is armed — the default no-fault
path gains zero work and zero sync points.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from isotope_tpu import telemetry
from isotope_tpu.resilience.taxonomy import (
    DETERMINISTIC,
    RESOURCE_EXHAUSTED,
    TRANSIENT,
    InjectedFault,
)

ENV_FAULT_INJECT = "ISOTOPE_FAULT_INJECT"

KINDS = ("oom", "transient", "corrupt", "nan")

#: every instrumented ``check(site)`` call site in the engine — the
#: closed universe a spec may target.  A typo'd site used to parse
#: fine and silently never fire (the chaos test then "passed" without
#: exercising anything); now it raises at parse time with this list.
#: ``nan`` targets the pseudo-site ``segment`` (trace-time poisoning).
VALID_SITES = (
    "engine.build",
    "engine.run",
    "sharded.args_put",
    "sharded.compute",
    # fires only when the mesh has a DCN (slice) axis — the
    # dropped-cross-host-collective chaos site, so the transient
    # retry path for jaxlib DCN errors is testable without real hosts
    "sharded.dcn_collective",
    "sharded.gather",
    "cache.load",
)

#: fault kind -> (message template, taxonomy class).  Messages imitate
#: the real failure text so the taxonomy classifies injected faults by
#: the same patterns as real ones (the explicit class is a backstop).
_SHAPES = {
    "oom": (
        "RESOURCE_EXHAUSTED: out of memory while running {site} "
        "(injected fault)",
        RESOURCE_EXHAUSTED,
    ),
    "transient": (
        "UNAVAILABLE: injected transient fault at {site}",
        TRANSIENT,
    ),
    "corrupt": (
        "corrupted persistent-cache entry at {site}: digest mismatch "
        "(injected fault, unpickling failed)",
        DETERMINISTIC,
    ),
}


@dataclasses.dataclass
class _Entry:
    kind: str
    site: str
    arg: int          # fire count (oom/transient/corrupt) or segment (nan)
    remaining: int


class FaultPlan:
    """A parsed, mutable injection plan (per-entry fire budgets)."""

    def __init__(self, entries: List[_Entry]):
        self.entries = entries
        self._by_site: Dict[str, List[_Entry]] = {}
        for e in entries:
            if e.kind != "nan":
                self._by_site.setdefault(e.site, []).append(e)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        entries: List[_Entry] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) not in (2, 3):
                raise ValueError(
                    f"bad fault spec {part!r} (want kind:site[:arg])"
                )
            kind, site = bits[0].strip(), bits[1].strip()
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (one of {KINDS})"
                )
            arg = int(bits[2]) if len(bits) == 3 else (
                0 if kind == "nan" else 1
            )
            if kind == "nan" and site != "segment":
                raise ValueError(
                    f"nan faults target segments (nan:segment:<idx>), "
                    f"got site {site!r}"
                )
            if kind != "nan" and site not in VALID_SITES:
                raise ValueError(
                    f"unknown fault site {site!r} — the plan would "
                    f"never fire (valid sites: "
                    f"{', '.join(VALID_SITES)})"
                )
            entries.append(
                _Entry(kind=kind, site=site, arg=arg,
                       remaining=0 if kind == "nan" else arg)
            )
        return cls(entries)

    def pop(self, site: str) -> Optional[_Entry]:
        """The first live entry at ``site``, its budget decremented."""
        for e in self._by_site.get(site, ()):
            if e.remaining > 0:
                e.remaining -= 1
                return e
        return None

    def nan_segment(self) -> Optional[int]:
        for e in self.entries:
            if e.kind == "nan":
                return e.arg
        return None

    def signature(self) -> str:
        """Stable identity of the TRACE-AFFECTING part of the plan.

        Only NaN poisoning changes the traced program (it bakes a NaN
        constant into a segment), so only it participates — the
        executable caches must not share a poisoned program with a
        clean one, while pure host-side faults keep full cache reuse.
        """
        seg = self.nan_segment()
        return "" if seg is None else f"nan:segment:{seg}"


_plan: Optional[FaultPlan] = None
_env_loaded = False


def _load_env() -> None:
    global _plan, _env_loaded
    _env_loaded = True
    spec = os.environ.get(ENV_FAULT_INJECT)
    if spec:
        _plan = FaultPlan.parse(spec)
        telemetry.counter_inc("fault_plan_armed", 0.0)  # visibility key


def install(spec: str) -> FaultPlan:
    """Arm a plan programmatically (tests); replaces any existing one."""
    global _plan, _env_loaded
    _plan = FaultPlan.parse(spec)
    _env_loaded = True
    return _plan


def clear() -> None:
    """Disarm injection (and stop re-reading the environment)."""
    global _plan, _env_loaded
    _plan = None
    _env_loaded = True


def active() -> bool:
    if not _env_loaded:
        _load_env()
    return _plan is not None


def check(site: str) -> None:
    """Raise the planned fault for ``site``, if any budget remains.

    Called unconditionally from the instrumented phases; with no plan
    armed this is one boolean test.
    """
    if not _env_loaded:
        _load_env()
    if _plan is None:
        return
    entry = _plan.pop(site)
    if entry is None:
        return
    telemetry.counter_inc("faults_injected")
    telemetry.counter_inc(f"faults_injected.{entry.kind}")
    msg, fault_class = _SHAPES[entry.kind]
    raise InjectedFault(msg.format(site=site), fault_class)


def nan_segment() -> Optional[int]:
    """The segment index to poison with NaN, or None (trace-time hook)."""
    if not _env_loaded:
        _load_env()
    return None if _plan is None else _plan.nan_segment()


def signature() -> str:
    """Trace-affecting plan identity for executable-cache keys."""
    if not _env_loaded:
        _load_env()
    return "" if _plan is None else _plan.signature()
