"""Resilient execution supervision for the engine itself.

The simulated *workloads* were already fault-tolerant (retries,
timeouts, chaos schedules are modeled and oracle-tested), but the
engine running them was brittle: one XLA ``RESOURCE_EXHAUSTED`` on a
sharded run, one corrupted persistent-cache entry, or one NaN escaping
a segment killed an entire multi-hour sweep with a raw traceback.  This
package converts those hard-crash modes into counted, reported,
recoverable events — the engine-side analogue of the reference's
Kubernetes restarts + persistent-disk Prometheus durability
(SURVEY.md §5.4):

- :mod:`~isotope_tpu.resilience.taxonomy` classifies JAX/XLA exceptions
  into transient / resource-exhausted / deterministic;
- :mod:`~isotope_tpu.resilience.supervisor` retries transients with
  exponential backoff + deterministic jitter and walks the OOM
  degradation ladder (halve the request chunk, then sharded ->
  single-device -> CPU eager);
- :mod:`~isotope_tpu.resilience.sentinels` validates run outputs
  (finite, non-negative latencies) post-run;
- :mod:`~isotope_tpu.resilience.faults` injects deterministic faults
  (``ISOTOPE_FAULT_INJECT=oom:sharded.gather:1,nan:segment:2``) so all
  of the above is testable on CPU — chaos engineering aimed at the
  engine itself.
"""
from isotope_tpu.resilience.taxonomy import (  # noqa: F401
    DETERMINISTIC,
    RESOURCE_EXHAUSTED,
    TRANSIENT,
    InjectedFault,
    NumericSentinelError,
    classify,
    is_cache_corruption,
)
from isotope_tpu.resilience import faults  # noqa: F401
from isotope_tpu.resilience.supervisor import (  # noqa: F401
    ResiliencePolicy,
    backoff_seconds,
    call_with_retries,
    execution_rungs,
    run_ladder,
)
from isotope_tpu.resilience.sentinels import (  # noqa: F401
    check_results,
    check_summary,
)
