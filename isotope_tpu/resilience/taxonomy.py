"""Error taxonomy: classify engine-phase exceptions by recovery action.

The classes mirror what a training stack builds around device failures
(PAPERS.md: Pathways' resilient dataflow; JAX persistent-cache
durability) — WHAT failed matters less than WHAT TO DO NEXT:

- ``TRANSIENT`` — retry with backoff: infrastructure hiccups (RPC
  deadline, socket reset, preempted device, interrupted syscall) that
  a later identical attempt is expected to survive.
- ``RESOURCE_EXHAUSTED`` — degrade, don't retry: the same program at
  the same shape will OOM again; the supervisor walks the degradation
  ladder instead (smaller chunks, fewer devices, CPU eager).
- ``DETERMINISTIC`` — fail the case, keep the sweep: shape errors,
  invalid arguments, numeric-sentinel violations.  Retrying burns
  hours reproducing the same traceback, so the case is recorded as
  failed in the checkpoint and the sweep continues.

Classification is by exception *type* where python gives one
(``ConnectionError``, ``TimeoutError``) and by message pattern for the
XLA status strings jaxlib flattens into ``XlaRuntimeError`` text
(``RESOURCE_EXHAUSTED: ...``, ``UNAVAILABLE: ...``) — there is no
stable exception subclass per status code across jaxlib versions.

Kept import-light on purpose (no jax): the converter-only environment
and the fault-injection hooks both load this module.
"""
from __future__ import annotations

import re

#: retry with exponential backoff + deterministic jitter
TRANSIENT = "transient"
#: walk the degradation ladder (never naively retried)
RESOURCE_EXHAUSTED = "resource_exhausted"
#: record the case as failed; the sweep continues
DETERMINISTIC = "deterministic"

# XLA flattens its absl status codes into the message text; match the
# canonical code names plus the allocator phrasings TPU/CPU backends
# emit without a code prefix.
_RESOURCE_RE = re.compile(
    r"RESOURCE_EXHAUSTED|OUT_OF_MEMORY|out of memory|out-of-memory"
    r"|\bOOM\b|[Ff]ailed to allocate|[Aa]llocation .* exceeds"
    r"|exceeds the memory|[Ii]nsufficient memory",
)
_TRANSIENT_RE = re.compile(
    r"UNAVAILABLE|DEADLINE_EXCEEDED|\bABORTED\b|\bCANCELLED\b"
    r"|[Cc]onnection reset|[Ss]ocket closed|[Tt]emporarily unavailable"
    r"|[Tt]ry again|[Pp]reempt"
    # jaxlib DCN / multi-host collective failures (the PR 3 follow-up,
    # armed now that multi-host runs exist): cross-slice transfers and
    # the coordination service fail transiently when a peer host
    # stalls, restarts, or a DCN flow drops — a retry against healthy
    # hosts is expected to succeed.  Signatures collected from
    # jaxlib/XLA status text: MegaScale/DCN transfer engine errors,
    # collective/barrier timeouts, coordination-service heartbeat
    # loss, and gRPC's connect-failure phrasing.
    r"|[Mm]ega[Ss]cale|\bDCN\b"
    r"|[Cc]ollective (?:operation|permute)? ?timed out"
    r"|[Bb]arrier timed out|[Hh]eartbeat timeout"
    r"|[Cc]oordination service (?:agent|error|is unavailable)"
    r"|failed to connect to all addresses"
    r"|[Tt]ransfer server|[Pp]eer task .* (?:failed|disconnected)",
)
_CORRUPT_RE = re.compile(
    r"unpickl|[Cc]orrupt|[Dd]igest mismatch|deserial|[Bb]ad cache entry"
    r"|[Tt]runcated cache|zstd|[Ii]nvalid compilation cache",
)


class InjectedFault(RuntimeError):
    """A deterministic fault raised by :mod:`resilience.faults`.

    Carries its class explicitly so injected faults classify exactly
    like the real exception they imitate, whatever the message says.
    """

    def __init__(self, message: str, fault_class: str):
        super().__init__(message)
        self.fault_class = fault_class


class NumericSentinelError(RuntimeError):
    """A run produced non-finite or negative outputs (sentinels.py).

    Deterministic by definition: the same program on the same inputs
    reproduces the same NaN, so the supervisor fails the case instead
    of retrying.
    """

    fault_class = DETERMINISTIC


def classify(exc: BaseException) -> str:
    """Map an exception to its recovery class (see module docstring)."""
    explicit = getattr(exc, "fault_class", None)
    if explicit in (TRANSIENT, RESOURCE_EXHAUSTED, DETERMINISTIC):
        return explicit
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError)):
        return TRANSIENT
    if isinstance(exc, MemoryError):
        return RESOURCE_EXHAUSTED
    text = f"{type(exc).__name__}: {exc}"
    if _RESOURCE_RE.search(text):
        return RESOURCE_EXHAUSTED
    if _TRANSIENT_RE.search(text):
        return TRANSIENT
    return DETERMINISTIC


def is_cache_corruption(exc: BaseException) -> bool:
    """Whether ``exc`` looks like a corrupted persistent-cache entry
    (digest mismatch / unpickle failure) — the one deterministic error
    with a better move than failing: evict the entry and retrace."""
    return bool(_CORRUPT_RE.search(f"{type(exc).__name__}: {exc}"))
