"""``python -m isotope_tpu`` == the ``isotope-tpu`` console script."""
import sys

from isotope_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
