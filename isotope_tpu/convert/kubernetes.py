"""Kubernetes manifest generation from a ServiceGraph.

Capability parity with the reference converter
(isotope/convert/pkg/kubernetes/kubernetes.go:56-137): emits a Namespace
with istio-injection enabled (:150-157), a ConfigMap embedding the whole
topology YAML (:159-175), and per service a Service (:177-187) plus a
Deployment (:189-270) that mounts the config at
/etc/config/service-graph.yaml, sets SERVICE_NAME and downward-API env vars,
and carries the prometheus scrape annotation. A Fortio client
Deployment+Service is appended (fortio_client.go:28-78), and when the
environment is ISTIO, per-service RBAC policies (rbac.go:25-71).

The manifests target real clusters; in this framework they exist so users of
the reference can still deploy a topology for ground-truth runs to validate
the simulator against.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import yaml

from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.models.svctype import ServiceType

# consts/consts.go:22-46
SERVICE_GRAPH_NAMESPACE = "service-graph"
SERVICE_GRAPH_CONFIG_MAP = "service-graph-config"
CONFIG_PATH = "/etc/config"
SERVICE_GRAPH_YAML_KEY = "service-graph.yaml"
SERVICE_PORT = 8080
SERVICE_NAME_ENV = "SERVICE_NAME"
FORTIO_METRICS_PORT = 42422

DEFAULT_SERVICE_IMAGE = "istio.io/isotope-service:latest"
DEFAULT_CLIENT_IMAGE = "fortio/fortio"


@dataclasses.dataclass
class ConvertOptions:
    service_image: str = DEFAULT_SERVICE_IMAGE
    client_image: str = DEFAULT_CLIENT_IMAGE
    environment_name: str = "NONE"  # NONE | ISTIO (cmd/kubernetes.go:78)
    service_node_selector: Optional[dict] = None
    client_node_selector: Optional[dict] = None
    max_idle_connections_per_host: int = 0
    # multicluster: emit only this cluster's Deployments/Services (the
    # per-context apply of the reference's multicluster split,
    # perf/load/common.sh:36-42); None = everything.  The ConfigMap
    # always embeds the FULL topology — every pod needs the whole graph
    # — and the load client deploys only alongside the entrypoint's
    # cluster.
    cluster: Optional[str] = None


def service_graph_to_manifests(
    graph: ServiceGraph,
    topology_yaml: str,
    opts: Optional[ConvertOptions] = None,
) -> List[dict]:
    opts = opts if opts is not None else ConvertOptions()
    if opts.cluster is not None:
        known = {getattr(s, "cluster", "") for s in graph.services}
        if opts.cluster not in known:
            raise ValueError(
                f"no service is placed in cluster {opts.cluster!r}; "
                f"topology clusters: {sorted(known)}"
            )
    manifests: List[dict] = [
        _namespace(),
        _config_map(topology_yaml),
    ]
    for svc in graph.services:
        if opts.cluster is not None and (
            getattr(svc, "cluster", "") != opts.cluster
        ):
            continue
        manifests.append(_k8s_service(svc.name))
        manifests.append(_deployment(svc, opts))
    entry_cluster = next(
        (getattr(s, "cluster", "") for s in graph.services
         if s.is_entrypoint),
        "",
    )
    if opts.cluster is None or opts.cluster == entry_cluster:
        manifests.extend(_fortio_client(opts))
    if opts.environment_name == "ISTIO":
        manifests.extend(_rbac_policies(graph, opts.cluster))
    return manifests


def manifests_to_yaml(manifests: List[dict]) -> str:
    return "\n---\n".join(
        yaml.safe_dump(m, default_flow_style=False, sort_keys=False)
        for m in manifests
    )


def _namespace() -> dict:
    # kubernetes.go:150-157: istio-injection=enabled label.
    return {
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {
            "name": SERVICE_GRAPH_NAMESPACE,
            "labels": {"istio-injection": "enabled"},
        },
    }


def _config_map(topology_yaml: str) -> dict:
    # kubernetes.go:159-175: the full topology YAML is the single source of
    # truth, mounted into every pod.
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": SERVICE_GRAPH_CONFIG_MAP,
            "namespace": SERVICE_GRAPH_NAMESPACE,
        },
        "data": {SERVICE_GRAPH_YAML_KEY: topology_yaml},
    }


def _k8s_service(name: str) -> dict:
    # kubernetes.go:177-187.
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": name,
            "namespace": SERVICE_GRAPH_NAMESPACE,
            "labels": {"app": name},
        },
        "spec": {
            "ports": [{"port": SERVICE_PORT, "name": "http"}],
            "selector": {"app": name},
        },
    }


def _deployment(svc, opts: ConvertOptions) -> dict:
    # kubernetes.go:189-270.
    args = []
    if opts.max_idle_connections_per_host > 0:
        args = [
            f"--max-idle-connections-per-host={opts.max_idle_connections_per_host}"
        ]
    container = {
        "name": "mock-service",
        "image": opts.service_image,
        "args": args,
        "ports": [{"containerPort": SERVICE_PORT}],
        "env": [
            {"name": SERVICE_NAME_ENV, "value": svc.name},
            _downward("PODNAME", "metadata.name"),
            _downward("PODIP", "status.podIP"),
            _downward("NAMESPACE", "metadata.namespace"),
            _downward("NODENAME", "spec.nodeName"),
        ],
        "volumeMounts": [
            {"name": "config-volume", "mountPath": CONFIG_PATH}
        ],
    }
    spec = {
        "replicas": svc.num_replicas,
        "selector": {"matchLabels": {"app": svc.name}},
        "template": {
            "metadata": {
                "labels": {"app": svc.name},
                "annotations": {
                    # kubernetes.go:49-52: prometheus scrape annotations.
                    "prometheus.io/scrape": "true",
                    "prometheus.io/port": str(SERVICE_PORT),
                },
            },
            "spec": {
                "containers": [container],
                "volumes": [
                    {
                        "name": "config-volume",
                        "configMap": {"name": SERVICE_GRAPH_CONFIG_MAP},
                    }
                ],
            },
        },
    }
    if opts.service_node_selector:
        spec["template"]["spec"]["nodeSelector"] = dict(
            opts.service_node_selector
        )
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": svc.name,
            "namespace": SERVICE_GRAPH_NAMESPACE,
            "labels": {"app": svc.name},
        },
        "spec": spec,
    }


def _downward(name: str, field_path: str) -> dict:
    return {
        "name": name,
        "valueFrom": {"fieldRef": {"fieldPath": field_path}},
    }


def _fortio_client(opts: ConvertOptions) -> List[dict]:
    # fortio_client.go:28-78: client Deployment + Service, ports 8080 and
    # a separate metrics port 42422.
    name = "client"
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": name,
            "namespace": SERVICE_GRAPH_NAMESPACE,
            "labels": {"app": name},
        },
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "containers": [
                        {
                            "name": "fortio-client",
                            "image": opts.client_image,
                            "args": ["server"],
                            "ports": [
                                {"containerPort": SERVICE_PORT},
                                {"containerPort": FORTIO_METRICS_PORT},
                            ],
                        }
                    ]
                },
            },
        },
    }
    if opts.client_node_selector:
        deployment["spec"]["template"]["spec"]["nodeSelector"] = dict(
            opts.client_node_selector
        )
    service = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": name,
            "namespace": SERVICE_GRAPH_NAMESPACE,
            "labels": {"app": name},
        },
        "spec": {
            "ports": [
                {"port": SERVICE_PORT, "name": "http"},
                {"port": FORTIO_METRICS_PORT, "name": "metrics"},
            ],
            "selector": {"app": name},
        },
    }
    return [deployment, service]


def _rbac_policies(
    graph: ServiceGraph, cluster: Optional[str] = None
) -> List[dict]:
    # rbac.go:25-71 + kubernetes.go:107-133: per-service ServiceRole +
    # ServiceRoleBinding fan-out, plus an allow-all role and RbacConfig.
    # ``cluster`` mirrors the Deployment/Service filter: a per-context
    # apply (the reference's common.sh:36-42 flow) must only carry
    # policies for the workloads that live in that cluster.
    manifests: List[dict] = [
        {
            "apiVersion": "rbac.istio.io/v1alpha1",
            "kind": "RbacConfig",
            "metadata": {"name": "default"},
            "spec": {
                "mode": "ON_WITH_INCLUSION",
                "inclusion": {"namespaces": [SERVICE_GRAPH_NAMESPACE]},
            },
        }
    ]
    for svc in graph.services:
        if cluster is not None and getattr(svc, "cluster", "") != cluster:
            continue
        for i in range(svc.num_rbac_policies):
            role_name = f"{svc.name}-role-{i}"
            manifests.append(
                {
                    "apiVersion": "rbac.istio.io/v1alpha1",
                    "kind": "ServiceRole",
                    "metadata": {
                        "name": role_name,
                        "namespace": SERVICE_GRAPH_NAMESPACE,
                    },
                    "spec": {
                        "rules": [
                            {
                                "services": [
                                    f"{svc.name}.{SERVICE_GRAPH_NAMESPACE}.svc.cluster.local"
                                ],
                                "methods": ["GET"],
                            }
                        ]
                    },
                }
            )
            manifests.append(
                {
                    "apiVersion": "rbac.istio.io/v1alpha1",
                    "kind": "ServiceRoleBinding",
                    "metadata": {
                        "name": role_name,
                        "namespace": SERVICE_GRAPH_NAMESPACE,
                    },
                    "spec": {
                        "subjects": [{"user": "*"}],
                        "roleRef": {"kind": "ServiceRole", "name": role_name},
                    },
                }
            )
    return manifests


def validate_service_types(graph: ServiceGraph) -> None:
    """The deployable runtime is HTTP-only (service/main.go:191-203)."""
    for svc in graph.services:
        if svc.type is ServiceType.GRPC:
            raise ValueError(
                f"service {svc.name}: grpc services are not supported by the "
                "mock-service runtime in this fork"
            )
