"""Large-scale security policy generator.

Capability parity with the reference's Go generator
(perf/benchmark/security/generate_policies/): a JSON config with the
same schema (README.md "Config file") produces AuthorizationPolicy /
PeerAuthentication / RequestAuthentication manifests at scale for authz
benchmarks, plus a signed RS256 bearer token whose issuer matches the
generated jwtRules — so a driver can exercise the allow path as well as
the N-deny-rule evaluation cost.

Synthetic rule values mirror generate.go exactly: paths
``/invalid-path-%d`` (:36), namespaces ``invalid-namespace-%d`` (:96),
principals ``cluster.local/ns/<ns>/sa/Invalid-%d`` (:109), sourceIPs
``0.0.%d.%d`` (:83), condition key ``request.headers[x-token]`` with
guest/admin values (:55-70), and request principals where only the last
is the valid ``issuer-<numJwks>/subject`` (:119-126).
"""
from __future__ import annotations

import base64
import dataclasses
import json
from typing import List, Optional, Tuple

import yaml


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


@dataclasses.dataclass(frozen=True)
class AuthZ:
    action: str = "DENY"
    num_namespaces: int = 0
    num_paths: int = 0
    num_policies: int = 0
    num_principals: int = 0
    num_source_ip: int = 0
    num_values: int = 0
    num_request_principals: int = 0
    dry_run: bool = False


@dataclasses.dataclass(frozen=True)
class PeerAuthN:
    mtls_mode: str = "STRICT"
    num_policies: int = 0


@dataclasses.dataclass(frozen=True)
class RequestAuthN:
    invalid_token: bool = False
    num_policies: int = 0
    num_jwks: int = 0
    token_issuer: str = ""


@dataclasses.dataclass(frozen=True)
class SecurityPolicyConfig:
    authz: AuthZ = AuthZ()
    namespace: str = "twopods-istio"
    peer_authn: PeerAuthN = PeerAuthN()
    request_authn: RequestAuthN = RequestAuthN()

    @classmethod
    def from_json(cls, text: str) -> "SecurityPolicyConfig":
        doc = json.loads(text)
        az = doc.get("authZ", {})
        pa = doc.get("peerAuthN", {})
        ra = doc.get("requestAuthN", {})
        return cls(
            authz=AuthZ(
                action=az.get("action", "DENY"),
                num_namespaces=az.get("numNamespaces", 0),
                num_paths=az.get("numPaths", 0),
                num_policies=az.get("numPolicies", 0),
                num_principals=az.get("numPrincipals", 0),
                num_source_ip=az.get("numSourceIP", 0),
                num_values=az.get("numValues", 0),
                num_request_principals=az.get("numRequestPrincipals", 0),
                dry_run=az.get("dryRun", False),
            ),
            namespace=doc.get("namespace", "twopods-istio"),
            peer_authn=PeerAuthN(
                mtls_mode=pa.get("mtlsMode", "STRICT"),
                num_policies=pa.get("numPolicies", 0),
            ),
            request_authn=RequestAuthN(
                invalid_token=ra.get("invalidToken", False),
                num_policies=ra.get("numPolicies", 0),
                num_jwks=ra.get("numJwks", 0),
                token_issuer=ra.get("tokenIssuer", ""),
            ),
        )


def _authz_rule(cfg: SecurityPolicyConfig) -> dict:
    """One Rule with from/to/when fan-out (generate.go's generators)."""
    az = cfg.authz
    rule: dict = {}
    froms: List[dict] = []
    if az.num_source_ip > 0:
        froms.append(
            {
                "source": {
                    "ipBlocks": [
                        f"0.0.{i // 256}.{i % 256}"
                        for i in range(az.num_source_ip)
                    ]
                }
            }
        )
    if az.num_namespaces > 0:
        froms.append(
            {
                "source": {
                    "namespaces": [
                        f"invalid-namespace-{i}"
                        for i in range(az.num_namespaces)
                    ]
                }
            }
        )
    if az.num_principals > 0:
        froms.append(
            {
                "source": {
                    "principals": [
                        f"cluster.local/ns/{cfg.namespace}/sa/Invalid-{i}"
                        for i in range(az.num_principals)
                    ]
                }
            }
        )
    if az.num_request_principals > 0:
        # the valid principal matches the token's issuer (jwtRules are
        # issuer-1..issuer-max(numJwks,1), the token signs as the last)
        valid_issuer = f"issuer-{max(cfg.request_authn.num_jwks, 1)}"
        principals = [
            "invalid-issuer/subject"
        ] * (az.num_request_principals - 1) + [
            f"{valid_issuer}/subject"
        ]
        froms.append({"source": {"requestPrincipals": principals}})
    if froms:
        rule["from"] = froms
    if az.num_paths > 0:
        rule["to"] = [
            {
                "operation": {
                    "paths": [
                        f"/invalid-path-{i}" for i in range(az.num_paths)
                    ]
                }
            }
        ]
    if az.num_values > 0:
        values = ["guest"] * az.num_values
        if az.action == "ALLOW":
            values[-1] = "admin"
        rule["when"] = [
            {"key": "request.headers[x-token]", "values": values}
        ]
    return rule


def _generate_key():
    from cryptography.hazmat.primitives.asymmetric import rsa

    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _jwks(private_key) -> str:
    """Inline JWKS for the key's public half (jwt.go:62-75)."""
    pub = private_key.public_key().public_numbers()
    n_bytes = pub.n.to_bytes((pub.n.bit_length() + 7) // 8, "big")
    e_bytes = pub.e.to_bytes((pub.e.bit_length() + 7) // 8, "big")
    # RFC 7518 base64urlUInt: unpadded (Go's RawURLEncoding likewise)
    return json.dumps(
        {
            "keys": [
                {
                    "kty": "RSA",
                    "e": _b64url(e_bytes),
                    "n": _b64url(n_bytes),
                }
            ]
        }
    )


def sign_token(private_key, issuer: str) -> str:
    """RS256 JWT with the reference's claims (jwt.go:44-47)."""
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    header = _b64url(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
    payload = _b64url(
        json.dumps({"iss": issuer, "sub": "subject"}).encode()
    )
    signing_input = f"{header}.{payload}".encode()
    sig = private_key.sign(
        signing_input, padding.PKCS1v15(), hashes.SHA256()
    )
    return f"{header}.{payload}.{_b64url(sig)}"


def generate_policies(
    cfg: SecurityPolicyConfig,
) -> Tuple[str, Optional[str]]:
    """All manifests as one multi-doc YAML, plus the bearer token (None
    when no RequestAuthentication policies are requested)."""
    docs: List[dict] = []
    az = cfg.authz
    rule = _authz_rule(cfg)  # identical across policies; build once
    for i in range(az.num_policies):
        spec: dict = {"action": az.action, "rules": [rule]}
        docs.append(
            {
                "apiVersion": "security.istio.io/v1beta1",
                "kind": "AuthorizationPolicy",
                "metadata": {
                    "name": f"test-authz-policy-{i}",
                    "namespace": cfg.namespace,
                    **(
                        {
                            "annotations": {
                                "istio.io/dry-run": "true"
                            }
                        }
                        if az.dry_run
                        else {}
                    ),
                },
                "spec": spec,
            }
        )

    for i in range(cfg.peer_authn.num_policies):
        docs.append(
            {
                "apiVersion": "security.istio.io/v1beta1",
                "kind": "PeerAuthentication",
                "metadata": {
                    "name": f"test-peer-authn-policy-{i}",
                    "namespace": cfg.namespace,
                },
                "spec": {"mtls": {"mode": cfg.peer_authn.mtls_mode}},
            }
        )

    token = None
    ra = cfg.request_authn
    if ra.num_policies > 0:
        key = _generate_key()
        jwks = _jwks(key)
        issuer = ra.token_issuer or f"issuer-{max(ra.num_jwks, 1)}"
        signing_key = _generate_key() if ra.invalid_token else key
        token = sign_token(signing_key, issuer)
        for i in range(ra.num_policies):
            docs.append(
                {
                    "apiVersion": "security.istio.io/v1beta1",
                    "kind": "RequestAuthentication",
                    "metadata": {
                        "name": f"test-request-authn-policy-{i}",
                        "namespace": cfg.namespace,
                    },
                    "spec": {
                        "jwtRules": [
                            {"issuer": f"issuer-{j + 1}", "jwks": jwks}
                            for j in range(max(ra.num_jwks, 1))
                        ]
                    },
                }
            )

    return yaml.safe_dump_all(docs, sort_keys=False), token
