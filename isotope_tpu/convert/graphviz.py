"""Graphviz DOT export of a ServiceGraph.

Capability parity with the reference's exporter
(isotope/convert/pkg/graphviz/graphviz.go:59-167): one node per service
showing its type/error-rate/steps, one edge per call from the step that
makes it to the callee.
"""
from __future__ import annotations

from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.models.script import (
    ConcurrentCommand,
    RequestCommand,
    SleepCommand,
)


def _step_label(i: int, cmd) -> str:
    if isinstance(cmd, SleepCommand):
        return f"{i}: sleep {cmd}"
    if isinstance(cmd, RequestCommand):
        prob = f" ({cmd.probability}%)" if cmd.probability else ""
        return f"{i}: call {cmd.service_name} ({cmd.size}){prob}"
    if isinstance(cmd, ConcurrentCommand):
        inner = " | ".join(_step_label(i, c).split(": ", 1)[1] for c in cmd)
        return f"{i}: concurrent [{inner}]"
    return f"{i}: ?"


def _html_escape(s: str) -> str:
    return (
        s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")
    )


def _dot_id(name: str) -> str:
    """Quote a node id for DOT, escaping quotes/backslashes in names."""
    escaped = name.replace("\\", "\\\\").replace('"', '\\"')
    return f'"{escaped}"'


def _node_lines(svc, indent: str) -> str:
    rows = [
        f'    <tr><td bgcolor="#9cbae8"><b>{_html_escape(svc.name)}</b>'
        f" ({svc.type.encode()}, x{svc.num_replicas})</td></tr>"
    ]
    if float(svc.error_rate):
        rows.append(
            f"    <tr><td>errorRate: {_html_escape(str(svc.error_rate))}</td></tr>"
        )
    for i, cmd in enumerate(svc.script):
        rows.append(
            f'    <tr><td port="s{i}">{_html_escape(_step_label(i, cmd))}</td></tr>'
        )
    label = (
        '<<table border="0" cellborder="1" cellspacing="0">\n'
        + "\n".join(rows)
        + "\n  </table>>"
    )
    return f"{indent}{_dot_id(svc.name)} [label={label}];"


def to_dot(graph: ServiceGraph) -> str:
    lines = [
        "digraph {",
        "  node [shape=plaintext];",
    ]
    clusters = {getattr(s, "cluster", "") for s in graph.services}
    if len(clusters) > 1:
        # multicluster topology: group nodes into DOT cluster subgraphs,
        # mirroring the reference's cluster1/cluster2 split
        # (perf/load/templates/service-graph.gen.yaml:1-3)
        for ci, cname in enumerate(sorted(clusters)):
            shown = cname or "default"
            lines.append(f'  subgraph "cluster_{ci}" {{')
            lines.append(f"    label={_dot_id(shown)};")
            for svc in graph.services:
                if getattr(svc, "cluster", "") == cname:
                    lines.append(_node_lines(svc, "    "))
            lines.append("  }")
    else:
        for svc in graph.services:
            lines.append(_node_lines(svc, "  "))
    for svc in graph.services:
        for i, cmd in enumerate(svc.script):
            for callee in _callees(cmd):
                lines.append(
                    f"  {_dot_id(svc.name)}:s{i} -> {_dot_id(callee)};"
                )
    lines.append("}")
    return "\n".join(lines) + "\n"


def _callees(cmd):
    if isinstance(cmd, RequestCommand):
        yield cmd.service_name
    elif isinstance(cmd, ConcurrentCommand):
        for sub in cmd:
            yield from _callees(sub)
