"""Native (C++) components, loaded via ctypes.

The reference keeps its inner loop in a compiled language (Go —
isotope/service/pkg/srv/executable.go); here the TPU compute path is
JAX/XLA and the host-side hot paths are C++.  Libraries are compiled
on first use with the system toolchain and cached next to the source,
keyed by a source hash, so test environments never need a build step.
"""
from __future__ import annotations

import ctypes
import hashlib
import pathlib
import subprocess
import threading

_DIR = pathlib.Path(__file__).parent
_lock = threading.Lock()
_cache: dict = {}


class NativeBuildError(RuntimeError):
    pass


def load_library(name: str) -> ctypes.CDLL:
    """Compile (if needed) and load ``<name>.cpp`` from this directory."""
    with _lock:
        if name in _cache:
            return _cache[name]
        src = _DIR / f"{name}.cpp"
        code = src.read_bytes()
        tag = hashlib.sha256(code).hexdigest()[:16]
        out = _DIR / "_build" / f"{name}-{tag}.so"
        if not out.exists():
            out.parent.mkdir(exist_ok=True)
            tmp = out.with_suffix(".so.tmp")
            cmd = [
                "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                str(src), "-o", str(tmp),
            ]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise NativeBuildError(
                    f"building {src.name} failed:\n{proc.stderr}"
                )
            tmp.replace(out)  # atomic: parallel builders race safely
        lib = ctypes.CDLL(str(out))
        _cache[name] = lib
        return lib
