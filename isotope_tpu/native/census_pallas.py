"""Pallas kernel for the per-step census / WaitGroup-max reduction.

The hop kernel's inner join — for every (request, hop) pair, take each
step's ``max(sleep floor, concurrent-call census)``, mask the unused
step lanes, row-sum into the hop's busy time and keep the exclusive
per-step prefix for child start offsets — is today a chain of four XLA
HLOs (``max``, ``mul``, ``reduce``, ``cumsum``) that each round-trip the
(N, B, P) step grid through HBM.  This module fuses the chain into ONE
hand-written kernel: the grid is tiled over the request and hop axes,
each block streams through VMEM once, and the step axis (small, the
padded script width) is reduced in-register.

Packing (SimParams.packed_carries): the step MASK operand rides as
bfloat16 — its values are exactly 0/1, which bf16 represents exactly,
so the f32 multiply is bit-equal to the f32-mask reference while the
constant's footprint halves.  The step BASE and the census values stay
f32 (latency accumulators are pinned to f32 by the <= 1 ULP contract).

Execution modes:

- TPU backends run the compiled Mosaic kernel;
- everywhere else ``interpret=True`` evaluates the same kernel body
  op-by-op on the host — the CPU fallback used by the equivalence
  tests (tests/test_census_pallas.py), bit-identical to the kernel's
  semantics and within 1 ULP of the XLA reference chain.

The engine gates every call on ``SimParams.pallas_census`` (auto: on
for TPU, off elsewhere); with the flag off this module is never
imported and the op-by-op path is byte-identical to PR 5's.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

#: request-axis rows per kernel block; the hop axis is tiled so one
#: block's f32 footprint stays a few MB of VMEM
_ROW_BLOCK = 8
_HOP_BLOCK = 512

#: step grids past this many (B * P) elements skip the kernel — a
#: single row would not fit VMEM comfortably
MAX_GRID_ELEMS = 1 << 21


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def pack_mask(step_mask: jax.Array) -> jax.Array:
    """The bf16-packed census mask (exact: values are 0/1)."""
    return step_mask.astype(jnp.bfloat16)


def _census_kernel(base_ref, mask_ref, agg_ref, busy_ref, excl_ref,
                   *, has_fail: bool, has_err: bool, fail_ref=None,
                   err_ref=None):
    """One (rows x hops x steps) block of the census join.

    Argument order at call sites is (base, mask, agg[, fail][, err]);
    pallas passes them positionally, so the optional refs arrive via
    the keyword defaults bound by functools.partial below.
    """
    base = base_ref[...]                     # (Hb, P) f32
    mask = mask_ref[...].astype(jnp.float32)  # (Hb, P) bf16 -> f32
    agg = agg_ref[...]                       # (Rb, Hb, P) f32
    dur = jnp.maximum(base[None], agg) * mask[None]
    if has_fail:
        fail = fail_ref[...]                 # (Rb, Hb) i32
        step_ids = jax.lax.broadcasted_iota(
            jnp.int32, dur.shape, dimension=2
        )
        dur = dur * (step_ids <= fail[:, :, None])
    if has_err:
        err = err_ref[...]                   # (Rb, Hb) bool
        dur = dur * ~err[:, :, None]
    run = jnp.cumsum(dur, axis=-1)
    busy_ref[...] = run[:, :, -1]
    excl_ref[...] = run - dur


@functools.lru_cache(maxsize=64)
def _build(n: int, b: int, p: int, has_fail: bool, has_err: bool,
           interpret: bool):
    """Compile one census pallas_call for a padded (n, b, p) grid."""
    from jax.experimental import pallas as pl

    rb = min(_ROW_BLOCK, n)
    hb = min(_HOP_BLOCK, b)
    grid = (n // rb, b // hb)
    in_specs = [
        pl.BlockSpec((hb, p), lambda i, j: (j, 0)),          # base
        pl.BlockSpec((hb, p), lambda i, j: (j, 0)),          # mask
        pl.BlockSpec((rb, hb, p), lambda i, j: (i, j, 0)),   # agg
    ]
    if has_fail:
        in_specs.append(pl.BlockSpec((rb, hb), lambda i, j: (i, j)))
    if has_err:
        in_specs.append(pl.BlockSpec((rb, hb), lambda i, j: (i, j)))
    kernel = functools.partial(
        _census_kernel_dispatch, has_fail=has_fail, has_err=has_err,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((rb, hb), lambda i, j: (i, j)),
            pl.BlockSpec((rb, hb, p), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, b), jnp.float32),
            jax.ShapeDtypeStruct((n, b, p), jnp.float32),
        ],
        interpret=interpret,
    )


def _census_kernel_dispatch(*refs, has_fail: bool, has_err: bool):
    """Route pallas' positional refs into the keyword kernel."""
    base_ref, mask_ref, agg_ref = refs[0], refs[1], refs[2]
    k = 3
    fail_ref = err_ref = None
    if has_fail:
        fail_ref = refs[k]
        k += 1
    if has_err:
        err_ref = refs[k]
        k += 1
    busy_ref, excl_ref = refs[k], refs[k + 1]
    _census_kernel(
        base_ref, mask_ref, agg_ref, busy_ref, excl_ref,
        has_fail=has_fail, has_err=has_err,
        fail_ref=fail_ref, err_ref=err_ref,
    )


def supported(num_hops: int, pmax: int) -> bool:
    """Whether the kernel should serve a (B, P) step grid."""
    return num_hops * pmax <= MAX_GRID_ELEMS


def census(
    step_base: jax.Array,          # (B, P) f32
    step_mask: jax.Array,          # (B, P) f32 or bf16 (packed)
    agg: jax.Array,                # (N, B, P) f32 census (scatter-max out)
    fail_step: Optional[jax.Array] = None,  # (N, B) i32, sentinel >= P
    err: Optional[jax.Array] = None,        # (N, B) bool
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Fused census join: ``(busy, exclusive step prefix)``.

    Semantics (identical to the XLA reference chain):

    .. code-block:: python

        dur = max(step_base, agg) * step_mask
        dur *= (arange(P) <= fail_step[..., None])   # when given
        dur *= ~err[..., None]                       # when given
        busy = dur.sum(-1); excl = cumsum(dur, -1) - dur
    """
    n, b, p = agg.shape
    if interpret is None:
        interpret = _interpret_default()
    mask = step_mask if step_mask.dtype == jnp.bfloat16 else pack_mask(
        step_mask
    )
    rb = min(_ROW_BLOCK, n)
    hb = min(_HOP_BLOCK, b)
    pad_n = (-n) % rb
    pad_b = (-b) % hb
    args = [
        jnp.pad(step_base.astype(jnp.float32), ((0, pad_b), (0, 0))),
        jnp.pad(mask, ((0, pad_b), (0, 0))),
        jnp.pad(agg, ((0, pad_n), (0, pad_b), (0, 0))),
    ]
    if fail_step is not None:
        args.append(jnp.pad(
            fail_step.astype(jnp.int32), ((0, pad_n), (0, pad_b)),
        ))
    if err is not None:
        args.append(jnp.pad(err, ((0, pad_n), (0, pad_b))))
    fn = _build(
        n + pad_n, b + pad_b, p,
        fail_step is not None, err is not None, bool(interpret),
    )
    busy, excl = fn(*args)
    return busy[:n, :b], excl[:n, :b]
