// The exact discrete-event fidelity oracle.
//
// The analytic TPU engine (isotope_tpu/sim/engine.py) samples queueing
// waits from stationary M/M/k closed forms under independence assumptions.
// This file is the ground truth it is validated against: a heap-based
// event simulator of the *physical* system both model —
//
//   - one FIFO station per service with k = NumReplicas servers, each
//     holding a request for one sampled CPU time (the reference's mock
//     service saturates at ~13k QPS/vCPU, isotope/service/README.md:28-34;
//     goroutines yield while sleeping or waiting downstream, so only CPU
//     time occupies a server);
//   - per-request script execution with the reference executor's
//     semantics (isotope/service/pkg/srv/handler.go:66-76 +
//     executable.go:43-179): sequential steps, concurrent groups joined
//     by WaitGroup (= max over members, with a group's sleeps running in
//     parallel), call probability coins, errorRate 500s that skip the
//     script, downstream 500s that do NOT fail the caller
//     (executable.go:132-143) vs transport errors (down callee, timeout)
//     that DO (handler.go:66-76), serial retry attempts each capped by
//     the call timeout with the timed-out child left running
//     (no cancellation in net/http without context deadlines);
//   - Fortio's load loop (perf/benchmark/runner/runner.py:255-268):
//     open-loop Poisson arrivals or closed-loop connections pacing to
//     max(latency, connections/qps);
//   - chaos phases scaling a station's effective server count, with a
//     fully-down callee producing a transport error and a down entry
//     refusing the client's connection.
//
// No independence or stationarity assumptions anywhere: waits emerge from
// actual contention, fork-join correlations and retry storms included.
// Single-threaded, deterministic for a given seed.  Built as a shared
// library; driven from Python via ctypes (isotope_tpu/sim/oracle.py).
#include <algorithm>
#include <cmath>
#include <cstdint>
#include <deque>
#include <queue>
#include <random>
#include <vector>

namespace {

struct Call {
  int target;
  double prob, size, timeout;
  int attempts;
  // cross-cluster edge class: extra one-way latency (gateway traversal)
  // and an edge-specific bandwidth (<= 0 means the default net_bps)
  double extra, bps;
};

struct Step {
  double base;  // sleep seconds (max over a concurrent group's sleeps)
  int c0, c1;   // [c0, c1) into the call table
};

struct Svc {
  int k;  // configured replicas
  double err, resp;
  int s0, s1;  // [s0, s1) into the step table
};

struct Attempt;

struct Job {  // one hop execution (one service invocation)
  int svc;
  double t_step_start;
  double step_call_max;  // max call duration (relative) in current step
  int step;              // absolute index into the step table
  int outstanding;       // unresolved calls in the current step
  bool transport;        // a call in the current step finally failed
  Attempt* parent;       // attempt that spawned us (null = root)
  int parent_gen;        // parent attempt generation at spawn
  // root-only:
  int64_t req;
  double t_send;
  int conn;
  // lifecycle (ungraceful-kill support): gen invalidates pending
  // CPU_DONE/STEP_DONE events after an abort; refs counts pending job
  // events + live child attempts so the struct outlives stale
  // references; res_idx is the slot in the station's resident list
  int gen = 0;
  int refs = 0;
  int res_idx = -1;
  double t_cpu_end = 0.0;  // scheduled CPU completion (abort accounting)
  bool in_cpu = false;
  bool finished = false;
  bool aborted = false;
};

struct Attempt {  // one call site's serial retry chain
  Job* caller;
  int call;          // index into the call table
  int remaining;     // attempts left including the current one
  double dur_acc;    // sum of completed attempt durations
  double t_att;      // current attempt start time
  int gen;           // increments per attempt (stale-event filter)
  int resolved_gen;  // last generation already resolved
  int pending;       // in-flight events referencing this attempt
  bool reported;     // final outcome delivered to the caller
};

enum EvKind : int {
  EV_SEND,
  EV_ARRIVE,
  EV_CPU_DONE,
  EV_STEP_DONE,
  EV_ATT_TIMEOUT,
  EV_ATT_RESP,
  EV_PHASE,
};

struct Ev {
  double t;
  uint64_t seq;
  int kind;
  void* p;
  double aux;
  int iaux;
  bool operator<(const Ev& o) const {  // min-heap via std::greater-ish
    if (t != o.t) return t > o.t;
    return seq > o.seq;
  }
};

struct Station {
  int k;  // effective servers (chaos-adjusted)
  int busy = 0;
  std::deque<Job*> q;
  double busy_time = 0.0;
  int64_t arrivals = 0;
  // every job currently resident at this service (queued, in CPU, or
  // awaiting downstream) — the set an ungraceful replica kill samples
  std::vector<Job*> residents;
};

struct Sim {
  // topology
  std::vector<Svc> svcs;
  std::vector<Step> steps;
  std::vector<Call> calls;
  int entry;
  // network
  double net_base, net_bps;
  // service-time model: 0 exponential, 1 deterministic, 2 lognormal,
  // 3 pareto (mean-preserving, mirroring engine._sample_service_time)
  int st_kind;
  double cpu_mean, st_param;
  // chaos phases
  std::vector<double> phase_starts;       // ascending, [0] == 0
  std::vector<std::vector<int>> phase_k;  // per phase, per service
  // per phase: (service, kill fraction) for drain=false events starting
  // at that cut — each resident dies with probability down / k_before
  std::vector<std::vector<std::pair<int, double>>> phase_aborts;
  // load
  int load_kind;  // 0 open, 1 closed
  double qps;     // <= 0 => closed-loop "max"
  int connections;
  double pace_jitter;  // fortio's -jitter: +/- fraction of the pace gap
  int64_t n_requests;

  std::mt19937_64 rng;
  std::priority_queue<Ev> heap;
  uint64_t seq = 0;
  std::vector<Station> stations;
  int64_t sent = 0, completed = 0, hops = 0;

  double* out_start;
  double* out_latency;
  uint8_t* out_error;

  double one_way(double bytes) const { return net_base + bytes / net_bps; }

  // per-edge wire time: cross-cluster calls pay the gateway extra and
  // ride their own bandwidth (both legs of the call's edge)
  double one_way_call(const Call& c, double bytes) const {
    double bps = c.bps > 0.0 ? c.bps : net_bps;
    return net_base + c.extra + bytes / bps;
  }

  double uni() {
    return std::uniform_real_distribution<double>(0.0, 1.0)(rng);
  }

  double cpu_draw() {
    switch (st_kind) {
      case 1:
        return cpu_mean;
      case 2: {  // E[exp(sZ - s^2/2)] == 1
        double z = std::normal_distribution<double>(0.0, 1.0)(rng);
        return std::exp(st_param * z - 0.5 * st_param * st_param) * cpu_mean;
      }
      case 3: {  // standard Pareto rescaled to the configured mean
        double e = std::exponential_distribution<double>(1.0)(rng);
        return std::exp(e / st_param) *
               (cpu_mean * (st_param - 1.0) / st_param);
      }
      default:
        return std::exponential_distribution<double>(1.0)(rng) * cpu_mean;
    }
  }

  void schedule(double t, int kind, void* p, double aux = 0.0,
                int iaux = 0) {
    heap.push(Ev{t, seq++, kind, p, aux, iaux});
  }

  // ---- stations --------------------------------------------------------

  void maybe_free_job(Job* j) {
    if (j->finished && j->refs == 0) delete j;
  }

  void residents_add(Job* j) {
    Station& s = stations[j->svc];
    j->res_idx = static_cast<int>(s.residents.size());
    s.residents.push_back(j);
  }

  void residents_remove(Job* j) {
    if (j->res_idx < 0) return;
    Station& s = stations[j->svc];
    Job* last = s.residents.back();
    s.residents[j->res_idx] = last;
    last->res_idx = j->res_idx;
    s.residents.pop_back();
    j->res_idx = -1;
  }

  void dispatch(Job* j, double t) {
    Station& s = stations[j->svc];
    s.busy++;
    j->in_cpu = true;
    j->refs++;
    double d = cpu_draw();
    s.busy_time += d;
    j->t_cpu_end = t + d;
    schedule(t + d, EV_CPU_DONE, j, 0.0, j->gen);
  }

  void on_arrive(Job* j, double t) {
    Station& s = stations[j->svc];
    s.arrivals++;
    residents_add(j);
    if (s.busy < s.k) {
      dispatch(j, t);
    } else {
      s.q.push_back(j);
    }
  }

  void on_cpu_done(Job* j, double t, int gen) {
    j->refs--;
    if (gen != j->gen) {  // aborted mid-CPU: busy already released
      maybe_free_job(j);
      return;
    }
    j->in_cpu = false;
    Station& s = stations[j->svc];
    s.busy--;
    if (!s.q.empty() && s.busy < s.k) {
      Job* nx = s.q.front();
      s.q.pop_front();
      dispatch(nx, t);
    }
    const Svc& sv = svcs[j->svc];
    // errorRate: fast 500, script skipped (engine err_coin semantics)
    if (sv.err > 0.0 && uni() < sv.err) {
      complete_job(j, t, true);
      return;
    }
    j->step = sv.s0;
    if (sv.s0 == sv.s1) {
      complete_job(j, t, false);
      return;
    }
    start_step(j, t);
  }

  // ---- script interpreter ----------------------------------------------

  void start_step(Job* j, double t) {
    j->t_step_start = t;
    j->step_call_max = 0.0;
    j->transport = false;
    const Step& st = steps[j->step];
    // coins first so `outstanding` is final before any synchronous
    // resolution (an all-attempts-down chain resolves inline)
    std::vector<int> sent_calls;
    for (int c = st.c0; c < st.c1; ++c) {
      if (calls[c].prob >= 1.0 || uni() < calls[c].prob) {
        sent_calls.push_back(c);
      }
    }
    if (sent_calls.empty()) {
      j->refs++;
      schedule(t + st.base, EV_STEP_DONE, j, 0.0, j->gen);
      return;
    }
    j->outstanding = static_cast<int>(sent_calls.size());
    for (int c : sent_calls) {
      Attempt* a = new Attempt{j,   c, calls[c].attempts, 0.0,
                               t,   0, -1,
                               0,   false};
      j->refs++;  // the attempt holds a reference to its caller
      start_attempt(a);
      // an all-attempts-down chain resolves synchronously with no events
      // ever scheduled; this is its only chance to be freed
      maybe_free(a);
    }
  }

  bool svc_down(int s) const { return stations[s].k == 0; }

  void start_attempt(Attempt* a) {
    const Call& c = calls[a->call];
    a->gen++;
    if (svc_down(c.target)) {
      // a down callee refuses instantly: transport error, ~zero duration
      a->resolved_gen = a->gen;
      resolve_attempt(a, 0.0, true, false, a->t_att);
      return;
    }
    if (std::isfinite(c.timeout)) {
      a->pending++;
      schedule(a->t_att + c.timeout, EV_ATT_TIMEOUT, a, 0.0, a->gen);
    }
    a->pending++;  // the response below always eventually arrives
    Job* ch = new Job{};
    ch->svc = c.target;
    ch->parent = a;
    ch->parent_gen = a->gen;
    ch->req = -1;
    schedule(a->t_att + one_way_call(c, c.size), EV_ARRIVE, ch);
  }

  void resolve_attempt(Attempt* a, double dur, bool transport, bool err500,
                       double t_now) {
    a->dur_acc += dur;
    a->remaining--;
    bool failed = transport || err500;
    // a caller killed ungracefully can't issue new retries — only its
    // already-running children continue
    if (failed && a->remaining > 0 && !a->caller->aborted) {
      a->t_att = t_now;  // serial retry: next attempt starts immediately
      start_attempt(a);
      return;
    }
    a->reported = true;
    finish_call(a->caller, a->dur_acc, transport);
    // freeing happens in exactly one place per code path: the event
    // handlers (on_att_timeout / on_att_resp) or the spawn site in
    // start_step — never here, so callers can't double-free
  }

  void maybe_free(Attempt* a) {
    if (a->reported && a->pending == 0) {
      Job* caller = a->caller;
      delete a;
      caller->refs--;
      maybe_free_job(caller);
    }
  }

  void on_att_timeout(Attempt* a, double t, int gen) {
    a->pending--;
    if (gen == a->gen && a->resolved_gen != a->gen) {
      a->resolved_gen = a->gen;
      // the caller stops waiting; the child keeps running uncancelled
      resolve_attempt(a, calls[a->call].timeout, true, false, t);
    }
    maybe_free(a);
  }

  void on_att_resp(Attempt* a, double t, int gen, int code) {
    // code: 0 = ok, 1 = http 500 (retries, not transport), 2 = reset
    // from an ungraceful kill (transport: truncates + retries)
    a->pending--;
    if (gen == a->gen && a->resolved_gen != a->gen) {
      a->resolved_gen = a->gen;
      // duration includes both wire legs + the child's sojourn; a 500
      // triggers a retry but is not a transport failure
      resolve_attempt(a, t - a->t_att, code == 2, code == 1, t);
    }
    maybe_free(a);
  }

  void finish_call(Job* j, double dur, bool transport) {
    if (j->aborted) return;  // the killed job reported its reset already
    if (dur > j->step_call_max) j->step_call_max = dur;
    j->transport |= transport;
    if (--j->outstanding == 0) {
      const Step& st = steps[j->step];
      double base = st.base > j->step_call_max ? st.base : j->step_call_max;
      j->refs++;
      schedule(j->t_step_start + base, EV_STEP_DONE, j, 0.0, j->gen);
    }
  }

  void on_step_done(Job* j, double t, int gen) {
    j->refs--;
    if (gen != j->gen) {
      maybe_free_job(j);
      return;
    }
    if (j->transport) {
      // transport failure truncates the script after the failing step
      // and the hop itself returns 500 upward (handler.go:66-76)
      complete_job(j, t, true);
      return;
    }
    const Svc& sv = svcs[j->svc];
    j->step++;
    if (j->step >= sv.s1) {
      complete_job(j, t, false);
      return;
    }
    start_step(j, t);
  }

  void complete_job(Job* j, double t, bool err) {
    hops++;
    residents_remove(j);
    j->finished = true;
    if (j->parent != nullptr) {
      schedule(t + one_way_call(calls[j->parent->call], svcs[j->svc].resp),
               EV_ATT_RESP, j->parent, err ? 1.0 : 0.0, j->parent_gen);
      maybe_free_job(j);
      return;
    }
    // root: client receives at t + one_way(entry response size)
    double lat = (t - j->t_send) + one_way(svcs[j->svc].resp);
    finish_request(j->req, j->t_send, lat, err, j->conn);
    maybe_free_job(j);
  }

  // ungraceful replica kill: the request dies where it stands with a
  // connection reset — a TRANSPORT error at its caller (which truncates
  // the caller's script and retries if attempts remain); its own
  // outstanding downstream children keep running, uncancelled
  void abort_job(Job* j, double t) {
    hops++;  // the hop executed (partially) — it was really resident
    residents_remove(j);
    j->aborted = true;
    j->gen++;  // invalidate pending CPU_DONE / STEP_DONE events
    Station& s = stations[j->svc];
    if (j->in_cpu) {
      j->in_cpu = false;
      s.busy--;
      // un-credit the CPU time the kill prevented from being served
      if (j->t_cpu_end > t) s.busy_time -= j->t_cpu_end - t;
    } else {
      // may be waiting in the dispatch queue: drop it there
      for (auto it = s.q.begin(); it != s.q.end(); ++it) {
        if (*it == j) {
          s.q.erase(it);
          break;
        }
      }
    }
    j->finished = true;
    if (j->parent != nullptr) {
      // the reset travels back one payload-free wire leg
      schedule(t + one_way_call(calls[j->parent->call], 0.0), EV_ATT_RESP,
               j->parent, 2.0, j->parent_gen);
      maybe_free_job(j);
      return;
    }
    finish_request(j->req, j->t_send, (t - j->t_send) + one_way(0.0), true,
                   j->conn);
    maybe_free_job(j);
  }

  // ---- client ----------------------------------------------------------

  double pace_gap() const {
    return (load_kind == 1 && qps > 0.0) ? connections / qps : 0.0;
  }

  void finish_request(int64_t req, double t_send, double lat, bool err,
                      int conn) {
    out_start[req] = t_send;
    out_latency[req] = lat;
    out_error[req] = err ? 1 : 0;
    completed++;
    if (load_kind == 1 && sent < n_requests) {
      // closed loop: this connection issues its next request after
      // max(latency, pacing gap); the gap carries fortio's -jitter
      // (runner.py:255-268 always passes -jitter: +/-10% uniform)
      double gap = pace_gap();
      if (gap > 0.0 && pace_jitter > 0.0) {
        gap *= 1.0 + pace_jitter * (2.0 * uni() - 1.0);
      }
      schedule(t_send + (lat > gap ? lat : gap), EV_SEND, nullptr, 0.0,
               conn);
    }
  }

  void on_send(double t, int conn) {
    if (sent >= n_requests) return;
    int64_t req = sent++;
    if (svc_down(entry)) {
      // down entry: the TCP connect itself is refused after one wire
      // round trip (engine root_down semantics)
      finish_request(req, t, 2.0 * one_way(0.0), true, conn);
    } else {
      Job* root = new Job{};
      root->svc = entry;
      root->parent = nullptr;
      root->req = req;
      root->t_send = t;
      root->conn = conn;
      schedule(t + one_way(0.0), EV_ARRIVE, root);
    }
    if (load_kind == 0 && sent < n_requests) {
      double gap =
          std::exponential_distribution<double>(1.0)(rng) / qps;
      schedule(t + gap, EV_SEND, nullptr, 0.0, 0);
    }
  }

  void on_phase(double /*t*/, int phase, double t_now) {
    // ungraceful kills first: each resident of the killed service dies
    // with probability down / k_before (it sat on one of the killed
    // replicas) — queued, in CPU, or awaiting downstream alike
    for (const auto& ab : phase_aborts[phase]) {
      Station& st = stations[ab.first];
      std::vector<Job*> snap = st.residents;
      for (Job* j : snap) {
        if (uni() < ab.second) abort_job(j, t_now);
      }
    }
    for (size_t s = 0; s < stations.size(); ++s) {
      stations[s].k = phase_k[phase][s];
      Station& st = stations[s];
      while (st.busy < st.k && !st.q.empty()) {
        Job* nx = st.q.front();
        st.q.pop_front();
        dispatch(nx, t_now);
      }
    }
  }

  // ---- main loop -------------------------------------------------------

  void run() {
    for (size_t p = 1; p < phase_starts.size(); ++p) {
      schedule(phase_starts[p], EV_PHASE, nullptr, 0.0,
               static_cast<int>(p));
    }
    if (load_kind == 0) {
      double gap = std::exponential_distribution<double>(1.0)(rng) / qps;
      schedule(gap, EV_SEND, nullptr, 0.0, 0);
    } else {
      // paced connections start phase-staggered over one gap — the
      // steady state of fortio's jittered periodic workers (threads
      // de-synchronize within a few hundred sends); unpaced (-qps max)
      // workers have no phase to stagger
      double gap = pace_gap();
      for (int c = 0; c < connections; ++c) {
        if (static_cast<int64_t>(c) < n_requests) {
          schedule(gap > 0.0 ? uni() * gap : 0.0, EV_SEND, nullptr, 0.0,
                   c);
        }
      }
    }
    while (!heap.empty()) {
      Ev ev = heap.top();
      heap.pop();
      switch (ev.kind) {
        case EV_SEND:
          on_send(ev.t, ev.iaux);
          break;
        case EV_ARRIVE:
          on_arrive(static_cast<Job*>(ev.p), ev.t);
          break;
        case EV_CPU_DONE:
          on_cpu_done(static_cast<Job*>(ev.p), ev.t, ev.iaux);
          break;
        case EV_STEP_DONE:
          on_step_done(static_cast<Job*>(ev.p), ev.t, ev.iaux);
          break;
        case EV_ATT_TIMEOUT:
          on_att_timeout(static_cast<Attempt*>(ev.p), ev.t, ev.iaux);
          break;
        case EV_ATT_RESP:
          on_att_resp(static_cast<Attempt*>(ev.p), ev.t, ev.iaux,
                      static_cast<int>(ev.aux + 0.5));
          break;
        case EV_PHASE:
          on_phase(ev.t, ev.iaux, ev.t);
          break;
      }
    }
  }
};

}  // namespace

extern "C" {

// Returns 0 on success, a negative code on invalid input.  All arrays are
// caller-owned; outputs must have room for n_requests entries (out_busy /
// out_arrivals: one entry per service).
int des_run(
    // services
    int32_t S, const int32_t* replicas, const double* error_rate,
    const double* response_size,
    // scripts, flattened: service s owns steps [svc_step_off[s],
    // svc_step_off[s+1]); step t owns calls [step_call_off[t],
    // step_call_off[t+1])
    const int32_t* svc_step_off, const double* step_base,
    const int32_t* step_call_off, int32_t total_steps, int32_t total_calls,
    const int32_t* call_target, const double* call_prob,
    const double* call_size, const double* call_timeout,
    const int32_t* call_attempts, const double* call_extra,
    const double* call_bps, int32_t entry,
    // network + service-time model
    double net_base, double net_bps, int32_t st_kind, double cpu_mean,
    double st_param,
    // chaos events (replicas_down < 0 means all; chaos_drain[i] == 0
    // aborts the killed replicas' resident requests at the window start)
    int32_t n_chaos, const int32_t* chaos_svc, const double* chaos_start,
    const double* chaos_end, const int32_t* chaos_down,
    const uint8_t* chaos_drain,
    // load
    int32_t load_kind, double qps, int32_t connections,
    double pace_jitter, int64_t n_requests, uint64_t seed,
    // outputs
    double* out_start, double* out_latency, uint8_t* out_error,
    double* out_busy, double* out_arrivals, int64_t* out_hops) {
  if (S <= 0 || n_requests <= 0 || entry < 0 || entry >= S) return -1;
  if (load_kind == 0 && qps <= 0.0) return -2;
  if (load_kind == 1 && connections <= 0) return -3;

  Sim sim;
  sim.entry = entry;
  sim.net_base = net_base;
  sim.net_bps = net_bps;
  sim.st_kind = st_kind;
  sim.cpu_mean = cpu_mean;
  sim.st_param = st_param;
  sim.load_kind = load_kind;
  sim.qps = qps;
  sim.connections = connections;
  sim.pace_jitter = pace_jitter;
  sim.n_requests = n_requests;
  sim.rng.seed(seed);
  sim.out_start = out_start;
  sim.out_latency = out_latency;
  sim.out_error = out_error;

  sim.svcs.resize(S);
  for (int s = 0; s < S; ++s) {
    sim.svcs[s] = Svc{replicas[s], error_rate[s], response_size[s],
                      svc_step_off[s], svc_step_off[s + 1]};
  }
  sim.steps.resize(total_steps);
  for (int t = 0; t < total_steps; ++t) {
    sim.steps[t] = Step{step_base[t], step_call_off[t], step_call_off[t + 1]};
  }
  sim.calls.resize(total_calls);
  for (int c = 0; c < total_calls; ++c) {
    if (call_target[c] < 0 || call_target[c] >= S) return -4;
    sim.calls[c] = Call{call_target[c],  call_prob[c], call_size[c],
                        call_timeout[c], call_attempts[c],
                        call_extra ? call_extra[c] : 0.0,
                        call_bps ? call_bps[c] : 0.0};
  }

  // chaos -> piecewise-constant effective replica counts (mirrors
  // Simulator.__init__'s phase construction)
  std::vector<double> cuts{0.0};
  for (int i = 0; i < n_chaos; ++i) {
    cuts.push_back(chaos_start[i]);
    cuts.push_back(chaos_end[i]);
  }
  std::sort(cuts.begin(), cuts.end());
  cuts.erase(std::unique(cuts.begin(), cuts.end()), cuts.end());
  sim.phase_starts = cuts;
  sim.phase_k.assign(cuts.size(), std::vector<int>(S));
  sim.phase_aborts.assign(cuts.size(), {});
  for (size_t p = 0; p < cuts.size(); ++p) {
    for (int s = 0; s < S; ++s) sim.phase_k[p][s] = replicas[s];
    for (int i = 0; i < n_chaos; ++i) {
      if (chaos_start[i] <= cuts[p] && cuts[p] < chaos_end[i]) {
        int s = chaos_svc[i];
        int down = chaos_down[i] < 0 ? replicas[s] : chaos_down[i];
        sim.phase_k[p][s] -= down;
        if (sim.phase_k[p][s] < 0) sim.phase_k[p][s] = 0;
      }
      // an ungraceful event whose window STARTS at this cut kills its
      // share of the service's residents (down / k in the prior phase)
      if (chaos_drain && !chaos_drain[i] && chaos_start[i] == cuts[p] &&
          p > 0) {
        int s = chaos_svc[i];
        int down = chaos_down[i] < 0 ? replicas[s] : chaos_down[i];
        int k_before = sim.phase_k[p - 1][s];
        if (k_before > 0) {
          double frac = static_cast<double>(down) / k_before;
          sim.phase_aborts[p].emplace_back(s, frac > 1.0 ? 1.0 : frac);
        }
      }
    }
  }

  sim.stations.resize(S);
  for (int s = 0; s < S; ++s) sim.stations[s].k = sim.phase_k[0][s];

  sim.run();

  for (int s = 0; s < S; ++s) {
    out_busy[s] = sim.stations[s].busy_time;
    out_arrivals[s] = static_cast<double>(sim.stations[s].arrivals);
  }
  *out_hops = sim.hops;
  return 0;
}

}  // extern "C"
