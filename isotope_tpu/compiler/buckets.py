"""Level-bucket planning for the scan executor.

The engine's original data plane Python-unrolls one tensor-program body
per depth level, so trace/HLO size grows with depth (and with every
retry-widened level).  The bucketed executor instead packs *consecutive*
depth levels whose shapes are close into one **bucket**: each level's
tensors are padded up to the bucket's bounds and the per-level sweep
body is traced ONCE as a ``lax.scan`` over the stacked constants — the
GSPMD move (one small reusable program over padded static shapes,
arxiv 2105.04663) applied to the depth axis.

Planning is a pure host-side function over light per-level shape
metadata.  A level is *scan-eligible* when it has calls and children and
would not use the sparse call-slot encoding (sparse levels keep their
specialized unrolled path — it exists precisely because the dense grid
is pathological there).  Consecutive eligible levels are grouped
greedily while the padded element count stays within ``waste`` times the
real element count, so chains and plateau-shaped multitier graphs
collapse into a handful of buckets while geometric trees (3x size per
level) naturally stay unrolled.
"""
from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple, Union

from isotope_tpu import telemetry

#: padded-elements / real-elements budget for one bucket (see plan_segments)
DEFAULT_WASTE = 1.6

#: a bucket shorter than this runs unrolled (no padding, no scan overhead)
MIN_SCAN_LEVELS = 2


@dataclasses.dataclass(frozen=True)
class LevelShape:
    """Shape metadata of one depth level (host-side planning input)."""

    size: int       # hops at this level
    pmax: int       # widest script among the level's services
    children: int   # hops at the next level spawned here
    calls: int      # call sites (retry fans share one site)
    attempts: int   # max retry attempts of any call
    sparse: bool    # the engine would use the sparse call-slot encoding
    offset: int     # start of the level's slice in BFS hop order

    @property
    def leaf(self) -> bool:
        return self.calls == 0 or self.children == 0


@dataclasses.dataclass(frozen=True)
class ScanBucketPlan:
    """One scan segment: levels ``d0..d1`` padded to common bounds.

    ``bound_hops`` covers every level size in ``d0..d1`` AND the size of
    level ``d1+1`` — the scan carry holds the *child* level's outputs,
    so the deepest child must fit the carry width too.
    """

    d0: int
    d1: int
    bound_hops: int      # B — hop/children axis bound
    bound_steps: int     # P — step axis bound
    bound_calls: int     # K
    bound_attempts: int  # A

    @property
    def num_levels(self) -> int:
        return self.d1 - self.d0 + 1

    def signature(self) -> tuple:
        return ("scan", self.d0, self.d1, self.bound_hops,
                self.bound_steps, self.bound_calls, self.bound_attempts)


@dataclasses.dataclass(frozen=True)
class UnrolledLevelPlan:
    """One unrolled segment: a single level traced with static shapes."""

    d: int

    def signature(self) -> tuple:
        return ("unrolled", self.d)


Segment = Union[ScanBucketPlan, UnrolledLevelPlan]


def _bucket_cost(shapes: Sequence[LevelShape], bounds: Tuple[int, int, int,
                                                             int]) -> int:
    b, p, k, a = bounds
    return len(shapes) * (b * p + 3 * b + 2 * k * a)


def _real_cost(shapes: Sequence[LevelShape]) -> int:
    return sum(
        s.size * s.pmax + 3 * s.children + 2 * s.calls * s.attempts
        for s in shapes
    )


def _bounds(levels: Sequence[LevelShape], child_size: int
            ) -> Tuple[int, int, int, int]:
    return (
        max([child_size] + [s.size for s in levels]),
        max(s.pmax for s in levels),
        max(s.calls for s in levels),
        max(s.attempts for s in levels),
    )


def plan_segments(
    shapes: Sequence[LevelShape],
    waste: float = DEFAULT_WASTE,
    enabled: bool = True,
) -> List[Segment]:
    """Partition the depth levels into scan buckets and unrolled islands.

    Greedy left-to-right: starting at each eligible level, the run is
    extended while the padded cost (every member at the running bounds,
    including the carry-width contribution of the run's deepest child
    level) stays within ``waste`` x the real cost.  Runs shorter than
    ``MIN_SCAN_LEVELS`` fall back to unrolled segments.
    """
    segs: List[Segment] = []
    n = len(shapes)
    i = 0
    while i < n:
        s = shapes[i]
        eligible = enabled and not s.leaf and not s.sparse
        if not eligible:
            segs.append(UnrolledLevelPlan(i))
            i += 1
            continue
        # try to grow a run [i..j]
        j = i
        run = [s]
        while j + 1 < n:
            nxt = shapes[j + 1]
            if nxt.leaf or nxt.sparse:
                break
            cand = run + [nxt]
            # carry width must cover the candidate run's child level too
            child_size = shapes[j + 2].size if j + 2 < n else 0
            bounds = _bounds(cand, child_size)
            if _bucket_cost(cand, bounds) > waste * _real_cost(cand):
                break
            run = cand
            j += 1
        if len(run) >= MIN_SCAN_LEVELS:
            child_size = shapes[j + 1].size if j + 1 < n else 0
            b, p, k, a = _bounds(run, child_size)
            segs.append(ScanBucketPlan(i, j, b, p, k, a))
            i = j + 1
        else:
            segs.append(UnrolledLevelPlan(i))
            i += 1
    _record_plan(shapes, segs)
    return segs


def plan_signature(segs: Sequence[Segment]) -> tuple:
    """Hashable shape signature of a plan — part of the AOT cache key."""
    return tuple(s.signature() for s in segs)


def plan_stats(shapes: Sequence[LevelShape],
               segs: Sequence[Segment]) -> dict:
    """Padding/coverage accounting of one plan (telemetry + tests).

    ``padded_elems`` / ``real_elems`` count only the SCAN buckets —
    unrolled islands pay no padding — so ``padding_waste_fraction`` is
    the fraction of bucket element-slots that are pure padding.
    """
    buckets_list = [s for s in segs if isinstance(s, ScanBucketPlan)]
    padded = real = 0
    per_bucket = []
    for b in buckets_list:
        members = shapes[b.d0:b.d1 + 1]
        bounds = (b.bound_hops, b.bound_steps, b.bound_calls,
                  b.bound_attempts)
        p = _bucket_cost(members, bounds)
        r = _real_cost(members)
        padded += p
        real += r
        per_bucket.append(
            {"d0": b.d0, "d1": b.d1, "levels": b.num_levels,
             "padded_elems": p, "real_elems": r,
             "padded_rows": b.num_levels * b.bound_hops
             - sum(s.size for s in members)}
        )
    return {
        "num_segments": len(segs),
        "num_buckets": len(buckets_list),
        "levels_bucketed": sum(b.num_levels for b in buckets_list),
        "levels_unrolled": len(segs) - len(buckets_list),
        "padded_elems": padded,
        "real_elems": real,
        "padding_waste_fraction": (
            (padded - real) / padded if padded else 0.0
        ),
        "buckets": per_bucket,
    }


def _record_plan(shapes: Sequence[LevelShape],
                 segs: Sequence[Segment]) -> None:
    """Fold one plan's stats into the engine telemetry registry."""
    st = plan_stats(shapes, segs)
    telemetry.counter_inc("bucket_plans")
    telemetry.counter_inc("buckets_formed", st["num_buckets"])
    telemetry.counter_inc("levels_bucketed", st["levels_bucketed"])
    telemetry.counter_inc("levels_unrolled", st["levels_unrolled"])
    telemetry.counter_inc("bucket_padded_elems", st["padded_elems"])
    telemetry.counter_inc("bucket_real_elems", st["real_elems"])
    telemetry.counter_inc(
        "bucket_padded_rows",
        sum(b["padded_rows"] for b in st["buckets"]),
    )
    telemetry.gauge_set(
        "bucket_padding_waste_fraction", st["padding_waste_fraction"]
    )
